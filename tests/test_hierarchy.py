"""Hierarchical bucketed aggregation (aggregators/hierarchy.py).

Fast tier-1 coverage (n <= 256, d <= 1e3 — the 1-core budget): the
f-composition derivation, adversarial Byzantine placement (concentrated
vs spread, lie vs reverse, two (bucket_gar, top_gar) combinations),
bitwise determinism, streaming-vs-batch bitwise equality, wire-frame
ingest + ban-evidence propagation, and the hier_exclusion -> suspicion
telemetry path. The multi-wave exchange-driven ingest end-to-end lives in
tests/test_hierarchy_stream.py (slow, conftest._RUN_LAST).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from garfield_tpu import attacks
from garfield_tpu.aggregators import gars, hierarchy
from garfield_tpu.utils import wire

RNG = np.random.default_rng(20260805)


def honest_stack(n, d, mu=None, sigma=0.1):
    mu = RNG.normal(size=d).astype(np.float32) if mu is None else mu
    g = (mu[None, :] + sigma * RNG.normal(size=(n, d))).astype(np.float32)
    return g


# ---------------------------------------------------------------------------
# plan derivation / f-composition


class TestPlan:
    def test_level_structure_covers_all_clients(self):
        plan = hierarchy.plan_hierarchy(2 ** 10, 64, "krum")
        counts = [plan.n]
        for lv in plan.bucket_levels:
            assert sum(lv.sizes) == counts[-1]
            assert max(lv.sizes) - min(lv.sizes) <= 1  # balanced partition
            counts.append(len(lv.sizes))
        assert counts[-1] == plan.final_n

    def test_bucket_sizes_bounded_by_sort_network(self):
        plan = hierarchy.plan_hierarchy(2 ** 10, 10, "median")
        for lv in plan.bucket_levels:
            assert max(lv.sizes) <= hierarchy.DEFAULT_BUCKET_SIZE

    def test_composition_budget_is_respected(self):
        # Corrupting a bucket costs f_l + 1 clients; the derived split must
        # absorb the full global budget level by level.
        for n, f in [(128, 7), (1024, 64), (4096, 200)]:
            plan = hierarchy.plan_hierarchy(n, f, "krum")
            remaining = f
            for lv in plan.bucket_levels:
                remaining = remaining // (lv.f + 1)
            assert remaining <= plan.final_f

    def test_max_tolerated_f_is_tight(self):
        cap = hierarchy.max_tolerated_f(1024, "krum")
        hierarchy.plan_hierarchy(1024, cap, "krum")  # must compose
        with pytest.raises(ValueError, match="does not compose"):
            hierarchy.plan_hierarchy(1024, cap + 1, "krum")

    def test_small_n_degenerates_to_flat(self):
        plan = hierarchy.plan_hierarchy(16, 3, "krum")
        assert plan.bucket_levels == [] and plan.final_n == 16

    def test_bucket_count_grows_for_top_contract(self):
        # 128 clients in buckets of 32 leave 4 summaries — below krum's
        # n >= 2f+3 floor — so the planner rebalances to >= 5 buckets
        # instead of refusing to bucket at all.
        plan = hierarchy.plan_hierarchy(128, 7, "krum")
        assert len(plan.bucket_levels) == 1
        assert plan.final_n >= 5

    def test_unsupported_rules_rejected(self):
        with pytest.raises(ValueError, match="supports rules"):
            hierarchy.plan_hierarchy(64, 3, "condense")
        with pytest.raises(ValueError, match="supports rules"):
            hierarchy.plan_hierarchy(64, 3, "krum", top_gar="brute")

    def test_registered_check_surfaces_message(self):
        msg = gars["hier-krum"].check(np.zeros((64, 2), np.float32), f=10 ** 6)
        assert msg is not None and "does not compose" in msg
        assert gars["hier-krum"].check(
            np.zeros((64, 2), np.float32), f=3) is None

    def test_checked_wrapper_raises_like_flat_rules(self):
        with pytest.raises(AssertionError, match="hier-krum"):
            gars["hier-krum"].checked(
                np.zeros((64, 8), np.float32), f=10 ** 6)

    def test_upper_bound_composes_conservatively(self):
        ub = gars["hier-krum"].upper_bound(128, 7, 100)
        plan = hierarchy.plan_hierarchy(128, 7, "krum")
        flat = gars["krum"].upper_bound(
            min(plan.bucket_levels[0].sizes), plan.bucket_levels[0].f, 100)
        assert ub is not None and ub <= flat


# ---------------------------------------------------------------------------
# Byzantine composition: adversarial placement (the acceptance test)


@pytest.mark.parametrize("name", ["hier-krum", "hier-median-krum"])
@pytest.mark.parametrize("placement", ["concentrated", "spread"])
@pytest.mark.parametrize("attack", ["lie", "reverse"])
def test_byzantine_placement_composes(name, placement, attack):
    """f Byzantine clients — packed into one bucket or spread one per
    bucket — under lie/reverse must leave the two-level aggregate within
    the flat-GAR tolerance scale: near the honest mean, and orders of
    magnitude closer to it than the attack vector."""
    n, d, bucket, f = 128, 64, 16, 7
    bucket_gar, top_gar = hierarchy.parse_hier_name(name)
    mask = np.zeros(n, bool)
    if placement == "concentrated":
        mask[:f] = True  # all in bucket 0: overwhelms it; the top rule
        # must then exclude that bucket's summary
    else:
        mask[np.arange(f) * bucket] = True  # one per bucket: each bucket's
        # rule absorbs its lone Byzantine
    sigma = 0.1
    g = honest_stack(n, d, sigma=sigma)
    honest_mean = g[~mask].mean(axis=0)
    poisoned = np.asarray(attacks.gradient_attacks[attack](
        jnp.asarray(g), jnp.asarray(mask), key=None))

    agg = np.asarray(hierarchy.aggregate(
        poisoned, f, bucket_gar=bucket_gar, top_gar=top_gar,
        bucket_size=bucket))
    assert np.isfinite(agg).all()
    hier_dist = np.linalg.norm(agg - honest_mean)
    byz_dist = np.linalg.norm(poisoned[mask][0] - honest_mean)
    sigma_vec = sigma * np.sqrt(d)  # the honest dispersion scale

    # Within the flat-GAR tolerance scale (measured ~0.1 vs bound 0.8)...
    flat = np.asarray(gars[bucket_gar].unchecked(jnp.asarray(poisoned), f=f))
    flat_dist = np.linalg.norm(flat - honest_mean)
    assert hier_dist <= 3.0 * flat_dist + sigma_vec
    # ...and the attack vector gained no traction (reverse is 100x-
    # amplified: measured margin ~5000x, asserted at 100x).
    if attack == "reverse":
        assert hier_dist <= 0.01 * byz_dist


# ---------------------------------------------------------------------------
# determinism + streaming/batch equality


@pytest.mark.parametrize("name", ["hier-krum", "hier-median", "hier-tmean",
                                  "hier-krum-median"])
def test_streaming_equals_batch_bitwise(name):
    bucket_gar, top_gar = hierarchy.parse_hier_name(name)
    n, d, f = 100, 96, 5  # uneven: exercises the balanced partition
    g = honest_stack(n, d)
    batch = np.asarray(hierarchy.aggregate(
        g, f, bucket_gar=bucket_gar, top_gar=top_gar, bucket_size=16))
    red = hierarchy.StreamingAggregator(
        n, f, bucket_gar=bucket_gar, top_gar=top_gar, bucket_size=16,
        wave_buckets=3)
    for row in g:
        red.push(row)
    assert np.array_equal(red.finalize(), batch)


def test_deterministic_same_seed_same_assignment():
    g = honest_stack(128, 64)
    a = np.asarray(hierarchy.aggregate(g, 7, bucket_gar="krum",
                                       bucket_size=16))
    b = np.asarray(hierarchy.aggregate(g.copy(), 7, bucket_gar="krum",
                                       bucket_size=16))
    assert np.array_equal(a, b)
    # Streaming twice over the same arrival order is bitwise-stable too.
    outs = []
    for _ in range(2):
        red = hierarchy.StreamingAggregator(
            128, 7, bucket_gar="krum", bucket_size=16, wave_buckets=4)
        red.push_many(g)
        outs.append(red.finalize())
    assert np.array_equal(outs[0], outs[1])
    assert np.array_equal(outs[0], a)


def test_arrival_order_defines_buckets():
    # A different arrival order is a different bucket assignment — the
    # aggregate legitimately differs. Guards against accidentally sorting
    # or hashing clients into buckets host-side.
    g = honest_stack(64, 32)
    g[:8] += 3.0  # make one cohort distinctive
    a = np.asarray(hierarchy.aggregate(g, 3, bucket_gar="krum",
                                       bucket_size=8))
    perm = RNG.permutation(64)
    b = np.asarray(hierarchy.aggregate(g[perm], 3, bucket_gar="krum",
                                       bucket_size=8))
    assert not np.array_equal(a, b)


def test_tree_aggregate_matches_flat():
    g = honest_stack(64, 48)
    flat = np.asarray(hierarchy.aggregate(g, 3, bucket_gar="krum",
                                          bucket_size=16))
    tree = {"w": g[:, :32].reshape(64, 8, 4), "b": g[:, 32:]}
    out = gars["hier-krum"].tree_aggregate(tree, f=3)
    assert np.asarray(out["w"]).shape == (8, 4)
    # concat_stack flattens in key order (b before w), permuting the
    # columns; krum's selection is column-permutation-invariant, so the
    # tree result must match the flat aggregate up to that permutation
    # (allclose, not bitwise: the Gram reduces d in a different order).
    got = np.concatenate(
        [np.asarray(out["b"]).reshape(-1), np.asarray(out["w"]).reshape(-1)])
    want = np.concatenate([flat[32:], flat[:32]])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# streaming ingest mechanics


def test_wire_frame_ingest_round_trip():
    g = honest_stack(32, 40)
    red = hierarchy.StreamingAggregator(32, 1, bucket_gar="krum",
                                        bucket_size=8)
    for i, row in enumerate(g):
        assert red.push_frame(wire.encode(row)) == i
    batch = np.asarray(hierarchy.aggregate(g, 1, bucket_gar="krum",
                                           bucket_size=8))
    assert np.array_equal(red.finalize(), batch)


def test_wire_transform_rejects_are_ban_evidence():
    red = hierarchy.StreamingAggregator(8, 0, bucket_gar="median",
                                        bucket_size=4)
    with pytest.raises(wire.WireError):
        red.wire_transform(3, b"garbage-not-a-frame")
    # The bad frame must not have consumed an arrival slot.
    for row in honest_stack(8, 16):
        red.push(row)
    assert red.finalize().shape == (16,)


def test_sparse_frames_need_a_known_row_width():
    """REVIEW fix: push_frame must never scatter a sparse frame whose
    claimed dense size nothing corroborates. Before the row width is
    known a topk frame is refused outright (WireError — the caller's
    ban path, not an OOM); with the width pinned at construction
    (``d=``), honest sparse frames ingest and a CRC-valid forged frame
    claiming 2^40 elements rejects before the scatter allocates."""
    import struct
    import zlib

    v = np.arange(16, dtype=np.float32)
    red = hierarchy.StreamingAggregator(8, 0, bucket_gar="median",
                                        bucket_size=4)
    with pytest.raises(wire.WireError, match="row width"):
        red.push_frame(wire.encode(v, "topk", k=4))
    assert red._arrived == 0  # the refused frame consumed no slot
    red = hierarchy.StreamingAggregator(8, 0, bucket_gar="median",
                                        bucket_size=4, d=16)
    assert red.push_frame(wire.encode(v, "topk", k=4)) == 0
    pairs = np.zeros(2, np.dtype([("i", "<u4"), ("v", "<f4")]))
    pairs["i"] = [0, 1]
    pairs["v"] = [5.0, -5.0]
    payload = pairs.tobytes()
    giant = struct.pack(
        "!2sBBQI", b"GW", 1, 4, 2 ** 40, zlib.crc32(payload)
    ) + payload
    with pytest.raises(wire.WireError, match="expected"):
        red.push_frame(giant)
    # The pinned width also rejects wrong-size DENSE frames as codec
    # (not contract) errors — attributable like any WireError.
    with pytest.raises(wire.WireError):
        red.push_frame(wire.encode(np.ones(9, np.float32)))
    for row in np.zeros((7, 16), np.float32):
        red.push(row)
    assert red.finalize().shape == (16,)


def test_streaming_contract_errors():
    red = hierarchy.StreamingAggregator(4, 0, bucket_gar="median",
                                        bucket_size=2)
    red.push(np.zeros(8, np.float32))
    with pytest.raises(ValueError, match="9 elements"):
        red.push(np.zeros(9, np.float32))
    with pytest.raises(ValueError, match="ingested"):
        red.finalize()
    for _ in range(3):
        red.push(np.zeros(8, np.float32))
    out = red.finalize()
    assert np.array_equal(out, red.finalize())  # idempotent
    with pytest.raises(RuntimeError, match="finalize"):
        red.push(np.zeros(8, np.float32))


# ---------------------------------------------------------------------------
# telemetry: bucket-level exclusions -> per-client suspicion


def test_hier_exclusion_feeds_suspicion():
    """Byzantine clients (reverse attack, spread) must rank top of the
    MetricsHub suspicion derived from the reducer's hier_exclusion events
    — the same audit signal the in-graph taps feed, now at client
    granularity (docs/TELEMETRY.md). Bucket krum + median top: the bucket
    level attributes exclusion per client; the coordinate-wise top has no
    discrete selection, so honest clients accumulate only the bucket
    rule's random exclusion churn (~0.5/round) while the amplified
    Byzantine rows are refused EVERY round."""
    from garfield_tpu.telemetry import hub as tele_hub
    from garfield_tpu.telemetry.hub import MetricsHub

    n, d, bucket, f = 64, 32, 8, 3
    byz = np.arange(f) * bucket  # spread: in-bucket exclusion does the work
    mask = np.zeros(n, bool)
    mask[byz] = True
    hub = MetricsHub()
    prev = tele_hub.install(hub)
    try:
        for _ in range(24):
            g = honest_stack(n, d)
            poisoned = np.asarray(attacks.gradient_attacks["reverse"](
                jnp.asarray(g), jnp.asarray(mask), key=None))
            red = hierarchy.StreamingAggregator(
                n, f, bucket_gar="krum", top_gar="median",
                bucket_size=bucket, telemetry=True)
            red.push_many(poisoned)
            red.finalize()
    finally:
        tele_hub.install(prev)
        if prev is None:
            tele_hub.uninstall()
    susp = hub.suspicion()
    assert susp is not None and susp.shape == (n,)
    assert susp[mask].min() == 1.0  # refused every single round
    assert susp[mask].min() > susp[~mask].max()
    assert set(np.argsort(susp)[-f:]) == set(byz.tolist())
    # And the wave events made it into the ring.
    kinds = {r.get("event") for r in hub.records() if r["kind"] == "event"}
    assert "hier_exclusion" in kinds and "hier_wave" in kinds


def test_audit_matches_batch_and_stream():
    n, d, bucket, f = 64, 32, 8, 3
    mask = np.zeros(n, bool)
    mask[:f] = True  # concentrated: the top level must drop bucket 0
    g = honest_stack(n, d)
    poisoned = np.asarray(attacks.gradient_attacks["reverse"](
        jnp.asarray(g), jnp.asarray(mask), key=None))
    agg, audit = hierarchy.aggregate_with_audit(
        poisoned, f, bucket_gar="krum", bucket_size=bucket)
    assert audit["selected"][mask].sum() == 0  # every Byzantine excluded
    red = hierarchy.StreamingAggregator(
        n, f, bucket_gar="krum", bucket_size=bucket, audit=True)
    red.push_many(poisoned)
    red.finalize()
    assert np.array_equal(red.audit()["selected"], audit["selected"])


# ---------------------------------------------------------------------------
# Double-buffered wave fold (PR 19): overlap must be bitwise-invisible.


class TestDoubleBuffer:
    @pytest.mark.parametrize("double", [False, True])
    @pytest.mark.parametrize("mode", ["one", "many", "mixed"])
    def test_streaming_equals_batch_all_ingest_modes(self, double, mode):
        n, d, f = 200, 64, 9
        g = honest_stack(n, d)
        batch = np.asarray(hierarchy.aggregate(
            g, f, bucket_gar="krum", bucket_size=16))
        red = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=16, wave_buckets=3,
            double_buffer=double)
        if mode == "one":
            for row in g:
                red.push(row)
        elif mode == "many":
            red.push_many(g)
        else:
            red.push_many(g[:131])
            for row in g[131:140]:
                red.push(row)
            red.push_many(g[140:])
        assert np.array_equal(red.finalize(), batch)

    def test_push_many_across_buffer_swap(self):
        # Regression: push_many once cached the active buffer across its
        # fill loop, but a mid-loop drain SWAPS buffers in double-buffer
        # mode — later rows landed in the buffer the in-flight wave
        # still aliased while the real target stayed uninitialized
        # (visible as a wholly wrong aggregate at n >= 1024).
        n, d, f = 1024, 32, 20
        g = honest_stack(n, d)
        want = np.asarray(hierarchy.aggregate(
            g, f, bucket_gar="median", bucket_size=32))
        red = hierarchy.StreamingAggregator(
            n, f, bucket_gar="median", bucket_size=32, wave_buckets=4,
            double_buffer=True)
        red.push_many(g)  # one call: must survive every internal swap
        assert np.array_equal(red.finalize(), want)

    def test_reset_round_trip_under_double_buffer(self):
        n, d, f = 256, 48, 6
        red = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=16, wave_buckets=3,
            double_buffer=True)
        outs = []
        for r in range(2):
            g = honest_stack(n, d)
            red.push_many(g)
            outs.append((g, red.finalize().copy()))
            red.reset()
        for g, got in outs:
            want = np.asarray(hierarchy.aggregate(
                g, f, bucket_gar="krum", bucket_size=16))
            assert np.array_equal(got, want)

    def test_audit_identical_on_off(self):
        n, d, f = 200, 40, 5
        g = honest_stack(n, d)
        g[7] *= -80.0  # a reversed client the audit should flag
        keeps = []
        for double in (False, True):
            red = hierarchy.StreamingAggregator(
                n, f, bucket_gar="krum", bucket_size=16, wave_buckets=3,
                audit=True, double_buffer=double)
            red.push_many(g)
            red.finalize()
            keeps.append(red.audit()["selected"].copy())
        assert np.array_equal(keeps[0], keeps[1])

    def test_env_knob_default_on(self, monkeypatch):
        monkeypatch.delenv("GARFIELD_HIER_DOUBLE_BUFFER", raising=False)
        assert hierarchy.StreamingAggregator(64, 2)._double is True
        monkeypatch.setenv("GARFIELD_HIER_DOUBLE_BUFFER", "0")
        assert hierarchy.StreamingAggregator(64, 2)._double is False
        # explicit argument beats the environment
        assert hierarchy.StreamingAggregator(
            64, 2, double_buffer=True)._double is True


class TestFusedFrameIngest:
    @pytest.mark.parametrize("scheme", ["f32", "bf16", "int8", "int4",
                                        "topk"])
    def test_fused_equals_unfused_equals_batch(self, scheme, monkeypatch):
        n, d, f = 96, 64, 4
        g = honest_stack(n, d)
        frames = [wire.encode(row, dtype=scheme) for row in g]
        rows = np.stack([wire.decode(fr, expect_elems=d) for fr in frames])
        want = None
        outs = {}
        for fused in ("1", "0"):
            monkeypatch.setenv("GARFIELD_WIRE_FUSED_DECODE", fused)
            red = hierarchy.StreamingAggregator(
                n, f, bucket_gar="krum", bucket_size=16, wave_buckets=3,
                d=d)
            assert red._fused is (fused == "1")
            for fr in frames:
                red.push_frame(fr)
            outs[fused] = red.finalize()
        want = np.asarray(hierarchy.aggregate(
            rows, f, bucket_gar="krum", bucket_size=16))
        assert np.array_equal(outs["1"], outs["0"])
        assert np.array_equal(outs["1"], want)

    def test_fused_reject_leaves_trajectory_intact(self, monkeypatch):
        monkeypatch.setenv("GARFIELD_WIRE_FUSED_DECODE", "1")
        n, d, f = 48, 32, 2
        g = honest_stack(n, d)
        frames = [wire.encode(row) for row in g]
        red = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=16, wave_buckets=2, d=d)
        bad = bytearray(frames[5])
        bad[-1] ^= 0xFF  # CRC break mid-stream
        for i, fr in enumerate(frames):
            if i == 5:
                with pytest.raises(wire.WireError):
                    red.push_frame(bytes(bad))
                # the reject must not consume an ingest slot
                assert red._arrived == 5
            red.push_frame(fr)
        ref = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=16, wave_buckets=2, d=d)
        for fr in frames:
            ref.push_frame(fr)
        assert np.array_equal(red.finalize(), ref.finalize())


# ---------------------------------------------------------------------------
# batched frame ingest + zero-copy stable dispatch (ISSUE 20)


class TestBatchFrameIngest:
    """push_frames is a bulk entry, not a new semantics: batch ingest ==
    per-frame ingest == the batch hierarchy, bitwise; rejects surface as
    indexed WireErrors (ban evidence) that consume no arrival slot; the
    env kill-switch path is bitwise-identical."""

    def _frames(self, g, scheme="f32", plane=0, epoch=None):
        kw = {} if epoch is None else {"epoch": epoch}
        return [wire.encode(row, scheme, plane=plane, **kw) for row in g]

    @pytest.mark.parametrize("scheme", ["f32", "int8", "topk"])
    def test_push_frames_bitwise_equals_per_frame_and_batch(self, scheme):
        n, d, f = 32, 40, 1
        g = honest_stack(n, d)
        frames = self._frames(g, scheme)
        rows = np.stack([wire.decode(fr, expect_elems=d) for fr in frames])
        red_b = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=8, d=d)
        assert red_b.push_frames(frames) == list(range(n))
        red_s = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=8, d=d)
        for fr in frames:
            red_s.push_frame(fr)
        want = np.asarray(hierarchy.aggregate(
            rows, f, bucket_gar="krum", bucket_size=8))
        assert np.array_equal(red_b.finalize(), red_s.finalize())
        assert np.array_equal(red_b.finalize(), want)

    def test_rejects_are_indexed_and_consume_no_slot(self):
        n, d = 7, 16
        g = honest_stack(8, d)
        frames = self._frames(g)
        bad = bytearray(frames[2])
        bad[-1] ^= 0xFF
        frames[2] = bytes(bad)
        red = hierarchy.StreamingAggregator(
            n, 0, bucket_gar="median", bucket_size=4, d=d)
        res = red.push_frames(frames[:5])
        assert isinstance(res[2], wire.WireError)
        assert [r for i, r in enumerate(res) if i != 2] == [0, 1, 2, 3]
        assert red.push_frames(frames[5:]) == [4, 5, 6]
        keep = np.delete(np.arange(8), 2)
        want = np.asarray(hierarchy.aggregate(
            g[keep], 0, bucket_gar="median", bucket_size=4))
        assert np.array_equal(red.finalize(), want)

    def test_batch_env_off_falls_back_bitwise(self, monkeypatch):
        n, d = 16, 24
        g = honest_stack(n, d)
        frames = self._frames(g, "int8")
        outs = {}
        for knob in ("1", "0"):
            monkeypatch.setenv("GARFIELD_WIRE_BATCH_DECODE", knob)
            red = hierarchy.StreamingAggregator(
                n, 0, bucket_gar="median", bucket_size=4, d=d)
            assert red.push_frames(frames) == list(range(n))
            outs[knob] = red.finalize()
        assert np.array_equal(outs["1"], outs["0"])

    def test_capacity_overflow_raises_before_any_ingest(self):
        d = 16
        g = honest_stack(8, d)
        red = hierarchy.StreamingAggregator(
            7, 0, bucket_gar="median", bucket_size=4, d=d)
        with pytest.raises(ValueError, match="8 frames"):
            red.push_frames(self._frames(g))
        assert red._arrived == 0

    def test_epoch_pins_thread_through(self):
        n, d = 8, 16
        g = honest_stack(n, d)
        frames = self._frames(g, plane=1, epoch=5)
        frames[3] = wire.encode(g[3], plane=1, epoch=4)  # stale
        red = hierarchy.StreamingAggregator(
            n, 0, bucket_gar="median", bucket_size=4, d=d)
        res = red.push_frames(frames, expect_plane=1, expect_epoch=5)
        assert isinstance(res[3], wire.WireError)
        assert "epoch" in str(res[3])
        assert [r for i, r in enumerate(res) if i != 3] == list(range(7))


class TestStableDispatch:
    """push_many(stable=True): whole waves fold straight on the caller's
    block (no staging memcpy) — bitwise-equal to the copy path, and
    non-eligible inputs (non-contiguous, wrong dtype, partial fill)
    silently take the copy path."""

    def test_stable_bitwise_equals_copy(self):
        n, d, f = 64, 32, 3
        g = honest_stack(n, d)
        red_c = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=8, wave_buckets=2)
        red_c.push_many(g.copy())
        red_s = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=8, wave_buckets=2)
        red_s.push_many(g, stable=True)
        assert np.array_equal(red_c.finalize(), red_s.finalize())

    def test_stable_with_tail_and_partial_fill(self):
        # 50 rows over 8-bucket waves: whole waves go zero-copy, the
        # tail rides the copy path; a pre-filled buffer (odd split)
        # forces the copy path until the fill drains.
        n, d, f = 50, 24, 2
        g = honest_stack(n, d)
        red_c = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=8, wave_buckets=2)
        red_c.push_many(g.copy())
        red_s = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=8, wave_buckets=2)
        red_s.push(g[0])                      # fill != 0: copy path
        red_s.push_many(g[1:4], stable=True)  # still unaligned
        red_s.push_many(g[4:], stable=True)   # drains to whole waves
        assert np.array_equal(red_c.finalize(), red_s.finalize())

    def test_non_contiguous_and_wrong_dtype_fall_back(self):
        n, d, f = 32, 16, 1
        wide = honest_stack(n, 2 * d)
        view = wide[:, ::2]  # non-contiguous view
        assert not view.flags["C_CONTIGUOUS"]
        red_v = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=8, wave_buckets=2)
        red_v.push_many(view, stable=True)
        red_r = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=8, wave_buckets=2)
        red_r.push_many(np.ascontiguousarray(view))
        assert np.array_equal(red_v.finalize(), red_r.finalize())

    def test_stable_with_audit_keeps_attribution(self):
        from garfield_tpu.telemetry import hub as tele_hub

        n, d, f = 32, 16, 1
        g = honest_stack(n, d)
        h = tele_hub.MetricsHub(num_ranks=n)
        prev = tele_hub.install(h)
        try:
            red = hierarchy.StreamingAggregator(
                n, f, bucket_gar="krum", bucket_size=8, wave_buckets=2,
                telemetry=True)
            red.push_many(g, stable=True)
            out = red.finalize()
        finally:
            tele_hub.uninstall()
            if prev is not None:
                tele_hub.install(prev)
        ref = hierarchy.StreamingAggregator(
            n, f, bucket_gar="krum", bucket_size=8, wave_buckets=2)
        ref.push_many(g)
        assert np.array_equal(out, ref.finalize())
        evs = [r for r in h.records()
               if r["kind"] == "event" and r["event"] == "hier_exclusion"]
        assert evs  # the audit trail survived the zero-copy path

"""Multi-host (DCN) scaffolding: jax.distributed init, cluster config, and
host-level fault simulation.

Counterparts:
  - cluster config: the TF_CONFIG-style JSON cluster files of the TF impl —
    host lists + per-task {type, index} plus Garfield extras (GAR, attacks)
    — parsed by ``Network`` (tensorflow_impl/rsrcs/network.py:36-89) and
    written interactively by each app's ``config_generator.py`` (:30-90);
  - process bootstrap: ``dist.init_process_group`` / ``rpc.init_rpc``
    (Garfield_CC/trainer.py:367-380, Aggregathor/trainer.py:217-224) ->
    ``jax.distributed.initialize`` (one controller per host, collectives ride
    ICI within a slice and DCN across);
  - failure simulation: the reference has no failure detector — resilience is
    wait-n-f (SURVEY §5). On a bulk-synchronous mesh, a crashed/straggling
    host cannot simply be absent, so ``FaultSchedule`` turns host-level
    crash/straggler scenarios into per-step value faults: crashed hosts'
    worker slots join the Byzantine mask (their gradient rows become zeros —
    exactly what Garfield_CC's ``mar='crash'`` mode feeds the model GAR,
    trainer.py:97,137) and the wait-n-f ``subset`` knob models which peers
    answered in time.
"""

import json
import os

import numpy as np

from . import tools

__all__ = [
    "ClusterConfig",
    "generate_config",
    "init_distributed",
    "FaultSchedule",
]


class ClusterConfig:
    """JSON cluster spec: {"cluster": {"worker": [hosts], "ps": [hosts]},
    "task": {"type": "worker", "index": 0}, "garfield": {...}}.

    The shape mirrors TF_CONFIG (tensorflow_impl/README.md:46-96) so existing
    Garfield deployment tooling maps 1:1; the "garfield" section carries the
    per-run parameters the reference spreads over CLI flags.
    """

    def __init__(self, spec):
        if isinstance(spec, (str, os.PathLike)):
            with open(spec) as fp:
                spec = json.load(fp)
        self.spec = dict(spec)
        cluster = self.spec.get("cluster", {})
        self.workers = list(cluster.get("worker", []))
        self.ps = list(cluster.get("ps", []))
        # Decentralized (LEARN) deployments have no ps/worker split: every
        # process is a peer "node" (LEARN/trainer.py:224-231 — each rank
        # constructs both a Worker and a Server).
        self.nodes = list(cluster.get("node", []))
        task = self.spec.get("task", {"type": "worker", "index": 0})
        self.task_type = task.get("type", "worker")
        self.task_index = int(task.get("index", 0))
        self.garfield = dict(self.spec.get("garfield", {}))

    @classmethod
    def from_env(cls, var="GARFIELD_CONFIG"):
        """Load from the env var (path or inline JSON), like TF_CONFIG."""
        raw = os.environ.get(var)
        if not raw:
            return None
        if raw.lstrip().startswith("{"):
            return cls(json.loads(raw))
        return cls(raw)

    @property
    def hosts(self):
        return self.nodes if self.nodes else self.ps + self.workers

    @property
    def num_processes(self):
        return len(self.hosts)

    @property
    def process_id(self):
        if self.task_type == "node":
            return self.task_index
        base = 0 if self.task_type == "ps" else len(self.ps)
        return base + self.task_index

    @property
    def coordinator(self):
        """First host (the reference's --master / rank-0 convention)."""
        return self.hosts[0] if self.hosts else None


def generate_config(path, *, workers=(), ps=(), nodes=(), task_type="worker",
                    task_index=0, **garfield):
    """Write a cluster config JSON (config_generator.py:30-90 counterpart,
    non-interactive). ``nodes`` describes a decentralized (LEARN) peer
    deployment and is mutually exclusive with ps/workers."""
    if nodes and (workers or ps):
        raise ValueError("a node (LEARN) cluster has no ps/worker split")
    cluster = (
        {"node": list(nodes)} if nodes
        else {"worker": list(workers), "ps": list(ps)}
    )
    spec = {
        "cluster": cluster,
        "task": {"type": task_type, "index": task_index},
        "garfield": garfield,
    }
    with open(path, "w") as fp:
        json.dump(spec, fp, indent=1)
    return spec


def init_distributed(config=None, **overrides):
    """Initialize jax.distributed from a ClusterConfig / env / overrides.

    No-op on single-process runs (coordinator is None and no env setup).
    Returns (num_processes, process_id).
    """
    import jax

    if config is None:
        config = ClusterConfig.from_env()
    kwargs = {}
    if config is not None and config.coordinator:
        kwargs = dict(
            coordinator_address=config.coordinator,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
    kwargs.update(overrides)
    if not kwargs:
        return 1, 0
    jax.distributed.initialize(**kwargs)
    tools.info(
        f"[multihost] initialized process "
        f"{jax.process_index()}/{jax.process_count()}"
    )
    return jax.process_count(), jax.process_index()


class FaultSchedule:
    """Deterministic host-level crash/straggler plan -> per-step value faults.

    ``crashes`` maps host_id -> step at which it dies; ``stragglers`` maps
    host_id -> probability its contribution misses the wait-n-f cut.
    ``byz_mask(step, num_workers, hosts)`` returns the mask of worker slots
    whose rows must be zeroed this step (dead hosts); ``subset(step, n, f)``
    returns the wait-for-q value emulating stragglers (q = n - #suspected).
    Seeded: replayable across the whole fleet without coordination.
    """

    def __init__(self, num_hosts, *, crashes=None, stragglers=None, seed=1234):
        self.num_hosts = int(num_hosts)
        self.crashes = dict(crashes or {})
        self.stragglers = dict(stragglers or {})
        self.seed = seed

    def dead_hosts(self, step):
        return {h for h, at in self.crashes.items() if step >= at}

    def byz_mask(self, step, num_workers, *, base_mask=None):
        """Worker slots on dead hosts (slots split evenly across hosts)."""
        mask = (
            np.zeros(num_workers, bool)
            if base_mask is None else np.asarray(base_mask, bool).copy()
        )
        per_host = num_workers // self.num_hosts
        for h in self.dead_hosts(step):
            mask[h * per_host : (h + 1) * per_host] = True
        return mask

    def subset(self, step, n, f):
        """q for the wait-n-f path this step: full minus suspected laggards,
        never below n - f (the tolerance budget)."""
        rng = np.random.default_rng((self.seed, step))
        slow = sum(
            1 for h, prob in self.stragglers.items()
            if h not in self.dead_hosts(step) and rng.random() < prob
        )
        return max(n - f, n - slow)


def _cli(argv=None):
    """Cluster-config writer CLI (counterpart of the reference's interactive
    per-app ``config_generator.py`` :30-90, which asks for the host lists and
    per-task role/GAR/attack on stdin and writes one JSON per node).

      python -m garfield_tpu.utils.multihost out/ --workers h1 h2 --ps h0 \\
          --gar krum --fw 1 --attack lie

    Writes ``out/task_<role><i>.json`` for every task; with no host flags it
    prompts interactively like the reference.
    """
    import argparse

    p = argparse.ArgumentParser(description="Garfield cluster config writer")
    p.add_argument("out_dir", help="Directory for the per-task config files.")
    p.add_argument("--workers", nargs="*", default=None,
                   help="Worker host[:port] list.")
    p.add_argument("--ps", nargs="*", default=[],
                   help="Parameter-server host[:port] list.")
    p.add_argument("--gar", default="average")
    p.add_argument("--attack", default=None)
    p.add_argument("--fw", type=int, default=0)
    p.add_argument("--fps", type=int, default=0)
    args = p.parse_args(argv)

    workers, ps = args.workers, list(args.ps)
    if workers is None:  # interactive, like config_generator.py
        workers = input("Worker hosts (space-separated host[:port]): ").split()
        if not ps:  # keep an explicitly passed --ps list
            ps = input("PS hosts (space-separated, empty for none): ").split()
    if not workers:
        raise SystemExit("config needs at least one worker host (--workers).")
    if not (0 <= args.fw) or args.fw * 2 >= len(workers):
        raise SystemExit(
            f"--fw {args.fw} incompatible with {len(workers)} workers "
            f"(need 0 <= 2*fw < workers, the apps' contract)."
        )
    if not (0 <= args.fps) or (ps and args.fps * 2 >= len(ps)) or (args.fps and not ps):
        raise SystemExit(
            f"--fps {args.fps} incompatible with {len(ps)} ps hosts "
            f"(need 0 <= 2*fps < ps)."
        )
    os.makedirs(args.out_dir, exist_ok=True)
    garfield = {"gar": args.gar, "fw": args.fw, "fps": args.fps}
    if args.attack:
        garfield["attack"] = args.attack
    written = []
    for role, hosts in (("ps", ps), ("worker", workers)):
        for i in range(len(hosts)):
            path = os.path.join(args.out_dir, f"task_{role}{i}.json")
            generate_config(
                path, workers=workers, ps=ps,
                task_type=role, task_index=i, **garfield,
            )
            written.append(path)
    tools.info(f"[multihost] wrote {len(written)} config(s) to {args.out_dir}")
    return written


if __name__ == "__main__":  # pragma: no cover - exercised via tests calling _cli
    _cli()

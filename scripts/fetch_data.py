#!/usr/bin/env python3
"""Fetch the real datasets into GARFIELD_TPU_DATA_DIR (default ~/data).

The counterpart of the reference's automatic acquisition — torchvision
``download=True`` (pytorch_impl/libs/garfieldpp/datasets.py:181-215) and the
tfds percent-split loader (tensorflow_impl/libs/dataset.py:41-87). This repo
runs in zero-egress environments, so acquisition is a separate, stdlib-only
script for egress-enabled hosts; the library itself transparently falls back
to the deterministic synthetic surrogate when files are absent
(garfield_tpu/data/__init__.py).

Produces exactly the layouts ``garfield_tpu.data`` reads:
  mnist:    <root>/{train,t10k}-{images-idx3,labels-idx1}-ubyte.gz
  cifar10:  <root>/cifar-10-batches-py/{data_batch_1..5,test_batch}
  cifar100: <root>/cifar-100-python/{train,test}
  pima:     <root>/pima_diabetes.csv   (header + 768 rows)

Usage:
  python scripts/fetch_data.py [--root DIR] [--datasets mnist cifar10 ...]
"""

import argparse
import io
import os
import pathlib
import sys
import tarfile
import urllib.request

# Mirrors, first-hit-wins: the same sources torchvision's MNIST mirror list
# and CIFAR download use (datasets.py:181-215 era), plus the canonical pima
# CSV (the UCI original was withdrawn; this is the standard mirror).
URLS = {
    "mnist": [
        ("https://storage.googleapis.com/cvdf-datasets/mnist/", [
            "train-images-idx3-ubyte.gz",
            "train-labels-idx1-ubyte.gz",
            "t10k-images-idx3-ubyte.gz",
            "t10k-labels-idx1-ubyte.gz",
        ]),
        ("https://ossci-datasets.s3.amazonaws.com/mnist/", [
            "train-images-idx3-ubyte.gz",
            "train-labels-idx1-ubyte.gz",
            "t10k-images-idx3-ubyte.gz",
            "t10k-labels-idx1-ubyte.gz",
        ]),
    ],
    "cifar10": "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
    "cifar100": "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz",
    "pima": ("https://raw.githubusercontent.com/jbrownlee/Datasets/master/"
             "pima-indians-diabetes.data.csv"),
}

PIMA_HEADER = ("pregnancies,glucose,blood_pressure,skin_thickness,insulin,"
               "bmi,diabetes_pedigree,age,outcome\n")


def _urllib_download(url, timeout=120):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def fetch_mnist(root, download=_urllib_download):
    """idx-ubyte .gz files straight into <root>/ (data/__init__ reads .gz)."""
    last_err = None
    for base, names in URLS["mnist"]:
        try:
            for name in names:
                dest = root / name
                if dest.exists():
                    continue
                dest.write_bytes(download(base + name))
            return [root / n for n in URLS["mnist"][0][1]]
        except Exception as exc:  # try the next mirror
            last_err = exc
    raise RuntimeError(f"all MNIST mirrors failed: {last_err}")


def _extract_tar(raw, root, expect_prefix):
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r:gz") as tar:
        for member in tar.getmembers():
            if not member.name.startswith(expect_prefix):
                raise RuntimeError(
                    f"unexpected member {member.name!r} (want "
                    f"{expect_prefix!r}/...)"
                )
        tar.extractall(root, filter="data")
    return root / expect_prefix


def fetch_cifar(root, name="cifar10", download=_urllib_download):
    """Extract the python-pickle tarball into the layout the loader reads."""
    prefix = "cifar-10-batches-py" if name == "cifar10" else "cifar-100-python"
    if (root / prefix).exists():
        return root / prefix
    return _extract_tar(download(URLS[name]), root, prefix)


def fetch_pima(root, download=_urllib_download):
    """CSV with header (the loader does skip_header=1); the mirror ships
    the raw 768 rows without one."""
    dest = root / "pima_diabetes.csv"
    if dest.exists():
        return dest
    body = download(URLS["pima"]).decode("utf-8").strip()
    first = body.splitlines()[0]
    if any(c.isalpha() for c in first):  # mirror already has a header
        dest.write_text(body + "\n")
    else:
        dest.write_text(PIMA_HEADER + body + "\n")
    return dest


FETCHERS = {
    "mnist": fetch_mnist,
    "cifar10": lambda root, download=_urllib_download: fetch_cifar(
        root, "cifar10", download),
    "cifar100": lambda root, download=_urllib_download: fetch_cifar(
        root, "cifar100", download),
    "pima": fetch_pima,
}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--root", type=str, default=os.environ.get(
        "GARFIELD_TPU_DATA_DIR", str(pathlib.Path.home() / "data")))
    p.add_argument("--datasets", nargs="*", default=sorted(FETCHERS))
    args = p.parse_args(argv)
    root = pathlib.Path(args.root)
    root.mkdir(parents=True, exist_ok=True)
    for name in args.datasets:
        if name not in FETCHERS:
            raise SystemExit(
                f"unknown dataset {name!r}; available: {sorted(FETCHERS)}"
            )
        print(f"fetching {name} -> {root}", flush=True)
        out = FETCHERS[name](root)
        print(f"  ok: {out}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])

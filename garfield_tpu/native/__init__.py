"""Native (C++) runtime: JIT build system + ctypes bindings.

Counterpart of the reference's native loader
(pytorch_impl/libs/native/__init__.py:19-152): that one scans ``so_*``/
``py_*`` directories, resolves ``.deps`` files and compiles each module via
``torch.utils.cpp_extension.load`` at import time with env knobs
NATIVE_OPT/NATIVE_STD/NATIVE_QUIET (:37-50). This one compiles the sources
under ``src/`` into one shared object with g++ (no pybind11 in this image;
the Python boundary is a C ABI over ctypes), caches it by content hash under
``~/.cache/garfield_tpu/native`` (incremental: same sources + flags => reuse),
and exposes typed numpy wrappers.

Env knobs (reference parity):
  GARFIELD_NATIVE_OPT     extra optimization flags (default "-O3");
                          "-O0 -g" gives the reference's debug build (:72-74)
  GARFIELD_NATIVE_STD     C++ standard (default "c++17")
  GARFIELD_NATIVE_QUIET   suppress build logging
  GARFIELD_NATIVE_DISABLE force-disable (``available()`` returns False)

Import never raises: if the toolchain or build fails, ``available()`` is
False and the ``native-*`` GARs simply do not register (the reference's
``import native`` try/except, aggregators/krum.py:23-26).
"""

import ctypes
import hashlib
import os
import pathlib
import subprocess
import sys

import numpy as np

from ..utils import tools

__all__ = [
    "available",
    "load",
    "krum",
    "median",
    "bulyan",
    "brute",
    "num_threads",
    "MultiBuffer",
]

_SRC_DIR = pathlib.Path(__file__).parent / "src"
_lib = None
_load_error = None


def _cache_dir():
    root = os.environ.get(
        "GARFIELD_NATIVE_CACHE",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "garfield_tpu",
            "native",
        ),
    )
    return pathlib.Path(root)


def _build():
    """Compile src/*.cpp into one cached .so; return its path."""
    opt = os.environ.get("GARFIELD_NATIVE_OPT", "-O3").split()
    std = os.environ.get("GARFIELD_NATIVE_STD", "c++17")
    sources = sorted(_SRC_DIR.glob("*.cpp"))
    headers = sorted(_SRC_DIR.glob("*.hpp"))
    if not sources:
        raise FileNotFoundError(f"no native sources under {_SRC_DIR}")
    flags = [
        f"-std={std}", "-fPIC", "-shared", "-pthread",
        "-fvisibility=hidden", *opt,
    ]
    if __debug__ and "NDEBUG" not in " ".join(opt):
        pass  # keep asserts, mirroring the reference's __debug__ coupling
    else:
        flags.append("-DNDEBUG")
    h = hashlib.sha256()
    for path in sources + headers:
        h.update(path.name.encode())
        h.update(path.read_bytes())
    h.update(" ".join(flags).encode())
    out = _cache_dir() / h.hexdigest()[:16] / "libgarfield_native.so"
    if out.exists():
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    cmd = ["g++", *flags, *(str(s) for s in sources), "-o", str(out) + ".tmp"]
    if not os.environ.get("GARFIELD_NATIVE_QUIET"):
        tools.info(f"[native] building: {' '.join(cmd)}")
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(str(out) + ".tmp", out)
    return out


def load():
    """Build (if needed) and dlopen the native library; cached."""
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    if os.environ.get("GARFIELD_NATIVE_DISABLE"):
        _load_error = RuntimeError("disabled via GARFIELD_NATIVE_DISABLE")
        return None
    try:
        lib = ctypes.CDLL(str(_build()))
    except Exception as exc:  # toolchain missing / build failure
        _load_error = exc
        if not os.environ.get("GARFIELD_NATIVE_QUIET"):
            tools.warning(f"[native] unavailable: {exc}")
        return None
    i64 = ctypes.c_int64
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    for suffix, ptr in (("f32", f32p), ("f64", f64p)):
        getattr(lib, f"gt_krum_{suffix}").argtypes = [ptr, i64, i64, i64, i64, ptr]
        getattr(lib, f"gt_median_{suffix}").argtypes = [ptr, i64, i64, ptr]
        getattr(lib, f"gt_bulyan_{suffix}").argtypes = [ptr, i64, i64, i64, i64, ptr]
        getattr(lib, f"gt_brute_{suffix}").argtypes = [ptr, i64, i64, i64, ptr]
    lib.gt_num_threads.restype = i64
    lib.gt_multibuffer_new.argtypes = [i64]
    lib.gt_multibuffer_new.restype = ctypes.c_void_p
    lib.gt_multibuffer_free.argtypes = [ctypes.c_void_p]
    lib.gt_multibuffer_write.argtypes = [ctypes.c_void_p, i64, u8p, i64]
    lib.gt_multibuffer_write.restype = i64
    lib.gt_multibuffer_wait.argtypes = [ctypes.c_void_p, i64, i64, i64]
    lib.gt_multibuffer_wait.restype = i64
    lib.gt_multibuffer_read.argtypes = [
        ctypes.c_void_p, i64, u8p, i64, ctypes.POINTER(i64)
    ]
    lib.gt_multibuffer_read.restype = i64
    lib.gt_multibuffer_version.argtypes = [ctypes.c_void_p, i64]
    lib.gt_multibuffer_version.restype = i64
    _lib = lib
    return _lib


def available():
    return load() is not None


def _as_2d(gradients, dtype=None):
    if isinstance(gradients, (list, tuple)):
        g = np.stack([np.asarray(v).reshape(-1) for v in gradients])
    else:
        g = np.asarray(gradients)
    if g.ndim != 2:
        raise ValueError(f"expected (n, d) stack, got shape {g.shape}")
    if dtype is None:
        dtype = np.float64 if g.dtype == np.float64 else np.float32
    return np.ascontiguousarray(g, dtype=dtype)


def _ptr(a):
    ct = ctypes.c_double if a.dtype == np.float64 else ctypes.c_float
    return a.ctypes.data_as(ctypes.POINTER(ct))


def _dispatch(name, g):
    lib = load()
    if lib is None:
        raise RuntimeError(f"native runtime unavailable: {_load_error}")
    suffix = "f64" if g.dtype == np.float64 else "f32"
    return getattr(lib, f"gt_{name}_{suffix}")


def krum(gradients, f, m=None):
    """Native Multi-Krum (py_krum/krum.cpp counterpart)."""
    g = _as_2d(gradients)
    out = np.empty(g.shape[1], dtype=g.dtype)
    _dispatch("krum", g)(_ptr(g), g.shape[0], g.shape[1], int(f),
                         int(m) if m else 0, _ptr(out))
    return out


def median(gradients):
    """Native coordinate-wise lower median (py_median counterpart)."""
    g = _as_2d(gradients)
    out = np.empty(g.shape[1], dtype=g.dtype)
    _dispatch("median", g)(_ptr(g), g.shape[0], g.shape[1], _ptr(out))
    return out


def bulyan(gradients, f, m=None):
    """Native Bulyan (py_bulyan counterpart)."""
    g = _as_2d(gradients)
    out = np.empty(g.shape[1], dtype=g.dtype)
    _dispatch("bulyan", g)(_ptr(g), g.shape[0], g.shape[1], int(f),
                           int(m) if m else 0, _ptr(out))
    return out


def brute(gradients, f):
    """Native brute min-diameter selection (py_brute counterpart)."""
    g = _as_2d(gradients)
    out = np.empty(g.shape[1], dtype=g.dtype)
    _dispatch("brute", g)(_ptr(g), g.shape[0], g.shape[1], int(f), _ptr(out))
    return out


def num_threads():
    lib = load()
    return int(lib.gt_num_threads()) if lib else 0


class MultiBuffer:
    """MRMW atomic register array with blocking reads (T9 counterpart).

    ``write(slot, bytes)`` replaces the slot value (last-writer-wins);
    ``read(slot, min_version, timeout_ms)`` blocks until the slot has been
    written at least ``min_version`` times, then returns (version, bytes).
    Used by the multi-host control plane to hand serialized models/gradients
    between threads without polling (the reference's history lists poll at
    1 ms, grpc_message_exchange_servicer.py:58-65).
    """

    def __init__(self, nslots):
        lib = load()
        if lib is None:
            raise RuntimeError(f"native runtime unavailable: {_load_error}")
        self._lib = lib
        self._handle = lib.gt_multibuffer_new(int(nslots))
        self.nslots = int(nslots)

    def write(self, slot, data):
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(bytes(data))
        return int(self._lib.gt_multibuffer_write(
            self._handle, int(slot), buf, len(data)
        ))

    def read(self, slot, min_version=1, timeout_ms=-1):
        size = int(self._lib.gt_multibuffer_wait(
            self._handle, int(slot), int(min_version), int(timeout_ms)
        ))
        if size < 0:
            raise TimeoutError(
                f"multibuffer slot {slot} not at version {min_version} "
                f"within {timeout_ms} ms"
            )
        out = (ctypes.c_uint8 * size)()
        version = ctypes.c_int64(0)
        actual = int(self._lib.gt_multibuffer_read(
            self._handle, int(slot), out, size, ctypes.byref(version)
        ))
        if actual < 0:  # concurrent grow between wait and read: retry
            return self.read(slot, min_version, timeout_ms)
        # A concurrent write may have shrunk the slot; `actual` is the real
        # payload length, so never hand back stale padding bytes.
        return int(version.value), bytes(out)[:actual]

    def version(self, slot):
        return int(self._lib.gt_multibuffer_version(self._handle, int(slot)))

    def close(self):
        if self._handle:
            self._lib.gt_multibuffer_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

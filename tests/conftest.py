"""Test configuration: force a virtual 8-device CPU platform.

This is the fake-backend the reference lacked (SURVEY §4): every distributed
construct is testable single-process by running the SPMD program over 8
host-local CPU devices.

Two paths, because jax may already be preloaded (and a TPU PJRT plugin
registered) by the interpreter's sitecustomize before this file runs:
  - if jax is not yet imported, plain env vars do the job;
  - if it is, ``jax.config.update`` still wins as long as no backend has been
    initialized — it both overrides the platform choice and sets the virtual
    CPU device count, and keeps the TPU plugin from ever being initialized
    (its init can block on an unavailable device tunnel).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)

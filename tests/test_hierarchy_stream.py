"""End-to-end multi-wave streaming ingest over the host exchange plane.

The federated arrival pattern the hierarchy exists for: W worker peers
publish typed wire frames over R rounds through ``PeerExchange``, the
collector's PRE-REGISTERED waiters (``collect_begin``) hand each frame to
``StreamingAggregator.wire_transform`` in the waiter threads (decode +
bucket folding overlap the quorum wait), and the finalized aggregate must
equal the batch hierarchy over the stack in the reducer's actual arrival
order — bitwise. Slow-marked and registered in conftest._RUN_LAST: it
spins a real TCP mesh.
"""

import socket
import threading

import numpy as np
import pytest

pytest.importorskip("garfield_tpu.native")
from garfield_tpu import native

if native.load() is None:  # no compiler / native runtime in this env
    pytest.skip("native runtime unavailable", allow_module_level=True)

from garfield_tpu.aggregators import hierarchy
from garfield_tpu.utils import wire
from garfield_tpu.utils.exchange import PeerExchange


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


@pytest.mark.slow
def test_multi_wave_exchange_ingest_matches_batch():
    workers, rounds, d, bucket = 4, 16, 256, 8
    n = workers * rounds  # 64 clients over 16 waves
    f = 3
    hosts = [f"127.0.0.1:{p}" for p in _ports(workers + 1)]
    peers = [PeerExchange(i, hosts) for i in range(workers + 1)]
    collector, senders = peers[0], peers[1:]

    rng = np.random.default_rng(99)
    grads = rng.normal(size=(rounds, workers, d)).astype(np.float32)

    red = hierarchy.StreamingAggregator(
        n, f, bucket_gar="krum", top_gar="median", bucket_size=bucket,
        wave_buckets=2)
    arrival = {}
    arrival_lock = threading.Lock()

    def transform(idx, payload):
        vec = wire.decode(payload)
        pos = red.push(vec)
        with arrival_lock:
            arrival[pos] = np.asarray(vec, np.float32)
        return pos

    try:
        for step in range(rounds):
            wait = collector.collect_begin(
                step, q=workers, peers=list(range(1, workers + 1)),
                timeout_ms=30_000, transform=transform)
            for w, sender in enumerate(senders):
                sender.publish(step, wire.encode(grads[step, w]), to=[0])
            got = wait()
            assert len(got) == workers
            assert all(isinstance(v, int) for v in got.values())
        streamed = red.finalize()
    finally:
        for p in peers:
            p.close()

    assert len(arrival) == n
    stack = np.stack([arrival[i] for i in range(n)])
    batch = np.asarray(hierarchy.aggregate(
        stack, f, bucket_gar="krum", top_gar="median", bucket_size=bucket))
    assert np.array_equal(streamed, batch)


@pytest.mark.slow
def test_exchange_ingest_attributes_codec_rejects():
    """A Byzantine sender's corrupted frame must surface as that peer's
    attributable WireError in the collect result — ban evidence — while
    the honest frames still fold into the reducer."""
    workers, d = 3, 64
    hosts = [f"127.0.0.1:{p}" for p in _ports(workers + 1)]
    peers = [PeerExchange(i, hosts) for i in range(workers + 1)]
    collector, senders = peers[0], peers[1:]
    red = hierarchy.StreamingAggregator(
        workers - 1, 0, bucket_gar="median", bucket_size=2)

    def transform(idx, payload):
        return red.push(wire.decode(payload))

    try:
        wait = collector.collect_begin(
            0, q=workers, peers=list(range(1, workers + 1)),
            timeout_ms=30_000, transform=transform)
        rng = np.random.default_rng(5)
        senders[0].publish(0, wire.encode(rng.normal(size=d)), to=[0])
        frame = bytearray(wire.encode(rng.normal(size=d)))
        frame[-1] ^= 0xFF  # payload flip: CRC must catch it
        senders[1].publish(0, bytes(frame), to=[0])
        senders[2].publish(0, wire.encode(rng.normal(size=d)), to=[0])
        got = wait()
    finally:
        for p in peers:
            p.close()

    assert isinstance(got[2], wire.WireError)
    assert sorted(v for k, v in got.items() if k != 2) == [0, 1]
    assert red.finalize().shape == (d,)


@pytest.mark.slow
def test_batch_harvest_matches_per_frame_collect():
    """ISSUE 20: routing a multi-frame collect through
    ``wire_batch_transform`` (one decode_batch_into harvest per quorum)
    must produce the same aggregate as the per-frame ``wire_transform``
    waiters — and a forged frame still surfaces as its sender's indexed
    WireError while batchmates ingest. The batch harvest ingests in
    sorted-peer order deterministically, so the reference reducer
    replays that exact order per round."""
    workers, rounds, d, bucket = 4, 8, 128, 8
    n = workers * rounds
    f = 1
    hosts = [f"127.0.0.1:{p}" for p in _ports(workers + 1)]
    peers = [PeerExchange(i, hosts) for i in range(workers + 1)]
    collector, senders = peers[0], peers[1:]

    rng = np.random.default_rng(42)
    grads = rng.normal(size=(rounds, workers, d)).astype(np.float32)

    red = hierarchy.StreamingAggregator(
        n, f, bucket_gar="krum", top_gar="median", bucket_size=bucket,
        wave_buckets=2, d=d)
    ref = hierarchy.StreamingAggregator(
        n, f, bucket_gar="krum", top_gar="median", bucket_size=bucket,
        wave_buckets=2, d=d)
    try:
        for step in range(rounds):
            wait = collector.collect_begin(
                step, q=workers, peers=list(range(1, workers + 1)),
                timeout_ms=30_000,
                batch_transform=red.wire_batch_transform)
            frames = {}
            for w, sender in enumerate(senders):
                frames[1 + w] = wire.encode(grads[step, w])
                sender.publish(step, frames[1 + w], to=[0])
            got = wait()
            assert sorted(got) == list(range(1, workers + 1))
            assert all(isinstance(v, int) for v in got.values())
            # the batch harvest ingests in sorted peer order
            for p in sorted(frames):
                ref.push_frame(frames[p])
        streamed = red.finalize()
    finally:
        for p in peers:
            p.close()
    assert np.array_equal(streamed, ref.finalize())


@pytest.mark.slow
def test_batch_harvest_attributes_forged_frame():
    workers, d = 3, 64
    hosts = [f"127.0.0.1:{p}" for p in _ports(workers + 1)]
    peers = [PeerExchange(i, hosts) for i in range(workers + 1)]
    collector, senders = peers[0], peers[1:]
    red = hierarchy.StreamingAggregator(
        workers - 1, 0, bucket_gar="median", bucket_size=2, d=d)
    try:
        wait = collector.collect_begin(
            0, q=workers, peers=list(range(1, workers + 1)),
            timeout_ms=30_000, batch_transform=red.wire_batch_transform)
        rng = np.random.default_rng(5)
        senders[0].publish(0, wire.encode(rng.normal(size=d)), to=[0])
        frame = bytearray(wire.encode(rng.normal(size=d)))
        frame[-1] ^= 0xFF  # payload flip: CRC must catch it
        senders[1].publish(0, bytes(frame), to=[0])
        senders[2].publish(0, wire.encode(rng.normal(size=d)), to=[0])
        got = wait()
    finally:
        for p in peers:
            p.close()
    assert isinstance(got[2], wire.WireError)
    assert sorted(v for k, v in got.items() if k != 2) == [0, 1]
    assert red.finalize().shape == (d,)

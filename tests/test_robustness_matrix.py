"""Robustness matrix: every robust GAR vs every gradient attack.

The reference validates rules only implicitly (training runs + the
``upper_bound``/``influence`` formulas, SURVEY §4); here each (rule, attack)
cell is checked directly at the stack level: with n=11 workers, f=2 Byzantine
rows poisoned by the attack, the robust aggregate must stay near the honest
mean — and for the blatant attacks, beat plain averaging by an order of
magnitude. This is the Byzantine-tolerance contract the reference's paper
claims, as an executable test.
"""

import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu.aggregators import gars
from garfield_tpu.attacks import apply_gradient_attack

# n = 11 admits every rule's contract at f = 2 (bulyan needs n >= 4f+3).
N, F, D = 11, 2, 64
SIGMA = 0.01
RULES = ["krum", "median", "bulyan", "brute", "aksel", "condense", "tmean",
         "cclip"]
# reverse/empire shove the Byzantine rows far from the cluster; random
# replaces them with unit-scale noise (moderate displacement); lie/drop are
# designed to be subtle (stay within/near the honest spread).
STRONG = ["reverse", "empire"]
MODERATE = ["random"]
SUBTLE = ["lie", "drop"]


def _stack(seed):
    rng = np.random.default_rng(seed)
    mu = np.ones(D, np.float32)
    honest = mu + SIGMA * rng.standard_normal((N, D)).astype(np.float32)
    return jnp.asarray(honest), jnp.asarray(mu)


def _attacked(attack, g, seed):
    mask = jnp.arange(N) >= N - F  # last F rows Byzantine
    key = jax.random.PRNGKey(seed)
    return apply_gradient_attack(attack, g, mask, key=key), mask


def _err(agg, mu):
    return float(jnp.linalg.norm(agg - mu))


@pytest.mark.parametrize("attack", STRONG + MODERATE + SUBTLE)
@pytest.mark.parametrize("rule", RULES)
def test_rule_bounds_attack(rule, attack):
    g, mu = _stack(seed=zlib.crc32(f"{rule}-{attack}".encode()))
    attacked, _ = _attacked(attack, g, seed=7)
    agg = gars[rule].unchecked(attacked, f=F)
    err = _err(agg, mu)
    tol = 5 * SIGMA * np.sqrt(D)  # a few honest-noise lengths from the mean
    assert np.isfinite(err), f"{rule} vs {attack}: non-finite aggregate"
    assert err <= tol, f"{rule} vs {attack}: err {err:.4f} > tol {tol:.4f}"
    if attack in STRONG + MODERATE:
        ratio = 10 if attack in STRONG else 3
        err_avg = _err(jnp.mean(attacked, axis=0), mu)
        assert err <= err_avg / ratio, (
            f"{rule} vs {attack}: robust err {err:.4f} not << "
            f"average err {err_avg:.4f}"
        )


@pytest.mark.parametrize("attack", STRONG)
def test_average_is_broken_by_strong_attacks(attack):
    """Sanity: the non-robust baseline really is destroyed (otherwise the
    matrix above proves nothing)."""
    g, mu = _stack(seed=3)
    attacked, _ = _attacked(attack, g, seed=11)
    err_avg = _err(gars["average"].unchecked(attacked), mu)
    assert err_avg > 20 * 5 * SIGMA * np.sqrt(D)


# --- adaptive rows (DESIGN.md §16) -----------------------------------------
#
# The stack-level closed loop: a bisection controller (attacks/adaptive.py)
# plays the lie magnitude against the rule's actual admission each round —
# feedback is the fraction of the fake's excess direction present in the
# aggregate, the exact signal a real attacker probes from the broadcast
# model delta. ``async`` composes the bounded-staleness discount weights
# into the rows (utils/rounds.py), the same composition the async PS
# applies.

ADAPTIVE_RULES = ["krum", "bulyan", "hier-krum"]


def _adaptive_lie_rounds(rule, mode, T=48):
    from garfield_tpu.attacks import adaptive
    from garfield_tpu.utils import rounds

    cfg = adaptive.configure(
        "adaptive-lie", {"mag_max": 6.0}, num_workers=N, f=F
    )
    lo, hi = cfg.mag_min, cfg.mag_max
    rng = np.random.default_rng(zlib.crc32(f"{rule}-{mode}".encode()))
    mu = np.ones(D, np.float32)
    mask = jnp.arange(N) >= N - F
    errs, max_admitted = [], 0.0
    for _ in range(T):
        honest = mu + SIGMA * rng.standard_normal((N, D)).astype(np.float32)
        z = float(adaptive.played_magnitude(lo, hi))
        attacked = apply_gradient_attack(
            "lie", jnp.asarray(honest), mask, z=z
        )
        if mode == "async":
            taus = np.zeros(N, np.int64)
            taus[1] = 2  # one stale honest rank, discounted not dropped
            w = rounds.staleness_weights(taus, decay=0.5, max_staleness=4)
            attacked = attacked * jnp.asarray(w)[:, None]
        agg = np.asarray(gars[rule].unchecked(attacked, f=F))
        hm = honest[: N - F].mean(axis=0)
        u = np.asarray(attacked[N - 1]) - hm  # the fake's excess direction
        frac = float(np.dot(agg - hm, u) / max(np.dot(u, u), 1e-12))
        detected = frac < 0.05
        if not detected:
            max_admitted = max(max_admitted, z)
        lo, hi = (float(v) for v in adaptive.update_bracket(
            lo, hi, detected, mag_min=cfg.mag_min, mag_max=cfg.mag_max,
        ))
        errs.append(float(np.linalg.norm(agg - mu)))
    return errs, max_admitted, (lo, hi)


@pytest.mark.parametrize("mode", ["sync", "async"])
@pytest.mark.parametrize("rule", ADAPTIVE_RULES)
def test_adaptive_lie_converges_and_stays_bounded(rule, mode):
    """Both halves of the adaptive contract at stack level: the attacker
    SUSTAINS a magnitude well above the static ALIE z without being
    excluded (it measurably beats the oblivious attack), and the rule
    still bounds the adapted aggregate within the matrix tolerance (the
    reason escalating to a stronger rule restores the accuracy bar)."""
    from garfield_tpu.attacks import LIE_Z

    errs, max_admitted, (lo, hi) = _adaptive_lie_rounds(rule, mode)
    tol = 5 * SIGMA * np.sqrt(D)
    assert all(np.isfinite(errs)), f"{rule}/{mode}: non-finite aggregate"
    assert max(errs) <= tol, (
        f"{rule}/{mode}: adapted attack broke the bound "
        f"({max(errs):.4f} > {tol:.4f})"
    )
    assert max_admitted > 1.2 * LIE_Z, (
        f"{rule}/{mode}: controller only sustained z={max_admitted:.3f} "
        f"(static ALIE is {LIE_Z})"
    )
    # Converged: the bracket closed far inside its initial width (the
    # re-expansion keeps probing, so it never pinches to a point).
    assert hi - lo < 2.0, f"{rule}/{mode}: bracket never converged"


@pytest.mark.parametrize("rule", [r for r in RULES if r != "condense"])
def test_permutation_invariant_under_attack(rule):
    """Shuffling worker rows must not change the aggregate (the mesh slot a
    Byzantine worker occupies is arbitrary). condense is excluded: it mixes
    the median with gradient 0 by design (condense.py), so it is
    order-dependent per the reference semantics."""
    g, _ = _stack(seed=5)
    attacked, _ = _attacked("reverse", g, seed=13)
    perm = np.random.default_rng(0).permutation(N)
    a1 = np.asarray(gars[rule].unchecked(attacked, f=F))
    a2 = np.asarray(gars[rule].unchecked(attacked[perm], f=F))
    np.testing.assert_allclose(a1, a2, rtol=2e-5, atol=2e-6)

"""Pre-activation ResNet (counterpart of garfieldpp/models/preact_resnet.py)."""

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm


class PreActBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        out = nn.relu(norm(train, dtype=self.dtype)(x))
        shortcut = x
        if self.stride != 1 or x.shape[-1] != self.features:
            shortcut = conv1x1(self.features, stride=self.stride, dtype=self.dtype)(out)
        out = conv(self.features, 3, self.stride, padding=1, dtype=self.dtype)(out)
        out = conv(self.features, 3, 1, padding=1, dtype=self.dtype)(
            nn.relu(norm(train, dtype=self.dtype)(out)))
        return out + shortcut


class PreActResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = conv(64, 3, 1, padding=1, dtype=self.dtype)(x)
        for stage, nblocks in enumerate(self.stage_sizes):
            for i in range(nblocks):
                stride = 2 if stage > 0 and i == 0 else 1
                x = PreActBlock(64 * 2 ** stage, stride, dtype=self.dtype)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


def PreActResNet18(num_classes=10, dtype=jnp.float32):
    return PreActResNet((2, 2, 2, 2), num_classes, dtype)

"""Device-mesh construction and logical-slot folding.

TPU-native replacement for the reference's process-group plumbing
(pytorch_impl/applications/Garfield_CC/trainer.py:347-380 ``init_groups`` /
``init_processes``): instead of building NCCL/Gloo groups per (PS, workers)
pair, we lay out one ``jax.sharding.Mesh`` whose named axes carry the node
roles ("workers", "ps", "nodes"), and every collective rides the ICI mesh as
an XLA op (all_gather/psum) inside jit.

The reference runs one OS process per logical node; here logical nodes are
*slots folded onto physical devices* (SURVEY §7 "hard parts"): a mesh axis of
size k hosts n >= k logical slots, each device vmapping over its n/k local
slots. ``fold`` computes that factorization.
"""

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "fold", "replicated", "sharded", "shard_map", "P"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """Version-tolerant ``jax.shard_map`` for the topology builders.

    Newer jax exposes ``jax.shard_map(..., check_vma=)``; older releases
    only ship ``jax.experimental.shard_map.shard_map(..., check_rep=)``
    (same semantics, pre-rename). Routing every topology through this
    shim keeps the whole parallel stack importable and runnable on both,
    instead of failing at trainer-build time on the older runtime.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(axes, devices=None):
    """Build a Mesh from an ordered ``{axis_name: size}`` dict.

    ``size = -1`` for at most one axis means "all remaining devices". Device
    count must equal the product of axis sizes; the axes are laid out in the
    given order over ``jax.devices()`` (ICI-adjacent devices end up adjacent
    on the innermost axis, which is where the gradient all_gather runs).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    names = list(axes)
    sizes = [axes[n] for n in names]
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may have size -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if known == 0 or len(devices) % known:
            raise ValueError(
                f"cannot infer -1 axis: {len(devices)} devices, others {known}"
            )
        sizes[sizes.index(-1)] = len(devices) // known
    total = math.prod(sizes)
    if total != len(devices):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} wants {total} devices, "
            f"got {len(devices)}"
        )
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def fold(num_logical, axis_size, what="slots"):
    """Number of logical slots per device shard; requires exact divisibility.

    Reference analog: none — torch runs one process per node. Folding lets n
    logical workers run SPMD on k chips (n % k == 0), each chip vmapping over
    its n/k slots.
    """
    if num_logical % axis_size:
        raise ValueError(
            f"{num_logical} logical {what} do not fold onto a mesh axis of "
            f"size {axis_size} (must divide exactly)"
        )
    return num_logical // axis_size


def replicated(mesh):
    """NamedSharding replicating an array over the whole mesh."""
    return NamedSharding(mesh, P())


def sharded(mesh, *axis_names):
    """NamedSharding splitting an array's leading dims over named axes."""
    return NamedSharding(mesh, P(*axis_names))

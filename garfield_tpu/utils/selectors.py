"""Loss / optimizer selectors (optax-based).

Counterpart of pytorch_impl/libs/garfieldpp/tools.py: select_loss (:47-57,
nll/cross-entropy/bce), select_optimizer (:107-123, sgd/adam/adamw/rmsprop/
adagrad) and adjust_learning_rate (:165-172, lr *= 0.2 scheduling).
"""

import jax
import jax.flatten_util
import jax.numpy as jnp
import optax


def select_loss(name):
    """Return ``loss_fn(outputs, labels) -> scalar`` by name.

    Supported: ``nll`` (expects log-probabilities), ``cross-entropy`` /
    ``crossentropy`` (expects raw logits), ``bce`` / ``binary-cross-entropy``
    (expects a *probability* per example like torch nn.BCELoss — the pima
    model ends in sigmoid), ``bce-logits`` / ``bce-with-logits`` (expects a
    single raw logit per example).
    """
    name = name.lower()
    if name == "nll":
        def nll(log_probs, labels):
            return -jnp.mean(
                jnp.take_along_axis(log_probs, labels[:, None], axis=-1)
            )
        return nll
    if name in ("cross-entropy", "crossentropy", "ce"):
        def ce(logits, labels):
            return jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, labels)
            )
        return ce
    if name in ("bce", "binary-cross-entropy"):
        # torch nn.BCELoss (tools.py:55) expects *probabilities* (the pima
        # model ends in sigmoid, models/pimanet.py) — not logits.
        def bce(probs, labels):
            p = jnp.clip(probs.reshape(labels.shape), 1e-7, 1.0 - 1e-7)
            labels = labels.astype(p.dtype)
            return -jnp.mean(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))
        return bce
    if name in ("bce-logits", "bce-with-logits"):
        def bce_logits(logits, labels):
            logits = logits.reshape(labels.shape)
            return jnp.mean(
                optax.sigmoid_binary_cross_entropy(logits, labels.astype(logits.dtype))
            )
        return bce_logits
    raise ValueError(
        f"unknown loss {name!r}; available: nll, cross-entropy, bce, bce-logits"
    )


def select_optimizer(name, *, lr, momentum=0.0, weight_decay=0.0, **kwargs):
    """Return an ``optax.GradientTransformation`` by name.

    Mirrors the reference's optimizer table (garfieldpp/tools.py:107-123):
    sgd / adam / adamw / rmsprop / adagrad, with the reference CLI's JSON
    optimizer-args (lr, momentum, weight_decay) accepted uniformly.
    """
    name = name.lower()
    if callable(lr):
        schedule = lr
    else:
        schedule = optax.constant_schedule(float(lr))
    if name == "sgd":
        tx = optax.sgd(schedule, momentum=momentum or None)
    elif name == "adam":
        tx = optax.adam(schedule, **kwargs)
    elif name == "adamw":
        tx = optax.adamw(schedule, weight_decay=weight_decay, **kwargs)
        weight_decay = 0.0  # already applied decoupled
    elif name == "rmsprop":
        tx = optax.rmsprop(schedule, momentum=momentum, **kwargs)
    elif name == "adagrad":
        tx = optax.adagrad(schedule, **kwargs)
    else:
        raise ValueError(
            f"unknown optimizer {name!r}; available: sgd, adam, adamw, rmsprop, adagrad"
        )
    if weight_decay and name != "adamw":
        # Reference applies L2 via the optimizer's weight_decay argument
        # (coupled decay) — optax equivalent is additive decay before update.
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


def adjust_learning_rate(base_lr, *, decay=0.2, every_epochs=30, iters_per_epoch=1):
    """Step-decay schedule: lr = base_lr * decay^(epoch // every_epochs).

    Counterpart of garfieldpp/tools.py:165-172 and the AggregaThor trainer's
    epoch decay (Aggregathor/trainer.py:227-229, x0.2 every 30 epochs).
    Returns an optax schedule over *iteration* count.
    """
    def schedule(step):
        epoch = step // iters_per_epoch
        return base_lr * (decay ** (epoch // every_epochs))
    return schedule


def tree_flatten_1d(tree):
    """Flatten a pytree of arrays into one 1-D vector plus an unflattener.

    The reference flattens all parameter gradients into a single 1-D tensor
    before shipping them (worker.py:93-94, tools/pytorch.py:27-64 `flatten`);
    GARs operate on those flat vectors. This is the jax equivalent.
    """
    flat, unravel = jax.flatten_util.ravel_pytree(tree)
    return flat, unravel

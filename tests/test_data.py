"""Tests for garfield_tpu.data — partitioner parity and manager semantics."""

import numpy as np
import pytest

from garfield_tpu import data


class TestDataPartitioner:
    def test_reference_partition_scheme(self):
        """Bit-compatibility with datasets.py:121-150: same rng stream, same
        slicing (first int(frac*n) of the remaining indices, shuffled)."""
        from random import Random

        n, sizes, seed = 100, [0.25, 0.25, 0.25, 0.25], 1234
        part = data.DataPartitioner(n, sizes, seed)
        rng = Random()
        rng.seed(seed)
        indexes = list(range(n))
        for k, frac in enumerate(sizes):
            plen = int(frac * n)
            tmp = indexes[0:plen]
            rng.shuffle(tmp)
            assert list(part.use(k)) == tmp
            indexes = indexes[plen:]

    def test_partitions_disjoint_and_cover(self):
        part = data.DataPartitioner(1000, [0.5, 0.3, 0.2])
        all_idx = np.concatenate([part.use(i) for i in range(3)])
        assert len(all_idx) == 1000
        assert len(set(all_idx.tolist())) == 1000

    def test_deterministic(self):
        a = data.DataPartitioner(64, [0.5, 0.5]).use(0)
        b = data.DataPartitioner(64, [0.5, 0.5]).use(0)
        np.testing.assert_array_equal(a, b)


class TestDatasetManager:
    def test_worker_partitions_disjoint(self):
        m1 = data.DatasetManager("mnist", 8, num_workers=4, size=5, rank=1)
        m2 = data.DatasetManager("mnist", 8, num_workers=4, size=5, rank=2)
        x1, _ = m1.get_train_set()
        x2, _ = m2.get_train_set()
        assert x1.shape == x2.shape
        assert not np.array_equal(x1[0], x2[0])

    def test_batch_shapes(self):
        m = data.DatasetManager("mnist", 8, num_workers=4, size=5, rank=1)
        xb, yb = m.get_train_set()
        assert xb.shape[1:] == (8, 28, 28, 1)
        assert yb.shape[1] == 8
        test_batches = m.get_test_set()
        assert test_batches[0][0].shape == (100, 28, 28, 1)

    def test_sharded_train_batches(self):
        m = data.DatasetManager("mnist", 4, num_workers=4, size=4, rank=0)
        # size == num_workers => num_ps == 0, every rank is a worker
        xs, ys = m.sharded_train_batches()
        assert xs.shape[0] == 4 and xs.shape[2] == 4
        assert ys.shape[:2] == xs.shape[:2]
        # worker streams must differ (disjoint partitions)
        assert not np.array_equal(xs[0], xs[1])

    def test_pima_shapes(self):
        m = data.DatasetManager("pima", 16, num_workers=2, size=3, rank=1)
        xb, yb = m.get_train_set()
        assert xb.shape[2] == 8  # 8 diagnostic features
        assert yb.shape[2] == 1  # binary target column
        assert yb.dtype == np.float32

    def test_pima_test_set_keeps_ragged_tail(self):
        """All 168 pima test samples must be served (datasets.py:245-250
        keeps the final partial batch; dropping it loses 68 samples)."""
        m = data.DatasetManager("pima", 16, num_workers=2, size=3, rank=1)
        batches = m.get_test_set(batch=100)
        assert sum(len(x) for x, _ in batches) == 168
        assert [len(x) for x, _ in batches] == [100, 68]

    def test_cifar_shapes(self):
        m = data.DatasetManager("cifar10", 4, num_workers=2, size=2, rank=0)
        xb, yb = m.get_train_set()
        assert xb.shape[2:] == (4, 32, 32, 3)[1:]

    def test_unknown_dataset_raises(self):
        with pytest.raises(ValueError):
            data.DatasetManager("svhn", 4, 2, 2, 0)

    def test_synthetic_determinism(self):
        (a, _), _ = data.load_dataset("mnist")
        (b, _), _ = data.load_dataset("mnist")
        np.testing.assert_array_equal(a[:10], b[:10])

    def test_synthetic_learnable_structure(self):
        """Class-conditional means: nearest-centroid on train centroids must
        beat chance on test — the property convergence tests rely on."""
        (tx, ty), (vx, vy) = data.load_dataset("mnist")
        tx = tx.reshape(len(tx), -1)[:5000]
        ty = ty[:5000]
        vx = vx.reshape(len(vx), -1)[:1000]
        vy = vy[:1000]
        cents = np.stack([tx[ty == c].mean(0) for c in range(10)])
        pred = np.argmin(
            ((vx[:, None, :] - cents[None, :, :]) ** 2).sum(-1), axis=1
        )
        assert (pred == vy).mean() > 0.5

"""LEARN topology: fully decentralized Byzantine-resilient collaborative
learning (every node is Worker + Server).

TPU-native re-design of ``pytorch_impl/applications/LEARN/trainer.py``
(node loop :224-257, ``avg_agree`` gossip :208-222): n peer nodes each hold
their own model and data shard; per step each node

    1. computes its own gradient                       (trainer.py:233-236)
    2. gathers everyone's gradients and aggregates     (:237-241)
    3. (non-iid) repeats ceil(log2 t) "agreement" rounds, re-gathering the
       peers' *aggregated* gradients and re-aggregating (:208-222, :251-252)
    4. applies its optimizer                            (:247-249)
    5. gossips models: gathers peer models, GAR-aggregates, writes back
                                                        (:255-257)

SPMD mapping (SURVEY §2.3 "Decentralized P2P" row): one "nodes" mesh axis;
model/optimizer state is stacked over it; every get_aggr_grads/get_models RPC
poll (server.py:202-233) becomes one all_gather. Byzantine nodes inject
gradient attacks (byzWorker.py) in phases 1-3 and model attacks
(byzServer.py) in phase 5 — value transforms on their rows of the gathered
stacks.

Wait-n-f semantics: the reference's LEARN never waits for everyone — each
node takes the *fastest* ``n - f`` peer responses at every exchange
(``ps.get_gradients(i, n-f)`` trainer.py:249, ``get_models(n-f)`` :255, and
``avg_agree``'s ``num_wait_ps`` :208-222). Arrival order is effectively
random, so the bulk-synchronous stand-in is a per-node seeded subset
(``core.subset_indices``, same pattern as byzsgd's per-PS subsets): each
node aggregates its OWN q-subset of the gathered stack. That is exactly why
honest nodes hold *different* aggregates — the disagreement the ceil(log2 t)
agreement rounds exist to reconcile (and without which they would be vacuous
re-aggregations of one vector).

The ceil(log2 t) round count is data-dependent on the step counter, so the
gossip loop is a ``lax.fori_loop`` over a static ``max_rounds`` with rounds
beyond the target masked to no-ops (XLA needs static trip structure).
"""

import functools
import math

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..attacks import apply_gradient_attack, apply_model_attack
from . import core, mesh as mesh_lib
from .aggregathor import _check_gar, _resolve_gar

__all__ = ["make_trainer"]


def make_trainer(
    module,
    loss_fn,
    optimizer,
    gar,
    *,
    num_nodes,
    f=0,
    attack=None,
    attack_params=None,
    model_attack=None,
    model_attack_params=None,
    byz_mask=None,
    mesh=None,
    axis="nodes",
    non_iid=False,
    max_rounds=12,
    model_gossip=True,
    subset=None,
    track_spread=False,
    gar_dtype=None,
    worker_momentum=None,
    gar_params=None,
):
    """Build ``(init_fn, step_fn, eval_fn)`` for the LEARN topology.

    ``non_iid=True`` enables the ceil(log2 t) agreement rounds
    (LEARN/trainer.py:251-252 runs them only for non-iid data); ``max_rounds``
    caps them (2^12 = 4096 steps of exact parity by default).
    ``subset=q`` enables wait-n-f: every node aggregates its own seeded
    q-subset of the gathered gradients / agreement aggregates / gossiped
    models, the stand-in for taking the q = n - f *fastest* peer responses
    (LEARN/trainer.py:249, :255, avg_agree :208-222). With it, honest nodes
    hold genuinely different aggregates between agreement rounds.
    ``track_spread=True`` adds ``aggr_spread_pre`` / ``aggr_spread_post``
    metrics — the max pairwise L-inf distance between honest nodes'
    aggregates before and after the agreement rounds (costs one extra
    (n, d) all_gather; leave off in production).
    ``gar_dtype`` narrows the gradient pipeline (cast at the backward
    epilogue; gathers, attacks, aggregation and agreement rounds run at
    the narrow width; cast back at the optimizer boundary) — aggregathor's
    flag, applied to LEARN's phases 2-4. Model gossip stays full width.
    ``worker_momentum`` (beta in [0, 1)): each node publishes the EMA of
    its OWN gradients instead of the raw gradient — the decentralized form
    of Karimireddy et al. 2021 (their ClippedGossip follow-up pairs exactly
    this with clipped aggregation; use ``gar="cclip"``). The per-node
    momentum stack lives in ``TrainState.worker_mom``, sharded over the
    nodes axis with the rest of the node state. Pair with a plain-SGD
    optimizer (see aggregathor.make_trainer — the EMA is the momentum).
    ``step_fn(state, x, y)``: leading ``num_nodes`` axis on x/y and on every
    params/opt_state leaf, all sharded over ``axis``.
    """
    gar = _resolve_gar(gar)
    attack_params = dict(attack_params or {})
    gar_params = dict(gar_params or {})
    model_attack_params = dict(model_attack_params or {})
    if mesh is None:
        mesh = mesh_lib.make_mesh({axis: -1})
    per_n = mesh_lib.fold(num_nodes, mesh.shape[axis], "nodes")
    if subset is not None and not (1 <= subset <= num_nodes):
        raise ValueError(f"subset must be in [1, {num_nodes}], got {subset}")
    # The GAR sees `subset` rows when waiting (reference passes the n-f
    # received gradients straight to the rule, LEARN/trainer.py:241).
    _check_gar(gar, subset if subset else num_nodes, f)
    if worker_momentum is not None and not (0.0 <= worker_momentum < 1.0):
        raise ValueError(
            f"worker_momentum must be in [0, 1), got {worker_momentum}"
        )
    if byz_mask is None:
        byz_mask = core.default_byz_mask(
            num_nodes, f if (attack or model_attack) else 0
        )
    byz_mask = jnp.asarray(byz_mask, bool)

    init_worker, grad_fn, eval_apply = core.make_worker_fns(module, loss_fn)
    node_sharding = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    def init_fn(key, example_x, seed_rng=None):
        params, model_state = init_worker(key, example_x)
        opt_state = optimizer.init(params)
        stack = lambda tree: jax.tree.map(
            lambda l: jnp.broadcast_to(l[None], (num_nodes,) + l.shape), tree
        )
        worker_mom = None
        if worker_momentum is not None:
            worker_mom = jax.device_put(
                core.worker_mom_init(params, num_nodes, gar_dtype),
                node_sharding,
            )
        return core.TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), repl),
            params=jax.device_put(stack(params), node_sharding),
            model_state=jax.device_put(model_state, repl),
            opt_state=jax.device_put(stack(opt_state), node_sharding),
            rng=jax.device_put(key if seed_rng is None else seed_rng, repl),
            worker_mom=worker_mom,
        )

    waiting = subset is not None and subset < num_nodes

    def _local_step(state, x_local, y_local):
        base = jax.random.fold_in(state.rng, state.step)
        (atk_key, gossip_key, matk_key, drop_base,
         sub_key, msub_key) = jax.random.split(base, 6)
        shard = jax.lax.axis_index(axis)
        node_ids = shard * per_n + jnp.arange(per_n)

        def node_aggregate(stack, key, nid):
            """One node's view of an exchange: its own seeded arrival subset
            (the q fastest peers), then the GAR. Keyed by the global node id
            so every shard agrees on what node ``nid`` sampled."""
            sel_key, gkey = jax.random.split(jax.random.fold_in(key, nid))
            if waiting:
                sel = core.subset_indices(sel_key, stack.shape[0], subset)
                stack = stack[sel]
            return gar.unchecked(stack, f=f, key=gkey, **gar_params)

        def local_aggregates(stack, key):
            """All of this shard's node slots aggregate the same gathered
            stack through their own subsets -> (per_n, d). vmapped over the
            node ids (one subset+GAR graph regardless of per_n, the same
            shape as byzsgd's vmapped per-PS slot step)."""
            if waiting:
                return jax.vmap(
                    lambda nid: node_aggregate(stack, key, nid)
                )(node_ids)
            # Full participation: one aggregate, identical for every node.
            one = gar.unchecked(stack, f=f, key=key, **gar_params)
            return jnp.broadcast_to(one[None], (per_n,) + one.shape)

        def honest_spread(aggr_local):
            """Max pairwise L-inf distance between honest nodes' aggregates:
            the disagreement the agreement rounds must shrink."""
            rows = jax.lax.all_gather(aggr_local, axis, tiled=True)  # (n, d)
            byz = byz_mask[:, None]
            hi = jnp.max(jnp.where(byz, -jnp.inf, rows), axis=0)
            lo = jnp.min(jnp.where(byz, jnp.inf, rows), axis=0)
            return jnp.max(hi - lo)

        # Phase 1: per-node gradient on its own model + batch (unrolled over
        # the static local slots; vmapping params over nodes trips conv
        # batching rules). Keep the stacked TREE through the gather and
        # flatten once afterwards — raveling each slot inside the unroll
        # serializes the per-slot concats against fwd+bwd (measured 12%
        # slower in aggregathor; core.per_slot_grads docstring).
        grads, losses, ms_list = [], [], []
        for k in range(per_n):
            p_k = jax.tree.map(lambda l: l[k], state.params)
            rng_k = jax.random.fold_in(drop_base, node_ids[k])
            g, (loss, ms_out) = grad_fn(
                p_k, state.model_state, x_local[k], y_local[k], rng_k
            )
            grads.append(g)
            losses.append(loss)
            ms_list.append(ms_out)
        grads_local = jax.tree.map(lambda *ls: jnp.stack(ls), *grads)
        losses = jnp.stack(losses)
        grads_local = core.cast_leaves(grads_local, gar_dtype)

        # Per-node momentum (see make_trainer docstring): each node
        # publishes its EMA; the honest update is stored (sharded with the
        # node state), Byzantine rows are re-poisoned after the gather.
        new_mom = state.worker_mom
        if worker_momentum is not None:
            grads_local = core.worker_mom_update(
                worker_momentum, state.worker_mom, grads_local
            )
            new_mom = grads_local
        new_ms = core.mean_model_state(
            jax.tree.map(lambda *ls: jnp.stack(ls), *ms_list), axis
        )

        # Phase 2: gather + attack + aggregate (= get_gradients(i, n-f) of
        # the fastest peers, LEARN/trainer.py:249; per-node subsets).
        gathered = jax.tree.map(
            lambda l: jax.lax.all_gather(l, axis, tiled=True), grads_local
        )
        stack0 = core.flatten_rows(gathered)  # (n, d)
        stack0 = apply_gradient_attack(
            attack, stack0, byz_mask, key=atk_key, **attack_params
        )
        aggr_local = local_aggregates(stack0, sub_key)  # (per_n, d)

        metrics_extra = {}
        if track_spread:
            metrics_extra["aggr_spread_pre"] = honest_spread(aggr_local)

        # Phase 3: avg_agree rounds (ceil(log2 t), LEARN/trainer.py:208-222).
        # Each round every node PUBLISHES its own current aggregate (they
        # differ under wait-n-f), Byzantine rows are poisoned, and each node
        # re-aggregates its own num_wait_ps = q subset of the gathered stack
        # (get_aggr_grads polling, server.py:202-233).
        if non_iid:
            t = jnp.maximum(state.step, 1).astype(jnp.float32)
            rounds = jnp.ceil(jnp.log2(jnp.maximum(t, 2.0))).astype(jnp.int32)
            rounds = jnp.minimum(rounds, max_rounds)

            def round_body(r, aggr_local):
                served = jax.lax.all_gather(
                    aggr_local, axis, tiled=True
                )  # (n, d): every node's own aggregate, not n copies of one
                akey, skey = jax.random.split(jax.random.fold_in(gossip_key, r))
                served = apply_gradient_attack(
                    attack, served, byz_mask, key=akey, **attack_params
                )
                new = local_aggregates(served, skey)
                return jnp.where(r < rounds, new, aggr_local)

            aggr_local = jax.lax.fori_loop(0, max_rounds, round_body, aggr_local)

        if track_spread:
            metrics_extra["aggr_spread_post"] = honest_spread(aggr_local)

        # Phase 4: per-node optimizer step on that node's own aggregate.
        new_params_list, new_opt_list = [], []
        for k in range(per_n):
            p_k = jax.tree.map(lambda l: l[k], state.params)
            o_k = jax.tree.map(lambda l: l[k], state.opt_state)
            aggr_tree = core.unflatten_like(p_k, aggr_local[k])
            aggr_tree = core.cast_like(aggr_tree, p_k)  # no-op at f32
            updates, o_k = optimizer.update(aggr_tree, o_k, p_k)
            new_params_list.append(optax.apply_updates(p_k, updates))
            new_opt_list.append(o_k)
        new_params = jax.tree.map(lambda *ls: jnp.stack(ls), *new_params_list)
        new_opt = jax.tree.map(lambda *ls: jnp.stack(ls), *new_opt_list)

        # Phase 5: model gossip (LEARN/trainer.py:255-257, get_models(n-f) —
        # each node GAR-aggregates its own subset of the gossiped models).
        if model_gossip:
            flat_models = core.flatten_rows(new_params)  # (per_n, d)
            models = jax.lax.all_gather(flat_models, axis, tiled=True)
            poisoned = jax.vmap(
                lambda i, m: apply_model_attack(
                    model_attack, m, key=jax.random.fold_in(matk_key, i),
                    **model_attack_params,
                )
            )(jnp.arange(num_nodes), models)
            models = jnp.where(byz_mask[:, None], poisoned, models)
            aggr_models = local_aggregates(models, msub_key)  # (per_n, d)
            template = jax.tree.map(lambda l: l[0], new_params)
            new_params = jax.tree.map(
                lambda *ls: jnp.stack(ls),
                *[
                    core.unflatten_like(template, aggr_models[k])
                    for k in range(per_n)
                ],
            )

        honest = (~byz_mask).astype(losses.dtype)[node_ids]
        loss_num = jax.lax.psum(jnp.sum(losses * honest), axis)
        loss_den = jax.lax.psum(jnp.sum(honest), axis)
        mean_loss = loss_num / jnp.maximum(loss_den, 1.0)
        # Per-node losses for observers (the reference demo renders per-node
        # progress, LEARN/demo.py:401-441 + templates/index.html); a tiny
        # replicated (n,) vector, node-id ordered.
        metrics_extra["node_losses"] = jax.lax.all_gather(
            losses, axis, tiled=True
        )

        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                model_state=new_ms,
                opt_state=new_opt,
                worker_mom=new_mom,
            ),
            {"loss": mean_loss, **metrics_extra},
        )

    state_specs = core.TrainState(
        step=P(), params=P(axis), model_state=P(), opt_state=P(axis), rng=P(),
        worker_mom=(P(axis) if worker_momentum is not None else None),
    )
    sharded_step = jax.shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(state_specs, P(axis), P(axis)),
        out_specs=(state_specs, P()),
        check_vma=False,
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, x, y):
        return sharded_step(state, x, y)

    @jax.jit
    def eval_fn(state, x):
        params0 = jax.tree.map(lambda l: l[0], state.params)
        return eval_apply(params0, state.model_state, x)

    step_fn.mesh = mesh
    step_fn.batch_sharding = node_sharding
    return init_fn, step_fn, eval_fn

"""Shard autoscaling: the elastic axis the worker autoscaler can't reach.

``utils.autoscale.AutoscaleController`` provisions WORKERS — more
gradient producers per second for the async plane. This module points
the same control law (mean-rate window, hysteresis, cooldown — and now
``rescind``) at the OTHER capacity axis: the PS shard count. FEDBENCH
measured round time scaling ~1/S because every shard folds only d/S of
each client, so under round-latency pressure the right move is a span
SPLIT (S -> S+1, each shard thinner), and under sustained headroom a
MERGE (S -> S-1, fewer processes doing the same work). The controller
decides; ``FedRoundEngine.resize`` applies — re-plan the balanced
partition, rebuild the shard servers, and bump the membership epoch by
exactly one, so every split/merge is a membership change the wire
plane enforces (a client still slicing for the OLD spans sends frames
stamped with the old epoch: attributable rejects, not silently
mis-sliced folds — DESIGN.md §22).

Why the worker controller transplants cleanly: its inputs are
role-free. ``observe(round_s, active, quorum_margin)`` reads wall time
per round, a capacity count, and a health bit; here ``active`` is the
shard count and the health bit is "no shard's reducer was starved".
+1 (the controller's "spawn") means "add capacity" on either axis. The
one genuinely new case is REFUSAL: a split can be impossible (the wire
header's 16-slot shard nibble, or more shards than parameters) in a
way worker spawns never were, and the satellite-2 fix exists for
exactly this call site — a refused resize rescinds the controller
action, so the refusal costs nothing: no consumed cooldown, no cleared
measurement window, no phantom action count.
"""

from ..federated import sharding
from ..utils import autoscale

__all__ = ["ShardAutoscaler"]


class ShardAutoscaler:
    """Round-latency-driven split/merge of an engine's shard group.

    Call ``observe(round_s)`` once per finished round, BETWEEN rounds
    (``FedRoundEngine.resize`` rebuilds the shard servers, so applying
    mid-round would drop the round in flight). Returns the applied
    delta: +1 split, -1 merge, 0 nothing — refused actions are
    rescinded and return 0, indistinguishable from no advice because
    accounting-wise they ARE no advice.
    """

    def __init__(self, engine, *, target_rate=0.0, min_shards=1,
                 max_shards=None, window=8, cooldown=8,
                 up_margin=0.9, down_margin=1.3):
        if max_shards is None:
            max_shards = sharding.MAX_SHARDS
        self.engine = engine
        self.controller = autoscale.AutoscaleController(
            autoscale.AutoscaleConfig(
                target_rate=target_rate,
                min_workers=int(min_shards),
                max_workers=int(max_shards),
                window=window, cooldown=cooldown,
                up_margin=up_margin, down_margin=down_margin,
            )
        )
        self.splits = 0
        self.merges = 0
        self.refusals = 0

    def observe(self, round_s, *, healthy=True):
        """Fold one finished round's wall time; maybe resize.

        ``healthy=False`` marks a round where the shard plane already
        struggled (a failover mid-round, a starved reducer) — it maps
        to the controller's negative quorum margin, vetoing merges for
        a full window so a wobble is never compounded by a shrink.
        """
        s = self.engine.spec.num_shards
        act = self.controller.observe(
            float(round_s), active=s,
            quorum_margin=0 if healthy else -1,
        )
        if act == 0:
            return 0
        try:
            self.engine.resize(s + act)
        except ValueError:
            # Impossible resize (nibble cap / more shards than params):
            # the engine changed nothing, so the controller must
            # remember nothing — satellite-2's rescind contract.
            self.controller.rescind()
            self.refusals += 1
            return 0
        if act > 0:
            self.splits += 1
        else:
            self.merges += 1
        return act

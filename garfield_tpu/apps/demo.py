"""Browser demo: decentralized Byzantine-resilient LEARN on Pima.

Counterpart of ``pytorch_impl/applications/LEARN/demo.py`` (P22): the
reference spawns n ``multiprocessing.Process`` ranks on localhost behind a
Quart app (:244-349, routes :401-441). Here the n nodes are logical slots of
one jit'd SPMD program (the "multi-node on one host" harness is the mesh
itself), the web layer is stdlib ``http.server`` (no Quart in this image),
and training runs in a background thread publishing progress:

  POST /train {"nodes": 8, "f": 1, "gar": "median", "attack": "lie"}
  GET  /status -> {"running", "step", "total", "loss", "accuracy",
                   "suspicion", "selection_history", "active_workers",
                   ...}
  GET  /metrics -> Prometheus text exposition of the telemetry hub
                   (telemetry/exporters.prometheus_text)
  GET  /       -> minimal HTML page driving the endpoints, with the
                  GAR selection-history panel (who got excluded when)

  python -m garfield_tpu.apps.demo --port 8000
"""

import argparse
import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import numpy as np

from .. import data as data_lib, models as models_lib, parallel
from ..parallel import learn
from ..telemetry import MetricsHub, prometheus_text
from ..telemetry import hub as tele_hub_lib, trace as tele_trace
from ..utils import selectors, tools

_PAGE = """<!doctype html>
<html><head><title>garfield-tpu LEARN demo</title></head>
<body style="font-family:sans-serif;max-width:44em;margin:2em auto">
<h2>Byzantine-resilient collaborative learning (LEARN, Pima)</h2>
<form onsubmit="start(event)">
  nodes <input id=n value=8 size=2>
  f <input id=f value=1 size=2>
  gar <select id=g><option>median<option>krum<option>average<option>aksel<option>cclip<option>tmean
      </select>
  attack <select id=a><option>none<option>lie<option>random<option>reverse
      <option>empire<option>drop</select>
  epochs <input id=e value=15 size=3>
  <button>train</button>
</form>
<!-- Topology sketch + per-node progress: the reference demo's observable
     behavior (LEARN/static/network_topologies.svg + per-node rows in
     templates/index.html). LEARN is fully connected; Byzantine nodes (the
     last f ranks, trainer rank convention) draw red. -->
<svg id=topo width=440 height=300></svg>
<div id=nodes></div>
<!-- Telemetry selection-history panel (docs/TELEMETRY.md): one row per
     node, one cell per recent step; cell opacity = the GAR's selection
     weight that step, so excluded (suspicious) nodes show as dark rows.
     The bar on the right is the cumulative suspicion score. Raw series:
     GET /metrics (Prometheus text). -->
<h4 style="margin-bottom:4px">GAR selection history (telemetry)</h4>
<div id=hist style="font-family:monospace;font-size:11px"></div>
<!-- Round-tracing phase breakdown (docs/TELEMETRY.md §4): where the
     last completed round's wall clock went — one bar per traced phase
     (dispatch/eval), widths proportional to seconds. -->
<h4 style="margin-bottom:4px">Last round phase breakdown (tracing)</h4>
<div id=phases style="font-family:monospace;font-size:11px"></div>
<pre id=out>idle</pre>
<script>
async function start(ev) {
  ev.preventDefault();
  await fetch('/train', {method:'POST', body: JSON.stringify({
    nodes:+document.getElementById('n').value,
    f:+document.getElementById('f').value,
    gar:document.getElementById('g').value,
    attack:document.getElementById('a').value,
    epochs:+document.getElementById('e').value})});
  poll();
}
function drawTopo(r) {
  const svg = document.getElementById('topo');
  const losses = r.node_losses || [], byz = r.byz_nodes || [];
  const n = losses.length;
  if (!n) { svg.innerHTML = ''; return; }
  const cx = 220, cy = 150, R = 110;
  const pos = [...Array(n)].map((_, i) => {
    const a = 2 * Math.PI * i / n - Math.PI / 2;
    return [cx + R * Math.cos(a), cy + R * Math.sin(a)];
  });
  let s = '';
  for (let i = 0; i < n; i++)           // fully-connected gossip edges
    for (let j = i + 1; j < n; j++)
      s += `<line x1=${pos[i][0]} y1=${pos[i][1]} x2=${pos[j][0]} ` +
           `y2=${pos[j][1]} stroke="#ddd"/>`;
  for (let i = 0; i < n; i++) {
    const c = byz[i] ? '#c0392b' : '#27ae60';
    s += `<circle cx=${pos[i][0]} cy=${pos[i][1]} r=14 fill="${c}"/>` +
         `<text x=${pos[i][0]} y=${pos[i][1] + 4} text-anchor=middle ` +
         `fill=white font-size=11>${i}</text>` +
         `<text x=${pos[i][0]} y=${pos[i][1] + 28} text-anchor=middle ` +
         `font-size=10>${byz[i] ? 'byz' : (+losses[i]).toFixed(3)}</text>`;
  }
  svg.innerHTML = s;
}
function drawNodes(r) {
  const losses = r.node_losses || [], byz = r.byz_nodes || [];
  document.getElementById('nodes').innerHTML = losses.map((l, i) =>
    `<div>node ${i}: ${byz[i] ? '<b style="color:#c0392b">byzantine</b>'
       : 'loss ' + (+l).toFixed(4)}</div>`).join('');
}
function drawHistory(r) {
  const hist = r.selection_history || [], susp = r.suspicion || [];
  const el = document.getElementById('hist');
  if (!hist.length) { el.innerHTML = ''; return; }
  const n = hist[0][1].length;
  let rows = '';
  for (let i = 0; i < n; i++) {
    let cells = hist.map(([s, sel]) =>
      `<span title="step ${s}: ${(+sel[i]).toFixed(2)}" style="display:` +
      `inline-block;width:6px;height:12px;background:rgba(41,128,185,` +
      `${Math.max(0.06, +sel[i])})"></span>`).join('');
    const sp = susp[i] === undefined ? '' :
      ` <span style="color:#c0392b">${(+susp[i]).toFixed(2)}</span>`;
    rows += `<div>n${i} ${cells}${sp}</div>`;
  }
  el.innerHTML = rows +
    '<div style="color:#888">cell = per-step selection weight; ' +
    'red number = cumulative suspicion (exclusion frequency)</div>';
}
function drawPhases(r) {
  const pb = r.phase_breakdown, el = document.getElementById('phases');
  if (!pb || !pb.phases) { el.innerHTML = ''; return; }
  const entries = Object.entries(pb.phases);
  const total = entries.reduce((a, [, v]) => a + v, 0) || 1;
  el.innerHTML = `<div>round ${pb.step}:</div>` + entries.map(([k, v]) =>
    `<div>${k.padEnd ? k : k} <span style="display:inline-block;height:10px;`
    + `background:#2980b9;width:${Math.max(2, 220 * v / total)}px"></span> `
    + `${(v * 1e3).toFixed(2)} ms</div>`).join('');
}
async function poll() {
  const r = await (await fetch('/status')).json();
  drawTopo(r); drawNodes(r); drawHistory(r); drawPhases(r);
  document.getElementById('out').textContent = JSON.stringify(r, null, 1);
  if (r.running) setTimeout(poll, 500);
}
poll();
</script></body></html>"""


class DemoState:
    """Progress shared between the trainer thread and HTTP handlers
    (the reference's progress queue + lock, demo.py:260, 305-320)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.progress = {"running": False}
        self.thread = None
        self.hub = None  # telemetry.MetricsHub of the active/last run

    def update(self, **kw):
        with self.lock:
            self.progress.update(kw)

    def snapshot(self):
        with self.lock:
            return dict(self.progress)


STATE = DemoState()


def run_training(nodes, f, gar, attack, epochs, batch=16):
    """LEARN on pima/pimanet — the reference demo's fixed config
    (demo.py:267-270,294: batch 16, 15 epochs, rmsprop lr 1e-3)."""
    try:
        t0 = time.time()
        manager = data_lib.DatasetManager("pima", batch, nodes, nodes, 0)
        manager.num_ps = 0
        xs, ys = manager.sharded_train_batches()
        test = manager.get_test_set()
        iters_per_epoch = xs.shape[1]
        total = epochs * iters_per_epoch
        module = models_lib.select_model("pimanet", "pima")
        loss_fn = selectors.select_loss("bce")
        optimizer = selectors.select_optimizer(
            "rmsprop", lr=1e-3, momentum=0.9, weight_decay=5e-4
        )
        n_dev = len(jax.devices())
        axis = n_dev if nodes % n_dev == 0 else 1
        mesh = parallel.mesh.make_mesh(
            {"nodes": axis}, devices=jax.devices()[:axis]
        )
        init_fn, step_fn, eval_fn = learn.make_trainer(
            module, loss_fn, optimizer, gar,
            num_nodes=nodes, f=f,
            attack=None if attack in (None, "none") else attack,
            mesh=mesh,
            telemetry=True,  # feeds /metrics + the selection-history panel
        )
        hub = MetricsHub(
            num_ranks=nodes,
            meta={"tag": "demo", "gar": gar, "attack": attack, "f": f},
        )
        STATE.hub = hub
        # Round tracing (docs/TELEMETRY.md §4): the demo always traces —
        # its spans are in-process and cheap, and they feed the /status
        # phase-breakdown panel + the garfield_phase_seconds histograms
        # on /metrics.
        tele_hub_lib.install(hub)
        tele_trace.enable(who="demo")
        state = init_fn(jax.random.PRNGKey(1234), xs[0, 0])
        xs = jax.device_put(jax.numpy.asarray(xs), step_fn.batch_sharding)
        ys = jax.device_put(jax.numpy.asarray(ys), step_fn.batch_sharding)
        # Byzantine ranks are the LAST f (core.default_byz_mask, the
        # trainer rank convention) — rendered red in the topology sketch.
        byz = [False] * nodes
        if attack not in (None, "none") and f:
            byz = [i >= nodes - f for i in range(nodes)]
        metrics = {}

        def publish(i, metrics, running, done=False):
            with tele_trace.span("eval", step=i):
                acc = parallel.compute_accuracy(
                    state, eval_fn, test, binary=True
                )
            susp = hub.suspicion()
            lastp = hub.last_round_phases()
            live = hub.active_workers()
            STATE.update(
                # Active-worker count (schema v6): the autoscale gauge
                # when an elastic run feeds this hub, else the demo's
                # fixed node count.
                active_workers=nodes if live is None else int(live),
                # Last COMPLETED round's phase breakdown (seconds) — the
                # tracing satellite of ISSUE 8, rendered next to the
                # suspicion panel.
                phase_breakdown=(
                    None if lastp is None
                    else {"step": lastp[0], "phases": lastp[1]}
                ),
                running=running, step=i + 1, total=total,
                epoch=i // iters_per_epoch,
                loss=float(metrics["loss"]), accuracy=acc,
                node_losses=[
                    round(float(l), 5)
                    for l in np.asarray(metrics["node_losses"])
                ],
                byz_nodes=byz, done=done,
                elapsed_s=round(time.time() - t0, 1),
                suspicion=(
                    None if susp is None
                    else [round(float(s), 4) for s in susp]
                ),
                selection_history=hub.selection_history(60),
            )

        for i in range(total):
            with tele_trace.span("dispatch", step=i):
                state, metrics = step_fn(state, xs[:, i % iters_per_epoch],
                                         ys[:, i % iters_per_epoch])
                loss_host = float(metrics["loss"])  # blocks on the step
            hub.record_step(i, loss=loss_host, tap=metrics.get("tap"))
            if i % iters_per_epoch == 0 or i == total - 1:
                publish(i, metrics, running=True)
        publish(total - 1, metrics, running=False, done=True)
    except Exception as exc:  # surfaced via /status, like demo.py's liveness
        STATE.update(running=False, error=repr(exc))
    finally:
        tele_trace.disable()
        if tele_hub_lib.current() is STATE.hub:
            tele_hub_lib.uninstall()


class Handler(BaseHTTPRequestHandler):
    def _send(self, code, body, ctype="application/json"):
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        if self.path == "/":
            self._send(200, _PAGE, "text/html")
        elif self.path == "/status":
            self._send(200, json.dumps(STATE.snapshot()))
        elif self.path == "/metrics":
            # Prometheus text exposition (format 0.0.4) of the live hub —
            # scrape-able the moment a run starts; empty before any run.
            hub = STATE.hub
            body = prometheus_text(hub) if hub is not None else ""
            self._send(200, body, "text/plain; version=0.0.4")
        else:
            self._send(404, json.dumps({"error": "not found"}))

    def do_POST(self):
        if self.path != "/train":
            self._send(404, json.dumps({"error": "not found"}))
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            req = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            self._send(400, json.dumps({"error": "bad json"}))
            return
        # check-then-spawn under the lock: ThreadingHTTPServer handles
        # concurrent POSTs on separate threads.
        with STATE.lock:
            if STATE.thread and STATE.thread.is_alive():
                self._send(
                    409, json.dumps({"error": "training already running"})
                )
                return
            STATE.progress.update(running=True, step=0, error=None,
                                  done=False)
            STATE.thread = threading.Thread(
                target=run_training,
                kwargs=dict(
                    nodes=int(req.get("nodes", 8)),
                    f=int(req.get("f", 1)),
                    gar=req.get("gar", "median"),
                    attack=req.get("attack", "none"),
                    epochs=int(req.get("epochs", 15)),
                ),
                daemon=True,
            )
            STATE.thread.start()
        self._send(200, json.dumps({"started": True}))

    def log_message(self, fmt, *args):  # route through our logger
        tools.trace("[demo] " + fmt % args)


def main(argv=None):
    p = argparse.ArgumentParser(description="garfield-tpu LEARN web demo")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--warm", action="store_true",
                   help="Run one tiny training first (the reference's "
                        "init_demo warm build, demo.py:440).")
    args = p.parse_args(argv)
    if args.warm:
        run_training(nodes=4, f=0, gar="average", attack="none", epochs=1)
    server = ThreadingHTTPServer((args.host, args.port), Handler)
    tools.info(f"[demo] serving on http://{args.host}:{args.port}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return server


if __name__ == "__main__":
    main(sys.argv[1:])

"""Aksel GAR: average of the gradients closest to the coordinate-wise median.

Counterpart of pytorch_impl/libs/aggregators/aksel.py (:24-64): compute the
coordinate-wise median, rank gradients by squared Euclidean distance to it,
and average the c closest, where c = (n+1)//2 in mode "mid" or c = n-f in
mode "n-f". Requires n >= 2f+1.
"""

import jax.numpy as jnp
import numpy as np

from . import register
from ._common import as_stack, coordinate_median, num_gradients


def _selection(g, f, mode):
    n = g.shape[0]
    med = coordinate_median(g)
    dist = jnp.sum((g - med[None, :]) ** 2, axis=1)
    if mode == "mid":
        c = (n + 1) // 2
    elif mode == "n-f":
        c = n - f
    else:
        raise NotImplementedError(f"unknown aksel mode {mode!r}")
    return jnp.argsort(dist)[:c], c


def aggregate(gradients, f, mode="mid", **kwargs):
    """Average of the c gradients closest to the coordinate median."""
    g = as_stack(gradients)
    sel, _ = _selection(g, f, mode)
    return jnp.mean(g[sel], axis=0)


def check(gradients, f, mode="mid", **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 1:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = {f!r}, "
            f"expected 1 <= f <= {(n - 1) // 2}"
        )
    if mode not in ("mid", "n-f"):
        return f"invalid operation mode {mode!r}"
    return None


def influence(honests, attacks, f, mode="mid", **kwargs):
    """Ratio of Byzantine gradients among the c selected (aksel.py:76-98)."""
    stack = jnp.concatenate([as_stack(honests), as_stack(attacks)], axis=0)
    sel, c = _selection(stack, f, mode)
    sel = np.asarray(sel)
    return float(np.sum(sel >= len(honests))) / c


register("aksel", aggregate, check, influence=influence)

"""GAR kernel latency sweep.

Counterpart of ``pytorch_impl/applications/benchmarks/gar_bench.py``
(:41-89): per-GAR latency across n in powers of two, f as allowed by each
rule's contract, d in powers of ten — the same sweep grid, but timed as
jit'd XLA executions (compile excluded) with dependency-chained paired-reps
timing (see ``bench_one``; JSON key ``latency_s``) and, for the
``native-*`` rules, as C++ host kernels. Each cell's chain consumes the
aggregate through a NONLINEAR guard (the r5 microbench-trap rule — a
linear consumer lets XLA rewrite the timed reductions away) and the
committed value is the min over ``--trials`` independent measurements
(VERDICT r4 #3), recorded in the rows as ``dce_guard``/``trials``.

  python -m garfield_tpu.apps.benchmarks.gar_bench --gars krum median \\
      --ns 4 16 64 --ds 10 1000 100000 --reps 10 --json out.json
"""

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ... import aggregators
from ...aggregators import bulyan as _bulyan
from ...aggregators import hierarchy
from ...aggregators import krum as _krum
from ...aggregators._common import distances_from_gram
from ...utils import profiling
from ..common import peak_rss_bytes

# Practical bound for brute's exhaustive enumeration, like the reference's
# sweep bound (gar_bench.py:51 keeps n small for brute).
BRUTE_MAX_N = 25

# bench_one sentinel: the rule's contract rejects this (n, f) combination.
INCOMPATIBLE = object()


def max_f(rule, n):
    """Largest f each rule's contract admits (aggregators/*.check; the
    hier-* rules report their composed capacity, aggregators/hierarchy)."""
    if rule.startswith("hier"):
        try:
            bucket_gar, top_gar = hierarchy.parse_hier_name(rule)
        except ValueError:
            bucket_gar, top_gar = "krum", None  # the env-configured alias
        cap = hierarchy.max_tolerated_f(n, bucket_gar, top_gar)
        return max(cap or 0, 0)
    bounds = {
        "krum": (n - 3) // 2,
        "bulyan": (n - 3) // 4,
        "brute": (n - 1) // 2,
        "condense": (n - 2) // 2,
        "aksel": (n - 1) // 2,
        "median": (n - 1) // 2,
        "tmean": (n - 1) // 2,
        "average": (n - 1) // 2,
        "cclip": (n - 1) // 2,
    }
    base = rule.split("native-")[-1]
    return max(bounds.get(base, 0), 0)


def bench_one(gar, n, f, d, reps, key, trials=1):
    g = jax.random.normal(key, (n, d), jnp.float32)
    kwargs = {"f": f} if f else {}
    try:
        if gar.check(np.zeros((n, 2), np.float32), **kwargs) is not None:
            return INCOMPATIBLE
    except TypeError:
        pass
    # Timing that survives tunneled/remote device backends, where
    # ``block_until_ready`` may return before the device finishes and the
    # only true synchronization is a host readback that also flushes the
    # queue at a large constant cost:
    #   - dependency-chain the iterations ((n, d) -> (n, d) by writing the
    #     aggregate back into row 0) so they cannot be overlapped;
    #   - run the chain at ``reps`` and ``2*reps`` with a readback sync each,
    #     and report the difference / reps — the per-sync constant cancels.
    # The chain input is donated so the row-0 write updates the buffer in
    # place instead of copying the whole (n, d) stack every iteration (which
    # would bias cheap rules); each timed run starts from a fresh device
    # buffer because donation consumes the previous one.
    #
    # DCE guard (VERDICT r4 #3 + the r5 microbench-trap rule): the
    # aggregate is consumed through a cheap NONLINEAR elementwise map
    # (softsign: a * rsqrt(1 + a^2), one fused VPU pass over d) before the
    # row-0 write-back. A linear consumer lets XLA algebraically rewrite
    # the rule's reductions (r5 traced sum(conv(x, dy)) collapsing into
    # direct reductions — the timed ops vanish from the graph); the
    # nonlinearity pins every aggregate coordinate as a real data
    # dependency of the next iteration. Bonus: softsign's (-1, 1) range
    # keeps the chained stack bounded over thousands of reps.
    def _chain(s):
        a = gar.unchecked(s, **kwargs).astype(jnp.float32)
        guarded = a * jax.lax.rsqrt(1.0 + a * a)
        return s.at[0].set(guarded.astype(s.dtype))

    chain = jax.jit(_chain, donate_argnums=0)
    # np.array/jnp.array (not asarray): on CPU an asarray view would alias
    # the device buffer the next chain() call donates, corrupting s0_host.
    s0_host = np.array(chain(g))  # compile + warm + sync (g donated)

    def timed(k):
        s = jnp.array(s0_host)
        np.asarray(s[0, :1])  # finish H2D transfer + drain queue
        t0 = time.perf_counter()
        for _ in range(k):
            s = chain(s)
        np.asarray(s[0, :1])  # host readback: the only reliable sync
        return time.perf_counter() - t0

    # Two-phase adaptive timing (VERDICT r4 weak #2): sub-ms cells at the
    # configured reps leave the chained run far below the host-sync noise
    # floor, and their committed values bounced >1.3x between sweeps. A
    # coarse estimate sizes reps so the timed chain runs ~0.5 s, then the
    # recorded value is the MIN over ``trials`` independent min-of-pairs
    # measurements (VERDICT r4 #3's min-over-k: co-tenant interference
    # only adds time; the minimum estimates the kernel itself).
    est = profiling.paired_reps(timed, reps, pairs=2)
    if est is not None and est * reps < 0.25:
        reps = min(4000, max(reps, int(0.5 / max(est, 1e-7))))
    vals = [
        profiling.paired_reps(timed, reps, pairs=4, agg="min")
        for _ in range(max(1, trials))
    ]
    vals = [v for v in vals if v is not None]
    return min(vals) if vals else None


def hier_bench_one(name, n, f, d, *, bucket_size, wave, trials, seed=0):
    """Time one hierarchical cell END TO END through the streaming reducer:
    full wave-based ingest of n clients plus the cascaded folds plus
    ``finalize`` — the federated arrival pattern, not an (n, d)-resident
    microkernel. Memory stays O(wave · bucket_size · d): client waves are
    generated into two fixed pools cycled through ``push_many`` (generation
    stays OUTSIDE the timed region), so the (n, d) stack never exists —
    at n = 2^17, d = 1e5 that stack alone would be 52 GB.

    DCE guard: finalize()'s host readback is a hard sync, and the returned
    aggregate is still consumed through the softsign map (the r5
    microbench-trap rule) so no consumer-side rewrite can shed it. The
    committed value is the min over ``trials`` full runs (VERDICT r4 #3).
    """
    bucket_gar, top_gar = hierarchy.parse_hier_name(name)
    rng = np.random.default_rng(seed)
    wave_rows = wave * bucket_size
    pools = [rng.normal(size=(wave_rows, d)).astype(np.float32)
             for _ in range(2)]

    def run_once():
        red = hierarchy.StreamingAggregator(
            n, f, bucket_gar=bucket_gar, top_gar=top_gar,
            bucket_size=bucket_size, wave_buckets=wave,
        )
        i = 0
        while i < n:
            pool = pools[(i // wave_rows) % 2]
            take = min(wave_rows, n - i)
            red.push_many(pool[:take])
            i += take
        out = red.finalize()
        guarded = float(np.sum(out * (1.0 / np.sqrt(1.0 + out * out))))
        return guarded, red.plan

    _, plan = run_once()  # compile + warm
    vals = []
    for _ in range(max(1, trials)):
        t0 = time.perf_counter()
        run_once()
        vals.append(time.perf_counter() - t0)
    total = min(vals)
    return {
        "latency_s": total,
        "per_client_s": total / n,
        "bucket_size": bucket_size,
        "wave_buckets": wave,
        "levels": plan.num_levels,
        "num_buckets": plan.num_buckets,
    }


# Selection micro mode (--selection): the Gram-rule selection step in
# isolation, batched across a wave of buckets exactly as the hierarchy's
# vmapped fold runs it. Both impls are explicit ``use_sortnet`` closures
# — NOT the env knob — so each gets its own jit program and the shared
# cache is never poisoned by a trace-time env read (see
# krum._sortnet_select).
SELECTION_RULES = ("krum", "bulyan")
SELECTION_IMPLS = ("sortnet", "xla_sort")


def _selection_fn(rule, f, use_sortnet):
    """(W, s, d) wave -> per-bucket selection weights, the Gram rule's
    selection step only (Gram matmul + scores + ranked pick). Krum emits
    (W, s) one-hot/m weights; Bulyan its (W, rounds, s) phase-1 weight
    matrix — in both cases exactly what the wave fold consumes."""
    if rule == "krum":
        def one(gb):
            acc = jnp.promote_types(gb.dtype, jnp.float32)
            gram = jnp.matmul(gb, gb.T, preferred_element_type=acc)
            return _krum.gram_select(gram, f, use_sortnet=use_sortnet)
    elif rule == "bulyan":
        def one(gb):
            s = gb.shape[0]
            acc = jnp.promote_types(gb.dtype, jnp.float32)
            gram = jnp.matmul(gb, gb.T, preferred_element_type=acc)
            return _bulyan._selection_weight_matrix(
                distances_from_gram(gram), s, f, s - f - 2, jnp.float32,
                use_sortnet,
            )
    else:
        raise ValueError(
            f"--selection supports {SELECTION_RULES}, got {rule!r}"
        )
    return jax.vmap(one)


def selection_bench_one(rule, s, f, d, wave, reps, key, trials, impl):
    """Time one (rule, bucket_size, impl) selection cell: a jitted
    dependency-chained wave of ``wave`` buckets of ``s`` rows, selection
    weights consumed through the softsign DCE guard and written back
    into the stack (the bench_one methodology verbatim — paired reps,
    adaptive sizing, min over trials)."""
    g = jax.random.normal(key, (wave, s, d), jnp.float32)
    sel = _selection_fn(rule, f, impl == "sortnet")

    def _chain(stack):
        w = sel(stack).astype(jnp.float32)
        # Reduce whatever weight shape the rule emits to one scalar per
        # bucket through the nonlinear guard — every weight is a real
        # data dependency of the next iteration's stack.
        guarded = w * jax.lax.rsqrt(1.0 + w * w)
        per_bucket = guarded.reshape(wave, -1).sum(axis=1)
        return stack.at[:, 0, 0].add(per_bucket * 1e-6)

    chain = jax.jit(_chain, donate_argnums=0)
    s0_host = np.array(chain(g))  # compile + warm + sync (g donated)

    def timed(k):
        st = jnp.array(s0_host)
        np.asarray(st[0, :1, :1])  # finish H2D + drain queue
        t0 = time.perf_counter()
        for _ in range(k):
            st = chain(st)
        np.asarray(st[0, :1, :1])  # host readback sync
        return time.perf_counter() - t0

    est = profiling.paired_reps(timed, reps, pairs=2)
    if est is not None and est * reps < 0.25:
        reps = min(4000, max(reps, int(0.5 / max(est, 1e-7))))
    vals = [
        profiling.paired_reps(timed, reps, pairs=4, agg="min")
        for _ in range(max(1, trials))
    ]
    vals = [v for v in vals if v is not None]
    return min(vals) if vals else None


def _selection_main(args):
    """The --selection sweep: (rule x bucket_size x impl) grid, JSON +
    schema-versioned JSONL twin like the other modes."""
    from ...ops import coordinate as _coord

    rules = args.gars or list(SELECTION_RULES)
    sizes = args.sel_buckets or [8, 16, 32]
    # Default d sweep: the legacy 256 anchor plus the attention-shaped
    # regimes (d = heads * d_head * seq — a transformer worker's
    # per-layer activation-gradient granularity): 768 = the gpt_tiny
    # block (48-dim x 16-token copytask window), 3072 = the vit_tiny
    # block (3 heads x 16 d_head x 64 patches). Selection cost is
    # d-linear only through the Gram build, so these rows pin where the
    # transformer family's buckets actually land.
    ds = args.ds or [256, 768, 3072]
    wave = args.hier_wave
    key = jax.random.PRNGKey(0)
    results = []
    for rule in rules:
        for s in sorted(sizes):
            f = max_f(rule, s) if args.f_mode == "max" else min(
                1, max_f(rule, s))
            for d in ds:
                for impl in SELECTION_IMPLS:
                    if impl == "sortnet" and s > _coord.MAX_SORT_N:
                        continue  # the network is bounded; xla row stays
                    key, sub = jax.random.split(key)
                    try:
                        latency = selection_bench_one(
                            rule, s, f, d, wave, args.reps, sub,
                            args.trials, impl,
                        )
                    except Exception as exc:
                        print(f"{rule} s={s} f={f} impl={impl}: SKIP "
                              f"({exc})", file=sys.stderr)
                        continue
                    row = {"gar": rule, "n": s, "f": f, "d": d,
                           "grid": "selection", "impl": impl,
                           "wave_buckets": wave,
                           "latency_s": latency,
                           "per_bucket_s": (None if latency is None
                                            else latency / wave),
                           "trials": args.trials,
                           "dce_guard": "softsign",
                           "peak_rss_bytes": peak_rss_bytes()}
                    if latency is None:
                        row["below_noise_floor"] = True
                        print(f"{rule:>8} s={s:<3} f={f:<3} d={d:<5} "
                              f"impl={impl:<9} below noise floor",
                              flush=True)
                    else:
                        print(f"{rule:>8} s={s:<3} f={f:<3} d={d:<5} "
                              f"impl={impl:<9} "
                              f"{latency * 1e6:9.1f} us/wave  "
                              f"{latency / wave * 1e6:8.2f} us/bucket",
                              flush=True)
                    results.append(row)
    if args.json:
        with open(args.json, "w") as fp:
            json.dump(results, fp, indent=1)
        import os

        from ...telemetry import exporters

        jsonl_path = os.path.splitext(args.json)[0] + ".jsonl"
        with exporters.JsonlExporter(jsonl_path) as exp:
            for row in results:
                exp.write(exporters.make_record(
                    "gar_bench",
                    gar=row["gar"], n=row["n"], f=row["f"], d=row["d"],
                    latency_s=row["latency_s"],
                    grid=row["grid"], impl=row["impl"],
                    wave_buckets=row["wave_buckets"],
                    per_bucket_s=row["per_bucket_s"],
                    below_noise_floor=row.get("below_noise_floor", False),
                    trials=row["trials"], dce_guard=row["dce_guard"],
                    peak_rss_bytes=row["peak_rss_bytes"],
                ))
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description="GAR latency microbenchmark")
    p.add_argument("--gars", nargs="*", default=None)
    p.add_argument("--ns", nargs="*", type=int, default=None)
    p.add_argument("--ds", nargs="*", type=int, default=None)
    p.add_argument("--reps", type=int, default=10)
    p.add_argument("--trials", type=int, default=3,
                   help="Independent min-of-pairs timing trials per cell; "
                        "the committed value is the minimum (VERDICT r4 "
                        "#3 min-over-k — co-tenant noise only adds time).")
    p.add_argument("--f_mode", choices=["max", "one"], default="max",
                   help="f per (rule, n): contract maximum or fixed 1.")
    p.add_argument("--hier", action="store_true",
                   help="Hierarchical federated-scale grid: streaming-"
                        "ingest hier-* rules at n in 2^10..2^17 (defaults; "
                        "override with --gars/--ns/--ds), peak-RSS per "
                        "row, 'hier_bench' JSONL records — HIERBENCH_r*'s "
                        "capture mode.")
    p.add_argument("--selection", action="store_true",
                   help="Selection micro mode: the Gram-rule selection "
                        "step alone (Gram + scores + ranked pick), "
                        "batched over a wave of buckets as the "
                        "hierarchy's vmapped fold runs it, once per "
                        "impl (sortnet vs xla_sort as explicit "
                        "use_sortnet closures). 'gar_bench' rows with "
                        "grid='selection' and an 'impl' field.")
    p.add_argument("--sel_buckets", nargs="*", type=int, default=None,
                   metavar="S",
                   help="With --selection: bucket sizes to sweep "
                        "(default 8 16 32; the sortnet impl requires "
                        "S <= MAX_SORT_N).")
    p.add_argument("--hier_bucket", type=int, default=None,
                   help="Hierarchy bucket size (default MAX_SORT_N=32, "
                        "the Pallas sorting-network sweet spot).")
    p.add_argument("--hier_wave", type=int, default=8,
                   help="Streaming wave width: buckets folded per vmapped "
                        "dispatch.")
    p.add_argument("--flat_baseline", nargs="*", type=int, default=None,
                   metavar="N",
                   help="With --hier: also time the flat krum/median cells "
                        "at these n (same container, same methodology) so "
                        "the artifact carries its own apples-to-apples "
                        "baseline — GARBENCH_r3's flat numbers are a CHIP "
                        "capture (BASELINE.md).")
    p.add_argument("--json", type=str, default=None,
                   help="Also dump results to this JSON file (plus the "
                        "schema-versioned telemetry JSONL twin at the same "
                        "path with a .jsonl suffix — one 'gar_bench'/"
                        "'hier_bench' record per cell, validated by the "
                        "tier-1 schema check).")
    args = p.parse_args(argv)

    if args.selection:
        return _selection_main(args)

    if args.hier:
        names = args.gars or ["hier-krum", "hier-median"]
        ns = args.ns or [2 ** k for k in range(10, 18)]
        ds = args.ds or [10 ** 5]
    else:
        names = args.gars or sorted(
            g for g in aggregators.gars if not g.startswith("hier"))
        ns = args.ns or [2 ** k for k in range(2, 8)]
        ds = args.ds or [10 ** k for k in range(1, 5)]

    key = jax.random.PRNGKey(0)
    results = []

    def flat_cell(name, n, d, trials):
        gar = aggregators.gars[name]
        f = max_f(name, n) if args.f_mode == "max" else min(1, max_f(name, n))
        nonlocal key
        key, sub = jax.random.split(key)
        try:
            latency = bench_one(gar, n, f, d, args.reps, sub, trials=trials)
        except Exception as exc:
            print(f"{name} n={n} f={f} d={d}: SKIP ({exc})", file=sys.stderr)
            return None
        if latency is INCOMPATIBLE:
            return None
        row = {"gar": name, "n": n, "f": f, "d": d,
               "latency_s": latency,
               # provenance: future GARBENCH_r* readers can tell
               # guarded min-over-k sweeps from the r3/r4 format
               "trials": trials, "dce_guard": "softsign",
               "peak_rss_bytes": peak_rss_bytes()}
        results.append(row)
        if latency is None:  # below noise floor (paired_reps)
            row["below_noise_floor"] = True
            print(f"{name:>16} n={n:<4} f={f:<3} d={d:<7} "
                  f"below noise floor", flush=True)
        else:
            print(f"{name:>16} n={n:<4} f={f:<3} d={d:<7} "
                  f"{latency * 1e3:8.3f} ms", flush=True)
        return row

    for name in names:
        if name.startswith("hier"):
            bucket = args.hier_bucket or hierarchy.DEFAULT_BUCKET_SIZE
            # Ascending n: ru_maxrss is a high-water mark, so this order
            # makes the O(buckets) memory profile readable row-to-row.
            for n in sorted(ns):
                f = (max_f(name, n) if args.f_mode == "max"
                     else min(1, max_f(name, n)))
                for d in ds:
                    try:
                        cell = hier_bench_one(
                            name, n, f, d, bucket_size=bucket,
                            wave=args.hier_wave, trials=args.trials,
                        )
                    except Exception as exc:
                        print(f"{name} n={n} f={f} d={d}: SKIP ({exc})",
                              file=sys.stderr)
                        continue
                    row = {"gar": name, "n": n, "f": f, "d": d,
                           "grid": "hier", "trials": args.trials,
                           "dce_guard": "softsign",
                           "peak_rss_bytes": peak_rss_bytes(), **cell}
                    results.append(row)
                    print(f"{name:>16} n={n:<7} f={f:<6} d={d:<7} "
                          f"{cell['latency_s']:8.3f} s total  "
                          f"{cell['per_client_s'] * 1e6:9.1f} us/client  "
                          f"rss {row['peak_rss_bytes'] / 2**20:7.0f} MiB",
                          flush=True)
        else:
            for n in sorted(ns):
                if name.endswith("brute") and n > BRUTE_MAX_N:
                    continue
                for d in ds:
                    flat_cell(name, n, d, args.trials)

    # Same-container flat anchor cells for the hier artifact (reps=1:
    # a flat median at n=512, d=1e5 runs ~7 s PER CALL on this class of
    # host — the paired-reps chain at default reps would take hours).
    if args.hier and args.flat_baseline:
        saved_reps, args.reps = args.reps, 1
        for n in args.flat_baseline:
            for base in ("krum", "median"):
                for d in ds:
                    row = flat_cell(base, n, d, 1)
                    if row is not None:
                        row["grid"] = "flat_baseline"
        args.reps = saved_reps

    if args.json:
        with open(args.json, "w") as fp:
            json.dump(results, fp, indent=1)
        # Schema-versioned JSONL twin (telemetry/exporters.py): the format
        # GARBENCH_r*/HIERBENCH_r* artifacts adopt — the tier-1 schema
        # check validates it, so a malformed sweep fails loudly.
        import os

        from ...telemetry import exporters

        jsonl_path = os.path.splitext(args.json)[0] + ".jsonl"
        with exporters.JsonlExporter(jsonl_path) as exp:
            for row in results:
                if row.get("grid") == "hier":
                    exp.write(exporters.make_record(
                        "hier_bench",
                        gar=row["gar"], n=row["n"], f=row["f"], d=row["d"],
                        bucket_size=row["bucket_size"],
                        levels=row["levels"],
                        num_buckets=row["num_buckets"],
                        latency_s=row["latency_s"],
                        per_client_s=row["per_client_s"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                        wave_buckets=row["wave_buckets"],
                        trials=row["trials"], dce_guard=row["dce_guard"],
                    ))
                else:
                    exp.write(exporters.make_record(
                        "gar_bench",
                        gar=row["gar"], n=row["n"], f=row["f"], d=row["d"],
                        latency_s=row["latency_s"],
                        below_noise_floor=row.get(
                            "below_noise_floor", False),
                        trials=row["trials"], dce_guard=row["dce_guard"],
                        peak_rss_bytes=row["peak_rss_bytes"],
                    ))
    return results


if __name__ == "__main__":
    main(sys.argv[1:])

"""Cross-process AggregaThor: one OS process per node, PeerExchange DCN.

This is the host-driver deployment shape of the reference — one process per
node pulling models/gradients through the message exchange
(tensorflow_impl/applications/AggregaThor/trainer.py:55-95, fanned out by
run_exp.sh) — with the gRPC servicer replaced by ``utils.exchange.
PeerExchange`` (TCP frames + the native MRMW register). Unlike the on-mesh
SPMD topologies (parallel/aggregathor.py), synchronization here is REAL
wait-n-f: the PS proceeds with the q = n_w - f *fastest* worker gradients
per step (server.py:134-155), so crashed or straggling workers are simply
absent from the quorum — no seeded-subset emulation.

Roles (ClusterConfig task):
  - ``ps`` (rank 0, exactly one — the AggregaThor SSMW trusted server):
    publishes the flat model each step, collects the q fastest worker
    gradients, aggregates with the GAR, applies the optimizer update.
  - ``worker`` (ranks 1..n_w): collects the step's model from the PS slot,
    computes its data shard's gradient, publishes the flat gradient back to
    the PS. A worker started with ``--attack`` is a REAL Byzantine process
    (byzWorker.py:50-125): it poisons its own published gradient
    host-side; it cannot see honest gradients, so only the self-contained
    attacks (reverse, random, crash) apply — the statistics-aware ones
    (lie, empire) remain the on-mesh topologies' domain.

Both planes share one exchange: the PS slot only ever carries models, the
worker slots only gradients, and ``collect(..., peers=...)`` waits on
exactly the relevant slots.

Model-state (BatchNorm) caveat: only gradients/params travel, so worker BN
statistics evolve locally — the same silent semantics as the reference,
whose RPC path also ships gradients only (see parallel/core.py docstring).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree

from ..aggregators import gars
from ..parallel import core
from ..utils import multihost, tools
from ..utils.exchange import PeerExchange
from . import common

__all__ = ["run"]


def _host_attack(name, params):
    """Self-contained Byzantine gradient attacks, applied by the attacker
    process to its OWN gradient (byzWorker.py: 'random' :60-66, 'reverse'
    :68-77; 'crash' = the process simply dies, covered by killing it)."""
    if name is None:
        return None
    scale = float(params.get("scale", 100.0))
    rng = np.random.default_rng(int(params.get("seed", 666)))
    if name == "random":
        return lambda g: rng.standard_normal(g.shape).astype(g.dtype) * scale
    if name == "reverse":
        return lambda g: g * (-scale)
    raise SystemExit(
        f"--attack {name!r} needs the honest gradients' statistics and only "
        "exists on the on-mesh topologies; cluster workers support "
        "random/reverse (or kill the process for a crash)."
    )


def _setup(args):
    """Shared ingredients for both roles."""
    cfg = multihost.ClusterConfig(args.cluster)
    if args.task:
        ttype, _, tidx = args.task.partition(":")
        cfg.task_type = ttype
        cfg.task_index = int(tidx or 0)
    if len(cfg.ps) != 1:
        raise SystemExit(
            "cluster mode is the AggregaThor SSMW topology: exactly one "
            f"trusted PS (got {len(cfg.ps)}); multi-PS ByzSGD runs on-mesh."
        )
    n_w = len(cfg.workers)
    f = args.fw
    q = n_w - f
    wm = getattr(args, "worker_momentum", None)
    if wm is not None and not (0.0 <= wm < 1.0):
        raise SystemExit(f"worker_momentum must be in [0, 1), got {wm}")
    if not f * 2 < n_w:
        # The majority-honest invariant the reference asserts
        # (Aggregathor/trainer.py:150-152) — enforced against the CONFIG's
        # worker count (the --cluster path bypasses the on-mesh assert).
        raise SystemExit(
            f"the number of Byzantine workers should be less than half the "
            f"number of workers (fw={f}, config has {n_w} workers)"
        )
    # Fail fast with the GAR's own contract before any process waits on
    # another (e.g. krum needs q >= 2f+3).
    if f:
        msg = gars[args.gar].check(np.zeros((q, 4), np.float32), f=f)
        if msg is not None:
            raise SystemExit(
                f"GAR {args.gar!r} cannot run on the q = n_w - fw = {q} "
                f"collected gradients: {msg}"
            )
    xs, ys, test_batches, iters_per_epoch = common.load_data(args, n_w)
    module, loss_fn, optimizer = common.build_ingredients(
        args, iters_per_epoch
    )
    init_fn, grad_fn, eval_fn = core.make_worker_fns(module, loss_fn)
    params0, ms0 = init_fn(jax.random.PRNGKey(args.seed), xs[0, 0])
    # Role-aware retention: the PS never trains (drop the shards), a worker
    # only reads its own shard (drop the rest and the test set) — no point
    # keeping n_w + 1 copies of the dataset across the deployment's hosts.
    if cfg.task_type == "ps":
        xs = ys = None
    else:
        xs, ys = xs[cfg.task_index], ys[cfg.task_index]
        test_batches = None
    flat0, unravel = ravel_pytree(params0)
    ex = PeerExchange(cfg.process_id, cfg.hosts)
    return (cfg, n_w, f, q, xs, ys, test_batches, optimizer, grad_fn,
            eval_fn, params0, ms0, flat0, unravel, ex)


def run(args):
    """Entry: dispatch on the configured role."""
    (cfg, n_w, f, q, xs, ys, test_batches, optimizer, grad_fn, eval_fn,
     params0, ms0, flat0, unravel, ex) = _setup(args)
    worker_ranks = list(range(1, 1 + n_w))
    timeout_ms = args.cluster_timeout_ms
    try:
        if cfg.task_type == "ps":
            return _run_ps(
                args, q, worker_ranks, test_batches, optimizer, eval_fn,
                params0, ms0, flat0, unravel, ex, timeout_ms,
            )
        return _run_worker(
            args, cfg.task_index, xs, ys, grad_fn, ms0, flat0, unravel, ex,
            timeout_ms,
        )
    finally:
        ex.close()


def _run_ps(args, q, worker_ranks, test_batches, optimizer, eval_fn,
            params0, ms0, flat0, unravel, ex, timeout_ms):
    """The trusted server: model out, q fastest gradients in, GAR, update."""
    from .. import parallel

    f = args.fw
    gar = gars[args.gar]
    opt_state0 = optimizer.init(params0)
    test_batches = parallel.EvalSet(
        test_batches, binary=args.dataset == "pima"
    )

    gar_params = dict(getattr(args, "gar_params", None) or {})

    gar_base_key = jax.random.PRNGKey(args.seed)

    @jax.jit
    def ps_update(flat_params, opt_state, grads_stack, step):
        # f=0 with the default rule short-circuits to the mean, but an
        # explicitly requested rule (e.g. cclip, which is valid at f=0)
        # must run — silently averaging would fake the defense. Randomized
        # rules (condense) need a fresh per-step key: without it the fixed
        # keyless fallback would apply the SAME coordinate mask every
        # iteration under jit.
        if f or args.gar != "average":
            agg = gar.unchecked(
                grads_stack, f=f,
                key=jax.random.fold_in(gar_base_key, step), **gar_params,
            )
        else:
            agg = jnp.mean(grads_stack, axis=0)
        params = unravel(flat_params)
        updates, opt_state = optimizer.update(
            unravel(agg), opt_state, params
        )
        params = optax.apply_updates(params, updates)
        return ravel_pytree(params)[0], opt_state

    def acc_eval(state_flat):
        return parallel.compute_accuracy(
            (unravel(state_flat), ms0),
            lambda s, x: eval_fn(s[0], s[1], x),
            test_batches,
            binary=args.dataset == "pima",
        )

    t0 = time.time()
    flat = np.asarray(flat0, np.float32)
    flat_dev, opt_state = jnp.asarray(flat), opt_state0
    d_bytes = flat.size * 4
    good_ranks = list(worker_ranks)
    losses_seen = 0
    # PS-side checkpoint/resume (utils/checkpoint.py — the deliberate
    # upgrade over the reference, which has none; the on-mesh analog with
    # sharded TrainState + bit-exact rng replay lives in common.train).
    # Only the PS holds TRAINING state: resumed workers request model
    # round 0 and read_latest's catch-up semantics jump them straight to
    # the PS's resumed round. Exception: with --worker_momentum the workers
    # hold the EMA, which is NOT persisted — it re-warms over ~1/(1-beta)
    # steps after a resume (the worker warns; see _run_worker).
    ckpt = None
    start_iter = last_saved = 0
    if args.checkpoint_dir:
        from ..utils import checkpoint as ckpt_lib

        ckpt = ckpt_lib.Checkpointer(args.checkpoint_dir)
        step = ckpt.latest_step()
        if args.resume and step is not None:
            restored = ckpt.restore(
                {"flat": flat, "opt_state": jax.tree.map(
                    np.asarray, opt_state)},
                step=step,
            )
            flat = np.asarray(restored["flat"], np.float32)
            flat_dev = jnp.asarray(flat)
            opt_state = jax.tree.map(jnp.asarray, restored["opt_state"])
            start_iter = last_saved = int(step)
            print(f"[cluster-ps] resumed from step {start_iter}", flush=True)
    for i in range(start_iter, args.num_iter):
        ex.publish(i, flat.tobytes(), to=worker_ranks)
        # A Byzantine PROCESS controls its wire bytes, not just its values:
        # a wrong-length payload cannot enter the GAR (frombuffer/stack
        # would throw) and proves its sender Byzantine — exclude the rank
        # from all future quorums and re-collect from the rest (the frames
        # already received return instantly). A quorum TIMEOUT triggers a
        # model re-publish before the final attempt: the model plane is
        # fire-and-forget, so workers whose listener bound after this
        # step's publish (cold start) would otherwise never see a frame to
        # catch up to and the healthy cluster would deadlock.
        attempts = 0
        while True:
            try:
                got = ex.collect(
                    i, q, peers=good_ranks, timeout_ms=timeout_ms
                )
            except TimeoutError:
                attempts += 1
                if attempts >= 3:
                    raise
                tools.warning(
                    f"[cluster-ps] step {i} quorum timed out; re-publishing "
                    f"the model (attempt {attempts})"
                )
                ex.publish(i, flat.tobytes(), to=worker_ranks)
                continue
            bad = [k for k in got if len(got[k]) != d_bytes]
            if not bad:
                break
            for k in bad:
                tools.warning(
                    f"[cluster-ps] worker rank {k} sent a malformed "
                    f"{len(got[k])}-byte gradient (expected {d_bytes}); "
                    "excluding it from all future quorums"
                )
            good_ranks = [k for k in good_ranks if k not in bad]
            if len(good_ranks) < q:
                raise SystemExit(
                    f"only {len(good_ranks)} well-formed workers remain "
                    f"but the quorum needs q={q}; aborting"
                )
        # Deterministic composition: of the >= q arrivals, aggregate the q
        # lowest ranks (the GAR's n is static under jit).
        rows = [
            np.frombuffer(got[k], np.float32) for k in sorted(got)[:q]
        ]
        flat_dev, opt_state = ps_update(
            flat_dev, opt_state, jnp.asarray(np.stack(rows)),
            jnp.asarray(i, jnp.int32),
        )
        flat = np.asarray(flat_dev, np.float32)  # next step's publication
        losses_seen = i + 1
        if ckpt and args.checkpoint_freq and (i + 1) % args.checkpoint_freq == 0:
            ckpt.save(i + 1, {
                "flat": flat,
                "opt_state": jax.tree.map(np.asarray, opt_state),
            })
            last_saved = i + 1
        if args.acc_freq and i % args.acc_freq == 0:
            acc = acc_eval(flat_dev)
            print(
                f"Step: {i} Accuracy: {acc:.4f} "
                f"Time: {time.time() - t0:.1f}",
                flush=True,
            )
    # Stop sentinel: an empty frame at step num_iter tells every worker
    # (including stragglers that skipped rounds) training is over.
    ex.publish(args.num_iter, b"", to=worker_ranks)
    acc = acc_eval(flat_dev)
    if ckpt:
        if args.checkpoint_freq and last_saved != args.num_iter:
            # Final save, skipped when the in-loop save already wrote this
            # exact step (orbax writes are synchronous; workers idle
            # meanwhile).
            ckpt.save(args.num_iter, {
                "flat": flat,
                "opt_state": jax.tree.map(np.asarray, opt_state),
            })
        ckpt.close()
    summary = {
        "final_accuracy": acc,
        "steps": losses_seen,
        "wall_s": time.time() - t0,
    }
    print(json.dumps({"tag": "cluster-ps", **summary}), flush=True)
    return summary


def _run_worker(args, windex, my_xs, my_ys, grad_fn, ms0, flat0, unravel,
                ex, timeout_ms):
    """One worker process: model in, shard gradient out. ``windex`` is the
    worker's data shard; its exchange rank is 1 + windex.

    The model read is ``read_latest`` (newest round >= the expected one),
    NOT an exact-step collect: a straggler whose expected model was already
    overwritten in the last-writer-wins slot must catch up to the PS's
    current round, not crash — turning a tolerated straggler into a
    permanent casualty would silently consume the f budget.
    """
    attack = _host_attack(args.attack, args.attack_params)
    # Worker momentum (Karimireddy et al. 2021; same EMA + zeros init as the
    # on-mesh trainers, core.worker_mom_update): this process publishes its
    # EMA instead of the raw gradient. A Byzantine worker poisons whatever
    # it publishes (attack applied after), and a straggler that skips steps
    # via read_latest only folds in gradients it actually computed — the
    # real deployment semantics.
    beta = getattr(args, "worker_momentum", None)
    mom = None
    if beta is not None and getattr(args, "resume", False):
        tools.warning(
            f"worker {windex}: worker momentum is not checkpointed — the "
            f"EMA restarts from zero and re-warms over ~{1.0 / (1.0 - beta):.0f} "
            "steps after this resume"
        )

    @jax.jit
    def worker_grad(flat_params, ms, x, y, rng):
        grads, (loss, new_ms) = grad_fn(unravel(flat_params), ms, x, y, rng)
        return ravel_pytree(grads)[0], loss, new_ms

    base_key = jax.random.PRNGKey(args.seed + 1 + windex)
    d_bytes = int(np.asarray(flat0).size) * 4
    num_batches = my_xs.shape[0]
    ms = ms0
    loss = None
    steps_done = 0
    i = 0
    while i < args.num_iter:
        step, payload = ex.read_latest(0, i, timeout_ms=timeout_ms)
        if step >= args.num_iter or not payload:
            break  # PS's stop sentinel (empty frame at num_iter)
        if len(payload) != d_bytes:
            # NOT the sentinel: a non-empty model frame of the wrong size
            # means the PS runs a different model/dtype config — a
            # deployment error that must fail loudly, not exit rc 0.
            raise SystemExit(
                f"model frame is {len(payload)} bytes but this worker's "
                f"model flattens to {d_bytes}; PS and worker configs "
                "disagree (--model/--dtype/--dataset)"
            )
        b = step % num_batches
        g, loss, ms = worker_grad(
            jnp.asarray(np.frombuffer(payload, np.float32)), ms,
            my_xs[b], my_ys[b], jax.random.fold_in(base_key, step),
        )
        g = np.asarray(g, np.float32)
        if beta is not None:
            mom = (1.0 - beta) * g + beta * (0.0 if mom is None else mom)
            g = mom.astype(np.float32)
        if attack is not None:
            g = attack(g)
        ex.publish(step, g.tobytes(), to=[0])
        steps_done += 1
        if args.log:
            print(
                f"Worker {windex} loss {step}: {float(loss):.6f}", flush=True
            )
        i = step + 1
    summary = {
        "steps": steps_done,
        "final_loss": float(loss) if loss is not None else None,
    }
    print(json.dumps({"tag": f"cluster-worker-{windex}", **summary}),
          flush=True)
    return summary

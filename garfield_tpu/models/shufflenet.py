"""ShuffleNet v1 (counterpart of garfieldpp/models/shufflenet.py): grouped
1x1 convs + channel shuffle."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import avg_pool, conv, conv1x1, global_avg_pool, norm


def channel_shuffle(x, groups):
    n, h, w, c = x.shape
    return (x.reshape(n, h, w, groups, c // groups)
             .transpose(0, 1, 2, 4, 3)
             .reshape(n, h, w, c))


class ShuffleBlock(nn.Module):
    out_planes: int
    stride: int
    groups: int
    first_group_conv: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        in_planes = x.shape[-1]
        cat = self.stride == 2
        mid = self.out_planes // 4
        out_planes = self.out_planes - in_planes if cat else self.out_planes
        g = self.groups if self.first_group_conv else 1
        out = nn.relu(norm(train, dtype=d)(
            conv1x1(mid, groups=g, dtype=d)(x)))
        out = channel_shuffle(out, self.groups)
        out = norm(train, dtype=d)(
            conv(mid, 3, self.stride, padding=1, groups=mid, dtype=d)(out))
        out = norm(train, dtype=d)(
            conv1x1(out_planes, groups=self.groups, dtype=d)(out))
        if cat:
            res = avg_pool(x, 2)
            return nn.relu(jnp.concatenate([out, res], axis=-1))
        return nn.relu(out + x)


class ShuffleNet(nn.Module):
    out_planes: tuple
    num_blocks: tuple
    groups: int
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        x = nn.relu(norm(train, dtype=d)(conv1x1(24, dtype=d)(x)))
        for stage in range(3):
            for i in range(self.num_blocks[stage]):
                stride = 2 if i == 0 else 1
                x = ShuffleBlock(
                    self.out_planes[stage], stride, self.groups,
                    first_group_conv=not (stage == 0 and i == 0), dtype=d,
                )(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)


def ShuffleNetG2(num_classes=10, dtype=jnp.float32):
    return ShuffleNet((200, 400, 800), (4, 8, 4), 2, num_classes, dtype)


def ShuffleNetG3(num_classes=10, dtype=jnp.float32):
    return ShuffleNet((240, 480, 960), (4, 8, 4), 3, num_classes, dtype)

"""Tests for garfield_tpu.attacks — parity with byzWorker.py / byzServer.py."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import attacks
from garfield_tpu.aggregators import gars


def _stack(n=8, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))


def _mask(n=8, byz=(0, 3)):
    m = np.zeros(n, dtype=bool)
    m[list(byz)] = True
    return jnp.asarray(m)


class TestGradientAttacks:
    def test_honest_rows_untouched(self):
        g, m = _stack(), _mask()
        key = jax.random.PRNGKey(0)
        for name in attacks.gradient_attacks:
            out = attacks.apply_gradient_attack(name, g, m, key=key)
            np.testing.assert_array_equal(
                np.asarray(out)[~np.asarray(m)], np.asarray(g)[~np.asarray(m)],
                err_msg=f"attack {name} modified honest rows",
            )

    def test_none_passthrough(self):
        g, m = _stack(), _mask()
        for name in (None, "none"):
            out = attacks.apply_gradient_attack(name, g, m)
            np.testing.assert_array_equal(np.asarray(out), np.asarray(g))

    def test_unknown_attack_raises(self):
        g, m = _stack(), _mask()
        with pytest.raises(ValueError):
            attacks.apply_gradient_attack("nope", g, m)

    def test_random_needs_key(self):
        g, m = _stack(), _mask()
        with pytest.raises(ValueError):
            attacks.apply_gradient_attack("random", g, m)

    def test_reverse_is_times_minus_100(self):
        """byzWorker.py:94 — grad * -100."""
        g, m = _stack(), _mask()
        out = attacks.apply_gradient_attack("reverse", g, m)
        np.testing.assert_allclose(
            np.asarray(out)[0], np.asarray(g)[0] * -100.0, rtol=1e-6
        )

    def test_drop_zeroes_about_p_fraction(self):
        """byzWorker.py:103-105 — ~30% of coords zeroed on byz rows only."""
        g = jnp.ones((4, 10000), dtype=jnp.float32)
        m = jnp.asarray([True, False, True, False])
        out = np.asarray(
            attacks.apply_gradient_attack("drop", g, m, key=jax.random.PRNGKey(1))
        )
        frac0 = (out[0] == 0).mean()
        assert 0.25 < frac0 < 0.35
        assert (out[1] == 1).all()

    def test_lie_matches_reference_formula(self):
        """byzWorker.py:119-124 — mu + 1.035*sigma over cohort honest grads,
        with torch's unbiased std."""
        g, m = _stack(n=8), _mask(byz=(1, 4, 6))
        out = np.asarray(attacks.apply_gradient_attack("lie", g, m))
        cohort = np.asarray(g)[[1, 4, 6]]
        expect = cohort.mean(0) + 1.035 * cohort.std(0, ddof=1)
        for r in (1, 4, 6):
            np.testing.assert_allclose(out[r], expect, rtol=1e-5)

    def test_empire_matches_reference_formula(self):
        """byzWorker.py:140-142 — -10 * mu over cohort honest grads."""
        g, m = _stack(n=8), _mask(byz=(2, 5))
        out = np.asarray(attacks.apply_gradient_attack("empire", g, m))
        cohort = np.asarray(g)[[2, 5]]
        np.testing.assert_allclose(out[2], -10.0 * cohort.mean(0), rtol=1e-5)

    def test_lie_single_byzantine_nan_like_torch(self):
        """fw=1: torch.std of one sample is NaN (byzWorker.py:121); GARs must
        then treat the row as infinitely distant, not crash."""
        g, m = _stack(n=6), _mask(n=6, byz=(3,))
        out = attacks.apply_gradient_attack("lie", g, m)
        assert np.isnan(np.asarray(out)[3]).all()
        agg = gars["median"](out, f=1)
        assert np.isfinite(np.asarray(agg)).all()

    def test_attacks_jit_and_vmap_compatible(self):
        g, m = _stack(), _mask()
        key = jax.random.PRNGKey(2)

        @jax.jit
        def step(g, m, key):
            return attacks.apply_gradient_attack("lie", g, m, key=key)

        out = step(g, m, key)
        assert out.shape == g.shape

    def test_krum_resists_reverse(self):
        """Integration: Multi-Krum must not select a reversed gradient when
        n >= 2f+3 (the Byzantine-resilience contract the attacks exercise)."""
        n, f = 11, 2
        rng = np.random.default_rng(7)
        base = rng.normal(size=(16,)).astype(np.float32)
        g = jnp.asarray(base[None, :] + 0.01 * rng.normal(size=(n, 16)).astype(np.float32))
        m = _mask(n=n, byz=(0, 1))
        poisoned = attacks.apply_gradient_attack("reverse", g, m)
        agg = np.asarray(gars["krum"](poisoned, f=f))
        honest_mean = np.asarray(g)[2:].mean(0)
        assert np.linalg.norm(agg - honest_mean) < 1.0
        assert np.dot(agg, base) > 0  # not reversed


class TestModelAttacks:
    def test_reverse(self):
        m = jnp.arange(8.0)
        out = attacks.apply_model_attack("reverse", m)
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * -100.0)

    def test_random_shape_and_range(self):
        m = jnp.zeros(100)
        out = np.asarray(
            attacks.apply_model_attack("random", m, key=jax.random.PRNGKey(3))
        )
        assert out.shape == (100,)
        assert (out >= 0).all() and (out < 1).all()

    def test_drop_fraction(self):
        m = jnp.ones(10000)
        out = np.asarray(
            attacks.apply_model_attack("drop", m, key=jax.random.PRNGKey(4))
        )
        assert 0.25 < (out == 0).mean() < 0.35

    def test_passthrough_and_unknown(self):
        m = jnp.ones(4)
        np.testing.assert_array_equal(
            np.asarray(attacks.apply_model_attack(None, m)), np.ones(4)
        )
        with pytest.raises(ValueError):
            attacks.apply_model_attack("bogus", m)

"""D-SHARDING: partition the parameter vector across a PS shard group.

The MSMW topology replicates the parameter server for FAULT TOLERANCE —
every replica holds the full model and ingests every client (PAPER.md's
f_ps axis). This module adds the orthogonal axis the paper era never
needed: PARTITION the flat parameter/gradient vector into ``S``
contiguous shards, each owned by a PS shard process that runs its own
hierarchy levels (aggregators/hierarchy.py) and its own wire plane
(utils/exchange.py register slots), so wave ingest, hier-GAR folds and
model broadcast parallelize across shards — round time scales ~1/S
(FEDBENCH_r01) because every shard touches only d/S of each client.

Shard identity on the wire
--------------------------
Shard ``s``'s frames travel on exchange plane ``s`` AND carry ``s`` in
the wire codec header's spare plane nibble (utils/wire.py, DESIGN.md
§15) — the frames are self-describing end to end, so a frame that
arrives at the wrong shard is an attributable codec reject
(``wire.decode(buf, expect_plane=s)`` raises ``WireError``), exactly
like a CRC failure: a Byzantine client cannot smuggle a d/S-sized
payload for shard 0 into shard 1's fold and have the mismatch blamed on
the network. The nibble holds 16 values, so ``MAX_SHARDS = 16`` — a
deployment that needs more shards must widen the header (a new wire
version), not truncate ids (the capacity guard raises loudly at
publish/encode time, never wraps).

Sharded checkpoints
-------------------
``save_sharded``/``restore_sharded`` write one ``utils.checkpoint``
checkpoint PER SHARD (each shard process persists only its own span —
no shard ever materializes the full model), and restore reassembles the
spans bitwise into the unsharded vector (pinned by the tier-1
round-trip test at pima scale).
"""

import os

import numpy as np

from ..utils import checkpoint as ckpt_lib
from ..utils import wire

__all__ = [
    "MAX_SHARDS",
    "ShardSpec",
    "plan_shards",
    "shard_plane",
    "reassemble",
    "save_sharded",
    "restore_sharded",
    "restore_span",
    "latest_sharded_step",
    "sharded_steps",
]

# The shard id rides the wire codec header's spare plane nibble (and the
# transport header's plane byte is clamped to the same range by
# PeerExchange(planes<=16)) — 16 shard slots, enforced loudly.
MAX_SHARDS = wire.MAX_PLANE + 1


def shard_plane(shard, num_shards=None):
    """Exchange/wire plane of shard ``shard`` — the identity mapping,
    guarded: an out-of-range shard id must fail at the call site that
    would stamp it, never truncate into a foreign shard's nibble."""
    s = int(shard)
    if isinstance(shard, bool) or s != shard:
        raise TypeError(f"shard id must be an integer, got {shard!r}")
    hi = (MAX_SHARDS if num_shards is None else int(num_shards)) - 1
    if not 0 <= s <= hi:
        raise ValueError(
            f"shard id {s} out of range [0, {hi}]: the shard tag rides "
            f"the wire header's spare plane nibble ({MAX_SHARDS} slots); "
            "a larger shard group needs a wider wire header, not a "
            "truncated id"
        )
    return s


class ShardSpec:
    """Contiguous balanced partition of a ``d``-element flat vector into
    ``num_shards`` spans (larger spans first, like the hierarchy's
    balanced buckets — no tiny remainder shard)."""

    __slots__ = ("d", "num_shards", "spans")

    def __init__(self, d, num_shards):
        d = int(d)
        s = int(num_shards)
        if d < 1:
            raise ValueError(f"d must be >= 1, got {d}")
        if not 1 <= s <= MAX_SHARDS:
            raise ValueError(
                f"num_shards must be in [1, {MAX_SHARDS}] (the wire "
                f"header's shard nibble), got {num_shards}"
            )
        if s > d:
            raise ValueError(
                f"cannot split {d} parameters across {s} shards"
            )
        self.d = d
        self.num_shards = s
        base, rem = divmod(d, s)
        sizes = [base + 1] * rem + [base] * (s - rem)
        spans, off = [], 0
        for size in sizes:
            spans.append((off, off + size))
            off += size
        self.spans = tuple(spans)

    def width(self, shard):
        lo, hi = self.spans[shard_plane(shard, self.num_shards)]
        return hi - lo

    def slice_rows(self, rows, shard):
        """Shard ``shard``'s column span of an (k, d) block (or a (d,)
        vector) — the per-shard view every client publish and every
        shard ingest takes."""
        lo, hi = self.spans[shard_plane(shard, self.num_shards)]
        return rows[..., lo:hi]

    def __repr__(self):
        return f"<ShardSpec d={self.d} shards={self.num_shards}>"


def plan_shards(d, num_shards):
    return ShardSpec(d, num_shards)


def reassemble(spec, parts):
    """Concatenate per-shard (d_s,) vectors back to the unsharded (d,)
    float32 vector — bitwise: a pure span copy, no arithmetic."""
    if len(parts) != spec.num_shards:
        raise ValueError(
            f"expected {spec.num_shards} shard parts, got {len(parts)}"
        )
    out = np.empty(spec.d, np.float32)
    for s, (lo, hi) in enumerate(spec.spans):
        part = np.asarray(parts[s], np.float32).reshape(-1)
        if part.size != hi - lo:
            raise ValueError(
                f"shard {s} part has {part.size} elements, expected "
                f"{hi - lo}"
            )
        out[lo:hi] = part
    return out


# --- sharded checkpoints -----------------------------------------------------


def _shard_dir(directory, shard):
    return os.path.join(str(directory), f"shard_{int(shard):02d}")


def save_sharded(directory, step, model_vec, spec, *, shards=None,
                 max_to_keep=3):
    """Per-shard checkpoint of a flat model vector through
    ``utils.checkpoint.Checkpointer`` — one step-keyed checkpoint per
    shard subdirectory, each carrying its span so restore can verify the
    partition. ``shards`` restricts the write to a subset (a shard
    process saves only its own span); default all."""
    model_vec = np.asarray(model_vec, np.float32).reshape(-1)
    if model_vec.size != spec.d:
        raise ValueError(
            f"model has {model_vec.size} elements, spec expects {spec.d}"
        )
    for s in (range(spec.num_shards) if shards is None else shards):
        lo, hi = spec.spans[shard_plane(s, spec.num_shards)]
        ckpt_lib.Checkpointer(
            _shard_dir(directory, s), max_to_keep=max_to_keep
        ).save(step, {
            "model": model_vec[lo:hi].copy(),
            "span": np.asarray([lo, hi], np.int64),
            "meta": np.asarray([spec.d, spec.num_shards], np.int64),
        })


def sharded_steps(directory, spec):
    """Sorted steps present in EVERY shard subdirectory — the complete
    (untorn) checkpoints. A step some shards are missing never appears:
    restoring it would mix rounds across spans."""
    steps = None
    for s in range(spec.num_shards):
        mine = set(ckpt_lib.Checkpointer(_shard_dir(directory, s)).steps())
        steps = mine if steps is None else steps & mine
        if not steps:
            return []
    return sorted(steps)


def latest_sharded_step(directory, spec):
    """Newest step present in EVERY shard subdirectory (a torn save —
    some shards ahead of others — must not restore mixed rounds), or
    None when any shard has no checkpoint."""
    steps = sharded_steps(directory, spec)
    return steps[-1] if steps else None


def restore_span(directory, spec, shard, step):
    """ONE shard's span from its per-span checkpoint — the restore half
    of the failover handoff (controlplane/failover.py): a standby
    taking over span ``shard`` reads only that shard's subdirectory,
    never the full model. Verifies the recorded span/meta against the
    spec exactly like ``restore_sharded``. Returns the (d_s,) float32
    span, bitwise the bytes ``save_sharded`` wrote."""
    s = shard_plane(shard, spec.num_shards)
    lo, hi = spec.spans[s]
    like = {
        "model": np.zeros(hi - lo, np.float32),
        "span": np.zeros(2, np.int64),
        "meta": np.zeros(2, np.int64),
    }
    state = ckpt_lib.Checkpointer(_shard_dir(directory, s)).restore(
        like, step=int(step)
    )
    span = tuple(int(x) for x in np.asarray(state["span"]))
    meta = tuple(int(x) for x in np.asarray(state["meta"]))
    if span != (lo, hi) or meta != (spec.d, spec.num_shards):
        raise ValueError(
            f"shard {s} checkpoint was written for span {span} of a "
            f"d={meta[0]}, S={meta[1]} model; the spec expects span "
            f"({lo}, {hi}) of d={spec.d}, S={spec.num_shards}"
        )
    return np.asarray(state["model"], np.float32)


def restore_sharded(directory, spec, step=None):
    """Reassemble the unsharded (d,) model vector from per-shard
    checkpoints — bitwise equal to the vector ``save_sharded`` split
    (pinned). Raises if any shard is missing, a span mismatches the
    spec, or ``step`` is absent from a shard."""
    step = latest_sharded_step(directory, spec) if step is None else step
    if step is None:
        raise FileNotFoundError(
            f"no complete sharded checkpoint under {directory}"
        )
    return reassemble(spec, [
        restore_span(directory, spec, s, step)
        for s in range(spec.num_shards)
    ])

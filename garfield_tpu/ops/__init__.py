"""TPU kernel library (Pallas) for the coordinate-wise GAR hot path.

The reference ships hand-written CUDA kernels for exactly this layer
(pytorch_impl/libs/native/py_median/median.cu, py_bulyan/bulyan.cu — SURVEY
P13): the GAR math that sweeps the full d-dimensional gradient (d ≈ 1.1e7 for
ResNet-18) rather than the tiny (n, n) score matrices. On TPU the equivalents
are Pallas kernels: each kernel makes ONE pass over HBM, streaming (n, TILE)
column blocks through VMEM and running an in-register odd-even transposition
sorting network over the small n axis on the VPU — no (n, d) re-layout, no
XLA variadic sort, no second pass for the selection step.

Public entry points dispatch by backend: the Pallas path on TPU (or when
forced via ``interpret=True`` for CPU testing), a pure-jnp fallback elsewhere
with identical semantics (the fallback IS the spec; kernels are tested
against it, including NaN propagation and stable tie-breaking).
"""

from .coordinate import (
    MAX_SORT_N,
    averaged_median_mean,
    coordinate_median,
    sortnet_argmin,
    sortnet_argsort,
    sortnet_median,
    sortnet_row_sums,
    sortnet_sort,
    sortnet_top_m,
    sortnet_trimmed_mean,
    trimmed_mean,
    use_pallas,
)

__all__ = [
    "MAX_SORT_N",
    "averaged_median_mean",
    "coordinate_median",
    "sortnet_argmin",
    "sortnet_argsort",
    "sortnet_median",
    "sortnet_row_sums",
    "sortnet_sort",
    "sortnet_top_m",
    "sortnet_trimmed_mean",
    "trimmed_mean",
    "use_pallas",
]

"""MobileNet v1 (counterpart of garfieldpp/models/mobilenet.py):
depthwise-separable conv stacks, CIFAR-scale."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm

# (out_planes, stride) table; int means stride 1.
cfg = [64, (128, 2), 128, (256, 2), 256, (512, 2),
       512, 512, 512, 512, 512, (1024, 2), 1024]


class Block(nn.Module):
    out_planes: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        in_planes = x.shape[-1]
        x = nn.relu(norm(train, dtype=self.dtype)(
            conv(in_planes, 3, self.stride, padding=1, groups=in_planes,
                 dtype=self.dtype)(x)))
        return nn.relu(norm(train, dtype=self.dtype)(
            conv1x1(self.out_planes, dtype=self.dtype)(x)))


class MobileNet(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = nn.relu(norm(train, dtype=self.dtype)(
            conv(32, 3, 1, padding=1, dtype=self.dtype)(x)))
        for v in cfg:
            out, stride = (v, 1) if isinstance(v, int) else v
            x = Block(out, stride, dtype=self.dtype)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)

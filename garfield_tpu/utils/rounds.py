"""Unified round/staleness policy: ONE weighting law for both planes.

Bounded-staleness asynchronous aggregation (DESIGN.md §14) decouples the
PS round rate from the slowest rank: the server applies the robust
aggregate over the freshest ``q = n - f`` arrivals, each carrying a round
tag, with staleness-discounted weights — Kardam's dampening (Damaskinos
et al., 2018) composed with any registered GAR. The weighting law lives
HERE, in one module both deployment scales import verbatim:

  - the **host plane** (``apps/cluster.py`` roles over ``PeerExchange``):
    real round tags from the wire, ``staleness_weights`` on the host,
    rows scaled before the jit'd GAR call;
  - the **in-graph SPMD plane** (``parallel/aggregathor.make_trainer``'s
    ``staleness=`` emulation, the async analog of the seeded wait-n-f
    ``subset``): the same function traced into the step program, weights
    composed with the folded-attack row scales so ``fold.plan_for``'s
    fast path still applies (parallel/fold.py ``row_weights``).

A topology's staleness policy is therefore written once and deploys at
either scale — the refactor target ROADMAP item 3 names.

The law: ``w(tau) = decay ** tau`` for ``0 <= tau <= max_staleness``,
``0`` past the hard cutoff, and **exactly 1.0 at tau = 0** (IEEE pow is
exact there), so a fully-fresh quorum is bitwise-indistinguishable from
the synchronous path — the ``--max_staleness 0`` equality contract
(tests/test_staleness.py).
"""

import dataclasses
import os

import numpy as np

__all__ = [
    "DEFAULT_MAX_STALENESS",
    "DEFAULT_DECAY",
    "StalenessPolicy",
    "staleness_weights",
    "discount_rows",
    "resolve",
]

DEFAULT_MAX_STALENESS = 4
DEFAULT_DECAY = 0.5


def staleness_weights(tau, *, decay=DEFAULT_DECAY,
                      max_staleness=DEFAULT_MAX_STALENESS):
    """Per-row weights ``decay ** tau`` with a hard cutoff.

    ``tau`` is the per-row staleness in rounds (current round minus the
    row's round tag; negative values clamp to 0 — a frame can only be
    tagged ahead of the consumer transiently, during catch-up races).
    Accepts a numpy array (host plane) or a jnp array/tracer (in-graph
    emulation) and computes with the matching backend, so the SAME
    function serves both scales. Returns float32 weights; ``tau == 0``
    maps to exactly 1.0 and ``tau > max_staleness`` to exactly 0.0.
    """
    import jax
    import jax.numpy as jnp

    on_device = isinstance(tau, jax.Array)
    xp = jnp if on_device else np
    tau = xp.maximum(xp.asarray(tau, xp.int32), 0)
    w = xp.power(xp.float32(decay), tau.astype(xp.float32))
    w = xp.where(tau > max_staleness, xp.float32(0.0), w)
    return w.astype(xp.float32)


def discount_rows(stack, w):
    """Scale each row of an ``(n, d)`` stack (or any array with leading
    row axis) by its staleness weight — the "weights composed before the
    GAR" step on every path. At ``w == 1`` this is a bitwise no-op per
    IEEE multiply; callers that need *program*-level identity (the
    ``--max_staleness 0`` bitwise contract) short-circuit before calling.
    """
    return (stack * w.reshape((-1,) + (1,) * (stack.ndim - 1))).astype(
        stack.dtype
    )


@dataclasses.dataclass(frozen=True)
class StalenessPolicy:
    """The deployment's bounded-staleness contract: hard cutoff + decay.

    ``max_staleness`` bounds how many rounds behind the PS a gradient may
    be and still enter the aggregate (0 = the synchronous contract:
    exact-round frames only, all weights 1); ``decay`` is the per-round
    geometric discount.
    """

    max_staleness: int = DEFAULT_MAX_STALENESS
    decay: float = DEFAULT_DECAY

    def __post_init__(self):
        if self.max_staleness < 0:
            raise ValueError(
                f"max_staleness must be >= 0, got {self.max_staleness}"
            )
        if not (0.0 < self.decay <= 1.0):
            raise ValueError(f"decay must be in (0, 1], got {self.decay}")

    def weights(self, tau):
        return staleness_weights(
            tau, decay=self.decay, max_staleness=self.max_staleness
        )


def resolve(args):
    """``StalenessPolicy`` from the CLI flags, or None when ``--async``
    is off. Flag defaults come from ``GARFIELD_MAX_STALENESS`` /
    ``GARFIELD_STALENESS_DECAY`` so a deployment script can switch the
    whole fleet without editing every role's command line."""
    if not getattr(args, "async_agg", False):
        return None
    ms = getattr(args, "max_staleness", None)
    if ms is None:
        ms = int(os.environ.get(
            "GARFIELD_MAX_STALENESS", DEFAULT_MAX_STALENESS
        ))
    decay = getattr(args, "staleness_decay", None)
    if decay is None:
        decay = float(os.environ.get(
            "GARFIELD_STALENESS_DECAY", DEFAULT_DECAY
        ))
    return StalenessPolicy(max_staleness=int(ms), decay=float(decay))

"""Topology tests on the virtual 8-device CPU mesh.

This is the multi-node-without-a-cluster harness the reference approximates
with localhost multiprocessing (demo.py:264-301, SURVEY §4): every distributed
construct runs single-process over 8 host-local devices, so gather/aggregate/
update semantics are exercised with real XLA collectives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import models
from garfield_tpu.parallel import (
    aggregathor,
    byzsgd,
    compute_accuracy,
    learn,
    make_mesh,
)
from garfield_tpu.utils import selectors


def _pima_setup():
    module = models.select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer("sgd", lr=0.05, momentum=0.9)
    return module, loss, opt


def _pima_batches(num, bsz, seed=0):
    """Learnable synthetic binary task: y = 1[sum(x) > 0]."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(num, bsz, 8)).astype(np.float32)
    y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _run(step_fn, state, x, y, iters):
    losses = []
    for _ in range(iters):
        state, m = step_fn(state, x, y)
        losses.append(float(m["loss"]))
    return state, losses


class TestAggregathor:
    def test_converges_fault_free(self):
        module, loss, opt = _pima_setup()
        init_fn, step_fn, eval_fn = aggregathor.make_trainer(
            module, loss, opt, "average", num_workers=8
        )
        x, y = _pima_batches(8, 16)
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 30)
        assert losses[-1] < losses[0] * 0.7

    @pytest.mark.parametrize("gar,attack,f,subset", [
        ("krum", "lie", 2, None),
        ("krum", "reverse", 2, None),
        # subset=7 with a Gram-form rule: r5's sub-Gram composition keeps
        # the tree/fold fast path under true wait-n-f subsets — this row
        # is a REAL tree-vs-flat equivalence check on the per-subset key
        # derivation (it was a tripwire while subsets forced both paths
        # flat).
        ("krum", "reverse", 2, 7),
        ("krum", "lie", 2, 7),  # the extra-row fold composed with subset
        ("brute", "lie", 2, None),
        ("aksel", "reverse", 2, None),
        ("condense", "lie", 2, None),
        # subset == n never selects rows and stays tree-eligible: this row
        # genuinely compares tree vs flat.
        ("krum", "reverse", 2, 8),
        ("average", "empire", 2, None),
        ("average", None, 0, None),
        ("cclip", "lie", 2, None),
        ("median", "lie", 2, None),
        ("tmean", "reverse", 2, None),
        # r4: tree-mode Bulyan (concat-first; with a foldable attack this
        # row drives the FOLDED path); f=1 because Bulyan needs n >= 4f+3.
        ("bulyan", "lie", 1, None),
    ])
    def test_tree_path_matches_flat_path(self, gar, attack, f, subset):
        """The tree-mode fast path (no flat (n, d) stack) must produce the
        same training trajectory as the flat path for every deterministic
        attack/GAR/subset combination it serves."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        runs = []
        for tree_path in (True, False):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, gar, num_workers=8, f=f, attack=attack,
                subset=subset, tree_path=tree_path,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 5)
            runs.append((losses, jax.device_get(state.params)))
        np.testing.assert_allclose(runs[0][0], runs[1][0], rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
            runs[0][1], runs[1][1],
        )

    @pytest.mark.parametrize("gar,f", [("krum", 2), ("bulyan", 1)])
    def test_tree_where_path_matches_flat(self, gar, f, monkeypatch):
        """With GARFIELD_NO_FOLD the tree branch takes the where-path
        (apply_gradient_attack_tree + gar.tree_aggregate) — the foldable
        attacks otherwise dispatch to parallel.fold, leaving that branch
        without end-to-end coverage."""
        monkeypatch.setenv("GARFIELD_NO_FOLD", "1")
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        runs = []
        for tree_path in (True, False):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, gar, num_workers=8, f=f, attack="lie",
                tree_path=tree_path,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 5)
            runs.append((losses, jax.device_get(state.params)))
        np.testing.assert_allclose(runs[0][0], runs[1][0], rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
            runs[0][1], runs[1][1],
        )

    def test_krum_resists_reverse_attack(self):
        # Under the x-100 reverse attack (byzWorker.py:87-94), plain average
        # diverges while Krum stays stable — the core Garfield claim.
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)

        def final_loss(gar, f, attack):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, gar, num_workers=8, f=f, attack=attack
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            _, losses = _run(step_fn, state, x, y, 25)
            return losses[-1]

        clean = final_loss("average", 0, None)
        attacked_avg = final_loss("average", 2, "reverse")
        attacked_krum = final_loss("krum", 2, "reverse")
        assert attacked_krum < 1.5 * max(clean, 0.3)
        assert attacked_avg > 2 * attacked_krum

    def test_fold_invariance(self):
        # 8 logical workers on an 8-device mesh vs folded onto 2 devices must
        # produce the same training trajectory (SURVEY §7 "hard parts").
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)

        def run(mesh):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2, attack="lie",
                mesh=mesh,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 5)
            return losses



        full = run(make_mesh({"workers": 8}))
        folded = run(make_mesh({"workers": 2}, devices=jax.devices()[:2]))
        np.testing.assert_allclose(full, folded, rtol=1e-4, atol=1e-5)

    def test_subset_wait_n_minus_f(self):
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "krum", num_workers=8, f=1, attack="lie",
            subset=6,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        _, losses = _run(step_fn, state, x, y, 10)
        assert np.isfinite(losses).all()

    def test_layer_granularity(self):
        # Garfield_CC per-parameter aggregation (Garfield_CC/trainer.py:91-127).
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "median", num_workers=8, f=2, attack="reverse",
            granularity="layer",
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        _, losses = _run(step_fn, state, x, y, 20)
        assert losses[-1] < losses[0]

    def test_centralized_degenerate(self):
        # Centralized app (P16) = 1 worker, f=0, average, no attack.
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(1, 32)
        init_fn, step_fn, eval_fn = aggregathor.make_trainer(
            module, loss, opt, "average", num_workers=1,
            mesh=make_mesh({"workers": 1}, devices=jax.devices()[:1]),
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        _, losses = _run(step_fn, state, x, y, 20)
        assert losses[-1] < losses[0]

    def test_gar_contract_checked_at_build(self):
        module, loss, opt = _pima_setup()
        with pytest.raises(AssertionError, match="krum"):
            aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=4, f=2
            )

    def test_bf16_gar_pipeline_converges(self):
        """gar_dtype=bfloat16 (narrow aggregation pipeline, the TPU HBM
        lever in PERF.md) must train like the f32 pipeline: loss drops,
        params stay finite, trajectories track each other loosely (bf16
        rounding makes them non-bitwise by design)."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        runs = {}
        for dt in (None, jnp.bfloat16):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2, attack="lie",
                gar_dtype=dt,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 30)
            assert all(np.isfinite(l) for l in losses)
            for leaf in jax.tree.leaves(jax.device_get(state.params)):
                assert np.isfinite(leaf).all()
            runs[dt] = losses
        assert runs[jnp.bfloat16][-1] < runs[jnp.bfloat16][0] * 0.8
        # Same task, same seeds: end-of-run losses agree to bf16-ish slack.
        assert abs(runs[None][-1] - runs[jnp.bfloat16][-1]) < 0.15

    def test_worker_momentum_beta0_matches_baseline(self):
        """beta = 0 degenerates to the raw-gradient pipeline: identical
        trajectories (the momentum stack is write-through)."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        runs = []
        for wm in (None, 0.0):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2, attack="lie",
                worker_momentum=wm,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 6)
            runs.append(losses)
        np.testing.assert_allclose(runs[0], runs[1], rtol=1e-5)

    def test_worker_momentum_cclip_converges_under_lie(self):
        """The Karimireddy et al. pairing (worker momentum + cclip) trains
        through the lie attack; momentum state stays finite and updated."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "cclip", num_workers=8, f=2, attack="lie",
            worker_momentum=0.9,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 40)
        assert losses[-1] < losses[0] * 0.7
        mom_leaves = jax.tree.leaves(jax.device_get(state.worker_mom))
        assert mom_leaves, "momentum stack missing from TrainState"
        for leaf in mom_leaves:
            assert leaf.shape[0] == 8
            assert np.isfinite(leaf).all()
            assert np.abs(leaf).sum() > 0  # actually written

    def test_worker_momentum_with_wait_nf_subset(self):
        """Momentum composes with the wait-n-f path: the EMA updates on
        every worker before the gather, the subset samples rows after the
        attack — training proceeds and stays finite."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "cclip", num_workers=8, f=1, attack="lie",
            subset=7, worker_momentum=0.9,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 30)
        assert all(np.isfinite(l) for l in losses)
        # 0.9, not 0.8: the 30-step convergence RATE of this adversarial
        # config (cclip + lie + subset + momentum) is jax-version
        # sensitive (0.87 on 0.4.37 vs <0.8 on the tuning runtime); the
        # contract under test is composition-trains-finitely, not a rate.
        assert losses[-1] < losses[0] * 0.9

    def test_worker_momentum_checkpoint_roundtrip(self, tmp_path):
        """worker_mom travels through orbax save/restore like the rest of
        the state (template-based restore, utils/checkpoint.py)."""
        from garfield_tpu.utils import checkpoint as ckpt_lib

        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "cclip", num_workers=8, f=2, attack="lie",
            worker_momentum=0.9,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, _ = _run(step_fn, state, x, y, 3)
        ckpt_lib.save(str(tmp_path), 3, state)
        restored = ckpt_lib.restore(str(tmp_path), state)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            jax.device_get(state.worker_mom),
            jax.device_get(restored.worker_mom),
        )

    def test_accuracy_eval(self):
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, eval_fn = aggregathor.make_trainer(
            module, loss, opt, "average", num_workers=8
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, _ = _run(step_fn, state, x, y, 40)
        vx, vy = _pima_batches(4, 25, seed=7)
        batches = [(np.asarray(vx[i]), np.asarray(vy[i])) for i in range(4)]
        acc = compute_accuracy(state, eval_fn, batches, binary=True)
        assert acc > 0.7

    def test_batchnorm_model_state(self):
        # CNNet has BatchNorm: batch_stats must update and stay finite.
        module = models.select_model("cnn", "mnist")
        loss = selectors.select_loss("cross-entropy")
        opt = selectors.select_optimizer("sgd", lr=0.01)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "median", num_workers=8, f=1, attack="random"
        )
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(8, 2, 16, 16, 1)),
            jnp.float32,
        )
        y = jnp.zeros((8, 2), jnp.int32)
        state = init_fn(jax.random.PRNGKey(0), x[0])
        # step_fn donates its input state — copy to host before stepping.
        before = [np.asarray(l) for l in jax.tree.leaves(state.model_state)]
        state, m = step_fn(state, x, y)
        after = [np.asarray(l) for l in jax.tree.leaves(state.model_state)]
        assert len(after) > 0  # batch_stats collection exists
        assert all(np.isfinite(np.asarray(l)).all() for l in after)
        changed = any(
            not np.allclose(np.asarray(a), np.asarray(b))
            for a, b in zip(before, after)
        )
        assert changed


class TestByzSGD:
    def test_gar_dtype_smoke_byzsgd_learn(self):
        """gar_dtype=bfloat16 plumbs through the ByzSGD gradient phase and
        LEARN's phases 2-4: steps run, losses stay finite and decrease."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        mesh = make_mesh({"ps": 2, "workers": 4})
        init_fn, step_fn, _ = byzsgd.make_trainer(
            module, loss, opt, "krum", num_workers=8, num_ps=4, fw=2,
            fps=1, attack="reverse", ps_attack="random", mesh=mesh,
            model_gar="median", gar_dtype=jnp.bfloat16,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 15)
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, "median", num_nodes=8, f=1, attack="empire",
            non_iid=True, gar_dtype=jnp.bfloat16,
        )
        state = init_fn(jax.random.PRNGKey(1), x[0])
        state, losses = _run(step_fn, state, x, y, 15)
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

    def test_replicated_ps_under_both_attacks(self):
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        mesh = make_mesh({"ps": 2, "workers": 4})
        init_fn, step_fn, eval_fn = byzsgd.make_trainer(
            module, loss, opt, "median", num_workers=8, num_ps=4, fw=2,
            fps=1, attack="reverse", ps_attack="random", mesh=mesh,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 25)
        assert losses[-1] < losses[0]
        # After the model gather step all PS replicas agree (write_model).
        params = jax.device_get(state.params)
        for leaf in jax.tree.leaves(params):
            for i in range(1, leaf.shape[0]):
                np.testing.assert_allclose(leaf[i], leaf[0], rtol=1e-6)

    def test_tree_path_matches_flat_path_byzsgd(self):
        """ByzSGD's tree-mode gradient phase (krum) must reproduce the flat
        path's trajectory. (subset runs always take the flat path — the
        tree gate — so the A/B uses full participation, where the paths
        genuinely differ.)"""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        mesh = make_mesh({"ps": 2, "workers": 4})
        runs = []
        for tree_path in (True, False):
            init_fn, step_fn, _ = byzsgd.make_trainer(
                module, loss, opt, "krum", num_workers=8, num_ps=4, fw=2,
                fps=1, attack="lie", ps_attack="reverse", mesh=mesh,
                model_gar="median", tree_path=tree_path,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 5)
            runs.append((losses, jax.device_get(state.params)))
        np.testing.assert_allclose(runs[0][0], runs[1][0], rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
            runs[0][1], runs[1][1],
        )

    def test_per_ps_subset_divergence_then_agreement(self):
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        mesh = make_mesh({"ps": 4, "workers": 2})
        init_fn, step_fn, _ = byzsgd.make_trainer(
            module, loss, opt, "krum", num_workers=8, num_ps=4, fw=1, fps=1,
            attack="lie", ps_attack="reverse", mesh=mesh, subset=6,
            model_gar="median",  # krum needs n_ps >= 2*fps+3
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        _, losses = _run(step_fn, state, x, y, 10)
        assert np.isfinite(losses).all()

    def test_model_subset_fastest_q_semantics(self):
        """model_subset=q_m: each PS aggregates only its seeded fastest
        q_m = num_ps - fps peer models (get_models(num_ps - fps),
        ByzSGD/trainer.py:240-242) — so honest PS replicas genuinely hold
        DIFFERENT post-gather models (the broadcast-one-aggregate default
        leaves them identical), while training still converges."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        mesh = make_mesh({"ps": 4, "workers": 2})
        init_fn, step_fn, _ = byzsgd.make_trainer(
            module, loss, opt, "krum", num_workers=8, num_ps=4, fw=1,
            fps=1, attack="lie", mesh=mesh, subset=6,  # per-PS grad subsets
            model_gar="average", model_subset=3,  # num_ps - fps
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 20)
        assert losses[-1] < losses[0]
        params = jax.device_get(state.params)
        diverged = any(
            not np.allclose(np.asarray(leaf[i]), np.asarray(leaf[0]))
            for leaf in jax.tree.leaves(params)
            for i in range(1, leaf.shape[0])
        )
        assert diverged, (
            "per-PS model subsets must leave replicas with different "
            "post-gather models (each sampled its own fastest-q_m set)"
        )

    def test_model_subset_full_equals_none(self):
        """model_subset == num_ps never drops a model: bitwise-identical
        trajectories to the aggregate-all default."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        out = []
        for msub in (None, 4):
            mesh = make_mesh({"ps": 2, "workers": 4})
            init_fn, step_fn, _ = byzsgd.make_trainer(
                module, loss, opt, "krum", num_workers=8, num_ps=4, fw=2,
                fps=1, attack="lie", ps_attack="reverse", mesh=mesh,
                model_gar="median", model_subset=msub,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 3)
            out.append((losses, jax.device_get(state.params)))
        assert out[0][0] == out[1][0]
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            out[0][1], out[1][1],
        )

    @pytest.mark.parametrize("ps_attack", ["reverse", "random", None])
    def test_model_subset_subgram_matches_flat(self, ps_attack):
        """The model-plane sub-Gram fast path (one model Gram, per-PS
        (q_m, q_m) selections; deterministic PS attacks folded into the
        Gram remap) must pin the flat per-PS gather path exactly —
        tree_path=False forces the flat route."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        runs = []
        for tree_path in (True, False):
            mesh = make_mesh({"ps": 4, "workers": 2})
            init_fn, step_fn, _ = byzsgd.make_trainer(
                module, loss, opt, "krum", num_workers=8, num_ps=4, fw=2,
                fps=1, attack="lie", ps_attack=ps_attack, mesh=mesh,
                model_gar="average", model_subset=3, tree_path=tree_path,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 5)
            runs.append((losses, jax.device_get(state.params)))
        np.testing.assert_allclose(runs[0][0], runs[1][0], rtol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
            runs[0][1], runs[1][1],
        )


class TestLearn:
    def test_decentralized_convergence(self):
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(16, 8)
        init_fn, step_fn, eval_fn = learn.make_trainer(
            module, loss, opt, "median", num_nodes=16, f=3, attack="lie",
            model_attack="reverse", non_iid=True,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 25)
        assert losses[-1] < losses[0]
        # Model gossip leaves all honest replicas in agreement.
        params = jax.device_get(state.params)
        for leaf in jax.tree.leaves(params):
            np.testing.assert_allclose(leaf[1], leaf[0], rtol=1e-6)

    def test_node_momentum_beta0_matches_baseline(self):
        """beta = 0 degenerates to the raw-gradient LEARN pipeline."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        runs = []
        for wm in (None, 0.0):
            init_fn, step_fn, _ = learn.make_trainer(
                module, loss, opt, "median", num_nodes=8, f=1, attack="lie",
                non_iid=True, worker_momentum=wm,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 6)
            runs.append(losses)
        np.testing.assert_allclose(runs[0], runs[1], rtol=1e-5)

    def test_node_momentum_cclip_converges_under_lie(self):
        """Decentralized momentum + cclip (the ClippedGossip pairing)
        trains through the lie attack; the momentum stack is node-stacked
        and live."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, "cclip", num_nodes=8, f=2, attack="lie",
            worker_momentum=0.9,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 40)
        assert losses[-1] < losses[0] * 0.7
        for leaf in jax.tree.leaves(jax.device_get(state.worker_mom)):
            assert leaf.shape[0] == 8
            assert np.isfinite(leaf).all()
            assert np.abs(leaf).sum() > 0

    def test_wait_nf_agreement_rounds_reconcile(self):
        """Wait-n-f makes honest nodes provably disagree; the ceil(log2 t)
        agreement rounds reconcile them — under attack.

        The reference's LEARN never waits for all peers (get_gradients(i, n-f)
        trainer.py:249): per-node arrival subsets give every honest node a
        different aggregate, which is the entire reason avg_agree
        (trainer.py:208-222) exists. aggr_spread_* is the max pairwise L-inf
        distance between honest nodes' aggregates before/after the rounds.
        """
        module, loss, opt = _pima_setup()
        n, f = 8, 1
        x, y = _pima_batches(n, 16)
        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, "median", num_nodes=n, f=f, attack="lie",
            non_iid=True, subset=n - f, track_spread=True,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        pre, post = [], []
        for _ in range(8):
            state, m = step_fn(state, x, y)
            pre.append(float(m["aggr_spread_pre"]))
            post.append(float(m["aggr_spread_post"]))
        assert np.isfinite(pre).all() and np.isfinite(post).all()
        # Divergence is real: every step, some pair of honest nodes holds
        # different aggregates before the rounds.
        assert min(pre) > 0
        # Rounds never expand disagreement. A SINGLE median round cannot
        # contract the max-coordinate spread at all: each node's aggregate
        # coordinate is the 4th or 5th order statistic of the original 8
        # values (median of its 7-subset), and a median over values drawn
        # from that two-element set stays inside it. So demand strict
        # contraction only once ceil(log2 t) >= 2 (state.step >= 3), and
        # substantial contraction in aggregate.
        assert all(po <= pr for po, pr in zip(post, pre))
        assert all(po < pr for po, pr in zip(post[3:], pre[3:]))
        assert sum(post) < 0.75 * sum(pre)

    def test_wait_nf_full_subset_equals_none(self):
        """subset == num_nodes is full participation: bitwise-identical to
        the subset=None path (the permutation is sampled but unused)."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        kw = dict(num_nodes=8, f=2, attack="empire", non_iid=True)
        out = []
        for subset in (None, 8):
            init_fn, step_fn, _ = learn.make_trainer(
                module, loss, opt, "median", subset=subset, **kw
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, 3)
            out.append((losses, jax.device_get(state.params)))
        assert out[0][0] == out[1][0]
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            out[0][1], out[1][1],
        )

    def test_iid_no_gossip_rounds(self):
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, "krum", num_nodes=8, f=2, attack="empire",
            non_iid=False, model_gossip=True,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        _, losses = _run(step_fn, state, x, y, 15)
        assert losses[-1] < losses[0] * 1.5

    @pytest.mark.parametrize("gar,attack,f,subset,non_iid,model_attack", [
        # Folded deterministic attacks, full participation: every exchange
        # (phase 2, agreement rounds, gossip incl. the folded model-plane
        # reverse) runs tree-mode.
        ("krum", "lie", 2, None, True, "reverse"),
        ("median", "lie", 2, None, True, "crash"),
        ("cclip", "lie", 2, None, True, None),       # stateful center
        ("bulyan", "lie", 1, None, False, None),     # fold_aggregate form
        # Per-node wait-n-f subsets composed onto the sub-Gram (the
        # multi-observer fold) — Gram-form rules only.
        ("krum", "reverse", 2, 7, True, None),
        ("krum", "lie", 2, 7, False, "reverse"),     # extra-row fold + subset
        ("average", "empire", 2, 7, True, None),
        # brute: model_gossip off — its min-diameter argmin over the
        # CLUSTERED gossiped models (all within one step of each other)
        # near-ties across candidate subsets, so tree/flat Gram ulp
        # differences legitimately flip the exact subset; the gradient
        # plane (well-separated rows) pins the sub-Gram composition.
        ("brute", "crash", 2, 7, False, "nogossip"),
        # subset == n never selects rows; genuinely compares tree vs flat.
        ("krum", "reverse", 2, 8, True, None),
        # No attack at all: plain tree dispatch vs flat.
        ("krum", None, 2, None, True, None),
    ])
    def test_learn_tree_path_matches_flat_path(self, gar, attack, f, subset,
                                               non_iid, model_attack):
        """The LEARN tree/fold fast path must reproduce the flat path's
        training trajectory (same key => identical wait-n-f subsets and
        selections) — the decentralized mirror of aggregathor's
        tree-vs-flat matrix (tests above / tests/test_fold.py).

        True-subset rows run fewer steps at a slightly looser tolerance:
        the sub-Gram composition's weight-scatter sums rows in STACK order
        while the flat path sums the subset-PERMUTED rows, and the folded
        reverse scales the Gram where the flat path scales rows before the
        matmul — identical selections, pure f32 reassociation (verified at
        the single-exchange level to 1e-5 across every node in
        tests/test_fold.py's multi-observer suite) — but LEARN amplifies
        that last-ulp noise through 2-4 aggregations per step x the x100
        attack dynamics, chaotically past any fixed tolerance by step ~4.
        A wrong subset/key derivation diverges at step 1 by orders of
        magnitude more than the tolerance.
        """
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        waiting = subset is not None and subset < 8
        steps = 2 if waiting else 5
        runs = []
        gossip = model_attack != "nogossip"
        for tree_path in (True, False):
            init_fn, step_fn, _ = learn.make_trainer(
                module, loss, opt, gar, num_nodes=8, f=f, attack=attack,
                model_attack=model_attack if gossip else None,
                model_gossip=gossip, subset=subset, non_iid=non_iid,
                tree_path=tree_path,
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            state, losses = _run(step_fn, state, x, y, steps)
            runs.append((losses, jax.device_get(state.params)))
        np.testing.assert_allclose(
            runs[0][0], runs[1][0], rtol=1e-4 if waiting else 1e-5
        )
        rtol, atol = (1e-3, 1e-5) if waiting else (1e-4, 1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=rtol, atol=atol
            ),
            runs[0][1], runs[1][1],
        )

    def test_learn_cclip_single_median_init(self, monkeypatch):
        """LEARN's cclip carries a per-node stateful center: across a
        multi-step run the robust coordinate-median init exists ONCE in
        the traced step program (the step-0 branch of the lax.cond) — the
        agreement rounds re-center on the current aggregate and the gossip
        on the node's own model, so no other median pass is ever traced.
        The old per-call-init dispatch traced one median per exchange
        (phase 2 + each agreement round + gossip >= 3)."""
        from garfield_tpu import ops

        calls = {"n": 0}
        real = ops.coordinate_median

        def counting(*args, **kwargs):
            calls["n"] += 1
            return real(*args, **kwargs)

        monkeypatch.setattr(ops, "coordinate_median", counting)
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, "cclip", num_nodes=8, f=2, attack="lie",
            non_iid=True,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 4)
        assert all(np.isfinite(l) for l in losses)
        assert calls["n"] == 1, (
            f"expected exactly one coordinate-median init in the traced "
            f"LEARN step (the step-0 cond branch), saw {calls['n']}"
        )
        # The carried state is live: nonzero after a step, node-stacked.
        for leaf in jax.tree.leaves(jax.device_get(state.gar_state)):
            assert leaf.shape[0] == 8
            assert np.isfinite(leaf).all()
            assert np.abs(leaf).sum() > 0

    def test_learn_cclip_momentum_converges_on_fast_path(self):
        """The headline decentralized defense config (cclip + worker
        momentum) on the default fast path: trains through the lie attack
        with the carried center."""
        module, loss, opt = _pima_setup()
        x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, "cclip", num_nodes=8, f=2, attack="lie",
            worker_momentum=0.9, non_iid=True,
        )
        state = init_fn(jax.random.PRNGKey(0), x[0])
        state, losses = _run(step_fn, state, x, y, 40)
        assert losses[-1] < losses[0] * 0.7

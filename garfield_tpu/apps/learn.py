"""LEARN: fully decentralized Byzantine-resilient collaborative learning.

Counterpart of ``pytorch_impl/applications/LEARN/trainer.py`` (P19): every
node is Worker + Server (:224-231); per step each node aggregates everyone's
gradients, optionally runs ceil(log2 t) extra agreement rounds for non-iid
data (:208-222, :251-252), then gossips and GAR-aggregates models (:255-257).
``--num_workers`` is the node count (the reference demo calls it n).
``--subset`` enables the wait-n-f path: the reference's LEARN always waits
for only the n - f fastest peers (trainer.py:249, :255); pass
``--subset $((n - f))`` for exact protocol parity, or leave unset for full
participation.

  python -m garfield_tpu.apps.learn --dataset pima --model pimanet \\
      --loss bce --num_workers 8 --fw 1 --gar median \\
      --optimizer rmsprop --opt_args '{"lr":"0.001","momentum":"0.9","weight_decay":"0.0005"}'
"""

import sys

from ..parallel import learn
from . import common


def main(argv=None):
    parser = common.base_parser(
        "LEARN implementation using garfield-tpu", default_loss="bce"
    )
    parser.add_argument(
        "--non_iid", action="store_true",
        help="Enable the ceil(log2 t) agreement rounds "
             "(LEARN/trainer.py:251-252).",
    )
    parser.add_argument(
        "--model_attack", type=str, default=None,
        help="Byzantine model-gossip attack: random, reverse, drop; "
             "lie, empire (collusion over the gossiped stack, DESIGN.md "
             "§17); adaptive-lie, adaptive-empire (magnitude bisected "
             "against the gossip quorum's admission).",
    )
    parser.add_argument(
        "--no_model_gossip", action="store_true",
        help="Disable the model gossip phase (LEARN/trainer.py:255-257).",
    )
    parser.add_argument(
        "--model_attack_params", type=__import__("json").loads, default={},
        help="Model-attack parameters as JSON.",
    )
    parser.add_argument(
        "--model_gar", type=str, default=None,
        help="GAR for the model gossip (default: same as --gar).",
    )
    parser.add_argument(
        "--cluster", type=str, default=None,
        help='Cluster config JSON with a "node" host list: run as ONE peer '
             "of the decentralized multi-process LEARN deployment over "
             "PeerExchange (true per-node wait-n-f; LEARN/trainer.py's "
             "run_exp.sh fan-out shape).",
    )
    parser.add_argument(
        "--task", type=str, default=None,
        help='Role override for --cluster, "node:K".',
    )
    parser.add_argument(
        "--cluster_timeout_ms", type=int, default=60_000,
        help="Per-phase collect timeout in cluster mode.",
    )
    args = parser.parse_args(argv)
    if args.cluster:
        from . import cluster

        args.num_workers = None  # node count comes from the config
        return cluster.run(args)
    if args.model_gar is not None:
        # The on-mesh LEARN uses ONE rule for gradients and gossip (the
        # reference does too, LEARN/trainer.py); a separate model rule
        # exists only in the cluster deployment.
        raise SystemExit("--model_gar requires --cluster (node deployment)")
    assert args.fw * 2 < args.num_workers or args.fw == 0
    make_trainer_kwargs = dict(
        num_nodes=args.num_workers,
        f=args.fw,
        attack=args.attack,
        attack_params=args.attack_params,
        model_attack=args.model_attack,
        model_attack_params=args.model_attack_params,
        non_iid=args.non_iid,
        model_gossip=not args.no_model_gossip,
        subset=args.subset,
    )
    from ..utils import rounds

    policy = rounds.resolve(args)
    if policy is not None:
        # On-mesh --async: the seeded in-graph emulation of the host
        # plane's bounded-staleness gossip (parallel/learn ``staleness=``;
        # DESIGN.md §15) — per-phase discount weights under the same law
        # and flags as the cluster deployment (which runs the REAL
        # per-plane protocol through apps/cluster._run_learn above).
        make_trainer_kwargs["staleness"] = {
            "max_staleness": policy.max_staleness,
            "decay": policy.decay,
        }
    return common.train(
        args,
        topology=learn,
        make_trainer_kwargs=make_trainer_kwargs,
        num_slots=args.num_workers,
        tag="learn",
    )


if __name__ == "__main__":
    main(sys.argv[1:])

"""Multi-process federated deployment e2e (slow, conftest._RUN_LAST).

The real thing at small scale: shard PS planes over PeerExchange with
shard-stamped wire frames (cross-shard arrivals attributed to their
sender), the fed_bench shard-process scaling cells, and the autoscaled
jax-free client fleet driving rounds against a rate target.
"""

import json
import subprocess
import sys
import time

import numpy as np
import pytest

from garfield_tpu import federated as fed
from garfield_tpu.apps.benchmarks import fed_bench
from garfield_tpu.utils import wire
from garfield_tpu.utils.exchange import PeerExchange

pytestmark = pytest.mark.slow


def _hosts(k):
    return [f"127.0.0.1:{p}" for p in fed_bench._ports(k)]


class TestShardWirePlane:
    def test_cross_shard_frame_is_attributable_ban_evidence(self):
        """A client that stamps its frame for the WRONG shard (or
        garbles it) is excluded with the evidence stored against ITS
        slot — the shard plane's twin of the cluster quorum ban."""
        hosts = _hosts(3)  # rank 0 = shard PS, ranks 1..2 = clients
        spec = fed.plan_shards(40, 2)
        ps = PeerExchange(0, hosts, planes=2)
        c1 = PeerExchange(1, hosts, planes=2)
        c2 = PeerExchange(2, hosts, planes=2)
        try:
            server = fed.ShardServer(1, spec, bucket_gar="average")
            server.begin_round(0, 2, 0)
            wait = ps.collect_begin(
                5, 2, peers=[1, 2], timeout_ms=30_000,
                transform=server.wire_transform, plane=1,
            )
            rows = np.ones((2, 40), np.float32)
            good = spec.slice_rows(rows, 1)[0]
            # Client 1: honest shard-1 frame. Client 2: frame stamped
            # for shard 0 — cross-shard delivery.
            c1.publish(5, wire.encode(good, plane=1), to=[0], plane=1)
            c2.publish(
                5, wire.encode(spec.slice_rows(rows, 0)[0], plane=0),
                to=[0], plane=1,
            )
            got = wait()
            assert not isinstance(got[1], Exception)
            assert isinstance(got[2], wire.WireError)
            assert "cross-shard" in str(got[2])
        finally:
            for ex in (ps, c1, c2):
                ex.close()

    def test_two_shard_round_over_real_wire(self):
        """Both shards of one round over real sockets: per-shard
        collects on per-shard planes, reassembled model bitwise equal
        to the in-process engine over the same rows."""
        hosts = _hosts(2)
        d, n = 64, 4
        spec = fed.plan_shards(d, 2)
        ps = PeerExchange(0, hosts, planes=2)
        cl = PeerExchange(1, hosts, planes=2)
        try:
            rows = np.random.default_rng(3).normal(
                size=(n, d)).astype(np.float32)
            servers = [
                fed.ShardServer(s, spec, bucket_gar="average")
                for s in range(2)
            ]
            for sv in servers:
                sv.begin_round(0, n, 0)
            waits = [
                ps.collect_begin(
                    1, 1, peers=[1], timeout_ms=30_000,
                    transform=sv.wire_transform, plane=sv.shard,
                )
                for sv in servers
            ]
            for s in range(2):
                cl.publish(
                    1,
                    wire.encode(spec.slice_rows(rows, s).ravel(),
                                plane=s),
                    to=[0], plane=s,
                )
            for w in waits:
                got = w()
                assert not any(
                    isinstance(v, Exception) for v in got.values()
                )
            agg = fed.reassemble(
                spec, [sv.finish_round() for sv in servers]
            )
            np.testing.assert_allclose(
                agg, rows.mean(axis=0), rtol=1e-5, atol=1e-6
            )
        finally:
            ps.close()
            cl.close()


class TestFedBenchEndToEnd:
    def test_scaling_cells_spawn_shard_processes(self, tmp_path):
        """fed_bench's scaling mode at toy scale: one OS process per
        (cell, shard), S=1 vs S=2 rows with sane fields + the schema-
        valid JSONL twin."""
        out = tmp_path / "FED.json"
        rows = fed_bench.main([
            "--n", "2048", "--population", "4096", "--d", "1000",
            "--shards_list", "1", "2", "--scaling_gars", "median",
            "--rounds", "1",
            "--bitwise_n", "256", "--bitwise_d", "500",
            "--skip_fleet", "--json", str(out),
        ])
        by_check = {}
        for r in rows:
            by_check.setdefault(r["check"], []).append(r)
        assert by_check["s1_bitwise"][0]["s1_bitwise_equal"] is True
        scaling = {r["shards"]: r for r in by_check["scaling"]}
        assert set(scaling) == {1, 2}
        assert len(scaling[2]["per_shard_s"]) == 2
        assert scaling[2]["round_s"] <= scaling[1]["round_s"] * 1.05
        from garfield_tpu.telemetry import exporters

        assert exporters.validate_jsonl(str(tmp_path / "FED.jsonl")) == 3
        dumped = json.loads(out.read_text())
        assert len(dumped) == 3

    def test_autoscaled_fleet_reaches_target(self):
        """The fleet scenario end to end: jax-free client drivers over
        real sockets, the autoscale controller spawning toward a rate
        target the initial fleet cannot meet."""
        row = fed_bench.main([
            "--skip_scaling", "--skip_bitwise",
            "--fleet_rounds", "40", "--fleet_cohort", "32",
            "--fleet_d", "1000", "--fleet_delay_ms", "8",
        ])[0]
        assert row["check"] == "fleet"
        assert row["spawns"] >= 1, row
        assert row["active_final"] > row["active_initial"]
        assert row["recovered_rate"] > row["pre_rate"], row


class TestFleetProcessLifecycle:
    def test_client_fleet_spawn_retire_reaps_processes(self):
        sleeper = [sys.executable, "-c", "import time; time.sleep(60)"]
        from garfield_tpu.utils import autoscale as autoscale_lib

        fleet = fed.ClientFleet(
            lambda k: sleeper,
            autoscale_lib.AutoscaleConfig(
                target_rate=1.0, min_workers=1, max_workers=3,
                window=2, cooldown=0,
            ),
        )
        try:
            fleet.spawn_initial(2)
            assert fleet.active() == [0, 1]
            idx = fleet.retire()
            assert idx == 1 and fleet.active() == [0]
            # retire() joins: the process is actually gone, not dying.
            assert fleet._procs[1].poll() is not None
        finally:
            fleet.stop_all()
        assert fleet.active() == []

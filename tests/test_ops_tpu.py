"""On-device Pallas kernel equality (opt-in: real TPU only).

The interpret-mode tests (test_ops.py) verify the kernels against the jnp
spec on CPU; this file runs the SAME equality checks through real Mosaic
lowering — bf16 16-sublane tiling with n < 16 rows, the (n, tile)
BlockSpec, NaN ordering — so a lowering divergence from the spec cannot
ship unnoticed (ADVICE r1). Skipped automatically off-TPU; the verify
drive runs it on the real chip each round:

    cd /root/repo && python -m pytest tests/test_ops_tpu.py -q
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

if jax.default_backend() != "tpu":
    pytest.skip("real-TPU kernel checks; CPU runs use interpret mode",
                allow_module_level=True)

from garfield_tpu.ops import coordinate


def _rand(n, d, seed, nan_frac=0.0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(dtype)
    if nan_frac:
        mask = rng.random((n, d)) < nan_frac
        mask[0] = False
        x = np.where(mask, np.nan, x).astype(dtype)
    return x


@pytest.mark.parametrize("n,d,dtype,nan_frac", [
    (8, 4096, np.float32, 0.0),
    (9, 1031, np.float32, 0.15),   # odd n, non-tile-multiple d, NaNs
    (7, 2048, jnp.bfloat16, 0.0),  # n < 16 rows under bf16 (2,1) tiling
    (32, 1024, np.float32, 0.0),   # MAX_SORT_N boundary
])
def test_median_on_device(n, d, dtype, nan_frac):
    x = _rand(n, d, seed=n * 7 + d, nan_frac=nan_frac, dtype=dtype)
    got = np.asarray(coordinate.coordinate_median(jnp.asarray(x)), np.float32)
    want = np.asarray(
        coordinate.coordinate_median_reference(jnp.asarray(x)), np.float32
    )
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("n,f", [(9, 2), (16, 5)])
def test_tmean_on_device(n, f):
    x = _rand(n, 4096, seed=n, nan_frac=0.05)
    got = np.asarray(coordinate.trimmed_mean(jnp.asarray(x), f))
    want = np.asarray(coordinate.trimmed_mean_reference(jnp.asarray(x), f))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("s,beta,dtype", [
    (8, 4, np.float32),
    (11, 5, np.float32),
    (7, 3, jnp.bfloat16),
])
def test_avgmed_on_device(s, beta, dtype):
    x = _rand(s, 4096, seed=s * 3 + beta, dtype=dtype)
    got = np.asarray(
        coordinate.averaged_median_mean(jnp.asarray(x), beta), np.float32
    )
    want = np.asarray(
        coordinate.averaged_median_mean_reference(jnp.asarray(x), beta),
        np.float32,
    )
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-6
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol)


def test_remap_kernel_on_device():
    """Folded-attack remap (row_map/row_scale) inside the Mosaic-lowered
    kernel: duplicated fake row + scaled row vs materialized remap."""
    ext = _rand(9, 2048, seed=21, dtype=jnp.bfloat16)
    row_map = np.array([0, 1, 2, 3, 4, 5, 8, 8])
    row_scale = np.array([1.0] * 5 + [-100.0, 1.0, 1.0])
    eff = (np.asarray(ext, np.float32)[row_map]
           * row_scale[:, None]).astype(np.float32)
    got = np.asarray(coordinate.coordinate_median(
        jnp.asarray(ext), row_map=row_map, row_scale=row_scale
    ), np.float32)
    want = np.asarray(coordinate.coordinate_median_reference(
        jnp.asarray(eff, jnp.float32)
    ), np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)

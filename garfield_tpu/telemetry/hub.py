"""Host-side metrics aggregation: ring buffer, suspicion scores, events.

``MetricsHub`` is the single host-side sink of the telemetry plane: the
training loops feed it per-step taps (``record_step``), the cluster
driver and ``utils.exchange`` feed it liveness / wait-n-f events through
the process-global hook (``install`` + ``emit_event`` — a no-op when no
hub is installed, so instrumented code paths cost nothing un-telemetered).

The derived audit signal is the per-rank **suspicion score**: the
cumulative exclusion frequency under the active GAR,

    suspicion[i] = sum_steps (observed[i] - selected[i]) /
                   sum_steps  observed[i]

i.e. "of the quorums that contained rank i, what fraction of influence
did the rule refuse it". Byzantine ranks that a robust rule keeps
rejecting converge to suspicion ~1 while honest ranks stay near 0 — the
audit that makes Byzantine ranks visible without ground truth (asserted
end-to-end in tests/test_telemetry.py under the lie attack).
"""

import collections
import threading
import time

import numpy as np

from .exporters import make_record

__all__ = ["MetricsHub", "install", "uninstall", "current", "emit_event",
           "emit_span"]

# Span-duration histogram buckets (seconds) for the Prometheus
# ``garfield_phase_seconds`` exposition — log-spaced from wire-decode
# scale (0.1 ms) to a straggler-dominated quorum wait (10 s).
PHASE_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
)


class MetricsHub:
    """Ring-buffered aggregation of taps, timings and liveness events.

    Thread-safe: the cluster driver's exchange threads emit events
    concurrently with the training loop's ``record_step``.
    """

    def __init__(self, num_ranks=None, capacity=2048, meta=None, sink=None,
                 suspicion_halflife=None):
        self.num_ranks = num_ranks
        self.meta = dict(meta or {})
        # Windowed suspicion (schema v7, DESIGN.md §16): the cumulative
        # exclusion frequency never decays, so a ROTATED Byzantine cohort
        # launders it for free — each member attacks briefly, then sits
        # honest while its denominator grows. With ``suspicion_halflife``
        # (in observed steps) the hub additionally keeps exponentially
        # decayed observed/excluded twins: suspicion_decayed() weights
        # the recent window, so a rank that attacked 50 rounds ago and a
        # rank attacking NOW stop looking identical. None keeps only the
        # cumulative score (v1 behavior).
        self._halflife = (
            float(suspicion_halflife) if suspicion_halflife else None
        )
        if self._halflife is not None and self._halflife <= 0.0:
            raise ValueError(
                f"suspicion_halflife must be > 0, got {suspicion_halflife}"
            )
        self._susp_decay = (
            0.5 ** (1.0 / self._halflife) if self._halflife else 1.0
        )
        self._observed_d = None
        self._excluded_d = None
        # Closed-loop defense accounting (schema v7): per-round
        # suspicion-weight digests + escalation state, folded from the
        # PS's ``defense_weights``/``defense_escalate`` events and the
        # attacker-side ``attack_adapt`` stream.
        self._defense = {
            "rounds": 0, "w_sum": 0.0, "w_min": None,
            "escalations": 0, "deescalations": 0, "level": None,
            "rule": None,
        }
        self._attack_adapt = {"events": 0, "last_mag": None}
        # Data-plane defense accounting (schema v9, DESIGN.md §18):
        # folded from ``data_defense`` events — per-rank spectral outlier
        # scores (the garfield_dataplane_outlier_score gauge), flag and
        # weight extremes for the summary digest.
        self._dataplane = {
            "rounds": 0, "flagged": 0, "max_score": None, "min_w": None,
            "scores": {},
        }
        # Federated round accounting (schema v10, DESIGN.md §19): folded
        # from the round engine's ``fed_round``/``cohort`` events.
        # Client suspicion is keyed by the STABLE GLOBAL client id, not
        # the per-round cohort index: under partial participation a
        # cohort index means a different client every round, so indexing
        # suspicion by it hands every resampled Byzantine client a fresh
        # ledger — the sampling-scale twin of the rotation laundering
        # the halflife window closes (pinned by the rotating-attacker
        # regression in tests/test_federated.py). The map is sparse
        # (only sampled-and-audited clients appear) with lazily applied
        # decay per cohort event, so a million-client population costs
        # only its audited cohorts.
        self._clients = {}  # cid -> [obs_d, exc_d, last_cohort_event]
        self._cohort_events = 0
        self._fed = {
            "rounds": 0, "shards": None, "last_cohort": None,
            "budget_exceeded": 0, "round_s_sum": 0.0, "f_budget": None,
        }
        # Targeted-attack eval accounting (schema v8, DESIGN.md §17):
        # folded from ``targeted_eval`` events — the per-class digest the
        # divergence-blind suspicion plane cannot produce.
        self._targeted = {
            "events": 0, "last_confusion": None, "last_asr": None,
        }
        # Optional streaming sink (a JsonlExporter): every record is
        # written as it is recorded — crash-safe for the cluster roles,
        # whose exchange threads emit events the training loop never sees.
        self._sink = sink
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=capacity)
        self._steps = 0
        self._events = 0
        self._last_loss = None
        self._last_tau = 0.0
        self._last_clip_frac = 0.0
        self._step_times = []
        self._observed = None
        self._excluded = None
        self._selected_hist = collections.deque(maxlen=120)
        # Wire-plane accounting (DESIGN.md §11): folded from the cluster
        # roles' per-step "wire" events and the exchange's publisher-side
        # "send_queue_drop" events, exposed by both exporters. Schema v6
        # adds the per-PLANE byte breakdown (wire events' ``planes``
        # sub-object) behind the plane-labelled Prometheus counters.
        self._wire = {
            "bytes_out": 0, "bytes_in": 0, "frames_in": 0,
            "encode_s": 0.0, "decode_s": 0.0, "send_queue_drops": 0,
        }
        self._wire_planes = {}  # plane -> {"bytes_out": n, "bytes_in": n}
        # Schema v11 (round 18, the compressed wire): per-SCHEME byte
        # breakdown (wire events' ``schemes`` sub-object) behind the
        # garfield_wire_bytes_total{scheme=} Prometheus counters.
        self._wire_schemes = {}  # scheme -> {"bytes_out": n, "bytes_in": n}
        # Schema v15 (round 22, batched wire ingest — DESIGN.md §24):
        # folded from ``ingest_batch`` events — bulk push_frames calls,
        # frames/rejects/seconds split by whether the vectorized decode
        # path ran (garfield_ingest_batch_seconds{batched=}).
        self._ingest_batch = {
            "calls": 0, "frames": 0, "rejected": 0,
            "batched_s": 0.0, "fallback_s": 0.0,
        }
        # Elastic-membership accounting (schema v6, DESIGN.md §15):
        # folded from the PS autoscaler's "autoscale" events — running
        # active-worker count (the garfield_active_workers gauge) and
        # spawn/retire totals for the run summary.
        self._autoscale = {"spawns": 0, "retires": 0, "active": None}
        # Bounded-staleness accounting (schema v4, DESIGN.md §14): the
        # async PS emits one "staleness" event per round with the
        # quorum's per-rank staleness + discount weights; folded into a
        # rounds histogram (garfield_staleness_rounds) and — alongside
        # the exclusion taps — into the per-rank suspicion score (a rank
        # whose influence the discount keeps refusing is suspect the
        # same way a rank the rule keeps excluding is).
        self._staleness = {
            "count": 0, "sum": 0, "max": 0,
            "hist": collections.Counter(),
        }
        # Span accounting (schema v5, trace.py): per-phase duration
        # digests for the exporters (Prometheus histogram, summary
        # ``phases``) and a small per-round phase breakdown for the
        # demo's /status panel. The raw spans stream to the sink like
        # every other record; the hub keeps only bounded aggregates.
        self._spans = 0
        self._phase = {}            # phase -> {count,sum,buckets,durs}
        self._round_phases = collections.OrderedDict()  # step -> {phase: s}

    # --- feeding -----------------------------------------------------------

    def _ensure_ranks(self, n):
        if self._observed is None:
            self.num_ranks = n
            self._observed = np.zeros(n, np.float64)
            self._excluded = np.zeros(n, np.float64)
            self._observed_d = np.zeros(n, np.float64)
            self._excluded_d = np.zeros(n, np.float64)

    def _fold_exclusion(self, obs_inc, exc_inc):
        """One exclusion observation into BOTH suspicion accumulators:
        the cumulative arrays, and — with ``suspicion_halflife`` — the
        exponentially decayed window twins (every feeder: taps, async
        staleness deficits, hierarchical per-client audits)."""
        self._observed += obs_inc
        self._excluded += exc_inc
        if self._halflife is not None:
            self._observed_d *= self._susp_decay
            self._excluded_d *= self._susp_decay
            self._observed_d += obs_inc
            self._excluded_d += exc_inc

    def record_step(self, step, *, loss=None, tap=None, step_time_s=None,
                    extra=None):
        """Fold one training step into the hub; returns the JSONL record."""
        tap_host = None
        if tap is not None:
            tap_host = {
                "observed": np.asarray(tap["observed"], np.float64),
                "selected": np.asarray(tap["selected"], np.float64),
                "score": np.asarray(tap["score"], np.float64),
                "tau": float(np.asarray(tap["tau"])),
                "clip_frac": float(np.asarray(tap["clip_frac"])),
            }
        with self._lock:
            self._steps += 1
            if loss is not None:
                self._last_loss = float(loss)
            if step_time_s is not None:
                self._step_times.append(float(step_time_s))
            if tap_host is not None:
                obs, sel = tap_host["observed"], tap_host["selected"]
                self._ensure_ranks(obs.size)
                # A rank's per-step exclusion is the influence the rule
                # refused it, bounded by how much of it was observed at
                # all (multi-observer bundles report fractions of both).
                self._fold_exclusion(
                    obs, np.maximum(obs - np.minimum(sel, obs), 0.0)
                )
                self._last_tau = tap_host["tau"]
                self._last_clip_frac = tap_host["clip_frac"]
                self._selected_hist.append(
                    (int(step), np.round(sel, 5).tolist())
                )
            rec = make_record(
                "step",
                step=int(step),
                loss=None if loss is None else float(loss),
                step_time_s=(
                    None if step_time_s is None else float(step_time_s)
                ),
                tap=None if tap_host is None else {
                    "observed": np.round(tap_host["observed"], 6).tolist(),
                    "selected": np.round(tap_host["selected"], 6).tolist(),
                    "score": np.round(tap_host["score"], 6).tolist(),
                    "tau": tap_host["tau"],
                    "clip_frac": tap_host["clip_frac"],
                },
                **(extra or {}),
            )
            self._ring.append(rec)
            self._drain(rec)
            return rec

    def record_event(self, kind, **fields):
        """Fold one liveness/exchange event (e.g. ``exchange_wait``,
        ``quorum_exclusion``, ``plane_drop``); returns the record."""
        rec = make_record("event", event=str(kind), t=time.time(), **fields)
        with self._lock:
            self._events += 1
            if kind == "wire":
                for key in ("bytes_out", "bytes_in", "frames_in"):
                    self._wire[key] += int(fields.get(key, 0) or 0)
                for key in ("encode_s", "decode_s"):
                    self._wire[key] += float(fields.get(key, 0.0) or 0.0)
                for p, d in (fields.get("planes") or {}).items():
                    acc = self._wire_planes.setdefault(
                        str(p), {"bytes_out": 0, "bytes_in": 0}
                    )
                    acc["bytes_out"] += int(d.get("bytes_out", 0) or 0)
                    acc["bytes_in"] += int(d.get("bytes_in", 0) or 0)
                for s, d in (fields.get("schemes") or {}).items():
                    acc = self._wire_schemes.setdefault(
                        str(s), {"bytes_out": 0, "bytes_in": 0}
                    )
                    acc["bytes_out"] += int(d.get("bytes_out", 0) or 0)
                    acc["bytes_in"] += int(d.get("bytes_in", 0) or 0)
            elif kind == "send_queue_drop":
                self._wire["send_queue_drops"] += 1
            elif kind == "ingest_batch":
                ib = self._ingest_batch
                ib["calls"] += 1
                ib["frames"] += int(fields.get("frames", 0) or 0)
                ib["rejected"] += int(fields.get("rejected", 0) or 0)
                key = "batched_s" if fields.get("batched") else "fallback_s"
                ib[key] += float(fields.get("dur_s", 0.0) or 0.0)
            elif kind == "autoscale":
                a = self._autoscale
                if fields.get("action") == "spawn":
                    a["spawns"] += 1
                elif fields.get("action") == "retire":
                    a["retires"] += 1
                if fields.get("active") is not None:
                    a["active"] = int(fields["active"])
            elif kind == "staleness":
                # Per-round async-quorum audit (apps/cluster.py): fold
                # the discount deficit (1 - w) into the same exclusion-
                # frequency suspicion the taps feed — each quorum rank
                # was observed once and had (1 - w) of its influence
                # refused by the staleness discount.
                ranks = np.asarray(fields.get("ranks", ()), np.int64)
                taus = np.asarray(fields.get("staleness", ()), np.int64)
                ws = np.asarray(fields.get("weights", ()), np.float64)
                if ranks.size and taus.size == ranks.size:
                    st = self._staleness
                    st["count"] += int(ranks.size)
                    st["sum"] += int(taus.sum())
                    st["max"] = max(st["max"], int(taus.max()))
                    for t in taus.tolist():
                        st["hist"][int(t)] += 1
                    if self.num_ranks and ranks.max() < self.num_ranks:
                        self._ensure_ranks(self.num_ranks)
                        if ws.size == ranks.size:
                            obs_inc = np.zeros_like(self._observed)
                            exc_inc = np.zeros_like(self._excluded)
                            np.add.at(obs_inc, ranks, 1.0)
                            np.add.at(
                                exc_inc, ranks,
                                np.clip(1.0 - ws, 0.0, 1.0),
                            )
                            self._fold_exclusion(obs_inc, exc_inc)
            elif kind == "defense_weights":
                # Closed-loop defense (schema v7): one per-round
                # suspicion-weight vector over the quorum — digested to
                # rounds/min/mean for the summary (the raw event streams
                # to the sink like everything else).
                ws = np.asarray(fields.get("weights", ()), np.float64)
                if ws.size:
                    d = self._defense
                    d["rounds"] += 1
                    d["w_sum"] += float(ws.mean())
                    wmin = float(ws.min())
                    d["w_min"] = (
                        wmin if d["w_min"] is None
                        else min(d["w_min"], wmin)
                    )
            elif kind == "defense_escalate":
                d = self._defense
                if fields.get("direction") == "deescalate":
                    d["deescalations"] += 1
                else:
                    d["escalations"] += 1
                if fields.get("level") is not None:
                    d["level"] = int(fields["level"])
                if fields.get("rule") is not None:
                    d["rule"] = str(fields["rule"])
            elif kind == "data_defense":
                # v9: one round of the data-plane detectors (aggregators/
                # dataplane.py) — digest extremes + the last per-rank
                # scores for the Prometheus gauge; raw events stream to
                # the sink like everything else.
                d = self._dataplane
                d["rounds"] += 1
                sc = list(fields.get("scores") or ())
                fl = list(fields.get("flags") or ())
                ws = list(fields.get("weights") or ())
                d["flagged"] += int(sum(1 for x in fl if x))
                if sc:
                    m = float(max(sc))
                    d["max_score"] = (
                        m if d["max_score"] is None
                        else max(d["max_score"], m)
                    )
                    ranks = fields.get("ranks")
                    if ranks is None:
                        ranks = range(len(sc))
                    for r, s in zip(ranks, sc):
                        d["scores"][int(r)] = float(s)
                if ws:
                    wmin = float(min(ws))
                    d["min_w"] = (
                        wmin if d["min_w"] is None
                        else min(d["min_w"], wmin)
                    )
            elif kind in ("attack_adapt", "ps_attack_adapt"):
                # v8: the model-plane twin folds into the same digest —
                # one adaptive adversary per run is the deployed shape,
                # and the raw plane-tagged events stream to the sink.
                a = self._attack_adapt
                a["events"] += 1
                if fields.get("magnitude") is not None:
                    a["last_mag"] = float(fields["magnitude"])
            elif kind == "targeted_eval":
                t = self._targeted
                t["events"] += 1
                if fields.get("confusion") is not None:
                    t["last_confusion"] = float(fields["confusion"])
                if fields.get("asr") is not None:
                    t["last_asr"] = float(fields["asr"])
            elif kind == "fed_round":
                # v10: one federated round (federated/engine.py) —
                # digest counters for the summary + Prometheus.
                fd = self._fed
                fd["rounds"] += 1
                if fields.get("shards") is not None:
                    fd["shards"] = int(fields["shards"])
                if fields.get("cohort") is not None:
                    fd["last_cohort"] = int(fields["cohort"])
                if fields.get("f_budget") is not None:
                    fd["f_budget"] = int(fields["f_budget"])
                if fields.get("budget_exceeded"):
                    fd["budget_exceeded"] += 1
                if fields.get("round_s") is not None:
                    fd["round_s_sum"] += float(fields["round_s"])
            elif kind == "cohort":
                # v10: one audited cohort — per-CLIENT observed/selected
                # keyed by stable global ids (see __init__'s comment on
                # why NOT cohort index). Lazy decay: a client's twins
                # decay by decay**(events since it was last sampled)
                # before the new observation folds in, so untouched
                # entries cost nothing per event.
                ids = fields.get("client_ids") or ()
                sel = fields.get("selected")
                if ids:
                    self._cohort_events += 1
                    now = self._cohort_events
                    if sel is None or len(sel) != len(ids):
                        sel = [1.0] * len(ids)
                    for cid, s in zip(ids, sel):
                        ent = self._clients.get(int(cid))
                        if ent is None:
                            ent = self._clients[int(cid)] = [0.0, 0.0, now]
                        elif self._halflife is not None:
                            k = now - ent[2]
                            if k:
                                dk = self._susp_decay ** k
                                ent[0] *= dk
                                ent[1] *= dk
                            ent[2] = now
                        else:
                            ent[2] = now
                        ent[0] += 1.0
                        ent[1] += max(0.0, 1.0 - float(s))
            elif kind == "hier_exclusion":
                # The hierarchical reducer's per-client audit (aggregators/
                # hierarchy.py): observed/selected weight vectors over the
                # n CLIENTS, folded into the same exclusion-frequency
                # suspicion the in-graph taps feed — bucket-level
                # exclusions (and whole excluded bucket summaries) surface
                # per client without ground truth.
                obs = np.asarray(fields.get("observed", ()), np.float64)
                sel = np.asarray(fields.get("selected", ()), np.float64)
                if obs.size and sel.size == obs.size:
                    self._ensure_ranks(obs.size)
                    if obs.size == self._observed.size:
                        self._fold_exclusion(
                            obs, np.maximum(obs - np.minimum(sel, obs), 0.0)
                        )
            self._ring.append(rec)
            self._drain(rec)
            return rec

    def record_span(self, phase, *, t_wall, dur_s, **tags):
        """Fold one trace span (schema v5, trace.py) into the hub: the
        record streams to the sink, the duration lands in the per-phase
        digest (Prometheus ``garfield_phase_seconds``), and — when the
        span carries a ``step`` tag — in the per-round phase breakdown
        behind ``last_round_phases`` (the demo's /status panel)."""
        phase = str(phase)
        dur = float(dur_s)
        rec = make_record(
            "span", phase=phase, t_wall=round(float(t_wall), 6),
            dur_s=round(dur, 9), **tags,
        )
        with self._lock:
            self._spans += 1
            ph = self._phase.get(phase)
            if ph is None:
                ph = self._phase[phase] = {
                    "count": 0, "sum": 0.0,
                    "buckets": collections.Counter(),
                    "durs": collections.deque(maxlen=2048),
                }
            ph["count"] += 1
            ph["sum"] += dur
            ph["durs"].append(dur)
            for le in PHASE_BUCKETS:
                if dur <= le:
                    ph["buckets"][le] += 1
                    break
            step = tags.get("step")
            if isinstance(step, int) and not isinstance(step, bool):
                rp = self._round_phases.setdefault(step, {})
                rp[phase] = rp.get(phase, 0.0) + dur
                while len(self._round_phases) > 32:
                    self._round_phases.popitem(last=False)
            self._ring.append(rec)
            self._drain(rec)
            return rec

    def _drain(self, rec):
        if self._sink is not None:
            try:
                self._sink.write(rec)
            except Exception:
                pass  # a full disk must not take down the data path

    # --- reading -----------------------------------------------------------

    def suspicion(self):
        """Per-rank cumulative exclusion frequency, or None before any tap."""
        with self._lock:
            if self._observed is None:
                return None
            return self._excluded / np.maximum(self._observed, 1e-9)

    def suspicion_decayed(self):
        """Per-rank exclusion frequency over the exponentially decayed
        window (``suspicion_halflife``), falling back to the cumulative
        score when no halflife was configured — what the closed-loop
        defense and the report tool's straggler cross-check consume: a
        rotation attack cannot launder THIS score by sitting honest
        while its cumulative denominator grows. None before any tap."""
        with self._lock:
            if self._observed is None:
                return None
            if self._halflife is None:
                return self._excluded / np.maximum(self._observed, 1e-9)
            return self._excluded_d / np.maximum(self._observed_d, 1e-9)

    def client_suspicion_decayed(self, k=None):
        """Per-CLIENT decayed exclusion frequency over the sampled
        cohorts, keyed by stable GLOBAL client id ({cid: score}), or
        None before any cohort event. Entries not sampled recently are
        decayed to 'now' on read (numerator and denominator by the same
        factor — the RATIO is sampling-gap-invariant, so a Byzantine
        client cannot shrink its score by being resampled later; what
        the halflife does change is how fast old exclusions stop
        counting, same law as ``suspicion_decayed``). ``k`` returns only
        the top-k by score."""
        with self._lock:
            if not self._clients:
                return None
            out = {
                cid: (exc / max(obs, 1e-9))
                for cid, (obs, exc, _) in self._clients.items()
            }
        if k is not None:
            top = sorted(out.items(), key=lambda kv: -kv[1])[:int(k)]
            return dict(top)
        return out

    def client_suspicion_snapshot(self):
        """The raw per-client suspicion accumulators
        ({cid: (obs, exc)}), decayed to 'now' — what a shard failover
        checkpoints so a handoff carries suspicion FORWARD
        (controlplane/failover.py, DESIGN.md §22): an adaptive attacker
        who times a crash must not get its exclusion history reset by
        the standby's fresh hub. Empty dict before any cohort event."""
        with self._lock:
            now = self._cohort_events
            out = {}
            for cid, (obs, exc, last) in self._clients.items():
                if self._halflife is not None and now > last:
                    dk = self._susp_decay ** (now - last)
                    obs, exc = obs * dk, exc * dk
                out[int(cid)] = (float(obs), float(exc))
            return out

    def absorb_client_suspicion(self, snapshot):
        """Fold a checkpointed ``client_suspicion_snapshot`` into this
        hub — the restore half of the failover handoff. Merge is
        element-wise MAX against any live accumulator: absorbing a
        snapshot can only ever RAISE a client's recorded history, so a
        replayed (older) snapshot cannot launder suspicion accumulated
        since it was taken."""
        with self._lock:
            now = self._cohort_events
            for cid, (obs, exc) in dict(snapshot).items():
                ent = self._clients.get(int(cid))
                if ent is None:
                    self._clients[int(cid)] = [
                        float(obs), float(exc), now
                    ]
                else:
                    if self._halflife is not None and now > ent[2]:
                        dk = self._susp_decay ** (now - ent[2])
                        ent[0] *= dk
                        ent[1] *= dk
                        ent[2] = now
                    ent[0] = max(ent[0], float(obs))
                    ent[1] = max(ent[1], float(exc))

    def federated_stats(self):
        """Federated-round digest (schema v10), or None when no
        ``fed_round`` event was folded (non-federated runs)."""
        with self._lock:
            fd = self._fed
            if not fd["rounds"]:
                return None
            return {
                "rounds": int(fd["rounds"]),
                "shards": fd["shards"],
                "last_cohort": fd["last_cohort"],
                "f_budget": fd["f_budget"],
                "budget_exceeded": int(fd["budget_exceeded"]),
                "mean_round_s": round(
                    fd["round_s_sum"] / fd["rounds"], 6
                ),
            }

    def defense_stats(self):
        """Suspicion-weight digest + escalation state of the closed-loop
        defense (schema v7), or None when no defense event was folded."""
        with self._lock:
            d = self._defense
            if (not d["rounds"] and not d["escalations"]
                    and not d["deescalations"] and d["level"] is None):
                return None
            return {
                "rounds": int(d["rounds"]),
                "mean_w": (
                    None if not d["rounds"]
                    else round(d["w_sum"] / d["rounds"], 6)
                ),
                "min_w": (
                    None if d["w_min"] is None else round(d["w_min"], 6)
                ),
                "escalations": int(d["escalations"]),
                "deescalations": int(d["deescalations"]),
                "level": d["level"],
                "rule": d["rule"],
            }

    def data_defense_stats(self):
        """Data-plane defense digest (schema v9), or None when no
        ``data_defense`` event was folded. ``scores`` is the last
        per-rank outlier-score map (the Prometheus gauge's samples);
        the summary digest drops it (rounds/flagged/max_score/min_w)."""
        with self._lock:
            d = self._dataplane
            if not d["rounds"]:
                return None
            return {
                "rounds": int(d["rounds"]),
                "flagged": int(d["flagged"]),
                "max_score": (
                    None if d["max_score"] is None
                    else round(d["max_score"], 6)
                ),
                "min_w": (
                    None if d["min_w"] is None else round(d["min_w"], 6)
                ),
                "scores": dict(d["scores"]),
            }

    def targeted_stats(self):
        """Targeted-eval digest (schema v8), or None when no
        ``targeted_eval`` event was folded (untargeted runs)."""
        with self._lock:
            t = self._targeted
            if not t["events"]:
                return None
            return {
                "events": int(t["events"]),
                "last_confusion": (
                    None if t["last_confusion"] is None
                    else round(t["last_confusion"], 6)
                ),
                "last_asr": (
                    None if t["last_asr"] is None
                    else round(t["last_asr"], 6)
                ),
            }

    def attack_adapt_stats(self):
        """Adaptive-attacker digest (schema v7), or None when no
        ``attack_adapt`` event was folded (oblivious-attack runs)."""
        with self._lock:
            a = self._attack_adapt
            if not a["events"]:
                return None
            return {
                "events": int(a["events"]),
                "last_magnitude": (
                    None if a["last_mag"] is None
                    else round(a["last_mag"], 6)
                ),
            }

    def selection_history(self, k=60):
        """Last k (step, selected-list) pairs — the demo's history panel."""
        with self._lock:
            return list(self._selected_hist)[-k:]

    def records(self):
        with self._lock:
            return list(self._ring)

    def counters(self):
        with self._lock:
            return {
                "steps": self._steps,
                "events": self._events,
                "spans": self._spans,
                "loss": self._last_loss,
                "tau": self._last_tau,
                "clip_frac": self._last_clip_frac,
            }

    def wire_counters(self):
        """Cumulative wire-plane totals (bytes/codec-seconds/drops)."""
        with self._lock:
            return dict(self._wire)

    def wire_plane_counters(self):
        """Per-plane wire byte totals ({plane: {bytes_out, bytes_in}}),
        or {} when no plane-tagged wire event was folded (schema v6)."""
        with self._lock:
            return {p: dict(d) for p, d in sorted(
                self._wire_planes.items()
            )}

    def wire_scheme_counters(self):
        """Per-scheme wire byte totals ({scheme: {bytes_out, bytes_in}}),
        or {} when no scheme-tagged wire event was folded (schema v11,
        the round-18 compressed wire)."""
        with self._lock:
            return {s: dict(d) for s, d in sorted(
                self._wire_schemes.items()
            )}

    def ingest_batch_stats(self):
        """Bulk-ingest digest (schema v15), or None when no
        ``ingest_batch`` event was folded (per-frame-only runs)."""
        with self._lock:
            ib = self._ingest_batch
            if not ib["calls"]:
                return None
            return {
                "calls": int(ib["calls"]),
                "frames": int(ib["frames"]),
                "rejected": int(ib["rejected"]),
                "batched_s": float(ib["batched_s"]),
                "fallback_s": float(ib["fallback_s"]),
            }

    def autoscale_stats(self):
        """spawns/retires/active_workers over the run, or None when no
        autoscale event was folded (fixed-membership runs)."""
        with self._lock:
            a = self._autoscale
            if not a["spawns"] and not a["retires"] and a["active"] is None:
                return None
            return {
                "spawns": int(a["spawns"]),
                "retires": int(a["retires"]),
                "active_workers": int(a["active"] or 0),
            }

    def active_workers(self):
        """Current active-worker count (last autoscale event), or None."""
        with self._lock:
            return self._autoscale["active"]

    def staleness_stats(self):
        """count/mean/max + rounds histogram over every quorum member of
        every async round, or None when no staleness event was folded
        (synchronous runs). The histogram keys are staleness-in-rounds —
        the ``garfield_staleness_rounds`` exposition."""
        with self._lock:
            st = self._staleness
            if not st["count"]:
                return None
            return {
                "count": int(st["count"]),
                "mean": float(st["sum"] / st["count"]),
                "max": int(st["max"]),
                "hist": {int(k): int(v) for k, v in sorted(
                    st["hist"].items()
                )},
            }

    def phase_stats(self):
        """Per-phase duration percentiles over the recorded spans
        ({phase: {count, mean_s, p50_s, p95_s, p99_s}}), or None before
        any span — the per-phase twin of ``step_time_stats`` (and what
        exchange_bench scenario rows record to attribute speedups)."""
        with self._lock:
            if not self._phase:
                return None
            out = {}
            for phase in sorted(self._phase):
                ph = self._phase[phase]
                a = np.asarray(ph["durs"])
                out[phase] = {
                    "count": int(ph["count"]),
                    "mean_s": float(ph["sum"] / ph["count"]),
                    "p50_s": float(np.percentile(a, 50)),
                    "p95_s": float(np.percentile(a, 95)),
                    "p99_s": float(np.percentile(a, 99)),
                }
            return out

    def phase_histograms(self):
        """Per-phase {buckets: {le: count}, sum, count} — raw (non-
        cumulative) bucket counts over PHASE_BUCKETS; the Prometheus
        exporter renders the cumulative form."""
        with self._lock:
            return {
                phase: {
                    "buckets": dict(ph["buckets"]),
                    "sum": float(ph["sum"]),
                    "count": int(ph["count"]),
                }
                for phase, ph in sorted(self._phase.items())
            }

    def last_round_phases(self):
        """(step, {phase: seconds}) for the last COMPLETED round — the
        second-newest step seen in span tags (the newest may still be
        mid-round) — or None before two rounds of spans. The demo's
        /status phase-breakdown panel."""
        with self._lock:
            if not self._round_phases:
                return None
            steps = list(self._round_phases)
            step = steps[-2] if len(steps) >= 2 else steps[-1]
            return step, {
                k: round(v, 6)
                for k, v in sorted(self._round_phases[step].items())
            }

    def step_time_stats(self):
        """count/mean/min/max plus p50/p95/p99 over the recorded step
        times (the chunking win — fewer, fatter dispatches — shows up in
        the tail percentiles, not the mean)."""
        with self._lock:
            if not self._step_times:
                return None
            a = np.asarray(self._step_times)
            return {
                "count": int(a.size),
                "mean_s": float(a.mean()),
                "min_s": float(a.min()),
                "max_s": float(a.max()),
                "p50_s": float(np.percentile(a, 50)),
                "p95_s": float(np.percentile(a, 95)),
                "p99_s": float(np.percentile(a, 99)),
            }

    def summary(self):
        """The run-closing JSONL record: suspicion, counters, timings."""
        susp = self.suspicion()
        susp_d = (
            self.suspicion_decayed() if self._halflife is not None else None
        )
        defense = self.defense_stats()
        adapt = self.attack_adapt_stats()
        targeted = self.targeted_stats()
        data_defense = self.data_defense_stats()
        if data_defense is not None:
            # The per-rank score map serves the Prometheus gauge only;
            # the summary digest keeps the bounded extremes.
            data_defense = {
                k: v for k, v in data_defense.items() if k != "scores"
            }
        stale = self.staleness_stats()
        autos = self.autoscale_stats()
        fed = self.federated_stats()
        if fed is not None:
            # v10: top sampled-client suspects ride the digest (the full
            # sparse map serves the Prometheus gauge only — a summary
            # must stay bounded at million-client populations).
            top = self.client_suspicion_decayed(k=8) or {}
            fed = {
                **fed,
                "top_clients": {
                    str(cid): round(s, 6) for cid, s in top.items()
                },
            }
        wire_planes = self.wire_plane_counters()
        wire_schemes = self.wire_scheme_counters()
        phases = self.phase_stats()
        if phases is not None:
            phases = {
                k: {kk: round(vv, 6) for kk, vv in v.items()}
                for k, v in phases.items()
            }
        with self._lock:
            return make_record(
                "summary",
                steps=self._steps,
                events=self._events,
                # schema v5: per-phase span digest (None when no spans
                # were recorded — tracing-off runs are unchanged).
                spans=self._spans,
                phases=phases,
                loss=self._last_loss,
                num_ranks=self.num_ranks,
                suspicion=(
                    None if susp is None else np.round(susp, 6).tolist()
                ),
                # schema v7: the windowed score (None without a
                # configured suspicion_halflife — v6 consumers see
                # nothing new).
                suspicion_decayed=(
                    None if susp_d is None
                    else np.round(susp_d, 6).tolist()
                ),
                suspicion_halflife=self._halflife,
                # schema v7: closed-loop defense + adaptive-attacker
                # digests (None on runs without those events).
                defense=defense,
                attack_adapt=adapt,
                # schema v8: targeted-eval digest (None on untargeted
                # runs — v7 consumers see nothing new).
                targeted=targeted,
                # schema v9: data-plane defense digest (None on runs
                # without the data detectors).
                data_defense=data_defense,
                observed=(
                    None if self._observed is None
                    else np.round(self._observed, 3).tolist()
                ),
                excluded=(
                    None if self._excluded is None
                    else np.round(self._excluded, 3).tolist()
                ),
                step_time=(
                    None if not self._step_times else {
                        "count": len(self._step_times),
                        "mean_s": float(np.mean(self._step_times)),
                        # schema v2: tail percentiles from the ring of
                        # recorded step times (see step_time_stats).
                        "p50_s": float(
                            np.percentile(self._step_times, 50)
                        ),
                        "p95_s": float(
                            np.percentile(self._step_times, 95)
                        ),
                        "p99_s": float(
                            np.percentile(self._step_times, 99)
                        ),
                    }
                ),
                wire=(
                    None if not any(self._wire.values())
                    else {k: (round(v, 6) if isinstance(v, float) else v)
                          for k, v in self._wire.items()}
                ),
                # schema v6: per-plane wire byte breakdown (None when no
                # plane-tagged wire event was folded).
                wire_planes=wire_planes or None,
                # schema v11: per-scheme wire byte breakdown (None when
                # no scheme-tagged wire event was folded — pre-round-18
                # streams and compression-off runs).
                wire_schemes=wire_schemes or None,
                # schema v4: the async plane's staleness digest (None on
                # synchronous runs — v3 consumers are unaffected).
                staleness=stale,
                # schema v6: elastic-membership digest (None on
                # fixed-membership runs).
                autoscale=autos,
                # schema v10: federated-round digest + top sampled-client
                # suspects (None on non-federated runs).
                federated=fed,
                meta=self.meta,
            )


# --- process-global hook ----------------------------------------------------
#
# The exchange layer and the cluster driver sit far from the training loop
# that owns the hub; they report through this module-level slot instead of
# threading a handle through every call. ``emit_event`` is a cheap no-op
# when nothing is installed, so the instrumented paths stay free in
# un-telemetered runs.

_GLOBAL = None


def install(hub):
    """Make ``hub`` the process-global event sink (returns the previous)."""
    global _GLOBAL
    prev, _GLOBAL = _GLOBAL, hub
    return prev


def uninstall():
    global _GLOBAL
    _GLOBAL = None


def current():
    return _GLOBAL


def emit_event(kind, **fields):
    hub = _GLOBAL
    if hub is not None:
        try:
            hub.record_event(kind, **fields)
        except Exception:
            pass  # telemetry must never take down the data path


def emit_span(phase, *, t_wall, dur_s, **tags):
    """Span twin of ``emit_event`` (trace.py's emission path): a no-op
    when no hub is installed, and never raises into the traced phase."""
    hub = _GLOBAL
    if hub is not None:
        try:
            hub.record_span(phase, t_wall=t_wall, dur_s=dur_s, **tags)
        except Exception:
            pass  # tracing must never take down the data path

"""Slot-fused gradient twins (models/slotlayers.py + models/slotfused.py).

Each twin must deliver the SAME per-slot gradients/losses/batch_stats as
the reference unroll (vmap-compatible layout). Two tiers of equality pin,
both PER LEAF (params AND batch_stats):

1. **Structural pins in float64** (the tight ones): every covered family
   is asserted per-leaf at 1e-5 rel against the f64 unroll (measured
   agreement ~1e-11 global). In f64 the reduction-order noise that
   separates any two valid f32 evaluations is ~1e-16 and even heavily
   amplified stays far below tolerance, so these pins catch ANY
   structural drift — including the subtly-wrong-BN-treatment class
   VERDICT r5 weak #3 worried f32 tolerances could hide.

2. **Pipeline pins in float32** (the honest ones): the production dtype,
   at tolerances set by the MEASURED noise floor of this test platform.
   The fused batch reorders the BN statistics reductions; the resulting
   ~1e-7 stat perturbations amplify through the backward's
   (var+eps)^{-3/2} terms (worst with near-degenerate channel variances:
   depthwise stacks, small batch x spatial). This is floating-point
   sensitivity, NOT twin drift: the vmap-vs-unroll CONTROL — two
   mathematically identical non-twin formulations — measures the SAME
   floor (resnet18 @16x16 b=2 on the 8-virtual-device platform: twin
   2.07e-2, vmap control 2.07e-2; f64 pins catch the structure).
   Per-leaf assertions use a leaf-norm floor so cancellation-dominated
   leaves (BN bias/scale residues) are bounded in absolute terms
   relative to the largest leaf.

The twins' two formulation knobs (GARFIELD_SLOTFUSED_BN=matmul|segsum,
GARFIELD_SLOTFUSED_DW=grouped|unroll|segsum) are equality-pinned against
each other, and trainer-level fused-vs-unroll trajectory A/B covers
cifarnet (existing) plus the DenseNet family (new this round).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu.models import select_model, slotfused
from garfield_tpu.models.densenet import DenseNet
from garfield_tpu.parallel import core
from garfield_tpu.utils import selectors

N, B = 3, 2


@pytest.fixture
def x64():
    """float64 scope for the structural pins (same pattern as
    test_reference_parity's env fixture)."""
    jax.config.update("jax_enable_x64", True)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", False)


def _setup(module, shape, n=N, b=B, dtype=jnp.float32, tokens=None):
    loss_fn = selectors.select_loss("nll")
    init_fn, grad_fn, _ = core.make_worker_fns(module, loss_fn)
    k = jax.random.PRNGKey(0)
    if tokens is not None:
        # Integer-token batches (the GPT/copytask family): ``shape`` is
        # the (T,) sequence geometry, ``tokens`` the vocab size.
        x = jax.random.randint(k, (n, b) + shape, 0, tokens)
    else:
        x = jax.random.normal(k, (n, b) + shape, dtype)
    y = jax.random.randint(k, (n, b), 0, 10)
    keys = jax.random.split(k, n)
    params, ms = init_fn(k, x[0])
    return loss_fn, grad_fn, params, ms, x, y, keys


def _unroll(grad_fn, params, ms, x, y, keys):
    n = x.shape[0]
    outs = [grad_fn(params, ms, x[i], y[i], keys[i]) for i in range(n)]
    g = jax.tree.map(lambda *ls: jnp.stack(ls), *[o[0] for o in outs])
    loss = jnp.stack([o[1][0] for o in outs])
    ms_out = jax.tree.map(lambda *ls: jnp.stack(ls), *[o[1][1] for o in outs])
    return g, loss, ms_out


def _assert_per_leaf(tree_t, tree_u, tol, floor_frac=0.02, what="grad"):
    """Per-leaf rel-L2 pin with a leaf-norm floor.

    Leaves whose reference norm is below ``floor_frac`` of the LARGEST
    leaf norm are cancellation-dominated (their own norm is the residue
    of a near-cancelling sum — the vmap-vs-unroll control already shows
    1e-2-level per-leaf rel there); for those the denominator floors at
    ``floor_frac * max_norm``, turning the pin into an absolute bound at
    the gradient's global scale.
    """
    norms = [
        float(np.linalg.norm(np.asarray(l, np.float64)))
        for l in jax.tree.leaves(tree_u)
    ]
    gmax = max(norms) if norms else 0.0
    failures = []

    def chk(path, a, b):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        denom = max(np.linalg.norm(b), floor_frac * gmax, 1e-30)
        rel = np.linalg.norm(a - b) / denom
        if not rel < tol:
            failures.append(f"{jax.tree_util.keystr(path)}: {rel:.3e}")

    jax.tree_util.tree_map_with_path(chk, tree_t, tree_u)
    assert not failures, (
        f"{what} per-leaf rel L2 >= {tol} on {len(failures)} leaves:\n  "
        + "\n  ".join(failures[:10])
    )


def _check_family(module, shape, g_tol, ms_tol, n=N, b=B, loss_tol=1e-4,
                  dtype=jnp.float32, tokens=None):
    loss_fn, grad_fn, params, ms, x, y, keys = _setup(
        module, shape, n, b, dtype, tokens=tokens
    )
    slot_fn = slotfused.build_slot_grad_fn(module, loss_fn)
    assert slot_fn is not None
    g_t, (loss_t, ms_t) = jax.jit(slot_fn)(params, ms, x, y, keys)
    g_u, loss_u, ms_u = _unroll(grad_fn, params, ms, x, y, keys)
    np.testing.assert_allclose(
        np.asarray(loss_t), np.asarray(loss_u), rtol=loss_tol, atol=loss_tol
    )
    _assert_per_leaf(g_t, g_u, g_tol)
    if jax.tree.leaves(ms_u):
        _assert_per_leaf(ms_t, ms_u, ms_tol, what="batch_stats")


# --- tier 1: structural pins (float64, tight — catches any twin drift) ---

X64_FAMILIES = [
    ("cifarnet", (32, 32, 3)),
]
X64_FAMILIES_SLOW = [
    ("resnet18", (16, 16, 3)),
    ("vgg11", (32, 32, 3)),
    # 16x16 collapses mobilenet's tail blocks to 1x1 spatial — the BN
    # variance degeneracy that makes f32 pins meaningless there amplifies
    # f64 noise only to ~1e-8, still far under the 1e-5 pin.
    ("mobilenet", (16, 16, 3)),
    ("googlenet", (16, 16, 3)),
    ("mobilenetv2", (16, 16, 3)),
    ("resnet50", (16, 16, 3)),
]


def _x64_family(name, shape):
    module = select_model(name, "cifar10", dtype=jnp.float64)
    _check_family(
        module, shape, g_tol=1e-5, ms_tol=1e-7, loss_tol=1e-9,
        dtype=jnp.float64,
    )


@pytest.mark.parametrize("name,shape", X64_FAMILIES)
def test_twin_structural_pin_x64(x64, name, shape):
    """Per-leaf f64 equality vs the unroll (params AND batch_stats):
    measured agreement ~1e-11 global; tol 1e-5 flags any structural
    deviation orders of magnitude before an f32 pin could."""
    _x64_family(name, shape)


@pytest.mark.slow
def test_twin_structural_pin_x64_densenet(x64):
    """DenseNet family via a reduced instance (same class, same twin
    path, CPU-affordable): concat growth + pre-activation bottlenecks +
    transitions are all exercised."""
    _check_family(
        DenseNet((2, 2), growth_rate=8, dtype=jnp.float64), (16, 16, 3),
        g_tol=1e-5, ms_tol=1e-7, loss_tol=1e-9, dtype=jnp.float64,
    )


@pytest.mark.slow
@pytest.mark.parametrize("name,shape", X64_FAMILIES_SLOW)
def test_twin_structural_pin_x64_slow(x64, name, shape):
    """The heavier zoo members (googlenet's 9 inception blocks, v2's 17
    inverted residuals, the Bottleneck ResNet) — same pin, off the
    tier-1 fast shard for wall-time budget."""
    _x64_family(name, shape)


# --- tier 2: pipeline pins (float32, measured-floor tolerances) ----------

@pytest.mark.parametrize("name,shape,g_tol,ms_tol,loss_tol", [
    ("cifarnet", (32, 32, 3), 1e-5, 1e-5, 1e-5),
])
def test_twin_pipeline_pin_f32(name, shape, g_tol, ms_tol, loss_tol):
    _check_family(
        select_model(name, "cifar10"), shape, g_tol, ms_tol,
        loss_tol=loss_tol,
    )


def test_twin_pipeline_pin_f32_densenet():
    _check_family(DenseNet((2, 2), growth_rate=8), (16, 16, 3), 1e-3, 1e-3)


@pytest.mark.slow
@pytest.mark.parametrize("name,shape,g_tol,ms_tol,loss_tol", [
    # resnet18 @16x16 b=2: the vmap-vs-unroll CONTROL measures 2.07e-2 on
    # this platform (module docstring) — the pin sits just above it; the
    # structure itself is pinned at 1e-5 by the f64 tier.
    ("resnet18", (16, 16, 3), 6e-2, 1e-3, 1e-4),
    ("vgg11", (32, 32, 3), 1e-3, 1e-3, 1e-4),
    ("mobilenet", (32, 32, 3), 8e-2, 2e-2, 1e-2),
])
def test_twin_pipeline_pin_f32_slow(name, shape, g_tol, ms_tol, loss_tol):
    _check_family(
        select_model(name, "cifar10"), shape, g_tol, ms_tol,
        loss_tol=loss_tol,
    )


# --- transformer family (ViT + GPT, DESIGN.md §23) ------------------------
#
# CPU-affordable instances of the real classes (same twin path, same
# auto-naming): the attention core is literally the SAME callable in the
# flax module and the twin (slotlayers.attn_core), so these pins cover
# the slot-resolved contractions around it — seq_dense einsums, the
# per-slot LayerNorm affine, embedding gather transpose, positional
# broadcast transpose, and the tied-head attend einsum.

def _trans_modules(dtype=jnp.float32):
    from garfield_tpu.models import transformer

    vit = transformer.ViT(
        num_classes=10, dtype=dtype, patch=4, dim=24, depth=2, heads=2,
        mlp_dim=48,
    )
    gpt = transformer.GPT(
        num_classes=10, dtype=dtype, vocab=16, dim=16, depth=2, heads=2,
        mlp_dim=32,
    )
    gpt_tied = transformer.GPT(
        num_classes=16, dtype=dtype, vocab=16, dim=16, depth=2, heads=2,
        mlp_dim=32, tied=True,
    )
    return [("vit", vit, (8, 8, 3), None), ("gpt", gpt, (6,), 16),
            ("gpt_tied", gpt_tied, (6,), 16)]


@pytest.mark.parametrize("idx", range(3), ids=["vit", "gpt", "gpt_tied"])
def test_transformer_twin_structural_pin_x64(x64, idx):
    """Per-leaf f64 equality vs the unroll for the 8th family (measured
    agreement ~1e-16 abs — attention reductions included): same two-tier
    discipline as the conv zoo."""
    _, module, shape, tokens = _trans_modules(jnp.float64)[idx]
    _check_family(
        module, shape, g_tol=1e-5, ms_tol=1e-7, loss_tol=1e-9,
        dtype=jnp.float64, tokens=tokens,
    )


@pytest.mark.parametrize("idx", range(3), ids=["vit", "gpt", "gpt_tied"])
def test_transformer_twin_pipeline_pin_f32(idx):
    """f32 pipeline tier: no batch_stats (LayerNorm carries none) and no
    BN degeneracy, so the transformer pins sit near the conv zoo's
    tightest (cifarnet-level) tolerances."""
    _, module, shape, tokens = _trans_modules()[idx]
    _check_family(module, shape, g_tol=1e-4, ms_tol=1e-5,
                  loss_tol=1e-5, tokens=tokens)


def test_transformer_zoo_names_resolve_to_twins():
    """The registered zoo entries (models/__init__.py) resolve through
    the same registry the topology builders consult."""
    loss_fn = selectors.select_loss("nll")
    for name, dataset in (("vit_tiny", "cifar10"), ("gpt_tiny", "copytask")):
        module = select_model(name, dataset)
        assert slotfused.build_slot_grad_fn(module, loss_fn) is not None, name


def test_trainer_ab_gpt(monkeypatch):
    """Trainer-level fused-vs-unroll trajectory A/B on token batches:
    3 aggregathor steps (median + lie) of the small GPT land within f32
    tolerance — the transformer twin is live through the same
    resolve_slot_grad_fn gate the conv zoo uses."""
    from garfield_tpu.models import transformer

    module = transformer.GPT(
        num_classes=10, vocab=16, dim=16, depth=1, heads=2, mlp_dim=32
    )
    k = jax.random.PRNGKey(4)
    n_w = 2 * jax.device_count()
    x = jax.random.randint(k, (n_w, 4, 6), 0, 16)
    y = jax.random.randint(jax.random.fold_in(k, 1), (n_w, 4), 0, 10)
    finals = [
        _trainer_final_params(module, x, y, disable, monkeypatch)
        for disable in (False, True)
    ]
    np.testing.assert_allclose(finals[0], finals[1], rtol=1e-4, atol=1e-6)


def test_registry_covers_the_dropout_free_zoo():
    """>= 7 model families resolve to a twin by name; dropout models and
    unported families return None (callers fall back to the unroll)."""
    loss_fn = selectors.select_loss("nll")
    covered = [
        "cifarnet", "resnet18", "resnet34", "resnet50", "vgg11", "vgg16",
        "vgg19", "googlenet", "inception", "mobilenet", "mobilenetv2",
        "densenet121", "densenet_cifar",
    ]
    for name in covered:
        module = select_model(name, "cifar10")
        assert slotfused.build_slot_grad_fn(module, loss_fn) is not None, name
    uncovered = ["convnet", "cnn", "senet18", "dpn26", "shufflenetv2"]
    for name in uncovered:
        module = select_model(name, "mnist" if name == "convnet" else "cifar10")
        assert slotfused.build_slot_grad_fn(module, loss_fn) is None, name


def test_slot_path_decision():
    """Run-length-aware unroll/vmap choice (VERDICT r4 #8): the fused twin
    wins when available; a reference-scale 100k-iter n=64 run takes the
    unroll automatically; a short unknown-length large-n run keeps vmap."""
    d = core.slot_path_decision
    assert d(64, 100_000, True)[0] == "fused"
    assert d(8, None, False)[0] == "unroll"           # under the cap
    assert d(64, 100_000, False)[0] == "unroll"        # amortized
    assert d(64, 100, False)[0] == "vmap"              # too short
    assert d(64, None, False)[0] == "vmap"             # unknown length


def test_resolve_slot_grad_fn_gates():
    """The topology-uniform front-end: per-slot DISTINCT params (LEARN)
    and the escape hatch both gate the twin off; slots=1 has nothing to
    fuse."""
    module = select_model("cifarnet", "cifar10")
    loss_fn = selectors.select_loss("nll")
    assert core.resolve_slot_grad_fn(module, loss_fn, 4) is not None
    assert core.resolve_slot_grad_fn(module, loss_fn, 1) is None
    assert core.resolve_slot_grad_fn(
        module, loss_fn, 4, shared_params=False
    ) is None


def test_bn_stats_modes_agree(monkeypatch):
    """GARFIELD_SLOTFUSED_BN=matmul|segsum are the same per-slot sums
    (equal-length segments added in index order on both routes) — pinned
    tightly, grads AND batch_stats."""
    module = DenseNet((2, 2), growth_rate=8)
    loss_fn, grad_fn, params, ms, x, y, keys = _setup(module, (16, 16, 3))
    slot_fn = slotfused.build_slot_grad_fn(module, loss_fn)
    monkeypatch.setenv("GARFIELD_SLOTFUSED_BN", "matmul")
    g_a, (_, ms_a) = slot_fn(params, ms, x, y, keys)
    monkeypatch.setenv("GARFIELD_SLOTFUSED_BN", "segsum")
    g_b, (_, ms_b) = slot_fn(params, ms, x, y, keys)
    _assert_per_leaf(g_a, g_b, 1e-5)
    _assert_per_leaf(ms_a, ms_b, 1e-5, what="batch_stats")


def _dw_mode_check(module, shape, mode, monkeypatch, tol=1e-4):
    loss_fn, grad_fn, params, ms, x, y, keys = _setup(module, shape)
    slot_fn = slotfused.build_slot_grad_fn(module, loss_fn)
    monkeypatch.delenv("GARFIELD_SLOTFUSED_DW", raising=False)
    g_grouped, _ = slot_fn(params, ms, x, y, keys)
    monkeypatch.setenv("GARFIELD_SLOTFUSED_DW", mode)
    g_mode, _ = slot_fn(params, ms, x, y, keys)
    _assert_per_leaf(g_grouped, g_mode, tol)


@pytest.mark.parametrize("mode", ["unroll", "segsum"])
def test_dw_modes_agree(monkeypatch, mode):
    """grouped (default) / unroll / segsum dw formulations are the same
    math on a plain-conv BN model. (Env is read at trace time; the
    unjitted calls retrace.)"""
    _dw_mode_check(DenseNet((2, 2), growth_rate=8), (16, 16, 3), mode,
                   monkeypatch)


@pytest.mark.slow
def test_dw_segsum_depthwise(monkeypatch):
    """segsum's gather/segment expand is bitwise-equal to the S.T matmul
    on CPU — pinned tightly on the depthwise (grouped-conv) family, where
    the 16x16 BN-degeneracy would swamp a non-bitwise mode. Off the
    tier-1 fast shard for wall-time budget (modes are still covered
    tier-1 by test_dw_modes_agree on the reduced DenseNet)."""
    _dw_mode_check(select_model("mobilenet", "cifar10"), (16, 16, 3),
                   "segsum", monkeypatch)


@pytest.mark.slow
def test_dw_unroll_depthwise(monkeypatch):
    """grouped vs unroll dw on the depthwise family at the non-degenerate
    32x32 geometry (the two modes re-order f32 sums, so the degenerate
    geometry would amplify past any meaningful pin)."""
    _dw_mode_check(select_model("mobilenet", "cifar10"), (32, 32, 3),
                   "unroll", monkeypatch)


def test_per_slot_grads_routes_fused():
    module = select_model("cifarnet", "cifar10")
    loss_fn, grad_fn, params, ms, x, y, keys = _setup(module, (32, 32, 3))
    slot_fn = slotfused.build_slot_grad_fn(module, loss_fn)
    g_f, _ = core.per_slot_grads(
        grad_fn, params, ms, x, y, keys, fused_fn=slot_fn
    )
    g_u, _, _ = _unroll(grad_fn, params, ms, x, y, keys)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        g_f, g_u,
    )


def _trainer_final_params(module, x, y, disable, monkeypatch, gar="median"):
    import optax

    from garfield_tpu.parallel import aggregathor

    loss_fn = selectors.select_loss("nll")
    if disable:
        monkeypatch.setenv("GARFIELD_NO_SLOTFUSED", "1")
    else:
        monkeypatch.delenv("GARFIELD_NO_SLOTFUSED", raising=False)
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss_fn, optax.sgd(0.05), gar,
        num_workers=x.shape[0], f=1, attack="lie",
    )
    state = init_fn(jax.random.PRNGKey(2), x[0])
    for _ in range(3):
        state, metrics = step_fn(state, x, y)
    return np.asarray(jax.flatten_util.ravel_pytree(state.params)[0])


def test_trainer_env_escape_hatch(monkeypatch):
    """GARFIELD_NO_SLOTFUSED forces the unroll in the topology builder and
    both paths produce working trainers with close trajectories."""
    module = select_model("cifarnet", "cifar10")
    k = jax.random.PRNGKey(1)
    # 2 slots per shard so the builder actually engages the fused path
    # (per_shard == 1 has nothing to fold).
    n_w = 2 * jax.device_count()
    x = jax.random.normal(k, (n_w, 4, 32, 32, 3))
    y = jax.random.randint(k, (n_w, 4), 0, 10)
    finals = [
        _trainer_final_params(module, x, y, disable, monkeypatch)
        for disable in (False, True)
    ]
    np.testing.assert_allclose(finals[0], finals[1], rtol=1e-4, atol=1e-6)


def test_trainer_ab_densenet(monkeypatch):
    """Trainer-level fused-vs-unroll trajectory A/B for a NEW family
    (DenseNet — BN + concat growth), extending the matrix beyond
    cifarnet/resnet: 3 aggregathor steps under median+lie land within
    deep-net f32 tolerance of each other."""
    module = DenseNet((1, 1), growth_rate=8)
    k = jax.random.PRNGKey(3)
    n_w = 2 * jax.device_count()
    x = jax.random.normal(k, (n_w, 2, 16, 16, 3))
    y = jax.random.randint(k, (n_w, 2), 0, 10)
    finals = [
        _trainer_final_params(module, x, y, disable, monkeypatch)
        for disable in (False, True)
    ]
    np.testing.assert_allclose(finals[0], finals[1], rtol=1e-3, atol=1e-5)

"""Registration of the native (C++) GAR variants.

Counterpart of the reference's native registration blocks (e.g.
pytorch_impl/libs/aggregators/krum.py:156-166 registers ``krum`` and, when
``import native`` succeeded (:23-26), ``native-krum``). Here the native
kernels live in garfield_tpu/native (ctypes over a JIT-built .so); they are
registered lazily — the .so builds on first *call*, not at import — and only
when a C++ toolchain is present.

Inside a jit trace the wrappers route through ``jax.pure_callback`` (host
callback), so ``gars["native-krum"]`` is usable in the same places as the XLA
rules; on TPU this costs a device->host round trip and exists for parity and
as the CPU production path, mirroring how the reference's CUDA natives were
the GPU production path.
"""

import shutil

import numpy as np

from . import aksel, average, brute, bulyan, condense, krum, median, register


def _native_call(fn_name, gradients, *args):
    from .. import native

    return getattr(native, fn_name)(np.asarray(gradients), *args)


def _wrap(fn_name, *argnames):
    def unchecked(gradients, f=None, m=None, **kwargs):
        import jax
        import jax.numpy as jnp

        from ._common import as_stack

        g = as_stack(gradients)
        call_args = []
        for name in argnames:
            call_args.append({"f": f, "m": m}[name])
        if isinstance(g, jax.core.Tracer):
            return jax.pure_callback(
                lambda garr: _native_call(fn_name, garr, *call_args),
                jax.ShapeDtypeStruct((g.shape[1],), g.dtype),
                g,
                vmap_method="sequential",
            )
        return jnp.asarray(_native_call(fn_name, np.asarray(g), *call_args))

    return unchecked


if shutil.which("g++"):
    register(
        "native-krum", _wrap("krum", "f", "m"), krum.check,
        upper_bound=krum.upper_bound, influence=krum.influence,
    )
    register(
        "native-median", _wrap("median"), median.check,
        upper_bound=median.upper_bound,
    )
    register(
        "native-bulyan", _wrap("bulyan", "f", "m"), bulyan.check,
        upper_bound=bulyan.upper_bound,
    )
    register(
        "native-brute", _wrap("brute", "f"), brute.check,
        upper_bound=brute.upper_bound,
    )

"""Telemetry plane: in-graph GAR audit taps, host aggregation, exporters.

The repo's runtime observability layer (ISSUE 2). Three layers:

  - ``taps`` (in-graph): a small, fixed-shape ``TapBundle`` pytree —
    per-rank selection mask / scores, cclip's tau + clip fraction —
    recomputed inside the jit'd step from the SAME poisoned stack and PRNG
    keys the GAR consumed. The taps never feed back into ``TrainState``,
    so taps-on and taps-off trajectories are bitwise identical; when
    disabled (the default) nothing is traced at all — zero cost, not
    masked-out cost.
  - ``hub`` (host): a ring-buffered ``MetricsHub`` that merges per-step
    taps with ``profiling.StepTimer`` timings and the liveness/wait-n-f
    events the cluster driver and ``utils.exchange`` emit through the
    process-global hook (``install``/``emit_event``), and derives per-rank
    *suspicion scores* — cumulative exclusion frequency under the active
    GAR, the audit signal that makes Byzantine ranks visible without
    ground truth.
  - ``exporters``: schema-versioned JSONL (the format ``bench.py`` and
    the bench artifacts adopt), Prometheus text exposition, and stdlib
    schema validation so malformed artifacts fail loudly.

See docs/TELEMETRY.md for the record schema and overhead numbers.
"""

from .exporters import (  # noqa: F401
    JsonlExporter,
    SCHEMA,
    SCHEMA_VERSION,
    make_record,
    prometheus_text,
    validate_jsonl,
    validate_record,
)
from .hub import (  # noqa: F401
    MetricsHub,
    current,
    emit_event,
    emit_span,
    install,
    uninstall,
)
from . import trace  # noqa: F401  (span tracing, schema v5 — ISSUE 8)

__all__ = [
    "emit_span",
    "trace",
    "JsonlExporter",
    "MetricsHub",
    "SCHEMA",
    "SCHEMA_VERSION",
    "current",
    "emit_event",
    "install",
    "make_record",
    "prometheus_text",
    "uninstall",
    "validate_jsonl",
    "validate_record",
]

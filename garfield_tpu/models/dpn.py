"""Dual Path Networks (counterpart of garfieldpp/models/dpn.py)."""

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm


class DPNBottleneck(nn.Module):
    in_planes: int
    out_planes: int
    dense_depth: int
    stride: int
    first_layer: bool
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        out = nn.relu(norm(train, dtype=d)(conv1x1(self.in_planes, dtype=d)(x)))
        out = nn.relu(norm(train, dtype=d)(
            conv(self.in_planes, 3, self.stride, padding=1, groups=32, dtype=d)(out)))
        out = norm(train, dtype=d)(
            conv1x1(self.out_planes + self.dense_depth, dtype=d)(out))
        if self.first_layer:
            x = norm(train, dtype=d)(
                conv1x1(self.out_planes + self.dense_depth, stride=self.stride,
                        dtype=d)(x))
        res_x, dense_x = x[..., : self.out_planes], x[..., self.out_planes :]
        res_o, dense_o = out[..., : self.out_planes], out[..., self.out_planes :]
        out = jnp.concatenate(
            [res_x + res_o, dense_x, dense_o], axis=-1)
        return nn.relu(out)


class DPN(nn.Module):
    in_planes: Sequence[int]
    out_planes: Sequence[int]
    num_blocks: Sequence[int]
    dense_depth: Sequence[int]
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        x = nn.relu(norm(train, dtype=d)(conv(64, 3, 1, padding=1, dtype=d)(x)))
        for stage in range(4):
            ip, op = self.in_planes[stage], self.out_planes[stage]
            nb, dd = self.num_blocks[stage], self.dense_depth[stage]
            strides = [1 if stage == 0 else 2] + [1] * (nb - 1)
            for i, s in enumerate(strides):
                x = DPNBottleneck(ip, op, dd, s, i == 0, dtype=d)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)


def DPN26(num_classes=10, dtype=jnp.float32):
    return DPN((96, 192, 384, 768), (256, 512, 1024, 2048),
               (2, 2, 2, 2), (16, 32, 24, 128), num_classes, dtype)


def DPN92(num_classes=10, dtype=jnp.float32):
    return DPN((96, 192, 384, 768), (256, 512, 1024, 2048),
               (3, 4, 20, 3), (16, 32, 24, 128), num_classes, dtype)

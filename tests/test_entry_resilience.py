"""Driver-entry-point resilience to a dead/hung default backend.

The r5 outage (VERDICT "Next round" #1a): with the TPU tunnel down,
in-process ``jax.devices()`` blocked forever inside plugin init — bench.py
died rc=1 with an unparseable traceback and dryrun_multichip hung to the
driver's rc=124 timeout. The entry points now (a) probe the device count in
a short-timeout SUBPROCESS before any in-process backend use
(``profiling.probe_device_count``), (b) fall back to the virtual CPU mesh
(or honor ``GARFIELD_FORCE_CPU_DRYRUN``), and (c) emit one parseable
``{"error": ...}`` JSON line on any bench failure.
"""

import json
import os
import subprocess
import sys

import pytest

from garfield_tpu.utils import profiling

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestProbeDeviceCount:
    def test_probe_counts_cpu_devices(self):
        # conftest exports JAX_PLATFORMS=cpu + the 8-device XLA flag to
        # subprocesses, so the probe sees the same virtual platform.
        n = profiling.probe_device_count()
        assert n is not None and n >= 1

    def test_probe_timeout_returns_none(self):
        # A timeout must bound a hung plugin init: the probe gives up and
        # returns None instead of blocking the caller.
        assert profiling.probe_device_count(timeout_s=0.001) is None

    def test_probe_failure_returns_none(self, monkeypatch):
        # A broken interpreter path (stand-in for any probe crash) is a
        # clean None, never an exception.
        monkeypatch.setattr(
            sys, "executable", "/nonexistent/python-definitely-missing"
        )
        assert profiling.probe_device_count(timeout_s=5) is None


@pytest.mark.slow
class TestBenchErrorContract:
    def test_bench_failure_emits_parseable_error_json(self):
        """Any bench failure must surface as ONE parseable {"error": ...}
        line on stdout (rc 0), never a bare traceback — the r5 BENCH
        artifact was rc=1 with parsed: null."""
        env = dict(os.environ)
        env["GARFIELD_FORCE_CPU_DRYRUN"] = "1"  # skip the probe (fast path)
        env["GARFIELD_BENCH_GAR"] = "no-such-rule"
        env["GARFIELD_BENCH_STEPS"] = "1"
        env["GARFIELD_BENCH_TRIALS"] = "1"
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO_ROOT, "bench.py")],
            cwd=_REPO_ROOT,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [l for l in proc.stdout.splitlines() if l.strip()]
        assert lines, "bench printed nothing to stdout"
        payload = json.loads(lines[-1])
        assert "error" in payload and payload["error"]
        # Outage stamping (ISSUE 6 satellite): a config error is NOT a
        # backend outage — the mechanical filter must not flag it.
        assert payload.get("backend_outage") is False


def test_outage_error_is_stamped_transient():
    """The r5 outage signature ('UNAVAILABLE: TPU backend setup/compile
    error', BENCH_r05.json) must classify as a transient backend error —
    the predicate behind bench.py's ``backend_outage: true`` stamp that
    lets future ratchets filter outage captures mechanically."""
    exc = RuntimeError(
        "Unable to initialize backend 'axon': UNAVAILABLE: TPU backend "
        "setup/compile error (Unavailable)."
    )
    assert profiling.is_transient_backend_error(exc)
    assert not profiling.is_transient_backend_error(
        ValueError("unknown GAR 'no-such-rule'")
    )

"""scripts/fetch_data.py offline format-correctness (VERDICT r1 #5).

No egress in this environment, so the download step is injected: the fake
downloader produces byte-exact artifacts in the upstream formats (idx-ubyte
gz, python-pickle tarballs, headerless CSV), and the REAL loaders in
garfield_tpu.data must then read the fetched tree — proving the script's
layouts/URLs line up with what the library expects.
"""

import gzip
import importlib.util
import io
import os
import pickle
import struct
import sys
import tarfile

import numpy as np
import pytest

_SPEC = importlib.util.spec_from_file_location(
    "fetch_data",
    os.path.join(os.path.dirname(__file__), "..", "scripts", "fetch_data.py"),
)
fetch_data = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(fetch_data)


def _idx_gz(array):
    """Encode an array in idx-ubyte format, gzipped (the MNIST wire format)."""
    array = np.asarray(array, np.uint8)
    magic = 0x0800 | array.ndim
    header = struct.pack(">i", magic) + b"".join(
        struct.pack(">i", s) for s in array.shape
    )
    return gzip.compress(header + array.tobytes())


def _mnist_downloader(url, **_):
    rng = np.random.default_rng(0)
    if "images" in url:
        n = 64 if "train" in url else 16
        return _idx_gz(rng.integers(0, 256, (n, 28, 28)))
    n = 64 if "train" in url else 16
    return _idx_gz(rng.integers(0, 10, (n,)))


def _cifar_downloader(url, **_):
    rng = np.random.default_rng(1)

    def batch(n, label_key):
        return pickle.dumps({
            b"data": rng.integers(0, 256, (n, 3072), dtype=np.uint8),
            label_key: rng.integers(0, 10, n).tolist(),
        })

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        if "cifar-100" not in url:
            names = [f"cifar-10-batches-py/data_batch_{i}" for i in
                     range(1, 6)] + ["cifar-10-batches-py/test_batch"]
            key = b"labels"
        else:
            names = ["cifar-100-python/train", "cifar-100-python/test"]
            key = b"fine_labels"
        for name in names:
            payload = batch(8, key)
            info = tarfile.TarInfo(name)
            info.size = len(payload)
            tar.addfile(info, io.BytesIO(payload))
    return buf.getvalue()


def _pima_downloader(url, **_):
    rng = np.random.default_rng(2)
    rows = [
        ",".join(
            [f"{v:.1f}" for v in rng.normal(size=8)]
            + [str(int(rng.integers(0, 2)))]
        )
        for _ in range(768)
    ]
    return ("\n".join(rows)).encode()  # headerless, like the mirror


def test_urls_are_wellformed():
    from urllib.parse import urlparse

    flat = []
    for v in fetch_data.URLS.values():
        if isinstance(v, str):
            flat.append(v)
        else:
            flat += [base for base, _ in v]
    for url in flat:
        parsed = urlparse(url)
        assert parsed.scheme == "https" and parsed.netloc, url


def test_fetched_mnist_loads(tmp_path, monkeypatch):
    fetch_data.fetch_mnist(tmp_path, download=_mnist_downloader)
    monkeypatch.setenv("GARFIELD_TPU_DATA_DIR", str(tmp_path))
    from garfield_tpu import data

    (tx, ty), (vx, vy) = data.load_mnist()
    assert tx.shape == (64, 28, 28, 1) and vx.shape == (16, 28, 28, 1)
    assert ty.dtype == np.int32 and set(np.unique(ty)) <= set(range(10))


@pytest.mark.parametrize("name", ["cifar10", "cifar100"])
def test_fetched_cifar_loads(tmp_path, monkeypatch, name):
    fetch_data.fetch_cifar(tmp_path, name, download=_cifar_downloader)
    monkeypatch.setenv("GARFIELD_TPU_DATA_DIR", str(tmp_path))
    from garfield_tpu import data

    (tx, ty), (vx, vy) = data.load_cifar(name, augment_train=False)
    assert tx.shape[1:] == (32, 32, 3) and vx.shape[1:] == (32, 32, 3)
    assert tx.shape[0] == 40 if name == "cifar10" else 8


def test_fetched_pima_loads(tmp_path, monkeypatch):
    dest = fetch_data.fetch_pima(tmp_path, download=_pima_downloader)
    # The loader does skip_header=1, so the script must have added one.
    assert dest.read_text().splitlines()[0].startswith("pregnancies,")
    monkeypatch.setenv("GARFIELD_TPU_DATA_DIR", str(tmp_path))
    from garfield_tpu import data

    (tx, ty), (vx, vy) = data.load_pima()
    assert tx.shape == (600, 8) and vx.shape == (168, 8)
    assert ty.shape == (600, 1) and ty.dtype == np.float32

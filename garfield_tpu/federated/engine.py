"""The federated round engine: sharded PS plane over the hierarchy.

One round = SAMPLE (sampler.py) -> INGEST (every shard streams its d/S
column span of each cohort gradient through its own hierarchical
reducer, aggregators/hierarchy.StreamingAggregator) -> FOLD (per-shard
hier-GAR at the cohort's priced f budget) -> BROADCAST (per-shard model
spans re-published; the unsharded vector exists only where a consumer
reassembles it). ``ShardServer`` is the per-shard half — a standalone
object one OS process runs for exactly one shard, with its own wire
plane (frames stamped with the shard id, cross-shard arrivals are
attributable codec rejects) — and ``FedRoundEngine`` composes S of them
in one process: the simulation driver, the bitwise-equality anchor, and
the single-host deployment shape.

Bitwise anchor: at S=1 with full participation and no stragglers the
engine IS the existing unsharded single-PS streaming path — same
``StreamingAggregator`` programs over the same arrival order, same
``model -= lr * agg`` update — so its trajectory is bitwise equal to
the pre-sharding path (pinned in tests/test_federated.py and recorded
as ``s1_bitwise_equal`` in FEDBENCH_r01).

Why selection is per shard: each shard's hierarchy sees only its column
span, so krum's inlier geometry (and therefore which clients a bucket
excludes) can differ shard to shard — a client can be excluded in shard
0 and kept in shard 1. That is by design, not an approximation loss: a
Byzantine client must now defeat S independent robust folds to corrupt
the full vector, and each shard's f-composition contract holds verbatim
over its own slice (every cohort member contributes exactly one row per
shard). The flip side — a sharded fold is NOT bitwise the unsharded
fold for S > 1 — is documented in DESIGN.md §19, measured in
FEDBENCH_r01, and never hidden behind the S=1 anchor.

Telemetry (schema v10): one ``fed_round`` event per round (cohort size,
f budget, realized-Byzantine audit when the driver knows ground truth,
round wall, per-shard digests) and — with ``audit=True`` — one
``cohort`` event carrying the sampled GLOBAL client ids with their
composed selected weights, which ``telemetry.hub.MetricsHub`` folds
into client-id-keyed decayed suspicion (the score resampling cannot
launder).
"""

import json
import os
import time

import numpy as np

from . import sharding
from ..aggregators import hierarchy
from ..telemetry import hub as tele_hub
from ..telemetry import trace as _trace
from ..utils import wire

__all__ = ["ShardServer", "FedRoundEngine"]


class ShardServer:
    """One PS shard: hierarchy levels + wire plane for one column span.

    ``begin_round(n, f)`` arms the reducer for the round's active cohort
    size at the round's priced f budget; rows arrive via ``push_rows``
    (host blocks — the fleet driver / bench path) or ``push_frame`` /
    ``wire_transform`` (typed wire frames stamped with this shard's id;
    the transform plugs into ``PeerExchange`` waiter threads so decode
    and bucket folding overlap the still-open quorum, exactly like the
    unsharded streaming path). ``finish_round`` folds the remainder and
    returns the (d_shard,) aggregate.
    """

    def __init__(self, shard, spec, *, bucket_gar="krum", top_gar=None,
                 bucket_size=None, levels="auto", wave_buckets=8,
                 audit=False, epoch=None):
        self.shard = sharding.shard_plane(shard, spec.num_shards)
        self.spec = spec
        self.d_shard = spec.width(self.shard)
        self._cfg = dict(
            bucket_gar=bucket_gar, top_gar=top_gar, bucket_size=bucket_size,
            levels=levels, wave_buckets=wave_buckets, audit=audit,
        )
        self._red = None
        self._round = None
        self.wire_bytes_in = 0
        self._fused = wire.wire_fused()
        self._scratch = None
        # Membership epoch this shard serves (controlplane, DESIGN.md
        # §22): None = pre-epoch deployment, frames are not
        # epoch-checked. When set, every wire frame must carry exactly
        # this epoch (wire.decode's expect_epoch) — a stale-epoch frame
        # is the same attributable reject as a cross-shard stamp.
        self.epoch = None if epoch is None else wire.check_epoch(epoch)
        # Round this shard is allowed to serve next after a checkpoint
        # restore (mark_restored) — None once live again.
        self._expect_round = None

    # -- round lifecycle ----------------------------------------------------

    def mark_restored(self, next_round):
        """Pin the ONLY round this shard may serve next: it was just
        restored from the span checkpoint saved after round
        ``next_round - 1`` finished, so ``next_round`` is the one round
        its state is valid for. ``begin_round`` for any other round
        refuses loudly (see there); serving the pinned round clears the
        pin — from then on the shard is live and carries its own
        state."""
        self._expect_round = int(next_round)

    def begin_round(self, round_, n, f):
        """Arm the shard's reducer for ``n`` active cohort members at
        the priced budget ``f``. Reuses the previous round's wave
        buffers when (n, f) repeat — at bench scale the reallocation is
        measurable, and plan identity keeps the fold programs cached.

        A RESTORED shard (``mark_restored``) serves exactly the round
        after its checkpoint: round state is rebuilt from scratch here
        every round, so nothing else would catch a driver resuming at
        the wrong round — the shard would silently fold rows against a
        stale span and broadcast garbage with round-R labels. Refusing
        is the loud form of "I have no span checkpoint for that
        round"."""
        if self._expect_round is not None \
                and int(round_) != self._expect_round:
            raise RuntimeError(
                f"shard {self.shard} was restored from its round "
                f"{self._expect_round - 1} span checkpoint and can only "
                f"serve round {self._expect_round}; asked to begin round "
                f"{int(round_)}, for which it has no span checkpoint — "
                "refusing loudly instead of serving a stale span"
            )
        self._expect_round = None
        if self._red is not None and self._red.n == int(n) \
                and self._red.f == int(f):
            self._red.reset()
        else:
            self._red = hierarchy.StreamingAggregator(
                int(n), int(f), d=self.d_shard, **self._cfg
            )
        self._round = int(round_)
        self.wire_bytes_in = 0
        return self._red.plan

    def push_rows(self, rows, *, stable=False):
        """Ingest a (k, d_shard) block of already-sliced cohort rows in
        arrival order (the in-process fast path — one bulk copy into the
        wave buffer, hierarchy.push_many). ``stable=True`` promises the
        block stays alive and unwritten for the rest of the round, which
        lets whole waves fold zero-copy straight off it
        (hierarchy.push_many's stable contract) — the bench's immutable
        round pool qualifies; a buffer the caller refills per push does
        NOT."""
        return self._red.push_many(rows, stable=stable)

    def push_frame(self, buf):
        """Ingest one typed wire frame: decoded with
        ``expect_plane=shard`` so a frame stamped for another shard is a
        ``WireError`` — ban evidence attributable to its SENDER (the
        stamp is under the CRC; DESIGN.md §19), not a silent mis-fold.
        A frame may carry several whole rows (k·d_shard elements): the
        fleet's clients batch their simulated cohort members into one
        frame per shard per round — so the element count cannot be
        pinned exactly, but it IS bounded by the whole cohort
        (n·d_shard), and ``max_elems`` rejects a header claiming more
        BEFORE a sparse frame's scatter allocates (the sparse dense-size
        claim is otherwise sender-controlled, see wire.decode).

        Fused path (GARFIELD_WIRE_FUSED_DECODE, default on): the frame
        decodes into a REUSABLE per-shard scratch (wire.decode_into) —
        one allocation per high-water frame size instead of one O(k·d)
        transient per frame. The scratch is sized from the header's
        claimed count CLAMPED to the cohort bound (wire.frame_elems is a
        sizing hint, never an allocation grant), so an over-claiming
        frame still rejects on ``max_elems`` before any allocation
        grows past the bound."""
        bound = self._red.n * self.d_shard
        if self._fused:
            claim = min(wire.frame_elems(buf), bound)
            if self._scratch is None or self._scratch.size < claim:
                self._scratch = np.empty(claim, np.float32)
            k = wire.decode_into(buf, self._scratch,
                                 expect_plane=self.shard, max_elems=bound,
                                 expect_epoch=self.epoch)
            vec = self._scratch[:k]
        else:
            vec = wire.decode(buf, expect_plane=self.shard,
                              max_elems=bound, expect_epoch=self.epoch)
        if vec.size % self.d_shard:
            raise wire.WireError(
                f"shard {self.shard} frame has {vec.size} elements — "
                f"not a whole number of {self.d_shard}-wide rows"
            )
        self.wire_bytes_in += len(buf)
        return self._red.push_many(vec.reshape(-1, self.d_shard))

    def push_frames(self, bufs):
        """Bulk wire ingest (ISSUE 20): decode a whole batch of
        single-row frames straight into the reducer's level-0 wave rows
        via ``hierarchy.push_frames`` / ``wire.decode_batch_into`` — one
        vectorized header screen + same-scheme slab dequant instead of a
        Python codec trip per frame. Returns a list the length of
        ``bufs``: per-frame arrival index, or the indexed ``WireError``
        (the sender's ban evidence — one forged frame never poisons its
        batchmates, pinned in tests/test_wire.py).

        The batch fast path requires every frame's HEADER to claim
        exactly one ``d_shard``-wide row (the per-client wire shape; the
        claim is re-validated inside the codec). Batches carrying any
        multi-row fleet frame — or any header too broken to read — fall
        back to a per-frame ``push_frame`` loop in arrival order, so
        bucket assignment never depends on which path ran. Emits one
        v15 ``ingest_batch`` telemetry event per call."""
        bufs = list(bufs)
        t0 = time.perf_counter()
        single_row = True
        for b in bufs:
            try:
                if wire.frame_elems(b) != self.d_shard:
                    single_row = False
                    break
            except wire.WireError:
                single_row = False
                break
        if single_row and bufs:
            results = self._red.push_frames(
                bufs, expect_plane=self.shard, expect_epoch=self.epoch
            )
            batched = True
        else:
            results = []
            for b in bufs:
                try:
                    results.append(self.push_frame(b))
                except wire.WireError as err:
                    results.append(err)
            batched = False
        rejected = 0
        nbytes = 0
        for b, r in zip(bufs, results):
            if isinstance(r, wire.WireError):
                rejected += 1
            else:
                nbytes += len(b)
        if batched:
            # push_frame accounts accepted bytes itself on the fallback.
            self.wire_bytes_in += nbytes
        if tele_hub.current() is not None:
            tele_hub.emit_event(
                "ingest_batch", shard=int(self.shard),
                frames=len(bufs), rejected=int(rejected),
                bytes=int(nbytes), batched=bool(batched),
                dur_s=round(time.perf_counter() - t0, 6),
                step=self._round,
            )
        return results

    def wire_transform(self, idx, payload):
        """``PeerExchange`` transform hook (waiter-thread ingest +
        overlap, like the unsharded streaming path); a WireError
        propagates to the exchange as the peer's stored ban evidence."""
        return self.push_frame(payload)

    def wire_batch_transform(self, items):
        """``PeerExchange`` batch_transform hook: one ``push_frames``
        pass over the whole harvested quorum (``items`` = latched
        ``(peer, frame)`` pairs), per-peer arrival-index-or-WireError
        results — the bulk twin of ``wire_transform``."""
        return self.push_frames([p for _, p in items])

    def arrived(self):
        return 0 if self._red is None else self._red._arrived

    def finish_round(self):
        """Fold the remainder; returns the (d_shard,) float32 aggregate.
        The shard's broadcast payload is exactly this span — a consumer
        reassembles spans, it never receives the full vector from any
        single shard."""
        with _trace.span("fed_shard_fold", shard=int(self.shard),
                         step=self._round):
            return self._red.finalize()

    def audit(self):
        return self._red.audit()


class FedRoundEngine:
    """S in-process shard servers + the round loop (see module doc)."""

    def __init__(self, model_vec, num_shards, sampler, *,
                 bucket_gar="krum", top_gar=None, bucket_size=None,
                 levels="auto", wave_buckets=8, lr=0.1, audit=False,
                 telemetry=False, checkpoint_dir=None, max_to_keep=3,
                 epoch=None):
        self.model = np.asarray(model_vec, np.float32).reshape(-1).copy()
        self.spec = sharding.plan_shards(self.model.size, num_shards)
        self.sampler = sampler
        self.lr = float(lr)
        self._audit = bool(audit)
        self._telemetry = bool(telemetry)
        self._shard_cfg = dict(
            bucket_gar=bucket_gar, top_gar=top_gar,
            bucket_size=bucket_size, levels=levels,
            wave_buckets=wave_buckets, audit=self._audit,
        )
        # Control plane (DESIGN.md §22): ``epoch`` arms membership-epoch
        # enforcement — every shard decodes wire frames with
        # expect_epoch, and each failover / split / merge bumps the
        # epoch (``bump_epoch``). None keeps the pre-epoch wire format
        # (committed FEDBENCH drivers send v1 frames).
        self.epoch = None if epoch is None else wire.check_epoch(epoch)
        self._ckpt_dir = (
            None if checkpoint_dir is None else str(checkpoint_dir)
        )
        self._max_to_keep = int(max_to_keep)
        self.shards = [
            self.build_shard(s) for s in range(self.spec.num_shards)
        ]
        self.round = 0
        self._active_ids = None
        self._weights = None
        self._pos = None  # global id -> cohort arrival position
        self._t0 = None
        self.last_info = None

    def build_shard(self, shard):
        """A fresh ``ShardServer`` for span ``shard`` under the current
        spec and deployment config — what __init__ composes, what a
        failover standby promotion (controlplane/failover.py) and a
        ``resize`` rebuild call."""
        return ShardServer(
            shard, self.spec, epoch=self.epoch, **self._shard_cfg
        )

    # -- round lifecycle ----------------------------------------------------

    def begin_round(self, tags=None):
        """Sample the round's cohort, compose staleness (stragglers past
        the cutoff are dropped BEFORE planning — zero-weight rows never
        reach a Gram rule), price f on the active count, arm every
        shard. Returns (active_ids, f_budget)."""
        cohort = self.sampler.cohort(self.round)
        active, w, dropped = self.sampler.cohort_weights(
            self.round, cohort, tags
        )
        if active.size < 1:
            raise ValueError(
                f"round {self.round}: staleness cutoff dropped the "
                "entire cohort"
            )
        f = self.sampler.f_budget(active.size)
        self._active_ids = active
        self._weights = w
        self._dropped = dropped
        self._pos = {int(c): i for i, c in enumerate(active.tolist())}
        for sh in self.shards:
            sh.epoch = self.epoch  # track bumps (failover/split/merge)
            sh.begin_round(self.round, active.size, f)
        self._f = f
        self._t0 = time.perf_counter()
        return active, f

    def ingest(self, client_id, vec):
        """One cohort member's full (d,) gradient: staleness-discounted
        once (host-side; weight 1.0 is a bitwise no-op per IEEE
        multiply, so fresh full-participation rounds stay on the
        unsharded path's exact bytes), then column-sliced into every
        shard's reducer. Rows must arrive in cohort order — arrival
        order IS bucket assignment, shared with the unsharded path."""
        i = self._pos[int(client_id)]
        vec = np.asarray(vec, np.float32).reshape(-1)
        if vec.size != self.spec.d:
            raise ValueError(
                f"client {client_id} gradient has {vec.size} elements, "
                f"expected {self.spec.d}"
            )
        w = float(self._weights[i])
        if w != 1.0:
            vec = (vec * np.float32(w)).astype(np.float32)
        for sh in self.shards:
            sh.push_rows(self.spec.slice_rows(vec[None, :], sh.shard))
        return i

    def ingest_rows(self, rows, *, stable=False):
        """Bulk in-order ingest of a (k, d) block of ACTIVE cohort rows
        (the bench/simulation fast path: rows generated wave-at-a-time,
        weights applied in bulk). ``stable=True`` forwards the zero-copy
        contract to every shard reducer (see ShardServer.push_rows):
        only pass it when ``rows`` stays alive and unwritten until the
        round finishes. Weighted rounds stage a fresh weighted block, so
        they are stable regardless of the caller's buffer discipline."""
        rows = np.asarray(rows, np.float32)
        k = rows.shape[0]
        first = self.shards[0].arrived()
        w = self._weights[first:first + k]
        if not np.all(w == 1.0):
            rows = rows * w[:, None]
            stable = True  # the weighted block is ours and immutable
        for sh in self.shards:
            sh.push_rows(self.spec.slice_rows(rows, sh.shard),
                         stable=stable)
        return first

    def finish_round(self, *, byz_ids=None):
        """Fold every shard, apply the model update on each span, emit
        the v10 telemetry, advance the round counter. Returns an info
        dict (round, cohort/active sizes, f budget, realized-Byzantine
        audit when ``byz_ids`` ground truth is supplied, per-shard
        latencies, wall)."""
        per_shard = {}
        agg_parts = []
        for sh in self.shards:
            t0 = time.perf_counter()
            agg = sh.finish_round()
            per_shard[str(sh.shard)] = {
                "latency_s": round(time.perf_counter() - t0, 6),
                "wire_bytes": int(sh.wire_bytes_in),
            }
            agg_parts.append(agg)
        # Per-span SGD update: each shard updates only its own columns
        # (in deployment each shard process owns its span; here the
        # spans share one buffer). float32 throughout.
        for sh, agg in zip(self.shards, agg_parts):
            lo, hi = self.spec.spans[sh.shard]
            self.model[lo:hi] = (
                self.model[lo:hi] - np.float32(self.lr) * agg
            ).astype(np.float32)
        realized = None
        exceeded = None
        if byz_ids is not None:
            realized = self.sampler.realized_byzantine(
                self._active_ids, byz_ids
            )
            exceeded = realized > self._f
        wall = time.perf_counter() - self._t0
        info = {
            "round": self.round,
            "cohort": int(self.sampler.cohort_size),
            "active": int(self._active_ids.size),
            "dropped": int(self._dropped.size),
            "f_budget": int(self._f),
            "realized_byz": realized,
            "budget_exceeded": exceeded,
            "round_s": wall,
            "per_shard": per_shard,
        }
        if self._telemetry:
            tele_hub.emit_event(
                "fed_round", step=int(self.round),
                shards=int(self.spec.num_shards),
                cohort=int(self._active_ids.size),
                f_budget=int(self._f),
                realized_byz=realized,
                budget_exceeded=exceeded,
                round_s=round(wall, 6),
                per_shard=per_shard,
            )
            if self._audit:
                # Composed per-client selection: a client is kept iff
                # EVERY shard's hierarchy kept it (selection is per
                # shard — see the module docstring), reported against
                # the stable GLOBAL ids so resampling cannot reset it.
                sel = np.ones(self._active_ids.size, np.float32)
                for sh in self.shards:
                    sel *= np.asarray(
                        sh.audit()["selected"], np.float32
                    )
                tele_hub.emit_event(
                    "cohort", step=int(self.round),
                    client_ids=[int(c) for c in self._active_ids],
                    selected=[float(s) for s in sel],
                    f_budget=int(self._f),
                )
        self.last_info = info
        if self._ckpt_dir is not None:
            self.save_checkpoint()
        self.round += 1
        return info

    # -- control plane: checkpoints, failover, membership -------------------

    def _control_dir(self):
        return os.path.join(self._ckpt_dir, "control")

    def save_checkpoint(self):
        """Checkpoint the just-finished round: one per-span checkpoint
        per shard (sharding.save_sharded — in deployment each shard
        process writes only its own span) plus one CONTROL record (round
        number, membership epoch, and the hub's per-client suspicion
        snapshot) so a failover handoff restores the span AND the
        round/suspicion state an epoch-timed attacker would love to see
        dropped (DESIGN.md §22). Called automatically from
        ``finish_round`` when ``checkpoint_dir`` is set; the step key is
        the round just finished."""
        sharding.save_sharded(
            self._ckpt_dir, self.round, self.model, self.spec,
            max_to_keep=self._max_to_keep,
        )
        hub = tele_hub.current()
        snap = hub.client_suspicion_snapshot() if hub is not None else {}
        rec = {
            "round": int(self.round),
            "epoch": None if self.epoch is None else int(self.epoch),
            "num_shards": int(self.spec.num_shards),
            "suspicion": {
                str(cid): [float(o), float(e)]
                for cid, (o, e) in snap.items()
            },
        }
        # The control record is tiny host-side metadata with
        # variable-length content — a plain JSON file with an atomic
        # replace, not a Checkpointer (orbax restore needs fixed
        # shapes), GC'd to the same history bound as the span files.
        cdir = self._control_dir()
        os.makedirs(cdir, exist_ok=True)
        path = os.path.join(cdir, f"ctl_{int(self.round)}.json")
        with open(path + ".tmp", "w") as fp:
            json.dump(rec, fp)
        os.replace(path + ".tmp", path)
        for st in self.control_steps()[: -self._max_to_keep]:
            os.remove(os.path.join(cdir, f"ctl_{st}.json"))

    def control_steps(self):
        """Sorted steps with a control record (see ``save_checkpoint``)."""
        cdir = self._control_dir()
        if not os.path.isdir(cdir):
            return []
        return sorted(
            int(n[4:-5]) for n in os.listdir(cdir)
            if n.startswith("ctl_") and n.endswith(".json")
        )

    def load_control(self, step):
        """The control record saved at ``step`` (round/epoch/suspicion)."""
        with open(os.path.join(
            self._control_dir(), f"ctl_{int(step)}.json"
        )) as fp:
            return json.load(fp)

    def resume(self, step=None):
        """Restore the newest COMPLETE checkpoint — a step every span
        AND the control record agree on (a torn save never restores
        mixed rounds) — and pin every shard to the one round it can now
        serve. Returns the restored round number R; the next
        ``begin_round`` must be for round R + 1 (any other round is the
        loud ShardServer.begin_round refusal — the resumed engine has
        no span checkpoint for it). The hub (when installed) absorbs
        the checkpointed per-client suspicion via max-merge, so a
        crash/restore cycle cannot launder exclusion history."""
        if self._ckpt_dir is None:
            raise RuntimeError("engine has no checkpoint_dir to resume from")
        complete = set(sharding.sharded_steps(self._ckpt_dir, self.spec))
        complete &= set(self.control_steps())
        if step is None:
            if not complete:
                raise FileNotFoundError(
                    f"no complete checkpoint (all {self.spec.num_shards} "
                    f"spans + control record) under {self._ckpt_dir}"
                )
            step = max(complete)
        elif int(step) not in complete:
            raise FileNotFoundError(
                f"round {step} has no complete checkpoint under "
                f"{self._ckpt_dir} (complete: {sorted(complete)})"
            )
        self.model[:] = sharding.restore_sharded(
            self._ckpt_dir, self.spec, step=int(step)
        )
        ctl = self.load_control(step)
        if int(ctl["round"]) != int(step):
            raise ValueError(
                f"control record at step {step} claims round "
                f"{ctl['round']} — torn control plane"
            )
        self.round = int(step) + 1
        if ctl.get("epoch") is not None:
            self.epoch = wire.check_epoch(int(ctl["epoch"]))
        hub = tele_hub.current()
        if hub is not None and ctl.get("suspicion"):
            hub.absorb_client_suspicion({
                int(cid): (float(o), float(e))
                for cid, (o, e) in ctl["suspicion"].items()
            })
        for sh in self.shards:
            sh.epoch = self.epoch
            sh.mark_restored(self.round)
        return int(step)

    def bump_epoch(self, action, *, shard=None):
        """Advance the membership epoch by exactly one — every
        failover, split or merge is one epoch, so a frame stamped with
        any previous epoch is attributably stale (wire expect_epoch).
        Emits the v13 ``membership`` telemetry event. No-op epoch-wise
        when epoch enforcement is off (pre-epoch deployment), but the
        event still lands so the action is visible."""
        if self.epoch is not None:
            self.epoch = wire.check_epoch(self.epoch + 1)
            for sh in self.shards:
                sh.epoch = self.epoch
        if self._telemetry:
            tele_hub.emit_event(
                "membership",
                epoch=None if self.epoch is None else int(self.epoch),
                action=str(action),
                shard=None if shard is None else int(shard),
                num_shards=int(self.spec.num_shards),
                step=int(self.round),
            )
        return self.epoch

    def resize(self, num_shards):
        """Split/merge the shard group to ``num_shards`` spans BETWEEN
        rounds (the shard autoscaler's apply half,
        controlplane/shardscale.py): re-plan the contiguous balanced
        partition, rebuild every ShardServer over the new spans, bump
        the membership epoch once. The model vector itself is
        untouched — a repartition moves span boundaries, not bytes.
        Raises (and changes nothing) when the resize is impossible:
        past the wire header's 16 shard slots, or more shards than
        parameters — callers rescind the controller action on that
        refusal (utils/autoscale.rescind)."""
        num_shards = int(num_shards)
        if num_shards == self.spec.num_shards:
            return self.spec
        grew = num_shards > self.spec.num_shards
        self.spec = sharding.plan_shards(self.model.size, num_shards)
        self.shards = [
            self.build_shard(s) for s in range(self.spec.num_shards)
        ]
        self.bump_epoch("split" if grew else "merge")
        return self.spec

"""Child process for the multi-host (DCN) integration test.

Each process is one "host" of a 2-process jax.distributed cluster (CPU
backend, 4 virtual devices per process -> global 8-device mesh). It
bootstraps through the framework's ClusterConfig/init_distributed path,
then runs the Byzantine-resilient aggregation core — per-slot gradient
rows, a lie attack, Multi-Krum — as one SPMD program whose all_gather
crosses the process boundary, and prints the (replicated) aggregate.

Usage: python multihost_child.py <config.json>
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 4)
except AttributeError:  # older jax: XLA_FLAGS (set by the parent) rules
    pass


def main(config_path):
    import numpy as np

    from garfield_tpu.utils import multihost

    cfg = multihost.ClusterConfig(config_path)
    nproc, pid = multihost.init_distributed(cfg)
    assert nproc == 2, nproc

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from garfield_tpu import aggregators
    from garfield_tpu.attacks import apply_gradient_attack
    from garfield_tpu.parallel import mesh as mesh_lib

    n, d, f = 8, 4096, int(cfg.garfield.get("fw", 2))
    gar = aggregators.gars[cfg.garfield.get("gar", "krum")]
    mesh = mesh_lib.make_mesh({"workers": n})
    byz_mask = jnp.arange(n) >= n - f

    # Per-slot gradient rows: deterministic, same on every process.
    rows = np.random.default_rng(1234).standard_normal((n, d)).astype(np.float32)
    per_host = n // nproc
    x = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("workers")),
        rows[pid * per_host : (pid + 1) * per_host],  # this host's slots
    )

    def step(local_rows):
        stack = jax.lax.all_gather(local_rows, "workers", tiled=True)
        stack = apply_gradient_attack(
            "lie", stack, byz_mask, key=jax.random.PRNGKey(0)
        )
        return gar.unchecked(stack, f=f)

    aggr = jax.jit(
        mesh_lib.shard_map(
            step, mesh=mesh, in_specs=P("workers"), out_specs=P(),
            check_vma=False,
        )
    )(x)
    out = np.asarray(jax.device_get(aggr))
    print(f"AGG {pid} {float(out.sum()):.6f} {float(np.abs(out).max()):.6f}",
          flush=True)

    # Host-level wait-n-f exchange (T1/T2/T9 live path): publish this host's
    # serialized aggregate over TCP, block on the native MRMW register for
    # the peer's, and verify both hosts hold the identical replicated result
    # — the DCN analog of ByzSGD's model gather (server.py:161-184).
    ex_hosts = cfg.garfield.get("exchange")
    if ex_hosts:
        from garfield_tpu.utils.exchange import PeerExchange

        with PeerExchange(pid, ex_hosts) as ex:
            ex.publish(0, out.tobytes())
            got = ex.collect(0, q=len(ex_hosts), timeout_ms=60_000)
        peers_equal = all(
            np.array_equal(np.frombuffer(p, np.float32), out)
            for p in got.values()
        )
        print(f"EXCHANGE {pid} ok={peers_equal} n={len(got)}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1])

"""NaN-resilient coordinate-wise median GAR.

Counterpart of pytorch_impl/libs/aggregators/median.py (aggregate :39 =
``torch.stack(g).median(dim=0)[0]``, upper_bound 1/sqrt(n-f) :62-71). The
lower-median + NaN-sorts-last semantics are preserved (see
_common.coordinate_median). Sort-based median is the right TPU form: one
XLA sort along the small axis, no host round-trip (reference needed a CUDA
kernel, median.cu).
"""

import math

from . import register
from ._common import (
    as_stack, coordinate_median, num_gradients, tree_coordinatewise,
)


def aggregate(gradients, **kwargs):
    """NaN-resilient coordinate-wise (lower) median."""
    return coordinate_median(as_stack(gradients))


def tree_aggregate(stacked_tree, key=None, **kwargs):
    """Tree-mode twin (r3): the median is coordinate-wise, so it decomposes
    per leaf — the (n, d) flat stack (flatten + unflatten + its DUS
    staging) is never built. Measured on the v5e chip: the 8-worker
    ResNet-18 aggregathor step under lie drops 21.3 -> 16.2 ms/step
    (PERF.md); the per-leaf Pallas launches cost less than the flat-stack
    plumbing they replace."""
    return tree_coordinatewise(coordinate_median, stacked_tree)


def tree_aggregate_ext(ext_tree, row_map, row_scale, key=None, **kwargs):
    """Folded-attack twin (parallel/fold.py): per-leaf median over the
    EXTENDED stacked tree with the attack's static row remap applied
    in-register by the Pallas kernel — no poisoned stack, no moment
    passes."""
    from .. import ops

    return tree_coordinatewise(
        lambda g: ops.coordinate_median(
            g, row_map=row_map, row_scale=row_scale
        ),
        ext_tree,
    )


def check(gradients, **kwargs):
    if num_gradients(gradients) < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    return None


def upper_bound(n, f, d):
    """Variance/norm ratio bound 1/sqrt(n-f) (median.py:62-71)."""
    return 1 / math.sqrt(n - f)


register("median", aggregate, check, upper_bound=upper_bound,
         tree_aggregate=tree_aggregate, tree_aggregate_ext=tree_aggregate_ext)

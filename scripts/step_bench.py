"""Window-proof headline step benchmark (VERDICT r4 weak #1 / #3).

Measures the BASELINE.md rule x attack rows in ONE process, interleaving
every configuration with the fault-free floor (average/f0) in ABAB rounds,
and reports each row as BOTH an absolute ms/step and a RATIO to the
same-round floor — the ratio survives the shared chip's co-tenant windows
(measured 49.5 vs 81.5 steps/s within one hour), absolute numbers from
different windows do not. Run:

    cd /root/repo && python scripts/step_bench.py --json STEPBENCH.json

North-star shape: ResNet-18/CIFAR-10, 8 workers x batch 25, bf16 pipeline
(the bench.py config; Aggregathor/trainer.py:231-249 is the step being
measured).
"""

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, ".")

from garfield_tpu import models  # noqa: E402
from garfield_tpu.parallel import aggregathor  # noqa: E402
from garfield_tpu.utils import profiling, selectors  # noqa: E402

ROWS = [
    ("average", None, 0),
    ("krum", None, 2),
    ("krum", "lie", 2),
    ("median", "lie", 2),
    ("tmean", "lie", 2),
    ("bulyan", "lie", 1),
    ("cclip", "lie", 2),
    ("cclip", None, 2),
]


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--json", type=str, default=None)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--reps", type=int, default=40)
    args = p.parse_args(argv)

    profiling.enable_compile_cache()
    N, B = 8, 25
    module = models.select_model("resnet18", "cifar10", dtype=jnp.bfloat16)
    loss_fn = selectors.select_loss("nll")
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((N, B, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (N, B)), jnp.int32)

    def build(gar, attack, f):
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss_fn, optax.sgd(0.1), gar,
            num_workers=N, f=f, attack=attack, gar_dtype=jnp.bfloat16,
        )
        box = [init_fn(jax.random.PRNGKey(5), x[0])]
        box[0], m = step_fn(box[0], x, y)
        jax.block_until_ready(box[0].step)

        def run(reps):
            t0 = time.time()
            for _ in range(reps):
                box[0], m = step_fn(box[0], x, y)
            float(jnp.asarray(m["loss"]).sum())
            return time.time() - t0

        return run

    floor_run = build("average", None, 0)
    runs = {
        (g, a, f): build(g, a, f) for (g, a, f) in ROWS if g != "average"
    }
    results = {key: [] for key in [("average", None, 0), *runs]}
    floors = []
    for rnd in range(args.rounds):
        # Interleave: floor first, then every row, so each row has a
        # same-round floor to ratio against.
        fl = profiling.paired_reps(floor_run, args.reps, pairs=2)
        floors.append(fl)
        results[("average", None, 0)].append(fl)
        for key, run in runs.items():
            ms = profiling.paired_reps(run, args.reps, pairs=2)
            results[key].append(ms)
            g, a, f = key
            print(
                f"round {rnd} {g}+{a or 'none'}/f{f}: "
                + (f"{ms*1e3:.2f} ms ({1/ms:.1f}/s), "
                   f"ratio {ms/fl:.3f}x floor" if ms and fl else "n/a"),
                flush=True,
            )
    out = []
    for (g, a, f), vals in results.items():
        vals = [v for v in vals if v]
        if not vals:
            continue
        best = min(vals)
        ratios = [
            v / fl for v, fl in zip(results[(g, a, f)], floors)
            if v and fl
        ]
        out.append({
            "gar": g, "attack": a, "f": f,
            "ms_per_step_best": round(best * 1e3, 2),
            "steps_per_s_best": round(1 / best, 1),
            "ratio_vs_floor_median": (
                round(float(np.median(ratios)), 3) if ratios else None
            ),
        })
    for row in out:
        print(json.dumps(row), flush=True)
    if args.json:
        with open(args.json, "w") as fp:
            json.dump(out, fp, indent=1)
    return out


if __name__ == "__main__":
    main(sys.argv[1:])

"""LEARN topology: fully decentralized Byzantine-resilient collaborative
learning (every node is Worker + Server).

TPU-native re-design of ``pytorch_impl/applications/LEARN/trainer.py``
(node loop :224-257, ``avg_agree`` gossip :208-222): n peer nodes each hold
their own model and data shard; per step each node

    1. computes its own gradient                       (trainer.py:233-236)
    2. gathers everyone's gradients and aggregates     (:237-241)
    3. (non-iid) repeats ceil(log2 t) "agreement" rounds, re-gathering the
       peers' *aggregated* gradients and re-aggregating (:208-222, :251-252)
    4. applies its optimizer                            (:247-249)
    5. gossips models: gathers peer models, GAR-aggregates, writes back
                                                        (:255-257)

SPMD mapping (SURVEY §2.3 "Decentralized P2P" row): one "nodes" mesh axis;
model/optimizer state is stacked over it; every get_aggr_grads/get_models RPC
poll (server.py:202-233) becomes one all_gather. Byzantine nodes inject
gradient attacks (byzWorker.py) in phases 1-3 and model attacks
(byzServer.py) in phase 5 — value transforms on their rows of the gathered
stacks.

Wait-n-f semantics: the reference's LEARN never waits for everyone — each
node takes the *fastest* ``n - f`` peer responses at every exchange
(``ps.get_gradients(i, n-f)`` trainer.py:249, ``get_models(n-f)`` :255, and
``avg_agree``'s ``num_wait_ps`` :208-222). Arrival order is effectively
random, so the bulk-synchronous stand-in is a per-node seeded subset
(``core.subset_indices``, same pattern as byzsgd's per-PS subsets): each
node aggregates its OWN q-subset of the gathered stack. That is exactly why
honest nodes hold *different* aggregates — the disagreement the ceil(log2 t)
agreement rounds exist to reconcile (and without which they would be vacuous
re-aggregations of one vector).

The ceil(log2 t) round count is data-dependent on the step counter, so the
gossip loop is a ``lax.fori_loop`` over a static ``max_rounds`` with rounds
beyond the target masked to no-ops (XLA needs static trip structure).

Fast-path parity with aggregathor (the dispatch matrix that topology got in
r4-r5, ported here): both gradient exchanges (phase 2 and every agreement
round) AND the model gossip dispatch through the tree/fold stack when
eligible —

  - deterministic attacks (lie/empire/reverse/crash; byzServer's
    reverse/crash on the model plane) fold into a Gram remap
    (``fold.plan_for`` / ``fold.plan_for_model``): the poisoned rows are
    never written and the raw per-leaf Grams fuse like the fault-free step;
  - randomized attacks (random/drop) poison the stacked TREE via the
    where-path (``apply_gradient_attack_tree``) and the GAR still runs in
    tree mode — the (n, d) flat stack is never built;
  - per-node wait-n-f subsets COMPOSE with the fold for Gram-form rules:
    one extension + Gram build serves every local node slot, each adding
    only a (q, q) sub-Gram selection (``fold.folded_tree_aggregate_multi``
    — the multi-observer form of aggregathor's subset fast path); non-Gram
    rules under true subsets keep the flat path (the same
    ``_tree_path_ok`` gate as aggregathor/byzsgd);
  - stateful-center rules (cclip) carry a PER-NODE center in
    ``TrainState.gar_state``: v_0 of phase 2 is the node's previous final
    aggregate (robust coordinate-median init at step 0 only, under a
    ``lax.cond`` so the median pass executes exactly once per run), each
    agreement round re-centers on the node's current aggregate, and the
    model gossip centers on the node's OWN model — the ClippedGossip
    recipe (Karimireddy et al. 2021) — so the per-step median init
    (~5.3 ms at ResNet-18 scale, PERF.md r5) disappears from the
    decentralized defense config.

``tree_path=False`` forces the flat reference-shaped path everywhere (the
A/B lever the trajectory-equivalence tests drive).
"""

import functools
import math

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..attacks import (
    adaptive as adaptive_lib,
    apply_gradient_attack,
    apply_gradient_attack_tree,
    apply_model_attack_rows,
    model_attacks,
    model_collusion_attacks,
    note_attack_fallback,
)
from ..telemetry import taps as taps_lib
from . import core, fold, mesh as mesh_lib
from .aggregathor import _check_gar, _resolve_gar, _tree_path_ok

__all__ = ["make_trainer"]


def make_trainer(
    module,
    loss_fn,
    optimizer,
    gar,
    *,
    num_nodes,
    f=0,
    attack=None,
    attack_params=None,
    model_attack=None,
    model_attack_params=None,
    byz_mask=None,
    mesh=None,
    axis="nodes",
    non_iid=False,
    max_rounds=12,
    model_gossip=True,
    subset=None,
    track_spread=False,
    gar_dtype=None,
    worker_momentum=None,
    gar_params=None,
    tree_path=True,
    num_iter=None,
    telemetry=False,
    staleness=None,
    defense=None,
):
    """Build ``(init_fn, step_fn, eval_fn)`` for the LEARN topology.

    ``model_attack`` additionally accepts the model-plane COLLUSION
    attacks (``lie``/``empire`` over the gossiped stack, DESIGN.md §17)
    and their ADAPTIVE controllers (``adaptive-lie``/``adaptive-empire``):
    the gossip-poisoning magnitude becomes a bisection bracket carried in
    ``TrainState.attack_state``, fed back each step by whether the
    Byzantine nodes' gossiped models entered the model aggregation's
    selection — the decentralized twin of byzsgd's Byzantine-PS
    controller, attacking LEARN's plane-2 gossip.

    ``defense`` (aggregators/defense.py) deploys suspicion weighting on
    ALL THREE exchange phases: a dict with ``power``/``floor``/
    ``halflife`` enables a per-node exclusion EMA carried in
    ``TrainState.defense_state`` (fed by the phase-2 observer-mean
    selection — node identity is shared across the planes, so one
    history serves the gradient gather, every agreement round AND the
    model gossip), mapped through ``defense.suspicion_weights`` and
    composed into the SAME row-weight algebra as the per-phase staleness
    discount (fold ``row_weights`` on Gram rules, explicit row scaling
    elsewhere). ``defense=None`` (default) traces nothing — trajectories
    are bitwise the undefended ones. Rule escalation lives above the
    trainer (apps/common.py), which rebuilds the step at level changes.

    ``telemetry`` adds ``metrics["tap"]`` — the phase-2 gradient
    exchange's ``TapBundle`` (telemetry/taps.py). Under per-node
    wait-n-f subsets the exported bundle is the OBSERVER MEAN across all
    n nodes' views: ``observed`` is the fraction of nodes whose quorum
    contained the rank, ``selected`` the mean influence its gradient
    earned. Agreement rounds and the model gossip are not tapped (the
    phase-2 selection is the per-rank audit signal). Off by default:
    nothing tap-shaped is traced, and taps never enter TrainState —
    taps-on trajectories are bitwise equal to taps-off.

    ``non_iid=True`` enables the ceil(log2 t) agreement rounds
    (LEARN/trainer.py:251-252 runs them only for non-iid data); ``max_rounds``
    caps them (2^12 = 4096 steps of exact parity by default).
    ``subset=q`` enables wait-n-f: every node aggregates its own seeded
    q-subset of the gathered gradients / agreement aggregates / gossiped
    models, the stand-in for taking the q = n - f *fastest* peer responses
    (LEARN/trainer.py:249, :255, avg_agree :208-222). With it, honest nodes
    hold genuinely different aggregates between agreement rounds.
    ``track_spread=True`` adds ``aggr_spread_pre`` / ``aggr_spread_post``
    metrics — the max pairwise L-inf distance between honest nodes'
    aggregates before and after the agreement rounds (costs one extra
    (n, d) all_gather; leave off in production).
    ``gar_dtype`` narrows the gradient pipeline (cast at the backward
    epilogue; gathers, attacks, aggregation and agreement rounds run at
    the narrow width; cast back at the optimizer boundary) — aggregathor's
    flag, applied to LEARN's phases 2-4. Model gossip stays full width.
    ``worker_momentum`` (beta in [0, 1)): each node publishes the EMA of
    its OWN gradients instead of the raw gradient — the decentralized form
    of Karimireddy et al. 2021 (their ClippedGossip follow-up pairs exactly
    this with clipped aggregation; use ``gar="cclip"``). The per-node
    momentum stack lives in ``TrainState.worker_mom``, sharded over the
    nodes axis with the rest of the node state. Pair with a plain-SGD
    optimizer (see aggregathor.make_trainer — the EMA is the momentum).
    ``tree_path`` (default on) routes every exchange through the tree/fold
    fast path where eligible (see module docstring); False forces the flat
    (n, d) path everywhere (A/B tests).
    ``num_iter`` is the run-length hint for the unroll-vs-vmap per-slot
    gradient decision (``core.slot_path_decision``; the slot-FUSED twin is
    structurally inapplicable here — per-node params mean there is no
    single shared kernel for the fused forward to use).
    ``staleness`` is the in-graph EMULATION of the host plane's
    bounded-staleness async mode on the decentralized topology
    (DESIGN.md §15) — the asynchrony analog of the seeded ``subset``
    emulation, now PER PHASE: a dict with ``max_staleness`` (hard
    cutoff, rounds), ``decay`` (geometric discount), and optional
    ``taus`` (a FIXED per-node staleness assignment). Each exchange
    PHASE — the phase-2 gradient gather, every agreement round, and the
    phase-5 model gossip — draws its own seeded per-node staleness
    (fixed ``taus`` apply to every phase) and scales the gathered rows
    by ``utils.rounds.staleness_weights`` before the rule, composed into
    the folded-attack row scales on Gram-form rules
    (``fold.folded_tree_aggregate_multi`` ``row_weights``) so the fast
    path survives; non-Gram rules route to the flat path, which weights
    rows explicitly. At ``max_staleness=0`` (or all-zero ``taus``) the
    machinery is dropped at build time and trajectories are BITWISE the
    synchronous ones (tests/test_staleness.py).
    ``step_fn(state, x, y)``: leading ``num_nodes`` axis on x/y and on every
    params/opt_state leaf, all sharded over ``axis``.
    """
    gar = _resolve_gar(gar)
    attack_params = dict(attack_params or {})
    gar_params = dict(gar_params or {})
    model_attack_params = dict(model_attack_params or {})
    if gar.stateful_center and "center" in gar_params:
        raise ValueError(
            f"{gar.name!r} carries its center across steps "
            "(TrainState.gar_state); a fixed gar_params 'center' would "
            "silently fight the carried state — remove it (standalone "
            "gars[...](stack, center=...) calls still accept one)"
        )
    if mesh is None:
        mesh = mesh_lib.make_mesh({axis: -1})
    per_n = mesh_lib.fold(num_nodes, mesh.shape[axis], "nodes")
    if subset is not None and not (1 <= subset <= num_nodes):
        raise ValueError(f"subset must be in [1, {num_nodes}], got {subset}")
    # The GAR sees `subset` rows when waiting (reference passes the n-f
    # received gradients straight to the rule, LEARN/trainer.py:241).
    _check_gar(gar, subset if subset else num_nodes, f)
    if worker_momentum is not None and not (0.0 <= worker_momentum < 1.0):
        raise ValueError(
            f"worker_momentum must be in [0, 1), got {worker_momentum}"
        )
    from ..attacks import targeted as targeted_lib

    if targeted_lib.is_targeted(attack):
        raise ValueError(
            f"targeted attack {attack!r} poisons worker BATCHES and is "
            "deployed on the aggregathor topology in-graph (and on real "
            "cluster workers/nodes via apps/cluster.py); the LEARN "
            "in-graph twin does not support it"
        )
    # Adaptive GOSSIP poisoner (DESIGN.md §17): resolve the controller,
    # keep the base collusion attack; the magnitude comes from the
    # carried bracket each step.
    model_adaptive_cfg = None
    if adaptive_lib.is_adaptive(model_attack):
        if not model_gossip:
            raise ValueError(
                "adaptive gossip attacks poison the phase-5 model gossip; "
                "model_gossip=False leaves them nothing to attack"
            )
        if byz_mask is not None:
            raise ValueError(
                "adaptive gossip attacks derive their own Byzantine pool "
                'from model_attack_params ("f_pool"/"pool"); an explicit '
                "byz_mask would silently fight the rotation schedule"
            )
        model_adaptive_cfg = adaptive_lib.configure(
            model_attack, model_attack_params, num_workers=num_nodes, f=f
        )
        model_attack = model_adaptive_cfg.base
        model_attack_params = adaptive_lib.base_params(model_attack_params)
        byz_mask = model_adaptive_cfg.pool_mask()
    if (model_attack is not None and model_attack != "none"
            and model_attack not in model_attacks
            and model_attack not in model_collusion_attacks):
        raise ValueError(f"unknown model attack {model_attack!r}")
    # Closed-loop defense (see docstring): normalized knobs, the
    # aggregathor convention.
    d_power = d_floor = d_decay = None
    if defense is not None:
        from ..aggregators import defense as defense_lib

        dd = dict(defense)
        d_power = float(dd.pop("power", 2.0))
        d_floor = float(dd.pop("floor", 0.1))
        halflife = float(dd.pop("halflife", 16.0))
        if dd:
            raise ValueError(f"unknown defense keys {sorted(dd)}")
        if halflife <= 0.0:
            raise ValueError(f"defense halflife must be > 0, got {halflife}")
        d_decay = float(0.5 ** (1.0 / halflife))
        defense_lib.suspicion_weights([0.0], power=d_power, floor=d_floor)
    if byz_mask is None:
        byz_mask = core.default_byz_mask(
            num_nodes, f if (attack or model_attack) else 0
        )
    # Folded plans (static): the gradient plan serves phase 2 AND every
    # agreement round; the model plan serves the gossip. None -> where-path.
    fold_plan = fold.plan_for(gar, attack, byz_mask, attack_params)
    model_fold_plan = fold.plan_for_model(
        gar, model_attack, byz_mask, model_attack_params
    )
    byz_mask = jnp.asarray(byz_mask, bool)

    waiting = subset is not None and subset < num_nodes
    # Gradient-exchange eligibility: the aggregathor/byzsgd gate, with the
    # sub-Gram subset composition enabled (multi-observer form).
    grad_tree_ok = _tree_path_ok(
        tree_path, subset, num_nodes, "model", gar, subset_gram_ok=True
    )
    # Model-gossip eligibility: randomized MODEL attacks have no tree
    # where-path (their draws are defined on the flat model vector), so the
    # tree route additionally needs the attack to fold (or be absent).
    gossip_tree_ok = grad_tree_ok and (
        model_attack in (None, "none") or model_fold_plan is not None
    )
    if model_adaptive_cfg is not None:
        # The traced-magnitude collusion fake is stack-level (flat gossip
        # path only) — reported once so benches attribute the path.
        note_attack_fallback(
            f"adaptive-{model_adaptive_cfg.base}", path="where",
            why="model-plane collusion poisons the flat gossip stack",
        )
    if defense is not None and gar.gram_select is None:
        # Suspicion weights are row weights: they compose with the tree
        # route only through the Gram algebra — non-Gram rules take the
        # flat path, which weights rows explicitly (the staleness rule).
        grad_tree_ok = False
        gossip_tree_ok = False

    # Bounded-staleness emulation (see docstring). Normalized at build so
    # trivially-synchronous configs drop the machinery entirely — the step
    # program is then literally the synchronous one (the bitwise half of
    # the --max_staleness 0 contract, like aggregathor's normalization).
    stale_ms = stale_decay = stale_weights_static = None
    if staleness is not None:
        import numpy as np

        from ..utils import rounds as rounds_lib

        st = dict(staleness)
        stale_ms = int(st.pop(
            "max_staleness", rounds_lib.DEFAULT_MAX_STALENESS
        ))
        stale_decay = float(st.pop("decay", rounds_lib.DEFAULT_DECAY))
        taus = st.pop("taus", None)
        if st:
            raise ValueError(f"unknown staleness keys {sorted(st)}")
        rounds_lib.StalenessPolicy(stale_ms, stale_decay)  # validate
        if stale_ms == 0:
            staleness = None  # all weights exactly 1: synchronous program
        elif taus is not None:
            taus = np.clip(np.asarray(taus, np.int64), 0, stale_ms)
            if taus.shape != (num_nodes,):
                raise ValueError(
                    f"staleness taus must have shape ({num_nodes},), "
                    f"got {taus.shape}"
                )
            stale_weights_static = rounds_lib.staleness_weights(
                taus, decay=stale_decay, max_staleness=stale_ms
            )
            if np.all(stale_weights_static == 1.0):
                staleness = None  # all-fresh schedule: same program
        if staleness is not None and gar.gram_select is None:
            # Row weights compose with the tree route only through the
            # Gram algebra (fold row_weights); coordinate/iterative rules
            # consume row values — route every exchange to the flat path,
            # which weights the rows explicitly (the aggregathor rule).
            grad_tree_ok = False
            gossip_tree_ok = False
        if staleness is not None:
            stale_weights_fn = rounds_lib.staleness_weights

    init_worker, grad_fn, eval_apply = core.make_worker_fns(module, loss_fn)
    # Per-slot gradient formulation (VERDICT r5 #3): LEARN consults the
    # SAME registry front-end as aggregathor/byzsgd, declaring its
    # per-node DISTINCT params (shared_params=False) — the twin's fused
    # primal uses ONE shared kernel, so resolve_slot_grad_fn returns None
    # today and the run-length-aware unroll-vs-vmap choice applies; if a
    # stacked-params twin formulation ever lands, LEARN picks it up here
    # with no further change.
    slot_fused_fn = core.resolve_slot_grad_fn(
        module, loss_fn, per_n, shared_params=False
    )
    slot_path, slot_why = core.slot_path_decision(
        per_n, num_iter, fused_available=slot_fused_fn is not None
    )
    if per_n > 1:
        from ..utils import tools

        tools.info(f"[learn] per-slot gradients: {slot_path} ({slot_why})")
    unroll_grads = slot_path == "unroll"
    node_sharding = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())

    def init_fn(key, example_x, seed_rng=None):
        params, model_state = init_worker(key, example_x)
        opt_state = optimizer.init(params)
        stack = lambda tree: jax.tree.map(
            lambda l: jnp.broadcast_to(l[None], (num_nodes,) + l.shape), tree
        )
        worker_mom = None
        if worker_momentum is not None:
            worker_mom = jax.device_put(
                core.worker_mom_init(params, num_nodes, gar_dtype),
                node_sharding,
            )
        gar_state = None
        if gar.stateful_center:
            # Per-NODE carried center (v_0 = that node's previous final
            # aggregate, f32). The zeros here are never consumed: step 0
            # takes the robust-median-init branch of the lax.cond below.
            gar_state = jax.device_put(
                jax.tree.map(
                    lambda p: jnp.zeros((num_nodes,) + p.shape, jnp.float32),
                    params,
                ),
                node_sharding,
            )
        attack_state = None
        if model_adaptive_cfg is not None:
            # The gossip-magnitude bisection bracket starts wide open.
            attack_state = jax.device_put(
                adaptive_lib.init_state(model_adaptive_cfg), repl
            )
        defense_state = None
        if defense is not None:
            # Carried per-node exclusion EMA: clean history, weights 1.0.
            defense_state = jax.device_put({
                "obs": jnp.zeros((num_nodes,), jnp.float32),
                "exc": jnp.zeros((num_nodes,), jnp.float32),
            }, repl)
        return core.TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32), repl),
            params=jax.device_put(stack(params), node_sharding),
            model_state=jax.device_put(model_state, repl),
            opt_state=jax.device_put(stack(opt_state), node_sharding),
            rng=jax.device_put(key if seed_rng is None else seed_rng, repl),
            worker_mom=worker_mom,
            gar_state=gar_state,
            attack_state=attack_state,
            defense_state=defense_state,
        )

    def _local_step(state, x_local, y_local):
        base = jax.random.fold_in(state.rng, state.step)
        (atk_key, gossip_key, matk_key, drop_base,
         sub_key, msub_key) = jax.random.split(base, 6)
        shard = jax.lax.axis_index(axis)
        node_ids = shard * per_n + jnp.arange(per_n)

        def stale_w_for(phase_id):
            """Per-PHASE bounded-staleness weights (emulation; see the
            make_trainer docstring): the fixed ``taus`` schedule, or a
            seeded per-phase draw — each exchange phase (gradients, every
            agreement round, gossip) samples its own per-node staleness,
            like the host plane's per-plane gathers. fold_in-derived (NOT
            an extra split) so synchronous configs' key derivation — and
            every pinned trajectory — is untouched."""
            if staleness is None:
                return None
            if stale_weights_static is not None:
                return jnp.asarray(stale_weights_static)
            taus = jax.random.randint(
                jax.random.fold_in(
                    jax.random.fold_in(base, 0x57A1E), phase_id
                ),
                (num_nodes,), 0, stale_ms + 1,
            )
            return stale_weights_fn(
                taus, decay=stale_decay, max_staleness=stale_ms
            )

        def weight_rows(stack, w):
            """Flat-path staleness application: rows scaled after the
            attack, before subsets/aggregation — per-row weights commute
            with row selection, so each observer's subset sees exactly
            its members' discounts (the host-plane order)."""
            if w is None:
                return stack
            return (stack * w[:, None]).astype(stack.dtype)

        # Closed-loop defense weights (DESIGN.md §16/§17): per-node
        # suspicion from the carried exclusion EMA; exactly 1.0 on a
        # clean history. ONE history serves all three phases — node
        # identity is shared across the planes.
        def_w = None
        if defense is not None:
            susp = state.defense_state["exc"] / jnp.maximum(
                state.defense_state["obs"], 1e-6
            )
            def_w = defense_lib.suspicion_weights(
                susp, power=d_power, floor=d_floor
            )

        def row_w_for(phase_id):
            """Per-phase composed row weights: the bounded-staleness
            discount times the defense's suspicion weight — the shared
            row-scale algebra, so both ride the same fold/flat paths."""
            w = stale_w_for(phase_id)
            if def_w is None:
                return w
            return def_w if w is None else (
                (w * def_w).astype(jnp.float32)
            )

        # Adaptive GOSSIP controller (DESIGN.md §17): the collusion
        # magnitude played on the plane-2 model gossip is the carried
        # bracket's midpoint; rotation picks this round's active nodes.
        act_mask_m = byz_mask
        eff_m_params = model_attack_params
        m_mag = None
        m_lo = m_hi = None
        if model_adaptive_cfg is not None:
            m_lo = state.attack_state["lo"]
            m_hi = state.attack_state["hi"]
            m_mag = adaptive_lib.played_magnitude(m_lo, m_hi)
            act_mask_m = adaptive_lib.active_mask_traced(
                model_adaptive_cfg, state.step
            )
            eff_m_params = dict(model_attack_params)
            eff_m_params[
                adaptive_lib.magnitude_key(model_adaptive_cfg.base)
            ] = m_mag

        def node_subset_keys(key):
            """Per-node (sel, gar_key) for one exchange — the SAME key
            derivation as the flat path's ``node_aggregate`` (keyed by the
            global node id), so tree and flat trajectories sample identical
            wait-n-f subsets."""

            def one(nid):
                sel_key, gkey = jax.random.split(jax.random.fold_in(key, nid))
                return core.subset_indices(sel_key, num_nodes, subset), gkey

            return jax.vmap(one)(node_ids)

        def node_aggregate(stack, key, nid, center=None):
            """One node's view of an exchange: its own seeded arrival subset
            (the q fastest peers), then the GAR. Keyed by the global node id
            so every shard agrees on what node ``nid`` sampled."""
            sel_key, gkey = jax.random.split(jax.random.fold_in(key, nid))
            if waiting:
                sel = core.subset_indices(sel_key, stack.shape[0], subset)
                stack = stack[sel]
            extra = {} if center is None else {"center": center}
            return gar.unchecked(stack, f=f, key=gkey, **gar_params, **extra)

        def local_aggregates(stack, key, centers=None):
            """All of this shard's node slots aggregate the same gathered
            (n, d) stack through their own subsets -> (per_n, d). vmapped
            over the node ids (one subset+GAR graph regardless of per_n,
            the same shape as byzsgd's vmapped per-PS slot step).
            ``centers``: optional (per_n, d) per-node carried centers
            (stateful rules)."""
            if waiting:
                if centers is None:
                    return jax.vmap(
                        lambda nid: node_aggregate(stack, key, nid)
                    )(node_ids)
                return jax.vmap(
                    lambda nid, c: node_aggregate(stack, key, nid, c)
                )(node_ids, centers)
            # Full participation: one aggregate, identical for every node
            # (and identical carried centers, so slot 0's suffices).
            extra = {} if centers is None else {"center": centers[0]}
            one = gar.unchecked(stack, f=f, key=key, **gar_params, **extra)
            return jnp.broadcast_to(one[None], (per_n,) + one.shape)

        def tree_exchange(stacked_tree, plan, akey, key, attack_name,
                          attack_kw, center_tree=None, row_weights=None):
            """One exchange on the stacked TREE: folded deterministic
            attacks poison the Gram (never the rows); randomized attacks
            take the tree where-path first; per-node subsets compose onto
            the sub-Gram. Returns the per-node aggregates as a tree with a
            leading per_n axis. ``center_tree``: per-node carried centers
            (leading per_n axis) for stateful rules — consumed on the
            full-participation route only (the subset route is Gram-form,
            stateless). ``row_weights``: the bounded-staleness discount,
            composed into the Gram row-scale algebra (the tree route is
            gated to gram_select rules when weights are active)."""
            if plan is None and attack_name not in (None, "none"):
                stacked_tree = apply_gradient_attack_tree(
                    attack_name, stacked_tree, byz_mask, key=akey,
                    **attack_kw,
                )
            if waiting:
                sels, gkeys = node_subset_keys(key)
                return fold.folded_tree_aggregate_multi(
                    gar, plan, stacked_tree, f=f, keys=gkeys,
                    gar_params=gar_params, subset_sels=sels,
                    row_weights=row_weights,
                )
            if row_weights is not None:
                # Weighted full participation: one observer view through
                # the multi form (it accepts plan None AND composes the
                # weights into the Gram; with neither subsets nor keys it
                # returns the single selection WITHOUT a leading axis),
                # broadcast to the local slots — gram_select rules are
                # stateless, so center_tree never reaches this route.
                one = fold.folded_tree_aggregate_multi(
                    gar, plan, stacked_tree, f=f,
                    gar_params=gar_params, row_weights=row_weights,
                )
                return jax.tree.map(
                    lambda l: jnp.broadcast_to(
                        l[None], (per_n,) + l.shape
                    ),
                    one,
                )
            center_kw = {}
            if center_tree is not None:
                # Full participation: every node's carried center is equal
                # (identical aggregates every step) — use slot 0's.
                center_kw = {
                    "center": jax.tree.map(lambda l: l[0], center_tree)
                }
            if plan is not None:
                one = fold.folded_tree_aggregate(
                    gar, plan, stacked_tree, f=f, key=key,
                    gar_params={**gar_params, **center_kw},
                )
            else:
                one = gar.tree_aggregate(
                    stacked_tree, f=f, key=key, **gar_params, **center_kw
                )
            return jax.tree.map(
                lambda l: jnp.broadcast_to(l[None], (per_n,) + l.shape), one
            )

        def honest_spread(aggr_rows):
            """Max pairwise L-inf distance between honest nodes' aggregates:
            the disagreement the agreement rounds must shrink."""
            rows = jax.lax.all_gather(aggr_rows, axis, tiled=True)  # (n, d)
            byz = byz_mask[:, None]
            hi = jnp.max(jnp.where(byz, -jnp.inf, rows), axis=0)
            lo = jnp.min(jnp.where(byz, jnp.inf, rows), axis=0)
            return jnp.max(hi - lo)

        def aggr_rows_of(aggr):
            """(per_n, d) flat rows of the per-node aggregates, whichever
            representation the dispatch produced (spread metric only)."""
            return core.flatten_rows(aggr) if grad_tree_ok else aggr

        # Phase 1: per-node gradient on its own model + batch. Unrolled over
        # the static local slots below the slot_path_decision cap (vmapping
        # params over nodes trips conv batching rules at small n; keep the
        # stacked TREE through the gather and flatten once afterwards —
        # raveling each slot inside the unroll serializes the per-slot
        # concats against fwd+bwd, measured 12% slower in aggregathor;
        # core.per_slot_grads docstring). Above the cap (or when the run
        # length cannot amortize the unroll's compile premium) the per-node
        # gradients vmap with params mapped over the node axis.
        if unroll_grads:
            grads, losses_list, ms_list = [], [], []
            for k in range(per_n):
                p_k = jax.tree.map(lambda l: l[k], state.params)
                rng_k = jax.random.fold_in(drop_base, node_ids[k])
                g, (loss, ms_out) = grad_fn(
                    p_k, state.model_state, x_local[k], y_local[k], rng_k
                )
                grads.append(g)
                losses_list.append(loss)
                ms_list.append(ms_out)
            grads_local = jax.tree.map(lambda *ls: jnp.stack(ls), *grads)
            losses = jnp.stack(losses_list)
            ms_stack = jax.tree.map(lambda *ls: jnp.stack(ls), *ms_list)
        else:
            rngs = jax.vmap(
                lambda i: jax.random.fold_in(drop_base, i)
            )(node_ids)
            grads_local, (losses, ms_stack) = jax.vmap(
                grad_fn, in_axes=(0, None, 0, 0, 0)
            )(state.params, state.model_state, x_local, y_local, rngs)
        grads_local = core.cast_leaves(grads_local, gar_dtype)

        # Per-node momentum (see make_trainer docstring): each node
        # publishes its EMA; the honest update is stored (sharded with the
        # node state), Byzantine rows are re-poisoned after the gather.
        new_mom = state.worker_mom
        if worker_momentum is not None:
            grads_local = core.worker_mom_update(
                worker_momentum, state.worker_mom, grads_local
            )
            new_mom = grads_local
        new_ms = core.mean_model_state(ms_stack, axis)

        # Phase 2: gather + attack + aggregate (= get_gradients(i, n-f) of
        # the fastest peers, LEARN/trainer.py:249; per-node subsets). The
        # carried center (stateful rules) is each node's previous final
        # aggregate; at step 0 the lax.cond takes the robust-median-init
        # branch instead — the ONLY coordinate-median pass in the whole
        # step program, executed exactly once per run.
        gathered = jax.tree.map(
            lambda l: jax.lax.all_gather(l, axis, tiled=True), grads_local
        )

        stale_w2 = row_w_for(0)

        def phase2(centers_tree, centers_rows):
            if grad_tree_ok:
                return tree_exchange(
                    gathered, fold_plan, atk_key, sub_key, attack,
                    attack_params, center_tree=centers_tree,
                    row_weights=stale_w2,
                )
            stack0 = core.flatten_rows(gathered)  # (n, d)
            stack0 = apply_gradient_attack(
                attack, stack0, byz_mask, key=atk_key, **attack_params
            )
            stack0 = weight_rows(stack0, stale_w2)
            return local_aggregates(stack0, sub_key, centers=centers_rows)

        if gar.stateful_center:
            carried = state.gar_state  # (per_n, ...) local shard
            carried_rows = (
                None if grad_tree_ok else core.flatten_rows(carried)
            )
            aggr_local = jax.lax.cond(
                state.step == 0,
                lambda: phase2(None, None),
                lambda: phase2(
                    carried if grad_tree_ok else None, carried_rows
                ),
            )
        else:
            aggr_local = phase2(None, None)

        metrics_extra = {}
        grad_bundle = None
        if telemetry or defense is not None:
            # Phase-2 audit tap: the poisoned gathered stack rebuilt with
            # the SAME atk_key the exchange used (CSE'd on the flat path;
            # the enabled-only extra pass on the tree/fold paths). cclip
            # taps here use the rule's median-init center — the per-node
            # carried centers differ across observers (taps.py caveats).
            # With the defense on, this bundle is ALSO the feedback that
            # updates the carried exclusion EMA below.
            stack0p = apply_gradient_attack(
                attack, core.flatten_rows(gathered), byz_mask, key=atk_key,
                **attack_params,
            )
            # The tap audits the rows the rule consumed — staleness- and
            # suspicion-weighted included (the aggregathor convention).
            stack0p = weight_rows(stack0p, stale_w2)
            if waiting:
                def one_tap(nid):
                    # SAME (sel, key) derivation as node_aggregate /
                    # node_subset_keys, so the tap audits exactly the
                    # quorum node ``nid`` aggregated.
                    sel_key, gkey = jax.random.split(
                        jax.random.fold_in(sub_key, nid)
                    )
                    sel = core.subset_indices(sel_key, num_nodes, subset)
                    bundle = taps_lib.compute_flat(
                        gar.name, stack0p[sel], f, key=gkey,
                        params=gar_params,
                    )
                    return taps_lib.scatter(bundle, sel, num_nodes)

                local_mean = taps_lib.mean_bundles(
                    jax.vmap(one_tap)(node_ids)
                )
                grad_bundle = jax.tree.map(
                    lambda l: jax.lax.pmean(l, axis), local_mean
                )
            else:
                grad_bundle = taps_lib.compute_flat(
                    gar.name, stack0p, f, key=sub_key, params=gar_params,
                )
            if telemetry:
                metrics_extra["tap"] = grad_bundle
        if track_spread:
            metrics_extra["aggr_spread_pre"] = honest_spread(
                aggr_rows_of(aggr_local)
            )

        # Phase 3: avg_agree rounds (ceil(log2 t), LEARN/trainer.py:208-222).
        # Each round every node PUBLISHES its own current aggregate (they
        # differ under wait-n-f), Byzantine rows are poisoned, and each node
        # re-aggregates its own num_wait_ps = q subset of the gathered stack
        # (get_aggr_grads polling, server.py:202-233). Stateful rules
        # re-center each round on the node's CURRENT aggregate (the natural
        # v_0: the previous round's output).
        if non_iid:
            t = jnp.maximum(state.step, 1).astype(jnp.float32)
            rounds = jnp.ceil(jnp.log2(jnp.maximum(t, 2.0))).astype(jnp.int32)
            rounds = jnp.minimum(rounds, max_rounds)

            if grad_tree_ok:
                def round_body(r, aggr):
                    served = jax.tree.map(
                        lambda l: jax.lax.all_gather(l, axis, tiled=True),
                        aggr,
                    )  # (n, ...) leaves: every node's own aggregate
                    akey, skey = jax.random.split(
                        jax.random.fold_in(gossip_key, r)
                    )
                    new = tree_exchange(
                        served, fold_plan, akey, skey, attack, attack_params,
                        center_tree=aggr if gar.stateful_center else None,
                        row_weights=row_w_for(1 + r),
                    )
                    return jax.tree.map(
                        lambda a, b: jnp.where(r < rounds, a, b), new, aggr
                    )
            else:
                def round_body(r, aggr):
                    served = jax.lax.all_gather(aggr, axis, tiled=True)
                    akey, skey = jax.random.split(
                        jax.random.fold_in(gossip_key, r)
                    )
                    served = apply_gradient_attack(
                        attack, served, byz_mask, key=akey, **attack_params
                    )
                    served = weight_rows(served, row_w_for(1 + r))
                    new = local_aggregates(
                        served, skey,
                        centers=aggr if gar.stateful_center else None,
                    )
                    return jnp.where(r < rounds, new, aggr)

            aggr_local = jax.lax.fori_loop(
                0, max_rounds, round_body, aggr_local
            )

        if track_spread:
            metrics_extra["aggr_spread_post"] = honest_spread(
                aggr_rows_of(aggr_local)
            )

        # Phase 4: per-node optimizer step on that node's own aggregate.
        new_params_list, new_opt_list, aggr_trees = [], [], []
        for k in range(per_n):
            p_k = jax.tree.map(lambda l: l[k], state.params)
            o_k = jax.tree.map(lambda l: l[k], state.opt_state)
            if grad_tree_ok:
                aggr_tree = jax.tree.map(lambda l: l[k], aggr_local)
            else:
                aggr_tree = core.unflatten_like(p_k, aggr_local[k])
            aggr_trees.append(aggr_tree)
            aggr_tree = core.cast_like(aggr_tree, p_k)  # no-op at f32
            updates, o_k = optimizer.update(aggr_tree, o_k, p_k)
            new_params_list.append(optax.apply_updates(p_k, updates))
            new_opt_list.append(o_k)
        new_params = jax.tree.map(lambda *ls: jnp.stack(ls), *new_params_list)
        new_opt = jax.tree.map(lambda *ls: jnp.stack(ls), *new_opt_list)

        new_gar_state = state.gar_state
        if gar.stateful_center:
            # Next step's per-node v_0 = this step's final aggregate (f32 —
            # the carried center should not round through the bf16 pipeline).
            new_gar_state = jax.tree.map(
                lambda *ls: jnp.stack([l.astype(jnp.float32) for l in ls]),
                *aggr_trees,
            )

        # Phase 5: model gossip (LEARN/trainer.py:255-257, get_models(n-f) —
        # each node GAR-aggregates its own subset of the gossiped models).
        # Deterministic model attacks (reverse/crash) fold like the
        # gradient plane; stateful rules center each node's clip on its OWN
        # model (the ClippedGossip recipe) instead of a per-call median.
        new_attack_state = state.attack_state
        if model_gossip:
            stale_wg = row_w_for(0x5009)
            if gossip_tree_ok:
                models_tree = jax.tree.map(
                    lambda l: jax.lax.all_gather(l, axis, tiled=True),
                    new_params,
                )
                new_params = tree_exchange(
                    models_tree, model_fold_plan, matk_key, msub_key,
                    None, {},
                    center_tree=new_params if gar.stateful_center else None,
                    row_weights=stale_wg,
                )
            else:
                flat_models = core.flatten_rows(new_params)  # (per_n, d)
                models = jax.lax.all_gather(flat_models, axis, tiled=True)
                models = apply_model_attack_rows(
                    model_attack, models, act_mask_m, key=matk_key,
                    **eff_m_params,
                )
                # Gossip-plane staleness: a stale model's row is
                # discounted like a stale gradient's — the robust rule
                # then treats the down-scaled row as the outlier it is,
                # and the fresh honest majority keeps its influence
                # (DESIGN.md §15; the same composition as the PS plane;
                # the defense's suspicion weight rides the same multiply).
                models = weight_rows(models, stale_wg)
                if model_adaptive_cfg is not None:
                    # Gossip-plane selection feedback (DESIGN.md §17):
                    # the rule's verdict over the SAME poisoned, weighted
                    # stack the gossip aggregates — majority-excluded
                    # among the observed active nodes means detected; a
                    # round that observed none holds the bracket.
                    if waiting:
                        def one_mtap(nid):
                            # SAME (sel, key) derivation as
                            # node_aggregate over msub_key.
                            sel_key, gkey = jax.random.split(
                                jax.random.fold_in(msub_key, nid)
                            )
                            sel = core.subset_indices(
                                sel_key, num_nodes, subset
                            )
                            bundle = taps_lib.compute_flat(
                                gar.name, models[sel], f, key=gkey,
                                params=gar_params,
                            )
                            return taps_lib.scatter(bundle, sel, num_nodes)

                        gb = taps_lib.mean_bundles(
                            jax.vmap(one_mtap)(node_ids)
                        )
                        gossip_bundle = jax.tree.map(
                            lambda l: jax.lax.pmean(l, axis), gb
                        )
                    else:
                        gossip_bundle = taps_lib.compute_flat(
                            gar.name, models, f, key=msub_key,
                            params=gar_params,
                        )
                    act_f = act_mask_m.astype(jnp.float32) * gossip_bundle[
                        "observed"
                    ]
                    cnt = jnp.sum(act_f)
                    admitted = jnp.sum(
                        (gossip_bundle["selected"] > 0).astype(jnp.float32)
                        * act_f
                    )
                    m_detected = admitted * 2.0 < cnt
                    upd_lo, upd_hi = adaptive_lib.update_bracket(
                        m_lo, m_hi, m_detected,
                        mag_min=model_adaptive_cfg.mag_min,
                        mag_max=model_adaptive_cfg.mag_max,
                        regrow=model_adaptive_cfg.regrow,
                    )
                    hold = cnt == 0.0
                    new_attack_state = {
                        "lo": jnp.where(hold, m_lo, upd_lo),
                        "hi": jnp.where(hold, m_hi, upd_hi),
                    }
                    metrics_extra["model_attack_mag"] = jnp.asarray(
                        m_mag, jnp.float32
                    )
                    metrics_extra["model_attack_detected"] = (
                        m_detected.astype(jnp.float32)
                    )
                aggr_models = local_aggregates(
                    models, msub_key,
                    centers=flat_models if gar.stateful_center else None,
                )  # (per_n, d)
                template = jax.tree.map(lambda l: l[0], new_params)
                new_params = jax.tree.map(
                    lambda *ls: jnp.stack(ls),
                    *[
                        core.unflatten_like(template, aggr_models[k])
                        for k in range(per_n)
                    ],
                )

        new_defense_state = state.defense_state
        if defense is not None:
            # The hub's exclusion law (observed minus admitted) carried
            # as a decayed EMA — the in-graph twin of the node hub's
            # windowed suspicion, fed by the phase-2 observer mean.
            dec = jnp.float32(d_decay)
            obs_v = grad_bundle["observed"]
            ind_v = (grad_bundle["selected"] > 0).astype(jnp.float32) * obs_v
            new_defense_state = {
                "obs": state.defense_state["obs"] * dec + obs_v,
                "exc": state.defense_state["exc"] * dec + (obs_v - ind_v),
            }
            metrics_extra["defense_w"] = def_w

        honest = (~byz_mask).astype(losses.dtype)[node_ids]
        loss_num = jax.lax.psum(jnp.sum(losses * honest), axis)
        loss_den = jax.lax.psum(jnp.sum(honest), axis)
        mean_loss = loss_num / jnp.maximum(loss_den, 1.0)
        # Per-node losses for observers (the reference demo renders per-node
        # progress, LEARN/demo.py:401-441 + templates/index.html); a tiny
        # replicated (n,) vector, node-id ordered.
        metrics_extra["node_losses"] = jax.lax.all_gather(
            losses, axis, tiled=True
        )

        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                model_state=new_ms,
                opt_state=new_opt,
                worker_mom=new_mom,
                gar_state=new_gar_state,
                attack_state=new_attack_state,
                defense_state=new_defense_state,
            ),
            {"loss": mean_loss, **metrics_extra},
        )

    state_specs = core.TrainState(
        step=P(), params=P(axis), model_state=P(), opt_state=P(axis), rng=P(),
        worker_mom=(P(axis) if worker_momentum is not None else None),
        gar_state=(P(axis) if gar.stateful_center else None),
        attack_state=(P() if model_adaptive_cfg is not None else None),
        defense_state=(P() if defense is not None else None),
    )
    sharded_step = mesh_lib.shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(state_specs, P(axis), P(axis)),
        out_specs=(state_specs, P()),
        check_vma=False,
    )

    @functools.partial(jax.jit, donate_argnums=core.step_donation())
    def step_fn(state, x, y):
        return sharded_step(state, x, y)

    @jax.jit
    def eval_fn(state, x):
        params0 = jax.tree.map(lambda l: l[0], state.params)
        return eval_apply(params0, state.model_state, x)

    step_fn.mesh = mesh
    step_fn.batch_sharding = node_sharding
    # Chunking hook (core.make_chunked_step): scan the shard_map body
    # directly; shardings propagate as in the per-step jit (none pinned).
    step_fn.inner = sharded_step
    return init_fn, step_fn, eval_fn

"""Typed wire codec (utils/wire.py) + its cluster integration.

Codec robustness IS Byzantine robustness on the host plane: a Byzantine
PROCESS controls its wire bytes, so the codec's reject surface (magic /
version / dtype tag / element count / crc) is the ban evidence the
quorum paths act on. The fuzz test is the core guarantee: NO corrupted
frame ever decodes — it gets its sender excluded exactly like the old
wrong-length frame did.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from garfield_tpu.utils import wire


# --- pure codec (no native / jax dependency) --------------------------------


def test_f32_roundtrip_exact_and_payload_byte_identical():
    """f32 wire must keep trajectory parity with the pre-codec format:
    the payload after the 16-byte header is the exact ``tobytes()``."""
    rng = np.random.default_rng(0)
    v = rng.standard_normal(999).astype(np.float32)
    frame = wire.encode(v, "f32")
    assert frame[wire.HEADER_NBYTES:] == v.tobytes()
    assert len(frame) == wire.frame_nbytes(v.size, "f32")
    out = wire.decode(frame)
    assert out.dtype == np.float32
    np.testing.assert_array_equal(out, v)


def test_bf16_roundtrip_within_cast_tolerance():
    rng = np.random.default_rng(1)
    v = (rng.standard_normal(2048) * 10.0 ** rng.integers(
        -6, 6, 2048
    )).astype(np.float32)
    frame = wire.encode(v, "bf16")
    assert len(frame) == wire.frame_nbytes(v.size, "bf16")
    out = wire.decode(frame)
    rel = np.abs(out - v) / np.maximum(np.abs(v), 1e-30)
    assert rel.max() <= 2.0 ** -8  # bf16 has 8 mantissa bits

    # Specials survive (the lie attack at cohort=1 publishes NaN — the
    # reference's emergent behavior must not be laundered by the wire).
    specials = np.array([np.nan, np.inf, -np.inf, 0.0, -0.0], np.float32)
    out = wire.decode(wire.encode(specials, "bf16"))
    assert np.isnan(out[0]) and np.isposinf(out[1]) and np.isneginf(out[2])
    assert out[3] == 0.0 and out[4] == 0.0


def test_bf16_matches_xla_convert():
    """The host cast must equal XLA's f32->bf16 convert (round-to-nearest-
    even): a host-decoded gradient is bit-equal to what the on-mesh bf16
    pipeline would have produced for the same value."""
    jnp = pytest.importorskip("jax.numpy")
    rng = np.random.default_rng(2)
    v = rng.standard_normal(4096).astype(np.float32)
    host = wire.decode(wire.encode(v, "bf16"))
    xla = np.asarray(jnp.asarray(v).astype(jnp.bfloat16).astype(jnp.float32))
    np.testing.assert_array_equal(host, xla)


def test_plane_tag_round_trip():
    """Schema v6: the plane tag rides the dtype byte's spare high nibble
    — plane 0 frames are byte-identical to the pre-plane format, any
    plane decodes to the same values, and frame_plane reads the tag
    without paying the CRC."""
    v = np.arange(16, dtype=np.float32)
    for dtype in wire.WIRE_DTYPES:
        base = wire.encode(v, dtype)
        assert wire.encode(v, dtype, plane=0) == base  # byte-identical
        for plane in (0, 1, 2, wire.MAX_PLANE):
            frame = wire.encode(v, dtype, plane=plane)
            assert wire.frame_plane(frame) == plane
            np.testing.assert_array_equal(
                wire.decode(frame), wire.decode(base)
            )
    with pytest.raises(ValueError):
        wire.encode(v, "f32", plane=wire.MAX_PLANE + 1)
    with pytest.raises(wire.WireError):
        wire.frame_plane(b"short")
    with pytest.raises(wire.WireError):
        wire.frame_plane(b"XX" + b"\0" * 14)  # bad magic


def test_plane_capacity_guard_boundary():
    """ISSUE 13 satellite: the plane/shard tag has exactly
    ``MAX_PLANE + 1`` values — the boundary encodes, one past it fails
    loudly at publish/encode time (named capacity in the message), and
    non-integral tags are rejected instead of int()-truncated into a
    foreign shard's nibble."""
    v = np.ones(4, np.float32)
    frame = wire.encode(v, plane=wire.MAX_PLANE)  # boundary: fine
    assert wire.frame_plane(frame) == wire.MAX_PLANE
    with pytest.raises(ValueError, match="nibble"):
        wire.encode(v, plane=wire.MAX_PLANE + 1)
    with pytest.raises(ValueError, match="nibble"):
        wire.encode(v, plane=-1)
    with pytest.raises(TypeError):
        wire.encode(v, plane=2.5)
    with pytest.raises(TypeError):
        wire.encode(v, plane=True)
    assert wire.check_plane(np.int64(3)) == 3  # numpy ints are integral


def test_decode_expect_plane_rejects_cross_shard_frames():
    """DESIGN.md §19: a shard consumer decoding with ``expect_plane``
    rejects a frame stamped for any other shard as a WireError — the
    stamp is under the sender's CRC, so the mismatch is attributable
    ban evidence, never a silent mis-fold."""
    v = np.arange(8, dtype=np.float32)
    f1 = wire.encode(v, plane=1)
    np.testing.assert_array_equal(wire.decode(f1, expect_plane=1), v)
    with pytest.raises(wire.WireError, match="cross-shard"):
        wire.decode(f1, expect_plane=0)
    # expect_plane itself is capacity-guarded.
    with pytest.raises(ValueError):
        wire.decode(f1, expect_plane=16)


def test_wire_dtype_env(monkeypatch):
    monkeypatch.delenv("GARFIELD_WIRE_DTYPE", raising=False)
    assert wire.wire_dtype() == "f32"
    monkeypatch.setenv("GARFIELD_WIRE_DTYPE", "bf16")
    assert wire.wire_dtype() == "bf16"
    v = np.ones(4, np.float32)
    assert len(wire.encode(v)) == wire.frame_nbytes(4, "bf16")
    monkeypatch.setenv("GARFIELD_WIRE_DTYPE", "f16")
    with pytest.raises(ValueError):
        wire.wire_dtype()


def test_fuzz_corrupted_frames_never_decode():
    """Every single-bit flip and every truncation of a valid frame must
    raise WireError — corrupted bytes can NEVER reach a GAR — EXCEPT the
    four plane-tag bits (the dtype byte's spare high nibble, schema v6):
    a flip there only relabels the frame's plane, and the decode must
    return the IDENTICAL values (the payload is untouched and
    crc-verified), so nothing corrupted can reach a GAR through that
    nibble either. (A payload flip breaks the crc; any other header flip
    breaks magic/version/tag/length; a truncation breaks the length
    contract.)"""
    rng = np.random.default_rng(3)
    v = rng.standard_normal(257).astype(np.float32)
    # dtype byte = header byte 3 ("!2sBBQI"); its high nibble is the
    # plane tag.
    plane_bits = {3 * 8 + b for b in (4, 5, 6, 7)}
    for dtype in wire.WIRE_DTYPES:
        frame = wire.encode(v, dtype)
        baseline = wire.decode(frame)
        # exhaustive over the header, random over the payload
        bits = list(range(wire.HEADER_NBYTES * 8)) + list(
            rng.integers(wire.HEADER_NBYTES * 8, len(frame) * 8, 400)
        )
        for bit in bits:
            ba = bytearray(frame)
            ba[bit // 8] ^= 1 << (bit % 8)
            if bit in plane_bits:
                np.testing.assert_array_equal(
                    wire.decode(bytes(ba)), baseline
                )
                assert wire.frame_plane(bytes(ba)) != 0
                continue
            with pytest.raises(wire.WireError):
                wire.decode(bytes(ba))
        for cut in list(range(0, wire.HEADER_NBYTES + 2)) + list(
            rng.integers(0, len(frame), 60)
        ):
            with pytest.raises(wire.WireError):
                wire.decode(frame[:int(cut)])
        with pytest.raises(wire.WireError):
            wire.decode(frame + b"x")  # trailing garbage
    with pytest.raises(wire.WireError):
        wire.decode(b"")  # the SSMW stop sentinel must not decode


# --- exchange integration (native runtime required) -------------------------

pytest.importorskip("garfield_tpu.native")
from garfield_tpu import native  # noqa: E402

_HAVE_NATIVE = native.load() is not None

needs_native = pytest.mark.skipif(
    not _HAVE_NATIVE, reason="native runtime unavailable"
)


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _mesh(n, **kw):
    from garfield_tpu.utils.exchange import PeerExchange

    hosts = [f"127.0.0.1:{p}" for p in _ports(n)]
    return [PeerExchange(i, hosts, **kw) for i in range(n)]


@needs_native
def test_cross_dtype_publish_collect():
    """Mixed-width deployments interoperate: decoding is header-driven,
    never local-setting-driven — a bf16 sender and an f32 sender land in
    the same quorum."""
    rng = np.random.default_rng(4)
    v0 = rng.standard_normal(64).astype(np.float32)
    v1 = rng.standard_normal(64).astype(np.float32)

    def tf(idx, payload):
        return wire.decode(payload)

    peers = _mesh(2)
    try:
        peers[0].publish(3, wire.encode(v0, "f32"))
        peers[1].publish(3, wire.encode(v1, "bf16"))
        for p in peers:
            got = p.collect(3, q=2, timeout_ms=10_000, transform=tf)
            np.testing.assert_array_equal(got[0], v0)
            np.testing.assert_array_equal(
                got[1], wire.decode(wire.encode(v1, "bf16"))
            )
    finally:
        for p in peers:
            p.close()


@needs_native
def test_transform_error_is_stored_not_raised():
    """A transform that raises (codec reject) must surface as the peer's
    stored result — attributed ban evidence, not a missing-peer timeout."""
    from garfield_tpu.apps.cluster import _frame_transform

    peers = _mesh(2)
    try:
        tf = _frame_transform((8, 0))
        frame = bytearray(wire.encode(np.ones(8, np.float32), "f32"))
        frame[-1] ^= 0x40  # payload bit flip -> crc reject
        peers[1].publish(0, bytes(frame))
        peers[0].publish(0, wire.encode(np.zeros(8, np.float32), "f32"))
        got = peers[0].collect(0, q=2, timeout_ms=10_000, transform=tf)
        assert isinstance(got[1], wire.WireError)
        assert got[1].nbytes == len(frame)
        head, tail = got[0]
        np.testing.assert_array_equal(np.asarray(head), np.zeros(8))
        assert tail.size == 0
    finally:
        for p in peers:
            p.close()


@needs_native
def test_gradient_quorum_bans_corrupt_codec_frames():
    """The malformed-frame ban path, end to end: random bit-flipped and
    truncated codec payloads never reach the aggregation and get their
    sender excluded from all future quorums — exactly like the old
    wrong-length frame (ISSUE r8 satellite)."""
    from garfield_tpu.apps.cluster import _gradient_quorum
    from garfield_tpu.telemetry import hub as tele_hub

    d = 32
    rng = np.random.default_rng(5)
    honest = rng.standard_normal(d).astype(np.float32)
    hub = tele_hub.MetricsHub()
    prev = tele_hub.install(hub)
    peers = _mesh(3)  # 0 = PS, 1 = honest worker, 2 = Byzantine bytes
    try:
        for trial, corrupt in enumerate([
            b"\x00" * 10,                                   # garbage
            wire.encode(honest, "f32")[: wire.HEADER_NBYTES + 7],  # trunc
            bytes([b ^ (1 << rng.integers(8)) if i == 20 else b
                   for i, b in enumerate(wire.encode(honest, "bf16"))]),
        ]):
            step = trial
            peers[2].publish(step, corrupt, to=[0])
            # The honest frame arrives LATE so the q=1 quorum closes on
            # the corrupt frame first and the ban path must re-collect.
            t = threading.Timer(
                0.3, lambda s=step: peers[1].publish(
                    s, wire.encode(honest, "f32"), to=[0]
                )
            )
            t.start()
            deadline = time.time() + 10
            while peers[0]._mb.version(2) < trial + 1 and time.time() < deadline:
                time.sleep(0.02)
            got, good = _gradient_quorum(
                peers[0], step, 1, [1, 2], (d, 0),
                republish=lambda: None, timeout_ms=10_000, who="test-ps",
            )
            t.join()
            # The corrupt frame never enters the result; rank 2 is banned.
            assert good == [1]
            assert set(got) == {1}
            np.testing.assert_array_equal(np.asarray(got[1][0]), honest)
        events = [r for r in hub.records()
                  if r.get("event") == "quorum_exclusion"]
        assert events and all(e["rank"] == 2 for e in events)
    finally:
        tele_hub.uninstall()
        if prev is not None:
            tele_hub.install(prev)
        for p in peers:
            p.close()


@needs_native
def test_send_queue_drop_event_emitted():
    """Publisher-side backpressure is no longer silent: overflowing a
    hung receiver's bounded sender queue emits ``send_queue_drop``
    (ISSUE r8 satellite — mirrors the receive-side ``plane_drop``)."""
    from garfield_tpu.telemetry import hub as tele_hub
    from garfield_tpu.utils.exchange import PeerExchange

    srv = socket.create_server(("127.0.0.1", 0))
    conns = []

    def sink():  # accepts, never reads: a hung (not crashed) receiver
        try:
            while True:
                conn, _ = srv.accept()
                conns.append(conn)
        except OSError:
            pass

    threading.Thread(target=sink, daemon=True).start()
    p0 = _ports(1)[0]
    hosts = [f"127.0.0.1:{p0}", f"127.0.0.1:{srv.getsockname()[1]}"]
    hub = tele_hub.MetricsHub()
    prev = tele_hub.install(hub)
    ex = PeerExchange(0, hosts, send_queue_frames=1, send_timeout_ms=2_000)
    try:
        big = b"\x00" * (8 << 20)  # 8 MB: sendall blocks on TCP buffers
        deadline = time.time() + 20
        while not hub.wire_counters()["send_queue_drops"]:
            ex.publish(0, big, to=[1])
            assert time.time() < deadline, "no send_queue_drop emitted"
            time.sleep(0.05)
        drops = [r for r in hub.records()
                 if r.get("event") == "send_queue_drop"]
        assert drops and drops[0]["peer"] == 1
    finally:
        tele_hub.uninstall()
        if prev is not None:
            tele_hub.install(prev)
        ex.close()
        srv.close()
        for c in conns:
            c.close()


@needs_native
@pytest.mark.slow
def test_exchange_bench_multiprocess():
    """The committed-record generator works end to end: a tiny
    multi-process micro grid produces parseable JSON + a schema-valid
    JSONL twin, and bf16 measures >= 1.8x fewer wire bytes/step than f32
    (the ISSUE r8 acceptance bar)."""
    import json
    import tempfile

    from garfield_tpu.apps.benchmarks import exchange_bench
    from garfield_tpu.telemetry.exporters import validate_jsonl

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "exch.json")
        rows = exchange_bench.main([
            "--ns", "2", "--ds", "4096", "--wire", "f32", "bf16",
            "--rounds", "4", "--trials", "1", "--json", out,
        ])
        assert validate_jsonl(os.path.splitext(out)[0] + ".jsonl") == 2
        committed = json.load(open(out))
        assert committed == rows
        by_wire = {r["wire"]: r for r in rows}
        ratio = (by_wire["f32"]["wire_bytes_per_step"]
                 / by_wire["bf16"]["wire_bytes_per_step"])
        assert ratio >= 1.8, ratio
        for r in rows:
            assert r["round_s"] is None or r["round_s"] > 0

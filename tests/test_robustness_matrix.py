"""Robustness matrix: every robust GAR vs every gradient attack.

The reference validates rules only implicitly (training runs + the
``upper_bound``/``influence`` formulas, SURVEY §4); here each (rule, attack)
cell is checked directly at the stack level: with n=11 workers, f=2 Byzantine
rows poisoned by the attack, the robust aggregate must stay near the honest
mean — and for the blatant attacks, beat plain averaging by an order of
magnitude. This is the Byzantine-tolerance contract the reference's paper
claims, as an executable test.
"""

import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu.aggregators import gars
from garfield_tpu.attacks import apply_gradient_attack

# n = 11 admits every rule's contract at f = 2 (bulyan needs n >= 4f+3).
N, F, D = 11, 2, 64
SIGMA = 0.01
RULES = ["krum", "median", "bulyan", "brute", "aksel", "condense", "tmean",
         "cclip"]
# reverse/empire shove the Byzantine rows far from the cluster; random
# replaces them with unit-scale noise (moderate displacement); lie/drop are
# designed to be subtle (stay within/near the honest spread).
STRONG = ["reverse", "empire"]
MODERATE = ["random"]
SUBTLE = ["lie", "drop"]


def _stack(seed):
    rng = np.random.default_rng(seed)
    mu = np.ones(D, np.float32)
    honest = mu + SIGMA * rng.standard_normal((N, D)).astype(np.float32)
    return jnp.asarray(honest), jnp.asarray(mu)


def _attacked(attack, g, seed):
    mask = jnp.arange(N) >= N - F  # last F rows Byzantine
    key = jax.random.PRNGKey(seed)
    return apply_gradient_attack(attack, g, mask, key=key), mask


def _err(agg, mu):
    return float(jnp.linalg.norm(agg - mu))


@pytest.mark.parametrize("attack", STRONG + MODERATE + SUBTLE)
@pytest.mark.parametrize("rule", RULES)
def test_rule_bounds_attack(rule, attack):
    g, mu = _stack(seed=zlib.crc32(f"{rule}-{attack}".encode()))
    attacked, _ = _attacked(attack, g, seed=7)
    agg = gars[rule].unchecked(attacked, f=F)
    err = _err(agg, mu)
    tol = 5 * SIGMA * np.sqrt(D)  # a few honest-noise lengths from the mean
    assert np.isfinite(err), f"{rule} vs {attack}: non-finite aggregate"
    assert err <= tol, f"{rule} vs {attack}: err {err:.4f} > tol {tol:.4f}"
    if attack in STRONG + MODERATE:
        ratio = 10 if attack in STRONG else 3
        err_avg = _err(jnp.mean(attacked, axis=0), mu)
        assert err <= err_avg / ratio, (
            f"{rule} vs {attack}: robust err {err:.4f} not << "
            f"average err {err_avg:.4f}"
        )


@pytest.mark.parametrize("attack", STRONG)
def test_average_is_broken_by_strong_attacks(attack):
    """Sanity: the non-robust baseline really is destroyed (otherwise the
    matrix above proves nothing)."""
    g, mu = _stack(seed=3)
    attacked, _ = _attacked(attack, g, seed=11)
    err_avg = _err(gars["average"].unchecked(attacked), mu)
    assert err_avg > 20 * 5 * SIGMA * np.sqrt(D)


# --- adaptive rows (DESIGN.md §16) -----------------------------------------
#
# The stack-level closed loop: a bisection controller (attacks/adaptive.py)
# plays the lie magnitude against the rule's actual admission each round —
# feedback is the fraction of the fake's excess direction present in the
# aggregate, the exact signal a real attacker probes from the broadcast
# model delta. ``async`` composes the bounded-staleness discount weights
# into the rows (utils/rounds.py), the same composition the async PS
# applies.

ADAPTIVE_RULES = ["krum", "bulyan", "hier-krum"]


def _adaptive_lie_rounds(rule, mode, T=48):
    from garfield_tpu.attacks import adaptive
    from garfield_tpu.utils import rounds

    cfg = adaptive.configure(
        "adaptive-lie", {"mag_max": 6.0}, num_workers=N, f=F
    )
    lo, hi = cfg.mag_min, cfg.mag_max
    rng = np.random.default_rng(zlib.crc32(f"{rule}-{mode}".encode()))
    mu = np.ones(D, np.float32)
    mask = jnp.arange(N) >= N - F
    errs, max_admitted = [], 0.0
    for _ in range(T):
        honest = mu + SIGMA * rng.standard_normal((N, D)).astype(np.float32)
        z = float(adaptive.played_magnitude(lo, hi))
        attacked = apply_gradient_attack(
            "lie", jnp.asarray(honest), mask, z=z
        )
        if mode == "async":
            taus = np.zeros(N, np.int64)
            taus[1] = 2  # one stale honest rank, discounted not dropped
            w = rounds.staleness_weights(taus, decay=0.5, max_staleness=4)
            attacked = attacked * jnp.asarray(w)[:, None]
        agg = np.asarray(gars[rule].unchecked(attacked, f=F))
        hm = honest[: N - F].mean(axis=0)
        u = np.asarray(attacked[N - 1]) - hm  # the fake's excess direction
        frac = float(np.dot(agg - hm, u) / max(np.dot(u, u), 1e-12))
        detected = frac < 0.05
        if not detected:
            max_admitted = max(max_admitted, z)
        lo, hi = (float(v) for v in adaptive.update_bracket(
            lo, hi, detected, mag_min=cfg.mag_min, mag_max=cfg.mag_max,
        ))
        errs.append(float(np.linalg.norm(agg - mu)))
    return errs, max_admitted, (lo, hi)


@pytest.mark.parametrize("mode", ["sync", "async"])
@pytest.mark.parametrize("rule", ADAPTIVE_RULES)
def test_adaptive_lie_converges_and_stays_bounded(rule, mode):
    """Both halves of the adaptive contract at stack level: the attacker
    SUSTAINS a magnitude well above the static ALIE z without being
    excluded (it measurably beats the oblivious attack), and the rule
    still bounds the adapted aggregate within the matrix tolerance (the
    reason escalating to a stronger rule restores the accuracy bar)."""
    from garfield_tpu.attacks import LIE_Z

    errs, max_admitted, (lo, hi) = _adaptive_lie_rounds(rule, mode)
    tol = 5 * SIGMA * np.sqrt(D)
    assert all(np.isfinite(errs)), f"{rule}/{mode}: non-finite aggregate"
    assert max(errs) <= tol, (
        f"{rule}/{mode}: adapted attack broke the bound "
        f"({max(errs):.4f} > {tol:.4f})"
    )
    assert max_admitted > 1.2 * LIE_Z, (
        f"{rule}/{mode}: controller only sustained z={max_admitted:.3f} "
        f"(static ALIE is {LIE_Z})"
    )
    # Converged: the bracket closed far inside its initial width (the
    # re-expansion keeps probing, so it never pinches to a point).
    assert hi - lo < 2.0, f"{rule}/{mode}: bracket never converged"


@pytest.mark.parametrize("rule", [r for r in RULES if r != "condense"])
def test_permutation_invariant_under_attack(rule):
    """Shuffling worker rows must not change the aggregate (the mesh slot a
    Byzantine worker occupies is arbitrary). condense is excluded: it mixes
    the median with gradient 0 by design (condense.py), so it is
    order-dependent per the reference semantics."""
    g, _ = _stack(seed=5)
    attacked, _ = _attacked("reverse", g, seed=13)
    perm = np.random.default_rng(0).permutation(N)
    a1 = np.asarray(gars[rule].unchecked(attacked, f=F))
    a2 = np.asarray(gars[rule].unchecked(attacked[perm], f=F))
    np.testing.assert_allclose(a1, a2, rtol=2e-5, atol=2e-6)


# --- model-plane adaptive rows (DESIGN.md §17) ------------------------------
#
# The same closed loop on the MODEL plane: a Byzantine PS publishes the
# model-plane collusion fake (mu + z*sigma over the replica stack it
# gathered) into its peers' fastest-subset model gather (byzsgd
# ``model_subset``); feedback is whether the fake reached the observers'
# aggregates. The rule must bound the adapted MODEL aggregate exactly
# like the gradient plane's.

N_PS, F_PS, Q_M = 7, 1, 5  # krum needs q_m >= 2f + 3


def _adaptive_model_rounds(rule, T=48):
    from garfield_tpu.attacks import adaptive, apply_model_attack_rows

    cfg = adaptive.configure(
        "adaptive-lie", {"mag_max": 8.0}, num_workers=N_PS, f=F_PS
    )
    lo, hi = cfg.mag_min, cfg.mag_max
    rng = np.random.default_rng(zlib.crc32(f"model-{rule}".encode()))
    mu = np.ones(D, np.float32)
    mask = jnp.arange(N_PS) >= N_PS - F_PS
    errs, max_admitted = [], 0.0
    for t in range(T):
        models = mu + SIGMA * rng.standard_normal(
            (N_PS, D)
        ).astype(np.float32)
        z = float(adaptive.played_magnitude(lo, hi))
        attacked = apply_model_attack_rows(
            "lie", jnp.asarray(models), mask, z=z
        )
        # Per-observer fastest-subset gathers (model_subset): every
        # honest PS aggregates its own seeded q_m of n_ps models.
        key = jax.random.PRNGKey(t)
        fracs, aggs = [], []
        hm = models[: N_PS - F_PS].mean(axis=0)
        u = np.asarray(attacked[N_PS - 1]) - hm
        for obs in range(N_PS - F_PS):
            sel = np.asarray(jax.random.permutation(
                jax.random.fold_in(key, obs), N_PS
            ))[:Q_M]
            agg = np.asarray(
                gars[rule].unchecked(attacked[jnp.asarray(sel)], f=F_PS)
            )
            aggs.append(agg)
            if N_PS - 1 in sel:
                fracs.append(float(
                    np.dot(agg - hm, u) / max(np.dot(u, u), 1e-12)
                ))
        detected = (not fracs) or (np.mean(fracs) < 0.05)
        if not detected and fracs:
            max_admitted = max(max_admitted, z)
        lo, hi = (float(v) for v in adaptive.update_bracket(
            lo, hi, detected, mag_min=cfg.mag_min, mag_max=cfg.mag_max,
        ))
        errs.append(max(
            float(np.linalg.norm(a - mu)) for a in aggs
        ))
    return errs, max_admitted, (lo, hi)


@pytest.mark.parametrize("rule", ["krum", "median"])
def test_adaptive_model_plane_stays_bounded(rule):
    """The model-plane contract: under per-observer model subsets the
    adaptive PS's collusion fake never drives any honest observer's
    model aggregate outside the matrix tolerance, while the bisection
    genuinely converges on the rule's admission threshold."""
    errs, max_admitted, (lo, hi) = _adaptive_model_rounds(rule)
    tol = 5 * SIGMA * np.sqrt(D)
    assert all(np.isfinite(errs))
    assert max(errs) <= tol, (
        f"model/{rule}: adapted fake broke the bound "
        f"({max(errs):.4f} > {tol:.4f})"
    )
    assert hi - lo < 4.0, f"model/{rule}: bracket never converged"


# --- data-plane defense rows (DESIGN.md §18) --------------------------------
#
# The stack-level closed loop for the TARGETED family: per-rank head
# gradients with a poisoning cohort's signature (backdoor: coherent
# off-direction rows + shifted bias, the all-relabeled batch; labelflip:
# target-class rows flipped against the honest direction), run through
# the fingerprint detectors + EMA weighting of aggregators/dataplane.py
# and composed into the rule — sync and async (staleness-discount
# composition), data-only and escalate+data (GAR-suspicion weights
# composed on top), plus one hier-krum composition row.

DP_N, DP_F, DP_FEAT = 16, 3, 24


def _targeted_head_rows(attack, rng):
    """(rows, honest_mean): flat [bias | head-kernel] rows with the
    targeted cohort's data-plane signature in the last DP_F ranks."""
    base = rng.normal(size=(DP_FEAT,)).astype(np.float32)
    H = base[None] + 0.25 * rng.standard_normal(
        (DP_N, DP_FEAT)
    ).astype(np.float32)
    b = 0.3 * rng.standard_normal((DP_N, 1)).astype(np.float32)
    for i in range(DP_N - DP_F, DP_N):
        if attack == "backdoor":
            # Trigger cohort: near-identical poisoned batches, loss mass
            # on the target logit — coherent rows + strong bias shift.
            H[i] = -0.7 * base + 0.05 * rng.standard_normal(
                DP_FEAT
            ).astype(np.float32)
            b[i] = -2.5
        else:
            # Labelflip: the source samples' head rows push the target
            # logit the wrong way — flipped against the honest direction.
            H[i] = -base + 0.15 * rng.standard_normal(
                DP_FEAT
            ).astype(np.float32)
            b[i] = -1.5
    rows = np.concatenate([b, H], axis=1).astype(np.float32)
    honest_mean = rows[: DP_N - DP_F].mean(axis=0)
    return rows, honest_mean


@pytest.mark.parametrize("mode", ["sync", "async"])
@pytest.mark.parametrize("defense", ["data", "escalate+data"])
@pytest.mark.parametrize("attack", ["backdoor", "labelflip"])
def test_dataplane_defense_matrix(attack, defense, mode):
    """backdoor/labelflip x data/escalate+data x sync/async: the
    detectors pin the cohort at the weight floor within the EMA window,
    honest ranks (a staleness-discounted straggler included) keep ~1.0,
    and the weighted krum aggregate lands near the honest mean."""
    from garfield_tpu.aggregators import dataplane as dp, defense as dlib
    from garfield_tpu.utils import rounds

    rng = np.random.default_rng(
        zlib.crc32(f"dp-{attack}-{defense}-{mode}".encode())
    )
    spec = dp.HeadSpec(
        kernel=(1, 1 + DP_FEAT), bias=(0, 1), feat=DP_FEAT, classes=1
    )
    pdef = dp.DataPlaneDefense(
        DP_N, spec, f=DP_F, halflife=4.0, floor=0.1
    )
    gar_susp = np.zeros(DP_N)
    agg = hm = None
    for t in range(12):
        rows, hm = _targeted_head_rows(attack, rng)
        pdef.observe(np.arange(DP_N), rows)
        # Data-plane composition is CENTER-PULL (suspect rows collapse
        # onto the trusted-mean center — toward-zero scaling hands the
        # cohort krum centrality, the recorded negative result)...
        rows_def = dp.center_pull_rows(rows, pdef.weights_full())
        # ...while the GAR-side suspicion and staleness discounts keep
        # their row-scale slot, composed on top.
        w = np.ones(DP_N, np.float32)
        if defense == "escalate+data":
            w = w * np.asarray(dlib.suspicion_weights(gar_susp))
        if mode == "async":
            taus = np.zeros(DP_N, np.int64)
            taus[1] = 2  # one stale HONEST rank: discounted, not flagged
            w = w * rounds.staleness_weights(
                taus, decay=0.5, max_staleness=4
            )
        agg = np.asarray(gars["krum"].unchecked(
            jnp.asarray(rows_def * w[:, None]), f=DP_F
        ))
    w = pdef.weights_full()
    assert (w[DP_N - DP_F:] <= 0.11).all(), (attack, defense, mode, w)
    assert (w[: DP_N - DP_F] >= 0.9).all(), (attack, defense, mode, w)
    # The stale honest rank was discounted by staleness but never
    # FLAGGED by the data plane (its fingerprint is in-crowd).
    assert pdef.suspicion()[1] < 0.1
    err = float(np.linalg.norm(agg - hm))
    tol = 0.5 * np.sqrt(DP_FEAT + 1)
    assert err <= tol, f"{attack}/{defense}/{mode}: err {err:.3f}"


def test_dataplane_composes_with_hier_krum():
    """Composition row: the center-pulled stack feeds the hierarchical
    bucketed rule exactly like the flat rules — the hier-krum aggregate
    over the defended stack must land on the honest mean (the pulled
    cohort rows are selectable but informationless)."""
    from garfield_tpu.aggregators import dataplane as dp

    rng = np.random.default_rng(zlib.crc32(b"dp-hier"))
    spec = dp.HeadSpec(
        kernel=(1, 1 + DP_FEAT), bias=(0, 1), feat=DP_FEAT, classes=1
    )
    pdef = dp.DataPlaneDefense(
        DP_N, spec, f=DP_F, halflife=4.0, floor=0.1
    )
    agg = hm = None
    for _ in range(12):
        rows, hm = _targeted_head_rows("backdoor", rng)
        pdef.observe(np.arange(DP_N), rows)
        rows_def = dp.center_pull_rows(rows, pdef.weights_full())
        agg = np.asarray(gars["hier-krum"].unchecked(
            jnp.asarray(rows_def), f=DP_F
        ))
    err = float(np.linalg.norm(agg - hm))
    assert err <= 0.5 * np.sqrt(DP_FEAT + 1), err
    w = pdef.weights_full()
    assert (w[DP_N - DP_F:] <= 0.11).all()


# --- targeted rows (DESIGN.md §17) ------------------------------------------


@pytest.mark.parametrize("attack", ["labelflip", "backdoor"])
def test_targeted_attack_raises_asr_not_divergence(attack):
    """The targeted family's defining property, as a trained row: the
    poisoned cohort measurably raises the per-class attack-success-rate
    (source→target confusion / trigger ASR — parallel.targeted_eval)
    while the aggregate stays non-divergent (finite, training still
    converges on the untargeted classes) — the blindness of the
    divergence-based audit made measurable."""
    import os

    import jax as _jax
    from garfield_tpu import data as data_lib, parallel
    from garfield_tpu.attacks import targeted as targeted_lib
    from garfield_tpu.models import select_model
    from garfield_tpu.parallel import aggregathor
    from garfield_tpu.utils import selectors

    os.environ["GARFIELD_SURROGATE_MARGIN"] = "1.35"
    try:
        data_lib._warned_synthetic.clear()
        module = select_model("pimanet", "pima")
        loss = selectors.select_loss("bce")
        opt = selectors.select_optimizer(
            "sgd", lr=0.1, momentum=0.0, weight_decay=0.0
        )
        m = data_lib.DatasetManager("pima", 8, 8, 8, 0)
        m.num_ps = 0
        xs, ys = m.sharded_train_batches()
        test = parallel.EvalSet(m.get_test_set(), binary=True)
        params = {"source": 0, "target": 1, "poison_frac": 1.0}
        cfg = targeted_lib.configure(attack, params, num_classes=1)
        rates = {}
        for atk in (None, attack):
            init_fn, step_fn, eval_fn = aggregathor.make_trainer(
                module, loss, opt, "average", num_workers=8, f=3,
                attack=atk, attack_params=params if atk else {},
            )
            state = init_fn(_jax.random.PRNGKey(0), xs[0, 0])
            nb = xs.shape[1]
            for i in range(150):
                b = i % nb
                state, metrics = step_fn(
                    state, jnp.asarray(xs[:, b]), jnp.asarray(ys[:, b])
                )
            assert np.isfinite(float(metrics["loss"]))
            rep = parallel.targeted_eval(
                state, eval_fn, test, source=0, target=1,
                trigger_cfg=cfg if attack == "backdoor" else None,
            )
            rates[atk] = (
                rep["asr"] if attack == "backdoor" else rep["confusion"]
            )
            # Non-divergence: the poisoned run still classifies the
            # TARGET class fine (it only moved the source boundary).
            assert rep["per_class"][1] > 0.5
        # The ASR bar: the poisoned run's success rate clearly exceeds
        # the clean confusion baseline.
        assert rates[attack] > rates[None] + 0.05, (
            f"{attack}: ASR {rates[attack]} vs clean {rates[None]}"
        )
    finally:
        os.environ.pop("GARFIELD_SURROGATE_MARGIN", None)
        data_lib._warned_synthetic.clear()


# --- transformer-family rows (DESIGN.md §23) --------------------------------
#
# The matrix above runs on synthetic Gaussian stacks; these rows run the
# same contract on REAL transformer gradients: per-worker grads of the
# small GPT on token batches (the slot-fused twin's workload), flattened
# to (n, d) rows. Real gradient stacks are anisotropic — per-leaf scales
# spread orders of magnitude — so the tolerance is set from the stack's
# own measured honest spread, at the matrix's 5x multiplier.

TRANS_RULES = ["krum", "median", "cclip"]
_GPT_ROWS_CACHE = []


def _gpt_rows():
    """(n, d) float32 per-worker GPT gradient rows, computed once."""
    if not _GPT_ROWS_CACHE:
        from garfield_tpu.models import transformer
        from garfield_tpu.parallel import core as pcore
        from garfield_tpu.utils import selectors

        module = transformer.GPT(
            num_classes=10, vocab=16, dim=16, depth=1, heads=2,
            mlp_dim=32,
        )
        loss = selectors.select_loss("nll")
        init_fn, grad_fn, _ = pcore.make_worker_fns(module, loss)
        k = jax.random.PRNGKey(0)
        x = jax.random.randint(k, (N, 4, 8), 0, 16)
        y = jax.random.randint(jax.random.fold_in(k, 1), (N, 4), 0, 10)
        keys = jax.random.split(jax.random.PRNGKey(2), N)
        params, ms = init_fn(k, x[0])
        g_st, _ = jax.vmap(
            grad_fn, in_axes=(None, None, 0, 0, 0)
        )(params, ms, x, y, keys)
        rows = np.stack([
            np.asarray(jax.flatten_util.ravel_pytree(
                jax.tree.map(lambda l: l[i], g_st)
            )[0], np.float32)
            for i in range(N)
        ])
        _GPT_ROWS_CACHE.append(rows)
    return _GPT_ROWS_CACHE[0]


def _maybe_stale(rows, mode):
    if mode != "async":
        return rows
    from garfield_tpu.utils import rounds

    taus = np.zeros(N, np.int64)
    taus[1] = 2  # one stale honest rank, discounted not dropped
    w = rounds.staleness_weights(taus, decay=0.5, max_staleness=4)
    return rows * jnp.asarray(w)[:, None]


@pytest.mark.parametrize("mode", ["sync", "async"])
@pytest.mark.parametrize("attack", ["lie", "adaptive-lie", "none"])
@pytest.mark.parametrize("rule", TRANS_RULES)
def test_transformer_rows_bounded(rule, attack, mode):
    """krum/median/cclip x lie/adaptive-lie/none x sync/async on real
    GPT gradient rows: the robust aggregate stays within a few measured
    honest-spread lengths of the honest mean — the Byzantine-tolerance
    contract carries to the transformer family's gradient geometry."""
    rows = _gpt_rows()
    hm = rows[: N - F].mean(axis=0)
    spread = float(
        np.linalg.norm(rows[: N - F] - hm, axis=1).mean()
    )
    tol = 5.0 * spread
    mask = jnp.arange(N) >= N - F
    if attack == "adaptive-lie":
        from garfield_tpu.attacks import adaptive

        cfg = adaptive.configure(
            "adaptive-lie", {"mag_max": 6.0}, num_workers=N, f=F
        )
        lo, hi = cfg.mag_min, cfg.mag_max
        errs = []
        for _ in range(16):
            z = float(adaptive.played_magnitude(lo, hi))
            attacked = _maybe_stale(apply_gradient_attack(
                "lie", jnp.asarray(rows), mask, z=z
            ), mode)
            agg = np.asarray(gars[rule].unchecked(attacked, f=F))
            u = np.asarray(attacked[N - 1]) - hm
            frac = float(np.dot(agg - hm, u) / max(np.dot(u, u), 1e-12))
            lo, hi = (float(v) for v in adaptive.update_bracket(
                lo, hi, frac < 0.05, mag_min=cfg.mag_min,
                mag_max=cfg.mag_max,
            ))
            errs.append(float(np.linalg.norm(agg - hm)))
        err = max(errs)
    else:
        attacked = jnp.asarray(rows)
        if attack == "lie":
            attacked = apply_gradient_attack(
                "lie", attacked, mask, key=jax.random.PRNGKey(7)
            )
        attacked = _maybe_stale(attacked, mode)
        agg = np.asarray(gars[rule].unchecked(attacked, f=F))
        err = float(np.linalg.norm(agg - hm))
    assert np.isfinite(err), f"{rule}/{attack}/{mode}: non-finite"
    assert err <= tol, (
        f"{rule}/{attack}/{mode}: err {err:.5f} > tol {tol:.5f} "
        f"(spread {spread:.5f})"
    )

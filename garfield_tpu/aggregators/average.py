"""Averaging GAR (non-robust baseline).

Counterpart of pytorch_impl/libs/aggregators/average.py (:21-29 aggregate,
influence = accepted fraction).
"""

import jax
import jax.numpy as jnp

from . import register
from ._common import as_stack, num_gradients


def aggregate(gradients, **kwargs):
    """Arithmetic mean of the gradients."""
    return jnp.mean(as_stack(gradients), axis=0)


def tree_aggregate(grads_tree, **kwargs):
    """Tree-mode mean over the leading slot axis (no flat stack)."""
    return jax.tree.map(lambda l: jnp.mean(l, axis=0), grads_tree)


def gram_select(gram, f=0, **kwargs):
    """Uniform weights (the Gram is unused and DCE'd by XLA) — lets the
    folded attack path (parallel.fold) serve the average baseline too."""
    n = gram.shape[0]
    return jnp.full((n,), 1.0 / n, jnp.float32)


def check(gradients, **kwargs):
    if num_gradients(gradients) < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    return None


def influence(honests, attacks, **kwargs):
    """Every gradient is accepted: ratio = |attacks| / n (average.py:29-37)."""
    return len(attacks) / (len(honests) + len(attacks))


register("average", aggregate, check, influence=influence,
         tree_aggregate=tree_aggregate, gram_select=gram_select)

"""CIFAR-style ResNet family (counterpart of garfieldpp/models/resnet.py).

3x3 stem (no maxpool) as in the CIFAR zoo; BasicBlock for 18/34,
Bottleneck for 50/101/152. The reference's resnet50/152 come from
torchvision (garfieldpp/tools.py:70-72) but share this block structure.
"""

from typing import Sequence, Type

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32
    expansion = 1

    @nn.compact
    def __call__(self, x, train=False):
        out = nn.relu(norm(train, dtype=self.dtype)(
            conv(self.features, 3, self.stride, padding=1, dtype=self.dtype)(x)))
        out = norm(train, dtype=self.dtype)(
            conv(self.features, 3, 1, padding=1, dtype=self.dtype)(out))
        if self.stride != 1 or x.shape[-1] != self.features:
            x = norm(train, dtype=self.dtype)(
                conv1x1(self.features, stride=self.stride, dtype=self.dtype)(x))
        return nn.relu(out + x)


class Bottleneck(nn.Module):
    features: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32
    expansion = 4

    @nn.compact
    def __call__(self, x, train=False):
        out = nn.relu(norm(train, dtype=self.dtype)(
            conv1x1(self.features, dtype=self.dtype)(x)))
        out = nn.relu(norm(train, dtype=self.dtype)(
            conv(self.features, 3, self.stride, padding=1, dtype=self.dtype)(out)))
        out = norm(train, dtype=self.dtype)(
            conv1x1(self.features * 4, dtype=self.dtype)(out))
        if self.stride != 1 or x.shape[-1] != self.features * 4:
            x = norm(train, dtype=self.dtype)(
                conv1x1(self.features * 4, stride=self.stride, dtype=self.dtype)(x))
        return nn.relu(out + x)


class ResNet(nn.Module):
    block: Type[nn.Module]
    stage_sizes: Sequence[int]
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = nn.relu(norm(train, dtype=self.dtype)(
            conv(64, 3, 1, padding=1, dtype=self.dtype)(x)))
        for stage, nblocks in enumerate(self.stage_sizes):
            for i in range(nblocks):
                stride = 2 if stage > 0 and i == 0 else 1
                x = self.block(64 * 2 ** stage, stride, dtype=self.dtype)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


def ResNet18(num_classes=10, dtype=jnp.float32):
    return ResNet(BasicBlock, (2, 2, 2, 2), num_classes, dtype)


def ResNet34(num_classes=10, dtype=jnp.float32):
    return ResNet(BasicBlock, (3, 4, 6, 3), num_classes, dtype)


def ResNet50(num_classes=10, dtype=jnp.float32):
    return ResNet(Bottleneck, (3, 4, 6, 3), num_classes, dtype)


def ResNet101(num_classes=10, dtype=jnp.float32):
    return ResNet(Bottleneck, (3, 4, 23, 3), num_classes, dtype)


def ResNet152(num_classes=10, dtype=jnp.float32):
    return ResNet(Bottleneck, (3, 8, 36, 3), num_classes, dtype)

"""Aksel GAR: average of the gradients closest to the coordinate-wise median.

Counterpart of pytorch_impl/libs/aggregators/aksel.py (:24-64): compute the
coordinate-wise median, rank gradients by squared Euclidean distance to it,
and average the c closest, where c = (n+1)//2 in mode "mid" or c = n-f in
mode "n-f". Requires n >= 2f+1.
"""

import jax.numpy as jnp
import numpy as np

from . import register
from ._common import as_stack, coordinate_median, num_gradients


def _selection(g, f, mode):
    n = g.shape[0]
    med = coordinate_median(g)
    # f32 accumulation: under a bf16 pipeline an input-dtype sum over ~1e7
    # terms absorbs late addends and quantizes the ranking — the flat,
    # tree, and folded paths must make the SAME selections (the
    # pairwise_distances/tree_gram parity rule, _common.py).
    dist = jnp.sum(
        jnp.square((g - med[None, :]).astype(jnp.float32)), axis=1
    )
    return jnp.argsort(dist)[: _count(n, f, mode)], _count(n, f, mode)


def _weights(dist, n, c):
    """1/c one-hot weights over the c rows closest to the median — the
    single source of the selection, shared by every path."""
    sel = jnp.argsort(dist)[:c]
    return jnp.zeros((n,), jnp.float32).at[sel].set(1.0 / c)


def _count(n, f, mode):
    if mode == "mid":
        return (n + 1) // 2
    if mode == "n-f":
        return n - f
    raise NotImplementedError(f"unknown aksel mode {mode!r}")


def aggregate(gradients, f, mode="mid", **kwargs):
    """Average of the c gradients closest to the coordinate median."""
    g = as_stack(gradients)
    sel, _ = _selection(g, f, mode)
    return jnp.mean(g[sel], axis=0)


def tree_aggregate(stacked_tree, f, mode="mid", **kwargs):
    """Tree-mode aksel: per-leaf medians (Pallas kernels on TPU), the
    distances-to-median tree-reduce as sums of per-leaf squared norms, and
    the average is one per-leaf weighted row sum — no (n, d) flat stack."""
    import jax

    from ._common import tree_coordinatewise, tree_weighted_sum

    leaves = jax.tree.leaves(stacked_tree)
    n = leaves[0].shape[0]
    med = tree_coordinatewise(coordinate_median, stacked_tree)
    dist = sum(
        jnp.sum(
            jnp.square(
                (l - m[None]).astype(jnp.float32).reshape(n, -1)
            ),
            axis=1,
        )
        for l, m in zip(leaves, jax.tree.leaves(med))
    )
    return tree_weighted_sum(
        stacked_tree, _weights(dist, n, _count(n, f, mode))
    )


def fold_flat_aggregate(ext_stack, row_map, row_scale, f=0, key=None,
                        mode="mid", **kwargs):
    """Folded-attack form (parallel/fold.py): median of the poisoned rows
    via the remapped-row Pallas kernel, distances via per-row scalars of
    the raw extended stack (direct cancellation-free ||row - med|| for
    unit-scale rows; the additive expansion for scaled rows), selection
    average as one scattered-weight matvec — the poisoned stack never
    materializes."""
    import numpy as np_

    from .. import ops

    rows = ext_stack.shape[0]
    rmap = np_.asarray(row_map)
    scales = np_.asarray(row_scale, np_.float32)
    n = rmap.size
    med = ops.coordinate_median(ext_stack, row_map=rmap, row_scale=scales)
    med32 = med.astype(jnp.float32)
    finite = jnp.isfinite(ext_stack)
    x_safe = jnp.where(finite, ext_stack, 0)
    # Subtract in the STACK dtype and upcast only for the square (ADVICE
    # r5 #3): the flat/tree paths compute (g - med) in the input dtype
    # before the f32 cast, so a f32 subtraction here would round the sort
    # keys differently under a bf16 pipeline and rank near-tied rows
    # differently — the same quantize-before-square rule as
    # ops._avgmed_kernel's ``quant_dtype``. Unit-scale rows (every row of
    # the lie/empire/crash folds) now match the where-path bitwise; the
    # additive expansion for exotic scales below stays f32 (its where-path
    # counterpart materializes scaled rows, which no dtype choice here can
    # reproduce exactly — it is selection-equivalent away from exact ties).
    dev = (x_safe - med.astype(ext_stack.dtype)[None, :]).astype(jnp.float32)
    nsq_direct = jnp.sum(dev * dev, axis=1)
    unit_mask = scales == 1.0
    if bool(unit_mask.all()):
        dist = nsq_direct[rmap]
    elif bool((scales[~unit_mask] == 0.0).all()):
        # Only zero scales besides units (the crash fold): the expansion
        # degenerates to ||med||^2 — skip the sq/dot stack passes.
        msq = jnp.sum(med32 * med32)
        dist = jnp.where(jnp.asarray(unit_mask), nsq_direct[rmap], msq)
    else:
        sq = jnp.sum(jnp.square(x_safe.astype(jnp.float32)), axis=1)
        dot = jnp.sum(x_safe.astype(jnp.float32) * med32[None, :], axis=1)
        msq = jnp.sum(med32 * med32)
        s = jnp.asarray(scales)
        dist = jnp.where(
            jnp.asarray(unit_mask),
            nsq_direct[rmap],
            jnp.maximum(s * s * sq[rmap] - 2.0 * s * dot[rmap] + msq, 0.0),
        )
    # The spec ranks by squared distance where non-finite rows sort by
    # their (non-finite) distance; mirror pairwise semantics: non-finite
    # logical rows rank last (+inf), and zero-scaled rows are exact zero
    # vectors whatever the raw row holds.
    row_bad = jnp.any(~finite, axis=1)[rmap] & jnp.asarray(scales != 0)
    dist = jnp.where(row_bad, jnp.inf, dist)
    w_log = _weights(dist, n, _count(n, f, mode))
    w_phys = (
        jnp.zeros((rows,), jnp.float32)
        .at[rmap]
        .add(w_log * jnp.asarray(scales))
    )
    # x_safe is already non-finite-sanitized, so no extra row mask is
    # needed (a per-row `used` built with .at[rmap].set would be
    # nondeterministic for the duplicate physical indices lie/empire
    # plans produce).
    return jnp.matmul(
        w_phys.astype(ext_stack.dtype), x_safe,
        preferred_element_type=jnp.float32,
    ).astype(ext_stack.dtype)


def check(gradients, f, mode="mid", **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 1:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = {f!r}, "
            f"expected 1 <= f <= {(n - 1) // 2}"
        )
    if mode not in ("mid", "n-f"):
        return f"invalid operation mode {mode!r}"
    return None


def influence(honests, attacks, f, mode="mid", **kwargs):
    """Ratio of Byzantine gradients among the c selected (aksel.py:76-98)."""
    stack = jnp.concatenate([as_stack(honests), as_stack(attacks)], axis=0)
    sel, c = _selection(stack, f, mode)
    sel = np.asarray(sel)
    return float(np.sum(sel >= len(honests))) / c


register("aksel", aggregate, check, influence=influence,
         tree_aggregate=tree_aggregate,
         fold_flat_aggregate=fold_flat_aggregate)

"""Pallas coordinate-kernel tests (interpret mode on the CPU test mesh).

The jnp reference implementations in garfield_tpu/ops/coordinate.py ARE the
spec (they reproduce the torch semantics of the reference's median.py:39 and
bulyan.py:77-84); the kernels must match them bit-for-bit, including NaN
placement and stable tie-breaking.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu.ops import coordinate


def _rand(n, d, seed, nan_frac=0.0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    if nan_frac:
        mask = rng.random((n, d)) < nan_frac
        # never a full-NaN column beyond what median tolerates
        mask[0] = False
        x = np.where(mask, np.nan, x)
    return x


@pytest.mark.parametrize("n", [1, 2, 3, 8, 9, 15])
@pytest.mark.parametrize("d", [1, 64, 130, 1024])
def test_median_matches_reference(n, d):
    x = _rand(n, d, seed=n * 1000 + d)
    got = coordinate.coordinate_median(x, interpret=True, tile=128)
    want = coordinate.coordinate_median_reference(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_median_nan_resilient():
    x = _rand(9, 257, seed=7, nan_frac=0.2)
    got = coordinate.coordinate_median(x, interpret=True, tile=128)
    want = coordinate.coordinate_median_reference(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_median_even_n_takes_lower():
    x = np.array([[1.0, 10.0], [2.0, 20.0], [3.0, 30.0], [4.0, 40.0]], np.float32)
    got = coordinate.coordinate_median(x, interpret=True, tile=128)
    np.testing.assert_array_equal(np.asarray(got), [2.0, 20.0])


@pytest.mark.parametrize("s,beta", [(3, 1), (5, 3), (8, 4), (9, 9), (11, 5)])
def test_averaged_median_mean_matches_reference(s, beta):
    x = _rand(s, 300, seed=s * 31 + beta)
    got = coordinate.averaged_median_mean(x, beta, interpret=True, tile=128)
    want = coordinate.averaged_median_mean_reference(jnp.asarray(x), beta)
    # rtol floor 1e-5, atol 1e-7: interpret-mode accumulation order drifts
    # by a ulp or two across jax releases (observed 1e-8 abs on 0.4.37);
    # selection flips would show as whole-row ~1e-1 jumps, not last-ulp.
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-7
    )


def test_averaged_median_mean_stable_ties():
    # Rows 0 and 2 are equidistant from the median; stable argsort must pick
    # the lower row index. Any unstable sort averages a different pair.
    x = np.array([[0.0], [1.0], [2.0], [5.0]], np.float32)  # median = 1.0
    got = coordinate.averaged_median_mean(x, 2, interpret=True, tile=128)
    want = coordinate.averaged_median_mean_reference(jnp.asarray(x), 2)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got), [0.5])  # rows 1 then 0


def test_averaged_median_mean_nan():
    x = _rand(7, 140, seed=3, nan_frac=0.15)
    got = coordinate.averaged_median_mean(x, 3, interpret=True, tile=128)
    want = coordinate.averaged_median_mean_reference(jnp.asarray(x), 3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_beta_bounds():
    x = _rand(4, 8, seed=0)
    with pytest.raises(ValueError):
        coordinate.averaged_median_mean(x, 0, interpret=True)
    with pytest.raises(ValueError):
        coordinate.averaged_median_mean(x, 5, interpret=True)


def test_dispatch_falls_back_off_tpu():
    # On the CPU test backend use_pallas() is False: public wrappers must
    # route to the jnp reference and still be correct.
    assert not coordinate.use_pallas()
    x = _rand(6, 50, seed=11)
    np.testing.assert_array_equal(
        np.asarray(coordinate.coordinate_median(x)),
        np.asarray(coordinate.coordinate_median_reference(jnp.asarray(x))),
    )


def test_cpu_lowering_on_tpu_default_process(monkeypatch):
    """ADVICE r1 / VERDICT r2 #7 regression: a computation jitted for CPU
    devices in a process whose DEFAULT backend is TPU must take the XLA
    fallback, not fail lowering the Pallas kernel. The per-call choice is
    made by ``lax.platform_dependent`` at lowering time; simulate the
    TPU-default process by patching ``jax.default_backend`` so the
    ``use_pallas`` gate opens, then lower+run on this CPU backend."""
    monkeypatch.setattr(coordinate.jax, "default_backend", lambda: "tpu")
    assert coordinate.use_pallas()  # gate open: dispatch reaches the router
    x = _rand(6, 50, seed=13)
    try:
        got = jax.jit(coordinate.coordinate_median)(x)
    except ValueError as e:
        if "interpret mode" in str(e):
            # Old jax lowers EVERY lax.platform_dependent branch behind a
            # runtime platform-index select instead of pruning to the
            # lowering platforms, so the Pallas TPU branch poisons CPU
            # lowering outright. The per-call router this test guards
            # only exists where pruning does; nothing to regress here.
            pytest.skip(
                "this jax has no per-platform pruning in "
                "lax.platform_dependent; TPU-default router untestable "
                "on a CPU-only runtime"
            )
        raise
    np.testing.assert_array_equal(
        np.asarray(got),
        np.asarray(coordinate.coordinate_median_reference(jnp.asarray(x))),
    )
    got = jax.jit(lambda a: coordinate.averaged_median_mean(a, 3))(x)
    np.testing.assert_allclose(
        np.asarray(got),
        np.asarray(
            coordinate.averaged_median_mean_reference(jnp.asarray(x), 3)
        ),
        rtol=1e-6,
    )


def test_median_bf16():
    """bfloat16 stacks go through the same kernels (16-sublane tiling)."""
    x = _rand(9, 257, seed=21).astype(jnp.bfloat16)
    got = coordinate.coordinate_median(x, interpret=True, tile=128)
    want = coordinate.coordinate_median_reference(jnp.asarray(x))
    assert got.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(got, np.float32), np.asarray(want, np.float32)
    )


def test_averaged_median_mean_bf16():
    x = _rand(7, 140, seed=22).astype(jnp.bfloat16)
    got = coordinate.averaged_median_mean(x, 3, interpret=True, tile=128)
    want = coordinate.averaged_median_mean_reference(jnp.asarray(x), 3)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=1e-2,
    )


@pytest.mark.parametrize("n,f", [(3, 1), (7, 2), (9, 0), (11, 5)])
def test_trimmed_mean_matches_reference(n, f):
    x = _rand(n, 300, seed=n * 17 + f, nan_frac=0.05 if f else 0.0)
    got = coordinate.trimmed_mean(x, f, interpret=True, tile=128)
    want = coordinate.trimmed_mean_reference(jnp.asarray(x), f)
    # Same interpret-mode ulp allowance as the avgmed reference rows.
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-7
    )


def test_trimmed_mean_bounds():
    x = _rand(4, 8, seed=1)
    with pytest.raises(ValueError):
        coordinate.trimmed_mean(x, 2, interpret=True)  # n - 2f = 0


@pytest.mark.parametrize("s,beta", [(8, 4), (33, 13), (64, 31), (128, 17)])
def test_averaged_median_mean_xla_matches_reference(s, beta):
    """The gather-free production fallback == the argsort+gather spec,
    including at n > MAX_SORT_N where it is the only non-Pallas path."""
    x = _rand(s, 300, seed=s * 7 + beta, nan_frac=0.05)
    got = coordinate.averaged_median_mean_xla(jnp.asarray(x), beta)
    want = coordinate.averaged_median_mean_reference(jnp.asarray(x), beta)
    # atol: the masked sum and the gathered mean accumulate in different
    # orders; near-zero coordinates differ by O(1e-8) in f32.
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_averaged_median_mean_xla_stable_ties():
    x = np.array([[0.0], [1.0], [2.0], [5.0]], np.float32)  # median = 1.0
    got = coordinate.averaged_median_mean_xla(jnp.asarray(x), 2)
    np.testing.assert_array_equal(np.asarray(got), [0.5])  # rows 1 then 0
    # Duplicated deviations across MANY rows: quota admits exactly the
    # lowest-index ties.
    x2 = np.array([[1.0], [1.0], [1.0], [1.0], [9.0]], np.float32)
    got2 = coordinate.averaged_median_mean_xla(jnp.asarray(x2), 3)
    want2 = coordinate.averaged_median_mean_reference(jnp.asarray(x2), 3)
    np.testing.assert_array_equal(np.asarray(got2), np.asarray(want2))


def test_averaged_median_mean_xla_nan_flood():
    """> s - beta NaN rows per coordinate: spec result is NaN; the
    threshold formulation must restore it, not silently emit 0."""
    x = np.full((5, 3), np.nan, np.float32)
    x[0] = 1.0  # one finite row, beta=3 must pull 2 NaN rows
    got = coordinate.averaged_median_mean_xla(jnp.asarray(x), 3)
    want = coordinate.averaged_median_mean_reference(jnp.asarray(x), 3)
    assert np.isnan(np.asarray(want)).all()
    assert np.isnan(np.asarray(got)).all()


def test_large_n_fallback_warns_only_on_tpu_backend(monkeypatch):
    """n > MAX_SORT_N: silent on CPU (Pallas was never an option), loud on
    a TPU backend (the 75x fused path is being given up)."""
    x = _rand(coordinate.MAX_SORT_N + 1, 16, seed=2)
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")  # CPU backend: must NOT warn
        coordinate.coordinate_median(x)
    monkeypatch.setattr(
        coordinate.jax, "default_backend", lambda: "tpu"
    )
    coordinate._warned_large_n.discard("coordinate_median")
    with pytest.warns(UserWarning, match="MAX_SORT_N"):
        assert coordinate.use_pallas(
            coordinate.MAX_SORT_N + 1, op="coordinate_median"
        ) is False
    # ... and only once per op per process.
    with _w.catch_warnings():
        _w.simplefilter("error")
        coordinate.use_pallas(
            coordinate.MAX_SORT_N + 1, op="coordinate_median"
        )


def test_large_n_warning_recommends_hierarchy(monkeypatch):
    """Satellite pin (ISSUE 6): the n > MAX_SORT_N warning must point the
    user at the hierarchical bucketed rules (the recommended fix), and the
    XLA fallback it announces must be GRACEFUL — same result as the jnp
    reference at a federated-ish n."""
    monkeypatch.setattr(coordinate.jax, "default_backend", lambda: "tpu")
    coordinate._warned_large_n.discard("trimmed_mean")
    with pytest.warns(UserWarning) as rec:
        assert coordinate.use_pallas(64, op="trimmed_mean") is False
    text = str(rec[0].message)
    assert "MAX_SORT_N=32" in text
    assert "hier-krum" in text and "hierarchy" in text
    # Graceful XLA-path result at n > MAX_SORT_N (the non-Pallas path is
    # the spec itself).
    monkeypatch.setattr(coordinate.jax, "default_backend", lambda: "cpu")
    x = _rand(64, 200, seed=3)
    np.testing.assert_array_equal(
        np.asarray(coordinate.coordinate_median(x)),
        np.asarray(coordinate.coordinate_median_reference(x)),
    )


class TestSortNet:
    """The jnp odd-even-network entry points (the hierarchical bucket
    fold's coordinate fast path): bitwise-equal semantics to the reference
    sorts, batch axes, NaN resilience, and the MAX_SORT_N bound."""

    def test_median_matches_reference_bitwise(self):
        x = _rand(17, 300, seed=21)
        np.testing.assert_array_equal(
            np.asarray(coordinate.sortnet_median(x, axis=0)),
            np.asarray(coordinate.coordinate_median_reference(x)),
        )

    def test_median_batched_matches_per_bucket(self):
        xb = np.stack([_rand(8, 64, seed=s) for s in range(5)])
        got = np.asarray(coordinate.sortnet_median(xb, axis=1))
        want = np.stack([
            np.asarray(coordinate.coordinate_median_reference(xb[i]))
            for i in range(5)
        ])
        np.testing.assert_array_equal(got, want)

    def test_median_nan_resilient(self):
        x = _rand(9, 40, seed=22)
        x[:3, :] = np.nan  # up to ceil(n/2)-1 NaNs sort last
        np.testing.assert_array_equal(
            np.asarray(coordinate.sortnet_median(x, axis=0)),
            np.asarray(coordinate.coordinate_median_reference(x)),
        )

    def test_tmean_matches_reference(self):
        x = _rand(16, 128, seed=23)
        np.testing.assert_allclose(
            np.asarray(coordinate.sortnet_trimmed_mean(x, 3, axis=0)),
            np.asarray(coordinate.trimmed_mean_reference(x, 3)),
            rtol=1e-6, atol=1e-6,
        )

    def test_bounded_by_max_sort_n(self):
        with pytest.raises(ValueError, match="MAX_SORT_N"):
            coordinate.sortnet_median(
                np.zeros((coordinate.MAX_SORT_N + 1, 4), np.float32), axis=0)


@pytest.mark.parametrize("op", ["median", "tmean"])
def test_remap_kernel_matches_materialized(op):
    """row_map/row_scale (the folded-attack remap, parallel/fold.py) applied
    in-register must equal materializing the remapped stack first —
    including a duplicated fake row (lie) and a scaled row (reverse)."""
    ext = _rand(9, 300, seed=11)  # 8 raw rows + 1 fake row
    row_map = np.array([0, 1, 2, 3, 4, 5, 8, 8])  # byz rows 6,7 -> fake
    row_scale = np.array([1.0, 1.0, 1.0, 1.0, 1.0, -100.0, 1.0, 1.0])
    eff = ext[row_map] * row_scale[:, None].astype(np.float32)
    if op == "median":
        got = coordinate.coordinate_median(
            ext, row_map=row_map, row_scale=row_scale,
            interpret=True, tile=128,
        )
        want = coordinate.coordinate_median_reference(jnp.asarray(eff))
    else:
        got = coordinate.trimmed_mean(
            ext, 2, row_map=row_map, row_scale=row_scale,
            interpret=True, tile=128,
        )
        want = coordinate.trimmed_mean_reference(jnp.asarray(eff), 2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
    )


def test_remap_validates_bounds():
    x = _rand(4, 16, seed=3)
    with pytest.raises(ValueError):
        coordinate.coordinate_median(x, row_map=[0, 1, 2, 9])
    with pytest.raises(ValueError):
        coordinate.coordinate_median(
            x, row_map=[0, 1], row_scale=[1.0, 1.0, 1.0]
        )


class TestSortNetSelection:
    """The index-carrying network entry points (PR 19's selection
    kernels): bitwise-equal to ``jnp.argsort(..., stable=True)`` —
    stable ties, NaN-last — under vmap and bf16 upcast, plus the krum
    score's chained prefix sum and the MAX_SORT_N bound. These are the
    substitutability pins that let GARFIELD_SORTNET_SELECT default on
    without moving any Gram-path trajectory."""

    def _keys(self, w, n, seed, ties=False, nans=0):
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((w, n)).astype(np.float32)
        if ties:
            # Quantize hard so duplicate keys are guaranteed: stability
            # is only observable on ties.
            x = np.round(x * 2.0) / 2.0
        if nans:
            for r in range(w):
                x[r, rng.choice(n, size=nans, replace=False)] = np.nan
        return x

    @pytest.mark.parametrize("n", [1, 2, 3, 8, 17, 32])
    @pytest.mark.parametrize("ties,nans", [(False, 0), (True, 0),
                                           (False, 2), (True, 2)])
    def test_argsort_matches_stable_argsort(self, n, ties, nans):
        if nans >= n:
            pytest.skip("need at least one finite key")
        x = self._keys(6, n, seed=n * 7 + nans, ties=ties, nans=nans)
        got = np.asarray(coordinate.sortnet_argsort(x, axis=-1))
        want = np.asarray(jnp.argsort(x, axis=-1, stable=True))
        np.testing.assert_array_equal(got, want)

    def test_argmin_and_top_m_are_argsort_prefixes(self):
        x = self._keys(5, 16, seed=3, ties=True, nans=1)
        ref = np.asarray(jnp.argsort(x, axis=-1, stable=True))
        np.testing.assert_array_equal(
            np.asarray(coordinate.sortnet_argmin(x, axis=-1)), ref[:, 0])
        np.testing.assert_array_equal(
            np.asarray(coordinate.sortnet_top_m(x, 5, axis=-1)),
            ref[:, :5])

    def test_sort_matches_jnp_sort_bitwise(self):
        x = self._keys(4, 23, seed=9, ties=True, nans=3)
        np.testing.assert_array_equal(
            np.asarray(coordinate.sortnet_sort(x, axis=-1)),
            np.asarray(jnp.sort(x, axis=-1)))

    def test_vmap_matches_loop(self):
        xb = self._keys(7, 12, seed=5, ties=True)
        got = np.asarray(jax.vmap(
            lambda r: coordinate.sortnet_top_m(r, 4, axis=-1))(xb))
        want = np.stack([
            np.asarray(coordinate.sortnet_top_m(xb[i], 4, axis=-1))
            for i in range(7)
        ])
        np.testing.assert_array_equal(got, want)

    def test_bf16_upcast_orders_like_f32(self):
        x = jnp.asarray(self._keys(4, 20, seed=11, ties=True),
                        jnp.bfloat16)
        np.testing.assert_array_equal(
            np.asarray(coordinate.sortnet_argsort(x, axis=-1)),
            np.asarray(jnp.argsort(x.astype(jnp.float32), axis=-1,
                                   stable=True)))

    def test_row_sums_matches_chained_sorted_prefix(self):
        x = self._keys(6, 14, seed=13)
        k = 9
        rows = np.asarray(jnp.sort(x, axis=-1))
        acc = rows[:, 0]
        for i in range(1, k):
            acc = acc + rows[:, i]  # same chain shape as the kernel
        np.testing.assert_array_equal(
            np.asarray(coordinate.sortnet_row_sums(x, k, axis=-1)), acc)

    def test_bounded_by_max_sort_n_exact_message(self):
        n = coordinate.MAX_SORT_N + 1
        with pytest.raises(ValueError, match=(
                rf"sorting-network path is bounded by "
                rf"MAX_SORT_N={coordinate.MAX_SORT_N}, got n={n}; use the "
                rf"XLA sort or bucket hierarchically")):
            coordinate.sortnet_argsort(np.zeros((2, n), np.float32))
        with pytest.raises(ValueError, match="MAX_SORT_N"):
            coordinate.sortnet_row_sums(np.zeros((n, 2), np.float32).T, 3)

    def test_top_m_and_row_sums_validate_bounds(self):
        x = np.zeros((3, 8), np.float32)
        with pytest.raises(ValueError, match=r"m must be in \[1, 8\]"):
            coordinate.sortnet_top_m(x, 0)
        with pytest.raises(ValueError, match=r"k must be in \[1, 8\]"):
            coordinate.sortnet_row_sums(x, 9)

"""AggregaThor: single trusted PS, n workers, f Byzantine (SSMW).

Counterpart of ``pytorch_impl/applications/Aggregathor/trainer.py`` (P17).
The reference launches one process per node and branches on rank
(:217-268); here one driver jits the whole round as an SPMD program over a
"workers" mesh axis (garfield_tpu/parallel/aggregathor.py).

Reference default experiment (run_exp.sh:5-14,39-40):

  python -m garfield_tpu.apps.aggregathor --dataset cifar10 --model resnet50 \\
      --batch 25 --num_workers 8 --fw 2 --gar krum --attack lie \\
      --optimizer sgd --opt_args '{"lr":"0.2","momentum":"0.9","weight_decay":"0.0005"}' \\
      --lr_decay_epochs 30 --num_iter 100000
"""

import sys

from ..parallel import aggregathor
from . import common


def main(argv=None):
    parser = common.base_parser(
        "AggregaThor implementation using garfield-tpu"
    )
    parser.add_argument(
        "--cluster", type=str, default=None,
        help="Cluster config JSON (utils/multihost.ClusterConfig): run as "
             "ONE process of a multi-process deployment over PeerExchange "
             "(true wait-n-f; the reference's run_exp.sh fan-out shape) "
             "instead of the on-mesh SPMD fold.",
    )
    parser.add_argument(
        "--task", type=str, default=None,
        help='Role override for --cluster, "ps:0" or "worker:K" (default: '
             "the config's own task section).",
    )
    parser.add_argument(
        "--cluster_timeout_ms", type=int, default=60_000,
        help="Per-step collect timeout in cluster mode (the bounded-retry "
             "exit of the reference, ps.py:84-88).",
    )
    args = parser.parse_args(argv)
    if args.cluster:
        from . import cluster

        args.num_workers = None  # worker count comes from the config
        return cluster.run(args)
    assert args.fw * 2 < args.num_workers, (
        "the number of Byzantine workers should be less than half the number "
        "of workers"  # Aggregathor/trainer.py:150-152 invariant
    )
    make_trainer_kwargs = dict(
        num_workers=args.num_workers,
        f=args.fw,
        attack=args.attack,
        attack_params=args.attack_params,
        subset=args.subset,
        granularity=args.granularity,
    )
    from ..utils import rounds

    policy = rounds.resolve(args)
    if policy is not None:
        # On-mesh --async: the seeded in-graph emulation of the host
        # plane's bounded-staleness mode (parallel/aggregathor
        # ``staleness=``; DESIGN.md §14) — same weighting law, same
        # flags, one policy deployed at either scale.
        make_trainer_kwargs["staleness"] = {
            "max_staleness": policy.max_staleness,
            "decay": policy.decay,
        }
    return common.train(
        args,
        topology=aggregathor,
        make_trainer_kwargs=make_trainer_kwargs,
        num_slots=args.num_workers,
        tag="aggregathor",
    )


if __name__ == "__main__":
    main(sys.argv[1:])

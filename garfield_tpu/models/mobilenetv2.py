"""MobileNetV2 (counterpart of garfieldpp/models/mobilenetv2.py):
inverted residual blocks, CIFAR-scale."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm

# (expansion, out_planes, num_blocks, stride)
cfg = [(1, 16, 1, 1), (6, 24, 2, 1), (6, 32, 3, 2), (6, 64, 4, 2),
       (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]


class InvertedResidual(nn.Module):
    expansion: int
    out_planes: int
    stride: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        in_planes = x.shape[-1]
        planes = self.expansion * in_planes
        out = nn.relu(norm(train, dtype=d)(conv1x1(planes, dtype=d)(x)))
        out = nn.relu(norm(train, dtype=d)(
            conv(planes, 3, self.stride, padding=1, groups=planes, dtype=d)(out)))
        out = norm(train, dtype=d)(conv1x1(self.out_planes, dtype=d)(out))
        if self.stride == 1:
            shortcut = x if in_planes == self.out_planes else norm(train, dtype=d)(
                conv1x1(self.out_planes, dtype=d)(x))
            out = out + shortcut
        return out


class MobileNetV2(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        x = nn.relu(norm(train, dtype=d)(conv(32, 3, 1, padding=1, dtype=d)(x)))
        for expansion, out_planes, num_blocks, stride in cfg:
            for i in range(num_blocks):
                s = stride if i == 0 else 1
                x = InvertedResidual(expansion, out_planes, s, dtype=d)(x, train)
        x = nn.relu(norm(train, dtype=d)(conv1x1(1280, dtype=d)(x)))
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)

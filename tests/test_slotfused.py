"""Slot-fused gradient twins (models/slotfused.py + core.per_slot_grads).

The twin must deliver the SAME per-slot gradients/losses/batch_stats as the
reference unroll (vmap-compatible layout) — exactly for models whose math
involves no cross-example statistics (cifarnet), and to deep-net f32
reassociation tolerance for BatchNorm models (the fused batch reorders
reductions; ~1e-3 relative after ResNet-18's 20 layers of amplification).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu.models import select_model, slotfused
from garfield_tpu.parallel import core
from garfield_tpu.utils import selectors

N, B = 4, 6


def _setup(model, dataset, shape):
    module = select_model(model, dataset)
    loss_fn = selectors.select_loss("nll")
    init_fn, grad_fn, _ = core.make_worker_fns(module, loss_fn)
    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (N, B) + shape)
    y = jax.random.randint(k, (N, B), 0, 10)
    keys = jax.random.split(k, N)
    params, ms = init_fn(k, x[0])
    return module, loss_fn, grad_fn, params, ms, x, y, keys


def _unroll(grad_fn, params, ms, x, y, keys):
    outs = [grad_fn(params, ms, x[i], y[i], keys[i]) for i in range(N)]
    g = jax.tree.map(lambda *ls: jnp.stack(ls), *[o[0] for o in outs])
    loss = jnp.stack([o[1][0] for o in outs])
    ms_out = jax.tree.map(lambda *ls: jnp.stack(ls), *[o[1][1] for o in outs])
    return g, loss, ms_out


@pytest.mark.parametrize("model,dataset,shape,rtol", [
    ("cifarnet", "cifar10", (32, 32, 3), 1e-5),
    # ResNet-18: ~20 layers of BN-curvature amplification of f32
    # reassociation; measured ~5e-3 rel L2 against the unroll on CPU.
    ("resnet18", "cifar10", (32, 32, 3), 2e-2),
])
def test_twin_matches_unroll(model, dataset, shape, rtol):
    module, loss_fn, grad_fn, params, ms, x, y, keys = _setup(
        model, dataset, shape
    )
    slot_fn = slotfused.build_slot_grad_fn(module, loss_fn)
    assert slot_fn is not None
    g_t, (loss_t, ms_t) = jax.jit(slot_fn)(params, ms, x, y, keys)
    g_u, loss_u, ms_u = _unroll(grad_fn, params, ms, x, y, keys)
    np.testing.assert_allclose(
        np.asarray(loss_t), np.asarray(loss_u), rtol=1e-5, atol=1e-6
    )
    ft = np.asarray(jax.flatten_util.ravel_pytree(g_t)[0])
    fu = np.asarray(jax.flatten_util.ravel_pytree(g_u)[0])
    rel = np.linalg.norm(ft - fu) / np.linalg.norm(fu)
    assert rel < rtol, f"per-slot gradient rel L2 {rel} >= {rtol}"
    if jax.tree.leaves(ms_u):
        mt = np.asarray(jax.flatten_util.ravel_pytree(ms_t)[0])
        mu = np.asarray(jax.flatten_util.ravel_pytree(ms_u)[0])
        np.testing.assert_allclose(mt, mu, rtol=1e-4, atol=1e-6)


def test_slot_path_decision():
    """Run-length-aware unroll/vmap choice (VERDICT r4 #8): the fused twin
    wins when available; a reference-scale 100k-iter n=64 run takes the
    unroll automatically; a short unknown-length large-n run keeps vmap."""
    d = core.slot_path_decision
    assert d(64, 100_000, True)[0] == "fused"
    assert d(8, None, False)[0] == "unroll"           # under the cap
    assert d(64, 100_000, False)[0] == "unroll"        # amortized
    assert d(64, 100, False)[0] == "vmap"              # too short
    assert d(64, None, False)[0] == "vmap"             # unknown length


def test_unsupported_models_return_none():
    """Dropout models (convnet) keep the unroll: a twin cannot replicate
    flax's internal rng-path folding."""
    module = select_model("convnet", "mnist")
    loss_fn = selectors.select_loss("nll")
    assert slotfused.build_slot_grad_fn(module, loss_fn) is None


def test_dw_modes_agree(monkeypatch):
    """grouped (default) and unroll dw formulations are the same math."""
    module, loss_fn, grad_fn, params, ms, x, y, keys = _setup(
        "cifarnet", "cifar10", (32, 32, 3)
    )
    slot_fn = slotfused.build_slot_grad_fn(module, loss_fn)
    g_grouped, _ = slot_fn(params, ms, x, y, keys)
    monkeypatch.setattr(slotfused, "DW_MODE", "unroll")
    g_unrolled, _ = slot_fn(params, ms, x, y, keys)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        g_grouped, g_unrolled,
    )


def test_per_slot_grads_routes_fused():
    module, loss_fn, grad_fn, params, ms, x, y, keys = _setup(
        "cifarnet", "cifar10", (32, 32, 3)
    )
    slot_fn = slotfused.build_slot_grad_fn(module, loss_fn)
    g_f, _ = core.per_slot_grads(
        grad_fn, params, ms, x, y, keys, fused_fn=slot_fn
    )
    g_u, _, _ = _unroll(grad_fn, params, ms, x, y, keys)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        g_f, g_u,
    )


def test_trainer_env_escape_hatch(monkeypatch):
    """GARFIELD_NO_SLOTFUSED forces the unroll in the topology builder and
    both paths produce working trainers with close trajectories."""
    import optax

    from garfield_tpu.parallel import aggregathor

    module = select_model("cifarnet", "cifar10")
    loss_fn = selectors.select_loss("nll")
    k = jax.random.PRNGKey(1)
    # 2 slots per shard so the builder actually engages the fused path
    # (per_shard == 1 has nothing to fold).
    n_w = 2 * jax.device_count()
    x = jax.random.normal(k, (n_w, 4, 32, 32, 3))
    y = jax.random.randint(k, (n_w, 4), 0, 10)
    finals = []
    for disable in (False, True):
        if disable:
            monkeypatch.setenv("GARFIELD_NO_SLOTFUSED", "1")
        else:
            monkeypatch.delenv("GARFIELD_NO_SLOTFUSED", raising=False)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss_fn, optax.sgd(0.05), "median",
            num_workers=n_w, f=1, attack="lie",
        )
        state = init_fn(jax.random.PRNGKey(2), x[0])
        for _ in range(3):
            state, metrics = step_fn(state, x, y)
        finals.append(np.asarray(
            jax.flatten_util.ravel_pytree(state.params)[0]
        ))
    np.testing.assert_allclose(finals[0], finals[1], rtol=1e-4, atol=1e-6)

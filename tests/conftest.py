"""Test configuration: force a virtual 8-device CPU platform.

This is the fake-backend the reference lacked (SURVEY §4): every distributed
construct is testable single-process by running the SPMD program over
XLA_FLAGS=--xla_force_host_platform_device_count=8. Must be set before jax
is imported anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

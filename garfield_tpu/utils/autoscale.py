"""Load-driven worker autoscaling: the elastic half of the async plane.

The churn machinery (DESIGN.md §14) lets the cluster SURVIVE workers
appearing and disappearing — a killed worker's frames expire past the
staleness cutoff, a relaunched one rejoins through ``read_latest`` and
re-enters the admissible set. This module adds the other half (ROADMAP
item 3): PROVISIONING for load. A PS-side controller watches the round
telemetry it already produces — round wall time and the quorum's
admissibility margin — and decides when to spawn a fresh worker process
or retire a running one, so the deployment tracks a THROUGHPUT TARGET
instead of a fixed n.

Why round rate scales with the worker count in async mode: workers
publish-and-continue, so the bounded-staleness gather's binding
constraint in steady state is its freshness floor — at least one NEW
admissible frame per harvest (exchange.RoundCollector). W workers each
producing a gradient every T seconds supply W/T fresh frames per second,
so the PS's sustainable round rate is ~W/T: adding workers adds rate
linearly until the PS's own aggregate/update cost dominates. (The
synchronous plane has no such lever — its rate is pinned to the slowest
quorum member regardless of W, which is exactly why autoscaling composes
with ``--async`` and is refused without it.)

The control law is deliberately boring (hysteresis + cooldown, the
shape every production autoscaler converges to):

  - rate = window / sum(round_s over the last ``window`` rounds) — the
    MEAN-based throughput, deliberately not a median: async rounds
    complete in BURSTS (several workers' frames land together, a batch
    of harvests clears in microseconds, then a stall until the next
    batch), and a median over such a window reads the burst, not the
    throughput;
  - rate < target * up_margin  and active < max  ->  spawn one;
  - rate > target * down_margin and active > min and the quorum was
    never short an admissible frame all window      ->  retire one;
  - after any action, wait ``cooldown`` rounds with a CLEARED window so
    the new membership's steady state is measured, not the transient.

``target_rate <= 0`` auto-calibrates: the first full window's measured
rate becomes the target, so a deployment scaled for its initial load
holds that service level through load spikes (the exchange_bench
``scaleup`` scenario) without anyone computing a number up front.

The mechanics of spawning/retiring live with the caller (apps/cluster.py
spawns real OS processes via ``worker_command``; the bench spawns follow
children): the controller only decides. Retirement is a CLEAN teardown,
not a kill: the PS sends the worker its stop sentinel (the worker exits
rc 0 through its normal end-of-run path), retires its exchange watchers
(``PeerExchange.remove_peer`` — the symmetric-teardown contract) and
drops it from the collector; a later spawn of the same rank rejoins
through the existing ``read_latest`` catch-up path and re-reads its own
data shard (re-admit = re-shard).
"""

import collections
import dataclasses
import sys

__all__ = [
    "AutoscaleConfig",
    "AutoscaleController",
    "worker_command",
]


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """The deployment's elasticity contract.

    ``target_rate`` is rounds/s (<= 0 auto-calibrates from the first
    full window); ``min_workers``/``max_workers`` bound the active set
    (the min must keep the GAR feasible at q = min - f — the caller
    checks, it knows the rule); ``window`` rounds feed each decision and
    ``cooldown`` rounds separate consecutive actions.
    """

    target_rate: float = 0.0
    min_workers: int = 1
    max_workers: int = 1
    window: int = 8
    cooldown: int = 8
    up_margin: float = 0.9
    down_margin: float = 1.3

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError(
                f"min_workers must be >= 1, got {self.min_workers}"
            )
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) must be >= min_workers "
                f"({self.min_workers})"
            )
        if self.window < 1 or self.cooldown < 0:
            raise ValueError(
                f"window must be >= 1 and cooldown >= 0, got "
                f"({self.window}, {self.cooldown})"
            )
        if not 0 < self.up_margin <= 1.0 <= self.down_margin:
            raise ValueError(
                "margins must satisfy 0 < up_margin <= 1 <= down_margin, "
                f"got ({self.up_margin}, {self.down_margin})"
            )


class AutoscaleController:
    """Rolling-window rate controller; ``observe`` returns -1/0/+1.

    Host-side and allocation-free per round: one deque append and (on
    decision rounds) one median of ``window`` floats — nothing a
    sub-millisecond async round would notice.
    """

    def __init__(self, cfg):
        self.cfg = cfg
        self.target = float(cfg.target_rate)
        self._round_s = collections.deque(maxlen=cfg.window)
        self._margin_ok = collections.deque(maxlen=cfg.window)
        self._since_action = cfg.cooldown  # first decision needs no wait
        self.actions = 0
        self._pre_action = None  # rescind() snapshot (see _acted)

    def rate(self):
        """Mean throughput over the current window (rounds / total
        seconds — see the module docstring for why not a median), or
        None before the window fills (or right after an action clears
        it)."""
        if len(self._round_s) < self.cfg.window:
            return None
        total = sum(self._round_s)
        return (len(self._round_s) / total) if total > 0 else None

    def observe(self, round_s, *, active, quorum_margin=0):
        """Fold one round; returns +1 (spawn), -1 (retire) or 0.

        ``active`` is the current worker count, ``quorum_margin`` the
        gather's admissibility surplus (admissible frames minus q). A
        NEGATIVE margin anywhere in the window means the quorum already
        struggled (degrades/timeouts) — retiring into that would turn a
        wobble into an outage, so scale-down requires a clean window.
        """
        self._pre_action = None  # a rescind is only valid IMMEDIATELY
        self._round_s.append(float(round_s))
        self._margin_ok.append(quorum_margin >= 0)
        self._since_action += 1
        rate = self.rate()
        if rate is None:
            return 0
        if self.target <= 0:
            # Auto-calibration: the first full window IS the service
            # level this deployment signed up for.
            self.target = rate
            return 0
        if self._since_action <= self.cfg.cooldown:
            return 0
        if rate < self.target * self.cfg.up_margin:
            if active < self.cfg.max_workers:
                self._acted()
                return 1
            return 0
        if (rate > self.target * self.cfg.down_margin
                and active > self.cfg.min_workers
                and all(self._margin_ok)):
            self._acted()
            return -1
        return 0

    def _acted(self):
        self.actions += 1
        # Snapshot the pre-action accounting so a caller that cannot
        # actually perform the advised action (capacity, wire caps, no
        # standby) can rescind() it — a refused action must not consume
        # the cooldown window (the old behavior silenced the controller
        # for a full cooldown + window refill after doing NOTHING).
        self._pre_action = (
            list(self._round_s), list(self._margin_ok), self._since_action
        )
        self._since_action = 0
        # Measure the NEW membership's steady state, not the transient
        # (a spawning worker pays tens of seconds of jax boot; counting
        # those rounds would trigger a second spawn for the same cause).
        self._round_s.clear()
        self._margin_ok.clear()

    def rescind(self):
        """Undo the accounting of the action the LAST ``observe`` call
        advised — the caller refused it (fleet at its index capacity, a
        shard split past the wire header's 16-slot nibble, no standby
        to merge into). Restores the measurement window, the cooldown
        clock and the action count to their pre-advice state, so the
        refusal is accounting-free: the controller keeps measuring the
        UNCHANGED membership instead of a transient that never
        happened. Returns True if there was an action to rescind;
        becomes a no-op (False) once any later ``observe`` folds — at
        that point the window has moved on and a partial restore would
        splice two measurement regimes."""
        if self._pre_action is None:
            return False
        round_s, margin_ok, since = self._pre_action
        self._round_s.extend(round_s)
        self._margin_ok.extend(margin_ok)
        self._since_action = since
        self.actions -= 1
        self._pre_action = None
        return True


# CLI flags that configure the PS-side controller and must NOT leak into
# a spawned worker's command line (the worker would try to autoscale
# too). --task is re-written, not dropped.
_PS_ONLY_VALUED = (
    "--task", "--target_rate", "--autoscale_min", "--autoscale_max",
    "--autoscale_window", "--autoscale_cooldown",
)
_PS_ONLY_FLAGS = ("--autoscale",)


def worker_command(windex, argv=None, main_module=None, role="worker"):
    """This process's CLI, re-targeted at the ``{role}:windex`` role.

    The PS was launched as ``python -m garfield_tpu.apps.<app> --cluster
    ... --task ps:0 ...``; a spawned worker runs the SAME app with the
    same flags (dataset/model/gar/async must agree across roles — a
    disagreement is the wire codec's deployment-error path) minus the
    PS-only autoscale knobs, plus its own ``--task``. The module name
    comes from ``__main__.__spec__`` (set by ``-m`` execution); running
    the PS some other way must pass ``main_module`` explicitly.
    ``role`` generalizes the task name — the federated fleet spawns
    ``client:K`` drivers through the same derivation
    (federated/fleet.client_command).
    """
    if main_module is None:
        spec = getattr(sys.modules.get("__main__"), "__spec__", None)
        main_module = getattr(spec, "name", None)
        if main_module is None:
            raise RuntimeError(
                "cannot derive the worker command: the PS was not "
                "launched with `python -m <app>` (no __main__.__spec__); "
                "pass main_module explicitly"
            )
        if main_module.endswith(".__main__"):
            main_module = main_module[: -len(".__main__")]
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    out = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in _PS_ONLY_FLAGS or a.startswith(
            tuple(f + "=" for f in _PS_ONLY_VALUED)
        ):
            i += 1
            continue
        if a in _PS_ONLY_VALUED:
            i += 2
            continue
        out.append(a)
        i += 1
    return [sys.executable, "-m", main_module, *out,
            "--task", f"{role}:{int(windex)}"]

"""Web-demo test: the de-facto multi-node-on-one-host harness (SURVEY §4
item 4 — the reference's only distributed test was its demo; ours runs the
real HTTP server + a real tiny LEARN training)."""

import http.client
import json
import threading
import time

import pytest

from garfield_tpu.apps import demo

# Spins a live training thread + HTTP server: minutes per test by design
# (tier-1 fast shard skips via -m 'not slow').
pytestmark = pytest.mark.slow


def _request(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path, body=json.dumps(body) if body else None)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_demo_trains_via_http():
    from http.server import ThreadingHTTPServer

    server = ThreadingHTTPServer(("127.0.0.1", 0), demo.Handler)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        status, page = _request(port, "GET", "/")
        assert status == 200 and b"LEARN" in page

        status, _ = _request(
            port, "POST", "/train",
            {"nodes": 4, "f": 1, "gar": "median", "attack": "lie",
             "epochs": 1},
        )
        assert status == 200

        deadline = time.time() + 120
        final = None
        while time.time() < deadline:
            status, data = _request(port, "GET", "/status")
            final = json.loads(data)
            assert final.get("error") is None, final
            if final.get("done"):
                break
            time.sleep(0.5)
        assert final and final.get("done"), f"timed out: {final}"
        assert 0.0 <= final["accuracy"] <= 1.0
        assert final["step"] == final["total"]
        # Per-node progress + topology data (VERDICT r2 #8): one loss per
        # node, Byzantine flags on the last f ranks, rendered by the page.
        assert len(final["node_losses"]) == 4
        assert final["byz_nodes"] == [False, False, False, True]
        assert all(l == l for l in final["node_losses"][:3])  # honest finite
        status, page = _request(port, "GET", "/")
        assert b"drawTopo" in page and b"node_losses" in page

        status, _ = _request(port, "GET", "/nope")
        assert status == 404
    finally:
        server.shutdown()

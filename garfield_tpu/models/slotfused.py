"""Slot-fused per-worker gradients: fused fwd + fused dx, per-slot dw.

The round-4 closing decomposition (PERF.md, VERDICT r4 #1) left ONE big
cost on the table: folding n logical workers onto a chip with a Python
unroll pays ~8x the op count of a single fused fwd+bwd — measured 9.0 ms
(unroll, n=8 b=25 ResNet-18 bf16) against a 5.1 ms fused lower bound,
while both do identical FLOPs. vmap closes the op count but loses more to
5-D relayouts and grouped-conv weight gradients (12.9 ms; unrolling the
grouped dw inside vmap measured WORSE, 14.0 — r5 probe).

The structural fix implemented here: per-slot gradients only *differ* from
the fused computation in the parameter-cotangent contractions. Everything
else — the forward, the activation cotangents (dx), every elementwise op —
is identical arithmetic for "n workers of batch b" and "one batch n*b".
So run the model ONCE on the flat (n*b) batch and make ONLY the parameter
gradients slot-resolved:

  - every parameter enters the forward STACKED to (slots, ...) — the jax
    autodiff cotangent of a stacked parameter IS the per-slot gradient;
  - convolutions go through ``slot_conv`` (jax.custom_vjp): primal and dx
    use ``w[0]`` (all slot rows are equal by construction) at the fused
    n*b batch; the dw rule computes n per-slot conv weight gradients — the
    unrolled formulation the chip prefers (a both-batched grouped conv
    measured 2.9x slower at the primitive level, PERF.md r3);
  - dense layers become slot-batched matmuls ('sbf,sfo->sbo'), which the
    MXU handles natively — autodiff's dk ('sbf,sbo->sfo') is a batched
    matmul too, no custom rule needed;
  - BatchNorm computes per-slot statistics by a (slots, b, ...) reshaped
    reduction (a view, not a relayout: the 5-D tensor only feeds the
    reduce; the normalize stays on the flat 4-D batch with the per-slot
    stats broadcast back via ``_slot_expand``) — matching the per-worker
    BN semantics of the unroll path exactly;
  - scale/bias/bias-like parameters use ``_slot_expand`` (broadcast +
    reshape), whose autodiff transpose is a per-slot segment sum.

The result is per-slot gradients equal to the unroll path's (asserted in
tests/test_slotfused.py — exactly for cifarnet, to deep-net f32
reassociation tolerance for the BN families) at close to fused cost.

These are functional TWINS of the flax zoo modules (resnet.py / nets.py's
Cifarnet): they consume the exact flax param/batch_stats trees by name, so
``core.TrainState``, checkpoints and eval keep using the flax module while
only the gradient phase routes through the twin. Twins exist for the
model families where the win matters and the semantics are deterministic
(no dropout — a twin cannot replicate flax's internal rng-path folding,
so dropout models keep the unroll); ``build_slot_grad_fn`` returns None
for everything else and callers fall back to ``core.per_slot_grads``.

Reference anchor: this whole module replaces the per-worker backward pass
of Aggregathor/worker.py:89-91 (one process per worker on its own GPU);
folding n workers onto one chip has no reference counterpart.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["build_slot_grad_fn", "slot_conv"]

_DN = ("NHWC", "HWIO", "NHWC")


def _conv(x, w, stride, padding):
    return lax.conv_general_dilated(
        x, w, window_strides=stride, padding=padding, dimension_numbers=_DN
    )


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def slot_conv(x, w_st, stride, padding, slots):
    """Convolution over the flat (slots*b) batch with a STACKED kernel.

    ``w_st`` is (slots, kh, kw, ci, co) with all slot rows equal (a
    broadcast of the shared kernel); the primal and dx use ``w_st[0]`` at
    the fused batch, and the custom vjp returns the PER-SLOT weight
    gradients as ``w_st``'s cotangent — the only place worker-resolved
    arithmetic is actually required.
    """
    return _conv(x, w_st[0], stride, padding)


def _slot_conv_fwd(x, w_st, stride, padding, slots):
    return _conv(x, w_st[0], stride, padding), (x, w_st[0])


import os as _os

# dw formulation: "grouped" = ONE batch-grouped conv producing all slot
# kernels (no sliced operands, no stack); "unroll" = n per-slot convs +
# stack (traced 3.0 ms/step of operand copies + 1.6 ms of stack DUS at
# n=8 ResNet-18 — kept as the A/B escape hatch).
DW_MODE = _os.environ.get("GARFIELD_SLOTFUSED_DW", "grouped")


def _slot_conv_bwd(stride, padding, slots, res, dy):
    x, w0 = res
    # dx: one fused transposed conv over the whole n*b batch.
    dx = jax.linear_transpose(lambda x_: _conv(x_, w0, stride, padding), x)(
        dy
    )[0]
    nb = x.shape[0] // slots
    xs = x.reshape(slots, nb, *x.shape[1:])
    dys = dy.reshape(slots, nb, *dy.shape[1:])
    if DW_MODE == "grouped":
        # ONE grouped conv via the transpose of the slot-vmapped conv: the
        # (slots, nb) reshape is a view of the flat activations, so no
        # per-slot operand copies and the (slots, ...) result needs no
        # stacking DUS.
        def vconv(w_st_):
            return jax.vmap(
                lambda xi, wi: _conv(xi, wi, stride, padding)
            )(xs, w_st_)

        w_like = jnp.broadcast_to(w0[None], (slots,) + w0.shape)
        dw_st = jax.linear_transpose(vconv, w_like)(dys)[0]
        return dx, dw_st
    dws = [
        jax.linear_transpose(
            lambda w_: _conv(xs[i], w_, stride, padding), w0
        )(dys[i])[0]
        for i in range(slots)
    ]
    return dx, jnp.stack(dws)


slot_conv.defvjp(_slot_conv_fwd, _slot_conv_bwd)


def _slot_matrix(slots, nb, dtype=jnp.float32):
    """Constant (slots, slots*nb) slot-membership one-hot matrix.

    Per-slot segment reductions over the flat batch are expressed as this
    tiny matmul instead of a (slots, nb, ...) reshaped reduce: XLA lowers
    the grouped reduce over the MAJOR dim through transposing copies
    (traced 1.4 ms/step at ResNet-18 n=8), while `S @ (per-example
    reduction)` stays in natural layouts — and its autodiff transpose,
    `S.T @ _`, is the equally clean per-slot broadcast."""
    return jnp.repeat(jnp.eye(slots, dtype=dtype), nb, axis=1)


def _slot_expand(v_st, nb, spatial_dims):
    """(slots, C) per-slot vector -> flat per-example (slots*nb, 1..1, C).

    The S.T matmul twin of the stats reduction: its autodiff transpose is
    (spatial reduce -> S @ _), so the BN backward's per-slot segment sums
    take the same copy-free route as the forward stats (a broadcast+reshape
    formulation transposes to the 5-D grouped reduce this module avoids).
    """
    n = v_st.shape[0]
    S = _slot_matrix(n, nb, dtype=v_st.dtype)
    flat = S.T @ v_st  # (slots*nb, C)
    return flat.reshape(
        (flat.shape[0],) + (1,) * spatial_dims + (flat.shape[-1],)
    )


def _slot_bn_train(x, p_st, stats, slots, dtype, momentum=0.9, eps=1e-5):
    """Per-slot BatchNorm (train mode), flax-numerics-compatible.

    Statistics are computed in f32 over each slot's (b, H, W) block via a
    reshaped reduction (flax nn.BatchNorm computes f32 stats with the fast
    mean-of-squares variance); the normalize runs on the FLAT batch in the
    compute dtype with the per-slot stats expanded back. Returns
    ``(y, {"mean": (slots, C), "var": (slots, C)})`` where the new running
    stats follow flax's ``m*old + (1-m)*batch`` per slot — the per-worker
    semantics the unroll path produces.
    """
    nb = x.shape[0] // slots
    # Per-slot stats as (spatial reduce -> (n*b, C)) then a tiny one-hot
    # matmul — see _slot_matrix for why not a 5-D reshaped reduce.
    xf = x.astype(jnp.float32)
    spatial = tuple(range(1, xf.ndim - 1))
    denom = 1.0 / (nb * int(np.prod([x.shape[a] for a in spatial])))
    e1 = jnp.sum(xf, axis=spatial)          # (slots*nb, C)
    e2 = jnp.sum(xf * xf, axis=spatial)     # (slots*nb, C)
    S = _slot_matrix(slots, nb)
    mean = (S @ e1) * denom                 # (slots, C)
    var = (S @ e2) * denom - mean * mean
    new_stats = {
        "mean": momentum * stats["mean"][None] + (1.0 - momentum) * mean,
        "var": momentum * stats["var"][None] + (1.0 - momentum) * var,
    }
    new_stats = jax.tree.map(jax.lax.stop_gradient, new_stats)
    sd = x.ndim - 2
    # Exactly flax _normalize's association — y = (x - mean) * (rsqrt(var
    # + eps) * scale) + bias — so the twin's float rounding tracks the flax
    # path as closely as the fused batch allows (a reassociated scale/shift
    # form measured ~1e-3 relative after 20 layers of amplification).
    # Stats stay f32 (flax _compute_stats); the elementwise normalize runs
    # in the COMPUTE dtype like flax _normalize — an f32 normalize would
    # double the HBM traffic of every BN under the bf16 pipeline.
    mul = (jax.lax.rsqrt(var + eps)
           * p_st["scale"].astype(jnp.float32)).astype(dtype)
    y = (
        (x.astype(dtype) - _slot_expand(mean.astype(dtype), nb, sd))
        * _slot_expand(mul, nb, sd)
        + _slot_expand(p_st["bias"].astype(dtype), nb, sd)
    )
    return y, new_stats


def _slot_dense(x2, p_st, slots, dtype):
    """(slots*b, F) @ per-slot kernel -> (slots, b, O) via a slot-batched
    matmul; autodiff's dk is a slot-batched matmul too (MXU-native)."""
    nb = x2.shape[0] // slots
    x3 = x2.reshape(slots, nb, -1).astype(dtype)
    y = jnp.einsum("sbf,sfo->sbo", x3, p_st["kernel"].astype(dtype))
    if "bias" in p_st:
        y = y + p_st["bias"].astype(dtype)[:, None, :]
    return y


def _relu(x):
    return jax.nn.relu(x)


def _max_pool_flat(x, window=2):
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        (1, window, window, 1), (1, window, window, 1), "VALID",
    )


# --------------------------------------------------------------------------
# ResNet twin (models/resnet.py: BasicBlock and Bottleneck stacks)
# --------------------------------------------------------------------------

def _bn_relu(h, p, s, name, new, slots, dtype, relu=True):
    y, ns = _slot_bn_train(h, p[name], s[name], slots, dtype)
    new[name] = ns
    return _relu(y) if relu else y


def _basic_block(h, p, s, new, features, stride, slots, dtype):
    out = slot_conv(
        h, p["Conv_0"]["kernel"].astype(dtype),
        (stride, stride), ((1, 1), (1, 1)), slots,
    )
    out = _bn_relu(out, p, s, "BatchNorm_0", new, slots, dtype)
    out = slot_conv(
        out, p["Conv_1"]["kernel"].astype(dtype),
        (1, 1), ((1, 1), (1, 1)), slots,
    )
    out = _bn_relu(out, p, s, "BatchNorm_1", new, slots, dtype, relu=False)
    if stride != 1 or h.shape[-1] != features:
        h = slot_conv(
            h, p["Conv_2"]["kernel"].astype(dtype),
            (stride, stride), ((0, 0), (0, 0)), slots,
        )
        h = _bn_relu(h, p, s, "BatchNorm_2", new, slots, dtype, relu=False)
    return _relu(out + h)


def _bottleneck(h, p, s, new, features, stride, slots, dtype):
    out = slot_conv(
        h, p["Conv_0"]["kernel"].astype(dtype),
        (1, 1), ((0, 0), (0, 0)), slots,
    )
    out = _bn_relu(out, p, s, "BatchNorm_0", new, slots, dtype)
    out = slot_conv(
        out, p["Conv_1"]["kernel"].astype(dtype),
        (stride, stride), ((1, 1), (1, 1)), slots,
    )
    out = _bn_relu(out, p, s, "BatchNorm_1", new, slots, dtype)
    out = slot_conv(
        out, p["Conv_2"]["kernel"].astype(dtype),
        (1, 1), ((0, 0), (0, 0)), slots,
    )
    out = _bn_relu(out, p, s, "BatchNorm_2", new, slots, dtype, relu=False)
    if stride != 1 or h.shape[-1] != features * 4:
        h = slot_conv(
            h, p["Conv_3"]["kernel"].astype(dtype),
            (stride, stride), ((0, 0), (0, 0)), slots,
        )
        h = _bn_relu(h, p, s, "BatchNorm_3", new, slots, dtype, relu=False)
    return _relu(out + h)


def _resnet_forward(p_st, stats, x, slots, dtype, stage_sizes, block_kind):
    """Flat-batch forward of models/resnet.py's ResNet, stacked params.

    Returns ``(logits (slots, b, classes), new_batch_stats)`` with the
    flax module's exact naming so the caller's trees interoperate.
    """
    new = {}
    h = slot_conv(
        x.astype(dtype), p_st["Conv_0"]["kernel"].astype(dtype),
        (1, 1), ((1, 1), (1, 1)), slots,
    )
    h = _bn_relu(h, p_st, stats, "BatchNorm_0", new, slots, dtype)
    block_fn = _basic_block if block_kind == "basic" else _bottleneck
    idx = 0
    for stage, nblocks in enumerate(stage_sizes):
        for i in range(nblocks):
            stride = 2 if stage > 0 and i == 0 else 1
            name = (
                f"BasicBlock_{idx}" if block_kind == "basic"
                else f"Bottleneck_{idx}"
            )
            bnew = {}
            h = block_fn(
                h, p_st[name], stats[name], bnew,
                64 * 2 ** stage, stride, slots, dtype,
            )
            new[name] = bnew
            idx += 1
    h = h.mean(axis=(1, 2))  # global_avg_pool -> (slots*b, C)
    logits = _slot_dense(h, p_st["Dense_0"], slots, dtype)
    return logits, new


# --------------------------------------------------------------------------
# Cifarnet twin (models/nets.py:40-57 — convs + dense head, no BN/dropout)
# --------------------------------------------------------------------------

def _cifarnet_forward(p_st, stats, x, slots, dtype):
    del stats
    nb = x.shape[0] // slots

    def conv_bias(h, p):
        h = slot_conv(
            h, p["kernel"].astype(dtype), (1, 1), ((0, 0), (0, 0)), slots
        )
        return h + _slot_expand(p["bias"].astype(dtype), nb, 2)

    def dense(h3, p, relu=True):
        y = _slot_dense(h3.reshape(slots * nb, -1), p, slots, dtype)
        return _relu(y) if relu else y

    h = _max_pool_flat(_relu(conv_bias(x.astype(dtype), p_st["Conv_0"])))
    h = _max_pool_flat(_relu(conv_bias(h, p_st["Conv_1"])))
    h = dense(h.reshape(h.shape[0], -1), p_st["Dense_0"])
    h = dense(h, p_st["Dense_1"])
    return dense(h, p_st["Dense_2"], relu=False), {}


# --------------------------------------------------------------------------
# Dispatch
# --------------------------------------------------------------------------

def build_slot_grad_fn(module, loss_fn):
    """A drop-in for the vmap/unroll per-slot gradient computation.

    Returns ``fn(params, model_state, x, y, keys) -> (grads, (loss, ms))``
    with the same shapes/semantics as
    ``jax.vmap(grad_fn, in_axes=(None, None, 0, 0, 0))`` — stacked grads,
    per-slot losses, per-slot updated batch_stats — or None when the
    module has no twin (callers fall back to ``core.per_slot_grads``).
    """
    from . import nets, resnet

    dtype = getattr(module, "dtype", jnp.float32)
    if isinstance(module, resnet.ResNet):
        kind = "basic" if module.block is resnet.BasicBlock else (
            "bottleneck" if module.block is resnet.Bottleneck else None
        )
        if kind is None:
            return None
        stage_sizes = tuple(module.stage_sizes)

        def forward(p_st, stats, x_flat, slots):
            return _resnet_forward(
                p_st, stats, x_flat, slots, dtype, stage_sizes, kind
            )
    elif isinstance(module, nets.Cifarnet):
        def forward(p_st, stats, x_flat, slots):
            return _cifarnet_forward(p_st, stats, x_flat, slots, dtype)
    else:
        return None

    def slot_grad_fn(params, model_state, x, y, keys):
        del keys  # twins exist only for deterministic (dropout-free) models
        slots, b = x.shape[0], x.shape[1]
        x_flat = x.reshape((slots * b,) + x.shape[2:])
        stats = model_state.get("batch_stats", {})
        p_st = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (slots,) + p.shape), params
        )

        def total_loss(p_st):
            logits, new_stats = forward(p_st, stats, x_flat, slots)
            losses = jax.vmap(loss_fn)(logits, y)  # (slots,)
            return jnp.sum(losses), (losses, new_stats)

        grads_st, (losses, new_stats) = jax.grad(
            total_loss, has_aux=True
        )(p_st)
        # Every collection comes back slot-stacked like the vmap path:
        # batch_stats per-slot from the twin, anything else broadcast.
        new_ms = {
            k: (
                new_stats if k == "batch_stats"
                else jax.tree.map(
                    lambda l: jnp.broadcast_to(
                        l[None], (slots,) + jnp.shape(l)
                    ),
                    v,
                )
            )
            for k, v in model_state.items()
        }
        return grads_st, (losses, new_ms)

    return slot_grad_fn

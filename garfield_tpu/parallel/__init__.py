"""SPMD parallel training core: mesh, roles-as-functions, and the three
Byzantine-resilient topologies of the reference (SURVEY §2.3):

  - ``aggregathor`` — single trusted PS, n workers (SSMW;
    pytorch_impl/applications/Aggregathor/); ``granularity="layer"`` gives
    the Garfield_CC per-parameter collective semantics; num_workers=1, f=0
    degenerates to the Centralized baseline.
  - ``byzsgd``      — replicated Byzantine PS (MSMW / GuanYu;
    pytorch_impl/applications/ByzSGD/).
  - ``learn``       — fully decentralized gossip (LEARN;
    pytorch_impl/applications/LEARN/).

Each exposes ``make_trainer(...) -> (init_fn, step_fn, eval_fn)`` with
``step_fn`` one jit'd SPMD program over the ICI mesh — the reference's
RPC / NCCL / gRPC round trips (SURVEY §2.3 comm-backend row) appear only as
XLA all_gather/psum collectives inside it.
"""

import jax
import jax.numpy as jnp
import numpy as np

from . import aggregathor, byzsgd, core, learn, mesh
from .core import TrainState, default_byz_mask, make_worker_fns
from .mesh import make_mesh

__all__ = [
    "aggregathor",
    "byzsgd",
    "learn",
    "core",
    "mesh",
    "TrainState",
    "default_byz_mask",
    "make_worker_fns",
    "make_mesh",
    "topologies",
    "compute_accuracy",
    "compute_accuracy_async",
]

topologies = {
    "centralized": aggregathor,  # num_workers=1, f=0 (P16)
    "aggregathor": aggregathor,  # P17
    "byzsgd": byzsgd,  # P18
    "learn": learn,  # P19
    "garfield_cc": aggregathor,  # P20 — granularity="layer"
}


def _accuracy_counts(state, eval_fn, test_batches, *, binary=False):
    """Enqueue the full eval pass; return (correct, total) with ``correct``
    a DEVICE scalar — no host synchronization happens here.

    The per-batch compare+sum runs on device, so the caller decides when to
    pay the host readback (which on tunneled backends costs ~0.1 s per
    conversion — the old per-batch ``np.asarray`` made inline eval stall
    the step stream for seconds).
    """
    correct = jnp.zeros((), jnp.int32)
    total = 0
    for x, y in test_batches:
        logits = eval_fn(state, jnp.asarray(x))
        y_np = np.asarray(y).reshape(-1)
        yj = jnp.asarray(y_np)
        if binary:
            # pima path: sigmoid output, threshold 0.5 (demo.py accuracy).
            pred = (logits.reshape(-1) > 0.5).astype(yj.dtype)
            correct = correct + jnp.sum(pred == yj)
        else:
            correct = correct + jnp.sum(logits.argmax(-1) == yj)
        total += int(y_np.shape[0])
    return correct, total


def compute_accuracy(state, eval_fn, test_batches, *, binary=False):
    """Top-1 accuracy over a list of (x, y) test batches.

    Counterpart of ``Server.compute_accuracy`` (server.py:235-254) / the TF
    ``compute_accuracy`` (tensorflow_impl/libs/server.py:152-163). ``binary``
    follows the pima path (single sigmoid logit, byzWorker-era threshold 0.5).
    """
    correct, total = _accuracy_counts(
        state, eval_fn, test_batches, binary=binary
    )
    return int(correct) / max(total, 1)


def compute_accuracy_async(state, eval_fn, test_batches, *, binary=False,
                           on_done=None, after=None):
    """Overlapped accuracy: enqueue the eval pass now, pay the host readback
    in a side thread — the SPMD analog of the reference's accuracy thread
    (Aggregathor/trainer.py:251-264).

    All device work is dispatched synchronously in the caller's thread
    BEFORE returning, so a subsequent donating ``step_fn(state)`` call is
    safe: the enqueued eval executions already hold their buffer references
    and are sequenced ahead of the donated step on the device stream. Only
    the blocking scalar conversion moves off the training thread.

    ``after``: a previous thread from this function; the new thread waits
    for it before reporting, so successive reports stay in request order.
    Returns the started (daemon) thread; its ``.exc`` attribute holds any
    exception the readback or ``on_done`` raised — join it and re-raise at
    exit, or the failure is silently dropped.
    """
    import threading

    correct, total = _accuracy_counts(
        state, eval_fn, test_batches, binary=binary
    )

    def _finalize():
        try:
            if after is not None:
                after.join()
            acc = int(correct) / max(total, 1)  # the one host readback
            if on_done is not None:
                on_done(acc)
        except BaseException as exc:  # surfaced by the caller at join
            t.exc = exc

    t = threading.Thread(target=_finalize, daemon=True)
    t.exc = None
    t.start()
    return t

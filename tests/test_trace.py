"""Distributed round tracing tests (ISSUE 8): spans, schema v5, report.

Pins the tracing plane's contracts:
  1. span lifecycle — enabled spans record wall start + monotonic
     duration + tags through the hub hook; nesting works; exceptions
     record AND propagate; disabled tracing is the shared no-op (zero
     records, reusable object);
  2. schema v5 — the ``span`` kind and the summary's ``spans``/
     ``phases`` digest validate (and malformed ones fail loudly);
  3. the report merger is DETERMINISTIC on the committed multi-role
     fixture (tests/fixtures/trace_run — a real 1 PS + 4 worker
     --async --trace run with a 300 ms straggler on worker 3) and its
     per-round critical path sums to the measured round time within
     the quoted alignment error;
  4. tracing-on vs tracing-off trajectories are BITWISE equal (spans
     are host-only observers — the taps' purity contract, host
     edition);
  5. every committed ``*_r*.jsonl`` artifact schema-validates
     (scripts/validate_artifacts.py — the tier-1 wiring of the CI
     satellite).
"""

import json
import pathlib
import time

import jax
import numpy as np
import pytest

from garfield_tpu.parallel import aggregathor
from garfield_tpu.telemetry import (
    JsonlExporter,
    MetricsHub,
    SCHEMA_VERSION,
    install,
    make_record,
    prometheus_text,
    report,
    trace,
    uninstall,
    validate_record,
)

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
FIXTURE = REPO_ROOT / "tests" / "fixtures" / "trace_run"


@pytest.fixture
def hub():
    h = MetricsHub(num_ranks=4)
    prev = install(h)
    trace.enable(who="test")
    yield h
    trace.disable()
    uninstall()
    if prev is not None:
        install(prev)


def _spans(h):
    return [r for r in h.records() if r["kind"] == "span"]


class TestSpanLifecycle:
    def test_basic_span_records(self, hub):
        with trace.span("quorum", step=3) as sp:
            time.sleep(0.001)
            sp.set(arrived=7)
        recs = _spans(hub)
        assert len(recs) == 1
        rec = recs[0]
        assert rec["phase"] == "quorum"
        assert rec["step"] == 3
        assert rec["arrived"] == 7
        assert rec["who"] == "test"
        assert rec["dur_s"] >= 0.001
        assert abs(rec["t_wall"] - time.time()) < 5.0
        validate_record(rec)

    def test_nesting(self, hub):
        with trace.span("outer", step=0):
            with trace.span("inner", step=0):
                time.sleep(0.001)
        recs = {r["phase"]: r for r in _spans(hub)}
        assert set(recs) == {"outer", "inner"}
        # The inner span is emitted first (exits first) and nests
        # inside the outer one on both clocks.
        assert recs["inner"]["dur_s"] <= recs["outer"]["dur_s"]
        assert recs["inner"]["t_wall"] >= recs["outer"]["t_wall"] - 1e-6
        in_end = recs["inner"]["t_wall"] + recs["inner"]["dur_s"]
        out_end = recs["outer"]["t_wall"] + recs["outer"]["dur_s"]
        assert in_end <= out_end + 1e-3

    def test_exception_recorded_and_propagates(self, hub):
        with pytest.raises(RuntimeError):
            with trace.span("broadcast", step=1):
                raise RuntimeError("boom")
        (rec,) = _spans(hub)
        assert rec["phase"] == "broadcast"
        assert rec["error"] == "RuntimeError"
        validate_record(rec)

    def test_disabled_is_shared_noop(self):
        trace.disable()
        s1, s2 = trace.span("a", step=0), trace.span("b")
        assert s1 is s2  # the reusable null span: zero allocation growth
        with s1 as sp:
            sp.set(x=1)  # no-op, no error
        assert not trace.enabled()

    def test_no_hub_is_safe(self):
        # Enabled tracing without an installed hub must not raise.
        uninstall()
        trace.enable(who="nohub")
        try:
            with trace.span("publish", step=0):
                pass
        finally:
            trace.disable()

    def test_phase_stats_and_last_round(self, hub):
        for step in (0, 1):
            with trace.span("gar_apply", step=step):
                time.sleep(0.001)
        stats = hub.phase_stats()
        assert stats["gar_apply"]["count"] == 2
        assert stats["gar_apply"]["p50_s"] >= 0.001
        # Last COMPLETED round = second-newest step seen.
        step, phases = hub.last_round_phases()
        assert step == 0
        assert "gar_apply" in phases

    def test_prometheus_phase_histogram(self, hub):
        with trace.span("collect", step=0):
            time.sleep(0.001)
        text = prometheus_text(hub)
        assert 'garfield_phase_seconds_bucket{phase="collect",le="+Inf"} 1' \
            in text
        assert 'garfield_phase_seconds_count{phase="collect"} 1' in text

    def test_sink_streams_spans(self, hub, tmp_path):
        exp = JsonlExporter(tmp_path / "s.jsonl")
        hub._sink = exp
        with trace.span("eval", step=2):
            pass
        exp.close()
        lines = [json.loads(l) for l in open(tmp_path / "s.jsonl")]
        assert lines and lines[0]["kind"] == "span"
        assert lines[0]["phase"] == "eval"


class TestSchemaV5:
    def test_version_bumped(self):
        # v5 introduced spans; v6 (elastic asynchrony) is additive on
        # top — span records are unchanged.
        assert SCHEMA_VERSION >= 5

    def test_span_valid(self):
        validate_record(make_record(
            "span", phase="quorum", t_wall=1e9, dur_s=0.01, step=3,
            who="cluster-ps", tid=0, arrived=3,
        ))
        # step/who optional
        validate_record(make_record("span", phase="x", t_wall=0.0,
                                    dur_s=0.0))

    @pytest.mark.parametrize("bad", [
        {"phase": "", "t_wall": 0.0, "dur_s": 0.1},
        {"phase": "q", "dur_s": 0.1},                       # no t_wall
        {"phase": "q", "t_wall": 0.0, "dur_s": -1.0},       # negative dur
        {"phase": "q", "t_wall": 0.0, "dur_s": 0.1, "step": -1},
        {"phase": "q", "t_wall": 0.0, "dur_s": 0.1, "step": 1.5},
        {"phase": "q", "t_wall": 0.0, "dur_s": 0.1, "who": 7},
    ])
    def test_span_invalid(self, bad):
        with pytest.raises(ValueError):
            validate_record(make_record("span", **bad))

    def test_summary_phases(self):
        validate_record(make_record(
            "summary", steps=1, events=0, spans=4,
            phases={"quorum": {"count": 2, "p50_s": 0.1}},
        ))
        with pytest.raises(ValueError):
            validate_record(make_record(
                "summary", steps=1, events=0, phases={"quorum": "fast"},
            ))
        with pytest.raises(ValueError):
            validate_record(make_record(
                "summary", steps=1, events=0, spans=-2,
            ))

    def test_exchange_bench_trace_fields(self):
        validate_record(make_record(
            "exchange_bench", n=4, d=1000, wire="f32",
            trace_off_round_s=0.01, trace_on_round_s=0.0102,
            trace_overhead=1.02,
            phases={"collect": {"p50_s": 0.008, "p95_s": 0.01}},
        ))
        with pytest.raises(ValueError):
            validate_record(make_record(
                "exchange_bench", n=4, d=1000, wire="f32",
                phases={"collect": [1, 2]},
            ))


class TestReport:
    """The merger on the committed fixture: a real traced SSMW --async
    run (1 PS + 4 workers, worker 3 straggling 300 ms, max_staleness 4,
    10 rounds). The fixture is static, so every assertion here is a
    determinism pin."""

    def test_fixture_present(self):
        assert (FIXTURE / "cluster-ps.telemetry.jsonl").exists()
        assert len(list(FIXTURE.glob("*.telemetry.jsonl"))) == 5

    def test_build_deterministic(self):
        a1 = report.build(str(FIXTURE))
        a2 = report.build(str(FIXTURE))
        md1, md2 = report.render_markdown(a1), report.render_markdown(a2)
        assert md1 == md2
        t1 = json.dumps(report.chrome_trace(a1), sort_keys=True)
        t2 = json.dumps(report.chrome_trace(a2), sort_keys=True)
        assert t1 == t2

    def test_chrome_trace_valid(self):
        tr = report.chrome_trace(report.build(str(FIXTURE)))
        assert tr["traceEvents"]
        names = set()
        pids = set()
        for ev in tr["traceEvents"]:
            assert ev["ph"] in ("X", "M")
            if ev["ph"] == "X":
                assert ev["ts"] >= 0 and ev["dur"] >= 0
                names.add(ev["name"])
            else:
                pids.add(ev["args"]["name"])
        # One process lane per role; the waiter-thread decode spans are
        # present (the collect/compute overlap, visible at last).
        assert len(pids) == 5
        assert {"broadcast", "quorum", "gar_apply", "decode",
                "publish"} <= names

    def test_critical_path_sums_to_round_time(self):
        analysis = report.build(str(FIXTURE))
        crit = analysis["critical_path"]
        assert len(crit) == 10  # num_iter rounds, no sentinel phantom
        err = max(analysis["alignment_error_s"], 1e-3)
        for row in crit:
            # Attribution never exceeds the measured round (no double
            # counting: nested spans are dropped)...
            assert row["attributed_s"] <= row["measured_s"] + err
        # ...and covers it: the per-run residual is untraced host glue,
        # bounded well below the measured total on the fixture.
        total_meas = sum(r["measured_s"] for r in crit)
        total_attr = sum(r["attributed_s"] for r in crit)
        assert total_attr >= 0.9 * total_meas

    def test_straggler_ranking_finds_victim(self):
        analysis = report.build(str(FIXTURE))
        rows = analysis["stragglers"]
        assert rows and rows[0]["role"] == "cluster-worker-3"
        # The injected 300 ms sleep dominates the honest workers' ms-
        # scale lateness by an order of magnitude.
        assert rows[0]["median_lateness_s"] > 10 * max(
            r["median_lateness_s"] for r in rows[1:]
        )

    def test_staleness_reuse_reported(self):
        st = report.build(str(FIXTURE))["staleness"]
        assert st is not None and st["rounds"] == 10
        assert st["reuse_rate"] > 0.5  # the straggler forces heavy reuse

    def test_offsets_causally_bracketed(self):
        offsets = report.build(str(FIXTURE))["offsets"]
        assert offsets["cluster-ps"]["offset_s"] == 0.0
        for name, o in offsets.items():
            if name == "cluster-ps" or o["lb_s"] is None \
                    or o["ub_s"] is None:
                continue
            assert o["lb_s"] <= o["offset_s"] <= o["ub_s"] + 1e-9

    def test_main_writes_artifacts(self, tmp_path, capsys):
        report.main([
            str(FIXTURE),
            "--trace-out", str(tmp_path / "trace.json"),
            "--md-out", str(tmp_path / "report.md"),
        ])
        tr = json.loads((tmp_path / "trace.json").read_text())
        assert tr["traceEvents"]
        md = (tmp_path / "report.md").read_text()
        assert "Per-round critical path" in md
        assert "Straggler ranking" in md


class TestTrajectoryPin:
    def test_tracing_on_off_bitwise(self):
        """Spans are host-only: running the SAME trainer loop with a
        hub installed + tracing enabled (spans wrapped around each
        dispatch, the app loop's instrumentation shape) must leave the
        TrainState bitwise identical to the untraced run."""
        from garfield_tpu import models as models_lib
        from garfield_tpu.utils import selectors

        module = models_lib.select_model("pimanet", "pima")
        loss = selectors.select_loss("bce")
        opt = selectors.select_optimizer("sgd", lr=0.05, momentum=0.9)
        rng = np.random.default_rng(0)
        # (slots, bsz, features): one per-worker shard stack per step.
        x = jax.numpy.asarray(
            rng.normal(size=(8, 16, 8)).astype(np.float32))
        y = jax.numpy.asarray(
            (np.asarray(x).sum(-1, keepdims=True) > 0).astype(np.float32))
        states = []
        for traced in (True, False):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="lie",
            )
            state = init_fn(jax.random.PRNGKey(0), x[0])
            if traced:
                h = MetricsHub(num_ranks=8)
                install(h)
                trace.enable(who="pin")
            try:
                for i in range(5):
                    if traced:
                        with trace.span("dispatch", step=i):
                            state, _ = step_fn(state, x, y)
                    else:
                        state, _ = step_fn(state, x, y)
            finally:
                if traced:
                    trace.disable()
                    uninstall()
            if traced:
                assert h.counters()["spans"] == 5
            states.append(state)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            states[0], states[1],
        )


class TestValidateArtifacts:
    def test_all_committed_artifacts_validate(self, capsys):
        """The CI satellite: scripts/validate_artifacts.py over every
        committed *_r*.jsonl (and the trace fixture) — schema drift in
        a future round fails tier-1 loudly."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "validate_artifacts",
            REPO_ROOT / "scripts" / "validate_artifacts.py",
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        paths = mod.find_artifacts(str(REPO_ROOT))
        # The committed bench captures and the trace fixture are there.
        names = {pathlib.Path(p).name for p in paths}
        assert "EXCHBENCH_r03.jsonl" in names
        assert "cluster-ps.telemetry.jsonl" in names
        assert mod.main(root=str(REPO_ROOT)) == 0


class TestHierIngestAlignment:
    """ISSUE 20 satellite: per-wave ingest accounting. The hierarchy
    reports ONE pre-timed ``hier_ingest`` span per dispatched wave
    (``trace.emit``), so per-level span counts obey
    count(hier_ingest) == count(hier_wave) == count(hier_h2d) EXACTLY —
    the FEDBENCH_r02 capture timed an outer per-push span instead and
    undercounted ingest attribution (11721 ingest vs 12102 fold/h2d
    spans). Pinned over every ingest entry point: per-row push,
    push_many (copy and zero-copy stable), per-frame push_frame, and
    bulk push_frames."""

    def _ingest_paths(self, n, d, frames, g):
        from garfield_tpu.aggregators import hierarchy

        def mk():
            return hierarchy.StreamingAggregator(
                n, 3, bucket_gar="median", bucket_size=8, wave_buckets=2,
                d=d)

        def per_row(red):
            for row in g:
                red.push(row)

        def many_copy(red):
            red.push_many(g.copy())

        def many_stable(red):
            red.push_many(g, stable=True)

        def per_frame(red):
            for fr in frames:
                red.push_frame(fr)

        def bulk_frames(red):
            assert red.push_frames(frames) == list(range(n))

        return mk, (per_row, many_copy, many_stable, per_frame,
                    bulk_frames)

    def test_counts_align_per_level_on_every_path(self, hub):
        from garfield_tpu.utils import wire as wire_mod

        n, d = 64, 16
        rng = np.random.default_rng(11)
        g = rng.normal(size=(n, d)).astype(np.float32)
        frames = [wire_mod.encode(row) for row in g]
        mk, paths = self._ingest_paths(n, d, frames, g)
        seen = 0
        for ingest in paths:
            red = mk()
            ingest(red)
            red.finalize()
            counts = {}
            for rec in _spans(hub)[seen:]:
                if rec["phase"] in ("hier_ingest", "hier_wave",
                                    "hier_h2d"):
                    lv = rec["level"]
                    counts.setdefault(lv, {}).setdefault(
                        rec["phase"], 0)
                    counts[lv][rec["phase"]] += 1
                validate_record(rec)
            seen = len(_spans(hub))
            assert counts, ingest.__name__
            for lv, by_phase in counts.items():
                assert (
                    by_phase.get("hier_ingest", 0)
                    == by_phase.get("hier_wave", 0)
                    == by_phase.get("hier_h2d", 0)
                ), (ingest.__name__, lv, by_phase)
                assert by_phase.get("hier_wave", 0) > 0

    def test_ingest_spans_are_pretimed_and_tagged(self, hub):
        from garfield_tpu.aggregators import hierarchy

        n, d = 32, 8
        rng = np.random.default_rng(5)
        g = rng.normal(size=(n, d)).astype(np.float32)
        red = hierarchy.StreamingAggregator(
            n, 1, bucket_gar="median", bucket_size=8, wave_buckets=2)
        red.push_many(g)
        red.finalize()
        ing = [r for r in _spans(hub) if r["phase"] == "hier_ingest"]
        waves = [r for r in _spans(hub) if r["phase"] == "hier_wave"]
        assert len(ing) == len(waves) > 0
        for rec in ing:
            assert rec["dur_s"] >= 0.0
            assert rec["who"] == "test"
            assert "buckets" in rec and "size" in rec and "level" in rec
            validate_record(rec)

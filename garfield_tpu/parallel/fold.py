"""Folded attack+GAR fast path: poison the Gram, never the rows.

The round-3 profiling conclusion (PERF.md "Known frontier") was that ANY
gradient attack costs ~4.5 ms/step on the north-star krum+lie config because
the whole-tree ``where`` rewrite forces the stacked gradient tree to
materialize and breaks the Gram/weighted-sum-into-backward fusion the
fault-free step enjoys. This module removes that structural tax for the
deterministic attacks by exploiting their row-level algebra
(``attacks.plan_gradient_attack_fold``):

  poisoned row i == row_scale[i] * extended_stack[row_map[i]]

where ``extended_stack`` is the raw stack plus at most one shared fake row
(lie's mu + z*sigma / empire's -eps*mu, byzWorker.py:108-143 — every
colluding Byzantine publishes the SAME vector). Consequently

  poisoned_gram = (scale outer scale) * raw_gram[row_map][:, row_map]

is a static remap of the raw ``(n+1, n+1)`` Gram — computed with ONE extra
row in the per-leaf Gram matmuls that fuse into the backward epilogue
exactly like the fault-free step — and the GAR's selection average is one
weighted row sum over the extended stack. Nothing attack-shaped ever touches
the (n, d)-sized data path.

Measured on the v5e chip (same-process paired-reps, ResNet-18/CIFAR-10, 8
workers, krum f=2 under lie, bf16 pipeline): 14.4-14.7 -> 12.4-12.6 ms/step
(1.16x), within 0.6 ms of the fault-free step — where four round-2/3
attempts that still wrote poisoned rows (elementwise where, row scatter,
contiguous DUS, flat-path algebraic folding) all measured within noise of
each other (PERF.md).

Applies when the topology's tree path is eligible, the attack is
deterministic (lie/empire/reverse/crash), and the rule exposes
``gram_select`` (krum, average). Randomized attacks (random/drop) and
coordinate-wise rules keep the ``where`` tree path.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..aggregators._common import tree_gram, tree_weighted_sum
from ..attacks import plan_gradient_attack_fold

__all__ = ["plan_for", "folded_tree_aggregate"]


def plan_for(gar, attack, byz_mask, attack_params):
    """Single-sourced fold eligibility gate for the topology builders
    (aggregathor AND byzsgd): a plan exists iff the rule has a Gram form
    and the attack folds (deterministic, with actual Byzantine slots, and
    GARFIELD_NO_FOLD unset). ``byz_mask`` may be any array-like; it must be
    concrete (the plan is static)."""
    if gar.gram_select is None:
        return None
    return plan_gradient_attack_fold(
        attack, np.asarray(byz_mask, dtype=bool), **attack_params
    )


def folded_tree_aggregate(gar, plan, stacked_tree, *, f, key=None,
                          gar_params=None):
    """Aggregate a stacked gradient TREE under a folded attack plan.

    Args:
      gar: a registered GAR exposing ``gram_select``.
      plan: ``attacks.GradientAttackFold`` (static row_map/row_scale +
        optional shared fake-row builder).
      stacked_tree: raw per-worker gradients, leading n axis per leaf.
      f: declared tolerance (static).
      key: PRNG key forwarded to ``gram_select`` (none of the current
        Gram-form rules draw randomness; kept for interface parity).
      gar_params: rule hyper-parameters (e.g. krum's ``m``).

    Returns the aggregated gradient tree (no leading axis) — identical in
    exact arithmetic to ``gar.tree_aggregate(where-poisoned tree)``.
    """
    leaves = jax.tree.leaves(stacked_tree)
    n = leaves[0].shape[0]
    ext = stacked_tree
    if plan.build_extra is not None:
        extra = plan.build_extra(stacked_tree)
        ext = jax.tree.map(
            lambda l, e: jnp.concatenate([l, e[None]], axis=0),
            stacked_tree, extra,
        )
    gram = tree_gram(ext)  # (n+k, n+k), fuses into the backward like f=0
    rmap = plan.row_map
    scale = jnp.asarray(plan.row_scale)
    gram_p = gram[rmap][:, rmap] * (scale[:, None] * scale[None, :])
    w = gar.gram_select(gram_p, f=f, key=key, **(gar_params or {}))
    w = w.astype(jnp.float32) * scale
    w_ext = jnp.zeros((n + plan.num_extra,), jnp.float32).at[rmap].add(w)
    return tree_weighted_sum(ext, w_ext)

"""Model/gradient transfer latency over the mesh.

Counterpart of ``pytorch_impl/applications/benchmarks/rpc_bench.py``
(:95-118): the reference measures RPC model-fetch latency vs model dimension
d and node count n. The SPMD equivalent of "every PS pulls every model /
every worker's gradient" is one all_gather over the mesh axis, so this
benchmark times a jit'd all_gather of a (d,)-vector per device across d and
mesh sizes — the ICI-bandwidth number that bounds every topology's step.

  python -m garfield_tpu.apps.benchmarks.transfer_bench --ds 1000 1000000
"""

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...parallel import mesh as mesh_lib
from ...utils import profiling


def bench_gather(mesh, d, reps, trials=1):
    axis = mesh.axis_names[0]
    k = mesh.shape[axis]

    # Dependency-chained paired-reps timing (see gar_bench.bench_one): each
    # iteration all_gathers, then takes its OWN chunk back out of the
    # gathered stack so the next iteration depends on the collective without
    # adding a k*d reduction to the measured span (the fold reads d elements,
    # 1/k of the gather payload, so the bandwidth number stays honest).
    def gather_fold(x_local):
        gathered = jax.lax.all_gather(x_local, axis, tiled=False)
        return jax.lax.dynamic_index_in_dim(
            gathered, jax.lax.axis_index(axis), axis=0, keepdims=False
        )

    fn = jax.jit(
        mesh_lib.shard_map(
            gather_fold, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
        )
    )
    x0 = fn(jnp.zeros((k, d), jnp.float32))
    np.asarray(x0[0, :1])  # compile + warm + drain queue

    def timed(m):
        x = x0
        t0 = time.perf_counter()
        for _ in range(m):
            x = fn(x)
        np.asarray(x[0, :1])
        return time.perf_counter() - t0

    # gar_bench r7 parity: the committed value is the MIN over ``trials``
    # independent min-of-pairs measurements (VERDICT r4 #3 min-over-k —
    # co-tenant interference only ever adds time, so the minimum is the
    # best estimate of the collective itself).
    vals = [
        profiling.paired_reps(timed, reps, pairs=4, agg="min")
        for _ in range(max(1, trials))
    ]
    vals = [v for v in vals if v is not None]
    return min(vals) if vals else None


def main(argv=None):
    p = argparse.ArgumentParser(description="collective transfer benchmark")
    p.add_argument("--ds", nargs="*", type=int,
                   default=[10 ** k for k in range(2, 8)])
    p.add_argument("--reps", type=int, default=20)
    p.add_argument("--trials", type=int, default=3,
                   help="Independent min-of-pairs timing trials per cell; "
                        "the committed value is the minimum (gar_bench r7 "
                        "parity — min-over-k), recorded per row.")
    p.add_argument("--json", type=str, default=None,
                   help="Also dump results to this JSON file (plus the "
                        "schema-versioned telemetry JSONL twin at the same "
                        "path with a .jsonl suffix).")
    args = p.parse_args(argv)

    n_dev = len(jax.devices())
    sizes = sorted({s for s in (2, 4, 8, n_dev) if 1 < s <= n_dev})
    results = []
    for k in sizes:
        mesh = mesh_lib.make_mesh({"workers": k}, devices=jax.devices()[:k])
        for d in args.ds:
            latency = bench_gather(mesh, d, args.reps, trials=args.trials)
            if latency is None:  # below the host's noise floor (paired_reps)
                print(f"k={k} d={d:<9} below noise floor", flush=True)
                results.append({"devices": k, "d": d, "latency_s": None,
                                "below_noise_floor": True,
                                "trials": args.trials})
                continue
            payload = k * d * 4
            row = {
                "devices": k, "d": d, "latency_s": latency,
                "gather_gbit": profiling.convert_to_gbit(payload),
                "gbit_per_s": profiling.convert_to_gbit(payload) / latency,
                "trials": args.trials,
            }
            results.append(row)
            print(f"k={k} d={d:<9} {latency * 1e6:9.1f} us "
                  f"{row['gbit_per_s']:8.2f} Gbit/s", flush=True)
    if args.json:
        with open(args.json, "w") as fp:
            json.dump(results, fp, indent=1)
        # Schema-versioned JSONL twin (gar_bench r7 parity): validated by
        # the tier-1 schema check, so a malformed sweep fails loudly.
        import os

        from ...telemetry import exporters

        jsonl_path = os.path.splitext(args.json)[0] + ".jsonl"
        with exporters.JsonlExporter(jsonl_path) as exp:
            for row in results:
                exp.write(exporters.make_record(
                    "transfer_bench",
                    devices=row["devices"], d=row["d"],
                    latency_s=row["latency_s"],
                    gbit_per_s=row.get("gbit_per_s"),
                    below_noise_floor=row.get("below_noise_floor", False),
                    trials=row["trials"],
                ))
    return results


if __name__ == "__main__":
    main(sys.argv[1:])

"""In-graph compressed-wire emulation (parallel/compress.py, DESIGN.md §20).

Three contracts pinned tier-1:

1. **Host <-> graph parity**: the jitted quantizer grid equals the host
   codec's bit-for-bit (int8/int4/bf16); the sparsifier matches on
   tie-free inputs (the documented parity boundary — lax.top_k vs
   argpartition tie-breaking is NOT pinned).
2. **Trainer integration**: ``wire=`` off is a bitwise no-op; on, the
   compressed plane still trains and exposes the residual-norm metric.
3. **Bitwise resume**: the EF residual lives in ``TrainState.wire_state``
   — chunked dispatch and a mid-run checkpoint/restore (with NON-ZERO
   residuals at the cut) reproduce the straight run bit-for-bit. The
   host-side accumulator's restart-at-zero is a separate, documented
   semantic (wire.ErrorFeedback docstring), not covered here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import models
from garfield_tpu.parallel import aggregathor, compress, core
from garfield_tpu.utils import checkpoint as ckpt_lib, selectors, wire

NUM_BATCHES = 3


def _setup():
    module = models.select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer("sgd", lr=0.05, momentum=0.9)
    return module, loss, opt


def _batch_stack(seed=0, bsz=16):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(8, NUM_BATCHES, bsz, 8)).astype(np.float32)
    y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _assert_bitwise_equal(ref, got):
    ra = jax.tree.leaves(jax.device_get(ref))
    ga = jax.tree.leaves(jax.device_get(got))
    assert len(ra) == len(ga)
    for a, b in zip(ra, ga):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --- host <-> graph parity ---------------------------------------------------


def test_quantizer_grid_matches_host_codec_bitwise():
    """The emulated robustness matrix must measure the SHIPPED wire: the
    in-graph per-block grid (scale, RNE rounding, clip) equals the host
    encode->decode bit-for-bit, including the block-boundary padding and
    an all-zero block's zero scale."""
    rng = np.random.default_rng(0)
    rows = (rng.standard_normal((3, 2500)) * 4).astype(np.float32)
    rows[1, :1024] = 0.0  # one all-zero block: scale 0, codes 0
    for scheme in ("int8", "int4", "bf16"):
        graph = np.asarray(compress.roundtrip_rows(jnp.asarray(rows), scheme))
        host = np.stack([
            wire.decode(wire.encode(r, scheme)) for r in rows
        ])
        np.testing.assert_array_equal(graph, host)


def test_topk_matches_host_on_tie_free_rows():
    rng = np.random.default_rng(1)
    rows = rng.standard_normal((2, 400)).astype(np.float32)  # ties: P=0
    k = 25
    graph = np.asarray(
        compress.roundtrip_rows(jnp.asarray(rows), "topk", k=k)
    )
    host = np.stack([
        wire.decode(wire.encode(r, "topk", k=k)) for r in rows
    ])
    np.testing.assert_array_equal(graph, host)
    assert (np.count_nonzero(graph, axis=1) == k).all()


def test_topk_tie_keeps_at_least_k():
    """Ties at the k-th magnitude: the threshold mask keeps every tied
    coordinate (>= k survive) rather than an arbitrary subset — the
    documented drift from the host's exactly-k frames."""
    rows = jnp.asarray([[1.0, -1.0, 1.0, 0.5, 0.25]], jnp.float32)
    out = np.asarray(compress.roundtrip_rows(rows, "topk", k=2))
    assert np.count_nonzero(out) == 3  # all three tied |1.0| kept


def test_ef_roundtrip_rows_matches_host_accumulator():
    """One EF step in-graph == one host ErrorFeedback step around the
    codec, bitwise (int8 path; the parity anchor the resume tests lean
    on)."""
    rng = np.random.default_rng(2)
    rows = rng.standard_normal((2, 300)).astype(np.float32)
    resid0 = rng.standard_normal((2, 300)).astype(np.float32) * 0.01
    sent, resid = compress.ef_roundtrip_rows(
        jnp.asarray(rows), jnp.asarray(resid0), "int8"
    )
    ef = wire.ErrorFeedback()
    for i in range(2):
        ef._resid[i] = resid0[i]
        comp = ef.compensate(i, rows[i])
        dec = wire.decode(wire.encode(comp, "int8"))
        ef.update(i, comp, dec)
        np.testing.assert_array_equal(np.asarray(sent)[i], dec)
        np.testing.assert_array_equal(np.asarray(resid)[i], ef._resid[i])


def test_roundtrip_rows_validates():
    rows = jnp.ones((1, 8), jnp.float32)
    with pytest.raises(ValueError):
        compress.roundtrip_rows(rows, "f16")
    with pytest.raises(ValueError):
        compress.roundtrip_rows(rows, "topk")  # k is required


# --- trainer integration -----------------------------------------------------


def _trainer(wire_kw, **kw):
    module, loss, opt = _setup()
    return aggregathor.make_trainer(
        module, loss, opt, "krum", num_workers=8, f=2, attack="lie",
        wire=wire_kw, **kw,
    )


def _run(init_fn, step_fn, steps, state=None, start=0):
    xs, ys = _batch_stack()
    if state is None:
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
    metrics = []
    for i in range(start, start + steps):
        state, m = step_fn(state, xs[:, i % NUM_BATCHES],
                           ys[:, i % NUM_BATCHES])
        metrics.append(jax.device_get(m))
    return state, metrics


def test_wire_off_is_bitwise_noop():
    """``wire=None`` and the explicit f32/no-topk spelling trace the SAME
    program: identical params, and no wire_state is allocated."""
    init_a, step_a, _ = _trainer(None)
    init_b, step_b, _ = _trainer({"dtype": "f32", "topk": 0})
    sa, _ = _run(init_a, step_a, 4)
    sb, _ = _run(init_b, step_b, 4)
    assert sa.wire_state is None and sb.wire_state is None
    _assert_bitwise_equal(sa.params, sb.params)


def test_compressed_plane_trains_and_reports_residual():
    for wire_kw in ({"dtype": "int8"}, {"dtype": "int4"},
                    {"topk": 32}, {"dtype": "bf16"}):
        init_fn, step_fn, _ = _trainer(dict(wire_kw))
        state, metrics = _run(init_fn, step_fn, 3)
        assert np.isfinite(metrics[-1]["loss"])
        ef_expected = wire_kw != {"dtype": "bf16"}  # bf16 is EF-free
        assert (state.wire_state is not None) == ef_expected
        assert ("wire_resid_norm" in metrics[-1]) == ef_expected
        if ef_expected:
            # Lossy compression of a real gradient leaves a residual.
            assert float(np.max(metrics[-1]["wire_resid_norm"])) > 0
            assert np.asarray(state.wire_state["resid"]).any()


def test_wire_kwarg_validates():
    with pytest.raises(ValueError, match="unknown wire"):
        _trainer({"dtype": "int8", "bogus": 1})
    with pytest.raises(ValueError):
        _trainer({"dtype": "f16"})
    with pytest.raises(ValueError):
        _trainer({"topk": -1})


# --- bitwise chunked + resume ------------------------------------------------


def test_ef_chunked_bitwise_equal():
    """The EF residual is scan-carry state: K-step chunks equal per-step
    dispatches bit-for-bit, wire_state included."""
    init_fn, step_fn, _ = _trainer({"dtype": "int8"})
    xs, ys = _batch_stack()
    state0 = init_fn(jax.random.PRNGKey(0), xs[0, 0])
    ref, _ = _run(init_fn, step_fn, 6, state=state0)
    # K sweep stays lean (one compile per K on the 1-core suite box);
    # test_chunked.py owns the general K-alignment sweep.
    for K in (2, 6):
        fn = core.make_chunked_step(step_fn, K, NUM_BATCHES)
        state = state0
        for i in range(0, 6, K):
            state, _ = fn(state, xs, ys, np.int32(i))
        _assert_bitwise_equal(ref, state)
        assert np.asarray(state.wire_state["resid"]).any()


@pytest.mark.parametrize("wire_kw", [{"dtype": "int8"}, {"topk": 16}])
def test_ef_checkpoint_resume_bitwise(tmp_path, wire_kw):
    """Mid-run resume with NON-ZERO residuals: save at step 3 through the
    real checkpoint path (pickle-of-numpy on CPU), restore, run 3 more —
    bitwise equal to the uninterrupted 6-step run. This is the in-graph
    twin's half of the EF restart contract; the HOST accumulator
    deliberately rebuilds at zero on role restart (announced via the
    startup banner — wire.ErrorFeedback docstring), which is why bitwise
    resume lives here and not in apps/cluster."""
    init_fn, step_fn, _ = _trainer(dict(wire_kw))
    straight, _ = _run(init_fn, step_fn, 6)

    half, _ = _run(init_fn, step_fn, 3)
    assert np.asarray(half.wire_state["resid"]).any(), \
        "resume must carry a non-trivial residual to prove anything"
    ckpt_lib.save(tmp_path, 3, half)
    restored = ckpt_lib.restore(tmp_path, half)
    restored = jax.tree.map(jnp.asarray, restored)
    resumed, _ = _run(init_fn, step_fn, 3, state=restored, start=3)
    _assert_bitwise_equal(straight, resumed)

"""Multi-host (DCN) integration: 2 real processes, one SPMD program.

The reference's multi-node story was ssh fan-out plus gRPC/RPC glue with no
way to test it without a cluster (SURVEY §4). Here the jax.distributed
multi-controller path — ClusterConfig bootstrap, cross-process all_gather,
GAR agreement — is exercised for real by spawning two OS processes that
form one 8-device global mesh (4 virtual CPU devices per "host") and must
print bit-identical Multi-Krum aggregates under a lie attack.
"""

import os
import socket
import subprocess
import sys

import pytest

from garfield_tpu.utils import multihost

# Two full jax processes + DCN bootstrap per test: minutes by design
# (tier-1 fast shard skips via -m 'not slow').
pytestmark = pytest.mark.slow

_CHILD = os.path.join(os.path.dirname(__file__), "multihost_child.py")


def _free_ports(k):
    """k distinct free ports, each checked via its own bound socket (held
    simultaneously so they cannot alias each other; released just before
    the children spawn — ADVICE r1: the old code only ever checked one)."""
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def test_two_process_cluster_agreement(tmp_path):
    for attempt in range(2):  # retry once on a port being re-grabbed
        ports = _free_ports(4)
        hosts = [f"127.0.0.1:{ports[0]}", f"127.0.0.1:{ports[1]}"]
        ex_hosts = [f"127.0.0.1:{ports[2]}", f"127.0.0.1:{ports[3]}"]
        procs = []
        env = {
            k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"
        }
        env["JAX_PLATFORMS"] = "cpu"
        # CPU-only children: PYTHONPATH is safe here (it breaks only the axon
        # TPU plugin registration — see .claude/skills/verify gotchas).
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(_CHILD))
        for i, _ in enumerate(hosts):
            cfg_path = tmp_path / f"task_{i}_{attempt}.json"
            multihost.generate_config(
                cfg_path, workers=hosts, task_type="worker", task_index=i,
                gar="krum", fw=2, exchange=ex_hosts,
            )
            procs.append(subprocess.Popen(
                [sys.executable, _CHILD, str(cfg_path)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env, cwd=os.path.dirname(os.path.dirname(_CHILD)),
            ))
        outs, ex_lines, retry = [], [], False
        try:
            for p in procs:
                out, _ = p.communicate(timeout=280)
                if p.returncode != 0 and "Address already in use" in out:
                    retry = True
                    break
                assert p.returncode == 0, f"child failed:\n{out[-3000:]}"
                agg = [l for l in out.splitlines() if l.startswith("AGG ")]
                assert agg, f"no AGG line:\n{out[-2000:]}"
                outs.append(agg[-1].split()[2:])
                ex_lines += [
                    l for l in out.splitlines() if l.startswith("EXCHANGE ")
                ]
        finally:
            for p in procs:  # never leak a blocked jax.distributed child
                if p.poll() is None:
                    p.kill()
                    p.wait()
        if retry:
            if attempt == 0:
                continue
            import pytest

            pytest.fail("port collision ('Address already in use') on both "
                        "attempts")
        # Both hosts computed the identical replicated aggregate.
        assert outs[0] == outs[1], outs
        # And exchanged it for real over TCP + the native MRMW register:
        # each host verified the peer's serialized aggregate byte-equal.
        assert len(ex_lines) == 2 and all(
            "ok=True n=2" in l for l in ex_lines
        ), ex_lines
        return

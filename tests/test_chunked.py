"""On-device step chunking (parallel/core.make_chunked_step, DESIGN.md §12).

The acceptance bar is TRAJECTORY EQUALITY, not just speed: a K-step chunk
(one jitted lax.scan dispatch) must be bitwise equal to K per-step
dispatches — state carry (params, optimizer, stateful GAR centers, worker
momentum), per-step RNG derivation (fold_in(rng, step) advancing in the
scan carry), on-device batch indexing (b = (i0 + k) % num_batches), and
the stacked telemetry TapBundles all included. The fast tests below run
the richest path per topology on the 8-device CPU mesh and are tier-1;
the full topology x rule x attack x taps matrix is slow-marked (same
tiering as the trainer files; see the 1-core contention note in
tests/test_apps.py).

Boundary clipping (apps/common.chunk_length) gets one unit test per
boundary kind the loop special-cases: eval points, checkpoint saves,
crash-schedule re-jits, the profiled step, and end of run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import models
from garfield_tpu.apps.common import chunk_length
from garfield_tpu.parallel import aggregathor, byzsgd, core, learn, make_mesh
from garfield_tpu.utils import selectors

NUM_BATCHES = 3
STEPS = 6


def _setup():
    module = models.select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer("sgd", lr=0.05, momentum=0.9)
    return module, loss, opt


def _batch_stack(seed=0, bsz=16):
    """(slots=8, num_batches, bsz, 8) stacks of the learnable pima task."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(8, NUM_BATCHES, bsz, 8)).astype(np.float32)
    y = (x.sum(-1, keepdims=True) > 0).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _run_per_step(step_fn, state, xs, ys, steps=STEPS):
    """The app loop's per-step dispatch: one call per step, host-side
    batch indexing."""
    metrics = []
    for i in range(steps):
        b = i % NUM_BATCHES
        state, m = step_fn(state, xs[:, b], ys[:, b])
        metrics.append(jax.device_get(m))
    stacked = jax.tree.map(lambda *ls: np.stack(ls), *metrics)
    return state, stacked


def _run_chunked(step_fn, state, xs, ys, K, steps=STEPS):
    """Greedy chunks of size K (clipped at the end), one compiled program
    per distinct length — the app loop's chunked dispatch."""
    fns, metrics, i = {}, [], 0
    while i < steps:
        k = min(K, steps - i)
        fn = fns.setdefault(k, core.make_chunked_step(step_fn, k, NUM_BATCHES))
        state, m = fn(state, xs, ys, np.int32(i))
        metrics.append(jax.device_get(m))
        i += k
    stacked = jax.tree.map(lambda *ls: np.concatenate(ls), *metrics)
    return state, stacked


def _assert_bitwise_equal(ref, got):
    """Every leaf of (state, metrics) pairs identical to the bit."""
    ra, ga = jax.tree.leaves(jax.device_get(ref)), jax.tree.leaves(
        jax.device_get(got)
    )
    assert len(ra) == len(ga)
    for a, b in zip(ra, ga):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _compare(init_fn, step_fn, ks=(1, 4, 8)):
    xs, ys = _batch_stack()
    state0 = init_fn(jax.random.PRNGKey(0), xs[0, 0])
    ref_state, ref_metrics = _run_per_step(step_fn, state0, xs, ys)
    for K in ks:
        got_state, got_metrics = _run_chunked(step_fn, state0, xs, ys, K)
        _assert_bitwise_equal(ref_state, got_state)
        _assert_bitwise_equal(ref_metrics, got_metrics)


# --- tier-1 fast path per topology ------------------------------------------


def test_aggregathor_chunked_bitwise_equal():
    """Richest SSMW path: krum + lie + taps + subset quorums, K in
    {1, 4, 8} with a clipped tail chunk (6 steps)."""
    module, loss, opt = _setup()
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss, opt, "krum", num_workers=8, f=2, attack="lie",
        telemetry=True,
    )
    _compare(init_fn, step_fn)


def test_learn_stateful_center_chunked_bitwise_equal():
    """LEARN + cclip: the carried per-node center (TrainState.gar_state)
    and the step-0 median-init lax.cond must carry across scan iterations
    exactly as across dispatches. Per-node wait-n-f subsets exercise the
    per-step key splits."""
    module, loss, opt = _setup()
    init_fn, step_fn, _ = learn.make_trainer(
        module, loss, opt, "cclip", num_nodes=8, f=2, attack="lie",
        subset=6,
    )
    _compare(init_fn, step_fn, ks=(1, 4))


def test_byzsgd_chunked_bitwise_equal():
    """MSMW on the 2-D (ps=2, workers=4) mesh: per-PS gradient quorums +
    the model gather plane + observer-mean taps, chunked."""
    module, loss, opt = _setup()
    mesh = make_mesh({"ps": 2, "workers": 4})
    init_fn, step_fn, _ = byzsgd.make_trainer(
        module, loss, opt, "median", num_workers=8, num_ps=2, fw=1,
        attack="lie", mesh=mesh, telemetry=True,
    )
    _compare(init_fn, step_fn, ks=(4,))


def test_worker_momentum_chunk_carry():
    """The per-worker momentum stack (TrainState.worker_mom) is part of
    the scan carry — EMA state after a chunk must equal the per-step
    run's."""
    module, loss, opt = _setup()
    opt_plain = selectors.select_optimizer("sgd", lr=0.2)
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss, opt_plain, "cclip", num_workers=8, f=2, attack="lie",
        worker_momentum=0.9,
    )
    _compare(init_fn, step_fn, ks=(4,))


def test_rolled_scan_flavor_bitwise_equal():
    """Both scan flavors must be trajectory-exact: the CPU default is the
    fully-unrolled body (rolled while loops pin conv layouts on XLA:CPU,
    PERF.md r9), device backends keep the rolled loop — pin the ROLLED
    flavor against per-step here so the non-default path stays covered."""
    module, loss, opt = _setup()
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss, opt, "krum", num_workers=8, f=2, attack="lie",
    )
    xs, ys = _batch_stack()
    state0 = init_fn(jax.random.PRNGKey(0), xs[0, 0])
    ref_state, ref_metrics = _run_per_step(step_fn, state0, xs, ys)
    rolled = core.make_chunked_step(step_fn, 3, NUM_BATCHES, unroll=1)
    state, metrics = state0, []
    for i in range(0, STEPS, 3):
        state, m = rolled(state, xs, ys, np.int32(i))
        metrics.append(jax.device_get(m))
    _assert_bitwise_equal(ref_state, state)
    _assert_bitwise_equal(
        ref_metrics, jax.tree.map(lambda *ls: np.concatenate(ls), *metrics)
    )


def test_targeted_partial_poison_chunked_bitwise():
    """Poison-mask seeding under chunking (ISSUE 12 satellite): with
    ``poison_frac < 1`` the per-step poison subset is derived via
    ``fold_in(seed, step)`` from the SCAN CARRY's step counter, so a
    chunked run poisons bitwise-identical sample sets to the per-step
    loop — the seeding can never drift between the two dispatch shapes.
    (At poison_frac 1.0 the mask is statically all-ones and the program
    is unchanged — covered by the PR-11 pin in tests/test_dataplane.py.)
    """
    module, loss, opt = _setup()
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss, opt, "krum", num_workers=8, f=2,
        attack="backdoor",
        attack_params={"source": 0, "target": 1, "poison_frac": 0.5},
    )
    _compare(init_fn, step_fn, ks=(1, 4, 8))


def test_gpt_token_backdoor_chunked_bitwise():
    """The transformer family through the chunked dispatch (DESIGN.md
    §23): a small GPT on integer token batches, with the token-prefix
    backdoor poisoning the Byzantine slots' batches in-graph — K-step
    chunks must stay bitwise equal to per-step, token poisoning, twin
    gradients and all (poison_frac 1.0 keeps the mask static, so the
    program carries no mask RNG — the same contract the pima rows pin).
    """
    from garfield_tpu.models import transformer

    module = transformer.GPT(
        num_classes=10, vocab=16, dim=16, depth=1, heads=2, mlp_dim=32
    )
    loss = selectors.select_loss("nll")
    opt = selectors.select_optimizer("sgd", lr=0.05, momentum=0.9)
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss, opt, "krum", num_workers=8, f=2, attack="backdoor",
        attack_params={"source": 0, "target": 3, "trigger_token": 14,
                       "trigger_size": 2},
    )
    rng = np.random.default_rng(9)
    xs = jnp.asarray(
        rng.integers(0, 16, size=(8, NUM_BATCHES, 8, 6)).astype(np.int32)
    )
    ys = jnp.asarray(
        rng.integers(0, 10, size=(8, NUM_BATCHES, 8)).astype(np.int32)
    )
    state0 = init_fn(jax.random.PRNGKey(0), xs[0, 0])
    ref_state, ref_metrics = _run_per_step(step_fn, state0, xs, ys)
    for K in (1, 4, 8):
        got_state, got_metrics = _run_chunked(step_fn, state0, xs, ys, K)
        _assert_bitwise_equal(ref_state, got_state)
        _assert_bitwise_equal(ref_metrics, got_metrics)


def test_make_chunked_step_validates():
    module, loss, opt = _setup()
    init_fn, step_fn, _ = aggregathor.make_trainer(
        module, loss, opt, "average", num_workers=8
    )
    with pytest.raises(ValueError):
        core.make_chunked_step(step_fn, 0, NUM_BATCHES)
    with pytest.raises(ValueError):
        core.make_chunked_step(step_fn, 4, 0)


# --- slow full acceptance matrix --------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("topology", ["aggregathor", "byzsgd", "learn"])
@pytest.mark.parametrize("gar", ["krum", "median", "cclip"])
@pytest.mark.parametrize("attack", ["lie", None])
@pytest.mark.parametrize("telemetry", [True, False])
def test_chunked_matrix(topology, gar, attack, telemetry):
    """The full acceptance grid: every topology x {krum, median, cclip} x
    {lie, none}, taps on and off, K in {1, 4, 8} — all bitwise equal to
    per-step on the 8-device CPU mesh. The DECLARED tolerance stays f=2
    in the fault-free cells (krum's contract needs f >= 1; tolerating
    Byzantine workers that never show up is the normal deployment)."""
    module, loss, opt = _setup()
    f = 2
    if topology == "aggregathor":
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, gar, num_workers=8, f=f, attack=attack,
            telemetry=telemetry,
        )
    elif topology == "byzsgd":
        # Model plane on median: krum cannot validate a 2-row model
        # gather (needs n >= 2f+3); the grid varies the GRADIENT rule.
        mesh = make_mesh({"ps": 2, "workers": 4})
        init_fn, step_fn, _ = byzsgd.make_trainer(
            module, loss, opt, gar, num_workers=8, num_ps=2, fw=f,
            model_gar="median", attack=attack, mesh=mesh,
            telemetry=telemetry,
        )
    else:
        # LEARN exports phase-2 taps only when asked via telemetry=True
        # like the others; cclip additionally carries per-node centers.
        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, gar, num_nodes=8, f=f, attack=attack,
            telemetry=telemetry,
        )
    _compare(init_fn, step_fn)


@pytest.mark.slow
def test_learn_non_iid_agreement_rounds_chunked():
    """ceil(log2 t) agreement rounds are data-dependent on the step
    counter — inside a chunk the counter advances in the carry, so round
    counts per scan iteration must match the per-step run's."""
    module, loss, opt = _setup()
    init_fn, step_fn, _ = learn.make_trainer(
        module, loss, opt, "median", num_nodes=8, f=1, attack="lie",
        non_iid=True,
    )
    _compare(init_fn, step_fn, ks=(4, 8))


# --- boundary clipping: one test per boundary kind --------------------------


class TestChunkLength:
    def test_eval_boundary(self):
        # Eval after step j (j % acc_freq == 0): the chunk may include j
        # but must end at j + 1.
        assert chunk_length(1, chunk=8, num_iter=100, acc_freq=6) == 6
        # i itself an eval point: single-step chunk, then eval.
        assert chunk_length(0, chunk=8, num_iter=100, acc_freq=6) == 1
        assert chunk_length(6, chunk=8, num_iter=100, acc_freq=6) == 1
        # far from the next eval point: full chunk.
        assert chunk_length(7, chunk=4, num_iter=100, acc_freq=100) == 4

    def test_checkpoint_boundary(self):
        # Save fires after step j with (j + 1) % freq == 0: the chunk ends
        # on the next multiple of the cadence.
        assert chunk_length(0, chunk=8, num_iter=100, checkpoint_freq=6) == 6
        assert chunk_length(4, chunk=4, num_iter=100, checkpoint_freq=6) == 2
        assert chunk_length(6, chunk=4, num_iter=100, checkpoint_freq=6) == 4

    def test_crash_boundary(self):
        # A crash event at step s re-jits the step program: no chunk may
        # span s; the chunk STARTING at s runs under the new program.
        assert chunk_length(0, chunk=8, num_iter=100, crash_steps=[5]) == 5
        assert chunk_length(5, chunk=8, num_iter=100, crash_steps=[5]) == 8
        assert chunk_length(3, chunk=8, num_iter=100,
                            crash_steps=[5, 7]) == 2

    def test_profile_boundary(self):
        # The profiled step runs as its own single-step dispatch.
        assert chunk_length(2, chunk=8, num_iter=100, profile_step=5) == 3
        assert chunk_length(5, chunk=8, num_iter=100, profile_step=5) == 1
        assert chunk_length(6, chunk=8, num_iter=100, profile_step=5) == 8

    def test_end_of_run_boundary(self):
        assert chunk_length(7, chunk=8, num_iter=10) == 3
        assert chunk_length(9, chunk=8, num_iter=10) == 1

    def test_boundaries_compose(self):
        # All clips apply at once; the tightest wins, and the result is
        # never below 1 (a boundary AT i still advances the loop).
        assert chunk_length(
            1, chunk=8, num_iter=6, acc_freq=4, checkpoint_freq=3,
            crash_steps=[2], profile_step=5,
        ) == 1  # crash at 2 is the tightest
        assert chunk_length(
            2, chunk=8, num_iter=6, acc_freq=4, checkpoint_freq=3,
            crash_steps=[2], profile_step=5,
        ) == 1  # checkpoint at end 3

    def test_chunk_one_is_per_step(self):
        for i in range(10):
            assert chunk_length(
                i, chunk=1, num_iter=10, acc_freq=3, checkpoint_freq=4
            ) == 1

"""Condense GAR: randomized coordinate mixing of median and first gradient.

Counterpart of pytorch_impl/libs/aggregators/condense.py (:36-42): sample a
Bernoulli(p) mask per coordinate; output = mask * median + (1-mask) * g[0].
Requires n >= 2f+2 (:56).

Randomness: jax is functionally pure, so the rule takes an explicit PRNG
``key`` — the topologies all derive one from their replicated per-step rng
and pass it in (the torch-global-RNG coupling of the reference has no
counterpart here). When ``key`` is omitted (host-side convenience, e.g.
calling ``gars["condense"](stack, f=1)`` at a REPL), a fixed key(0) is used:
deterministic and independent of call order — pass distinct keys to vary
the mask.
"""

import math

import jax
import jax.numpy as jnp

from . import register
from ._common import as_stack, coordinate_median, num_gradients


def aggregate(gradients, f, p=0.9, key=None, **kwargs):
    """Bernoulli(p)-masked mix of coordinate median and gradient 0."""
    g = as_stack(gradients)
    if key is None:
        key = jax.random.key(0)
    mask = jax.random.bernoulli(key, p, shape=(g.shape[1],)).astype(g.dtype)
    return coordinate_median(g) * mask + g[0] * (1.0 - mask)


def check(gradients, f, p=0.9, key=None, **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 2:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = {f!r}, "
            f"expected 1 <= f <= {(n - 2) // 2}"
        )
    if p <= 0 or p > 1:
        return f"expected positive selection probability, got {p}"
    return None


def upper_bound(n, f, d):
    """Same bound as the median, 1/sqrt(n-f) (condense.py:60-69)."""
    return 1 / math.sqrt(n - f)


register("condense", aggregate, check, upper_bound=upper_bound)

"""GoogLeNet / Inception-v1 (counterpart of garfieldpp/models/googlenet.py).
Also registered under "inception" (the reference maps that name to
torchvision's inception_v3, garfieldpp/tools.py:73; here the v1 graph serves
both names — documented deviation, CIFAR-scale inputs don't fit v3's 299px
stem anyway)."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import avg_pool, conv, conv1x1, global_avg_pool, max_pool, norm


class Inception(nn.Module):
    n1x1: int
    n3x3red: int
    n3x3: int
    n5x5red: int
    n5x5: int
    pool_planes: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        def cbr(feats, kernel, pad, y):
            return nn.relu(norm(train, dtype=self.dtype)(
                conv(feats, kernel, 1, padding=pad, dtype=self.dtype)(y)))

        b1 = cbr(self.n1x1, 1, 0, x)
        b2 = cbr(self.n3x3, 3, 1, cbr(self.n3x3red, 1, 0, x))
        b3 = cbr(self.n5x5red, 1, 0, x)
        b3 = cbr(self.n5x5, 3, 1, cbr(self.n5x5, 3, 1, b3))
        b4 = cbr(self.pool_planes, 1, 0, max_pool(x, 3, 1, padding=1))
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class GoogLeNet(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        x = nn.relu(norm(train, dtype=d)(conv(192, 3, 1, padding=1, dtype=d)(x)))
        x = Inception(64, 96, 128, 16, 32, 32, d)(x, train)
        x = Inception(128, 128, 192, 32, 96, 64, d)(x, train)
        x = max_pool(x, 3, 2, padding=1)
        x = Inception(192, 96, 208, 16, 48, 64, d)(x, train)
        x = Inception(160, 112, 224, 24, 64, 64, d)(x, train)
        x = Inception(128, 128, 256, 24, 64, 64, d)(x, train)
        x = Inception(112, 144, 288, 32, 64, 64, d)(x, train)
        x = Inception(256, 160, 320, 32, 128, 128, d)(x, train)
        x = max_pool(x, 3, 2, padding=1)
        x = Inception(256, 160, 320, 32, 128, 128, d)(x, train)
        x = Inception(384, 192, 384, 48, 128, 128, d)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)

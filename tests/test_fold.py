"""Folded attack application (parallel/fold.py + attacks fold plans).

The folded path must be value-equivalent to the reference-semantics where-path
(poison rows, then aggregate): same attacks, same rules, same stacks — only
the algebra is restructured (Gram remap instead of row rewrite).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu.aggregators import gars
from garfield_tpu.attacks import (
    apply_gradient_attack_tree,
    plan_gradient_attack_fold,
)
from garfield_tpu.parallel import core
from garfield_tpu.parallel.fold import (
    folded_tree_aggregate,
    folded_tree_aggregate_multi,
)

N, F = 8, 2


def _stacked_tree(key, n=N):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w": jax.random.normal(k1, (n, 5, 3)),
        "b": jax.random.normal(k2, (n, 7)),
        "s": jax.random.normal(k3, (n, 1)),
    }


class TestFoldPlans:
    @pytest.mark.parametrize("attack", ["lie", "empire", "reverse", "crash"])
    def test_deterministic_attacks_fold(self, attack):
        plan = plan_gradient_attack_fold(attack, core.default_byz_mask(N, F))
        assert plan is not None
        assert plan.row_map.shape == (N,)
        assert plan.row_scale.shape == (N,)

    @pytest.mark.parametrize("attack", ["random", "drop", None, "none"])
    def test_unfoldable_attacks_return_none(self, attack):
        assert plan_gradient_attack_fold(
            attack, core.default_byz_mask(N, F)
        ) is None

    def test_no_byzantine_rows_returns_none(self):
        assert plan_gradient_attack_fold("lie", np.zeros(N, bool)) is None

    def test_env_escape_hatch(self, monkeypatch):
        monkeypatch.setenv("GARFIELD_NO_FOLD", "1")
        assert plan_gradient_attack_fold(
            "lie", core.default_byz_mask(N, F)
        ) is None


class TestFoldedAggregate:
    # bulyan (n >= 4f+3) runs at f=1 and exercises the fold_aggregate
    # branch (weight-MATRIX apply_rows); krum/average the gram_select
    # branch; median/tmean the coordinate-wise tree_aggregate_ext branch
    # (remapped-row kernels); cclip the fold_flat_aggregate branch
    # (extended-stack iterations, r5).
    @pytest.mark.parametrize("gar_name,f", [
        ("krum", F), ("average", F), ("bulyan", 1),
        ("median", F), ("tmean", F), ("cclip", F),
        # r5 completions: brute (gram_select), aksel (fold_flat),
        # condense (remapped-row kernels + reconstructed row 0).
        ("brute", F), ("aksel", F), ("condense", F),
    ])
    @pytest.mark.parametrize("attack", ["lie", "empire", "reverse", "crash"])
    def test_matches_where_path(self, gar_name, f, attack):
        gar = gars[gar_name]
        mask = core.default_byz_mask(N, f)
        tree = _stacked_tree(jax.random.PRNGKey(3))
        plan = plan_gradient_attack_fold(attack, mask)
        key = jax.random.PRNGKey(7)  # condense's mask; inert elsewhere
        got = folded_tree_aggregate(gar, plan, tree, f=f, key=key)
        poisoned = apply_gradient_attack_tree(attack, tree, jnp.asarray(mask))
        want = gar.tree_aggregate(poisoned, f=f, key=key)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            got, want,
        )

    @pytest.mark.parametrize("gar_name", ["krum", "average", "brute"])
    @pytest.mark.parametrize("attack", ["lie", "reverse"])
    def test_subset_composes_with_fold(self, gar_name, attack):
        """Wait-n-f subsets compose with the fold for Gram-form rules: the
        sub-Gram selection must equal poisoning + row subset + rule."""
        gar = gars[gar_name]
        mask = core.default_byz_mask(N, F)
        tree = _stacked_tree(jax.random.PRNGKey(19))
        q = N - 1
        sel = core.subset_indices(jax.random.PRNGKey(23), N, q)
        plan = plan_gradient_attack_fold(attack, mask)
        got = folded_tree_aggregate(
            gar, plan, tree, f=F, subset_sel=sel
        )
        poisoned = apply_gradient_attack_tree(attack, tree, jnp.asarray(mask))
        sub = jax.tree.map(lambda l: l[sel], poisoned)
        want = gar.tree_aggregate(sub, f=F)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            got, want,
        )

    def test_subset_rejected_for_non_gram_rules(self):
        plan = plan_gradient_attack_fold(
            "lie", core.default_byz_mask(N, F)
        )
        with pytest.raises(ValueError, match="gram_select"):
            folded_tree_aggregate(
                gars["median"], plan, _stacked_tree(jax.random.PRNGKey(2)),
                f=F, subset_sel=jnp.arange(N - 1),
            )

    def test_matches_where_path_nonstandard_mask(self):
        """Byzantine rows need not be the trailing slots."""
        mask = np.zeros(N, bool)
        mask[[1, 4]] = True
        tree = _stacked_tree(jax.random.PRNGKey(5))
        plan = plan_gradient_attack_fold("lie", mask)
        got = folded_tree_aggregate(gars["krum"], plan, tree, f=F)
        poisoned = apply_gradient_attack_tree("lie", tree, jnp.asarray(mask))
        want = gars["krum"].tree_aggregate(poisoned, f=F)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            got, want,
        )

    def test_krum_m_param_reaches_gram_select(self):
        mask = core.default_byz_mask(N, F)
        tree = _stacked_tree(jax.random.PRNGKey(9))
        plan = plan_gradient_attack_fold("reverse", mask)
        got = folded_tree_aggregate(
            gars["krum"], plan, tree, f=F, gar_params={"m": 1}
        )
        poisoned = apply_gradient_attack_tree("reverse", tree, jnp.asarray(mask))
        want = gars["krum"].tree_aggregate(poisoned, f=F, m=1)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            got, want,
        )

    def test_lie_single_byzantine_nan_cohort(self):
        """fw=1: Bessel std of a one-row cohort is NaN (torch semantics);
        both paths must agree — krum treats the NaN fake row as infinitely
        distant and never selects it."""
        mask = core.default_byz_mask(N, 1)
        tree = _stacked_tree(jax.random.PRNGKey(11))
        plan = plan_gradient_attack_fold("lie", mask)
        got = folded_tree_aggregate(gars["krum"], plan, tree, f=1)
        poisoned = apply_gradient_attack_tree("lie", tree, jnp.asarray(mask))
        want = gars["krum"].tree_aggregate(poisoned, f=1)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            got, want,
        )
        for leaf in jax.tree.leaves(got):
            assert np.isfinite(np.asarray(leaf)).all()

    @pytest.mark.parametrize("carried_center", [False, True])
    def test_cclip_lie_single_byzantine_nan_cohort(self, carried_center):
        """fw=1 lie: the fake row is all-NaN (Bessel std of one sample).
        cclip's fold guards at ROW level (weight 0 == vote the current
        center), which coincides with the where-path's entry-level guard
        exactly when the whole row is non-finite — this case. The carried
        (nonzero) center variant covers the PRODUCTION configuration (v_0
        = previous aggregate): the NaN row's radius must enter the tau
        median as the where-path's 0, not ||v|| (review-caught tau shift,
        r5)."""
        mask = core.default_byz_mask(N, 1)
        tree = _stacked_tree(jax.random.PRNGKey(11))
        center = (
            jax.tree.map(
                lambda l: 3.0 + jnp.mean(l, axis=0), tree
            ) if carried_center else None
        )
        plan = plan_gradient_attack_fold("lie", mask)
        got = folded_tree_aggregate(
            gars["cclip"], plan, tree, f=1,
            gar_params={"center": center} if center is not None else None,
        )
        poisoned = apply_gradient_attack_tree("lie", tree, jnp.asarray(mask))
        want = gars["cclip"].tree_aggregate(poisoned, f=1, center=center)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            got, want,
        )
        for leaf in jax.tree.leaves(got):
            assert np.isfinite(np.asarray(leaf)).all()

    @pytest.mark.parametrize("attack", ["lie", "empire", "reverse", "crash"])
    def test_cclip_fold_with_carried_center_matches_where_path(self, attack):
        """Every deterministic attack folds identically under a carried
        nonzero center (the aggregathor stateful-center configuration)."""
        mask = core.default_byz_mask(N, F)
        tree = _stacked_tree(jax.random.PRNGKey(17))
        center = jax.tree.map(lambda l: 1.5 * jnp.mean(l, axis=0), tree)
        plan = plan_gradient_attack_fold(attack, mask)
        got = folded_tree_aggregate(
            gars["cclip"], plan, tree, f=F, gar_params={"center": center}
        )
        poisoned = apply_gradient_attack_tree(attack, tree, jnp.asarray(mask))
        want = gars["cclip"].tree_aggregate(poisoned, f=F, center=center)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            got, want,
        )

    def test_gram_select_consistency(self):
        """gram_select(stack @ stack.T) @ stack == aggregate(stack)."""
        g = jax.random.normal(jax.random.PRNGKey(2), (N, 33))
        gram = g @ g.T
        w = gars["krum"].gram_select(gram, f=F)
        np.testing.assert_allclose(
            np.asarray(w @ g), np.asarray(gars["krum"].unchecked(g, f=F)),
            rtol=1e-5, atol=1e-6,
        )


class TestFoldedAggregateMulti:
    """Per-observer sub-Gram composition (fold.folded_tree_aggregate_multi):
    ONE extension+Gram build, m wait-n-f selections — must equal each
    observer's own poison-subset-aggregate where-path."""

    @pytest.mark.parametrize("gar_name", ["krum", "average", "brute"])
    @pytest.mark.parametrize("attack", ["lie", "reverse", "crash", None])
    def test_matches_per_observer_where_path(self, gar_name, attack):
        gar = gars[gar_name]
        mask = core.default_byz_mask(N, F)
        tree = _stacked_tree(jax.random.PRNGKey(29))
        q, m = N - 1, 4
        sels = jnp.stack([
            core.subset_indices(jax.random.PRNGKey(100 + i), N, q)
            for i in range(m)
        ])
        keys = jax.random.split(jax.random.PRNGKey(31), m)
        plan = (
            plan_gradient_attack_fold(attack, mask)
            if attack is not None else None
        )
        poisoned = tree
        if attack is not None and plan is None:
            pytest.skip("attack folds; nothing to test via identity plan")
        if attack is not None:
            poisoned = apply_gradient_attack_tree(
                attack, tree, jnp.asarray(mask)
            )
        got = folded_tree_aggregate_multi(
            gar, plan, tree, f=F, keys=keys, subset_sels=sels
        )
        for i in range(m):
            sub = jax.tree.map(lambda l: l[sels[i]], poisoned)
            want = gar.tree_aggregate(sub, f=F, key=keys[i])
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a[i]), np.asarray(b), rtol=1e-5, atol=1e-6
                ),
                got, want,
            )

    def test_identity_plan_randomized_attack_composes(self):
        """Randomized attacks take the tree where-path FIRST, then the
        identity fold — the dispatch the decentralized topologies use."""
        gar = gars["krum"]
        mask = core.default_byz_mask(N, F)
        tree = _stacked_tree(jax.random.PRNGKey(37))
        poisoned = apply_gradient_attack_tree(
            "random", tree, jnp.asarray(mask), key=jax.random.PRNGKey(5)
        )
        q, m = N - 1, 3
        sels = jnp.stack([
            core.subset_indices(jax.random.PRNGKey(200 + i), N, q)
            for i in range(m)
        ])
        got = folded_tree_aggregate_multi(
            gar, None, poisoned, f=F, subset_sels=sels
        )
        for i in range(m):
            sub = jax.tree.map(lambda l: l[sels[i]], poisoned)
            want = gar.tree_aggregate(sub, f=F)
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a[i]), np.asarray(b), rtol=1e-5, atol=1e-6
                ),
                got, want,
            )

    def test_non_gram_rule_rejected(self):
        with pytest.raises(ValueError, match="gram_select"):
            folded_tree_aggregate_multi(
                gars["median"], None, _stacked_tree(jax.random.PRNGKey(2)),
                f=F, subset_sels=jnp.stack([jnp.arange(N - 1)] * 2),
            )


class TestBf16FoldParity:
    """bf16 fold-parity rows (ADVICE r5 #3/#5): under the narrow pipeline
    the folded selection must match the where-path. aksel now quantizes its
    deviation to the stack dtype before squaring (same sort keys bitwise),
    so its aggregates agree to weighted-sum rounding; cclip's residual
    reduction-order drift is documented in its fold docstring, and this row
    pins the agreed tolerance."""

    def _bf16_tree(self, key):
        return jax.tree.map(
            lambda l: l.astype(jnp.bfloat16), _stacked_tree(key)
        )

    @pytest.mark.parametrize("attack", ["lie", "empire", "reverse", "crash"])
    def test_aksel_bf16_selection_parity(self, attack):
        gar = gars["aksel"]
        mask = core.default_byz_mask(N, F)
        tree = self._bf16_tree(jax.random.PRNGKey(41))
        plan = plan_gradient_attack_fold(attack, mask)
        got = folded_tree_aggregate(gar, plan, tree, f=F)
        poisoned = apply_gradient_attack_tree(attack, tree, jnp.asarray(mask))
        want = gar.tree_aggregate(poisoned, f=F)
        # A selection mismatch swaps O(1)-magnitude rows in a c=4 average
        # (error ~0.25); bf16 weighted-sum rounding is ~1e-2. The tolerance
        # separates the two regimes cleanly.
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=5e-2, atol=5e-2,
            ),
            got, want,
        )

    @pytest.mark.parametrize("attack", ["lie", "reverse"])
    @pytest.mark.parametrize("carried_center", [False, True])
    def test_cclip_bf16_documented_drift_bound(self, attack, carried_center):
        gar = gars["cclip"]
        mask = core.default_byz_mask(N, F)
        tree = self._bf16_tree(jax.random.PRNGKey(43))
        center = (
            jax.tree.map(
                lambda l: jnp.mean(l.astype(jnp.float32), axis=0), tree
            ) if carried_center else None
        )
        plan = plan_gradient_attack_fold(attack, mask)
        got = folded_tree_aggregate(
            gar, plan, tree, f=F,
            gar_params={"center": center} if center is not None else None,
        )
        poisoned = apply_gradient_attack_tree(attack, tree, jnp.asarray(mask))
        want = gar.tree_aggregate(poisoned, f=F, center=center)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=5e-2, atol=5e-2,
            ),
            got, want,
        )
        for leaf in jax.tree.leaves(got):
            assert np.isfinite(np.asarray(leaf, np.float32)).all()


@pytest.mark.parametrize("gar_name,f", [
    ("median", 1), ("tmean", 1),      # coordinate-wise kernels
    ("krum", 1), ("average", 1),      # gram_select (sanitized Gram)
    ("bulyan", 1),                    # fold_aggregate (sanitized Gram)
    ("cclip", 1),                     # fold_flat (row-level guard)
])
def test_crash_fold_nonfinite_row_stays_zero(gar_name, f):
    """A crashed slot whose raw gradient overflowed (inf) must behave as
    the where-path's literal ZERO row through every folded form: the
    coordinate-wise kernels special-case zero scales in-register, and the
    Gram-form rules sanitize the remapped Gram's zero-scale rows/cols
    (0 * inf would otherwise be NaN and read as infinitely distant,
    changing selection — ADVICE r4)."""
    gar = gars[gar_name]
    mask = core.default_byz_mask(N, 1)
    tree = _stacked_tree(jax.random.PRNGKey(13))
    tree = jax.tree.map(
        lambda l: l.at[N - 1].set(jnp.inf), tree
    )
    plan = plan_gradient_attack_fold("crash", mask)
    got = folded_tree_aggregate(gar, plan, tree, f=f)
    poisoned = apply_gradient_attack_tree("crash", tree, jnp.asarray(mask))
    want = gar.tree_aggregate(poisoned, f=f)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        got, want,
    )
    for leaf in jax.tree.leaves(got):
        assert np.isfinite(np.asarray(leaf)).all()

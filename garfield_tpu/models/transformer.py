"""Dropout-free transformer workloads: ViT-tiny and a small GPT.

The reference Garfield repo has no transformer machinery anywhere (its
workloads are the CNN zoo + the Pima tabular task), so this family is a
first-mover addition: Byzantine-robust DISTRIBUTED transformer training
on the slot-fused fast path (ROADMAP item: "slot-fused transformers").
Both models are deliberately dropout-free — the slot-fused gradient
twins (models/slotfused.py) cannot replicate flax's internal rng-path
folding, so like the rest of the twin-covered zoo the stochastic
regularizers stay out and equality against the unrolled per-slot
reference remains verifiable.

Design constraints the twins dictate:

  - every layer is an auto-named ``nn.compact`` submodule (``Conv_i`` /
    ``Dense_i`` / ``LayerNorm_i`` / ``EncoderBlock_i`` in creation
    order), so the twin mirrors the param tree by name;
  - the attention core (QK^T -> masked softmax -> PV) is
    ``slotlayers.attn_core`` — the SAME callable the twins trace, so
    fused and unrolled attention arithmetic can never drift (finite
    causal mask, f32 softmax statistics, in-order add-chain
    denominator);
  - ``ViT`` has no class token: patchify (``nn.Conv``, stride = patch)
    + learned positional embeddings + pre-LN encoder blocks + mean-pool
    + Dense head. The class token would be one more concat for zero
    test signal at this scale.
  - ``GPT`` is causal: token embedding (``nn.Embed``) + learned
    positional embeddings + pre-LN causal blocks + final LayerNorm,
    classifying from the LAST position's hidden state so the standard
    ``(logits, labels)`` losses and ``parallel.targeted_eval`` apply
    unchanged. ``tied=True`` reuses the embedding table as the output
    head (``nn.Embed.attend``) — the layout ``aggregators.dataplane``
    must REFUSE to fingerprint (no untied classifier head to locate).
"""

import flax.linen as nn
import jax.numpy as jnp

from . import slotlayers as sl

__all__ = ["EncoderBlock", "ViT", "GPT"]


class EncoderBlock(nn.Module):
    """Pre-LN transformer block: x + Attn(LN(x)); x + MLP(LN(x)).

    Creation order (the twin's contract): LayerNorm_0, Dense_0 (fused
    QKV), Dense_1 (out projection), LayerNorm_1, Dense_2 / Dense_3
    (GELU MLP). ``causal`` selects the masked attention variant.
    """

    dim: int
    heads: int
    mlp_dim: int
    causal: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        dh = self.dim // self.heads
        shape = q.shape[:-1] + (self.heads, dh)
        a = sl.attn_core(
            q.reshape(shape), k.reshape(shape), v.reshape(shape),
            causal=self.causal,
        )
        a = a.reshape(a.shape[:-2] + (self.dim,))
        x = x + nn.Dense(self.dim, dtype=self.dtype)(a)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype)(h)
        h = sl.gelu(h)
        return x + nn.Dense(self.dim, dtype=self.dtype)(h)


class ViT(nn.Module):
    """ViT-tiny for CIFAR-scale inputs: patchify -> encoder -> mean-pool.

    With the defaults on 32x32x3 inputs: 8x8 = 64 patches of 4x4, width
    48 over 3 heads (d_head 16) — the "attention-shaped d" regime the
    selection benchmarks bucket as heads * d_head * seq = 3072.
    """

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    patch: int = 4
    dim: int = 48
    depth: int = 2
    heads: int = 3
    mlp_dim: int = 96

    @nn.compact
    def __call__(self, x, train=False):
        del train  # dropout-free (twin-equality contract)
        h = nn.Conv(
            self.dim, (self.patch, self.patch),
            strides=(self.patch, self.patch), padding="VALID",
            dtype=self.dtype,
        )(x)
        h = h.reshape(h.shape[0], -1, self.dim)  # (b, T, D)
        pos = self.param(
            "pos_embedding", nn.initializers.normal(0.02),
            (h.shape[1], self.dim),
        )
        h = h + pos[None].astype(self.dtype)
        for _ in range(self.depth):
            h = EncoderBlock(
                self.dim, self.heads, self.mlp_dim, causal=False,
                dtype=self.dtype,
            )(h)
        h = nn.LayerNorm(dtype=self.dtype)(h)
        h = jnp.mean(h, axis=1)
        return nn.Dense(self.num_classes, dtype=self.dtype)(h)


class GPT(nn.Module):
    """Small causal transformer classifying from the last position.

    Consumes int token batches (b, T); the default vocab matches the
    ``copytask`` sequence dataset (data/__init__.py). ``tied=True``
    swaps the Dense head for ``nn.Embed.attend`` against the embedding
    table (logits over the vocab) — the embedding-tied layout the
    data-plane defense refuses loudly (``aggregators.dataplane``).
    """

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    vocab: int = 32
    dim: int = 48
    depth: int = 2
    heads: int = 3
    mlp_dim: int = 96
    tied: bool = False

    @nn.compact
    def __call__(self, x, train=False):
        del train  # dropout-free (twin-equality contract)
        emb = nn.Embed(self.vocab, self.dim, dtype=self.dtype)
        h = emb(x)  # (b, T, D)
        pos = self.param(
            "pos_embedding", nn.initializers.normal(0.02),
            (x.shape[-1], self.dim),
        )
        h = h + pos[None].astype(self.dtype)
        for _ in range(self.depth):
            h = EncoderBlock(
                self.dim, self.heads, self.mlp_dim, causal=True,
                dtype=self.dtype,
            )(h)
        h = nn.LayerNorm(dtype=self.dtype)(h)
        h = h[:, -1]
        if self.tied:
            return emb.attend(h)
        return nn.Dense(self.num_classes, dtype=self.dtype)(h)

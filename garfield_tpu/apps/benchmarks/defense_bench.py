"""Adaptive-adversary vs closed-loop-defense record (DEFBENCH_r*).

The committed acceptance artifact of DESIGN.md §16/§17/§18, measured as
matched accuracy CELLS (same task, same seed, same step budget — only
the attack/defense column changes). r01 covered the gradient plane on
the aggregathor topology; r02 (``--grid``) extends the record to the
full PLANE x ATTACK x DEFENSE matrix; r03 (the same ``--grid``) adds
the DATA-plane rows — the targeted family against ``data`` and
``escalate+data`` (fingerprint detectors + center-pull, aggregators/
dataplane.py), the ``asr_baseline`` attributable-lift column, and the
labelflip-vs-average row where the flip is actually measurable:

  - **gradient** (aggregathor): clean / static vs adaptive lie+empire /
    the labelflip + backdoor TARGETED family (success measured as
    source→target confusion and trigger ASR via ``parallel.
    targeted_eval`` — the per-class metric the divergence-blind
    suspicion plane cannot produce), each with defense off vs
    ``escalate``;
  - **model** (byzsgd): a Byzantine PS running the model-plane collusion
    (``--ps_attack lie`` / ``adaptive-lie`` — mu + z*sigma over the
    gathered replica stack) against the fps-tolerant gather, defended by
    the per-plane suspicion weighting (``defense=`` on both planes) +
    the gradient ladder;
  - **gossip** (learn): Byzantine nodes poisoning the plane-2 model
    gossip (``model_attack lie`` / ``adaptive-lie``) under per-node
    wait-n-f subsets, same defense.

Original r01 cells (kept; the ``main`` entry without ``--grid``):

  1. ``clean``              — no attack, vanilla krum: the accuracy bar.
  2. ``static-lie``         — the oblivious ALIE attack (z = 1.035).
  3. ``adaptive-lie``       — the suspicion-aware controller
                              (attacks/adaptive.py) against the SAME
                              vanilla krum: the bisection sustains a
                              magnitude far above the static z, so the
                              final accuracy must degrade MORE than the
                              static cell's.
  4. ``adaptive-defense``   — the same adaptive attack against the full
                              closed loop (--defense escalate:
                              suspicion-weighted rows + the
                              krum -> multi-krum -> bulyan ladder,
                              aggregators/defense.py): accuracy must
                              come back to within ``--acc_margin`` of
                              the clean bar.
  5. ``adaptive-rotation``  — the adaptive attack rotating its active
                              cohort over an f_pool = 2f colluder pool:
                              every pool member's DECAYED suspicion must
                              stay below the static-cohort cell's
                              victim — the laundering the windowed
                              score (MetricsHub suspicion_halflife)
                              exists to expose.

Each cell is one ``defense_bench`` record (telemetry schema v7) in the
JSONL twin; the .json artifact adds the derived acceptance verdicts.
Run (CPU container, ~2-4 min):

  python -m garfield_tpu.apps.benchmarks.defense_bench \
      --out DEFBENCH_r01 --num_iter 240
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from ... import data as data_lib, parallel
from ...aggregators import defense as defense_lib
from ...attacks import LIE_Z, targeted as targeted_lib
from ...models import select_model
from ...parallel import aggregathor, byzsgd, learn
from ...telemetry import exporters as tele_fmt, hub as hub_lib
from ...utils import selectors

N_WORKERS = 16
F = 3  # bulyan (the ladder's top) needs n >= 4f + 3 = 15
# Model-plane (byzsgd) grid geometry: enough replicas for honest
# divergence under per-PS gradient subsets, fps = 1 Byzantine replica.
N_PS, FPS = 5, 1
# Gossip-plane (learn) grid geometry: 10 nodes with 3 Byzantine and a
# wait-n-f subset of 9 — krum stays feasible (q >= 2f + 3) while the
# nodes genuinely diverge AND the 3-row duplicate fake cluster has
# enough mass inside a node's quorum to matter (measured: at f=2 of 8
# the per-node rule rejects the whole collusion family outright and the
# grid's gossip row degenerates to ties).
N_NODES, F_NODES, NODE_SUBSET = 10, 3, 9
# Model/gossip collusion bracket ceiling: the model planes' spread is
# smaller than the gradient plane's, so the search space is wider.
PLANE_MAG_MAX = 12.0


def _task(args):
    # The default surrogate margin (3.5) is one-shot learnable — every
    # cell saturates and no attack registers in accuracy. The committed
    # record pins a HARD margin (overlapping classes) where a sustained
    # gradient bias measurably moves the decision boundary; an explicit
    # operator env still wins.
    import os

    os.environ.setdefault("GARFIELD_SURROGATE_MARGIN", str(args.margin))
    module = select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer(
        "sgd", lr=args.lr, momentum=0.0, weight_decay=0.0
    )
    m = data_lib.DatasetManager("pima", args.batch, N_WORKERS, N_WORKERS, 0)
    m.num_ps = 0
    xs, ys = m.sharded_train_batches()
    test = parallel.EvalSet(m.get_test_set(), binary=True)
    return module, loss, opt, xs, ys, test


def run_cell(args, task, name, *, attack=None, attack_params=None,
             defense=None, gar="krum"):
    """One accuracy cell: train ``num_iter`` steps, return the record.

    ``defense`` names the composed mode (``"escalate"``, ``"data"``,
    ``"escalate+data"``, or None/False for off) and drives the SAME
    closed loop apps/common.py deploys: the in-graph suspicion weighting
    and/or data-plane detectors (``defense=`` kwarg) plus — with
    escalate — the host-side escalation policy fed by a MetricsHub's
    decayed suspicion, rebuilding the trainer at level changes (the
    TrainState carries across rebuilds — the ladder is
    stateful-homogeneous, and the dp EMA twins ride the same state).
    """
    module, loss, opt, xs, ys, test = task
    attack_params = dict(attack_params or {})
    if defense is True:  # legacy boolean spelling
        defense = "escalate"
    modes = set((defense or "").split("+")) - {""}
    unknown = modes - {"escalate", "weighted", "data"}
    if unknown:
        raise ValueError(f"unknown defense modes {sorted(unknown)}")
    escalate = "escalate" in modes
    data = "data" in modes
    telemetry = escalate or bool(args.halflife)
    hub = hub_lib.MetricsHub(
        num_ranks=N_WORKERS, suspicion_halflife=args.halflife,
        meta={"tag": "defense_bench", "cell": name},
    )
    policy = None
    gar_params = {}
    if escalate:
        policy = defense_lib.EscalationPolicy(defense_lib.EscalationConfig(
            theta_up=args.theta_up, theta_down=args.theta_down,
            patience=args.patience, clean_window=args.clean_window,
        ))
        policy.level = defense_lib.start_level(
            policy.config.levels, gar, gar_params
        )
        gar, gar_params = policy.current()
    defense_kw = None
    if modes:
        defense_kw = {}
        if escalate or "weighted" in modes:
            defense_kw["halflife"] = args.halflife or 16.0
        else:
            defense_kw["weighted"] = False
        if data:
            defense_kw["data"] = {
                "tau": args.dp_tau, "floor": args.dp_floor,
                "halflife": args.dp_halflife,
            }

    # Wire-compression emulation (round 18): the adaptive-lie cells over
    # a compressed gradient plane ARE the attack-headroom instrument —
    # the controller's admitted magnitude under int8/int4/topk minus the
    # bf16 baseline is the extra room quantization noise hands ALIE.
    wire_kw = None
    if getattr(args, "wire_dtype", "f32") != "f32" or \
            getattr(args, "wire_topk", 0):
        wire_kw = {"dtype": args.wire_dtype, "topk": args.wire_topk}

    def build(g, gp):
        return aggregathor.make_trainer(
            module, loss, opt, g,
            num_workers=N_WORKERS, f=F,
            attack=attack, attack_params=attack_params,
            gar_params=gp,
            telemetry=telemetry,
            defense=defense_kw,
            wire=wire_kw,
        )

    t0 = time.time()
    init_fn, step_fn, eval_fn = build(gar, gar_params)
    state = init_fn(jax.random.PRNGKey(args.seed), xs[0, 0])
    x = jnp.asarray(xs[:, 0])
    y = jnp.asarray(ys[:, 0])
    escalations = 0
    last_mag = None
    num_batches = xs.shape[1]
    for i in range(args.num_iter):
        b = i % num_batches
        state, metrics = step_fn(
            state, jnp.asarray(xs[:, b]), jnp.asarray(ys[:, b])
        )
        if "attack_mag" in metrics:
            last_mag = float(metrics["attack_mag"])
        if telemetry and "tap" in metrics:
            hub.record_step(i, loss=float(metrics["loss"]),
                            tap=jax.device_get(metrics["tap"]))
        if policy is not None:
            susp = hub.suspicion_decayed()
            if susp is not None:
                act = policy.observe(float(
                    defense_lib.suspicion_concentration(susp, F)
                ))
                if act:
                    escalations += 1
                    gar, gar_params = policy.current()
                    print(f"[{name}] step {i}: defense "
                          f"{'escalates' if act > 0 else 'de-escalates'} "
                          f"to {policy.level_name!r}", flush=True)
                    _, step_fn, eval_fn = build(gar, gar_params)
    del x, y
    acc = parallel.compute_accuracy(state, eval_fn, test, binary=True)
    # Targeted success metrics (schema v8): source→target confusion on
    # EVERY gradient cell (the clean cell's value is the baseline the
    # acceptance bar is 2x of), trigger ASR on backdoor cells.
    tcfg = None
    if targeted_lib.is_targeted(attack):
        tcfg = targeted_lib.configure(attack, attack_params, num_classes=1)
    trep = parallel.targeted_eval(
        state, eval_fn, test,
        source=(tcfg.source if tcfg else 0),
        target=(tcfg.target if tcfg else 1),
        trigger_cfg=(
            tcfg if tcfg is not None and tcfg.attack == "backdoor"
            else targeted_lib.TargetedConfig(
                "backdoor", 0, 1, binary=True
            ) if attack is None else None
        ),
    )
    susp = hub.suspicion()
    susp_d = hub.suspicion_decayed()
    rec = tele_fmt.make_record(
        "defense_bench",
        cell=name,
        plane="gradient",
        gar=str(gar),
        attack=attack,
        defense=(defense or None),
        n=N_WORKERS, f=F,
        steps=int(args.num_iter),
        seed=int(args.seed),
        final_accuracy=round(float(acc), 6),
        attack_magnitude=(
            None if last_mag is None else round(last_mag, 6)
        ),
        confusion=(
            None if trep["confusion"] is None
            else round(trep["confusion"], 6)
        ),
        asr=None if trep["asr"] is None else round(trep["asr"], 6),
        asr_baseline=(
            None if trep["asr_baseline"] is None
            else round(trep["asr_baseline"], 6)
        ),
        escalations=int(escalations) if escalate else None,
        suspicion=(
            None if susp is None else np.round(susp, 6).tolist()
        ),
        suspicion_decayed=(
            None if susp_d is None else np.round(susp_d, 6).tolist()
        ),
        wall_s=round(time.time() - t0, 3),
    )
    print(f"[{name}] accuracy {acc:.4f} "
          f"({rec['wall_s']}s, mag={rec['attack_magnitude']}, "
          f"confusion={rec['confusion']}, asr={rec['asr']})", flush=True)
    return rec


def _task_n(args, n):
    """The gradient-plane task re-sharded for ``n`` slots (model/gossip
    cells use fewer, bigger shards so divergence is real)."""
    import os

    os.environ.setdefault("GARFIELD_SURROGATE_MARGIN", str(args.margin))
    module = select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer(
        "sgd", lr=args.lr, momentum=0.0, weight_decay=0.0
    )
    m = data_lib.DatasetManager("pima", args.batch, n, n, 0)
    m.num_ps = 0
    xs, ys = m.sharded_train_batches()
    test = parallel.EvalSet(m.get_test_set(), binary=True)
    return module, loss, opt, xs, ys, test


def _run_plane_cell(args, name, build, *, plane, attack, defense,
                    mag_metric, gar_name, n, f, xs, ys, test):
    """Shared cell driver for the model/gossip planes: train, track the
    adaptive magnitude metric, return the schema-v8 record."""
    t0 = time.time()
    init_fn, step_fn, eval_fn = build()
    state = init_fn(jax.random.PRNGKey(args.seed), xs[0, 0])
    last_mag = None
    num_batches = xs.shape[1]
    for i in range(args.num_iter):
        b = i % num_batches
        state, metrics = step_fn(
            state, jnp.asarray(xs[:, b]), jnp.asarray(ys[:, b])
        )
        if mag_metric in metrics:
            last_mag = float(metrics[mag_metric])
    acc = parallel.compute_accuracy(state, eval_fn, test, binary=True)
    rec = tele_fmt.make_record(
        "defense_bench",
        cell=name,
        plane=plane,
        gar=str(gar_name),
        attack=attack,
        defense=defense,
        n=int(n), f=int(f),
        steps=int(args.num_iter),
        seed=int(args.seed),
        final_accuracy=round(float(acc), 6),
        attack_magnitude=(
            None if last_mag is None else round(last_mag, 6)
        ),
        wall_s=round(time.time() - t0, 3),
    )
    print(f"[{name}] accuracy {acc:.4f} "
          f"({rec['wall_s']}s, mag={rec['attack_magnitude']})", flush=True)
    return rec


def run_model_cell(args, task, name, *, ps_attack=None,
                   ps_attack_params=None, defense=False):
    """One MODEL-plane cell: byzsgd with a Byzantine replica publishing
    the collusion fake into the fps-tolerant gather. Honest replicas
    diverge through per-PS gradient subsets (the async reality), which
    is the spread the model-plane ALIE hides inside. The defended cell
    runs the in-graph per-plane suspicion weighting (``defense=`` —
    worker AND replica histories)."""
    module, loss, opt, xs, ys, test = task

    def build():
        return byzsgd.make_trainer(
            module, loss, opt, "krum",
            num_workers=N_WORKERS, num_ps=N_PS, fw=F, fps=FPS,
            subset=N_WORKERS - F,
            ps_attack=ps_attack,
            ps_attack_params=dict(ps_attack_params or {}),
            defense=(
                {"halflife": args.halflife or 16.0} if defense else None
            ),
        )

    return _run_plane_cell(
        args, name, build, plane="model", attack=ps_attack,
        defense="weighted" if defense else None,
        mag_metric="ps_attack_mag", gar_name="krum", n=N_PS, f=FPS,
        xs=xs, ys=ys, test=test,
    )


def run_gossip_cell(args, task, name, *, model_attack=None,
                    model_attack_params=None, defense=False):
    """One GOSSIP-plane cell: LEARN nodes under wait-n-f subsets with
    Byzantine nodes poisoning the plane-2 model gossip; the defended
    cell weights all three phases by the carried per-node suspicion
    EMA (``defense=``)."""
    module, loss, opt, xs, ys, test = task

    def build():
        return learn.make_trainer(
            module, loss, opt, "krum",
            num_nodes=N_NODES, f=F_NODES, subset=NODE_SUBSET,
            model_attack=model_attack,
            model_attack_params=dict(model_attack_params or {}),
            defense=(
                {"halflife": args.halflife or 16.0} if defense else None
            ),
        )

    return _run_plane_cell(
        args, name, build, plane="gossip", attack=model_attack,
        defense="weighted" if defense else None,
        mag_metric="model_attack_mag", gar_name="krum",
        n=N_NODES, f=F_NODES, xs=xs, ys=ys, test=test,
    )


def run_grid(args):
    """The r02 PLANE x ATTACK x DEFENSE grid (DESIGN.md §17) + the r03
    data-plane rows (DESIGN.md §18): the targeted family against
    ``data`` and ``escalate+data``, the composed closed loop that
    finally touches the backdoor cell the GAR ladder cannot."""
    task = _task(args)
    adaptive_params = {"mag_max": args.mag_max}
    plane_params = {"mag_max": PLANE_MAG_MAX}
    cells = [
        # --- gradient plane (aggregathor) ------------------------------
        run_cell(args, task, "grad/clean"),
        run_cell(args, task, "grad/clean/data", defense="data"),
        run_cell(args, task, "grad/static-lie", attack="lie",
                 attack_params={"z": LIE_Z}),
        run_cell(args, task, "grad/adaptive-lie/off",
                 attack="adaptive-lie", attack_params=adaptive_params),
        run_cell(args, task, "grad/adaptive-lie/escalate",
                 attack="adaptive-lie", attack_params=adaptive_params,
                 defense="escalate"),
        run_cell(args, task, "grad/static-empire", attack="empire",
                 attack_params={"eps": 10.0}),
        run_cell(args, task, "grad/adaptive-empire/off",
                 attack="adaptive-empire",
                 attack_params={"mag_max": args.mag_max}),
        run_cell(args, task, "grad/adaptive-empire/escalate",
                 attack="adaptive-empire",
                 attack_params={"mag_max": args.mag_max},
                 defense="escalate"),
        # --- targeted family (gradient plane data poisoning) -----------
        run_cell(args, task, "grad/labelflip/off", attack="labelflip",
                 attack_params=dict(args.targeted_params)),
        run_cell(args, task, "grad/labelflip/escalate",
                 attack="labelflip",
                 attack_params=dict(args.targeted_params),
                 defense="escalate"),
        run_cell(args, task, "grad/backdoor/off", attack="backdoor",
                 attack_params=dict(args.targeted_params)),
        run_cell(args, task, "grad/backdoor/escalate", attack="backdoor",
                 attack_params=dict(args.targeted_params),
                 defense="escalate"),
        # --- r03: the data plane closes the backdoor -------------------
        run_cell(args, task, "grad/backdoor/data", attack="backdoor",
                 attack_params=dict(args.targeted_params),
                 defense="data"),
        run_cell(args, task, "grad/backdoor/escalate+data",
                 attack="backdoor",
                 attack_params=dict(args.targeted_params),
                 defense="escalate+data"),
        run_cell(args, task, "grad/labelflip/data", attack="labelflip",
                 attack_params=dict(args.targeted_params),
                 defense="data"),
        run_cell(args, task, "grad/labelflip/escalate+data",
                 attack="labelflip",
                 attack_params=dict(args.targeted_params),
                 defense="escalate+data"),
        # The krum rows above mostly ABSORB labelflip already (its
        # confusion lift sits inside the binary surrogate's eval noise
        # — recorded, the r02 finding). The measurable labelflip bar
        # runs on the rule the flip actually beats: plain averaging,
        # where the data plane alone must recover the confusion crater.
        run_cell(args, task, "grad/labelflip-avg/clean", gar="average"),
        run_cell(args, task, "grad/labelflip-avg/off", gar="average",
                 attack="labelflip",
                 attack_params=dict(args.targeted_params)),
        run_cell(args, task, "grad/labelflip-avg/data", gar="average",
                 attack="labelflip",
                 attack_params=dict(args.targeted_params),
                 defense="data"),
    ]
    # --- model plane (byzsgd, Byzantine replica) -----------------------
    task_m = task
    cells += [
        run_model_cell(args, task_m, "model/clean"),
        run_model_cell(args, task_m, "model/static-lie",
                       ps_attack="lie", ps_attack_params={"z": LIE_Z}),
        run_model_cell(args, task_m, "model/adaptive-lie/off",
                       ps_attack="adaptive-lie",
                       ps_attack_params=plane_params),
        run_model_cell(args, task_m, "model/adaptive-lie/weighted",
                       ps_attack="adaptive-lie",
                       ps_attack_params=plane_params, defense=True),
    ]
    # --- gossip plane (learn, Byzantine nodes) -------------------------
    task_g = _task_n(args, N_NODES)
    cells += [
        run_gossip_cell(args, task_g, "gossip/clean"),
        run_gossip_cell(args, task_g, "gossip/static-lie",
                        model_attack="lie",
                        model_attack_params={"z": LIE_Z}),
        run_gossip_cell(args, task_g, "gossip/adaptive-lie/off",
                        model_attack="adaptive-lie",
                        model_attack_params=plane_params),
        run_gossip_cell(args, task_g, "gossip/adaptive-lie/weighted",
                        model_attack="adaptive-lie",
                        model_attack_params=plane_params,
                        defense=True),
    ]
    by = {c["cell"]: c for c in cells}
    acc = {k: c["final_accuracy"] for k, c in by.items()}

    def mag(cell):
        return by[cell]["attack_magnitude"]

    clean_conf = by["grad/clean"]["confusion"] or 0.0
    clean_asr = by["grad/clean"]["asr"] or 0.0
    # r02-era ACCURACY-DELTA comparisons, RECORDED but no longer gated:
    # their margins (degrade_margin 0.01, acc_margin 0.05) were
    # calibrated in the r02 container, and this container's float
    # environment moved the identical-code clean cell by 0.03 (the eval
    # quantum is 1/168 ≈ 0.006, run-to-run wobble ±0.02-0.03) — re-run
    # here they flip per run on noise, which is evidence about the
    # container, not the defense. The r02 artifact remains the committed
    # record of those contracts in its own environment; r03 gates the
    # structural verdicts and the data-plane bars below.
    legacy = {
        "grad_adaptive_beats_static": bool(
            acc["grad/adaptive-lie/off"]
            <= acc["grad/static-lie"] - args.degrade_margin
        ),
        "grad_adaptive_empire_damages": bool(
            acc["grad/adaptive-empire/off"]
            <= acc["grad/clean"] - args.degrade_margin
        ),
        "model_adaptive_beats_static": bool(
            acc["model/adaptive-lie/off"] <= acc["model/static-lie"]
        ),
        "gossip_adaptive_beats_static": bool(
            acc["gossip/adaptive-lie/off"] <= acc["gossip/static-lie"]
        ),
        "grad_defense_restores_bar": bool(
            acc["grad/adaptive-lie/escalate"]
            >= acc["grad/clean"] - args.acc_margin
        ),
        "grad_defense_restores_bar_empire": bool(
            acc["grad/adaptive-empire/escalate"]
            >= acc["grad/clean"] - args.acc_margin
        ),
        "model_defense_restores_bar": bool(
            acc["model/adaptive-lie/weighted"]
            >= acc["model/clean"] - args.acc_margin
        ),
        "gossip_defense_restores_bar": bool(
            acc["gossip/adaptive-lie/weighted"]
            >= acc["gossip/clean"] - args.acc_margin
        ),
        "grad_defense_beats_undefended": bool(
            acc["grad/adaptive-lie/escalate"]
            >= acc["grad/adaptive-lie/off"]
        ),
        "gossip_defense_beats_undefended": bool(
            acc["gossip/adaptive-lie/weighted"]
            >= acc["gossip/adaptive-lie/off"]
        ),
        "note": (
            "environment-sensitive accuracy comparisons re-run in the "
            "r03 container; the r02 artifact is the committed record "
            "of these contracts (clean cell moved 0.03 on identical "
            "code across containers)"
        ),
    }
    lfa_clean = by["grad/labelflip-avg/clean"]["confusion"]
    lfa_off = by["grad/labelflip-avg/off"]["confusion"]
    lfa_data = by["grad/labelflip-avg/data"]["confusion"]
    verdicts = {
        # Bracket pinning: where the defended rule refuses the fake, the
        # bisection collapses onto mag_min (the model plane's gather
        # does this exactly) — structural, not a noise-bound accuracy
        # delta, so it stays gated.
        "model_attacker_pinned_to_floor": bool(
            mag("model/adaptive-lie/weighted") is not None
            and mag("model/adaptive-lie/weighted") <= 0.5
        ),
        # Targeted family on the krum grid: measurable with defense
        # off, bounded under the GAR-side row (the r02 contracts).
        "labelflip_measurable": bool(
            by["grad/labelflip/off"]["confusion"] > clean_conf
        ),
        "labelflip_defended": bool(
            by["grad/labelflip/escalate"]["confusion"]
            < 2.0 * max(clean_conf, 1e-3)
        ),
        "backdoor_measurable": bool(
            by["grad/backdoor/off"]["asr"] > clean_asr
        ),
        # r02 finding, now CLOSED by the r03 data plane: the backdoor's
        # trigger ASR survives every divergence-based (GAR-side) defense
        # (its gradients are honest gradients of the poisoned task —
        # consistent with the backdoor literature); the fingerprint
        # detectors (DESIGN.md §18) are what finally touch it.
        "backdoor_asr_off": by["grad/backdoor/off"]["asr"],
        "backdoor_asr_defended": by["grad/backdoor/escalate"]["asr"],
        "clean_confusion": clean_conf,
        "clean_asr": clean_asr,
        # --- r03 gates: the data-plane defense bar (ISSUE 12) ----------
        # The composed loop drops the backdoor trigger ASR to <=
        # --asr_bar (vs ~0.6 GAR-only in DEFBENCH_r02) while the SAME
        # cell's clean accuracy stays within --acc_margin of the bar...
        "backdoor_data_asr_bar": bool(
            by["grad/backdoor/escalate+data"]["asr"] is not None
            and by["grad/backdoor/escalate+data"]["asr"] <= args.asr_bar
        ),
        "backdoor_data_only_asr_bar": bool(
            by["grad/backdoor/data"]["asr"] is not None
            and by["grad/backdoor/data"]["asr"] <= args.asr_bar
        ),
        "backdoor_data_clean_delta_ok": bool(
            acc["grad/backdoor/escalate+data"]
            >= acc["grad/clean"] - args.acc_margin
        ),
        # ...the detectors are an identity on the clean cell (no honest
        # cohort gets crushed)...
        "data_clean_identity": bool(
            acc["grad/clean/data"] >= acc["grad/clean"] - args.acc_margin
        ),
        # ...and labelflip confusion measurably improves on the rule the
        # flip actually beats (plain averaging — the krum rows absorb
        # labelflip into eval noise already, recorded above): the
        # avg/off cell must show a real confusion lift over avg/clean,
        # and the data plane must claw back at least half of it.
        "labelflip_avg_measurable": bool(
            lfa_off >= lfa_clean + 0.05
        ),
        "labelflip_data_improves": bool(
            lfa_data <= lfa_off - 0.05
            and lfa_data <= lfa_clean + (lfa_off - lfa_clean) / 2.0
        ),
        "labelflip_avg_confusions": {
            "clean": lfa_clean, "off": lfa_off, "data": lfa_data,
        },
        "backdoor_asr_data": by["grad/backdoor/data"]["asr"],
        "backdoor_asr_escalate_data":
            by["grad/backdoor/escalate+data"]["asr"],
        # v9: the clean-model trigger-rate floor — the ASR cells'
        # attributable-lift denominator (parallel.targeted_eval).
        "backdoor_asr_baseline":
            by["grad/backdoor/escalate+data"]["asr_baseline"],
    }
    doc = {
        "bench": "defense_bench",
        "grid": "r03",
        "legacy_acc_comparisons": legacy,
        "schema_v": tele_fmt.SCHEMA_VERSION,
        "config": {
            "grad": {"n": N_WORKERS, "f": F},
            "model": {"n_w": N_WORKERS, "n_ps": N_PS, "fps": FPS,
                      "subset": N_WORKERS - F},
            "gossip": {"n": N_NODES, "f": F_NODES,
                       "subset": NODE_SUBSET},
            "num_iter": args.num_iter, "batch": args.batch,
            "lr": args.lr, "seed": args.seed, "margin": args.margin,
            "mag_max": args.mag_max, "halflife": args.halflife,
            "theta_up": args.theta_up, "theta_down": args.theta_down,
            "patience": args.patience, "acc_margin": args.acc_margin,
            "degrade_margin": args.degrade_margin,
            "targeted_params": dict(args.targeted_params),
            "dp_tau": args.dp_tau, "dp_floor": args.dp_floor,
            "dp_halflife": args.dp_halflife, "asr_bar": args.asr_bar,
        },
        "accuracy": acc,
        "verdicts": verdicts,
        "cells": cells,
    }
    with open(args.out + ".json", "w") as fp:
        json.dump(doc, fp, indent=1)
    with open(args.out + ".jsonl", "w") as fp:
        for c in cells:
            tele_fmt.validate_record(c)
            fp.write(json.dumps(c) + "\n")
    print(json.dumps({"accuracy": acc, "verdicts": verdicts}, indent=1))
    gates = [v for k, v in verdicts.items() if isinstance(v, bool)]
    ok = all(gates)
    print(f"defense_bench grid: {'ACCEPTED' if ok else 'REJECTED'}")
    return 0 if ok else 1


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", type=str, default="DEFBENCH",
                   help="Artifact prefix: writes <out>.json + <out>.jsonl")
    p.add_argument("--num_iter", type=int, default=300)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--margin", type=float, default=1.2,
                   help="Surrogate class margin (GARFIELD_SURROGATE_"
                        "MARGIN default for this run; lower = harder).")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--mag_max", type=float, default=6.0,
                   help="Adaptive bracket ceiling (lie z upper bound).")
    p.add_argument("--halflife", type=float, default=24.0,
                   help="Suspicion halflife (windowed score, schema v7).")
    p.add_argument("--theta_up", type=float, default=0.35)
    p.add_argument("--theta_down", type=float, default=0.1)
    p.add_argument("--patience", type=int, default=4)
    p.add_argument("--clean_window", type=int, default=60)
    p.add_argument("--acc_margin", type=float, default=0.05,
                   help="Defense cell must land within this of clean.")
    p.add_argument("--degrade_margin", type=float, default=0.01,
                   help="Adaptive must undercut static by at least this.")
    p.add_argument("--grid", action="store_true",
                   help="Run the PLANE x ATTACK x DEFENSE grid "
                        "(gradient/model/gossip x adaptive/targeted x "
                        "off/weighted/escalate, plus the r03 data-plane "
                        "rows: targeted x data/escalate+data) instead "
                        "of the r01 gradient-plane cells.")
    p.add_argument("--dp_tau", type=float, default=2.0,
                   help="Data-plane spectral tail threshold (flag ranks "
                        "with outlier score > tau).")
    p.add_argument("--dp_floor", type=float, default=0.0,
                   help="Data-plane suspicion-weight floor (0: a fully-"
                        "suspect row collapses exactly onto the center "
                        "— the detector observes raw rows regardless, "
                        "so the GAR plane's observability floor does "
                        "not apply here).")
    p.add_argument("--dp_halflife", type=float, default=8.0,
                   help="Data-plane flag-EMA halflife (steps).")
    p.add_argument("--asr_bar", type=float, default=0.15,
                   help="r03 gate: defended backdoor trigger ASR must "
                        "land at or below this.")
    p.add_argument("--targeted_params", type=json.loads,
                   default={"source": 0, "target": 1},
                   help="Targeted-attack knobs for the grid's labelflip/"
                        "backdoor cells (source/target/poison_frac/"
                        "trigger_*).")
    p.add_argument("--wire_dtype", type=str, default="f32",
                   choices=("f32", "bf16", "int8", "int4"),
                   help="In-graph wire-compression emulation for the "
                        "gradient-plane cells (parallel/compress.py): "
                        "the adaptive cells then measure the attack "
                        "headroom the scheme hands the controller.")
    p.add_argument("--wire_topk", type=int, default=0,
                   help="Top-k sparsification divisor for the emulated "
                        "wire (0 = off; nonzero replaces --wire_dtype "
                        "on the gradient rows).")
    args = p.parse_args(argv)

    if args.grid:
        return run_grid(args)

    task = _task(args)
    adaptive_params = {"mag_max": args.mag_max}
    cells = [
        run_cell(args, task, "clean"),
        run_cell(args, task, "static-lie", attack="lie",
                 attack_params={"z": LIE_Z}),
        run_cell(args, task, "adaptive-lie", attack="adaptive-lie",
                 attack_params=adaptive_params),
        run_cell(args, task, "adaptive-defense", attack="adaptive-lie",
                 attack_params=adaptive_params, defense=True),
        run_cell(args, task, "adaptive-rotation", attack="adaptive-lie",
                 attack_params={**adaptive_params, "f_pool": 2 * F,
                                "rotation": 8}),
    ]
    by = {c["cell"]: c for c in cells}
    acc = {k: c["final_accuracy"] for k, c in by.items()}

    # Acceptance verdicts (ISSUE 10): the adaptive attack beats the
    # static one against the vanilla rule; the closed loop restores the
    # bar; rotation launders the cumulative score but NOT the decayed
    # one below the static-cohort victim's.
    pool = list(range(N_WORKERS - 2 * F, N_WORKERS))
    static_cohort = list(range(N_WORKERS - F, N_WORKERS))
    rot_d = by["adaptive-rotation"]["suspicion_decayed"]
    adp_d = by["adaptive-lie"]["suspicion_decayed"]
    rot_max = (
        max(rot_d[r] for r in pool) if rot_d is not None else None
    )
    static_victim = (
        max(adp_d[r] for r in static_cohort) if adp_d is not None else None
    )
    verdicts = {
        "adaptive_beats_static": bool(
            acc["adaptive-lie"]
            <= acc["static-lie"] - args.degrade_margin
        ),
        "defense_restores_bar": bool(
            acc["adaptive-defense"] >= acc["clean"] - args.acc_margin
        ),
        "rotation_launders_decayed_below_static_victim": (
            None if rot_max is None or static_victim is None
            else bool(rot_max < static_victim)
        ),
        "rotation_pool_max_decayed": rot_max,
        "static_cohort_max_decayed": static_victim,
    }
    doc = {
        "bench": "defense_bench",
        "schema_v": tele_fmt.SCHEMA_VERSION,
        "config": {
            "n": N_WORKERS, "f": F, "num_iter": args.num_iter,
            "batch": args.batch, "lr": args.lr, "seed": args.seed,
            "mag_max": args.mag_max, "halflife": args.halflife,
            "theta_up": args.theta_up, "theta_down": args.theta_down,
            "patience": args.patience, "acc_margin": args.acc_margin,
            "degrade_margin": args.degrade_margin,
        },
        "accuracy": acc,
        "verdicts": verdicts,
        "cells": cells,
    }
    with open(args.out + ".json", "w") as fp:
        json.dump(doc, fp, indent=1)
    with open(args.out + ".jsonl", "w") as fp:
        for c in cells:
            tele_fmt.validate_record(c)
            fp.write(json.dumps(c) + "\n")
    print(json.dumps({"accuracy": acc, "verdicts": verdicts}, indent=1))
    ok = all(v for v in (
        verdicts["adaptive_beats_static"],
        verdicts["defense_restores_bar"],
        verdicts["rotation_launders_decayed_below_static_victim"],
    ))
    print(f"defense_bench: {'ACCEPTED' if ok else 'REJECTED'}")
    return 0 if ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main(sys.argv[1:]))

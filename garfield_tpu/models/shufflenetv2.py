"""ShuffleNetV2 (counterpart of garfieldpp/models/shufflenetv2.py)."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, norm

configs = {
    0.5: {"out_planes": (48, 96, 192), "num_blocks": (3, 7, 3)},
    1.0: {"out_planes": (116, 232, 464), "num_blocks": (3, 7, 3)},
    1.5: {"out_planes": (176, 352, 704), "num_blocks": (3, 7, 3)},
    2.0: {"out_planes": (224, 488, 976), "num_blocks": (3, 7, 3)},
}


def channel_shuffle(x, groups=2):
    n, h, w, c = x.shape
    return (x.reshape(n, h, w, groups, c // groups)
             .transpose(0, 1, 2, 4, 3)
             .reshape(n, h, w, c))


class BasicUnit(nn.Module):
    out_planes: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        c = x.shape[-1] // 2
        left, right = x[..., :c], x[..., c:]
        mid = self.out_planes // 2
        out = nn.relu(norm(train, dtype=d)(conv1x1(mid, dtype=d)(right)))
        out = norm(train, dtype=d)(
            conv(mid, 3, 1, padding=1, groups=mid, dtype=d)(out))
        out = nn.relu(norm(train, dtype=d)(conv1x1(mid, dtype=d)(out)))
        return channel_shuffle(jnp.concatenate([left, out], axis=-1))


class DownUnit(nn.Module):
    out_planes: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        mid = self.out_planes // 2
        # left branch: depthwise stride-2 + 1x1
        left = norm(train, dtype=d)(
            conv(x.shape[-1], 3, 2, padding=1, groups=x.shape[-1], dtype=d)(x))
        left = nn.relu(norm(train, dtype=d)(conv1x1(mid, dtype=d)(left)))
        # right branch: 1x1 + depthwise stride-2 + 1x1
        right = nn.relu(norm(train, dtype=d)(conv1x1(mid, dtype=d)(x)))
        right = norm(train, dtype=d)(
            conv(mid, 3, 2, padding=1, groups=mid, dtype=d)(right))
        right = nn.relu(norm(train, dtype=d)(conv1x1(mid, dtype=d)(right)))
        return channel_shuffle(jnp.concatenate([left, right], axis=-1))


class ShuffleNetV2(nn.Module):
    net_size: float = 1.0
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        cfg = configs[self.net_size]
        x = nn.relu(norm(train, dtype=d)(conv(24, 3, 1, padding=1, dtype=d)(x)))
        for stage in range(3):
            x = DownUnit(cfg["out_planes"][stage], dtype=d)(x, train)
            for _ in range(cfg["num_blocks"][stage]):
                x = BasicUnit(cfg["out_planes"][stage], dtype=d)(x, train)
        x = nn.relu(norm(train, dtype=d)(conv1x1(1024, dtype=d)(x)))
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)

"""Bounded-staleness async aggregation (DESIGN.md §14): the weighting law.

Fast tier-1 coverage of the unified round/staleness policy
(utils/rounds.py) at both deployment scales it serves: the pure weight
law (decay, hard cutoff, exact identity at tau=0), its composition into
the folded-attack fast path (parallel/fold.py ``row_weights`` — the Gram
algebra must equal weighting the rows), the in-graph emulation on the
aggregathor topology (``staleness=``; --max_staleness 0 is BITWISE the
synchronous program), convergence under a slow Byzantine rank, and the
telemetry v4 staleness plumbing (suspicion folding, schema validation,
Prometheus histogram). The multi-process host-plane twins live in
tests/test_async_cluster.py (slow).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import data as data_lib
from garfield_tpu.aggregators import gars
from garfield_tpu.attacks import apply_gradient_attack
from garfield_tpu.models import select_model
from garfield_tpu.parallel import aggregathor, core, fold
from garfield_tpu.utils import rounds, selectors


class TestWeights:
    def test_decay_and_cutoff(self):
        w = rounds.staleness_weights(
            np.array([0, 1, 2, 3, 4, 5, 9]), decay=0.5, max_staleness=4
        )
        np.testing.assert_array_equal(
            w, np.array([1.0, 0.5, 0.25, 0.125, 0.0625, 0.0, 0.0],
                        np.float32),
        )
        assert w.dtype == np.float32

    def test_tau_zero_is_exactly_one(self):
        # The --max_staleness 0 bitwise contract rests on this: a fresh
        # row's weight is EXACTLY 1.0, whatever the decay.
        for decay in (0.3, 0.5, 0.9, 1.0):
            w = rounds.staleness_weights(
                np.array([0]), decay=decay, max_staleness=8
            )
            assert w[0] == np.float32(1.0)

    def test_negative_tau_clamps(self):
        # A frame tagged AHEAD of the consumer (catch-up race) is fresh.
        w = rounds.staleness_weights(
            np.array([-3, 0]), decay=0.5, max_staleness=2
        )
        np.testing.assert_array_equal(w, [1.0, 1.0])

    def test_jnp_matches_np_and_jits(self):
        taus = np.array([0, 1, 3, 7])
        w_np = rounds.staleness_weights(taus, decay=0.7, max_staleness=5)
        w_j = jax.jit(
            lambda t: rounds.staleness_weights(
                t, decay=0.7, max_staleness=5
            )
        )(jnp.asarray(taus))
        np.testing.assert_array_equal(np.asarray(w_j), w_np)

    def test_discount_rows(self):
        stack = np.arange(12, dtype=np.float32).reshape(4, 3)
        w = np.array([1.0, 0.5, 0.25, 0.0], np.float32)
        out = rounds.discount_rows(stack, w)
        np.testing.assert_array_equal(out, stack * w[:, None])
        # w == 1 everywhere is a bitwise no-op (IEEE multiply).
        ones = np.ones(4, np.float32)
        assert np.array_equal(rounds.discount_rows(stack, ones), stack)

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            rounds.StalenessPolicy(-1, 0.5)
        with pytest.raises(ValueError):
            rounds.StalenessPolicy(2, 0.0)
        with pytest.raises(ValueError):
            rounds.StalenessPolicy(2, 1.5)

    def test_resolve_env_defaults(self, monkeypatch):
        class A:
            async_agg = True
            max_staleness = None
            staleness_decay = None

        monkeypatch.setenv("GARFIELD_MAX_STALENESS", "7")
        monkeypatch.setenv("GARFIELD_STALENESS_DECAY", "0.8")
        p = rounds.resolve(A())
        assert (p.max_staleness, p.decay) == (7, 0.8)

        class B:
            async_agg = False

        assert rounds.resolve(B()) is None


def _tiny_tree(key, n=8):
    """A small stacked gradient tree (two leaves) for fold tests."""
    k1, k2 = jax.random.split(key)
    return {
        "a": jax.random.normal(k1, (n, 6, 3), jnp.float32),
        "b": jax.random.normal(k2, (n, 5), jnp.float32),
    }


class TestWeightedFold:
    def _reference(self, gar, tree, w, byz_mask, f, attack="lie"):
        """Where-path reference: poison the flat stack, weight the rows,
        aggregate — the semantics the Gram composition must reproduce."""
        flat = core.flatten_rows(tree)
        poisoned = apply_gradient_attack(attack, flat, byz_mask)
        weighted = poisoned * jnp.asarray(w)[:, None]
        return gar.unchecked(weighted, f=f)

    def test_fold_row_weights_match_weighted_rows(self):
        n, f = 8, 2
        gar = gars["krum"]
        byz_mask = core.default_byz_mask(n, f)
        tree = _tiny_tree(jax.random.PRNGKey(0), n)
        w = rounds.staleness_weights(
            np.array([0, 0, 1, 0, 2, 0, 3, 1]), decay=0.5, max_staleness=4
        )
        plan = fold.plan_for(gar, "lie", byz_mask, {})
        assert plan is not None
        got = fold.folded_tree_aggregate(
            gar, plan, tree, f=f, row_weights=jnp.asarray(w)
        )
        got_flat = jnp.concatenate(
            [l.reshape(-1) for l in jax.tree.leaves(got)]
        )
        ref = self._reference(gar, tree, w, byz_mask, f)
        np.testing.assert_allclose(
            np.asarray(got_flat), np.asarray(ref), rtol=2e-5, atol=1e-6
        )

    def test_fold_row_weights_bitwise_deterministic(self):
        n, f = 8, 2
        gar = gars["krum"]
        byz_mask = core.default_byz_mask(n, f)
        tree = _tiny_tree(jax.random.PRNGKey(1), n)
        w = jnp.asarray(rounds.staleness_weights(
            np.array([0, 1, 0, 2, 0, 0, 4, 3]), decay=0.5, max_staleness=4
        ))
        a = fold.folded_tree_aggregate(gar, plan := fold.plan_for(
            gar, "lie", byz_mask, {}
        ), tree, f=f, row_weights=w)
        b = fold.folded_tree_aggregate(
            gar, plan, tree, f=f, row_weights=w
        )
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_row_weights_rejected_off_gram_rules(self):
        n, f = 8, 2
        gar = gars["median"]  # tree_aggregate_ext fold, no gram_select
        byz_mask = core.default_byz_mask(n, f)
        plan = fold.plan_for(gar, "lie", byz_mask, {})
        assert plan is not None
        with pytest.raises(ValueError, match="row_weights"):
            fold.folded_tree_aggregate(
                gar, plan, _tiny_tree(jax.random.PRNGKey(2), n), f=f,
                row_weights=jnp.ones((n,)),
            )


def _pima_setup():
    module = select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer(
        "sgd", lr=0.05, momentum=0.0, weight_decay=0.0
    )
    return module, loss, opt


def _pima_batches(n, bsz):
    m = data_lib.DatasetManager("pima", bsz, n, n, 0)
    m.num_ps = 0
    xs, ys = m.sharded_train_batches()
    return xs, jnp.asarray(xs[:, 0]), jnp.asarray(ys[:, 0])


def _run(step_fn, state, x, y, iters):
    losses = []
    for _ in range(iters):
        state, m = step_fn(state, x, y)
        losses.append(float(m["loss"]))
    return state, losses


def _flat_params(state):
    return np.concatenate(
        [np.asarray(l).ravel() for l in jax.tree.leaves(state.params)]
    )


class TestEmulation:
    def test_max_staleness_zero_is_bitwise_synchronous(self):
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        runs = []
        for staleness in (None, {"max_staleness": 0, "decay": 0.5}):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="lie", staleness=staleness,
            )
            state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
            state, losses = _run(step_fn, state, x, y, 6)
            runs.append((losses, _flat_params(state)))
        assert runs[0][0] == runs[1][0]
        np.testing.assert_array_equal(runs[0][1], runs[1][1])

    def test_all_zero_taus_is_bitwise_synchronous(self):
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        runs = []
        for staleness in (
            None,
            {"max_staleness": 3, "decay": 0.5, "taus": [0] * 8},
        ):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "median", num_workers=8, f=1,
                attack="reverse", staleness=staleness,
            )
            state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
            state, losses = _run(step_fn, state, x, y, 5)
            runs.append(losses)
        assert runs[0] == runs[1]

    def test_weighted_tree_matches_flat_path(self):
        # The fold composition (tree path, Gram algebra) and the flat
        # path (rows weighted explicitly) must train identically.
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        staleness = {
            "max_staleness": 4, "decay": 0.5,
            "taus": [0, 0, 1, 0, 2, 0, 3, 4],
        }
        states = []
        for tree_path in (True, False):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="lie", staleness=staleness, tree_path=tree_path,
            )
            state = init_fn(jax.random.PRNGKey(1), xs[0, 0])
            state, losses = _run(step_fn, state, x, y, 4)
            assert all(np.isfinite(l) for l in losses)
            states.append(_flat_params(state))
        np.testing.assert_allclose(
            states[0], states[1], rtol=2e-5, atol=1e-6
        )

    def test_random_taus_deterministic_and_finite(self):
        # Seeded per-step draws: two identical runs agree bitwise.
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        runs = []
        for _ in range(2):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="lie",
                staleness={"max_staleness": 3, "decay": 0.7},
            )
            state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
            state, losses = _run(step_fn, state, x, y, 5)
            runs.append(losses)
        assert runs[0] == runs[1]
        assert all(np.isfinite(l) for l in runs[0])

    def test_lie_attack_converges_with_slow_byzantine_rank(self):
        # The acceptance smoke at unit scale: the Byzantine rank is ALSO
        # the straggler (max staleness — its lie rows enter the GAR
        # discounted), krum at f=1 must train through it.
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "krum", num_workers=8, f=1, attack="lie",
            staleness={
                "max_staleness": 4, "decay": 0.5,
                # Rank 7 is the Byzantine slot (core.default_byz_mask
                # marks the LAST f ranks) — and the slow one.
                "taus": [0, 0, 0, 0, 0, 0, 0, 4],
            },
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        state, losses = _run(step_fn, state, x, y, 40)
        assert losses[-1] < losses[0] * 0.7, losses[::8]

    def test_bad_staleness_config_rejected(self):
        module, loss, opt = _pima_setup()
        with pytest.raises(ValueError, match="unknown staleness"):
            aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="lie", staleness={"max_stale": 3},
            )
        with pytest.raises(ValueError, match="shape"):
            aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="lie",
                staleness={"max_staleness": 3, "taus": [0, 1]},
            )


class TestTelemetryV4:
    def test_hub_folds_staleness_into_suspicion(self):
        from garfield_tpu.telemetry.hub import MetricsHub

        hub = MetricsHub(num_ranks=4)
        for step in range(10):
            hub.record_event(
                "staleness", who="t", step=step,
                ranks=[0, 1, 3], staleness=[0, 1, 4],
                weights=[1.0, 0.5, 0.0625], reused=2,
            )
        susp = hub.suspicion()
        # Rank 0 fresh (deficit 0), rank 1 deficit 0.5, rank 3 ~0.94;
        # rank 2 never observed.
        assert susp[0] == pytest.approx(0.0)
        assert susp[1] == pytest.approx(0.5)
        assert susp[3] == pytest.approx(1 - 0.0625)
        st = hub.staleness_stats()
        assert st["count"] == 30 and st["max"] == 4
        assert st["hist"] == {0: 10, 1: 10, 4: 10}
        assert st["mean"] == pytest.approx(5 / 3)

    def test_summary_staleness_block_validates(self):
        from garfield_tpu.telemetry import exporters
        from garfield_tpu.telemetry.hub import MetricsHub

        hub = MetricsHub(num_ranks=3)
        hub.record_event(
            "staleness", who="t", step=0, ranks=[0, 1],
            staleness=[0, 2], weights=[1.0, 0.25], reused=1,
        )
        rec = hub.summary()
        exporters.validate_record(rec)
        assert rec["staleness"]["count"] == 2
        # Synchronous hubs stay v3-shaped (staleness None).
        rec2 = MetricsHub(num_ranks=3).summary()
        exporters.validate_record(rec2)
        assert rec2["staleness"] is None

    def test_validate_staleness_event(self):
        from garfield_tpu.telemetry import exporters

        good = exporters.make_record(
            "event", event="staleness", step=3, ranks=[0, 1],
            staleness=[0, 2], weights=[1.0, 0.25],
        )
        exporters.validate_record(good)
        bad = dict(good, weights=[1.0])  # length mismatch
        with pytest.raises(ValueError):
            exporters.validate_record(bad)
        bad2 = dict(good, step=-1)
        with pytest.raises(ValueError):
            exporters.validate_record(bad2)

    def test_prometheus_staleness_histogram(self):
        from garfield_tpu.telemetry import exporters
        from garfield_tpu.telemetry.hub import MetricsHub

        hub = MetricsHub(num_ranks=2)
        hub.record_event(
            "staleness", who="t", step=0, ranks=[0, 1],
            staleness=[0, 3], weights=[1.0, 0.125],
        )
        text = exporters.prometheus_text(hub)
        assert 'garfield_staleness_rounds_bucket{le="0"} 1' in text
        assert 'garfield_staleness_rounds_bucket{le="+Inf"} 2' in text
        assert "garfield_staleness_rounds_count 2" in text
        assert "garfield_staleness_rounds_max" in text
        # Synchronous hubs expose no staleness family at all.
        assert "garfield_staleness" not in exporters.prometheus_text(
            MetricsHub(num_ranks=2)
        )

    def test_exchange_bench_scenario_record_validates(self):
        from garfield_tpu.telemetry import exporters

        rec = exporters.make_record(
            "exchange_bench", n=4, d=100000, wire="f32",
            scenario="straggler", straggler_ms=120, sync_round_s=0.12,
            async_round_s=0.004, speedup=30.0, peak_rss_bytes=123456,
        )
        exporters.validate_record(rec)
        with pytest.raises(ValueError):
            exporters.validate_record(dict(rec, speedup="fast"))
        with pytest.raises(ValueError):
            exporters.validate_record(dict(rec, peak_rss_bytes=-1))


class TestLearnEmulation:
    """LEARN per-phase staleness emulation (parallel/learn ``staleness=``,
    DESIGN.md §15): the decentralized half of the ms=0 bitwise contract
    plus the weighted fold-vs-flat equivalence on every exchange phase
    (phase-2 gradients, agreement rounds, model gossip)."""

    def _learn(self, staleness, *, tree_path=True, gar="krum", subset=None,
               non_iid=False, steps=4, f=2):
        from garfield_tpu.parallel import learn

        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, gar, num_nodes=8, f=f, attack="lie",
            staleness=staleness, tree_path=tree_path, subset=subset,
            non_iid=non_iid,
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        state, losses = _run(step_fn, state, x, y, steps)
        return losses, _flat_params(state)

    def test_max_staleness_zero_is_bitwise_synchronous(self):
        l0, f0 = self._learn(None)
        l1, f1 = self._learn({"max_staleness": 0, "decay": 0.5})
        assert l0 == l1
        np.testing.assert_array_equal(f0, f1)

    def test_all_zero_taus_is_bitwise_synchronous(self):
        l0, f0 = self._learn(None, gar="median", f=1)
        l1, f1 = self._learn(
            {"max_staleness": 3, "decay": 0.5, "taus": [0] * 8},
            gar="median", f=1,
        )
        assert l0 == l1
        np.testing.assert_array_equal(f0, f1)

    def test_weighted_fold_matches_flat_per_phase(self):
        # Subsets + agreement rounds + gossip all active: the Gram
        # row-weight composition (folded_tree_aggregate_multi) must
        # train like the flat path that weights rows explicitly.
        st = {"max_staleness": 4, "decay": 0.5,
              "taus": [0, 0, 1, 0, 2, 0, 3, 4]}
        lt, ft = self._learn(st, tree_path=True, subset=7, non_iid=True)
        lf, ff = self._learn(st, tree_path=False, subset=7, non_iid=True)
        assert all(np.isfinite(v) for v in lt + lf)
        np.testing.assert_allclose(ft, ff, rtol=2e-5, atol=1e-6)

    def test_weighted_fold_matches_flat_full_participation(self):
        st = {"max_staleness": 4, "decay": 0.5,
              "taus": [0, 0, 1, 0, 2, 0, 3, 4]}
        lt, ft = self._learn(st, tree_path=True)
        lf, ff = self._learn(st, tree_path=False)
        np.testing.assert_allclose(ft, ff, rtol=2e-5, atol=1e-6)

    def test_seeded_per_phase_draws_deterministic(self):
        a = self._learn({"max_staleness": 3, "decay": 0.7})
        b = self._learn({"max_staleness": 3, "decay": 0.7})
        assert a[0] == b[0]
        np.testing.assert_array_equal(a[1], b[1])
        assert all(np.isfinite(v) for v in a[0])

    def test_bad_config_rejected(self):
        from garfield_tpu.parallel import learn

        module, loss, opt = _pima_setup()
        with pytest.raises(ValueError, match="unknown staleness"):
            learn.make_trainer(
                module, loss, opt, "krum", num_nodes=8, f=2,
                staleness={"max_stale": 3},
            )
        with pytest.raises(ValueError, match="shape"):
            learn.make_trainer(
                module, loss, opt, "krum", num_nodes=8, f=2,
                staleness={"max_staleness": 3, "taus": [0, 1]},
            )


class TestMultiFoldRowWeights:
    def test_multi_observer_weights_match_per_observer_reference(self):
        # folded_tree_aggregate_multi(row_weights=) vs each observer's
        # explicit weighted where-path aggregate over its subset.
        n, f, q = 8, 2, 7
        gar = gars["krum"]
        byz_mask = core.default_byz_mask(n, f)
        tree = _tiny_tree(jax.random.PRNGKey(3), n)
        w = jnp.asarray(rounds.staleness_weights(
            np.array([0, 1, 0, 2, 0, 0, 3, 4]), decay=0.5, max_staleness=4
        ))
        plan = fold.plan_for(gar, "lie", byz_mask, {})
        sels = jnp.stack([
            core.subset_indices(jax.random.PRNGKey(10 + m), n, q)
            for m in range(3)
        ])
        got = fold.folded_tree_aggregate_multi(
            gar, plan, tree, f=f, subset_sels=sels, row_weights=w
        )
        flat = core.flatten_rows(tree)
        poisoned = apply_gradient_attack("lie", flat, byz_mask)
        weighted = poisoned * w[:, None]
        got_rows = core.flatten_rows(got)
        for m in range(3):
            ref = gar.unchecked(weighted[sels[m]], f=f)
            np.testing.assert_allclose(
                np.asarray(got_rows[m]), np.asarray(ref),
                rtol=2e-5, atol=1e-6,
            )


class TestTelemetryV6:
    def test_autoscale_event_validates(self):
        from garfield_tpu.telemetry import exporters

        good = exporters.make_record(
            "event", event="autoscale", who="cluster-ps", step=4,
            action="spawn", rank=3, active=5, rate=12.5, target=20.0,
        )
        exporters.validate_record(good)
        with pytest.raises(ValueError):
            exporters.validate_record(dict(good, action="explode"))
        with pytest.raises(ValueError):
            exporters.validate_record(dict(good, active=-1))
        with pytest.raises(ValueError):
            exporters.validate_record(dict(good, rate="fast"))

    def test_hub_folds_autoscale_and_summary_validates(self):
        from garfield_tpu.telemetry import exporters
        from garfield_tpu.telemetry.hub import MetricsHub

        hub = MetricsHub(num_ranks=4)
        assert hub.autoscale_stats() is None
        assert hub.active_workers() is None
        hub.record_event("autoscale", action="spawn", rank=2, active=3)
        hub.record_event("autoscale", action="spawn", rank=3, active=4)
        hub.record_event("autoscale", action="retire", rank=3, active=3)
        st = hub.autoscale_stats()
        assert st == {"spawns": 2, "retires": 1, "active_workers": 3}
        assert hub.active_workers() == 3
        rec = hub.summary()
        exporters.validate_record(rec)
        assert rec["autoscale"] == st
        # Fixed-membership hubs stay v5-shaped (autoscale None).
        rec2 = MetricsHub(num_ranks=4).summary()
        exporters.validate_record(rec2)
        assert rec2["autoscale"] is None

    def test_prometheus_active_workers_gauge(self):
        from garfield_tpu.telemetry import exporters
        from garfield_tpu.telemetry.hub import MetricsHub

        hub = MetricsHub(num_ranks=4)
        hub.record_event("autoscale", action="spawn", rank=1, active=2)
        text = exporters.prometheus_text(hub)
        assert "garfield_active_workers 2" in text
        assert 'garfield_autoscale_actions_total{action="spawn"} 1' in text
        assert "garfield_active_workers" not in exporters.prometheus_text(
            MetricsHub(num_ranks=4)
        )

    def test_plane_labelled_wire_counters(self):
        from garfield_tpu.telemetry import exporters
        from garfield_tpu.telemetry.hub import MetricsHub

        hub = MetricsHub(num_ranks=2)
        hub.record_event(
            "wire", who="t", step=0, bytes_out=100, bytes_in=50,
            frames_in=2, encode_s=0.0, decode_s=0.0,
            planes={"1": {"bytes_out": 60, "bytes_in": 50},
                    "2": {"bytes_out": 40, "bytes_in": 0}},
        )
        hub.record_event(
            "wire", who="t", step=1, bytes_out=10, bytes_in=0,
            frames_in=0, encode_s=0.0, decode_s=0.0,
            planes={"1": {"bytes_out": 10, "bytes_in": 0}},
        )
        planes = hub.wire_plane_counters()
        assert planes["1"] == {"bytes_out": 70, "bytes_in": 50}
        assert planes["2"] == {"bytes_out": 40, "bytes_in": 0}
        text = exporters.prometheus_text(hub)
        assert ('garfield_wire_plane_bytes_total'
                '{plane="1",direction="out"} 70') in text
        rec = hub.summary()
        from garfield_tpu.telemetry import exporters as _e
        _e.validate_record(rec)
        assert rec["wire_planes"]["2"]["bytes_out"] == 40

    def test_plane_tagged_exchange_wait_and_staleness_validate(self):
        from garfield_tpu.telemetry import exporters

        exporters.validate_record(exporters.make_record(
            "event", event="exchange_wait", step=2, q=3, arrived=3,
            wait_s=0.01, timed_out=False, plane=1,
        ))
        exporters.validate_record(exporters.make_record(
            "event", event="staleness", who="cluster-node-0", step=2,
            plane="model", ranks=[0, 1], staleness=[0, 2],
            weights=[1.0, 0.25], reused=1,
        ))

    def test_exchange_bench_v6_rows_validate(self):
        from garfield_tpu.telemetry import exporters

        exporters.validate_record(exporters.make_record(
            "exchange_bench", n=8, d=10000, wire="f32",
            scenario="scaleup", pre_rate=25.0, spike_rate=6.2,
            recovered_rate=24.0, active_initial=2, active_final=8,
            spawns=6, retires=0, peak_rss_bytes=1,
        ))
        exporters.validate_record(exporters.make_record(
            "exchange_bench", n=3, d=0, wire="f32",
            scenario="learn_ms0", learn_ms0_bitwise=True,
        ))
        with pytest.raises(ValueError):
            exporters.validate_record(exporters.make_record(
                "exchange_bench", n=3, d=0, wire="f32",
                learn_ms0_bitwise="yes",
            ))
        with pytest.raises(ValueError):
            exporters.validate_record(exporters.make_record(
                "exchange_bench", n=8, d=0, wire="f32", spawns=1.5,
            ))

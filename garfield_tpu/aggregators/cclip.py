"""Centered-clipping GAR (beyond-reference addition).

Karimireddy, He & Jaggi, "Learning from History for Byzantine Robust
Optimization" (ICML 2021): iteratively re-center on the clipped mean,

    v_{l+1} = v_l + (1/n) * sum_i  clip(x_i - v_l, tau_l),
    clip(z, tau) = z * min(1, tau / ||z||),

so every input's influence on the aggregate is bounded by ``tau_l / n``
regardless of its magnitude — the property selection rules (krum.py,
bulyan.py) lack, and the reason this rule (paired with worker momentum,
``worker_momentum=`` in the topology builders) survives the "little is
enough" attack that defeats Krum AND Bulyan on the round-3 TTA grid
(BASELINE.md). The reference library ships no clipping rule; this is the
standard modern baseline alongside its Krum/Median/Bulyan generation.

Defaults follow the paper's practical recipe: 3 fixed-point iterations;
``center`` starts at the coordinate-wise median (robust init — the paper
uses the previous aggregate, which the worker-momentum trainers get
implicitly because the momentum stack itself carries history); ``tau``
auto-scales to the median of the current radii ||x_i - v_l|| so the rule
is scale-free (no per-model tuning).

TPU form: the whole update is elementwise + row reductions — XLA fuses
each iteration into ~2 HBM passes over the (n, d) stack; no sort over d,
no gather. A tree-mode twin aggregates the stacked gradient TREE without
materializing the flat (n, d) stack (see aggregators/__init__.py on
``tree_aggregate``): per-leaf medians + a tree-reduced squared-norm
accumulator give the same radii.
"""

import math

import jax
import jax.numpy as jnp

from . import register
from ._common import (
    as_stack, coordinate_median, num_gradients, tree_coordinatewise,
)

ITERS = 3  # fixed-point iterations (paper §4: 1-3 suffice)


def _clip_step(stack, center, tau, eps):
    """One fixed-point iteration on the flat (n, d) stack."""
    dev = stack - center[None, :]
    # A NaN/Inf-poisoned row must not poison the aggregate (the same
    # resilience contract as krum/median's isfinite guards): its non-finite
    # entries become zero deviation, i.e. the row degenerates to a vote for
    # the current center — influence bounded like everyone else's.
    dev = jnp.nan_to_num(dev, nan=0.0, posinf=0.0, neginf=0.0)
    # Radii in f32: bf16 squared-norms overflow/underflow at d ~ 1e7.
    norms = jnp.sqrt(
        jnp.sum(jnp.square(dev.astype(jnp.float32)), axis=1)
    )
    tau_l = jnp.median(norms) if tau is None else jnp.asarray(
        tau, jnp.float32
    )
    scale = jnp.minimum(1.0, tau_l / jnp.maximum(norms, eps))
    return center + jnp.mean(
        dev * scale[:, None].astype(dev.dtype), axis=0
    )


def aggregate(gradients, f=0, key=None, center=None, tau=None,
              iters=ITERS, **kwargs):
    """Centered clipping around a robust center (see module docstring)."""
    stack = as_stack(gradients)
    eps = jnp.asarray(1e-12, jnp.float32)
    if center is None:
        # NaN-last lower median (jnp.median would propagate a poisoned
        # row's NaN into every coordinate of the init).
        center = coordinate_median(stack)
    for _ in range(iters):
        center = _clip_step(stack, center, tau, eps)
    return center


def tree_aggregate(stacked_tree, f=0, key=None, center=None, tau=None,
                   iters=ITERS, **kwargs):
    """Tree-mode twin: same math, no (n, d) flat stack.

    Radii need the GLOBAL row norms, which tree-reduce as the sum of
    per-leaf squared norms; everything else is leafwise.
    """
    leaves, treedef = jax.tree.flatten(stacked_tree)
    n = leaves[0].shape[0]
    eps = jnp.asarray(1e-12, jnp.float32)
    if center is None:
        c_leaves = jax.tree.leaves(
            tree_coordinatewise(coordinate_median, stacked_tree)
        )
    else:
        c_leaves = jax.tree.leaves(center)
    for _ in range(iters):
        devs = [
            jnp.nan_to_num(
                l - c[None], nan=0.0, posinf=0.0, neginf=0.0
            )
            for l, c in zip(leaves, c_leaves)
        ]
        sq = sum(
            jnp.sum(
                jnp.square(d.astype(jnp.float32)).reshape(n, -1), axis=1
            )
            for d in devs
        )
        norms = jnp.sqrt(sq)
        tau_l = jnp.median(norms) if tau is None else jnp.asarray(
            tau, jnp.float32
        )
        scale = jnp.minimum(1.0, tau_l / jnp.maximum(norms, eps))
        c_leaves = [
            c + jnp.mean(
                d * scale.reshape((n,) + (1,) * (d.ndim - 1)).astype(
                    d.dtype
                ),
                axis=0,
            )
            for c, d in zip(c_leaves, devs)
        ]
    return jax.tree.unflatten(treedef, c_leaves)


def check(gradients, f=0, **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 0 or n < 2 * f + 1:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = "
            f"{f!r}, expected 0 <= f <= {(n - 1) // 2}"
        )
    return None


def upper_bound(n, f, d):
    """Paper Thm. III: aggregation error O(sqrt(delta)) at fraction
    delta = f/n of Byzantine inputs (radius-normalized)."""
    return math.sqrt(f / n) if f else 1 / math.sqrt(n)


register("cclip", aggregate, check, upper_bound=upper_bound,
         tree_aggregate=tree_aggregate)

"""Sustained-load soak harness for the control plane (SOAKBENCH_r*).

Hours-equivalent sustained rounds through the federated engine — fully
in-process (no subprocess fleet: every scenario is deterministic and
replayable, and nothing here needs the ``_RUN_LAST`` port discipline) —
under the three stresses a production deployment actually meets, each a
schema-v13 ``soak_bench`` row with the trace plane's round-latency
p50/p95/p99 as the SLO columns (telemetry.hub.phase_stats over one
``soak_round`` span per round):

``steady``
    The baseline: N rounds, nothing injected. Its percentiles are the
    SLO floor the stress scenarios are read against.

``rolling_restart``
    Every ``--kill_every`` rounds the next shard (round-robin) is
    KILLED MID-ROUND at a pinned ingest count and its standby promoted
    (controlplane.promote_standby: span restored bitwise from the
    round-(R-1) checkpoint, suspicion absorbed, epoch bumped), then the
    interrupted round re-runs from scratch. Two claims are measured,
    not asserted: ``kill_cost_rounds`` — the mean extra latency of a
    kill round over the scenario's own clean-round p50, in rounds; the
    handoff contract says ≤ 1 (one re-run) — and ``bitwise_equal`` —
    the final model is bitwise identical to an undisturbed twin run
    (failover costs latency, never trajectory).

``partition``
    Every ``--part_every`` rounds a partitioned sender — one still
    holding the pre-change membership — delivers a frame stamped with a
    stale epoch, plus one pre-epoch (v1) frame, plus a replayed stale
    ``MembershipView``. All three must be attributable rejects
    (``stale_rejects`` counts them; a miss raises) while the round
    completes undisturbed on the fresh cohort's frames: a partition
    costs the partitioned side its traffic, never the healthy side its
    round.

``churn``
    Client churn + elasticity: a staleness policy drops/discounts a
    rotating subset of the cohort every round (tags drive
    ``CohortSampler.cohort_weights`` — stragglers past the cutoff leave
    the round before planning), while a ``ShardAutoscaler`` with an
    unreachable latency target splits the shard group under pressure
    (each split is an epoch bump; ``resizes`` counts them, and refused
    splits are rescinded — the satellite-2 contract, accounting-free).

Environment knobs (CLI flags override): ``GARFIELD_SOAK_ROUNDS``
(rounds per scenario), ``GARFIELD_SOAK_COHORT``, ``GARFIELD_SOAK_D``,
``GARFIELD_SOAK_SHARDS``. The committed artifact runs the defaults
(4 x 60 = 240 sustained rounds); the tier-1 smoke runs ``--rounds 6``
in seconds.

  python -m garfield_tpu.apps.benchmarks.soak_bench --json SOAKBENCH.json
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

from ... import controlplane as cp
from ... import federated as fed
from ...telemetry import hub as tele_hub
from ...telemetry import trace as tele_trace
from ...utils import rounds as rounds_lib
from ...utils import wire


def _env_int(name, default):
    v = os.environ.get(name, "").strip()
    return int(v) if v else default


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


def _rows_for(n, d, round_, seed):
    """The round's cohort gradients — deterministic in (seed, round) and
    independent of everything else, so a killed-and-rerun round replays
    the exact bytes and the bitwise twin-run comparison is meaningful."""
    rng = np.random.default_rng([seed, 31, int(round_)])
    return rng.normal(size=(n, d)).astype(np.float32)


class _Soak:
    """One scenario's engine + bookkeeping (fresh hub and trace stream
    per scenario, so each row's percentiles are its own)."""

    def __init__(self, args, name, *, shards=None, staleness=None,
                 ckpt_dir=None):
        self.args = args
        self.name = name
        self.hub = tele_hub.MetricsHub()
        self._prev_hub = tele_hub.install(self.hub)
        tele_trace.enable(who=f"soak-{name}")
        self.sampler = fed.CohortSampler(
            args.population, args.cohort, seed=args.seed,
            byz_frac=args.byz_frac, staleness=staleness,
        )
        model0 = np.random.default_rng(args.seed).normal(
            size=args.d).astype(np.float32)
        self.engine = fed.FedRoundEngine(
            model0, args.shards if shards is None else shards,
            self.sampler, lr=0.05, telemetry=True,
            checkpoint_dir=ckpt_dir, epoch=1,
        )
        self.walls = []        # clean-round walls
        self.kill_walls = []   # killed-round walls (incl. the re-run)
        self.stale_rejects = 0
        self.failovers = 0
        self.partitions = 0

    def close(self):
        tele_trace.disable()
        tele_hub.install(self._prev_hub)

    def run_round(self, r, *, tags=None, kill_shard=None, record=True):
        """One soak round; with ``kill_shard`` the shard dies mid-round
        at a pinned ingest count and the round re-runs after handoff.
        ``record=False`` runs the round but keeps it out of the span
        stream and the wall lists — round 0 is a compile warmup in
        every scenario (fed_bench's convention), so the committed
        percentiles are steady-state, not jit-compile tails."""
        t0 = time.perf_counter()
        span = (tele_trace.span("soak_round", scenario=self.name, step=r)
                if record else _NULL)
        with span:
            active, _f = self.engine.begin_round(tags)
            rows = _rows_for(active.size, self.args.d, r, self.args.seed)
            if kill_shard is not None:
                # Pinned mid-round death: half the cohort is already in
                # every reducer when shard ``kill_shard`` dies. The
                # handoff restores its span from the round-(r-1)
                # checkpoint and the WHOLE round re-runs (mid-round fold
                # state is deliberately never checkpointed — see
                # controlplane/failover.py).
                self.engine.ingest_rows(rows[: active.size // 2])
                _, rerun = cp.promote_standby(self.engine, kill_shard)
                assert rerun == r, (rerun, r)
                self.failovers += 1
                active2, _ = self.engine.begin_round(tags)
                assert np.array_equal(active, active2)
            self.engine.ingest_rows(rows)
            self.engine.finish_round()
        wall = time.perf_counter() - t0
        if record:
            (self.kill_walls if kill_shard is not None
             else self.walls).append(wall)
        return wall

    def inject_partition(self, r):
        """One partitioned sender's worth of stale traffic: a frame
        stamped one epoch behind, a pre-epoch v1 frame, and a replayed
        stale membership view — three attributable rejects or bust."""
        sh = self.engine.shards[r % self.engine.spec.num_shards]
        row = np.zeros(sh.d_shard, np.float32)
        stale = wire.encode(row, plane=sh.shard, epoch=sh.epoch - 1)
        v1 = wire.encode(row, plane=sh.shard)  # epoch-less pre-epoch frame
        for frame in (stale, v1):
            try:
                sh.push_frame(frame)
            except wire.WireError:
                self.stale_rejects += 1
            else:
                raise AssertionError(
                    f"stale/pre-epoch frame ACCEPTED by shard {sh.shard} "
                    f"at epoch {sh.epoch}"
                )
        # The membership-record replay ban, same partition story: the
        # partitioned side re-publishes the view it still holds.
        cur = cp.MembershipView.for_engine(self.engine)
        directory = cp.MembershipDirectory(cur)
        old = cp.MembershipView(max(0, cur.epoch - 1), cur.d,
                                list(cur.seats))
        try:
            directory.install_frame(old.encode())
        except cp.StaleViewError:
            self.stale_rejects += 1
        else:
            raise AssertionError("stale membership view ACCEPTED")
        self.partitions += 1

    def row(self, check, **extra):
        st = (self.hub.phase_stats() or {}).get("soak_round")
        n_rounds = len(self.walls) + len(self.kill_walls)
        out = {
            "check": check, "rounds": n_rounds,
            "d": self.args.d, "shards": self.engine.spec.num_shards,
            "cohort": self.args.cohort,
            "population": self.args.population,
            "p50_s": round(st["p50_s"], 6), "p95_s": round(st["p95_s"], 6),
            "p99_s": round(st["p99_s"], 6),
            "mean_s": round(st["mean_s"], 6),
            "wall_s": round(sum(self.walls) + sum(self.kill_walls), 4),
            "failovers": self.failovers,
            "partitions": self.partitions,
            "stale_rejects": self.stale_rejects,
            "epoch_final": int(self.engine.epoch),
        }
        out.update(extra)
        return out


# --- scenarios ---------------------------------------------------------------


def steady(args):
    with tempfile.TemporaryDirectory() as td:
        s = _Soak(args, "steady", ckpt_dir=td)
        try:
            for r in range(args.rounds + 1):
                s.run_round(r, record=r > 0)
            return s.row("steady")
        finally:
            s.close()


def rolling_restart(args):
    # The undisturbed twin first: same seeds, same rounds, no kills.
    with tempfile.TemporaryDirectory() as td:
        twin = _Soak(args, "rolling_twin", ckpt_dir=td)
        try:
            for r in range(args.rounds + 1):
                twin.run_round(r, record=r > 0)
            twin_model = twin.engine.model.copy()
        finally:
            twin.close()
    with tempfile.TemporaryDirectory() as td:
        s = _Soak(args, "rolling_restart", ckpt_dir=td)
        try:
            for r in range(args.rounds + 1):
                kill = None
                if r and r % args.kill_every == 0:
                    # Round-robin victim; r >= 1 so a checkpoint exists.
                    kill = (r // args.kill_every - 1) \
                        % s.engine.spec.num_shards
                s.run_round(r, kill_shard=kill, record=r > 0)
            p50 = float(np.percentile(np.asarray(s.walls), 50))
            kill_cost = (
                float(np.mean(np.asarray(s.kill_walls)) / p50) - 1.0
                if s.kill_walls else None
            )
            return s.row(
                "rolling_restart",
                kill_cost_rounds=(
                    None if kill_cost is None else round(kill_cost, 3)
                ),
                bitwise_equal=bool(
                    np.array_equal(s.engine.model, twin_model)
                ),
            )
        finally:
            s.close()


def partition(args):
    with tempfile.TemporaryDirectory() as td:
        s = _Soak(args, "partition", ckpt_dir=td)
        try:
            for r in range(args.rounds + 1):
                if r and r % args.part_every == 0:
                    s.inject_partition(r)
                s.run_round(r, record=r > 0)
            return s.row("partition")
        finally:
            s.close()


def churn(args):
    policy = rounds_lib.StalenessPolicy(max_staleness=2, decay=0.9)
    with tempfile.TemporaryDirectory() as td:
        s = _Soak(args, "churn", staleness=policy, ckpt_dir=td)
        # Unreachable latency target: every full window reads as
        # pressure, so the autoscaler splits as often as its cooldown
        # allows — the sustained-split path, with refusals rescinded
        # once the group hits a cap.
        scaler = cp.ShardAutoscaler(
            s.engine, target_rate=1e9, max_shards=args.churn_max_shards,
            window=4, cooldown=4,
        )
        dropped = 0
        try:
            rng = np.random.default_rng([args.seed, 97])
            for r in range(args.rounds + 1):
                # A rotating straggler subset: ~1/4 of the population is
                # 1-4 rounds behind this round; past the cutoff (2) they
                # are dropped before planning.
                lag_ids = rng.choice(args.population,
                                     args.population // 4, replace=False)
                lag = rng.integers(1, 5, lag_ids.size)
                tags = {int(c): int(r - t)
                        for c, t in zip(lag_ids.tolist(), lag.tolist())}
                wall = s.run_round(r, tags=tags, record=r > 0)
                if r == 0:
                    continue
                dropped += int(s.engine._dropped.size)
                scaler.observe(wall)
            return s.row(
                "churn",
                resizes=scaler.splits + scaler.merges,
                dropped_total=dropped,
            )
        finally:
            s.close()


# --- entry -------------------------------------------------------------------


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Control-plane soak harness (SOAKBENCH_r*)"
    )
    p.add_argument("--rounds", type=int,
                   default=_env_int("GARFIELD_SOAK_ROUNDS", 60),
                   help="Sustained rounds PER scenario.")
    p.add_argument("--cohort", type=int,
                   default=_env_int("GARFIELD_SOAK_COHORT", 64))
    p.add_argument("--population", type=int, default=None,
                   help="Client population (default 4x cohort).")
    p.add_argument("--d", type=int,
                   default=_env_int("GARFIELD_SOAK_D", 2048))
    p.add_argument("--shards", type=int,
                   default=_env_int("GARFIELD_SOAK_SHARDS", 4))
    p.add_argument("--seed", type=int, default=20260807)
    p.add_argument("--byz_frac", type=float, default=0.01)
    p.add_argument("--kill_every", type=int, default=10,
                   help="rolling_restart: kill a shard mid-round every "
                        "K rounds.")
    p.add_argument("--part_every", type=int, default=8,
                   help="partition: inject stale-epoch traffic every K "
                        "rounds.")
    p.add_argument("--churn_max_shards", type=int, default=8,
                   help="churn: autoscaler split ceiling (< the wire "
                        "nibble's 16, so refusals exercise rescind).")
    p.add_argument("--scenarios", nargs="*", type=str,
                   default=["steady", "rolling_restart", "partition",
                            "churn"])
    p.add_argument("--json", type=str, default=None,
                   help="Dump rows to this JSON file + the schema-v13 "
                        "JSONL twin (soak_bench records).")
    args = p.parse_args(argv)
    if args.population is None:
        args.population = 4 * args.cohort

    fns = {"steady": steady, "rolling_restart": rolling_restart,
           "partition": partition, "churn": churn}
    rows = []
    for name in args.scenarios:
        row = fns[name](args)
        rows.append(row)
        extra = ""
        if row.get("kill_cost_rounds") is not None:
            extra += (f" kill_cost={row['kill_cost_rounds']}r "
                      f"bitwise={row['bitwise_equal']}")
        if row.get("resizes") is not None:
            extra += f" resizes={row['resizes']}"
        print(f"{name}: rounds={row['rounds']} "
              f"p50={row['p50_s'] * 1e3:.1f}ms "
              f"p95={row['p95_s'] * 1e3:.1f}ms "
              f"p99={row['p99_s'] * 1e3:.1f}ms "
              f"failovers={row['failovers']} "
              f"stale_rejects={row['stale_rejects']} "
              f"epoch={row['epoch_final']}{extra}", flush=True)

    if args.json:
        with open(args.json, "w") as fp:
            json.dump(rows, fp, indent=1)
        from ...telemetry import exporters

        jsonl_path = os.path.splitext(args.json)[0] + ".jsonl"
        with exporters.JsonlExporter(jsonl_path) as exp:
            for row in rows:
                exp.write(exporters.make_record("soak_bench", **row))
    return rows


if __name__ == "__main__":
    main(sys.argv[1:])

"""Byzantine fault injection as pure, jit'd value transforms.

TPU-native counterpart of the reference's attack components:
  - gradient attacks: ``pytorch_impl/libs/garfieldpp/byzWorker.py`` (attack
    table :62-68, attacks :78-143) and ``tensorflow_impl/libs/attacker.py``
    (:36-127);
  - model attacks:    ``pytorch_impl/libs/garfieldpp/byzServer.py`` (attack
    table :74-78, attacks :86-108).

Design shift (SURVEY §7): the reference injects faults by *subclassing the
node role* and replacing its RPC response. On a TPU mesh every worker slot is
an SPMD shard of one jit'd program, so Byzantine behavior becomes a **value
transformation of the gathered gradient stack**: compute honest gradients for
every slot, then rewrite the rows selected by a boolean ``byz_mask``. This
keeps the whole fault-injection path on-device, inside jit, and differentiably
close to the reference semantics:

  - colluding attacks (lie / empire) need the ``fw`` honest gradients of the
    Byzantine cohort (byzWorker.py:114-117 computes them locally from extra
    batches); here the cohort's honest rows are already in the stack, so the
    collusion statistics (mu, sigma) are masked reductions over those rows;
  - randomized attacks thread an explicit ``jax.random`` key instead of torch
    global RNG, keeping steps reproducible and replay-exact.

Registries mirror the reference dicts, plus the crash fault:
  ``gradient_attacks``: random, reverse, drop, lie, empire, crash
  ``model_attacks``:    random, reverse, drop, crash
(``crash`` zeroes the dead slot's contribution — Garfield_CC's
``mar='crash'`` semantics — used by utils/multihost.FaultSchedule.)
"""

import jax
import jax.numpy as jnp

__all__ = [
    "gradient_attacks",
    "model_attacks",
    "apply_gradient_attack",
    "apply_gradient_attack_tree",
    "apply_model_attack",
    "apply_model_attack_rows",
    "GradientAttackFold",
    "plan_gradient_attack_fold",
    "plan_model_attack_fold",
    "note_attack_fallback",
    "reset_attack_fallback",
]


def _masked_moments(g, mask):
    """Mean and unbiased std over the rows of ``g`` selected by ``mask``.

    Matches ``torch.mean``/``torch.std`` over the stacked cohort gradients
    (byzWorker.py:119-121): std is Bessel-corrected (ddof=1), so a cohort of
    one (fw=1) yields sigma=NaN exactly as torch does — downstream GARs treat
    the resulting non-finite gradient as infinitely distant (krum.py:46-48),
    reproducing the reference's emergent behavior.
    """
    w = mask.astype(g.dtype)[:, None]
    count = jnp.sum(w)
    mu = jnp.sum(w * g, axis=0) / count
    var = jnp.sum(w * (g - mu[None, :]) ** 2, axis=0) / (count - 1.0)
    return mu, jnp.sqrt(var)


# --- gradient attacks (byzWorker.py:78-143) --------------------------------


# Reference attack defaults (byzWorker.py:108-143), shared by the direct
# attack functions AND the folded-plan builder so the two application paths
# can never drift apart.
LIE_Z = 1.035
EMPIRE_EPS = 10.0
REVERSE_FACTOR = -100.0


def random_attack(g, mask, *, key, **_):
    """Replace Byzantine rows with uniform[0,1) noise (byzWorker.py:78-85)."""
    fake = jax.random.uniform(key, g.shape, dtype=g.dtype)
    return jnp.where(mask[:, None], fake, g)


def reverse_attack(g, mask, *, factor=REVERSE_FACTOR, **_):
    """Amplified sign-flip: grad * -100 (byzWorker.py:87-94)."""
    return jnp.where(mask[:, None], g * factor, g)


def drop_attack(g, mask, *, key, p=0.3, **_):
    """Zero out a random 30% of coordinates (byzWorker.py:96-106)."""
    drop = jax.random.uniform(key, g.shape) > (1.0 - p)
    return jnp.where(mask[:, None] & drop, 0.0, g)


def lie_attack(g, mask, *, z=LIE_Z, **_):
    """Little-is-enough: mu + z*sigma over the colluding cohort's honest
    gradients (byzWorker.py:108-125; z_max=1.035 precomputed for n=20, f=8).
    """
    mu, sigma = _masked_moments(g, mask)
    fake = mu + z * sigma
    return jnp.where(mask[:, None], fake[None, :], g)


def empire_attack(g, mask, *, eps=EMPIRE_EPS, **_):
    """Fall-of-empires: -eps * mu over the colluding cohort
    (byzWorker.py:127-143; eps=10, empirical).
    """
    mu, _ = _masked_moments(g, mask)
    fake = -eps * mu
    return jnp.where(mask[:, None], fake[None, :], g)


def crash_attack(g, mask, **_):
    """Crash fault: the dead slots contribute all-zero gradients — what
    Garfield_CC's ``mar='crash'`` mode feeds the aggregation
    (Garfield_CC/trainer.py:97,137); used by the host-level fault
    simulation (utils/multihost.FaultSchedule)."""
    return jnp.where(mask[:, None], 0.0, g)


gradient_attacks = {
    "random": random_attack,
    "reverse": reverse_attack,
    "drop": drop_attack,
    "lie": lie_attack,
    "empire": empire_attack,
    "crash": crash_attack,
}

# Attacks that draw randomness (shared by both dispatchers below).
_NEEDS_KEY = {random_attack, drop_attack}
# Attacks that are coordinate-wise given per-coordinate masked row
# statistics — the invariant that makes per-LEAF application
# (apply_gradient_attack_tree) equivalent to flat application. A new
# attack must be added here explicitly to become tree-capable; otherwise
# the tree dispatcher rejects it instead of silently mis-applying it.
_COORDINATE_WISE = {
    random_attack, reverse_attack, drop_attack, lie_attack, empire_attack,
    crash_attack,
}


def _resolve_gradient_attack(attack, key):
    """Shared dispatch: name -> fn, with the needs-key check."""
    if attack not in gradient_attacks:
        raise ValueError(
            f"unknown attack {attack!r}; available: {sorted(gradient_attacks)}"
        )
    fn = gradient_attacks[attack]
    if fn in _NEEDS_KEY and key is None:
        raise ValueError(f"attack {attack!r} needs a PRNG key")
    return fn


def apply_gradient_attack(attack, gradients, byz_mask, *, key=None, **params):
    """Rewrite the Byzantine rows of a (n, d) gradient stack.

    Args:
      attack: name in ``gradient_attacks`` (byzWorker.py:62-68 table), or
        None/"none" for fault-free passthrough.
      gradients: (n, d) stack — one row per logical worker slot.
      byz_mask: (n,) bool — True rows are Byzantine.
      key: jax PRNG key; required by the randomized attacks (random, drop).
      **params: attack knobs (z, eps, p, factor) with reference defaults.

    Returns the poisoned (n, d) stack; honest rows are returned untouched.
    """
    if attack is None or attack == "none":
        return gradients
    fn = _resolve_gradient_attack(attack, key)
    mask = jnp.asarray(byz_mask, dtype=bool)
    if fn in _NEEDS_KEY:
        return fn(gradients, mask, key=key, **params)
    return fn(gradients, mask, **params)


def apply_gradient_attack_tree(attack, grads_tree, byz_mask, *, key=None,
                               **params):
    """Tree-mode twin of ``apply_gradient_attack``: poison the Byzantine rows
    of a stacked gradient TREE (leading n axis per leaf) leaf by leaf.

    Every gradient attack is coordinate-wise given the cohort row statistics,
    and lie/empire's mu/sigma are per-coordinate masked reductions — so
    applying the (n, d)-stack attack to each leaf reshaped to (n, size) is
    semantically identical to flattening first. Randomized attacks fold the
    key per leaf, so their draws differ from the flat path bitwise but not in
    distribution. Used by the tree-mode GAR fast path
    (parallel/aggregathor.py; PERF.md).
    """
    if attack is None or attack == "none":
        return grads_tree
    fn = _resolve_gradient_attack(attack, key)
    if fn not in _COORDINATE_WISE:
        raise ValueError(
            f"attack {attack!r} is not coordinate-wise; per-leaf application "
            "would use wrong cohort statistics — use the flat path"
        )
    mask = jnp.asarray(byz_mask, dtype=bool)

    leaves, treedef = jax.tree.flatten(grads_tree)
    out = []
    for i, leaf in enumerate(leaves):
        n = leaf.shape[0]
        flat = leaf.reshape(n, -1)
        kw = dict(params)
        if fn in _NEEDS_KEY:
            kw["key"] = jax.random.fold_in(key, i)
        out.append(fn(flat, mask, **kw).reshape(leaf.shape))
    return jax.tree.unflatten(treedef, out)


# --- folded (algebraic) attack application ---------------------------------
#
# The deterministic attacks have row-level structure a Gram-based GAR can
# exploit without ever writing the poisoned rows:
#   - lie / empire publish ONE shared fake vector from all Byzantine slots
#     (byzWorker.py:108-143: every colluding worker submits mu + z*sigma /
#     -eps*mu) -> append the fake as ONE extra stack row and remap;
#   - reverse scales each Byzantine row by a constant (byzWorker.py:87-94)
#     -> scale Gram rows/cols and the selection weights;
#   - crash zeroes the row -> scale 0.
# The poisoned Gram is then a static row remap + outer scaling of the raw
# (n+k, n+k) Gram, and the GAR's weighted row sum is one matvec over the
# extended stack. The raw Gram keeps fusing into the backward epilogue
# exactly like the fault-free step — the whole-tree `where` rewrite, which
# forces the stacked gradient tree to rematerialize, never happens. Measured
# 1.16x on the north-star krum+lie step (PERF.md round 4); the randomized
# attacks (random, drop) have no such structure and keep the `where` path.


class GradientAttackFold:
    """Static plan for applying a gradient attack inside a Gram-based GAR.

    Poisoned row i == ``row_scale[i] * extended_stack[row_map[i]]`` where
    ``extended_stack`` is the raw (n, ...) stack with ``num_extra`` (0 or 1)
    shared fake rows appended. All fields are static (numpy) except
    ``build_extra``, which builds the fake row tree from the stacked raw
    gradients at trace time. Consumed by ``parallel.fold``.
    """

    def __init__(self, row_map, row_scale, build_extra=None):
        import numpy as np

        self.row_map = np.asarray(row_map, dtype=np.int32)
        self.row_scale = np.asarray(row_scale, dtype=np.float32)
        self.build_extra = build_extra
        self.num_extra = 1 if build_extra is not None else 0


def _shared_fake_builder(byz_idx, count, transform):
    """Per-leaf shared fake row from the Byzantine cohort's honest rows.

    Moments are accumulated in f32 and agree with ``_masked_moments`` to
    f32 rounding (the masked sum reduces n terms, this one the fw gathered
    terms — same values, possibly different association, so last-ulp
    differences are possible); for bf16 pipelines the f32 accumulation is
    *better* than the where-path's leaf-dtype sums and the two paths agree
    only to bf16 rounding.
    """

    def build_extra(stacked_tree):
        def one(leaf):
            s = leaf[byz_idx].astype(jnp.float32)
            mu = jnp.sum(s, axis=0) / count
            var = jnp.sum((s - mu[None]) ** 2, axis=0) / (count - 1.0)
            return transform(mu, jnp.sqrt(var)).astype(leaf.dtype)

        return jax.tree.map(one, stacked_tree)

    return build_extra


# One-time attack_fallback telemetry guard: the randomized attacks
# (random, drop) have no folded form and silently keep the where-path —
# benches comparing fold-path wins must see that attributed, not infer it
# (docs/TELEMETRY.md v7). One event per (attack, why) per process.
_FALLBACK_EMITTED = set()


def note_attack_fallback(attack, *, path, why):
    """Emit the one-time ``attack_fallback`` telemetry event: ``attack``
    is taking ``path`` (e.g. "where") instead of the folded fast path
    because ``why``. No-op when no MetricsHub is installed, and at most
    once per (attack, why) per process so per-step plan rebuilds cannot
    flood the stream."""
    key = (str(attack), str(why))
    if key in _FALLBACK_EMITTED:
        return
    _FALLBACK_EMITTED.add(key)
    from ..telemetry import hub as _hub

    _hub.emit_event(
        "attack_fallback", attack=str(attack), path=str(path), why=str(why)
    )


def reset_attack_fallback():
    """Test hook: forget which fallbacks were already reported."""
    _FALLBACK_EMITTED.clear()


def plan_gradient_attack_fold(attack, byz_mask, *, z=LIE_Z, eps=EMPIRE_EPS,
                              factor=REVERSE_FACTOR, **_):
    """Return the ``GradientAttackFold`` for ``attack``, or None when the
    attack has no folded form (randomized rows, or no Byzantine slots, or
    ``GARFIELD_NO_FOLD`` set to any non-empty value — the A/B escape
    hatch, same any-value convention as GARFIELD_NO_PALLAS)."""
    import os

    import numpy as np

    if attack is None or attack == "none" or os.environ.get("GARFIELD_NO_FOLD"):
        return None
    if attack in ("random", "drop"):
        # The silent half of the fold dispatch, made loud (schema v7):
        # these rows are freshly random every step, so there is no static
        # remap+scale — the topology keeps the where-path.
        note_attack_fallback(
            attack, path="where", why="randomized attack has no folded form"
        )
        return None
    mask = np.asarray(byz_mask, dtype=bool)
    n = mask.size
    byz_idx = np.flatnonzero(mask)
    if byz_idx.size == 0:
        return None
    identity = np.arange(n)
    ones = np.ones(n)
    if attack == "lie":
        return GradientAttackFold(
            np.where(mask, n, identity), ones,
            _shared_fake_builder(
                byz_idx, float(byz_idx.size),
                lambda mu, sigma: mu + z * sigma,
            ),
        )
    if attack == "empire":
        return GradientAttackFold(
            np.where(mask, n, identity), ones,
            _shared_fake_builder(
                byz_idx, float(byz_idx.size), lambda mu, sigma: -eps * mu
            ),
        )
    if attack == "reverse":
        return GradientAttackFold(identity, np.where(mask, factor, 1.0))
    if attack == "crash":
        return GradientAttackFold(identity, np.where(mask, 0.0, 1.0))
    return None


def plan_model_attack_fold(attack, byz_mask, *, factor=-100.0, **_):
    """Folded plan for the DETERMINISTIC model attacks, or None.

    byzServer's reverse (model * -100, :93-98) and the crash fault are pure
    per-row scalings with no cohort statistics and no shared fake row, so
    their ``GradientAttackFold`` is an identity row map with scales — the
    Gram-remap machinery of ``parallel.fold`` applies to model-plane
    exchanges (LEARN gossip, ByzSGD gather step) unchanged. Randomized
    model attacks (random, drop) keep the where-path. Same
    ``GARFIELD_NO_FOLD`` escape hatch as the gradient plans."""
    import os

    import numpy as np

    if attack is None or attack == "none" or os.environ.get("GARFIELD_NO_FOLD"):
        return None
    mask = np.asarray(byz_mask, dtype=bool)
    if not mask.any():
        return None
    identity = np.arange(mask.size)
    if attack == "reverse":
        return GradientAttackFold(identity, np.where(mask, factor, 1.0))
    if attack == "crash":
        return GradientAttackFold(identity, np.where(mask, 0.0, 1.0))
    return None


# --- model attacks (byzServer.py:86-108) -----------------------------------


def model_random_attack(m, *, key, **_):
    """Random model of the same shape (byzServer.py:86-91)."""
    return jax.random.uniform(key, m.shape, dtype=m.dtype)


def model_reverse_attack(m, *, factor=-100.0, **_):
    """model * -100 (byzServer.py:93-98)."""
    return m * factor


def model_drop_attack(m, *, key, p=0.3, **_):
    """Zero a random 30% of model coordinates (byzServer.py:100-108)."""
    drop = jax.random.uniform(key, m.shape) > (1.0 - p)
    return jnp.where(drop, 0.0, m)


def model_crash_attack(m, **_):
    """Crash fault: a dead node serves an all-zero model (the model-space
    twin of ``crash_attack``; a crashed host cannot gossip its state)."""
    return jnp.zeros_like(m)


model_attacks = {
    "random": model_random_attack,
    "reverse": model_reverse_attack,
    "drop": model_drop_attack,
    "crash": model_crash_attack,
}

# --- model-plane collusion attacks (DESIGN.md §17) --------------------------
#
# The PAPERS.md attacks (lie = mu + z*sigma, empire = -eps*mu) are
# gradient-plane INSTANCES of a strategy that works at any aggregation
# point: hide inside the spread of whatever rows the rule aggregates. On
# the model planes (ByzSGD's gather step, LEARN's gossip) the "cohort" a
# Byzantine publisher hides inside is the WHOLE gathered replica stack —
# unlike the gradient plane it need not simulate colluders, every row it
# wants statistics over is handed to it by the protocol itself. These are
# STACK-level attacks (they need the peers' rows), so they live beside
# ``apply_model_attack_rows`` and are dispatched by it; the single-vector
# ``apply_model_attack`` path (a lone Byzantine PS poisoning only its own
# publish, no peer visibility at poison time) is served host-side by
# apps/cluster.py keeping the previous round's gathered stack.


def model_lie_attack_rows(models, mask, *, z=LIE_Z, **_):
    """Model-plane little-is-enough: every Byzantine row publishes
    ``mu + z*sigma`` with mu/sigma the coordinate-wise moments of ALL
    gathered models (Bessel std, like the gradient twin)."""
    mu = jnp.mean(models, axis=0)
    n = models.shape[0]
    var = jnp.sum((models - mu[None]) ** 2, axis=0) / (n - 1.0)
    fake = mu + z * jnp.sqrt(var)
    return jnp.where(mask[:, None], fake[None, :], models)


def model_empire_attack_rows(models, mask, *, eps=EMPIRE_EPS, **_):
    """Model-plane fall-of-empires: ``-eps * mu`` over the gathered
    stack from every Byzantine row."""
    fake = -eps * jnp.mean(models, axis=0)
    return jnp.where(mask[:, None], fake[None, :], models)


# Stack-form model attacks (need the gathered rows; the single-vector
# dispatch below rejects them — a row-less call site has no cohort).
model_collusion_attacks = {
    "lie": model_lie_attack_rows,
    "empire": model_empire_attack_rows,
}


def apply_model_attack(attack, model_vec, *, key=None, **params):
    """Poison a flattened model vector a Byzantine PS would serve
    (byzServer.py:80-84 dispatch). ``attack`` None/"none" is passthrough.
    """
    if attack is None or attack == "none":
        return model_vec
    if attack in model_collusion_attacks:
        raise ValueError(
            f"model attack {attack!r} is a collusion statistic over the "
            "gathered stack; use apply_model_attack_rows (or the host "
            "roles' last-gather path)"
        )
    if attack not in model_attacks:
        raise ValueError(
            f"unknown model attack {attack!r}; available: {sorted(model_attacks)}"
        )
    fn = model_attacks[attack]
    if fn in (model_random_attack, model_drop_attack):
        if key is None:
            raise ValueError(f"model attack {attack!r} needs a PRNG key")
        return fn(model_vec, key=key, **params)
    return fn(model_vec, **params)


def apply_model_attack_rows(attack, models, byz_mask, *, key=None, **params):
    """Poison the Byzantine ROWS of a gathered (n, d) model stack.

    The stack form of ``apply_model_attack`` shared by the model planes
    (LEARN gossip, ByzSGD gather step): row i is attacked with the key
    folded by its GLOBAL row index, so every shard derives identical
    draws for the randomized attacks. The collusion statistics
    (lie/empire, DESIGN.md §17) are stack-only and dispatch here too.
    None/"none" is passthrough.
    """
    if attack is None or attack == "none":
        return models
    if attack in model_collusion_attacks:
        return model_collusion_attacks[attack](
            models, jnp.asarray(byz_mask, bool), **params
        )
    if attack not in model_attacks:
        raise ValueError(
            f"unknown model attack {attack!r}; available: {sorted(model_attacks)}"
        )
    fn = model_attacks[attack]
    n = models.shape[0]
    if fn in (model_random_attack, model_drop_attack):
        if key is None:
            raise ValueError(f"model attack {attack!r} needs a PRNG key")
        poisoned = jax.vmap(
            lambda i, m: fn(m, key=jax.random.fold_in(key, i), **params)
        )(jnp.arange(n), models)
    else:
        poisoned = jax.vmap(lambda m: fn(m, **params))(models)
    return jnp.where(jnp.asarray(byz_mask, bool)[:, None], poisoned, models)

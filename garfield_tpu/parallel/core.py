"""Functional training core shared by all topologies.

Counterpart of the reference's node-role machinery, re-designed for SPMD:

  - ``make_worker_fns``  — the Worker role (pytorch_impl/libs/garfieldpp/
    worker.py:50-96): forward + backward on a minibatch, gradients flattened
    into one 1-D vector (worker.py:93-94). Here it is a pure function
    ``(params, model_state, x, y, rng) -> (grads_tree, aux)`` built from a
    flax module; topologies vmap it over logical worker slots and shard the
    vmapped axis over the mesh.
  - ``TrainState``       — the Server role's mutable state (server.py:56-99:
    model, optimizer, iteration counter) as an immutable pytree; ``update``
    applies a flat aggregated gradient exactly like ``Server.update_model``
    (server.py:277-287 slices the flat vector back into per-param grads).
  - ``flatten_rows`` / ``subset_indices`` / ``mean_model_state`` — stack
    handling, wait-n-f emulation (server.py:118-119,134-155: proceed with the
    fastest n-f responses; bulk-synchronous XLA has no stragglers, so the
    sampled subset models *which* n-f arrived first), and cross-worker
    BatchNorm-statistics averaging (a deliberate improvement: the reference
    silently drops worker BN-buffer updates because only gradients travel
    over RPC).
"""

import flax.struct
import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

__all__ = [
    "TrainState",
    "make_worker_fns",
    "make_chunked_step",
    "flatten_rows",
    "unflatten_like",
    "subset_indices",
    "mean_model_state",
    "default_byz_mask",
]


@flax.struct.dataclass
class TrainState:
    """Replicated (or ps/node-stacked) training state.

    ``model_state`` holds flax mutable collections (``batch_stats``);
    ``rng`` is the base PRNG key; per-step keys are derived by fold_in so a
    run is replayable from (seed, step) alone — the reference relies on
    ``torch.manual_seed(1234)`` + call order (Aggregathor/trainer.py:210-212).
    """

    step: jax.Array
    params: dict
    model_state: dict
    opt_state: object
    rng: jax.Array
    # Per-worker momentum stack (leading slot axis per leaf) when the
    # topology runs worker momentum (Karimireddy et al. 2021, the companion
    # of the cclip GAR); None otherwise. Sharded like the topology's node
    # state: aggregathor passes the whole state at P() (replicated — the
    # full num_workers x model stack costs HBM on EVERY device; budget
    # accordingly on large models), LEARN shards the leading axis at
    # P(axis) with params/opt_state.
    worker_mom: object = None
    # Carried aggregation state for stateful-center rules (cclip): the
    # previous step's aggregate tree, used as the next step's center v_0 —
    # the paper's actual recipe (Karimireddy et al. 2021 set v_0 to the
    # previous aggregate; a per-step robust median init costs a full
    # coordinate-median pass, ~4 ms at ResNet-18 scale, PERF.md r5).
    # None for stateless rules.
    gar_state: object = None
    # Adaptive-adversary controller state (attacks/adaptive.py, DESIGN.md
    # §16): the bisection bracket {lo, hi} over the attack magnitude,
    # updated each step from the rule's selection feedback. Riding in the
    # TrainState means the lax.scan chunk carry threads it for free
    # (core.make_chunked_step). None for oblivious attacks.
    attack_state: object = None
    # Closed-loop defense state (aggregators/defense.py): the carried
    # per-rank exclusion EMA {obs, exc} the in-graph suspicion weights
    # derive from — the on-mesh emulation of the host MetricsHub's
    # decayed suspicion. None when the defense is off.
    defense_state: object = None
    # Wire-compression emulation state (parallel/compress.py, DESIGN.md
    # §20): the per-worker error-feedback residual rows
    # {"resid": (n_workers, d) f32} when a lossy scheme runs with EF.
    # Riding in the TrainState is what makes chunked and mid-run-resumed
    # compressed trainings bitwise (scan carry + checkpoint tree). None
    # when compression is off or EF-free.
    wire_state: object = None


def make_worker_fns(module, loss_fn):
    """Build the pure Worker functions for a flax module.

    Returns ``(init_fn, grad_fn, eval_fn)``:
      - ``init_fn(key, example_x) -> (params, model_state)``
      - ``grad_fn(params, model_state, x, y, rng) -> (grads, (loss, new_ms))``
        where ``grads`` is a pytree shaped like params (flattening is the
        topology's job — per-layer GARs need the tree);
      - ``eval_fn(params, model_state, x) -> logits`` (train=False), used by
        ``compute_accuracy`` (server.py:235-254).
    """

    def init_fn(key, example_x):
        pkey, dkey = jax.random.split(key)
        variables = module.init(
            {"params": pkey, "dropout": dkey}, example_x, train=False
        )
        variables = dict(variables)
        params = variables.pop("params")
        return params, variables

    def loss_of(params, model_state, x, y, rng):
        out = module.apply(
            {"params": params, **model_state},
            x,
            train=True,
            mutable=list(model_state.keys()),
            rngs={"dropout": rng},
        )
        logits, new_ms = out
        return loss_fn(logits, y), new_ms

    def grad_fn(params, model_state, x, y, rng):
        (loss, new_ms), grads = jax.value_and_grad(loss_of, has_aux=True)(
            params, model_state, x, y, rng
        )
        return grads, (loss, new_ms)

    def eval_fn(params, model_state, x):
        return module.apply({"params": params, **model_state}, x, train=False)

    return init_fn, grad_fn, eval_fn


def flatten_rows(stacked_tree):
    """(n, ...) stacked gradient pytree -> (n, d) matrix of flat rows.

    Equivalent of the reference's per-worker ``torch.cat([g.view(-1)])``
    (worker.py:93-94) applied to every row of the gathered stack.
    """
    return jax.vmap(lambda row: ravel_pytree(row)[0])(stacked_tree)


def unflatten_like(template_tree, flat_vec):
    """Inverse of ``ravel_pytree``: slice a flat vector into a params-shaped
    pytree (Server.update_model's slicing loop, server.py:277-287)."""
    _, unravel = ravel_pytree(template_tree)
    return unravel(flat_vec)


def leaf_segments(tree):
    """Static (start, end) column spans of each leaf in ravel order.

    ``ravel_pytree`` concatenates leaves in ``jax.tree.leaves`` order, so a
    flat (n, d) stack can be sliced back into per-parameter blocks — the
    basis for per-layer GAR granularity (Garfield_CC/trainer.py:55-204 loops
    over ``model.parameters()``).
    """
    import numpy as np

    spans, start = [], 0
    for leaf in jax.tree.leaves(tree):
        size = int(np.prod(jnp.shape(leaf))) if jnp.ndim(leaf) else 1
        spans.append((start, start + size))
        start += size
    return spans


def segmented_aggregate(agg_fn, stack, segments):
    """Apply ``agg_fn(segment, i)`` independently to each column segment of
    an (n, d) stack and concatenate — per-layer aggregation over a flat
    stack. The segment index lets randomized rules fold a distinct key per
    layer."""
    return jnp.concatenate(
        [agg_fn(stack[:, s:e], i) for i, (s, e) in enumerate(segments)],
        axis=0,
    )


# Above this many logical slots per shard, per-slot gradients fall back to
# vmap: the unroll duplicates the model's fwd+bwd graph per slot and compile
# time grows linearly. (On a real multi-chip mesh per-shard slot counts are
# 1-2 and the unroll is always used.)
#
# Measured end-to-end at n=64 on the chip (PERF.md r4: ResNet-18, b=25,
# krum+lie): vmap fallback 127 ms/step (12.6k img/s, compile 6 s) vs forced
# unroll 103 ms/step (15.6k img/s, compile 136 s) — the relayout tax at
# n=64 is ~19%, far below the 36-63% measured at n=8, and the unroll
# amortizes its compile in ~5.4k steps. For reference-scale runs (100k
# iters) raising the cap is a win: override with GARFIELD_UNROLL_MAX_SLOTS.
import os as _os

UNROLL_MAX_SLOTS = int(_os.environ.get("GARFIELD_UNROLL_MAX_SLOTS", 16))

# Steps at which the unroll's compile-time premium amortizes against its
# steady-state win over vmap. Both sides scale ~linearly in slots (compile
# ~2 s/slot premium, win ~0.38 ms/step/slot at ResNet-18 scale, PERF.md
# r4), so the breakeven is roughly slot-count independent.
UNROLL_AMORTIZE_STEPS = int(
    _os.environ.get("GARFIELD_UNROLL_AMORTIZE_STEPS", 6000)
)


def step_donation():
    """``donate_argnums`` for the topology step functions: ``(0,)`` (donate
    the TrainState) on real device backends, ``()`` on XLA:CPU.

    This jaxlib's CPU runtime executes donation unsoundly when host views
    of the donated buffers are still alive — and on CPU both
    ``np.asarray(jax_array)`` and ``jax.device_put(np_array)`` are
    zero-copy, so checkpoint save/restore and the eval readback all
    create such views. Observed in the warm-compile-cache app suite as
    corrupted TrainState leaves (a resumed run's ``state.step`` reading
    an eval count) and native SIGSEGV/SIGABRT mid-run. Donation is only
    a memory-reuse optimization, so it is dropped on CPU; the device
    backends keep it. ``GARFIELD_DONATE=0|1`` forces either choice.
    """
    forced = _os.environ.get("GARFIELD_DONATE", "").strip()
    if forced in ("0", "1"):
        return (0,) if forced == "1" else ()
    return () if jax.default_backend() == "cpu" else (0,)


def chunk_unroll(chunk_steps):
    """Scan unroll factor for ``make_chunked_step``: the FULL chunk on
    XLA:CPU (the rolled while loop pins conv layouts at the loop boundary
    and per-iteration relayouts invert the chunk win — measured 2.6x
    WORSE than per-step on convnet/mnist, PERF.md r9), the rolled loop
    (factor 1) on device backends. ``GARFIELD_CHUNK_UNROLL=<factor>``
    forces a factor: 1 = rolled, >= chunk_steps = fully unrolled,
    in between = partial."""
    forced = _os.environ.get("GARFIELD_CHUNK_UNROLL", "").strip()
    if forced:
        return max(1, int(forced))
    return chunk_steps if jax.default_backend() == "cpu" else 1


def make_chunked_step(step_fn, chunk_steps, num_batches, unroll=None):
    """Fuse ``chunk_steps`` training steps into ONE jitted dispatch.

    The per-step driver loop (apps/common.py) pays one Python dispatch and
    one host round-trip per training step, so XLA can never overlap step
    i's optimizer/GAR tail with step i+1's forward — the schedule-level
    gap every perf round since r2 has pointed at (PERF.md "Known
    frontier"). This wraps any topology's step in a ``jax.lax.scan`` over
    K on-device batch indices: K-1 of every K host dispatches disappear
    and the whole chunk is one XLA program with cross-step overlap.

    ``step_fn`` is a topology step from ``make_trainer`` (its un-jitted
    ``shard_map`` body is consumed via the ``inner`` attribute the
    topologies attach, so the scan body is not re-wrapped in a nested
    jit). Returns

        ``chunked(state, xs, ys, i0) -> (state, metrics)``

    where ``xs``/``ys`` are the FULL device-resident batch stacks with a
    ``num_batches`` axis at position 1 (the app loop's ``(slots, B, ...)``
    layout), ``i0`` is the global step index of the chunk's first step
    (traced, so one compiled program serves every chunk of this length),
    and each metrics leaf gains a leading ``chunk_steps`` axis — K losses
    (and K fixed-shape telemetry ``TapBundle``s, when taps are on) per
    dispatch, which the host loop fans back out into per-step records.

    Trajectory semantics are EXACTLY the per-step loop's:

      - the batch index is computed on device, ``b = (i0 + k) %
        num_batches`` — the same ``i % num_batches`` the host loop uses;
      - the ``TrainState`` is the scan carry (params, optimizer state,
        ``gar_state`` stateful-rule centers, ``worker_mom``, step
        counter), so stateful rules carry across scan iterations exactly
        as across dispatches;
      - per-step RNG needs no extra plumbing: every topology derives its
        attack/subset/dropout keys by ``fold_in(state.rng, state.step)``
        and ``step`` advances in the carry, so scan iteration k uses the
        bitwise-same keys the per-step loop used at step ``i0 + k``
        (asserted bitwise in tests/test_chunked.py).

    Donation follows ``step_donation()``: the carried TrainState is
    donated on real device backends, while the batch stacks (args 1-2)
    are never donated — they are reused by every chunk.

    ``unroll`` is the scan unroll factor (None = backend-aware default,
    see ``chunk_unroll``): XLA:CPU pins operand layouts at the while-loop
    boundary, so conv bodies inside a ROLLED scan pay per-iteration
    relayouts that measurably invert the chunk win (convnet/mnist
    measured 31 -> 80 ms/step rolled, 31 -> 24.5 ms/step fully unrolled,
    PERF.md r9); full unroll restores layout freedom and the cross-step
    overlap at a ~K-times compile cost — the same compile-vs-steady-state
    trade the slot unroll already navigates. Device backends keep the
    rolled loop (compile time at ResNet scale is precious; the chip A/B
    is the next live-backend task).
    """
    if chunk_steps < 1:
        raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
    if num_batches < 1:
        raise ValueError(f"num_batches must be >= 1, got {num_batches}")
    inner = getattr(step_fn, "inner", step_fn)
    out_shardings = getattr(step_fn, "out_shardings", None)
    if unroll is None:
        unroll = chunk_unroll(chunk_steps)
    unroll = max(1, min(int(unroll), chunk_steps))

    def scan_steps(state, xs, ys, i0):
        def body(st, k):
            b = jax.lax.rem(i0 + k, jnp.int32(num_batches))
            x = jax.lax.dynamic_index_in_dim(xs, b, 1, keepdims=False)
            y = jax.lax.dynamic_index_in_dim(ys, b, 1, keepdims=False)
            return inner(st, x, y)

        return jax.lax.scan(
            body, state, jnp.arange(chunk_steps, dtype=jnp.int32),
            unroll=unroll,
        )

    import functools

    jit_kwargs = {}
    if out_shardings is not None:
        jit_kwargs["out_shardings"] = out_shardings
    chunked = functools.partial(jax.jit, donate_argnums=step_donation(),
                                **jit_kwargs)(scan_steps)
    chunked.mesh = getattr(step_fn, "mesh", None)
    chunked.batch_sharding = getattr(step_fn, "batch_sharding", None)
    chunked.chunk_steps = chunk_steps
    return chunked


def slot_path_decision(slots, num_iter=None, fused_available=False):
    """Pick the per-slot gradient formulation (VERDICT r4 #8).

    Returns ``(path, reason)`` with path in {"fused", "unroll", "vmap"}:
    the slot-fused twin when the model has one (fastest at every n and the
    cheapest compile); otherwise the unroll below UNROLL_MAX_SLOTS; above
    the cap, a RUN-LENGTH-aware choice — the unroll's ~2 s/slot compile
    premium amortizes in ~UNROLL_AMORTIZE_STEPS steps against its ~24%
    steady-state win (measured n=64, PERF.md r4), so reference-scale runs
    (100k iters, Aggregathor/run_exp.sh:39-40) take the unroll
    automatically instead of silently losing it to a static cap.
    """
    if fused_available:
        return "fused", "slot-fused twin (fused fwd/dx, per-slot dw)"
    if slots <= UNROLL_MAX_SLOTS:
        return "unroll", f"{slots} slots <= cap {UNROLL_MAX_SLOTS}"
    if num_iter is not None and num_iter >= UNROLL_AMORTIZE_STEPS:
        return "unroll", (
            f"{num_iter} steps amortize the unroll compile premium "
            f"(breakeven ~{UNROLL_AMORTIZE_STEPS})"
        )
    return "vmap", (
        f"{slots} slots > cap {UNROLL_MAX_SLOTS} and "
        + (f"{num_iter} steps < breakeven {UNROLL_AMORTIZE_STEPS}"
           if num_iter is not None else "run length unknown")
    )


def resolve_slot_grad_fn(module, loss_fn, slots, shared_params=True):
    """Resolve the slot-fused gradient twin for a module, or None.

    The single front-end every topology consults (directly or via
    ``select_slot_path``): it checks the fold geometry (``slots > 1`` —
    one slot per shard has nothing to fuse), the escape hatch
    (``GARFIELD_NO_SLOTFUSED``), the parameter-sharing precondition, and
    the ``models.slotfused.SLOTFUSED_MODELS`` registry — so a model family
    added to the registry reaches aggregathor, LEARN and ByzSGD with no
    per-topology change.

    ``shared_params=False`` declares that the slots carry DISTINCT
    parameter trees (LEARN's per-node models): the twin's fused primal
    runs the flat batch against ONE shared kernel (``slot_conv`` uses
    ``w_st[0]``), so it is structurally inapplicable there and this
    returns None. If a stacked-params twin formulation ever lands, only
    this gate changes.
    """
    if slots <= 1 or not shared_params:
        return None
    if _os.environ.get("GARFIELD_NO_SLOTFUSED"):
        return None
    from ..models import slotfused

    return slotfused.build_slot_grad_fn(module, loss_fn)


def select_slot_path(module, loss_fn, slots, num_iter=None, log_tag=None,
                     shared_params=True):
    """Shared topology-builder front-end to ``slot_path_decision``.

    Resolves the slot-fused twin via ``resolve_slot_grad_fn``, logs the
    decision, and returns ``(fused_fn, force_unroll)`` ready to pass to
    ``per_slot_grads``.
    """
    fused_fn = resolve_slot_grad_fn(module, loss_fn, slots, shared_params)
    path, why = slot_path_decision(slots, num_iter, fused_fn is not None)
    if slots > 1:
        from ..utils import tools

        tools.info(
            f"[{log_tag or 'trainer'}] per-slot gradients: {path} ({why})"
        )
    return fused_fn, path == "unroll"


def per_slot_grads(grad_fn, params, ms, x, y, keys, fused_fn=None,
                   force_unroll=False):
    """Per-slot gradients over a leading logical-slot axis, vmap-compatible.

    Returns exactly what ``jax.vmap(grad_fn, in_axes=(None, None, 0, 0, 0))``
    returns — ``(grads, (loss, ms))`` trees with a leading slot axis —
    computed by the fastest available formulation:

      1. ``fused_fn`` (``models.slotfused.build_slot_grad_fn``) when the
         topology supplies one: the model runs ONCE on the flat (n*b)
         batch (fused forward + fused dx), and only the parameter-cotangent
         contractions are slot-resolved — the r5 hybrid (PERF.md).
      2. A Python unroll over the slots when their count is small: keeps
         every subgraph 4-D and batch-minor; XLA schedules the independent
         per-slot fwd+bwd graphs without relayouts (r2; 12.9 -> 9.1 ms for
         the 8-worker ResNet-18 stack).
      3. vmap above UNROLL_MAX_SLOTS — compile time of the unroll grows
         linearly with slots; the 5-D relayout tax shrinks with n
         (~19% at n=64, PERF.md r4).

    lax.scan was measured 2.6x worse (sequential small batches), the
    patches-einsum custom VJP 3-6x worse, and raveling each slot inside
    the unroll 12% worse end-to-end (PERF.md).
    """
    n = x.shape[0]
    if fused_fn is not None:
        return fused_fn(params, ms, x, y, keys)
    if n > UNROLL_MAX_SLOTS and not force_unroll:
        return jax.vmap(grad_fn, in_axes=(None, None, 0, 0, 0))(
            params, ms, x, y, keys
        )
    outs = [grad_fn(params, ms, x[k], y[k], keys[k]) for k in range(n)]
    return jax.tree.map(lambda *ls: jnp.stack(ls), *outs)


def cast_leaves(tree, dtype):
    """Cast every leaf to ``dtype`` (no-op when dtype is None).

    The narrow-aggregation-pipeline cast-IN: applied to per-slot gradients
    at the backward epilogue so XLA fuses it into the backward's output
    writes (``gar_dtype`` in the topology builders).
    """
    if dtype is None:
        return tree
    return jax.tree.map(lambda l: l.astype(dtype), tree)


def cast_like(tree, ref_tree):
    """Cast every leaf of ``tree`` to the dtype of the matching ``ref_tree``
    leaf — the cast-BACK at the optimizer boundary (momentum/weight-decay
    state stays full width)."""
    return jax.tree.map(lambda a, p: a.astype(p.dtype), tree, ref_tree)


def worker_mom_init(params, num_slots, dtype=None):
    """Zeros momentum stack for ``worker_momentum`` topologies: one leading
    slot axis per leaf, at the aggregation pipeline's width (``gar_dtype``
    when narrowed — momentum is what workers exchange)."""
    return jax.tree.map(
        lambda p: jnp.zeros((num_slots,) + p.shape, dtype or p.dtype), params
    )


def worker_mom_update(beta, mom_tree, grads_tree):
    """EMA ``(1-beta) g + beta m`` per leaf, accumulated in f32 and cast
    back to the pipeline dtype (bf16 leaves would otherwise round the
    small ``(1-beta) g`` increments away)."""
    b = jnp.asarray(beta, jnp.float32)
    return jax.tree.map(
        lambda m, g: ((1.0 - b) * g.astype(jnp.float32)
                      + b * m.astype(jnp.float32)).astype(g.dtype),
        mom_tree, grads_tree,
    )


def subset_indices(key, n, q):
    """Uniformly sample q of n row indices (static shape (q,)).

    Emulates the wait-fastest-n-f path (server.py:134-155): the reference
    takes whichever q = n - f responses land first; arrival order on a real
    async cluster is effectively random, so a seeded uniform sample is the
    faithful bulk-synchronous stand-in (SURVEY §2.3 asynchrony row).
    """
    return jax.random.permutation(key, n)[:q]


def mean_model_state(stacked_ms, axis_name=None):
    """Average per-worker mutable collections (BatchNorm running stats) over
    the local slot axis and, if ``axis_name`` is given, over that mesh axis.
    """
    ms = jax.tree.map(lambda l: jnp.mean(l, axis=0), stacked_ms)
    if axis_name is not None:
        ms = jax.tree.map(lambda l: jax.lax.pmean(l, axis_name), ms)
    return ms


def default_byz_mask(n, f):
    """Boolean (n,) mask with the *last* f slots Byzantine, matching the
    reference's rank layout (Aggregathor/trainer.py:217-268: Byzantine
    workers are the highest ranks)."""
    import numpy as np

    mask = np.zeros(n, dtype=bool)
    if f:
        mask[n - f :] = True
    return mask

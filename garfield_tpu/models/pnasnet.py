"""PNASNet A/B (counterpart of garfieldpp/models/pnasnet.py): progressive
NAS cells — sep-conv and sep-conv+maxpool cell types."""

import flax.linen as nn
import jax.numpy as jnp

from ._layers import conv, conv1x1, global_avg_pool, max_pool, norm


class SepConv(nn.Module):
    out_planes: int
    kernel: int
    stride: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        in_planes = x.shape[-1]
        x = conv(in_planes, self.kernel, self.stride,
                 padding=(self.kernel - 1) // 2, groups=in_planes, dtype=d)(x)
        x = conv1x1(self.out_planes, dtype=d)(x)
        return norm(train, dtype=d)(x)


class CellA(nn.Module):
    out_planes: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        y1 = SepConv(self.out_planes, 7, self.stride, dtype=d)(x, train)
        y2 = max_pool(x, 3, self.stride, padding=1)
        if self.stride == 2 or x.shape[-1] != self.out_planes:
            y2 = norm(train, dtype=d)(conv1x1(self.out_planes, dtype=d)(y2))
        return nn.relu(y1 + y2)


class CellB(nn.Module):
    out_planes: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        # branch 1: two sep convs
        y1 = SepConv(self.out_planes, 3, self.stride, dtype=d)(x, train)
        y2 = SepConv(self.out_planes, 7, self.stride, dtype=d)(x, train)
        # branch 2: sep conv + maxpool
        y3 = max_pool(x, 3, self.stride, padding=1)
        if self.stride == 2 or x.shape[-1] != self.out_planes:
            y3 = norm(train, dtype=d)(conv1x1(self.out_planes, dtype=d)(y3))
        y4 = SepConv(self.out_planes, 5, self.stride, dtype=d)(x, train)
        b1 = nn.relu(y1 + y2)
        b2 = nn.relu(y3 + y4)
        return norm(train, dtype=d)(
            conv1x1(self.out_planes, dtype=d)(
                nn.relu(jnp.concatenate([b1, b2], axis=-1))))


class PNASNet(nn.Module):
    cell_type: str  # "A" or "B"
    num_cells: int
    num_planes: int
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        cell = CellA if self.cell_type == "A" else CellB
        planes = self.num_planes
        x = nn.relu(norm(train, dtype=d)(conv(planes, 3, 1, padding=1, dtype=d)(x)))
        for stage in range(3):
            for _ in range(self.num_cells):
                x = cell(planes, 1, dtype=d)(x, train)
            if stage < 2:
                planes *= 2
                x = cell(planes, 2, dtype=d)(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=d)(x)


def PNASNetA(num_classes=10, dtype=jnp.float32):
    return PNASNet("A", 6, 44, num_classes, dtype)


def PNASNetB(num_classes=10, dtype=jnp.float32):
    return PNASNet("B", 6, 32, num_classes, dtype)

"""Tests for the model zoo — registry parity + forward shapes on tiny inputs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import models

# Reference-registered names (garfieldpp/tools.py:66-88) that must exist.
REFERENCE_NAMES = [
    "convnet", "cifarnet", "cnn", "resnet18", "resnet34", "resnet50",
    "resnet152", "inception", "vgg16", "vgg19", "preactresnet18",
    "googlenet", "densenet121", "resnext29", "mobilenet", "mobilenetv2",
    "dpn92", "shufflenetg2", "senet18", "efficientnetb0", "regnetx200",
    "pimanet",
]


def test_registry_covers_reference_names():
    for name in REFERENCE_NAMES:
        assert name in models.models, f"missing model {name}"


def test_num_classes_dict_parity():
    # garfieldpp/tools.py:89 — plus copytask, the token-sequence task
    # behind the transformer family (no reference counterpart).
    assert models.num_classes_dict == {
        "cifar10": 10, "cifar100": 100, "mnist": 10, "imagenet": 1000, "pima": 1,
        "copytask": 10,
    }


def test_select_model_errors():
    with pytest.raises(ValueError):
        models.select_model("nope", "cifar10")
    with pytest.raises(ValueError):
        models.select_model("resnet18", "nope")


def _forward(model, shape, train=False):
    x = jnp.zeros(shape, jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    if train:
        out, _ = model.apply(
            variables, x, train=True,
            mutable=["batch_stats"], rngs={"dropout": jax.random.PRNGKey(1)},
        )
        return out
    return model.apply(variables, x, train=False)


# Small/cheap models: full forward both modes.
@pytest.mark.parametrize("name,shape", [
    ("convnet", (2, 28, 28, 1)),
    ("cifarnet", (2, 32, 32, 3)),
    ("lenet", (2, 32, 32, 3)),
    ("cnn", (2, 32, 32, 3)),
])
def test_small_model_forward(name, shape):
    model = models.models[name](num_classes=10)
    out = _forward(model, shape, train=True)
    assert out.shape == (2, 10)
    out = _forward(model, shape, train=False)
    assert np.isfinite(np.asarray(out)).all()


def test_pimanet_forward():
    model = models.models["pimanet"](num_classes=1)
    out = _forward(model, (4, 8))
    assert out.shape == (4, 1)
    o = np.asarray(out)
    assert ((o >= 0) & (o <= 1)).all()  # sigmoid output (pimanet.py:14)


# Mid-size models: eval forward only, tiny batch. The heaviest zoo
# members (deep-graph compiles of 5-30s each) carry a slow mark — off
# the tier-1 fast shard for wall-time budget; a fast representative per
# architecture style stays tier-1.
_SLOW_FWD = pytest.mark.slow
@pytest.mark.parametrize("name", [
    "resnet18", "preactresnet18", "vgg11", "mobilenet",
    pytest.param("mobilenetv2", marks=_SLOW_FWD),
    "senet18",
    pytest.param("shufflenetg2", marks=_SLOW_FWD),
    pytest.param("shufflenetv2", marks=_SLOW_FWD),
    pytest.param("regnetx200", marks=_SLOW_FWD),
    pytest.param("efficientnetb0", marks=_SLOW_FWD),
    pytest.param("densenet_cifar", marks=_SLOW_FWD),
    pytest.param("dpn26", marks=_SLOW_FWD),
    pytest.param("googlenet", marks=_SLOW_FWD),
    "resnext29",
])
def test_cifar_model_forward(name):
    model = models.models[name](num_classes=10)
    out = _forward(model, (1, 32, 32, 3))
    assert out.shape == (1, 10)
    assert np.isfinite(np.asarray(out)).all()


def test_batchnorm_collections_exist():
    model = models.models["resnet18"](num_classes=10)
    x = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert "batch_stats" in variables
    # train step must be able to mutate the running stats
    _, new_state = model.apply(
        variables, x, train=True, mutable=["batch_stats"])
    assert "batch_stats" in new_state


def test_select_model_dtype_threading():
    model = models.select_model("cifarnet", "cifar10", dtype=jnp.bfloat16)
    x = jnp.zeros((1, 32, 32, 3), jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.dtype == jnp.bfloat16

"""Typed wire codec for the host plane (DESIGN.md §11).

The cluster driver's frames used to be bare ``ndarray.tobytes()`` — the
reference's wire format (garfield.proto:24-33) — which (a) ships every
gradient/model/gossip frame at f32 width even though the on-mesh pipeline
already proved bf16 gradients converge (PERF.md r3), and (b) gives the
receiver nothing to validate beyond total length, so a Byzantine process
could only be caught by a wrong-size frame. Every data frame now carries a
16-byte self-describing header:

    magic   2s   b"GW"
    ver     u8   1
    dtype   u8   low nibble: 0 = f32, 1 = bf16, 2 = int8, 3 = int4,
                 4 = topk; HIGH nibble: plane tag
    elems   u64  logical float32 element count
    crc32   u32  zlib.crc32 of the payload bytes

Round 18 (DESIGN.md §20) adds three LOSSY payload schemes behind new
low-nibble tags. int8/int4 are linear per-block quantization — payload
``[u32 block || ceil(elems/block) f32 scales || codes]`` with a
symmetric grid per block; int4 packs biased nibbles (code + 8, so the
honest grid is [1, 15] and nibble 0 is ban evidence). topk is
sparsification — ``k`` little-endian ``(u32 index, f32 value)`` pairs
with strictly-increasing indices ``< elems``. Every semantic violation
(out-of-range scale, a block prefix past the element count, an int8
code -128 / int4 nibble 0 outside the honest grid,
duplicate/descending/out-of-bounds index) raises the same ``WireError``
as a CRC failure: the CRC proves the bytes are the sender's, so invalid
*content* is attributable Byzantine evidence feeding the PR 4
quorum-exclusion ban path. The decoder never allocates more than
O(elems) either — the block prefix is bounded by the element count and
a sparse frame's claimed dense size must be pinned (``expect_elems``)
or bounded (``max_elems``) by the consumer, so no CRC-valid frame can
demand a multi-GB scatter or dequant pad.

The dtype byte's high nibble is the **plane tag** (DESIGN.md §15): only
two of its 256 values were ever used, so the spare bits carry which
logical exchange plane (gradient / model / control) the frame belongs to
— the self-describing half of the per-plane register slots in
``utils.exchange`` (the transport header routes; this tag lets any
consumer label bytes per plane without context). Plane 0 frames are
byte-identical to the pre-plane format, so every committed trajectory
and artifact pins carry over; decoders reject only unknown LOW-nibble
dtype tags, never a nonzero plane.

Round 20 (DESIGN.md §22) adds the **membership epoch** behind a second
header version: ``encode(..., epoch=E)`` emits a 20-byte ``ver=2``
header carrying the sender's control-plane epoch as a u32 between the
element count and the CRC — and the CRC is SEEDED with the epoch bytes,
so the epoch claim is under the same integrity tag as the payload (a
relay cannot restamp a frame's epoch without producing a CRC mismatch;
a stale epoch is provably the SENDER's stale epoch). ``epoch=None``
(the default) emits the version-1 header unchanged — every committed
artifact and trajectory pin predates epochs and stays byte-identical.
Consumers on an epoch-checked plane pass ``expect_epoch=E``: a frame
stamped with any other epoch — or carrying no epoch at all — raises the
same attributable ``WireError`` as a cross-shard plane stamp
(controlplane/membership.py owns what E currently is; this codec only
enforces it).

``GARFIELD_WIRE_DTYPE=f32|bf16|int8|int4`` selects the SEND width
(default f32) and ``GARFIELD_WIRE_TOPK=<divisor>`` (default 0 = off)
overlays top-k sparsification on the GRADIENT plane (cluster policy:
model/gossip broadcasts are absolute state — a sparse model frame would
zero most parameters on any catch-up read, see DESIGN.md §20 — so they
keep the dense width). bf16 halves every gradient, model and gossip
frame on the DCN; int8/int4 cut ~4x/~8x; top-k at the default divisor
32 cuts 16x. The f32 setting keeps the payload bytes BYTE-IDENTICAL to
the pre-codec ``tobytes()`` format (modulo the header), so existing
trajectory pins carry over. Decoding is dtype-driven by the header,
never by the local setting — mixed-width deployments interoperate (each
peer chooses its own send width, exactly like per-link compression).

The bf16 cast is pure numpy (no jax dependency — the exchange bench and
its child processes stay jax-free): round-to-nearest-even on the high 16
bits of the f32 bit pattern, the same rounding XLA's ``convert`` uses, so
a host-decoded gradient matches what the on-mesh bf16 pipeline would have
produced for the same value. Restoring f32 is the exact ``u16 << 16``
view — bf16 -> f32 is lossless.

Why bf16-on-wire is safe UPSTREAM of the GAR: the rules aggregate at f32
(`aggregators/_common` Gram accumulation, cclip's f32 center iteration),
so wire quantization is a bounded per-coordinate perturbation of the
rule's INPUT rows — a strictly weaker disturbance than the Byzantine
value faults the f budget already absorbs, and the honest rows all carry
the same quantization so relative geometry (distances, medians) is
preserved to bf16 precision. The convergence smoke in tests/test_cluster
runs the lie attack over both widths.
"""

import os
import struct
import threading
import zlib

import numpy as np

__all__ = [
    "WIRE_DTYPES",
    "WIRE_SCHEMES",
    "WireError",
    "ErrorFeedback",
    "wire_dtype",
    "wire_topk",
    "wire_fused",
    "wire_batch_decode",
    "ingest_threads",
    "topk_k",
    "check_plane",
    "check_epoch",
    "encode",
    "decode",
    "decode_into",
    "decode_batch_into",
    "frame_plane",
    "frame_scheme",
    "frame_elems",
    "frame_epoch",
    "frame_nbytes",
    "HEADER_NBYTES",
    "HEADER2_NBYTES",
    "MAX_PLANE",
    "MAX_EPOCH",
    "QUANT_BLOCK",
    "DEFAULT_TOPK_DIV",
]

_HDR = struct.Struct("!2sBBQI")
HEADER_NBYTES = _HDR.size  # 16
# Round 20: the epoch-stamped header (ver=2) — same fields plus a u32
# membership epoch between the element count and the CRC. The epoch
# bytes SEED the payload CRC (see module docstring), so the stamp is
# tamper-evident, not advisory.
_HDR2 = struct.Struct("!2sBBQII")
HEADER2_NBYTES = _HDR2.size  # 20
_EPOCH = struct.Struct("!I")
_MAGIC = b"GW"
_VERSION = 1
_VERSION_EPOCH = 2
# Epochs ride a u32: 4 billion membership changes outlives any
# deployment, and a wider field would grow EVERY epoch-stamped frame.
MAX_EPOCH = 0xFFFFFFFF
_TAG_F32 = 0
_TAG_BF16 = 1
# Round 18 (DESIGN.md §20): lossy compressed payload schemes behind new
# LOW-nibble tags — the high (plane/shard) nibble semantics are
# untouched, and tags 0/1 frames stay byte-identical to the PR 4 format.
_TAG_INT8 = 2
_TAG_INT4 = 3
_TAG_TOPK = 4
# Dense send widths selectable via GARFIELD_WIRE_DTYPE; "topk" is a
# separate axis (GARFIELD_WIRE_TOPK) because it composes with a dense
# width per plane rather than replacing it everywhere.
WIRE_DTYPES = ("f32", "bf16", "int8", "int4")
WIRE_SCHEMES = WIRE_DTYPES + ("topk",)
_ITEMSIZE = {_TAG_F32: 4, _TAG_BF16: 2}
_TAG_NAME = {_TAG_F32: "f32", _TAG_BF16: "bf16", _TAG_INT8: "int8",
             _TAG_INT4: "int4", _TAG_TOPK: "topk"}
# Plane tag (high nibble of the dtype byte — see the module docstring).
MAX_PLANE = 0x0F
# Linear-quantization block: one f32 scale per QUANT_BLOCK coordinates.
# 1024 keeps the scale overhead under 0.4% of the codes while keeping a
# single hot coordinate from flattening the whole frame's grid (a
# per-frame scale hands one outlier coordinate veto power over every
# other coordinate's resolution).
QUANT_BLOCK = 1024
# Default top-k sparsification divisor: keep ceil(d / 32) coordinates
# (each an 8-byte index+value pair -> 16x fewer bytes than f32).
DEFAULT_TOPK_DIV = 32


class WireError(ValueError):
    """A frame failed codec validation (bad magic/version/dtype tag,
    truncation, length/element-count mismatch, or CRC failure). On the
    cluster's quorum paths this is BAN EVIDENCE: a Byzantine process
    controls its wire bytes, and a frame that fails the codec proves its
    sender faulty exactly like the old wrong-length check."""


def wire_dtype():
    """The configured send width (``GARFIELD_WIRE_DTYPE``, default f32)."""
    d = os.environ.get("GARFIELD_WIRE_DTYPE", "f32").strip().lower()
    if d not in WIRE_DTYPES:
        raise ValueError(
            f"GARFIELD_WIRE_DTYPE must be one of {WIRE_DTYPES}, got {d!r}"
        )
    return d


def wire_topk():
    """The configured top-k sparsification DIVISOR (``GARFIELD_WIRE_TOPK``,
    default 0 = off): gradient-plane frames keep the ceil(d / divisor)
    largest-magnitude coordinates. A divisor, not an absolute k, so one
    setting scales across every frame size in a deployment."""
    v = os.environ.get("GARFIELD_WIRE_TOPK", "0").strip()
    try:
        div = int(v)
    except ValueError:
        raise ValueError(
            f"GARFIELD_WIRE_TOPK must be a non-negative integer divisor, "
            f"got {v!r}"
        )
    if div < 0:
        raise ValueError(
            f"GARFIELD_WIRE_TOPK must be >= 0 (0 = off), got {div}"
        )
    return div


def wire_fused():
    """Whether frame consumers take the fused decode-into-buffer path
    (``GARFIELD_WIRE_FUSED_DECODE``, default on): ``decode_into``
    straight into the streaming wave buffer / a reusable shard scratch
    instead of materializing a fresh O(elems) array per frame. Purely a
    memory-traffic knob — both paths are bitwise-identical and run the
    same validation (pinned in tests/test_wire.py), so turning it off is
    only for isolating the fused path when debugging."""
    return os.environ.get(
        "GARFIELD_WIRE_FUSED_DECODE", "1"
    ).lower() not in ("", "0", "false")


def wire_batch_decode():
    """Whether bulk frame consumers take the batched decode path
    (``GARFIELD_WIRE_BATCH_DECODE``, default on): ``push_frames`` /
    multi-frame harvests route through ``decode_batch_into`` — one
    vectorized header screen + run-grouped slab dequant — instead of a
    per-frame ``decode_into`` loop. Purely a host-CPU knob: both paths
    are bitwise-identical and raise the same per-frame ``WireError``s
    (pinned in tests/test_wire.py), so turning it off is only for
    isolating the batch path when debugging."""
    return os.environ.get(
        "GARFIELD_WIRE_BATCH_DECODE", "1"
    ).lower() not in ("", "0", "false")


def ingest_threads():
    """Worker-thread count for the batch decoder's CRC pass
    (``GARFIELD_INGEST_THREADS``, default 0 = inline). ``zlib.crc32``
    releases the GIL on sizeable buffers, so on a multi-core host a
    small pool can overlap the integrity scan of wave w+1 with the fold
    of wave w; on the 1-core bench container it only adds dispatch
    overhead (measured in DESIGN.md §24), hence off by default."""
    v = os.environ.get("GARFIELD_INGEST_THREADS", "0").strip()
    try:
        n = int(v)
    except ValueError:
        raise ValueError(
            f"GARFIELD_INGEST_THREADS must be a non-negative integer, "
            f"got {v!r}"
        )
    if n < 0:
        raise ValueError(
            f"GARFIELD_INGEST_THREADS must be >= 0 (0 = inline), got {n}"
        )
    return n


# Shared CRC pool for decode_batch_into: built lazily at first use and
# reused across calls (a per-batch pool would pay thread spawn on every
# wave, drowning the overlap it exists to buy). Guarded by a lock —
# batch decodes run from exchange waiter threads concurrently.
_CRC_POOL = {"n": 0, "exec": None}
_CRC_POOL_LOCK = threading.Lock()


def _crc_pool(n):
    with _CRC_POOL_LOCK:
        if _CRC_POOL["exec"] is None or _CRC_POOL["n"] != n:
            from concurrent.futures import ThreadPoolExecutor

            if _CRC_POOL["exec"] is not None:
                _CRC_POOL["exec"].shutdown(wait=False)
            _CRC_POOL["exec"] = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="wire-crc"
            )
            _CRC_POOL["n"] = n
        return _CRC_POOL["exec"]


def topk_k(elems, div):
    """Kept-coordinate count for an ``elems``-element frame at divisor
    ``div`` — ceil(elems / div), floored at 1. The single shared
    definition (host codec AND the in-graph twin, parallel/compress.py)
    so the emulated and shipped sparsity cannot drift."""
    elems = int(elems)
    div = int(div)
    if div < 1:
        raise ValueError(f"top-k divisor must be >= 1, got {div}")
    if elems <= 0:
        return 0
    return max(1, -(-elems // div))


def _f32_to_bf16(vec):
    """Round-to-nearest-even truncation of f32 to its high 16 bits (the
    uint32 >> 16 view trick; NaN payload bits survive because the quiet
    bit lives in the kept half)."""
    u = vec.view(np.uint32)
    return ((u + np.uint32(0x7FFF) + ((u >> np.uint32(16)) & np.uint32(1)))
            >> np.uint32(16)).astype(np.uint16)


def _bf16_to_f32(u16):
    return (u16.astype(np.uint32) << np.uint32(16)).view(np.float32)


def check_plane(plane, what="plane"):
    """Validate a plane/shard tag for the header's spare nibble; returns
    it as an int. The tag has FOUR bits — the federated engine rides
    shard ids on it (federated/sharding.py) — so an id past 15 must
    fail HERE, at stamp time, with the capacity named: masking it into
    the nibble would silently deliver one shard's frames to another
    (the exact cross-shard mis-fold the stamp exists to make
    attributable). Non-integral tags (bools, floats) are rejected too:
    ``int(3.7)`` truncating to plane 3 is the same silent corruption.
    """
    if isinstance(plane, bool) or not isinstance(plane, (int, np.integer)):
        raise TypeError(
            f"{what} tag must be an integer, got {plane!r}"
        )
    plane = int(plane)
    if not 0 <= plane <= MAX_PLANE:
        raise ValueError(
            f"{what} tag {plane} does not fit the wire header's spare "
            f"nibble [0, {MAX_PLANE}] — a larger plane/shard space needs "
            "a wider header (new wire version), not a truncated tag"
        )
    return plane


def check_epoch(epoch, what="epoch"):
    """Validate a membership epoch for the v2 header's u32 field;
    returns it as an int. Same loud-failure contract as ``check_plane``:
    a non-integral epoch (bool, float) or one past the u32 would either
    truncate into a DIFFERENT epoch — exactly the stale/replayed-epoch
    confusion the stamp exists to make attributable — or overflow the
    header, so both fail at stamp time."""
    if isinstance(epoch, bool) or not isinstance(epoch, (int, np.integer)):
        raise TypeError(f"{what} must be an integer, got {epoch!r}")
    epoch = int(epoch)
    if not 0 <= epoch <= MAX_EPOCH:
        raise ValueError(
            f"{what} {epoch} does not fit the wire header's u32 epoch "
            f"field [0, {MAX_EPOCH}]"
        )
    return epoch


def _quant_payload(vec, qmax, block):
    """Linear per-block quantization payload: ``[u32 block || f32
    scales || codes]`` with symmetric grid ``scale = max|x| / qmax`` per
    block and round-to-nearest-even codes. An honest sender MUST fail
    loudly on non-finite input (the scale would be inf/NaN and the
    receiver's range check would turn the honest frame into ban
    evidence); raising here keeps the fault local."""
    if vec.size and not np.isfinite(vec).all():
        raise ValueError(
            "cannot quantize a non-finite vector — an inf/NaN scale "
            "would make this honest frame indistinguishable from a "
            "Byzantine one on the receiver's range check"
        )
    nblocks = -(-vec.size // block) if vec.size else 0
    pad = nblocks * block - vec.size
    x = np.pad(vec, (0, pad)) if pad else vec
    xb = x.reshape(nblocks, block) if nblocks else x.reshape(0, block)
    scales = (np.max(np.abs(xb), axis=1) / np.float32(qmax)).astype(
        np.float32
    )
    safe = np.where(scales > 0, scales, np.float32(1.0))
    codes = np.clip(
        np.rint(xb / safe[:, None]), -qmax, qmax
    ).astype(np.int8).reshape(-1)[: vec.size]
    return (
        np.array([block], "<u4").tobytes() + scales.tobytes(), codes
    )


def _dequant(codes, scales, block, elems):
    nblocks = scales.size
    pad = nblocks * block - elems
    c = np.pad(codes.astype(np.float32), (0, pad)) if pad else \
        codes.astype(np.float32)
    out = (c.reshape(nblocks, block) * scales[:, None].astype(np.float32))
    return out.reshape(-1)[:elems].astype(np.float32)


_PAIR = np.dtype([("i", "<u4"), ("v", "<f4")])


def encode(vec, dtype=None, *, plane=0, epoch=None, k=None, keep_from=None,
           block=QUANT_BLOCK):
    """Encode a flat float32 vector as one typed frame.

    ``dtype`` overrides the env-configured send width, and may also be
    ``"topk"`` (round 18): the payload becomes ``k`` sorted
    ``(u32 index, f32 value)`` pairs — ``k`` explicit, or derived from
    the ``GARFIELD_WIRE_TOPK`` divisor (``DEFAULT_TOPK_DIV`` when
    unset; an explicit ``k=0`` ships no head pairs — only the dense
    tail rides). ``keep_from`` marks the start of an always-kept dense tail
    (the ``[grad || stats]`` frames' BatchNorm segment: state, not an
    additive signal — sparsifying it away would corrupt the robust-stats
    fold, so its coordinates ride along as ordinary pairs). int8/int4
    are linear per-block quantization (``block`` coordinates per f32
    scale, carried in the payload and range-checked on decode). f32
    payload bytes are the exact ``vec.tobytes()`` of the pre-codec
    format. ``plane`` (0..15) stamps the header's spare high-nibble
    plane tag — plane 0 keeps the frame byte-identical to the pre-plane
    format. Out-of-range or non-integral tags fail loudly
    (``check_plane``), never truncate.

    ``epoch`` (round 20) stamps the sender's membership epoch into a
    version-2 header, with the epoch bytes seeding the payload CRC so
    the claim is tamper-evident; ``epoch=None`` (default) emits the
    version-1 header byte-identical to every committed frame.
    """
    vec = np.ascontiguousarray(np.asarray(vec).reshape(-1), np.float32)
    dtype = wire_dtype() if dtype is None else dtype
    plane = check_plane(plane)
    if dtype == "bf16":
        payload = _f32_to_bf16(vec).tobytes()
        tag = _TAG_BF16
    elif dtype == "f32":
        payload = vec.tobytes()
        tag = _TAG_F32
    elif dtype in ("int8", "int4"):
        block = int(block)
        if block < 1:
            raise ValueError(f"quantization block must be >= 1, got {block}")
        # Clamp the block to the vector: past vec.size it only grows the
        # dequant pad (nblocks is 1 either way, so scales and codes — and
        # therefore the decoded values — are identical), and the decoder
        # rejects block > elems as an allocation bomb, so the clamp keeps
        # every honest frame inside that bound.
        block = min(block, max(vec.size, 1))
        qmax = 127 if dtype == "int8" else 7
        head, codes = _quant_payload(vec, qmax, block)
        if dtype == "int8":
            payload = head + codes.tobytes()
            tag = _TAG_INT8
        else:
            nib = (codes.astype(np.int16) + 8).astype(np.uint8)
            if nib.size % 2:
                nib = np.append(nib, np.uint8(8))  # pad nibble = code 0
            payload = head + (nib[0::2] | (nib[1::2] << 4)).tobytes()
            tag = _TAG_INT4
    elif dtype == "topk":
        head_n = vec.size if keep_from is None else int(keep_from)
        if not 0 <= head_n <= vec.size:
            raise ValueError(
                f"keep_from must be in [0, {vec.size}], got {keep_from}"
            )
        if k is None:
            k = topk_k(head_n, wire_topk() or DEFAULT_TOPK_DIV)
        k = int(min(max(k, 0), head_n))
        if k and not np.isfinite(vec[:head_n]).all():
            # NaN never compares > anything: argpartition would silently
            # demote real coordinates below garbage. Same honest-sender
            # loud-failure contract as the quantizers.
            raise ValueError("cannot top-k sparsify a non-finite vector")
        if k == 0:
            # No head pairs — only the always-kept dense tail rides
            # (argpartition with kth == head_n would be out of bounds).
            idx = np.arange(head_n, vec.size, dtype=np.uint32)
        elif k >= head_n:
            idx = np.arange(vec.size, dtype=np.uint32)
        else:
            top = np.argpartition(np.abs(vec[:head_n]), head_n - k)[
                head_n - k:
            ]
            idx = np.concatenate([
                np.sort(top).astype(np.uint32),
                np.arange(head_n, vec.size, dtype=np.uint32),
            ])
        pairs = np.empty(idx.size, _PAIR)
        pairs["i"] = idx
        pairs["v"] = vec[idx.astype(np.int64)]
        payload = pairs.tobytes()
        tag = _TAG_TOPK
    else:
        raise ValueError(f"unknown wire dtype {dtype!r}")
    if epoch is None:
        return _HDR.pack(
            _MAGIC, _VERSION, tag | (plane << 4), vec.size,
            zlib.crc32(payload),
        ) + payload
    epoch = check_epoch(epoch)
    return _HDR2.pack(
        _MAGIC, _VERSION_EPOCH, tag | (plane << 4), vec.size, epoch,
        zlib.crc32(payload, zlib.crc32(_EPOCH.pack(epoch))),
    ) + payload


def decode(buf, *, expect_plane=None, expect_elems=None, max_elems=None,
           expect_epoch=None):
    """Decode a typed frame back to a float32 vector; raises WireError.

    Validation order matters for the ban path: header shape first (magic,
    version, dtype tag), then the length/element-count consistency, then
    the CRC — every random bit flip or truncation of a valid frame fails
    at least one of these (a payload flip breaks the CRC; a header flip
    breaks magic/version/tag/length), so corrupted bytes can never reach
    a GAR (fuzzed in tests/test_wire.py).

    ``expect_plane`` makes the plane/shard stamp load-bearing for the
    federated shard plane (DESIGN.md §19): a consumer that owns plane
    ``s`` rejects frames stamped for any other plane as a codec failure
    — and since the stamp sits in the sender-controlled header, the
    mismatch is attributable ban evidence against the SENDER (a correct
    transport cannot restamp it without also failing magic/CRC), not a
    routing accident to shrug off.

    ``expect_elems`` pins the header's dense element count. For the
    dense and quantized schemes the payload length already corroborates
    ``elems``, but a SPARSE frame's dense size is a bare header claim:
    the k pairs are consistent with any ``elems > idx[-1]``, so a
    Byzantine sender (or a bit flip in the u64) could cheaply demand a
    multi-GB ``np.zeros(elems)`` scatter target. Quorum consumers know
    their plane's d and MUST pass it (``cluster._frame_transform``
    does); the mismatch rejects BEFORE any allocation, as the same
    attributable WireError as the old wrong-length frame.

    ``max_elems`` is the inexact form of the same pin, for consumers
    whose frames legitimately vary in size (the federated shard plane's
    multi-row frames: any whole number of rows up to the cohort) — a
    header claiming more than the bound rejects before any allocation.
    Every Byzantine-facing decode site must pass one of the two: a
    sparse frame decoded with neither is an unbounded allocation the
    sender controls.

    ``expect_epoch`` (round 20, DESIGN.md §22) makes the v2 header's
    membership-epoch stamp load-bearing: a consumer serving membership
    epoch E rejects frames stamped with any OTHER epoch — stale (a
    pre-failover member replaying into the new membership) or ahead (a
    forged view claim) — and rejects epoch-less version-1 frames too,
    so a sender cannot dodge the check by omitting the stamp. The epoch
    bytes seed the CRC, so the mismatch is attributable to the sender
    exactly like a plane-stamp mismatch.
    """
    tag, elems, payload = _checked_frame(
        buf, expect_plane, expect_elems, max_elems, expect_epoch
    )
    if tag == _TAG_BF16:
        return _bf16_to_f32(np.frombuffer(payload, np.uint16))
    if tag == _TAG_F32:
        return np.frombuffer(payload, np.float32)
    if tag in (_TAG_INT8, _TAG_INT4):
        codes, scales, block = _checked_quant(payload, tag, elems)
        return _dequant(codes, scales, block, elems)
    pairs = _checked_pairs(payload, elems)
    out = np.zeros(elems, np.float32)
    out[pairs["i"].astype(np.int64)] = pairs["v"]
    return out


def _checked_frame(buf, expect_plane, expect_elems, max_elems,
                   expect_epoch=None):
    """Shared header + structural + CRC validation of ``decode`` and
    ``decode_into``: returns ``(low-nibble tag, elems, payload)`` only
    for a frame whose bytes are provably the sender's and whose payload
    length is consistent with the header. Semantic payload validation
    (scale range, code grid, index ordering) is per-tag
    (``_checked_quant`` / ``_checked_pairs``) and also precedes any
    output construction."""
    if len(buf) < HEADER_NBYTES:
        raise WireError(
            f"truncated frame: {len(buf)} bytes is shorter than the "
            f"{HEADER_NBYTES}-byte header"
        )
    magic, ver, tag, elems, crc = _HDR.unpack_from(buf)
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    epoch = None
    hdr_nbytes = HEADER_NBYTES
    if ver == _VERSION_EPOCH:
        if len(buf) < HEADER2_NBYTES:
            raise WireError(
                f"truncated frame: {len(buf)} bytes is shorter than the "
                f"{HEADER2_NBYTES}-byte epoch-stamped header"
            )
        magic, ver, tag, elems, epoch, crc = _HDR2.unpack_from(buf)
        hdr_nbytes = HEADER2_NBYTES
    elif ver != _VERSION:
        raise WireError(f"unsupported wire version {ver}")
    if expect_plane is not None and (tag >> 4) != check_plane(
        expect_plane, "expect_plane"
    ):
        raise WireError(
            f"frame stamped for plane/shard {tag >> 4} arrived at a "
            f"consumer of plane/shard {int(expect_plane)} — cross-shard "
            "delivery, attributable to the sender"
        )
    if expect_epoch is not None:
        exp = check_epoch(expect_epoch, "expect_epoch")
        if epoch is None:
            raise WireError(
                f"frame carries no membership epoch but the consumer "
                f"serves epoch {exp} — pre-epoch (v1) frames are not "
                "admissible on an epoch-checked plane, attributable to "
                "the sender"
            )
        if epoch != exp:
            raise WireError(
                f"frame stamped with membership epoch {epoch} arrived at "
                f"a consumer serving epoch {exp} — "
                f"{'stale' if epoch < exp else 'future'}-epoch delivery, "
                "attributable to the sender"
            )
    tag &= 0x0F  # the high nibble is the plane tag (frame_plane)
    if tag not in _TAG_NAME:
        raise WireError(f"unknown dtype tag {tag}")
    if expect_elems is not None and elems != int(expect_elems):
        raise WireError(
            f"frame promises {elems} elements, consumer expected "
            f"{int(expect_elems)}"
        )
    if max_elems is not None and elems > int(max_elems):
        raise WireError(
            f"frame promises {elems} elements, past the consumer's "
            f"bound of {int(max_elems)}"
        )
    payload = buf[hdr_nbytes:]
    # Structural length checks come BEFORE the CRC (cheap, and a
    # truncated frame should say "truncated", not "CRC mismatch"); the
    # semantic payload checks (scale range, index ordering) come AFTER —
    # a frame whose bytes survive the CRC but whose *content* is invalid
    # is exactly the attributable Byzantine case (only the sender could
    # have produced those bytes), and must raise the same WireError that
    # feeds the quorum-exclusion ban path.
    if tag in _ITEMSIZE:
        if len(payload) != elems * _ITEMSIZE[tag]:
            raise WireError(
                f"payload is {len(payload)} bytes but the header promises "
                f"{elems} elements of {_ITEMSIZE[tag]} bytes"
            )
    elif tag in (_TAG_INT8, _TAG_INT4):
        if len(payload) < 4:
            raise WireError(
                f"quantized payload is {len(payload)} bytes — too short "
                "for the u32 block-size prefix"
            )
    else:  # _TAG_TOPK
        if len(payload) % _PAIR.itemsize:
            raise WireError(
                f"sparse payload is {len(payload)} bytes — not a whole "
                f"number of {_PAIR.itemsize}-byte (index, value) pairs"
            )
        if len(payload) // _PAIR.itemsize > elems:
            raise WireError(
                f"sparse payload carries {len(payload) // _PAIR.itemsize} "
                f"pairs but the header promises only {elems} elements"
            )
    # The v2 CRC is seeded with the epoch bytes (module docstring): an
    # in-flight restamp of the epoch field fails here, so an epoch
    # mismatch that passes the CRC is provably the sender's own stamp.
    seed = 0 if epoch is None else zlib.crc32(_EPOCH.pack(epoch))
    if zlib.crc32(payload, seed) != crc:
        raise WireError("payload CRC mismatch")
    return tag, int(elems), payload


def _checked_quant(payload, tag, elems):
    """Semantic validation of a quantized payload (block bound, scale
    range, honest-grid codes) — every check the dequant step relies on,
    BEFORE any dequant output is written, so ``decode_into`` leaves its
    target untouched on ban evidence. Returns ``(codes, scales, block)``."""
    block = int(np.frombuffer(payload, "<u4", count=1)[0])
    if block < 1:
        raise WireError(f"quantization block {block} must be >= 1")
    if block > max(int(elems), 1):
        # An honest encoder clamps its block to the vector (same
        # values, see encode); a larger block is an allocation bomb —
        # the dequant pad is nblocks*block elements, which a
        # block=0xFFFFFFFF prefix on a tiny frame turns into ~17 GB.
        # This bound keeps it under 2x elems.
        raise WireError(
            f"quantization block {block} exceeds the frame's "
            f"{elems} elements"
        )
    nblocks = -(-int(elems) // block) if elems else 0
    codes_nbytes = (
        int(elems) if tag == _TAG_INT8 else (int(elems) + 1) // 2
    )
    if len(payload) != 4 + nblocks * 4 + codes_nbytes:
        raise WireError(
            f"quantized payload is {len(payload)} bytes but "
            f"{elems} elements at block {block} need "
            f"{4 + nblocks * 4 + codes_nbytes}"
        )
    scales = np.frombuffer(payload, "<f4", count=nblocks, offset=4)
    # Range check (the ISSUE's scale gate): a NaN/inf or negative
    # scale lets a Byzantine sender smuggle unbounded or
    # sign-flipped rows through an otherwise-valid frame.
    if nblocks and not (np.isfinite(scales).all()
                        and (scales >= 0).all()):
        raise WireError(
            "quantization scale out of range (non-finite or negative)"
        )
    raw = np.frombuffer(payload, np.uint8, offset=4 + nblocks * 4)
    if tag == _TAG_INT8:
        codes = raw.view(np.int8)
        if codes.size and (codes == -128).any():
            # The symmetric grid is [-127, 127] (encode clips at
            # qmax): code -128 is unreachable by any honest encoder
            # — ban evidence exactly like int4's nibble 0.
            raise WireError(
                "int8 code -128 is outside the symmetric grid"
            )
    else:
        nib = np.empty(raw.size * 2, np.uint8)
        nib[0::2] = raw & 0x0F
        nib[1::2] = raw >> 4
        nib = nib[: int(elems)]
        if nib.size and (nib == 0).any():
            # The biased-nibble grid is [1, 15] (code -7..7 + 8);
            # nibble 0 is unreachable by any honest encoder.
            raise WireError("int4 nibble 0 is outside the biased grid")
        codes = nib.astype(np.int16) - 8
    return codes, scales, block


def _checked_pairs(payload, elems):
    """Semantic validation of a sparse payload: the (index, value) pairs
    ready to scatter. Index validation is the sparse scheme's ban teeth —
    without it a Byzantine sender could double-count a coordinate
    (duplicate index) or write out of bounds."""
    pairs = np.frombuffer(payload, _PAIR)
    idx = pairs["i"]
    if idx.size:
        if int(idx[-1]) >= elems:
            raise WireError(
                f"sparse index {int(idx[-1])} out of bounds for "
                f"{elems} elements"
            )
        if idx.size > 1 and not (np.diff(idx.astype(np.int64)) > 0).all():
            raise WireError(
                "sparse indices must be strictly increasing "
                "(duplicate or descending index)"
            )
    return pairs


def decode_into(buf, out, *, expect_plane=None, expect_elems=None,
                max_elems=None, expect_epoch=None):
    """Decode a typed frame DIRECTLY into a preallocated float32 row;
    returns the element count written (``out[:elems]``).

    The fused half of the streaming ingest path (DESIGN.md §21):
    ``decode`` materializes an O(elems) float32 result that the reducer
    then memcpys into its wave buffer — at federated scale that
    transient is touched exactly once. ``decode_into`` runs the SAME
    validation pipeline (same ``WireError`` texts, same ban evidence)
    and then dequantizes/scatters straight into the caller's buffer
    row, bitwise-identical values to ``decode``:

    - f32/bf16 copy (bf16 via the exact ``u16 << 16`` widening, written
      through a uint32 view of the target);
    - int8/int4 dequantize per block with ``np.multiply(..., out=...)``
      — full blocks as one (nblocks, block) broadcast into the target,
      the ragged tail block against its scalar scale; both are the same
      f32 multiply ``_dequant`` does, minus the pad + slice copies;
    - topk zero-fills then scatters, only after index validation.

    Validation ALWAYS completes before the first byte of ``out`` is
    written: a frame that raises leaves the target untouched (pinned in
    tests/test_wire.py), so a Byzantine frame cannot scribble on a wave
    buffer slot it failed to claim. ``elems`` must fit ``out`` — with
    neither ``expect_elems`` nor ``max_elems`` given, ``out.size`` is
    the implicit allocation bound (the target IS the allocation, so a
    sparse frame's dense-size claim is bounded either way).
    """
    out = np.asarray(out)
    if (out.dtype != np.float32 or out.ndim != 1
            or not out.flags.c_contiguous or not out.flags.writeable):
        raise TypeError(
            "decode_into target must be a writable C-contiguous 1-D "
            f"float32 array, got {out.dtype} ndim={out.ndim}"
        )
    if expect_elems is None and max_elems is None:
        max_elems = out.size
    tag, elems, payload = _checked_frame(
        buf, expect_plane, expect_elems, max_elems, expect_epoch
    )
    if elems > out.size:
        raise WireError(
            f"frame carries {elems} elements but the target row holds "
            f"only {out.size}"
        )
    dst = out[:elems]
    if tag == _TAG_F32:
        dst[...] = np.frombuffer(payload, np.float32)
    elif tag == _TAG_BF16:
        np.left_shift(
            np.frombuffer(payload, np.uint16), np.uint32(16),
            out=dst.view(np.uint32), dtype=np.uint32, casting="unsafe",
        )
    elif tag in (_TAG_INT8, _TAG_INT4):
        codes, scales, block = _checked_quant(payload, tag, elems)
        cf = codes.astype(np.float32)
        nfull = elems // block
        split = nfull * block
        if nfull:
            np.multiply(
                cf[:split].reshape(nfull, block), scales[:nfull, None],
                out=dst[:split].reshape(nfull, block),
            )
        if split < elems:
            np.multiply(cf[split:], scales[nfull], out=dst[split:])
    else:
        pairs = _checked_pairs(payload, elems)
        dst[...] = 0.0
        dst[pairs["i"].astype(np.int64)] = pairs["v"]
    return elems


def decode_batch_into(bufs, out2d, *, expect_plane=None, expect_elems=None,
                      max_elems=None, expect_epoch=None):
    """Decode ``k`` typed frames into the rows of a preallocated 2-D
    float32 slab; frame ``i`` lands in ``out2d[i, :elems_i]``. Returns a
    ``k``-list of per-frame results: the element count written for an
    accepted frame, or the ``WireError`` REJECTING it — never raises per
    frame, so one forged frame bans its sender without poisoning its
    batchmates (the exchange layer's stored-exception convention).

    The batched half of the ingest plane (DESIGN.md §24). Per-frame
    ``decode_into`` pays a full Python trip per client frame — header
    unpack, CRC call, per-frame dequant — which FEDBENCH_r02 showed
    dominating the million-client round. This runs the SAME validation
    pipeline restructured into three batch passes:

    1. **vectorized header screen**: the first 20 bytes of every frame,
       packed into one (k, 20) uint8 view — magic/version/dtype-tag/
       plane/epoch/element-count/structural-length checks as numpy
       comparisons over the whole batch at once;
    2. **per-frame CRC** on zero-copy payload slices (``zlib.crc32``
       releases the GIL; ``GARFIELD_INGEST_THREADS`` optionally fans
       this pass over a small shared pool — see ``ingest_threads``);
    3. **run-grouped dequant**: maximal runs of consecutive accepted
       frames sharing (scheme, elems[, block]) decode as ONE vectorized
       op — an (m, elems) int8/int4 code slab times broadcast scales
       instead of m Python calls — written straight into the contiguous
       row range. f32/bf16 rows are single memcpy-bound ops per frame
       already (no dequant to fuse) and topk scatters are inherently
       per-frame, so those run per row inside the batch loop.

    Every multiply is elementwise-identical to ``decode_into``'s, so
    accepted rows are BITWISE-equal to the per-frame path (pinned in
    tests/test_wire.py). Any frame the screen, CRC, or semantic pass
    rejects is re-run through per-frame ``decode_into`` to produce its
    error — the reject text, the validation order, and the
    target-row-untouched guarantee are therefore identical to the
    per-frame path BY CONSTRUCTION, not by parallel maintenance; the
    recompute only ever costs on ban evidence. Allocation pins work
    exactly as in ``decode_into``: with neither ``expect_elems`` nor
    ``max_elems`` given, the slab's row width is the implicit bound, and
    the screen rejects over-claiming headers before any payload-sized
    work.
    """
    out2d = np.asarray(out2d)
    if (out2d.dtype != np.float32 or out2d.ndim != 2
            or not out2d.flags.c_contiguous or not out2d.flags.writeable):
        raise TypeError(
            "decode_batch_into target must be a writable C-contiguous "
            f"2-D float32 array, got {out2d.dtype} ndim={out2d.ndim}"
        )
    k = len(bufs)
    if k > out2d.shape[0]:
        raise ValueError(
            f"{k} frames but the target slab holds only "
            f"{out2d.shape[0]} rows"
        )
    if k == 0:
        return []
    row_elems = out2d.shape[1]
    pins = dict(expect_plane=expect_plane, expect_elems=expect_elems,
                max_elems=max_elems, expect_epoch=expect_epoch)

    # -- pass 1: vectorized header screen over a packed (k, 20) view --
    lens = np.fromiter((len(b) for b in bufs), np.int64, count=k)
    hdr = np.frombuffer(
        b"".join(
            bytes(b[:HEADER2_NBYTES]).ljust(HEADER2_NBYTES, b"\0")
            for b in bufs
        ),
        np.uint8,
    ).reshape(k, HEADER2_NBYTES)
    ver = hdr[:, 2]
    tag = hdr[:, 3] & 0x0F
    plane = hdr[:, 3] >> 4
    # Big-endian field reads via tiny contiguous copies (k*8 bytes).
    # elems stays u64: a forged header can claim up to 2**64-1, and a
    # signed cast could wrap a bomb into a small number that slips the
    # bound screen.
    elems_u = hdr[:, 4:12].copy().view(">u8").reshape(k)
    epoch_u = hdr[:, 12:16].copy().view(">u4").reshape(k)
    isv2 = ver == _VERSION_EPOCH
    ok = lens >= HEADER_NBYTES
    ok &= (hdr[:, 0] == _MAGIC[0]) & (hdr[:, 1] == _MAGIC[1])
    ok &= (ver == _VERSION) | isv2
    ok &= ~(isv2 & (lens < HEADER2_NBYTES))
    ok &= tag <= _TAG_TOPK
    if expect_plane is not None:
        ok &= plane == check_plane(expect_plane, "expect_plane")
    if expect_epoch is not None:
        ok &= isv2 & (epoch_u == check_epoch(expect_epoch, "expect_epoch"))
    if expect_elems is not None:
        ok &= elems_u == int(expect_elems)
    if max_elems is not None:
        ok &= elems_u <= int(max_elems)
    elif expect_elems is None:
        ok &= elems_u <= row_elems  # the implicit allocation bound
    ok &= elems_u <= row_elems  # decode_into's target-row fit check
    # Structural length (same pre-CRC position as _checked_frame's):
    # exact for the fixed-width schemes, the block prefix for quant,
    # whole bounded pairs for topk. Rejected lanes may hold garbage
    # element counts, so the arithmetic runs on a masked copy.
    plen = lens - np.where(isv2, HEADER2_NBYTES, HEADER_NBYTES)
    se = np.where(ok, elems_u, 0).astype(np.int64)
    st = ((tag == _TAG_F32) & (plen == se * 4))
    st |= (tag == _TAG_BF16) & (plen == se * 2)
    st |= ((tag == _TAG_INT8) | (tag == _TAG_INT4)) & (plen >= 4)
    st |= ((tag == _TAG_TOPK) & (plen % _PAIR.itemsize == 0)
           & (plen // _PAIR.itemsize <= se))
    ok &= st

    # -- pass 2: per-frame CRC on zero-copy payload slices --
    crc_hdr = np.where(
        isv2,
        hdr[:, 16:20].copy().view(">u4").reshape(k),
        hdr[:, 12:16].copy().view(">u4").reshape(k),
    )
    off = np.where(isv2, HEADER2_NBYTES, HEADER_NBYTES)
    payloads = [None] * k
    idx_ok = np.flatnonzero(ok)
    for i in idx_ok:
        payloads[i] = memoryview(bufs[i])[int(off[i]):]

    def _crc_ok(i):
        seed = zlib.crc32(_EPOCH.pack(int(epoch_u[i]))) if isv2[i] else 0
        return zlib.crc32(payloads[i], seed) == int(crc_hdr[i])

    nthr = ingest_threads()
    if nthr > 1 and idx_ok.size >= 2 * nthr:
        passed = list(_crc_pool(nthr).map(_crc_ok, idx_ok))
    else:
        passed = [_crc_ok(i) for i in idx_ok]
    for p, i in zip(passed, idx_ok):
        if not p:
            ok[i] = False

    # Quant structural prescreen (integer math only): the block prefix
    # and the exact payload length _checked_quant enforces, per frame,
    # so run grouping below can key on a trusted block.
    blocks = np.zeros(k, np.int64)
    for i in np.flatnonzero(ok & ((tag == _TAG_INT8) | (tag == _TAG_INT4))):
        e = int(elems_u[i])
        b = int.from_bytes(bytes(payloads[i][:4]), "little")
        nblocks = -(-e // b) if (b >= 1 and e) else 0
        cn = e if tag[i] == _TAG_INT8 else (e + 1) // 2
        if (b < 1 or b > max(e, 1)
                or int(plen[i]) != 4 + nblocks * 4 + cn):
            ok[i] = False
        else:
            blocks[i] = b

    # -- pass 3: run-grouped semantic checks + slab dequant --
    results = [None] * k
    fails = list(np.flatnonzero(~ok))
    i = 0
    while i < k:
        if not ok[i]:
            i += 1
            continue
        t = int(tag[i])
        e = int(elems_u[i])
        blk = int(blocks[i])
        j = i + 1
        while (j < k and ok[j] and int(tag[j]) == t
               and int(elems_u[j]) == e and int(blocks[j]) == blk):
            j += 1
        run = list(range(i, j))
        m = len(run)
        if t == _TAG_F32:
            for r in run:
                out2d[r, :e] = np.frombuffer(payloads[r], np.float32)
                results[r] = e
        elif t == _TAG_BF16:
            for r in run:
                np.left_shift(
                    np.frombuffer(payloads[r], np.uint16), np.uint32(16),
                    out=out2d[r, :e].view(np.uint32), dtype=np.uint32,
                    casting="unsafe",
                )
                results[r] = e
        elif t in (_TAG_INT8, _TAG_INT4):
            nblocks = -(-e // blk) if e else 0
            cn = e if t == _TAG_INT8 else (e + 1) // 2
            scales2d = np.empty((m, nblocks), np.float32)
            raw2d = np.empty((m, cn), np.uint8)
            for q, r in enumerate(run):
                scales2d[q] = np.frombuffer(
                    payloads[r], "<f4", count=nblocks, offset=4
                )
                raw2d[q] = np.frombuffer(
                    payloads[r], np.uint8, count=cn, offset=4 + nblocks * 4
                )
            bad = ~(np.isfinite(scales2d).all(axis=1)
                    & (scales2d >= 0).all(axis=1))
            if t == _TAG_INT8:
                codes2d = raw2d.view(np.int8)
                bad |= (codes2d == -128).any(axis=1)
                cf = codes2d.astype(np.float32)
            else:
                nib2d = np.empty((m, cn * 2), np.uint8)
                nib2d[:, 0::2] = raw2d & 0x0F
                nib2d[:, 1::2] = raw2d >> 4
                nib2d = nib2d[:, :e]
                bad |= (nib2d == 0).any(axis=1)
                cf = (nib2d.astype(np.int16) - 8).astype(np.float32)
            # Broadcast the per-block scales to per-element and multiply
            # the whole slab at once — elementwise-identical operands to
            # _dequant/decode_into's per-block multiplies, so the rows
            # are bitwise-equal (IEEE multiply is deterministic per
            # element; the grouping changes nothing).
            sc = np.repeat(scales2d, blk, axis=1)[:, :e] if e else \
                np.empty((m, 0), np.float32)
            np.multiply(cf, sc, out=cf)
            if not bad.any():
                out2d[i:j, :e] = cf
                for r in run:
                    results[r] = e
            else:
                for q, r in enumerate(run):
                    if bad[q]:
                        fails.append(r)
                    else:
                        out2d[r, :e] = cf[q]
                        results[r] = e
        else:  # _TAG_TOPK — scatter is inherently per-row
            for r in run:
                try:
                    pairs = _checked_pairs(payloads[r], e)
                except WireError:
                    fails.append(r)
                    continue
                dst = out2d[r, :e]
                dst[...] = 0.0
                dst[pairs["i"].astype(np.int64)] = pairs["v"]
                results[r] = e
        i = j

    # Every reject re-runs the per-frame path for its error: identical
    # text, identical validation order, target row provably untouched —
    # and if the screen ever under-accepts (it should be exact), the
    # frame simply decodes here instead of raising, keeping the batch
    # path semantics-preserving rather than semantics-approximating.
    for r in fails:
        try:
            results[r] = decode_into(bufs[r], out2d[r], **pins)
        except WireError as err:
            results[r] = err
    return results


def frame_plane(buf):
    """The plane tag of a typed frame's header (0 for pre-plane frames);
    raises WireError on anything too short to carry a header. Reads the
    spare high nibble only — it does NOT validate the payload (the full
    ``decode`` does), so byte-accounting consumers can label a frame's
    plane without paying the CRC."""
    if len(buf) < HEADER_NBYTES:
        raise WireError(
            f"truncated frame: {len(buf)} bytes is shorter than the "
            f"{HEADER_NBYTES}-byte header"
        )
    magic, ver, tag, _, _ = _HDR.unpack_from(buf)
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    return tag >> 4


def frame_epoch(buf):
    """The membership-epoch stamp of a typed frame's header, or None
    for a version-1 (pre-epoch) frame; raises WireError on a short
    header, bad magic, or unknown version. Header-only like
    ``frame_plane`` — the stamp is unvalidated against any view until
    ``decode``/``decode_into`` pins it with ``expect_epoch`` (which
    also proves it under the CRC), so this is strictly a labelling
    read: a directory deciding whether to even attempt a decode, a
    byte-accounting consumer tagging rejects per epoch."""
    if len(buf) < HEADER_NBYTES:
        raise WireError(
            f"truncated frame: {len(buf)} bytes is shorter than the "
            f"{HEADER_NBYTES}-byte header"
        )
    magic, ver, _, _, _ = _HDR.unpack_from(buf)
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if ver == _VERSION:
        return None
    if ver != _VERSION_EPOCH:
        raise WireError(f"unsupported wire version {ver}")
    if len(buf) < HEADER2_NBYTES:
        raise WireError(
            f"truncated frame: {len(buf)} bytes is shorter than the "
            f"{HEADER2_NBYTES}-byte epoch-stamped header"
        )
    return int(_HDR2.unpack_from(buf)[4])


def frame_scheme(buf):
    """The payload scheme name of a typed frame's header ("f32", "bf16",
    "int8", "int4", "topk"); raises WireError on a short header, bad
    magic, or unknown low-nibble tag. Like ``frame_plane`` this reads
    the header only — byte-accounting consumers label a frame's scheme
    without paying the CRC."""
    if len(buf) < HEADER_NBYTES:
        raise WireError(
            f"truncated frame: {len(buf)} bytes is shorter than the "
            f"{HEADER_NBYTES}-byte header"
        )
    magic, ver, tag, _, _ = _HDR.unpack_from(buf)
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    tag &= 0x0F
    if tag not in _TAG_NAME:
        raise WireError(f"unknown dtype tag {tag}")
    return _TAG_NAME[tag]


def frame_elems(buf):
    """The CLAIMED dense element count of a typed frame's header;
    raises WireError on a short header or bad magic. Header-only like
    ``frame_plane`` — the claim is unvalidated (a sparse frame's count
    is a bare sender assertion until ``decode``/``decode_into`` pins or
    bounds it), so this is strictly a SIZING hint: consumers use it to
    right-size a reusable scratch target, clamped to their own bound,
    and let the full decode reject an over-claiming frame before any
    write."""
    if len(buf) < HEADER_NBYTES:
        raise WireError(
            f"truncated frame: {len(buf)} bytes is shorter than the "
            f"{HEADER_NBYTES}-byte header"
        )
    magic, _, _, elems, _ = _HDR.unpack_from(buf)
    if magic != _MAGIC:
        raise WireError(f"bad magic {magic!r}")
    return int(elems)


def frame_nbytes(elems, dtype=None, *, k=None, block=QUANT_BLOCK,
                 epoch=False):
    """Total wire bytes of an ``elems``-element frame at ``dtype`` —
    the bench/telemetry accounting twin of ``encode``. For ``"topk"``,
    ``k`` is the kept-pair count (default: the GARFIELD_WIRE_TOPK
    divisor's ``topk_k``, falling back to DEFAULT_TOPK_DIV).
    ``epoch=True`` accounts the v2 epoch-stamped header (+4 bytes)."""
    dtype = wire_dtype() if dtype is None else dtype
    elems = int(elems)
    hdr = HEADER2_NBYTES if epoch else HEADER_NBYTES
    if dtype in ("f32", "bf16"):
        return hdr + elems * (2 if dtype == "bf16" else 4)
    if dtype in ("int8", "int4"):
        nblocks = -(-elems // int(block)) if elems else 0
        codes = elems if dtype == "int8" else (elems + 1) // 2
        return hdr + 4 + nblocks * 4 + codes
    if dtype == "topk":
        if k is None:
            k = topk_k(elems, wire_topk() or DEFAULT_TOPK_DIV)
        return hdr + int(k) * _PAIR.itemsize
    raise ValueError(f"unknown wire dtype {dtype!r}")


class ErrorFeedback:
    """Host-side error-feedback accumulators, one residual per key.

    Compressed SGD with a biased compressor (quantization, top-k)
    diverges unless the compression error is fed back into the next
    step's signal (Karimireddy et al., EF-SGD): the sender transmits
    ``C(g + e)`` and keeps ``e' = (g + e) - dequant(C(g + e))``. The
    cluster roles key the accumulator per PLANE — every frame is
    broadcast byte-identical to all peers, so per sender x plane is the
    full resolution ("per peer x plane" collapses to it; a per-LINK
    residual would let the same process drift different totals to
    different receivers).

    Error feedback applies to the GRADIENT plane's additive head segment
    only. Model/gossip broadcasts are absolute state, not an additive
    signal — accumulating their quantization error would smear stale
    parameters into fresh ones (DESIGN.md §20) — and the BN-stats tail
    of a ``[grad || stats]`` frame is robust-stats input, shipped dense.

    RESTART SEMANTICS (documented, not silent): the host accumulator is
    rebuilt at zero when a cluster role restarts — the residual is a
    bounded one-step correction (||e|| <= the per-step compression
    error), so dropping it costs one step of compensation, not
    convergence. Bitwise-reproducible resume lives on the in-graph twin
    (parallel/compress.py), whose residual rides ``TrainState`` through
    checkpoints; the cluster role logs the rebuild via its startup
    banner so a resumed run's telemetry shows the reset.
    """

    def __init__(self):
        self._resid = {}

    def compensate(self, key, vec, *, upto=None):
        """``vec + residual[key]`` over ``[0, upto)`` (default: all of
        ``vec``); returns a fresh f32 array. Shape changes (a different
        model) reset the key's residual to zero loudly-by-construction:
        the stale residual is discarded, not broadcast-added."""
        vec = np.ascontiguousarray(np.asarray(vec).reshape(-1), np.float32)
        e = self._resid.get(key)
        upto = vec.size if upto is None else int(upto)
        out = vec.copy()
        if e is not None and e.size == upto:
            out[:upto] += e
        return out

    def update(self, key, compensated, decoded, *, upto=None):
        """Store ``compensated - decoded`` over ``[0, upto)`` as the
        key's next residual. ``decoded`` must be the receiver-side
        dequantization of the frame actually sent (a full codec round
        trip), so the residual is exactly the error every peer saw."""
        upto = compensated.size if upto is None else int(upto)
        self._resid[key] = (
            compensated[:upto] - decoded[:upto]
        ).astype(np.float32)

    def residual_norm(self, key):
        """L2 norm of the key's residual (0.0 when absent) — the
        telemetry ``ef_residual_norm`` field on the ``wire`` event."""
        e = self._resid.get(key)
        return float(np.linalg.norm(e)) if e is not None else 0.0

    def total_norm(self):
        """L2 norm over ALL keys' residuals — the role-level
        ``ef_residual_norm`` a WireStats flush reports."""
        sq = sum(
            float(np.sum(e.astype(np.float64) ** 2))
            for e in self._resid.values()
        )
        return float(np.sqrt(sq))

"""Typed wire codec (utils/wire.py) + its cluster integration.

Codec robustness IS Byzantine robustness on the host plane: a Byzantine
PROCESS controls its wire bytes, so the codec's reject surface (magic /
version / dtype tag / element count / crc) is the ban evidence the
quorum paths act on. The fuzz test is the core guarantee: NO corrupted
frame ever decodes — it gets its sender excluded exactly like the old
wrong-length frame did.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from garfield_tpu.utils import wire


# --- pure codec (no native / jax dependency) --------------------------------


def test_f32_roundtrip_exact_and_payload_byte_identical():
    """f32 wire must keep trajectory parity with the pre-codec format:
    the payload after the 16-byte header is the exact ``tobytes()``."""
    rng = np.random.default_rng(0)
    v = rng.standard_normal(999).astype(np.float32)
    frame = wire.encode(v, "f32")
    assert frame[wire.HEADER_NBYTES:] == v.tobytes()
    assert len(frame) == wire.frame_nbytes(v.size, "f32")
    out = wire.decode(frame)
    assert out.dtype == np.float32
    np.testing.assert_array_equal(out, v)


def test_bf16_roundtrip_within_cast_tolerance():
    rng = np.random.default_rng(1)
    v = (rng.standard_normal(2048) * 10.0 ** rng.integers(
        -6, 6, 2048
    )).astype(np.float32)
    frame = wire.encode(v, "bf16")
    assert len(frame) == wire.frame_nbytes(v.size, "bf16")
    out = wire.decode(frame)
    rel = np.abs(out - v) / np.maximum(np.abs(v), 1e-30)
    assert rel.max() <= 2.0 ** -8  # bf16 has 8 mantissa bits

    # Specials survive (the lie attack at cohort=1 publishes NaN — the
    # reference's emergent behavior must not be laundered by the wire).
    specials = np.array([np.nan, np.inf, -np.inf, 0.0, -0.0], np.float32)
    out = wire.decode(wire.encode(specials, "bf16"))
    assert np.isnan(out[0]) and np.isposinf(out[1]) and np.isneginf(out[2])
    assert out[3] == 0.0 and out[4] == 0.0


def test_bf16_matches_xla_convert():
    """The host cast must equal XLA's f32->bf16 convert (round-to-nearest-
    even): a host-decoded gradient is bit-equal to what the on-mesh bf16
    pipeline would have produced for the same value."""
    jnp = pytest.importorskip("jax.numpy")
    rng = np.random.default_rng(2)
    v = rng.standard_normal(4096).astype(np.float32)
    host = wire.decode(wire.encode(v, "bf16"))
    xla = np.asarray(jnp.asarray(v).astype(jnp.bfloat16).astype(jnp.float32))
    np.testing.assert_array_equal(host, xla)


def test_plane_tag_round_trip():
    """Schema v6: the plane tag rides the dtype byte's spare high nibble
    — plane 0 frames are byte-identical to the pre-plane format, any
    plane decodes to the same values, and frame_plane reads the tag
    without paying the CRC."""
    v = np.arange(16, dtype=np.float32)
    for dtype in wire.WIRE_DTYPES:
        base = wire.encode(v, dtype)
        assert wire.encode(v, dtype, plane=0) == base  # byte-identical
        for plane in (0, 1, 2, wire.MAX_PLANE):
            frame = wire.encode(v, dtype, plane=plane)
            assert wire.frame_plane(frame) == plane
            np.testing.assert_array_equal(
                wire.decode(frame), wire.decode(base)
            )
    with pytest.raises(ValueError):
        wire.encode(v, "f32", plane=wire.MAX_PLANE + 1)
    with pytest.raises(wire.WireError):
        wire.frame_plane(b"short")
    with pytest.raises(wire.WireError):
        wire.frame_plane(b"XX" + b"\0" * 14)  # bad magic


def test_plane_capacity_guard_boundary():
    """ISSUE 13 satellite: the plane/shard tag has exactly
    ``MAX_PLANE + 1`` values — the boundary encodes, one past it fails
    loudly at publish/encode time (named capacity in the message), and
    non-integral tags are rejected instead of int()-truncated into a
    foreign shard's nibble."""
    v = np.ones(4, np.float32)
    frame = wire.encode(v, plane=wire.MAX_PLANE)  # boundary: fine
    assert wire.frame_plane(frame) == wire.MAX_PLANE
    with pytest.raises(ValueError, match="nibble"):
        wire.encode(v, plane=wire.MAX_PLANE + 1)
    with pytest.raises(ValueError, match="nibble"):
        wire.encode(v, plane=-1)
    with pytest.raises(TypeError):
        wire.encode(v, plane=2.5)
    with pytest.raises(TypeError):
        wire.encode(v, plane=True)
    assert wire.check_plane(np.int64(3)) == 3  # numpy ints are integral


def test_decode_expect_plane_rejects_cross_shard_frames():
    """DESIGN.md §19: a shard consumer decoding with ``expect_plane``
    rejects a frame stamped for any other shard as a WireError — the
    stamp is under the sender's CRC, so the mismatch is attributable
    ban evidence, never a silent mis-fold."""
    v = np.arange(8, dtype=np.float32)
    f1 = wire.encode(v, plane=1)
    np.testing.assert_array_equal(wire.decode(f1, expect_plane=1), v)
    with pytest.raises(wire.WireError, match="cross-shard"):
        wire.decode(f1, expect_plane=0)
    # expect_plane itself is capacity-guarded.
    with pytest.raises(ValueError):
        wire.decode(f1, expect_plane=16)


def test_wire_dtype_env(monkeypatch):
    monkeypatch.delenv("GARFIELD_WIRE_DTYPE", raising=False)
    assert wire.wire_dtype() == "f32"
    monkeypatch.setenv("GARFIELD_WIRE_DTYPE", "bf16")
    assert wire.wire_dtype() == "bf16"
    v = np.ones(4, np.float32)
    assert len(wire.encode(v)) == wire.frame_nbytes(4, "bf16")
    monkeypatch.setenv("GARFIELD_WIRE_DTYPE", "f16")
    with pytest.raises(ValueError):
        wire.wire_dtype()


def test_fuzz_corrupted_frames_never_decode():
    """Every single-bit flip and every truncation of a valid frame must
    raise WireError — corrupted bytes can NEVER reach a GAR — EXCEPT the
    four plane-tag bits (the dtype byte's spare high nibble, schema v6):
    a flip there only relabels the frame's plane, and the decode must
    return the IDENTICAL values (the payload is untouched and
    crc-verified), so nothing corrupted can reach a GAR through that
    nibble either. (A payload flip breaks the crc; any other header flip
    breaks magic/version/tag/length; a truncation breaks the length
    contract.)

    Round 18: the fuzz runs over EVERY payload scheme (int8/int4/topk
    included), decoding as the cluster consumer does — with
    ``expect_elems`` — because a sparse frame's dense size is a bare
    header claim the payload cannot corroborate (an ``elems`` bit flip
    on a topk frame passes every structural check and the CRC, and
    without the pin would scatter into a wrong-sized or multi-GB zeros
    vector)."""
    rng = np.random.default_rng(3)
    v = rng.standard_normal(257).astype(np.float32)
    # dtype byte = header byte 3 ("!2sBBQI"); its high nibble is the
    # plane tag.
    plane_bits = {3 * 8 + b for b in (4, 5, 6, 7)}
    for dtype in wire.WIRE_SCHEMES:
        frame = wire.encode(v, dtype)
        baseline = wire.decode(frame)
        # exhaustive over the header, random over the payload
        bits = list(range(wire.HEADER_NBYTES * 8)) + list(
            rng.integers(wire.HEADER_NBYTES * 8, len(frame) * 8, 400)
        )
        for bit in bits:
            ba = bytearray(frame)
            ba[bit // 8] ^= 1 << (bit % 8)
            if bit in plane_bits:
                np.testing.assert_array_equal(
                    wire.decode(bytes(ba)), baseline
                )
                assert wire.frame_plane(bytes(ba)) != 0
                continue
            with pytest.raises(wire.WireError):
                wire.decode(bytes(ba), expect_elems=v.size)
        for cut in list(range(0, wire.HEADER_NBYTES + 2)) + list(
            rng.integers(0, len(frame), 60)
        ):
            with pytest.raises(wire.WireError):
                wire.decode(frame[:int(cut)], expect_elems=v.size)
        with pytest.raises(wire.WireError):
            # trailing garbage
            wire.decode(frame + b"x", expect_elems=v.size)
    with pytest.raises(wire.WireError):
        wire.decode(b"")  # the SSMW stop sentinel must not decode


def test_fuzz_dense_schemes_self_validate_without_expect_elems():
    """The PR 4 contract stands on its own for the dense/quantized
    schemes: every non-plane header flip and truncation rejects WITHOUT
    ``expect_elems`` (payload length corroborates the element count).
    The sparse scheme is the documented exception — covered above with
    the pin and below by the forged-elems test."""
    rng = np.random.default_rng(7)
    v = rng.standard_normal(129).astype(np.float32)
    plane_bits = {3 * 8 + b for b in (4, 5, 6, 7)}
    for dtype in wire.WIRE_DTYPES:
        frame = wire.encode(v, dtype)
        for bit in range(wire.HEADER_NBYTES * 8):
            if bit in plane_bits:
                continue
            ba = bytearray(frame)
            ba[bit // 8] ^= 1 << (bit % 8)
            with pytest.raises(wire.WireError):
                wire.decode(bytes(ba))


# --- round 18: compressed schemes (int8 / int4 / topk) -----------------------


def _forge(tag, elems, payload, plane=0):
    """A CRC-valid frame with arbitrary payload bytes — what a Byzantine
    sender (who controls its wire bytes, CRC included) can actually
    produce. The semantic rejects below must fire AFTER the CRC passes:
    that ordering is what makes them attributable ban evidence."""
    import struct
    import zlib

    return struct.pack(
        "!2sBBQI", b"GW", 1, (plane << 4) | tag, elems,
        zlib.crc32(payload),
    ) + payload


def _topk_payload(idx, val):
    pairs = np.empty(len(idx), np.dtype([("i", "<u4"), ("v", "<f4")]))
    pairs["i"] = idx
    pairs["v"] = val
    return pairs.tobytes()


def test_int8_roundtrip_error_bound_and_nbytes():
    rng = np.random.default_rng(10)
    v = (rng.standard_normal(3000) * 3).astype(np.float32)
    frame = wire.encode(v, "int8")
    assert len(frame) == wire.frame_nbytes(v.size, "int8")
    out = wire.decode(frame)
    # Linear grid: per-block max error <= scale / 2 = max|block| / 254.
    for b in range(0, v.size, wire.QUANT_BLOCK):
        blk = v[b:b + wire.QUANT_BLOCK]
        bound = np.abs(blk).max() / 127 / 2 + 1e-7
        assert np.abs(out[b:b + wire.QUANT_BLOCK] - blk).max() <= bound
    # Zero vector: zero scale, exact roundtrip.
    z = wire.decode(wire.encode(np.zeros(100, np.float32), "int8"))
    np.testing.assert_array_equal(z, np.zeros(100))


def test_int4_roundtrip_error_bound_and_padding():
    rng = np.random.default_rng(11)
    for n in (7, 8, 257):  # odd sizes exercise the pad nibble
        v = rng.standard_normal(n).astype(np.float32)
        frame = wire.encode(v, "int4")
        assert len(frame) == wire.frame_nbytes(n, "int4")
        out = wire.decode(frame)
        bound = np.abs(v).max() / 7 / 2 + 1e-6
        assert np.abs(out - v).max() <= bound


def test_topk_roundtrip_keeps_largest_and_dense_tail():
    rng = np.random.default_rng(12)
    v = rng.standard_normal(1000).astype(np.float32)
    k = 50
    frame = wire.encode(v, "topk", k=k)
    assert len(frame) == wire.frame_nbytes(v.size, "topk", k=k)
    out = wire.decode(frame)
    kept = np.flatnonzero(out)
    assert kept.size == k
    # The kept coordinates are exactly the k largest magnitudes.
    top = np.sort(np.argpartition(np.abs(v), v.size - k)[v.size - k:])
    np.testing.assert_array_equal(kept, top)
    np.testing.assert_array_equal(out[kept], v[kept])
    # keep_from: the stats tail (BatchNorm segment) always rides along.
    tail_frame = wire.encode(v, "topk", k=10, keep_from=990)
    out = wire.decode(tail_frame)
    np.testing.assert_array_equal(out[990:], v[990:])
    assert np.flatnonzero(out[:990]).size == 10


def test_quantized_encode_rejects_non_finite_loudly():
    """Honest-sender loud failure: a NaN/inf input would produce a
    non-finite scale — indistinguishable on the wire from a Byzantine
    frame — so encode raises a plain ValueError (NOT WireError: there is
    no frame, and nobody to ban) instead of shipping it."""
    bad = np.array([1.0, np.nan, 2.0], np.float32)
    for scheme in ("int8", "int4", "topk"):
        with pytest.raises(ValueError) as ei:
            wire.encode(bad, scheme)
        assert not isinstance(ei.value, wire.WireError)
    inf = np.array([1.0, np.inf], np.float32)
    with pytest.raises(ValueError):
        wire.encode(inf, "int8")
    # bf16/f32 still pass specials through (the NaN-laundering pin in
    # test_bf16_roundtrip_within_cast_tolerance).
    wire.encode(bad, "f32")


def test_quantized_scale_range_rejected_post_crc():
    """The ISSUE's scale gate: CRC-valid frames whose carried scale is
    non-finite or negative reject as WireError with .nbytes — the
    attributable Byzantine case (only the sender makes those bytes)."""
    v = np.ones(8, np.float32)
    honest = wire.encode(v, "int8")
    head = honest[:wire.HEADER_NBYTES]
    payload = bytearray(honest[wire.HEADER_NBYTES:])
    for evil_scale in (np.inf, -np.inf, np.nan, -1.0):
        p = bytearray(payload)
        p[4:8] = np.float32(evil_scale).tobytes()
        frame = _forge(2, v.size, bytes(p))
        with pytest.raises(wire.WireError, match="scale"):
            wire.decode(frame)
    del head
    # block = 0 in the payload prefix: division bomb, rejected by name.
    p = bytearray(payload)
    p[0:4] = np.zeros(1, "<u4").tobytes()
    with pytest.raises(wire.WireError, match="block"):
        wire.decode(_forge(2, v.size, bytes(p)))


def test_int4_nibble_zero_rejected():
    """Nibble 0 is outside the biased [1, 15] grid — unreachable by any
    honest encoder, so its presence is ban evidence, not a value."""
    v = np.ones(4, np.float32)
    honest = wire.encode(v, "int4")
    payload = bytearray(honest[wire.HEADER_NBYTES:])
    payload[-1] &= 0xF0  # zero the low nibble of the last code byte
    with pytest.raises(wire.WireError, match="nibble"):
        wire.decode(_forge(3, v.size, bytes(payload)))


def test_quantized_block_bomb_rejected_post_crc():
    """A CRC-valid int8/int4 frame whose u32 block prefix dwarfs the
    element count passes every length check (nblocks is 1 either way)
    but would pad the dequant to nblocks*block f32 elements — ~17 GB at
    block=0xFFFFFFFF — a receiver-side allocation bomb from an
    attributable frame. The decoder bounds block by the element count
    BEFORE dequantizing; honest encoders clamp, so every honest frame
    sits inside the bound."""
    v = np.ones(8, np.float32)
    for scheme, tag in (("int8", 2), ("int4", 3)):
        honest = wire.encode(v, scheme)
        # The honest frame's block prefix is clamped to the vector.
        pfx = np.frombuffer(honest[wire.HEADER_NBYTES:], "<u4", count=1)
        assert int(pfx[0]) == v.size
        payload = bytearray(honest[wire.HEADER_NBYTES:])
        payload[0:4] = np.array([0xFFFFFFFF], "<u4").tobytes()
        with pytest.raises(wire.WireError, match="block"):
            wire.decode(_forge(tag, v.size, bytes(payload)))
        # One past the element count is already out.
        payload[0:4] = np.array([v.size + 1], "<u4").tobytes()
        with pytest.raises(wire.WireError, match="block"):
            wire.decode(_forge(tag, v.size, bytes(payload)))


def test_int8_code_minus_128_rejected():
    """encode clips int8 codes to the symmetric [-127, 127] grid, so a
    -128 byte is unreachable by any honest encoder — the same
    'invalid content = attributable ban evidence' contract as int4's
    nibble 0 (which already rejects)."""
    v = np.ones(4, np.float32)
    honest = wire.encode(v, "int8")
    payload = bytearray(honest[wire.HEADER_NBYTES:])
    payload[-1] = 0x80  # last code byte -> -128
    with pytest.raises(wire.WireError, match="-128"):
        wire.decode(_forge(2, v.size, bytes(payload)))


def test_topk_k_zero_ships_dense_tail_only():
    """An explicit k=0 is a clean edge, not a numpy argpartition bomb:
    no head pairs ride — only the always-kept dense tail (if any)."""
    v = np.arange(1.0, 11.0, dtype=np.float32)
    frame = wire.encode(v, "topk", k=0)
    assert len(frame) == wire.HEADER_NBYTES  # zero pairs
    np.testing.assert_array_equal(wire.decode(frame), np.zeros(10))
    tail = wire.encode(v, "topk", k=0, keep_from=8)
    out = wire.decode(tail)
    np.testing.assert_array_equal(out[8:], v[8:])
    assert np.flatnonzero(out[:8]).size == 0


def test_decode_max_elems_bounds_sparse_claims():
    """``max_elems``: the inexact consumer pin for variable-size frames
    (the federated shard plane's whole-number-of-rows frames). A sparse
    header claiming 2^40 elements rejects before the scatter allocates;
    honest frames inside the bound pass, for every scheme."""
    payload = _topk_payload([0, 1], [1.0, 2.0])
    with pytest.raises(wire.WireError, match="bound"):
        wire.decode(_forge(4, 2 ** 40, payload), max_elems=1 << 20)
    assert wire.decode(_forge(4, 16, payload), max_elems=16).size == 16
    v = np.ones(16, np.float32)
    for scheme in wire.WIRE_SCHEMES:
        assert wire.decode(wire.encode(v, scheme), max_elems=64).size == 16
        with pytest.raises(wire.WireError, match="bound"):
            wire.decode(wire.encode(v, scheme), max_elems=15)


def test_sparse_index_attacks_rejected_post_crc():
    """Every malformed-sparse shape the ISSUE names, as CRC-valid forged
    frames: duplicate index (double-count), descending index, index out
    of bounds, more pairs than elems, and a non-whole-pair payload. All
    WireError; the quorum path stamps .nbytes (integration test below)."""
    cases = [
        (_topk_payload([3, 3, 5], [1, 2, 3]), "increasing"),   # duplicate
        (_topk_payload([5, 3, 7], [1, 2, 3]), "increasing"),   # descending
        (_topk_payload([0, 2, 16], [1, 2, 3]), "bounds"),      # oob last
        (_topk_payload(range(17), np.ones(17)), "pairs"),      # k > elems
        (_topk_payload([0, 1], [1, 2])[:-3], "pairs"),         # ragged
    ]
    for payload, msg in cases:
        with pytest.raises(wire.WireError, match=msg):
            wire.decode(_forge(4, 16, payload))
    # Monotonicity + in-bounds LAST index suffices: any strictly
    # increasing sequence with an out-of-bounds middle element must have
    # an out-of-bounds last element too.
    ok = wire.decode(_forge(4, 16, _topk_payload([0, 7, 15], [1, 2, 3])))
    np.testing.assert_array_equal(np.flatnonzero(ok), [0, 7, 15])


def test_sparse_elems_claim_pinned_by_consumer():
    """A sparse frame's dense size is a bare header claim (the pairs are
    consistent with ANY larger elems): an honestly-CRC'd frame claiming
    2^40 elements must reject on the consumer's ``expect_elems`` pin
    BEFORE the scatter allocates a 4 TB zeros vector."""
    payload = _topk_payload([0, 1], [1.0, 2.0])
    giant = _forge(4, 2 ** 40, payload)
    with pytest.raises(wire.WireError, match="expected"):
        wire.decode(giant, expect_elems=16)
    # Dense consumers get the same pin for free (belt over the length
    # check) — and honest frames pass it.
    v = np.ones(16, np.float32)
    for scheme in wire.WIRE_SCHEMES:
        out = wire.decode(wire.encode(v, scheme), expect_elems=16)
        assert out.size == 16
        with pytest.raises(wire.WireError):
            wire.decode(wire.encode(v, scheme), expect_elems=17)


def test_unknown_low_nibble_tags_reject_loudly():
    """Forward/backward compat: tags 5..15 are unassigned — a frame
    stamped with one rejects by name on THIS decoder (and tags 2/3/4
    reject identically on a PR 4 decoder, which knew only 0/1), so a
    mixed-version deployment fails loudly instead of misinterpreting
    payload bytes."""
    for tag in range(5, 16):
        with pytest.raises(wire.WireError, match="tag"):
            wire.decode(_forge(tag, 4, b"\x00" * 16))
        with pytest.raises(wire.WireError, match="tag"):
            wire.frame_scheme(_forge(tag, 4, b""))


def test_f32_bf16_golden_frames_unchanged():
    """Backward-compat pin: the PR 4 wire format for f32/bf16 is frozen
    byte-for-byte — adding the compressed tags must not move a single
    bit of the dense frames (a mixed-version fleet keeps interoperating
    on the dense schemes)."""
    v = np.array([0.0, 1.0, -2.5], np.float32)
    f32 = wire.encode(v, "f32")
    assert f32.hex() == (
        "47570100"              # "GW", ver 1, tag 0 (f32, plane 0)
        "0000000000000003"      # elems = 3 (big-endian u64)
        "48f41bf2"              # crc32 of the payload below
        "000000000000803f0000"  # 0.0f, 1.0f, -2.5f little-endian
        "20c0"
    )
    bf16 = wire.encode(v, "bf16")
    assert bf16.hex() == (
        "47570101" "0000000000000003" "7d4c5327"
        "0000803f20c0"          # bf16 halves of the same three values
    )
    assert wire.frame_scheme(f32) == "f32"
    assert wire.frame_scheme(bf16) == "bf16"


def test_frame_scheme_reads_all_tags():
    v = np.ones(8, np.float32)
    for scheme in wire.WIRE_SCHEMES:
        assert wire.frame_scheme(wire.encode(v, scheme)) == scheme
    with pytest.raises(wire.WireError):
        wire.frame_scheme(b"short")


def test_topk_env_divisor_and_topk_k(monkeypatch):
    monkeypatch.delenv("GARFIELD_WIRE_TOPK", raising=False)
    assert wire.wire_topk() == 0
    monkeypatch.setenv("GARFIELD_WIRE_TOPK", "32")
    assert wire.wire_topk() == 32
    v = np.arange(1, 101, dtype=np.float32)
    frame = wire.encode(v, "topk")  # k = ceil(100/32) = 4 from the env
    assert np.flatnonzero(wire.decode(frame)).size == 4
    monkeypatch.setenv("GARFIELD_WIRE_TOPK", "-1")
    with pytest.raises(ValueError):
        wire.wire_topk()
    monkeypatch.setenv("GARFIELD_WIRE_TOPK", "x")
    with pytest.raises(ValueError):
        wire.wire_topk()
    assert wire.topk_k(100, 32) == 4
    assert wire.topk_k(0, 32) == 0
    assert wire.topk_k(1, 1000) == 1
    with pytest.raises(ValueError):
        wire.topk_k(100, 0)


def test_error_feedback_accumulator():
    """EF-SGD's host accumulator: on a CONSTANT signal the residual makes
    the mean sent value converge to the signal exactly (the bias a bare
    quantizer keeps forever); a residual of the wrong size (model resize
    / restart) is discarded, not misapplied."""
    ef = wire.ErrorFeedback()
    signal = np.full(64, 0.01, np.float32)  # far below one int8 step
    sent_sum = np.zeros(64, np.float64)
    n = 50
    for _ in range(n):
        comp = ef.compensate(1, signal)
        frame = wire.encode(comp, "int8")
        dec = wire.decode(frame)
        ef.update(1, comp, dec)
        sent_sum += dec
    np.testing.assert_allclose(sent_sum / n, signal, rtol=1e-5)
    assert ef.residual_norm(1) >= 0
    assert ef.total_norm() == pytest.approx(ef.residual_norm(1))
    # Wrong-size (stale) residual: discarded, compensate is identity.
    other = np.ones(32, np.float32)
    np.testing.assert_array_equal(ef.compensate(1, other), other)
    # Unknown key: identity too.
    np.testing.assert_array_equal(ef.compensate(9, other), other)
    assert ef.residual_norm(9) == 0.0


def test_error_feedback_upto_leaves_tail_uncompensated():
    """``upto`` scopes EF to the additive head segment: the stats tail
    (BatchNorm running stats — state, not a gradient) must never receive
    residual corrections."""
    ef = wire.ErrorFeedback()
    vec = np.concatenate([np.full(8, 0.01), np.ones(4)]).astype(np.float32)
    comp = ef.compensate(0, vec, upto=8)
    frame = wire.encode(comp, "int8")
    dec = wire.decode(frame)
    ef.update(0, comp, dec, upto=8)
    comp2 = ef.compensate(0, vec, upto=8)
    # Head got compensation (the residual is non-zero there)...
    assert not np.array_equal(comp2[:8], vec[:8])
    # ...the tail is passed through untouched.
    np.testing.assert_array_equal(comp2[8:], vec[8:])


# --- exchange integration (native runtime required) -------------------------

pytest.importorskip("garfield_tpu.native")
from garfield_tpu import native  # noqa: E402

_HAVE_NATIVE = native.load() is not None

needs_native = pytest.mark.skipif(
    not _HAVE_NATIVE, reason="native runtime unavailable"
)


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _mesh(n, **kw):
    from garfield_tpu.utils.exchange import PeerExchange

    hosts = [f"127.0.0.1:{p}" for p in _ports(n)]
    return [PeerExchange(i, hosts, **kw) for i in range(n)]


@needs_native
def test_cross_dtype_publish_collect():
    """Mixed-width deployments interoperate: decoding is header-driven,
    never local-setting-driven — a bf16 sender and an f32 sender land in
    the same quorum."""
    rng = np.random.default_rng(4)
    v0 = rng.standard_normal(64).astype(np.float32)
    v1 = rng.standard_normal(64).astype(np.float32)

    def tf(idx, payload):
        return wire.decode(payload)

    peers = _mesh(2)
    try:
        peers[0].publish(3, wire.encode(v0, "f32"))
        peers[1].publish(3, wire.encode(v1, "bf16"))
        for p in peers:
            got = p.collect(3, q=2, timeout_ms=10_000, transform=tf)
            np.testing.assert_array_equal(got[0], v0)
            np.testing.assert_array_equal(
                got[1], wire.decode(wire.encode(v1, "bf16"))
            )
    finally:
        for p in peers:
            p.close()


@needs_native
def test_transform_error_is_stored_not_raised():
    """A transform that raises (codec reject) must surface as the peer's
    stored result — attributed ban evidence, not a missing-peer timeout."""
    from garfield_tpu.apps.cluster import _frame_transform

    peers = _mesh(2)
    try:
        tf = _frame_transform((8, 0))
        frame = bytearray(wire.encode(np.ones(8, np.float32), "f32"))
        frame[-1] ^= 0x40  # payload bit flip -> crc reject
        peers[1].publish(0, bytes(frame))
        peers[0].publish(0, wire.encode(np.zeros(8, np.float32), "f32"))
        got = peers[0].collect(0, q=2, timeout_ms=10_000, transform=tf)
        assert isinstance(got[1], wire.WireError)
        assert got[1].nbytes == len(frame)
        head, tail = got[0]
        np.testing.assert_array_equal(np.asarray(head), np.zeros(8))
        assert tail.size == 0
    finally:
        for p in peers:
            p.close()


@needs_native
def test_gradient_quorum_bans_corrupt_codec_frames():
    """The malformed-frame ban path, end to end: random bit-flipped and
    truncated codec payloads never reach the aggregation and get their
    sender excluded from all future quorums — exactly like the old
    wrong-length frame (ISSUE r8 satellite)."""
    from garfield_tpu.apps.cluster import _gradient_quorum
    from garfield_tpu.telemetry import hub as tele_hub

    d = 32
    rng = np.random.default_rng(5)
    honest = rng.standard_normal(d).astype(np.float32)
    hub = tele_hub.MetricsHub()
    prev = tele_hub.install(hub)
    peers = _mesh(3)  # 0 = PS, 1 = honest worker, 2 = Byzantine bytes
    try:
        for trial, corrupt in enumerate([
            b"\x00" * 10,                                   # garbage
            wire.encode(honest, "f32")[: wire.HEADER_NBYTES + 7],  # trunc
            bytes([b ^ (1 << rng.integers(8)) if i == 20 else b
                   for i, b in enumerate(wire.encode(honest, "bf16"))]),
        ]):
            step = trial
            peers[2].publish(step, corrupt, to=[0])
            # The honest frame arrives LATE so the q=1 quorum closes on
            # the corrupt frame first and the ban path must re-collect.
            t = threading.Timer(
                0.3, lambda s=step: peers[1].publish(
                    s, wire.encode(honest, "f32"), to=[0]
                )
            )
            t.start()
            deadline = time.time() + 10
            while peers[0]._mb.version(2) < trial + 1 and time.time() < deadline:
                time.sleep(0.02)
            got, good = _gradient_quorum(
                peers[0], step, 1, [1, 2], (d, 0),
                republish=lambda: None, timeout_ms=10_000, who="test-ps",
            )
            t.join()
            # The corrupt frame never enters the result; rank 2 is banned.
            assert good == [1]
            assert set(got) == {1}
            np.testing.assert_array_equal(np.asarray(got[1][0]), honest)
        events = [r for r in hub.records()
                  if r.get("event") == "quorum_exclusion"]
        assert events and all(e["rank"] == 2 for e in events)
    finally:
        tele_hub.uninstall()
        if prev is not None:
            tele_hub.install(prev)
        for p in peers:
            p.close()


@needs_native
def test_gradient_quorum_bans_malformed_sparse_frames():
    """Round 18: a CRC-VALID topk frame with duplicate sparse indices (a
    forged frame only its sender could produce — the Byzantine case, not
    line noise) feeds the SAME quorum-exclusion path as a CRC reject:
    never reaches the aggregation, sender banned, ``quorum_exclusion``
    telemetry attributed. Extends the PR 4 codec-reject ban surface to
    the compressed schemes' semantic checks."""
    from garfield_tpu.apps.cluster import _gradient_quorum
    from garfield_tpu.telemetry import hub as tele_hub

    d = 32
    rng = np.random.default_rng(6)
    honest = rng.standard_normal(d).astype(np.float32)
    forged = _forge(
        4, d, _topk_payload([3, 3, 9], [5.0, -5.0, 1.0]), plane=1,
    )
    assert len(forged) >= wire.HEADER_NBYTES
    hub = tele_hub.MetricsHub()
    prev = tele_hub.install(hub)
    peers = _mesh(3)  # 0 = PS, 1 = honest worker, 2 = Byzantine sender
    try:
        peers[2].publish(0, forged, to=[0])
        t = threading.Timer(
            0.3, lambda: peers[1].publish(
                0, wire.encode(honest, "f32", plane=1), to=[0]
            )
        )
        t.start()
        deadline = time.time() + 10
        while peers[0]._mb.version(2) < 1 and time.time() < deadline:
            time.sleep(0.02)
        got, good = _gradient_quorum(
            peers[0], 0, 1, [1, 2], (d, 0),
            republish=lambda: None, timeout_ms=10_000, who="test-ps",
        )
        t.join()
        assert good == [1]
        assert set(got) == {1}
        np.testing.assert_array_equal(np.asarray(got[1][0]), honest)
        events = [r for r in hub.records()
                  if r.get("event") == "quorum_exclusion"]
        assert events and all(e["rank"] == 2 for e in events)
        # The ban evidence carries the observed frame length.
        assert any(e.get("got_bytes") == len(forged) for e in events)
    finally:
        tele_hub.uninstall()
        if prev is not None:
            tele_hub.install(prev)
        for p in peers:
            p.close()


@needs_native
def test_send_queue_drop_event_emitted():
    """Publisher-side backpressure is no longer silent: overflowing a
    hung receiver's bounded sender queue emits ``send_queue_drop``
    (ISSUE r8 satellite — mirrors the receive-side ``plane_drop``)."""
    from garfield_tpu.telemetry import hub as tele_hub
    from garfield_tpu.utils.exchange import PeerExchange

    srv = socket.create_server(("127.0.0.1", 0))
    conns = []

    def sink():  # accepts, never reads: a hung (not crashed) receiver
        try:
            while True:
                conn, _ = srv.accept()
                conns.append(conn)
        except OSError:
            pass

    threading.Thread(target=sink, daemon=True).start()
    p0 = _ports(1)[0]
    hosts = [f"127.0.0.1:{p0}", f"127.0.0.1:{srv.getsockname()[1]}"]
    hub = tele_hub.MetricsHub()
    prev = tele_hub.install(hub)
    ex = PeerExchange(0, hosts, send_queue_frames=1, send_timeout_ms=2_000)
    try:
        big = b"\x00" * (8 << 20)  # 8 MB: sendall blocks on TCP buffers
        deadline = time.time() + 20
        while not hub.wire_counters()["send_queue_drops"]:
            ex.publish(0, big, to=[1])
            assert time.time() < deadline, "no send_queue_drop emitted"
            time.sleep(0.05)
        drops = [r for r in hub.records()
                 if r.get("event") == "send_queue_drop"]
        assert drops and drops[0]["peer"] == 1
    finally:
        tele_hub.uninstall()
        if prev is not None:
            tele_hub.install(prev)
        ex.close()
        srv.close()
        for c in conns:
            c.close()


@needs_native
@pytest.mark.slow
def test_exchange_bench_multiprocess():
    """The committed-record generator works end to end: a tiny
    multi-process micro grid produces parseable JSON + a schema-valid
    JSONL twin, and bf16 measures >= 1.8x fewer wire bytes/step than f32
    (the ISSUE r8 acceptance bar)."""
    import json
    import tempfile

    from garfield_tpu.apps.benchmarks import exchange_bench
    from garfield_tpu.telemetry.exporters import validate_jsonl

    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "exch.json")
        rows = exchange_bench.main([
            "--ns", "2", "--ds", "4096", "--wire", "f32", "bf16",
            "--rounds", "4", "--trials", "1", "--json", out,
        ])
        assert validate_jsonl(os.path.splitext(out)[0] + ".jsonl") == 2
        committed = json.load(open(out))
        assert committed == rows
        by_wire = {r["wire"]: r for r in rows}
        ratio = (by_wire["f32"]["wire_bytes_per_step"]
                 / by_wire["bf16"]["wire_bytes_per_step"])
        assert ratio >= 1.8, ratio
        for r in rows:
            assert r["round_s"] is None or r["round_s"] > 0


# ---------------------------------------------------------------------------
# decode_into (PR 19): the fused dequantize-into-fold entry point.


class TestDecodeInto:
    SCHEMES = ["f32", "bf16", "int8", "int4", "topk"]

    def _vec(self, n, seed=0):
        return np.random.default_rng(seed).normal(
            size=n).astype(np.float32)

    @pytest.mark.parametrize("scheme", SCHEMES)
    @pytest.mark.parametrize("n", [0, 1, 3, 1023, 1024, 1025])
    def test_bitwise_parity_with_decode(self, scheme, n):
        vec = self._vec(n, seed=n + 1)
        frame = wire.encode(vec, dtype=scheme, plane=3)
        want = wire.decode(frame, expect_plane=3, expect_elems=n)
        out = np.full(n, np.float32(np.nan))
        k = wire.decode_into(frame, out, expect_plane=3, expect_elems=n)
        assert k == n
        np.testing.assert_array_equal(out, want)

    def test_oversized_target_decodes_prefix_only(self):
        vec = self._vec(100, seed=9)
        frame = wire.encode(vec, dtype="int8")
        out = np.full(130, np.float32(7.5))
        k = wire.decode_into(frame, out)  # max_elems defaults to out.size
        assert k == 100
        np.testing.assert_array_equal(out[:100], wire.decode(frame))
        # the tail beyond the frame's claim is untouched
        np.testing.assert_array_equal(out[100:], np.float32(7.5))

    @pytest.mark.parametrize("corrupt", ["crc", "truncate", "elems",
                                         "plane", "too_small"])
    def test_errors_leave_target_untouched(self, corrupt):
        vec = self._vec(64, seed=4)
        frame = bytearray(wire.encode(vec, dtype="int8", plane=1))
        sentinel = np.full(64, np.float32(-3.25))
        out = sentinel.copy()
        kwargs = {"expect_plane": 1, "expect_elems": 64}
        if corrupt == "crc":
            frame[-1] ^= 0x55
        elif corrupt == "truncate":
            frame = frame[:20]
        elif corrupt == "elems":
            kwargs["expect_elems"] = 63
        elif corrupt == "plane":
            kwargs["expect_plane"] = 2
        else:
            out = sentinel[:10].copy()
            kwargs = {"expect_plane": 1}
        with pytest.raises(wire.WireError):
            wire.decode_into(bytes(frame), out, **kwargs)
        np.testing.assert_array_equal(out, sentinel[:out.size])

    def test_rejects_unusable_targets_loudly(self):
        frame = wire.encode(self._vec(8))
        with pytest.raises(TypeError, match="float32"):
            wire.decode_into(frame, np.zeros(8, np.float64))
        with pytest.raises(TypeError, match="1-D"):
            wire.decode_into(frame, np.zeros((2, 4), np.float32))
        ro = np.zeros(8, np.float32)
        ro.flags.writeable = False
        with pytest.raises(TypeError, match="writable"):
            wire.decode_into(frame, ro)

    def test_frame_elems_header_only_sizing(self):
        frame = wire.encode(self._vec(321), dtype="int4")
        assert wire.frame_elems(frame) == 321
        with pytest.raises(wire.WireError):
            wire.frame_elems(frame[:10])
        bad = bytearray(frame)
        bad[0] = 0x00  # break the magic
        with pytest.raises(wire.WireError):
            wire.frame_elems(bytes(bad))

    def test_wire_fused_env_knob(self, monkeypatch):
        monkeypatch.delenv("GARFIELD_WIRE_FUSED_DECODE", raising=False)
        assert wire.wire_fused() is True  # default on
        monkeypatch.setenv("GARFIELD_WIRE_FUSED_DECODE", "0")
        assert wire.wire_fused() is False
        monkeypatch.setenv("GARFIELD_WIRE_FUSED_DECODE", "on")
        assert wire.wire_fused() is True


class TestEpochStamp:
    """The v2 epoch-stamped header (round 20, DESIGN.md §22): the
    membership epoch rides every frame under an epoch-seeded CRC, so a
    consumer pinned to its directory's epoch rejects stale, future,
    pre-epoch (v1) and restamped frames as attributable ban evidence."""

    SCHEMES = ["f32", "bf16", "int8", "int4", "topk"]

    def _vec(self, n=257, seed=0):
        return np.random.default_rng(seed).normal(
            size=n).astype(np.float32)

    @pytest.mark.parametrize("scheme", SCHEMES)
    def test_roundtrip_every_scheme(self, scheme):
        vec = self._vec()
        frame = wire.encode(vec, dtype=scheme, plane=2, epoch=7)
        # +4 header bytes vs the v1 frame of the same payload.
        assert len(frame) == len(
            wire.encode(vec, dtype=scheme, plane=2)) + 4
        assert len(frame) == wire.frame_nbytes(
            vec.size, scheme, epoch=True)
        assert wire.frame_epoch(frame) == 7
        assert wire.frame_plane(frame) == 2
        want = wire.decode(wire.encode(vec, dtype=scheme))
        out = wire.decode(frame, expect_plane=2, expect_epoch=7)
        np.testing.assert_array_equal(out, want)
        # decode_into sees the same stamp
        tgt = np.zeros(vec.size, np.float32)
        assert wire.decode_into(
            frame, tgt, expect_plane=2, expect_epoch=7) == vec.size
        np.testing.assert_array_equal(tgt, want)

    def test_v1_frames_carry_no_epoch(self):
        frame = wire.encode(self._vec(), "f32")
        assert wire.frame_epoch(frame) is None
        wire.decode(frame)  # unpinned consumers accept v1 unchanged

    def test_stale_future_and_epochless_rejected(self):
        vec = self._vec()
        stale = wire.encode(vec, "int8", epoch=6)
        with pytest.raises(wire.WireError, match="stale-epoch"):
            wire.decode(stale, expect_epoch=7)
        future = wire.encode(vec, "int8", epoch=8)
        with pytest.raises(wire.WireError, match="future-epoch"):
            wire.decode(future, expect_epoch=7)
        v1 = wire.encode(vec, "int8")
        with pytest.raises(wire.WireError, match="no membership epoch"):
            wire.decode(v1, expect_epoch=7)
        # Accepted exactly at the pin.
        np.testing.assert_array_equal(
            wire.decode(stale, expect_epoch=6), wire.decode(v1))

    def test_epoch_restamp_is_crc_mismatch(self):
        """A relay rewriting the header's epoch bytes to match the
        consumer's pin still fails: the CRC is seeded with the epoch,
        so the restamped frame is a codec failure, not a valid frame
        from a newer epoch."""
        frame = bytearray(wire.encode(self._vec(), "f32", epoch=6))
        off = wire._HDR2.size - 8  # epoch u32 sits before the crc u32
        assert int.from_bytes(frame[off:off + 4], "big") == 6
        frame[off:off + 4] = (7).to_bytes(4, "big")
        with pytest.raises(wire.WireError, match="CRC"):
            wire.decode(bytes(frame), expect_epoch=7)
        sentinel = np.full(257, np.float32(-1.5))
        out = sentinel.copy()
        with pytest.raises(wire.WireError):
            wire.decode_into(bytes(frame), out, expect_epoch=7)
        np.testing.assert_array_equal(out, sentinel)

    def test_check_epoch_validation(self):
        assert wire.check_epoch(0) == 0
        assert wire.check_epoch(wire.MAX_EPOCH) == wire.MAX_EPOCH
        for bad in (-1, wire.MAX_EPOCH + 1):
            with pytest.raises(ValueError):
                wire.check_epoch(bad)
        for bad in (True, 1.5, "7", None):
            with pytest.raises(TypeError):
                wire.check_epoch(bad)
        with pytest.raises(ValueError):
            wire.encode(self._vec(8), "f32", epoch=wire.MAX_EPOCH + 1)

    def test_frame_epoch_header_only_rejects(self):
        frame = wire.encode(self._vec(), "f32", epoch=3)
        with pytest.raises(wire.WireError):
            wire.frame_epoch(frame[:10])
        with pytest.raises(wire.WireError):
            wire.frame_epoch(frame[:18])  # v2 header cut short
        bad = bytearray(frame)
        bad[0] = 0x00
        with pytest.raises(wire.WireError):
            wire.frame_epoch(bytes(bad))
        bad = bytearray(frame)
        bad[2] = 0x09  # unknown version byte
        with pytest.raises(wire.WireError):
            wire.frame_epoch(bytes(bad))


# --- batched decode (decode_batch_into — ISSUE 20) ---------------------------


class TestDecodeBatchInto:
    """The vectorized batch decoder is pinned BITWISE to the per-frame
    ``decode_into`` loop: same outputs, same per-frame rejects with the
    same error text, same pins — for every scheme and both header
    versions. A forged frame in a batch bans its sender (an indexed
    ``WireError`` in the result list) and never poisons batchmates or
    touches its own target row."""

    SCHEMES = ("f32", "bf16", "int8", "int4", "topk")

    def _frames(self, scheme, k, d, *, plane=0, epoch=None, seed=0):
        rng = np.random.default_rng(seed)
        kw = {} if epoch is None else {"epoch": epoch}
        return [
            wire.encode(
                rng.standard_normal(d).astype(np.float32), scheme,
                plane=plane, **kw,
            )
            for _ in range(k)
        ]

    def _assert_matches_per_frame(self, frames, width, **pins):
        """Batch-decode ``frames`` and check EVERY per-frame verdict —
        accepted elems, written prefix, untouched tail/reject rows,
        and reject error text — against the per-frame decode_into
        reference. Returns the batch results."""
        k = len(frames)
        out = np.full((k, width), np.float32(-1.5))
        res = wire.decode_batch_into(frames, out, **pins)
        assert len(res) == k
        for i, fr in enumerate(frames):
            ref = np.full(width, np.float32(-1.5))
            try:
                want = wire.decode_into(fr, ref, **pins)
            except wire.WireError as exc:
                assert isinstance(res[i], wire.WireError), (i, res[i])
                assert str(res[i]) == str(exc)
            else:
                assert res[i] == want, (i, res[i])
            np.testing.assert_array_equal(out[i], ref)
        return res

    @pytest.mark.parametrize("epoch", [None, 7])
    @pytest.mark.parametrize("scheme", SCHEMES)
    def test_bitwise_parity_every_scheme_and_header(self, scheme, epoch):
        # 257 elems: a partial quant block + odd int4 nibble padding.
        k, d = 9, 257
        frames = self._frames(scheme, k, d, plane=1, epoch=epoch)
        res = self._assert_matches_per_frame(
            frames, d, expect_plane=1, expect_elems=d, expect_epoch=epoch,
        )
        assert res == [d] * k

    def test_mixed_schemes_and_sizes_in_one_batch(self):
        # Adjacent same-scheme runs of differing widths + scheme
        # switches: the slab-dequant run grouping must break correctly.
        rng = np.random.default_rng(3)
        frames, widths = [], []
        for rep in range(2):
            for j, scheme in enumerate(self.SCHEMES):
                d = 64 + 17 * j + 128 * rep
                frames.append(wire.encode(
                    rng.standard_normal(d).astype(np.float32), scheme,
                ))
                widths.append(d)
        res = self._assert_matches_per_frame(frames, max(widths))
        assert res == widths

    @pytest.mark.parametrize("scheme", SCHEMES)
    def test_corrupt_frame_never_poisons_batchmates(self, scheme):
        k, d = 7, 129
        frames = self._frames(scheme, k, d, seed=11)
        bad = bytearray(frames[3])
        bad[-1] ^= 0xFF  # payload flip: CRC must catch it
        frames[3] = bytes(bad)
        res = self._assert_matches_per_frame(frames, d, expect_elems=d)
        assert isinstance(res[3], wire.WireError)
        assert [r for i, r in enumerate(res) if i != 3] == [d] * (k - 1)

    def test_fuzz_byte_flips_match_per_frame_verdicts(self):
        """Flip one byte at a stride of positions in each scheme's
        frame; the batch verdict for EVERY frame (the corrupted one and
        its batchmates) must equal the per-frame path's — reject text
        included. No assumption about WHICH rejection fires: the pin is
        agreement, exactly the fuzz discipline of the per-frame fuzz
        above."""
        d = 65
        rng = np.random.default_rng(17)
        base = [
            wire.encode(rng.standard_normal(d).astype(np.float32), s)
            for s in self.SCHEMES
        ]
        for victim, fr in enumerate(base):
            for pos in range(0, len(fr), max(1, len(fr) // 13)):
                frames = list(base)
                bad = bytearray(fr)
                bad[pos] ^= 0x5A
                frames[victim] = bytes(bad)
                self._assert_matches_per_frame(frames, d)

    def test_truncated_and_garbage_frames_reject_in_batch(self):
        d = 48
        good = self._frames("f32", 1, d, seed=2)[0]
        frames = [good, good[:10], b"", b"not-a-frame", good[:-3], good]
        res = self._assert_matches_per_frame(frames, d)
        assert res[0] == d and res[5] == d
        assert all(isinstance(r, wire.WireError) for r in res[1:5])

    def test_pins_enforced_per_frame_in_batch(self):
        d = 33
        rng = np.random.default_rng(23)
        v = rng.standard_normal(d).astype(np.float32)
        v2 = rng.standard_normal(2 * d).astype(np.float32)
        frames = [
            wire.encode(v, "f32", plane=2, epoch=7),   # cross-plane
            wire.encode(v, "f32", plane=1, epoch=7),   # accepted
            wire.encode(v2, "f32", plane=1, epoch=7),  # wrong elems
            wire.encode(v, "f32", plane=1, epoch=6),   # stale epoch
            wire.encode(v, "f32", plane=1),            # epochless vs pin
            wire.encode(v, "int4", plane=1, epoch=7),  # accepted
        ]
        res = self._assert_matches_per_frame(
            frames, 2 * d, expect_plane=1, expect_elems=d, expect_epoch=7,
        )
        assert res[1] == d and res[5] == d
        for i in (0, 2, 3, 4):
            assert isinstance(res[i], wire.WireError), i

    def test_max_elems_bounds_sparse_claims_pre_allocation(self):
        """A CRC-valid topk frame claiming 2^40 dense elems must reject
        on ``max_elems`` in the batch path exactly like decode_into —
        BEFORE any payload-sized allocation (the allocation-bomb ban
        surface, Baruch-style)."""
        import struct
        import zlib

        d = 64
        pairs = np.zeros(2, np.dtype([("i", "<u4"), ("v", "<f4")]))
        pairs["i"] = [0, 1]
        pairs["v"] = [5.0, -5.0]
        payload = pairs.tobytes()
        giant = struct.pack(
            "!2sBBQI", b"GW", 1, 4, 2 ** 40, zlib.crc32(payload)
        ) + payload
        honest = self._frames("topk", 2, d, seed=5)
        frames = [honest[0], giant, honest[1]]
        res = self._assert_matches_per_frame(frames, d, max_elems=d)
        assert res[0] == d and res[2] == d
        assert isinstance(res[1], wire.WireError)

    def test_crc_thread_pool_is_bitwise_identical(self, monkeypatch):
        """GARFIELD_INGEST_THREADS only parallelizes the CRC pass —
        verdicts and decoded bytes must not depend on it."""
        k, d = 12, 257
        frames = self._frames("int8", k, d, seed=7)
        bad = bytearray(frames[5])
        bad[-1] ^= 0xFF
        frames[5] = bytes(bad)
        outs = []
        for threads in ("0", "2"):
            monkeypatch.setenv("GARFIELD_INGEST_THREADS", threads)
            out = np.zeros((k, d), np.float32)
            res = wire.decode_batch_into(frames, out, expect_elems=d)
            outs.append((out, res))
        (out0, res0), (out1, res1) = outs
        np.testing.assert_array_equal(out0, out1)
        assert [str(r) for r in res0] == [str(r) for r in res1]
        assert isinstance(res0[5], wire.WireError)

    def test_env_knobs_parse(self, monkeypatch):
        monkeypatch.delenv("GARFIELD_WIRE_BATCH_DECODE", raising=False)
        assert wire.wire_batch_decode() is True  # default on
        monkeypatch.setenv("GARFIELD_WIRE_BATCH_DECODE", "0")
        assert wire.wire_batch_decode() is False
        monkeypatch.setenv("GARFIELD_WIRE_BATCH_DECODE", "false")
        assert wire.wire_batch_decode() is False
        monkeypatch.delenv("GARFIELD_INGEST_THREADS", raising=False)
        assert wire.ingest_threads() == 0  # default inline
        monkeypatch.setenv("GARFIELD_INGEST_THREADS", "3")
        assert wire.ingest_threads() == 3
        monkeypatch.setenv("GARFIELD_INGEST_THREADS", "bogus")
        with pytest.raises(ValueError, match="GARFIELD_INGEST_THREADS"):
            wire.ingest_threads()

    def test_rejects_unusable_slabs_loudly(self):
        frames = self._frames("f32", 2, 16)
        with pytest.raises((TypeError, ValueError)):
            wire.decode_batch_into(frames, np.zeros((2, 16), np.float64))
        with pytest.raises((TypeError, ValueError)):
            wire.decode_batch_into(frames, np.zeros(32, np.float32))
        wide = np.zeros((2, 32), np.float32)
        with pytest.raises((TypeError, ValueError)):
            wire.decode_batch_into(frames, wide[:, ::2])  # non-contiguous

"""Data-plane defense: per-class gradient fingerprints + two detectors.

The one cell the GAR-side stack cannot touch (DEFBENCH_r02, DESIGN.md
§17): a low-``poison_frac`` BadNets backdoor submits HONEST gradients of
a poisoned task — in-distribution rows, nothing divergence-shaped for
Gram distances, suspicion weighting or the escalation ladder to measure
(``backdoor_asr_defended`` ~0.62 through the full krum→multi-krum→bulyan
ladder). What a data poisoner cannot hide is the PER-CLASS structure of
its classifier-head gradient: relabeling its samples as the target class
concentrates loss mass on that class's logit, so the head-gradient row
for the target class (and its bias component — the batch's summed logit
error) departs coherently from the honest crowd's. This module measures
exactly that:

  - **Fingerprints** (``fingerprints``): the classifier-head block of
    each submitted gradient — located by ``head_spec`` (flat wire rows,
    the host PS) or ``head_leaves`` (the stacked gradient tree, in-graph)
    and reshaped to a (num_classes, feat) matrix — reduced to fixed-shape
    per-class statistics: crowd-normalized per-class row norms, cosine
    projections onto the crowd's per-class head direction, and the bias
    gradient's per-class z-scores. Shape (n, 3*num_classes) (2*C without
    a bias), independent of d — cheap at any model scale, jit-safe.
  - **Spectral filtering** (``spectral_scores``; Tran et al., NeurIPS
    2018 "spectral signatures"): outlier scores along the top singular
    vector of the CENTERED fingerprint matrix (fixed-iteration power
    iteration on the (k, k) covariance — no data-dependent shapes).
    Scores are |projection| / rms(projection); ranks beyond the
    ``tau``-sigma tail are flagged.
  - **Head-gradient 2-means** (``cluster_flags``; Chen et al. 2018
    activation-clustering, applied to head GRADIENTS — the quantity the
    PS actually holds): fixed-iteration Lloyd over the suspect target
    class's head rows (``suspect_class`` picks the class whose bias
    z-scores disperse most). A trigger cohort forms a small, tight,
    well-separated cluster; its members are flagged iff the cluster is
    no larger than the declared ``f`` budget AND the between-center
    separation clears the within-cluster spread.

Both detectors are dual-backend (numpy on the host PS quorums, traced
jnp in the on-mesh step — the TapBundle convention: traced OUT entirely
when the data defense is off) and feed the EXISTING suspicion algebra:
per-round flags fold into a decayed exclusion EMA (the MetricsHub
halflife law), and ``defense.suspicion_weights`` maps the EMA's
suspicion through the same median-relative floored WEIGHT LAW the
staleness and GAR-suspicion discounts use. A clean history therefore
weighs exactly 1.0, and occasional single-round false flags wash out in
the EMA instead of down-weighting an honest rank. The COMPOSITION of
those weights is deliberately different, and the measured negative
result behind it is recorded here: multiplying data-plane weights into
the row-scale slot (the staleness algebra) made DEFBENCH's backdoor
cell WORSE than undefended (ASR 0.97 vs 0.10) — a toward-zero-scaled
cohort row lands where late-training honest gradients cluster, so krum
ADMITS it (the same inlier inversion that puts r02's
``backdoor/escalate`` at 0.62). Data-plane weights therefore compose by
CENTER-PULL (``center_pull_rows``/``center_pull_tree``): suspect rows
collapse onto the stack's coordinate median, so a fully-flagged row is
selectable but informationless.

``DataPlaneDefense`` is the host-side deployment (a ``PlaneDefense``
sibling) for the SSMW/MSMW PS gradient quorums: it fingerprints the wire
frames the PS already decoded, carries the per-rank EMA, and serves
per-quorum weights + the schema-v9 ``data_defense`` telemetry payload.
"""

import dataclasses

import numpy as np

__all__ = [
    "HeadSpec",
    "head_spec",
    "head_leaves",
    "head_from_rows",
    "fingerprints",
    "spectral_scores",
    "suspect_class",
    "cluster_flags",
    "detect",
    "center_pull_rows",
    "center_pull_tree",
    "DataPlaneDefense",
]

# Detector defaults (overridable via --defense_params dp_*): the spectral
# tail threshold, Lloyd/power iteration counts, and the 2-means
# separation gate (between-center distance^2 must exceed SEP x the mean
# within-cluster variance before the small cluster is called a cohort).
DEFAULT_TAU = 2.0
POWER_ITERS = 8
LLOYD_ITERS = 8
CLUSTER_SEP = 4.0
_EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class HeadSpec:
    """Static location of the classifier head inside the flat gradient.

    ``kernel`` is the (start, end) ravel-order span of the head's
    (feat, classes) kernel; ``bias`` the span of its (classes,) bias, or
    None when the kernel has no adjacent bias leaf. Derived once from a
    params TEMPLATE (``head_spec``), then applied to every wire row the
    PS decodes — the host twin of the in-graph ``head_leaves``.
    """

    kernel: tuple
    bias: tuple
    feat: int
    classes: int


def _key_str(k):
    # jax path entries are DictKey/GetAttrKey/SequenceKey wrappers; pull
    # the underlying name out so flax param dicts yield plain strings.
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def _named_leaves(tree):
    import jax
    import jax.numpy as jnp

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out, start = [], 0
    for path, leaf in flat:
        size = int(np.prod(jnp.shape(leaf))) if jnp.ndim(leaf) else 1
        out.append((
            tuple(_key_str(k) for k in path), leaf, (start, start + size)
        ))
        start += size
    return out


def _head_index(named, kernel_ndim):
    """Flatten-order index of the classifier-head kernel, or None.

    Resolution hierarchy (the transformer family broke the old "last
    2-D leaf" rule — flax flattens by SORTED string key, so ViT's
    ``pos_embedding`` param (lowercase sorts after every capitalized
    module scope) and GPT's nested ``EncoderBlock_*`` MLP kernels all
    flatten AFTER the top-level ``Dense_0`` head):

      1. the highest-numbered TOP-LEVEL ``Dense_i/kernel`` — flax's
         auto-naming for the final projection of every zoo model that
         has one (CNNs and transformers alike);
      2. a model with an ``nn.Embed`` table (final path key
         ``embedding``) but NO top-level Dense head ties its output
         head to the embedding (``Embed.attend``) — there is no head
         gradient distinct from the embedding gradient to fingerprint,
         so this REFUSES loudly rather than silently fingerprinting
         some interior matrix;
      3. the last ``kernel``-named leaf of head rank (nested heads in
         hand-rolled scopes);
      4. the last leaf of head rank (non-flax trees with no string
         naming — the legacy rule, still exercised by raw-dict tests).
    """
    top_dense, top_i = None, -1
    last_kernel = None
    last_nd = None
    has_embed = False
    for i, (path, leaf, _span) in enumerate(named):
        nd = int(np.ndim(leaf)) if not hasattr(leaf, "ndim") else int(
            leaf.ndim
        )
        if path and path[-1] == "embedding":
            has_embed = True
        if nd != kernel_ndim:
            continue
        last_nd = i
        if not path or path[-1] != "kernel":
            continue
        last_kernel = i
        if len(path) == 2 and path[0].startswith("Dense_"):
            try:
                di = int(path[0].rsplit("_", 1)[1])
            except ValueError:
                continue
            if di > top_i:
                top_i, top_dense = di, i
    if top_dense is not None:
        return top_dense
    if has_embed:
        raise ValueError(
            "data-plane defense cannot fingerprint an embedding-tied "
            "head: the params carry an nn.Embed table but no top-level "
            "Dense head (GPT(tied=True) layout) — the output head IS "
            "the embedding gradient, which every token in the batch "
            "touches, so no per-class head block exists. Use an untied "
            "head (tied=False) to run the data-plane defense."
        )
    if last_kernel is not None:
        return last_kernel
    return last_nd


def head_spec(params):
    """``HeadSpec`` of a params tree, or None when no head is found.

    The classifier head is located by ``_head_index`` (top-level
    ``Dense_{max}`` kernel first; embedding-tied layouts REFUSE with a
    ValueError; legacy last-matrix fallbacks for hand-rolled trees);
    its trailing dim is the class count. The bias is the immediately
    preceding leaf when that is a matching (classes,)-vector (flax
    sorts ``bias`` before ``kernel`` inside one Dense scope). Models
    without any matrix leaf get None and the data-plane defense
    refuses loudly at the caller.
    """
    import jax.numpy as jnp

    named = _named_leaves(params)
    k_idx = _head_index(named, 2)
    if k_idx is None:
        return None
    leaf = named[k_idx][1]
    feat, classes = (int(s) for s in jnp.shape(leaf))
    bias = None
    if k_idx > 0:
        prev = named[k_idx - 1][1]
        if jnp.ndim(prev) == 1 and int(jnp.shape(prev)[0]) == classes:
            bias = named[k_idx - 1][2]
    return HeadSpec(
        kernel=named[k_idx][2], bias=bias, feat=feat, classes=classes
    )


def head_leaves(stacked_tree):
    """(kernel (n, classes, feat), bias (n, classes) or None) from a
    STACKED gradient tree (leading rank axis per leaf) — the in-graph
    twin of ``head_spec`` + ``head_from_rows``, selected statically at
    trace time so nothing head-shaped exists in the program when the
    defense is off. The head kernel is resolved by the SAME hierarchy
    as ``head_spec`` (one rank higher: rank axis + the (feat, classes)
    matrix); rows are transposed to class-major.
    """
    import jax.numpy as jnp

    named = _named_leaves(stacked_tree)
    k_idx = _head_index(named, 3)
    if k_idx is None:
        return None, None
    kernel = jnp.swapaxes(named[k_idx][1], 1, 2)  # (n, classes, feat)
    classes = kernel.shape[1]
    bias = None
    if k_idx > 0:
        prev = named[k_idx - 1][1]
        if prev.ndim == 2 and prev.shape[1] == classes:
            bias = prev
    return kernel, bias


def head_from_rows(spec, rows):
    """Extract (kernel (n, classes, feat), bias (n, classes) or None)
    from flat (n, d) gradient rows — the wire frames the PS decoded."""
    xp = _xp(rows)
    n = rows.shape[0]
    s, e = spec.kernel
    kernel = xp.swapaxes(
        rows[:, s:e].reshape(n, spec.feat, spec.classes), 1, 2
    )
    bias = None
    if spec.bias is not None:
        bs, be = spec.bias
        bias = rows[:, bs:be]
    return kernel, bias


def _xp(x):
    import jax

    if isinstance(x, jax.Array):
        import jax.numpy as jnp

        return jnp
    return np


def fingerprints(kernel, bias=None):
    """(n, k) per-rank fingerprints from class-major head gradients.

    Three fixed-shape per-class statistics, each scale-free against the
    crowd (a lone magnitude outlier is the GAR plane's job; the data
    plane keys on per-class STRUCTURE):

      - crowd-normalized row norms ``||H_i[c]|| / mean_j ||H_j[c]||`` —
        a cohort concentrating loss on one class inflates that class's
        row against the crowd;
      - cosine projections onto the crowd's class direction
        ``<H_i[c], u_c> / ||H_i[c]||`` with ``u_c`` the normalized crowd
        sum — a relabeling cohort's target-class row points AGAINST the
        honest direction (it pushes the logit the other way);
      - bias z-scores ``(b_ic - mean) / std`` (when the head has a
        bias) — the summed per-class logit error of the rank's batch,
        the label-distribution signal a relabeled batch cannot mask.

    Accumulates in f32 (bf16 pipelines round norm sums), dual-backend.
    """
    xp = _xp(kernel)
    H = kernel.astype(xp.float32)
    r = xp.sqrt(xp.sum(H * H, axis=-1) + _EPS)  # (n, C)
    r_norm = r / (xp.mean(r, axis=0, keepdims=True) + _EPS)
    u = xp.sum(H, axis=0)  # (C, feat) crowd sum per class
    u = u / (xp.sqrt(xp.sum(u * u, axis=-1, keepdims=True)) + _EPS)
    proj = xp.sum(H * u[None], axis=-1) / r  # (n, C) cosine
    cols = [r_norm, proj]
    if bias is not None:
        b = bias.astype(xp.float32)
        bz = (b - xp.mean(b, axis=0, keepdims=True)) / (
            xp.std(b, axis=0, keepdims=True) + _EPS
        )
        cols.append(bz)
    return xp.concatenate(cols, axis=-1)


def spectral_scores(fp, iters=POWER_ITERS):
    """(n,) spectral outlier scores over a fingerprint matrix.

    Tran et al.'s spectral-signature statistic on the fingerprint space:
    center, power-iterate the (k, k) covariance to the top singular
    direction (deterministic ones-init — the fingerprint columns are
    crowd-normalized, so no column dominates degenerately), and score
    each rank by |projection| / rms(projection). Dimensionless: ~1 for
    the crowd, >> 1 for a coherent minority, so a single ``tau``
    threshold serves every task. Fixed iteration count and shapes —
    jit-safe; numpy in, numpy out on the host.
    """
    xp = _xp(fp)
    X = fp.astype(xp.float32)
    X = X - xp.mean(X, axis=0, keepdims=True)
    C = X.T @ X  # (k, k)
    v = xp.ones((C.shape[0],), xp.float32) / np.sqrt(C.shape[0])
    for _ in range(int(iters)):
        v = C @ v
        v = v / (xp.sqrt(xp.sum(v * v)) + _EPS)
    s = X @ v  # (n,) signed projections
    sigma = xp.sqrt(xp.mean(s * s) + _EPS)
    return xp.abs(s) / sigma


def suspect_class(kernel, bias=None):
    """Index of the class the data-plane evidence points at: the class
    whose bias statistics (or, bias-less, crowd-normalized row norms)
    disperse the most across ranks — a relabeling cohort concentrates
    its departure on the TARGET class's statistics. Traced-argmax safe.

    Dispersion is measured ROBUSTLY (|x - median| / MAD), not by
    mean/std z-scores: a cohort of f coherent outliers corrupts the
    mean and inflates the std of its OWN class, capping the classic
    z at ~sqrt((n-f)/f) — at f/n = 1/4 that is 1.73, and a single
    noisy rank in a quiet class beats it, steering the 2-means at the
    wrong rows (the token-backdoor cell that exposed this: 8 workers,
    f=2, target-class bias gradient -0.9 vs honest 0.05, and the old
    statistic picked a clean class). Median/MAD stay anchored to the
    honest crowd for any cohort below n/2, so the target class's z is
    unbounded in the departure size. Per-class MADs are floored by a
    fraction of their crowd median so a near-constant class cannot win
    on numerical noise.
    """
    xp = _xp(kernel)
    if bias is not None:
        stat = bias.astype(xp.float32)
    else:
        H = kernel.astype(xp.float32)
        stat = xp.sqrt(xp.sum(H * H, axis=-1) + _EPS)
    med = xp.median(stat, axis=0, keepdims=True)
    dev = xp.abs(stat - med)
    mad = xp.median(dev, axis=0, keepdims=True)
    floor = 0.01 * xp.mean(mad) + _EPS
    z = dev / (mad + floor)
    return xp.argmax(xp.max(z, axis=0))


def cluster_flags(rows, f, iters=LLOYD_ITERS, sep=CLUSTER_SEP):
    """(n,) bool flags from 2-means over one class's head-gradient rows.

    Fixed-iteration Lloyd (jit-safe: masked means, no data-dependent
    shapes), initialized at the extreme rows along the rows' own top
    singular direction (the spectral init — deterministic and
    permutation-equivariant). The SMALLER cluster is flagged iff

      - its size is within the declared Byzantine budget ``f`` (a
        "small cluster" of n/2 is a data modality, not a cohort), and
      - the squared between-center distance exceeds ``sep`` times the
        mean within-cluster variance (honest minibatch noise forms no
        such gap; a trigger cohort — near-identical poisoned batches —
        does).

    Returns all-False when the gates fail, so clean runs see no
    cluster evidence. Dual-backend.
    """
    xp = _xp(rows)
    X = rows.astype(xp.float32)
    n = X.shape[0]
    Xc = X - xp.mean(X, axis=0, keepdims=True)
    C = Xc.T @ Xc
    v = xp.ones((C.shape[0],), xp.float32) / np.sqrt(C.shape[0])
    for _ in range(int(iters)):
        v = C @ v
        v = v / (xp.sqrt(xp.sum(v * v)) + _EPS)
    t = Xc @ v
    c0 = X[xp.argmin(t)]
    c1 = X[xp.argmax(t)]
    assign = None
    for _ in range(int(iters)):
        d0 = xp.sum((X - c0[None]) ** 2, axis=-1)
        d1 = xp.sum((X - c1[None]) ** 2, axis=-1)
        assign = d1 < d0  # True -> cluster 1
        w1 = assign.astype(xp.float32)
        w0 = 1.0 - w1
        # Masked means with empty-cluster guards (keep the old center).
        n0 = xp.sum(w0)
        n1 = xp.sum(w1)
        m0 = (w0[:, None] * X).sum(axis=0) / xp.maximum(n0, 1.0)
        m1 = (w1[:, None] * X).sum(axis=0) / xp.maximum(n1, 1.0)
        c0 = xp.where(n0 > 0, m0, c0)
        c1 = xp.where(n1 > 0, m1, c1)
    w1 = assign.astype(xp.float32)
    w0 = 1.0 - w1
    n0 = xp.sum(w0)
    n1 = xp.sum(w1)
    small_is_1 = n1 <= n0
    small_w = xp.where(small_is_1, w1, w0)
    small_n = xp.minimum(n0, n1)
    between = xp.sum((c0 - c1) ** 2)
    within = (
        xp.sum(w0 * xp.sum((X - c0[None]) ** 2, axis=-1))
        + xp.sum(w1 * xp.sum((X - c1[None]) ** 2, axis=-1))
    ) / xp.maximum(xp.asarray(n, xp.float32), 1.0)
    ok = (
        (small_n >= 1.0)
        & (small_n <= xp.asarray(float(max(1, int(f))), xp.float32))
        & (between > sep * (within + _EPS))
    )
    return (small_w > 0.5) & ok


def detect(kernel, bias, *, f, tau=DEFAULT_TAU):
    """Run both detectors over one quorum's head gradients.

    Returns ``(scores, flags)``: the (n,) spectral outlier scores and
    the (n,) bool union of the tau-sigma spectral tail and the 2-means
    cohort flags over the suspect class's rows. Dual-backend — this is
    the single entry the in-graph step and the host ``DataPlaneDefense``
    both call, so the two deployments can never disagree on the math.
    """
    xp = _xp(kernel)
    fp = fingerprints(kernel, bias)
    scores = spectral_scores(fp)
    cls = suspect_class(kernel, bias)
    if xp is np:
        rows = kernel[:, int(cls), :]
    else:
        import jax.numpy as jnp

        rows = jnp.take(kernel, cls, axis=1)
    cflags = cluster_flags(rows, f)
    flags = (scores > tau) | cflags
    return scores, flags


def center_pull_rows(rows, w):
    """Data-plane weight COMPOSITION: pull suspect rows onto the
    TRUSTED center, ``row_i' = c + w_i * (row_i - c)`` with ``c`` the
    dp-weight-weighted mean of the stack (``sum_j w_j row_j / sum_j
    w_j`` — rows the EMA trusts at ~1.0 define it; flagged rows barely
    contribute).

    Two measured negative results shaped this (DEFBENCH probes,
    recorded in DESIGN.md §18):

      - Plain row SCALING (the staleness/GAR-suspicion algebra) is the
        wrong composition for data-plane evidence against proximity
        rules: a 0.1-scaled backdoor row lands near the ORIGIN, which
        is exactly where late-training honest gradients cluster, so
        krum ADMITS the scaled cohort — ASR 0.97 vs undefended 0.10,
        the same inlier inversion that puts r02's ``backdoor/escalate``
        at 0.62 (any toward-zero dampening of a data poisoner hands it
        centrality).
      - Pulling onto the RAW stack's coordinate median still leaked: a
        coherent f-cohort at one extreme shifts the contaminated
        median by an order statistic, and the rule (which now happily
        selects the central pulled rows) re-injects that bias every
        step — the defended model's target-emission base rate sat
        ~0.05 above the clean model's for the whole run.

    The trusted-mean center closes both: a fully-suspect row becomes
    the trusted rows' average — selectable but informationless — while
    honest rows at weight exactly 1.0 keep their values up to one float
    add/subtract (accuracy-level identity; the BITWISE contract applies
    to defense-off, which traces none of this). The per-rank
    radius-by-suspicion shape is centered clipping (cclip) with the
    radius driven by data-plane evidence instead of a norm bound.
    A cohort oscillating its weight around 0.5 both contributes to the
    center and keeps deviation — bounded at half strength, and the GAR
    plane still audits whatever residual it plays.
    """
    xp = _xp(rows)
    wv = xp.asarray(w, xp.float32)
    r32 = rows.astype(xp.float32)
    c = (wv[:, None] * r32).sum(axis=0) / xp.maximum(
        wv.sum(), xp.float32(1e-3)
    )
    out = c[None] + wv[:, None] * (r32 - c[None])
    return out.astype(rows.dtype)


def center_pull_tree(stacked_tree, w):
    """``center_pull_rows`` over a stacked gradient TREE (leading rank
    axis per leaf): per-leaf trusted-mean centers, one fused
    multiply-add per leaf — no (n, d) flat stack, so the tree/fold fast
    paths keep their layout (the transform is a per-leaf elementwise op
    exactly like the worker-momentum update)."""
    import jax
    import jax.numpy as jnp

    wv = jnp.asarray(w, jnp.float32)
    denom = jnp.maximum(wv.sum(), jnp.float32(1e-3))

    def one(leaf):
        wl = wv.reshape((leaf.shape[0],) + (1,) * (leaf.ndim - 1))
        l32 = leaf.astype(jnp.float32)
        c = (wl * l32).sum(axis=0, keepdims=True) / denom
        return (c + wl * (l32 - c)).astype(leaf.dtype)

    return jax.tree.map(one, stacked_tree)


class DataPlaneDefense:
    """Host-side data-plane defense for ONE PS gradient plane.

    The ``PlaneDefense`` sibling (aggregators/defense.py) for the third
    plane of the closed loop: per-round detector flags fold into a
    decayed per-rank exclusion EMA (the MetricsHub halflife law — a
    cohort cannot launder the score by pausing), and
    ``defense.suspicion_weights`` maps the EMA through the same
    median-relative floored row-weight path as every other discount.
    ``observe`` ingests one quorum's decoded wire rows; ``weights_for``
    returns the per-quorum-row weights, or None when every weight is
    exactly 1.0 (the caller dispatches the unweighted program — the
    clean-history identity the bitwise contract needs).
    """

    def __init__(self, num_ranks, spec, *, f, plane="gradient",
                 tau=DEFAULT_TAU, power=4.0, floor=0.0, halflife=8.0):
        if spec is None:
            raise ValueError(
                "data-plane defense needs a classifier head "
                "(head_spec found no 2-D parameter leaf)"
            )
        if halflife <= 0.0:
            raise ValueError(f"dp halflife must be > 0, got {halflife}")
        if tau <= 0.0:
            raise ValueError(f"dp tau must be > 0, got {tau}")
        self.num_ranks = int(num_ranks)
        self.spec = spec
        self.f = max(1, int(f))
        self.plane = str(plane)
        self.tau = float(tau)
        self.power = float(power)
        self.floor = float(floor)
        self._decay = 0.5 ** (1.0 / float(halflife))
        self._obs = np.zeros(self.num_ranks, np.float64)
        self._exc = np.zeros(self.num_ranks, np.float64)
        self.rounds = 0
        self.flagged_total = 0
        self.last_scores = np.zeros(self.num_ranks, np.float64)

    def observe(self, ranks, rows):
        """Fingerprint one quorum's flat rows, fold the flags into the
        EMA; returns {"scores", "flags"} over the quorum (taps order).

        Quorums of fewer than 4 rows carry no crowd to depart from —
        the detectors are skipped (zero scores, no flags) rather than
        thresholding noise.
        """
        ranks = np.asarray(ranks, np.int64)
        rows = np.asarray(rows, np.float32)
        q = rows.shape[0]
        if q < 4:
            scores = np.zeros(q, np.float64)
            flags = np.zeros(q, bool)
        else:
            kernel, bias = head_from_rows(self.spec, rows)
            scores, flags = detect(kernel, bias, f=self.f, tau=self.tau)
            scores = np.asarray(scores, np.float64)
            flags = np.asarray(flags, bool)
        obs_inc = np.zeros(self.num_ranks, np.float64)
        exc_inc = np.zeros(self.num_ranks, np.float64)
        np.add.at(obs_inc, ranks, 1.0)
        np.add.at(exc_inc, ranks, flags.astype(np.float64))
        self._obs *= self._decay
        self._exc *= self._decay
        self._obs += obs_inc
        self._exc += exc_inc
        self.rounds += 1
        self.flagged_total += int(flags.sum())
        self.last_scores[ranks] = scores
        return {"scores": scores, "flags": flags}

    def suspicion(self):
        return self._exc / np.maximum(self._obs, 1e-9)

    def weights_full(self):
        """(num_ranks,) data-plane suspicion weights — exactly 1.0 on a
        clean history (the same identity contract as PlaneDefense)."""
        from . import defense as defense_lib

        return np.asarray(defense_lib.suspicion_weights(
            self.suspicion(), power=self.power, floor=self.floor
        ), np.float32)

    def weights_for(self, ranks):
        w = self.weights_full()[np.asarray(ranks, np.int64)]
        if np.all(w == 1.0):
            return None
        return w.astype(np.float32)

    def stats(self):
        """The summary digest (schema v9 ``summary.data_defense``)."""
        w = self.weights_full()
        return {
            "rounds": int(self.rounds),
            "flagged": int(self.flagged_total),
            "max_score": round(float(self.last_scores.max()), 6),
            "min_w": round(float(w.min()), 6),
        }

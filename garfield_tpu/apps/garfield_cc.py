"""Garfield_CC: collective-communication training (per-layer GARs).

Counterpart of ``pytorch_impl/applications/Garfield_CC/trainer.py`` (P20) —
the reference's monolithic torch.distributed implementation whose
``reduce_gradients`` loops over model layers doing gather -> GAR -> broadcast
per parameter tensor (:55-204). Its three modes map to:

  - ``--mode vanilla``     dist.reduce(SUM)/n (:84-89)      -> average GAR
  - ``--mode aggregathor`` gather+GAR at one PS (:91-127)   -> SSMW topology
  - ``--mode guanyu``      Byzantine-PS path (:104-196)     -> MSMW topology
                           with model GAR (``mar``)

All modes use ``granularity="layer"`` so the GAR runs per parameter tensor
exactly like the reference's per-layer loop — on TPU the gather is one
all_gather per tensor and the "broadcast back" disappears (SPMD replication).
The ``mar='crash'`` crash-fault mode maps to --ps_attack drop.

  python -m garfield_tpu.apps.garfield_cc --mode aggregathor \\
      --dataset cifar10 --model resnet18 --num_workers 8 --fw 2 --gar median
"""

import json
import sys

from ..parallel import aggregathor, byzsgd
from . import common


def main(argv=None):
    parser = common.base_parser(
        "Garfield collective-communication trainer (garfield-tpu)"
    )
    parser.add_argument(
        "--mode", type=str, default="aggregathor",
        choices=["vanilla", "aggregathor", "guanyu"],
        help="Communication scheme (Garfield_CC/trainer.py:84-196).",
    )
    parser.add_argument(
        "--mar", type=str, default=None,
        help="Model aggregation rule for guanyu (default: --gar; "
             "Garfield_CC/trainer.py:163-168).",
    )
    parser.add_argument(
        "--ps_attack", type=str, default=None,
        help="Byzantine server model attack for guanyu mode.",
    )
    args = parser.parse_args(argv)
    args.granularity = "layer"
    if args.mode == "vanilla":
        args.gar = "average"
        args.attack = None
        args.fw = 0
    if args.mode in ("vanilla", "aggregathor"):
        return common.train(
            args,
            topology=aggregathor,
            make_trainer_kwargs=dict(
                num_workers=args.num_workers,
                f=args.fw,
                attack=args.attack,
                attack_params=args.attack_params,
                subset=args.subset,
                granularity="layer",
            ),
            num_slots=args.num_workers,
            tag="garfield_cc",
        )
    return common.train(
        args,
        topology=byzsgd,
        make_trainer_kwargs=dict(
            num_workers=args.num_workers,
            num_ps=args.num_ps,
            fw=args.fw,
            fps=args.fps,
            attack=args.attack,
            attack_params=args.attack_params,
            ps_attack=args.ps_attack,
            model_gar=args.mar,
            subset=args.subset,
            granularity="layer",
        ),
        num_slots=args.num_workers,
        tag="garfield_cc",
    )


if __name__ == "__main__":
    main(sys.argv[1:])

"""Adaptive (suspicion-aware) attack controllers: fast tier-1 coverage.

The controller laws of ``attacks/adaptive.py`` (DESIGN.md §16) at unit
scale — bisection convergence/re-expansion, rotation determinism, the
model-delta probe — plus the in-graph trainer integration on the 8-device
CPU mesh: the traced-magnitude fold path must train IDENTICALLY to the
flat where-path, bursts must key on the staleness emulation's degradation
windows, and oblivious configs must not grow any adaptive state (the
purity half of the acceptance). The host-plane controller's multi-process
twin lives in tests/test_defense_cluster.py (slow).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from garfield_tpu import data as data_lib
from garfield_tpu.attacks import (
    adaptive,
    plan_gradient_attack_fold,
    reset_attack_fallback,
)
from garfield_tpu.models import select_model
from garfield_tpu.parallel import aggregathor
from garfield_tpu.telemetry import hub as hub_lib
from garfield_tpu.utils import selectors


class TestBracket:
    def _converge(self, theta, *, mag_min=0.25, mag_max=6.0, rounds=40):
        lo, hi = mag_min, mag_max
        for _ in range(rounds):
            z = adaptive.played_magnitude(lo, hi)
            lo, hi = (float(v) for v in adaptive.update_bracket(
                lo, hi, z > theta, mag_min=mag_min, mag_max=mag_max,
            ))
        return lo, hi

    def test_bisection_tracks_threshold(self):
        # The played magnitude settles within a tenth of the bracket of
        # the exclusion threshold, from either side.
        for theta in (0.8, 2.7, 4.9):
            lo, hi = self._converge(theta)
            z = adaptive.played_magnitude(lo, hi)
            assert abs(z - theta) < 0.1 * (6.0 - 0.25), (theta, lo, hi)

    def test_always_accepted_regrows_to_max(self):
        # A threshold above the bracket: acceptance + collapse-regrow
        # must drive the play to mag_max, not freeze mid-bracket.
        lo, hi = self._converge(100.0, rounds=60)
        assert adaptive.played_magnitude(lo, hi) > 5.9

    def test_always_detected_collapses_to_min(self):
        lo, hi = self._converge(0.0, rounds=60)
        assert adaptive.played_magnitude(lo, hi) < 0.3

    def test_reexpansion_recovers_after_threshold_shift(self):
        # The defense escalates mid-run: the threshold drops, the bracket
        # re-closes below it; the defense relaxes, the regrow re-opens.
        lo, hi = self._converge(4.0)
        lo, hi = self._converge_from(lo, hi, 1.5)
        z = adaptive.played_magnitude(lo, hi)
        assert abs(z - 1.5) < 0.6, (lo, hi)

    def _converge_from(self, lo, hi, theta, rounds=40):
        for _ in range(rounds):
            z = adaptive.played_magnitude(lo, hi)
            lo, hi = (float(v) for v in adaptive.update_bracket(
                lo, hi, z > theta, mag_min=0.25, mag_max=6.0,
            ))
        return lo, hi

    def test_jnp_matches_host_law(self):
        lo = hi = None
        lo_j = jnp.float32(0.25)
        hi_j = jnp.float32(6.0)
        lo, hi = 0.25, 6.0
        for det in (True, False, False, True, False):
            lo, hi = (float(v) for v in adaptive.update_bracket(
                lo, hi, det, mag_min=0.25, mag_max=6.0,
            ))
            lo_j, hi_j = adaptive.update_bracket(
                lo_j, hi_j, jnp.asarray(det), mag_min=0.25, mag_max=6.0,
            )
            assert float(lo_j) == pytest.approx(lo, abs=1e-6)
            assert float(hi_j) == pytest.approx(hi, abs=1e-6)


class TestRotation:
    def test_schedule_covers_pool_and_is_deterministic(self):
        cfg = adaptive.configure(
            "adaptive-lie", {"f_pool": 5, "rotation": 3},
            num_workers=11, f=2,
        )
        seen = set()
        for r in range(30):
            m1 = adaptive.active_cohort(cfg, r)
            m2 = adaptive.active_cohort(cfg, r)  # colluders agree
            assert (m1 == m2).all()
            assert m1.sum() == 2
            assert set(np.flatnonzero(m1)) <= set(cfg.pool)
            seen |= set(np.flatnonzero(m1))
        assert seen == set(cfg.pool)  # every member takes a turn

    def test_traced_mask_matches_host_schedule(self):
        cfg = adaptive.configure(
            "adaptive-lie", {"f_pool": 4, "rotation": 2},
            num_workers=8, f=2,
        )
        fn = jax.jit(lambda s: adaptive.active_mask_traced(cfg, s))
        for r in (0, 1, 2, 5, 9, 17):
            np.testing.assert_array_equal(
                np.asarray(fn(jnp.asarray(r, jnp.int32))),
                adaptive.active_cohort(cfg, r),
            )

    def test_static_cohort_without_rotation(self):
        cfg = adaptive.configure(
            "adaptive-lie", {}, num_workers=8, f=2,
        )
        for r in (0, 7):
            np.testing.assert_array_equal(
                adaptive.active_cohort(cfg, r),
                np.arange(8) >= 6,
            )

    def test_config_validation(self):
        with pytest.raises(ValueError, match="f_pool"):
            adaptive.configure(
                "adaptive-lie", {"f_pool": 1}, num_workers=8, f=2
            )
        with pytest.raises(ValueError, match="adaptive"):
            adaptive.configure("lie", {}, num_workers=8, f=2)
        with pytest.raises(ValueError, match="mag_min"):
            adaptive.configure(
                "adaptive-lie", {"mag_min": 5.0, "mag_max": 1.0},
                num_workers=8, f=2,
            )


class TestHostController:
    def test_burst_triggers_on_gap_blowout_and_expires(self):
        cfg = adaptive.configure(
            "adaptive-lie", {"burst": 5.5}, num_workers=8, f=1,
        )
        c = adaptive.HostController(
            cfg, 7, burst_factor=3.0, burst_rounds=2
        )
        t = 0.0
        for _ in range(6):  # steady cadence: no burst
            t += 0.1
            assert not c.observe_round(t)
        assert not c.bursting()
        t += 1.0  # 10x gap: degradation window
        assert c.observe_round(t)
        assert c.bursting()
        assert c.magnitude() == pytest.approx(5.5)
        lo, hi = c.lo, c.hi
        c.feedback(True)  # burst rounds are not bracket probes
        assert (c.lo, c.hi) == (lo, hi)
        c.feedback(False)
        assert not c.bursting()  # expired after burst_rounds feedbacks

    def test_delta_probe_separates_admitted_from_excluded(self):
        rng = np.random.default_rng(0)
        mu = rng.standard_normal(512)
        sigma = np.abs(rng.standard_normal(512)) * 0.1
        u = 2.0 * sigma
        lr = 0.1
        prev = rng.standard_normal(512)
        for alpha, want_detected in ((0.0, True), (0.2, False)):
            new = prev - lr * (mu + alpha * u)
            det, score = adaptive.delta_probe(prev, new, u, mu_est=mu)
            assert det is want_detected, (alpha, score)

    def test_read_selected_tail(self, tmp_path):
        import json

        path = tmp_path / "ps.telemetry.jsonl"
        recs = [
            {"kind": "run", "meta": {}},
            {"kind": "step", "step": 3,
             "tap": {"selected": [1.0, 0.0, 1.0]}},
            {"kind": "step", "step": 4,
             "tap": {"selected": [1.0, 1.0, 0.0]}},
        ]
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        assert adaptive.read_selected(str(path), 2) == (4, 0.0)
        assert adaptive.read_selected(str(path), 1) == (4, 1.0)
        assert adaptive.read_selected(str(path), 9) is None


def _pima_setup(lr=0.05):
    module = select_model("pimanet", "pima")
    loss = selectors.select_loss("bce")
    opt = selectors.select_optimizer(
        "sgd", lr=lr, momentum=0.0, weight_decay=0.0
    )
    return module, loss, opt


def _pima_batches(n, bsz):
    m = data_lib.DatasetManager("pima", bsz, n, n, 0)
    m.num_ps = 0
    xs, ys = m.sharded_train_batches()
    return xs, jnp.asarray(xs[:, 0]), jnp.asarray(ys[:, 0])


def _flat_params(state):
    return np.concatenate(
        [np.asarray(l).ravel() for l in jax.tree.leaves(state.params)]
    )


class TestTrainerIntegration:
    def test_fold_path_matches_flat_path(self):
        # The traced-magnitude fold plan (Gram fast path) must train
        # identically to the flat where-path — the adaptive twin of the
        # weighted fold-vs-flat pin in test_staleness.py.
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        states = []
        for tree_path in (True, False):
            init_fn, step_fn, _ = aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="adaptive-lie", attack_params={"mag_max": 4.0},
                tree_path=tree_path,
            )
            state = init_fn(jax.random.PRNGKey(1), xs[0, 0])
            for _ in range(6):
                state, metrics = step_fn(state, x, y)
            assert np.isfinite(float(metrics["loss"]))
            states.append((
                _flat_params(state),
                float(state.attack_state["lo"]),
                float(state.attack_state["hi"]),
            ))
        np.testing.assert_allclose(
            states[0][0], states[1][0], rtol=2e-5, atol=1e-6
        )
        # Same feedback -> same bracket trajectory on both paths.
        assert states[0][1] == pytest.approx(states[1][1], abs=1e-5)
        assert states[0][2] == pytest.approx(states[1][2], abs=1e-5)

    def test_bracket_descends_under_detection(self):
        # krum's exclusion threshold is finite: starting from a wide
        # bracket, detections must pull hi below mag_max within a few
        # steps, and the played magnitude must stay inside the bracket.
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "krum", num_workers=8, f=2,
            attack="adaptive-lie", attack_params={"mag_max": 6.0},
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        mags = []
        for _ in range(10):
            state, metrics = step_fn(state, x, y)
            mags.append(float(metrics["attack_mag"]))
        assert float(state.attack_state["hi"]) < 6.0
        assert all(0.25 <= m <= 6.0 for m in mags)

    def test_rotation_runs_on_where_path(self):
        # f_pool > f with rotation gates the fold off (dynamic remap);
        # the run must still train and carry the bracket.
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "krum", num_workers=8, f=2,
            attack="adaptive-lie",
            attack_params={"f_pool": 4, "rotation": 2},
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        for _ in range(6):
            state, metrics = step_fn(state, x, y)
        assert np.isfinite(float(metrics["loss"]))
        assert state.attack_state is not None

    def test_burst_keys_on_staleness_degradation(self):
        # A staleness schedule that hard-cuts an HONEST rank every round
        # is a permanent degradation window: the attacker must play the
        # burst magnitude and hold its bracket (bursts are not probes).
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "krum", num_workers=8, f=2,
            attack="adaptive-lie",
            attack_params={"mag_max": 4.0, "burst": 3.75},
            staleness={
                "max_staleness": 2, "decay": 0.5,
                "taus": [0, 0, 0, 9, 0, 0, 0, 0],  # honest rank 3 cut
            },
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        for _ in range(4):
            state, metrics = step_fn(state, x, y)
            assert float(metrics["attack_mag"]) == pytest.approx(3.75)
        # Bracket held: every round was a burst, never a probe.
        assert float(state.attack_state["lo"]) == pytest.approx(0.25)
        assert float(state.attack_state["hi"]) == pytest.approx(4.0)

    def test_oblivious_attacks_grow_no_adaptive_state(self):
        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = aggregathor.make_trainer(
            module, loss, opt, "krum", num_workers=8, f=2, attack="lie",
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        state, _ = step_fn(state, x, y)
        assert state.attack_state is None
        assert state.defense_state is None

    def test_adaptive_rejects_explicit_mask_and_layer_granularity(self):
        module, loss, opt = _pima_setup()
        with pytest.raises(ValueError, match="byz_mask"):
            aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="adaptive-lie",
                byz_mask=np.arange(8) >= 6,
            )
        with pytest.raises(ValueError, match="granularity"):
            aggregathor.make_trainer(
                module, loss, opt, "krum", num_workers=8, f=2,
                attack="adaptive-lie", granularity="layer",
            )


class TestAttackFallbackEvent:
    def test_randomized_fold_fallback_emits_once(self):
        reset_attack_fallback()
        hub = hub_lib.MetricsHub(num_ranks=4)
        prev = hub_lib.install(hub)
        try:
            mask = np.array([False, False, True, True])
            assert plan_gradient_attack_fold("random", mask) is None
            assert plan_gradient_attack_fold("random", mask) is None
            events = [
                r for r in hub.records()
                if r.get("event") == "attack_fallback"
            ]
            assert len(events) == 1
            assert events[0]["attack"] == "random"
            assert events[0]["path"] == "where"
        finally:
            hub_lib.install(prev)
            reset_attack_fallback()

    def test_deterministic_attacks_emit_nothing(self):
        reset_attack_fallback()
        hub = hub_lib.MetricsHub(num_ranks=4)
        prev = hub_lib.install(hub)
        try:
            mask = np.array([False, False, True, True])
            assert plan_gradient_attack_fold("lie", mask) is not None
            assert not [
                r for r in hub.records()
                if r.get("event") == "attack_fallback"
            ]
        finally:
            hub_lib.install(prev)
            reset_attack_fallback()


class TestModelPlaneAdaptive:
    """The model-plane halves (DESIGN.md §17): collusion fakes from the
    GATHERED plane stack, the forward delta probe, and the in-graph
    byzsgd/learn controllers carrying their brackets."""

    def test_model_fake_lie_and_empire(self):
        rng = np.random.default_rng(0)
        stack = rng.normal(size=(5, 16)).astype(np.float32)
        lie = adaptive.model_fake("lie", stack, 2.0)
        np.testing.assert_allclose(
            lie, stack.mean(0) + 2.0 * stack.std(0, ddof=1), rtol=1e-5
        )
        emp = adaptive.model_fake("empire", stack, 3.0)
        np.testing.assert_allclose(emp, -3.0 * stack.mean(0), rtol=1e-5)

    def test_model_delta_probe_directions(self):
        rng = np.random.default_rng(1)
        d = 64
        u = rng.normal(size=d).astype(np.float64)
        u /= np.linalg.norm(u)
        drift = rng.normal(size=d) * 0.01
        prev = rng.normal(size=d)
        # Admitted: the peers' mean moved TOWARD the fake excess.
        det, score = adaptive.model_delta_probe(
            prev, prev + drift + 0.5 * u, 0.5 * u, honest_delta=drift
        )
        assert not det and score > 0.5
        # Excluded: only honest drift in the forward delta.
        det2, _ = adaptive.model_delta_probe(
            prev, prev + drift, 0.5 * u, honest_delta=drift
        )
        assert det2

    def test_byzsgd_model_bracket_converges(self):
        from garfield_tpu.parallel import byzsgd

        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = byzsgd.make_trainer(
            module, loss, opt, "krum", num_workers=8, num_ps=5,
            fw=1, fps=1,
            ps_attack="adaptive-lie", ps_attack_params={"mag_max": 8.0},
        )
        state = init_fn(jax.random.PRNGKey(0), xs[0, 0])
        lo0 = float(state.attack_state["lo"])
        hi0 = float(state.attack_state["hi"])
        for _ in range(10):
            state, metrics = step_fn(state, x, y)
        lo, hi = (float(state.attack_state[k]) for k in ("lo", "hi"))
        # Real probes happened and the bracket moved off its init.
        assert "ps_attack_mag" in metrics
        assert (hi - lo) < (hi0 - lo0)
        assert np.isfinite(float(metrics["loss"]))

    def test_learn_gossip_bracket_converges(self):
        from garfield_tpu.parallel import learn

        module, loss, opt = _pima_setup()
        xs, x, y = _pima_batches(8, 16)
        init_fn, step_fn, _ = learn.make_trainer(
            module, loss, opt, "krum", num_nodes=8, f=2,
            model_attack="adaptive-lie",
            model_attack_params={"mag_max": 8.0},
        )
        state = init_fn(jax.random.PRNGKey(1), xs[0, 0])
        for _ in range(10):
            state, metrics = step_fn(state, x, y)
        lo, hi = (float(state.attack_state[k]) for k in ("lo", "hi"))
        assert "model_attack_mag" in metrics
        assert hi - lo < 8.0 - 0.25
        assert np.isfinite(float(metrics["loss"]))

    def test_adaptive_ps_attack_rejects_explicit_mask(self):
        from garfield_tpu.parallel import byzsgd

        module, loss, opt = _pima_setup()
        with pytest.raises(ValueError, match="rotation schedule"):
            byzsgd.make_trainer(
                module, loss, opt, "krum", num_workers=8, num_ps=5,
                fw=1, fps=1, ps_attack="adaptive-lie",
                byz_ps_mask=np.array([False] * 4 + [True]),
            )

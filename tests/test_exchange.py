"""PeerExchange: host-level wait-n-f over TCP + the native MRMW register.

These are the tests that fail if MultiBuffer breaks in a way a user feels
(VERDICT r1 #8): the exchange's blocking rendezvous IS the register —
frames land via ``write``, ``collect`` wakes via ``read(min_version)``.
Three peers run in one process on localhost ports; the cross-process case
is covered by tests/test_multihost_integration.py.
"""

import socket

import pytest

pytest.importorskip("garfield_tpu.native")
from garfield_tpu import native

if native.load() is None:  # no compiler / native runtime in this env
    pytest.skip("native runtime unavailable", allow_module_level=True)

from garfield_tpu.utils.exchange import PeerExchange


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _mesh(n):
    hosts = [f"127.0.0.1:{p}" for p in _ports(n)]
    return [PeerExchange(i, hosts) for i in range(n)]


def test_all_publish_all_collect():
    peers = _mesh(3)
    try:
        for step in range(3):  # versions advance across steps
            for p in peers:
                p.publish(step, f"s{step}p{p.my_index}".encode())
            for p in peers:
                got = p.collect(step, q=3, timeout_ms=10_000)
                assert got == {
                    i: f"s{step}p{i}".encode() for i in range(3)
                }
    finally:
        for p in peers:
            p.close()


def test_wait_nf_excludes_straggler():
    peers = _mesh(3)
    try:
        # Peer 2 never publishes: the q=2 quorum must return without it.
        for p in peers[:2]:
            p.publish(0, bytes([p.my_index]))
        got = peers[0].collect(0, q=2, timeout_ms=10_000)
        assert set(got) == {0, 1}
        # ...and demanding all 3 times out (ps.py:84-88 bounded-wait exit).
        with pytest.raises(TimeoutError):
            peers[1].collect(0, q=3, timeout_ms=300)
    finally:
        for p in peers:
            p.close()


def test_overwritten_step_is_not_mixed_in():
    """Exact-step semantics: once a peer's newer frame overwrites the
    requested step in the last-writer-wins register, that peer cannot join
    the quorum with wrong-iteration data — the collect times out instead."""
    peers = _mesh(2)
    try:
        peers[0].publish(0, b"own-step0")
        peers[1].publish(0, b"peer-step0")
        peers[1].publish(1, b"peer-step1")  # overwrites step 0 in flight
        # Wait until peer 1's frames have landed in peer 0's register.
        import time

        deadline = time.time() + 10
        while peers[0]._mb.version(1) < 2 and time.time() < deadline:
            time.sleep(0.02)
        got = peers[0].collect(0, q=1, timeout_ms=5_000)
        assert got == {0: b"own-step0"}  # own slot still holds step 0
        with pytest.raises(TimeoutError):
            peers[0].collect(0, q=2, timeout_ms=300)  # step 0 gone for peer 1
    finally:
        for p in peers:
            p.close()


def test_publish_does_not_stall_on_crashed_peer():
    """ADVICE r2 (medium): once a peer has crashed, every subsequent
    publish must not burn the full first-connect grace window
    (connect_retry_ms, default 10 s) re-dialing it — reconnects get one
    short attempt and the frame is dropped (fire-and-forget contract)."""
    import time

    peers = _mesh(2)
    try:
        for p in peers:
            p.publish(0, b"warm")  # establishes both send sockets
        for p in peers:
            assert len(p.collect(0, q=2, timeout_ms=10_000)) == 2
        peers[1].close()  # peer 1 crashes
        # Publishes from peer 0 keep flowing; each must return fast even
        # though peer 1's endpoint now refuses/ignores connections.
        t0 = time.monotonic()
        for step in range(1, 4):
            peers[0].publish(step, b"alone")
        elapsed = time.monotonic() - t0
        assert elapsed < peers[0].connect_retry_ms / 1000.0, (
            f"publish stalled {elapsed:.1f}s on a crashed peer"
        )
        # Own slot still collects: the survivor makes progress at q=1.
        got = peers[0].collect(3, q=1, timeout_ms=5_000)
        assert got == {0: b"alone"}
    finally:
        for p in peers:
            p.close()


def test_read_latest_catches_up_past_overwrites():
    """read_latest: a slow consumer of a fast producer's last-writer-wins
    slot accepts the NEWEST frame >= its expected step instead of dying on
    the overwritten exact step (the cluster worker's model-plane read)."""
    import threading
    import time

    peers = _mesh(2)
    try:
        # Producer races ahead: steps 0..3 land, only 3 survives.
        for s in range(4):
            peers[1].publish(s, f"m{s}".encode())
        deadline = time.time() + 10
        while peers[0]._mb.version(1) < 4 and time.time() < deadline:
            time.sleep(0.02)
        step, payload = peers[0].read_latest(1, 1, timeout_ms=5_000)
        assert (step, payload) == (3, b"m3")
        # Expecting a FUTURE step blocks until it is published.
        result = {}

        def waiter():
            result["got"] = peers[0].read_latest(1, 7, timeout_ms=15_000)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)
        peers[1].publish(7, b"m7")
        t.join(timeout=15)
        assert not t.is_alive()
        assert result["got"] == (7, b"m7")
        # And a producer that never advances times out.
        with pytest.raises(TimeoutError):
            peers[0].read_latest(1, 99, timeout_ms=200)
    finally:
        for p in peers:
            p.close()


def test_late_joiner_catches_up():
    """A collect blocked on a not-yet-published step wakes when the frame
    arrives — the blocking-read path of the register, no polling."""
    import threading
    import time

    peers = _mesh(2)
    try:
        result = {}

        def waiter():
            result.update(peers[0].collect(5, q=2, timeout_ms=15_000))

        peers[0].publish(5, b"self")
        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.2)  # let the waiter block on the register
        peers[1].publish(5, b"late")
        t.join(timeout=15)
        assert not t.is_alive()
        assert result == {0: b"self", 1: b"late"}
    finally:
        for p in peers:
            p.close()


def test_collect_begin_cancel_retires_waiters():
    """Watcher lifecycle (DESIGN.md §14 satellite): a registration a role
    never harvests must cancel its waiter threads promptly — before this
    fix they lingered until the deadline or close(), leaking one thread
    per peer per abandoned round."""
    import time

    peers = _mesh(2)
    try:
        wait = peers[0].collect_begin(50, q=2, timeout_ms=600_000)
        time.sleep(0.3)
        assert sum(t.is_alive() for t in peers[0]._waiters) == 2
        wait.cancel()
        deadline = time.time() + 5
        while (any(t.is_alive() for t in peers[0]._waiters)
               and time.time() < deadline):
            time.sleep(0.05)
        assert not any(t.is_alive() for t in peers[0]._waiters)
    finally:
        for p in peers:
            p.close()


def test_harvest_auto_cancels_pending_waiters():
    """A harvested registration releases its beyond-quorum waiters
    immediately instead of at their deadline."""
    import time

    peers = _mesh(3)
    try:
        for p in peers[:2]:
            p.publish(4, b"x")
        wait = peers[0].collect_begin(4, q=2, timeout_ms=600_000)
        got = wait()
        assert set(got) == {0, 1}
        deadline = time.time() + 5
        while (any(t.is_alive() for t in peers[0]._waiters)
               and time.time() < deadline):
            time.sleep(0.05)
        assert not any(t.is_alive() for t in peers[0]._waiters), (
            "peer 2's waiter survived the harvest"
        )
    finally:
        for p in peers:
            p.close()


def test_read_latest_begin_cancel_retires_watcher():
    import time

    peers = _mesh(2)
    try:
        wait = peers[0].read_latest_begin(1, 99)
        time.sleep(0.2)
        assert any(t.is_alive() for t in peers[0]._waiters)
        wait.cancel()
        deadline = time.time() + 5
        while (any(t.is_alive() for t in peers[0]._waiters)
               and time.time() < deadline):
            time.sleep(0.05)
        assert not any(t.is_alive() for t in peers[0]._waiters)
    finally:
        for p in peers:
            p.close()


def test_round_collector_stale_reuse_and_cutoff():
    """The bounded-staleness quorum primitive (DESIGN.md §14): admissible
    frames are reused across gathers within the cutoff; past it the
    gather times out instead of mixing over-stale data in."""
    peers = _mesh(3)
    try:
        col = peers[0].round_collector([1, 2])
        peers[1].publish(5, b"p1r5", to=[0])
        peers[2].publish(3, b"p2r3", to=[0])
        got = col.gather(5, 2, max_staleness=2, timeout_ms=10_000)
        assert got == {1: (5, b"p1r5"), 2: (3, b"p2r3")}
        # Stale REUSE: round 6 re-admits peer 2's round-3 frame (tau=3)
        # without a re-collect; peer 1's new frame is the fresh floor.
        peers[1].publish(6, b"p1r6", to=[0])
        got = col.gather(6, 2, max_staleness=3, timeout_ms=10_000)
        assert got == {1: (6, b"p1r6"), 2: (3, b"p2r3")}
        # Hard cutoff: at round 8 with max_staleness=2 the round-3 frame
        # is inadmissible — 1/2 peers only.
        peers[1].publish(8, b"p1r8", to=[0])
        with pytest.raises(TimeoutError, match="1/2"):
            col.gather(8, 2, max_staleness=2, timeout_ms=300)
        col.close()
    finally:
        for p in peers:
            p.close()


def test_round_collector_freshness_membership_transform():
    """One mesh (the close() tax dominates this file's runtime), three
    contracts: the freshness floor (a gather must include >= 1 NEW
    arrival — no free-running on cached frames), membership changes
    (remove_peer retires the watcher + frame, add_peer restarts — the
    churn leave/join path), and the transform-error ban-evidence storage
    (same contract as collect())."""
    import threading
    import time

    peers = _mesh(3)
    try:
        # --- freshness floor (collector over peer 1 only) -------------
        col = peers[0].round_collector([1])
        peers[1].publish(1, b"r1", to=[0])
        assert col.gather(1, 1, max_staleness=4, timeout_ms=10_000) == {
            1: (1, b"r1")
        }
        result = {}

        def g():
            result.update(col.gather(2, 1, max_staleness=4,
                                     timeout_ms=15_000))

        t = threading.Thread(target=g)
        t.start()
        time.sleep(0.4)
        assert not result, "gather returned without a fresh arrival"
        peers[1].publish(2, b"r2", to=[0])
        t.join(timeout=10)
        assert result == {1: (2, b"r2")}
        # require_fresh=False reuses freely.
        assert col.gather(3, 1, max_staleness=4, timeout_ms=10_000,
                          require_fresh=False) == {1: (2, b"r2")}

        # --- membership (second collector, peers 1+2) ------------------
        col2 = peers[0].round_collector([1, 2])
        peers[2].publish(2, b"b", to=[0])
        col2.gather(2, 2, max_staleness=0, timeout_ms=10_000)
        col2.remove_peer(2)
        assert col2.peers() == [1]
        peers[1].publish(3, b"a3", to=[0])
        assert col2.gather(3, 1, max_staleness=0, timeout_ms=10_000) == {
            1: (3, b"a3")
        }
        col2.add_peer(2)
        peers[2].publish(3, b"b3", to=[0])
        got = col2.gather(3, 2, max_staleness=0, timeout_ms=10_000,
                          require_fresh=False)
        assert got == {1: (3, b"a3"), 2: (3, b"b3")}

        # --- transform error stored as ban evidence --------------------
        def boom(idx, payload):
            raise ValueError(f"bad frame from {idx}")

        col3 = peers[0].round_collector([2], transform=boom)
        peers[2].publish(4, b"x", to=[0])
        tag, payload = col3.gather(
            4, 1, max_staleness=0, timeout_ms=10_000
        )[2]
        assert tag == 4 and isinstance(payload, ValueError)

        # --- close() retires every watcher -----------------------------
        for c in (col, col2, col3):
            c.close()
            assert c.peers() == []
        deadline = time.time() + 5
        while (any(t.is_alive() for t in peers[0]._waiters)
               and time.time() < deadline):
            time.sleep(0.05)
        assert not any(t.is_alive() for t in peers[0]._waiters)
    finally:
        for p in peers:
            p.close()


def test_collect_begin_latches_before_overwrite():
    """Pre-registered waiters (collect_begin) must latch a frame that is
    later overwritten — the publish-then-collect race a symmetric gossip
    protocol hits on an oversubscribed host (apps/cluster._run_learn)."""
    import time

    peers = _mesh(2)
    try:
        wait = peers[0].collect_begin(7, q=2, timeout_ms=15_000)
        time.sleep(0.2)  # waiters blocked on the register
        peers[1].publish(7, b"frame7")
        time.sleep(0.2)  # latched by the blocked reader...
        peers[1].publish(8, b"frame8")  # ...then overwritten in the slot
        peers[0].publish(7, b"self")
        got = wait()
        assert got == {0: b"self", 1: b"frame7"}

        # Control: a collect REGISTERED after the overwrite cannot see 7.
        with pytest.raises(TimeoutError):
            peers[0].collect(7, q=1, peers=[1], timeout_ms=300)
    finally:
        for p in peers:
            p.close()


def _mesh_planes(n, planes):
    hosts = [f"127.0.0.1:{p}" for p in _ports(n)]
    return [PeerExchange(i, hosts, planes=planes) for i in range(n)]


def test_per_plane_slots_do_not_overwrite_each_other():
    """DESIGN.md §15: each (peer, plane) has its OWN register slot, so a
    multi-plane protocol (LEARN async gossip) publishing gradients and
    models for the same round no longer loses one plane's frame to the
    other's last-writer-wins overwrite — the multiplexing limitation the
    per-plane refactor removes."""
    peers = _mesh_planes(2, 3)
    try:
        # Same ROUND TAG on every plane: before per-plane slots, these
        # three publishes would overwrite one register cell.
        peers[1].publish(5, b"grad", plane=1)
        peers[1].publish(5, b"model", plane=2)
        peers[1].publish(5, b"ctrl", plane=0)
        assert peers[0].collect(
            5, q=1, peers=[1], plane=1, timeout_ms=10_000
        ) == {1: b"grad"}
        assert peers[0].collect(
            5, q=1, peers=[1], plane=2, timeout_ms=10_000
        ) == {1: b"model"}
        assert peers[0].collect(
            5, q=1, peers=[1], plane=0, timeout_ms=10_000
        ) == {1: b"ctrl"}
        # read_latest is plane-scoped too.
        step, payload = peers[0].read_latest(1, 5, plane=2)
        assert (step, payload) == (5, b"model")
    finally:
        for p in peers:
            p.close()


def test_plane_out_of_range_rejected():
    """ISSUE 13 satellite (boundary): the plane/shard tag rides spare
    header bits, so EVERY plane-taking entry point must fail loudly at
    the exact capacity boundary — a silently truncated tag would
    deliver one shard's frames into another shard's fold."""
    peers = _mesh_planes(2, 2)
    try:
        # In-range boundary works...
        peers[0].publish(1, b"ok", plane=1)
        # ...one past it fails on every entry point, loudly.
        with pytest.raises(ValueError):
            peers[0].publish(1, b"x", plane=2)
        with pytest.raises(ValueError):
            peers[0].round_collector([1], plane=5)
        with pytest.raises(ValueError):
            peers[0].collect_begin(1, q=1, peers=[1], plane=2)
        with pytest.raises(ValueError):
            peers[0].read_latest_begin(1, 0, plane=2)
        with pytest.raises(ValueError):
            peers[0].read_latest(1, 0, plane=2, timeout_ms=10)
        with pytest.raises(ValueError):
            peers[0].publish(1, b"x", plane=-1)
        # Non-integral tags are rejected, not int()-truncated.
        with pytest.raises(TypeError):
            peers[0].publish(1, b"x", plane=1.5)
    finally:
        for p in peers:
            p.close()
    with pytest.raises(ValueError):
        PeerExchange(0, ["127.0.0.1:1"], planes=0)
    # The exchange's plane space is capped at the wire header nibble's
    # 16 values — planes=17 must be refused at construction.
    with pytest.raises(ValueError):
        PeerExchange(0, ["127.0.0.1:1"], planes=17)


def test_round_collectors_per_plane_independent():
    """One collector per plane over the SAME peers: each gathers its own
    plane's frames, and newest() reads that plane's swarm clock."""
    peers = _mesh_planes(2, 3)
    try:
        cg = peers[0].round_collector([1], plane=1)
        cm = peers[0].round_collector([1], plane=2)
        peers[1].publish(3, b"g3", plane=1)
        peers[1].publish(2, b"m2", plane=2)
        got_g = cg.gather(3, 1, timeout_ms=10_000)
        got_m = cm.gather(2, 1, timeout_ms=10_000)
        assert got_g == {1: (3, b"g3")}
        assert got_m == {1: (2, b"m2")}
        assert cg.newest() == 3 and cm.newest() == 2
        cg.close()
        cm.close()
    finally:
        for p in peers:
            p.close()


def test_remove_peer_tears_down_all_watchers():
    """Regression (ISSUE 9 satellite): a churn leave used to cancel the
    round collector's watcher for the departed peer but LEAK any
    read_latest_begin latch (and leave collect waiters to their
    deadline). exchange.remove_peer now retires collect waiters,
    read_latest latches AND collector watchers on that peer
    symmetrically — and only that peer's."""
    import time

    peers = _mesh(3)
    try:
        ex = peers[0]
        # One of each watcher kind on peer 1, plus controls on peer 2.
        latch = ex.read_latest_begin(1, 99)
        wait = ex.collect_begin(42, q=2, peers=[1, 2], timeout_ms=600_000)
        col = ex.round_collector([1, 2])
        time.sleep(0.3)
        alive0 = sum(t.is_alive() for t in ex._waiters)
        assert alive0 >= 5  # latch + 2 collect waiters + 2 col watchers

        ex.remove_peer(1)
        deadline = time.time() + 5
        while (sum(t.is_alive() for t in ex._waiters) > 2
               and time.time() < deadline):
            time.sleep(0.05)
        # Exactly peer 2's collect waiter + collector watcher survive.
        assert sum(t.is_alive() for t in ex._waiters) == 2
        assert col.peers() == [2]

        # The collector still gathers from the survivor; the removed
        # peer's frames cannot resurrect.
        peers[2].publish(7, b"ok")
        assert col.gather(7, 1, timeout_ms=10_000) == {2: (7, b"ok")}
        wait.cancel()
        latch.cancel()
        col.close()
    finally:
        for p in peers:
            p.close()


# ---------------------------------------------------------------------------
# harvest-time batch transform (ISSUE 20)


def test_batch_transform_harvests_quorum_in_one_call():
    """batch_transform sees the whole quorum's latched raw frames as
    sorted (peer, payload) items in ONE call at harvest time and must
    return one result per item; results map back to peers."""
    peers = _mesh(4)
    calls = []

    def batch(items):
        calls.append([i for i, _ in items])
        return [payload.decode() + "!" for _, payload in items]

    try:
        wait = peers[0].collect_begin(
            0, q=3, peers=[1, 2, 3], timeout_ms=10_000,
            batch_transform=batch,
        )
        for p in peers[1:]:
            p.publish(0, f"p{p.my_index}".encode(), to=[0])
        got = wait()
    finally:
        for p in peers:
            p.close()
    assert len(calls) == 1 and calls[0] == sorted(calls[0])
    assert got == {i: f"p{i}!" for i in calls[0]}


def test_batch_transform_exception_results_and_hook_failure():
    """Step 0: an exception INSTANCE returned for one item is stored for
    that peer only (the per-frame transform's stored-exception
    convention, batched). Step 1: the whole hook raising stores the
    exception for EVERY item. One mesh, two rounds — the close cost of
    a localhost mesh dominates these tests."""
    peers = _mesh(3)

    def batch_instance(items):
        return [
            ValueError(f"bad {i}") if i == 2 else len(p)
            for i, p in items
        ]

    def batch_raise(items):
        raise RuntimeError("decoder exploded")

    try:
        wait = peers[0].collect_begin(
            0, q=2, peers=[1, 2], timeout_ms=10_000,
            batch_transform=batch_instance,
        )
        peers[1].publish(0, b"fine", to=[0])
        peers[2].publish(0, b"forged", to=[0])
        got = wait()
        assert got[1] == 4
        assert isinstance(got[2], ValueError) and "bad 2" in str(got[2])

        wait = peers[0].collect_begin(
            1, q=2, peers=[1, 2], timeout_ms=10_000,
            batch_transform=batch_raise,
        )
        for p in peers[1:]:
            p.publish(1, b"x", to=[0])
        got = wait()
        assert set(got) == {1, 2}
        assert all(isinstance(v, RuntimeError) for v in got.values())
    finally:
        for p in peers:
            p.close()


def test_batch_transform_exclusivity_and_length_mismatch():
    peers = _mesh(2)
    try:
        with pytest.raises(ValueError, match="batch_transform"):
            peers[0].collect_begin(
                0, q=1, peers=[1], transform=lambda i, p: p,
                batch_transform=lambda items: [p for _, p in items],
            )
        wait = peers[0].collect_begin(
            0, q=1, peers=[1], timeout_ms=10_000,
            batch_transform=lambda items: [],
        )
        peers[1].publish(0, b"x", to=[0])
        with pytest.raises(RuntimeError, match="batch_transform"):
            wait()
    finally:
        for p in peers:
            p.close()

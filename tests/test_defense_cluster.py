"""Adaptive adversary vs closed-loop defense, end to end (slow).

The multi-process twin of tests/test_adaptive.py / test_defense.py
(DESIGN.md §16): a REAL suspicion-aware Byzantine worker process
(``--attack adaptive-lie`` — bisection magnitude fed by the broadcast
model delta) against an SSMW PS running ``--defense escalate``
(suspicion-weighted quorums + the rule ladder) with the windowed
suspicion score, over PeerExchange on localhost. Plus the on-mesh CLI
closed loop (apps/common.py escalation rebuild) driven through
app_aggregathor.main.

Registered in conftest._RUN_LAST (multi-process e2e discipline): these
spawn subprocess fleets and compile per process — minutes by design, so
they are slow-marked and collect last.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    return env


def test_adaptive_attacker_vs_escalating_ps(tmp_path):
    """1 PS (--defense escalate, windowed suspicion) + 6 workers, one of
    them a real adaptive-lie process: the deployment must finish with
    every role rc 0, the attacker must have closed real probes through
    the model-delta channel, and the PS summary must carry the schema-v7
    defense digest."""
    from garfield_tpu.utils import multihost

    n_w = 6
    pp = _ports(1 + n_w)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{pp[0]}"],
        workers=[f"127.0.0.1:{p}" for p in pp[1:]],
        task_type="ps", task_index=0,
    )
    env = _env()
    tele = str(tmp_path / "tele")
    base = [
        sys.executable, "-m", "garfield_tpu.apps.aggregathor",
        "--cluster", cfg_path,
        "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
        "--batch", "16", "--fw", "1", "--gar", "krum",
        "--num_iter", "50", "--acc_freq", "10",
        "--opt_args", '{"lr":"0.05"}',
        "--cluster_timeout_ms", "120000",
    ]
    ps = subprocess.Popen(
        base + ["--task", "ps:0", "--defense", "escalate",
                "--defense_params",
                '{"patience": 3, "theta_up": 0.35, "theta_down": 0.1}',
                "--suspicion_halflife", "10", "--telemetry", tele],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    honest = [
        subprocess.Popen(
            base + ["--task", f"worker:{k}"], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        for k in range(n_w - 1)
    ]
    attacker = subprocess.Popen(
        base + ["--task", f"worker:{n_w - 1}", "--attack", "adaptive-lie",
                "--attack_params", '{"mag_max": 4.0}',
                "--telemetry", tele],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        out, _ = ps.communicate(timeout=600)
        assert ps.returncode == 0, f"PS failed:\n{out[-2000:]}"
        summary = json.loads(
            [l for l in out.splitlines() if l.startswith("{")][-1]
        )
        assert summary["steps"] == 50
        aout, _ = attacker.communicate(timeout=180)
        assert attacker.returncode == 0, f"attacker:\n{aout[-1500:]}"
        asum = json.loads(
            [l for l in aout.splitlines() if l.startswith("{")][-1]
        )
        # The controller closed real probes through the delta channel.
        assert asum["attack_adapt"]["probes"] > 10
        for w in honest:
            w.wait(timeout=180)
            assert w.returncode == 0
    finally:
        for p in [ps, attacker, *honest]:
            if p.poll() is None:
                p.kill()
    # Schema-v7 plumbing landed in the PS stream: defense digest (the
    # per-round suspicion weights were folded) + windowed suspicion.
    recs = [
        json.loads(l)
        for l in open(os.path.join(tele, "cluster-ps.telemetry.jsonl"))
    ]
    summaries = [r for r in recs if r["kind"] == "summary"]
    assert summaries, "PS wrote no summary"
    s = summaries[-1]
    assert s["defense"] is not None and s["defense"]["rounds"] > 0
    assert s["suspicion_decayed"] is not None
    assert any(r.get("event") == "defense_weights" for r in recs)
    # The attacker's own stream carries its controller telemetry.
    wrecs = [
        json.loads(l) for l in open(os.path.join(
            tele, f"cluster-worker-{n_w - 1}.telemetry.jsonl"
        ))
    ]
    assert any(r.get("event") == "attack_adapt" for r in wrecs)


def test_onmesh_cli_closed_loop(tmp_path):
    """The on-mesh CLI loop: app_aggregathor under adaptive-lie with
    --defense escalate must train, emit attack_adapt + defense_weights
    events, and write a v7 summary with both digests."""
    from garfield_tpu.apps import aggregathor as app_aggregathor

    tele = str(tmp_path / "tele")
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        app_aggregathor.main([
            "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
            "--batch", "16", "--num_workers", "8", "--fw", "2",
            "--gar", "krum", "--attack", "adaptive-lie",
            "--attack_params", '{"mag_max": 4.0}',
            "--defense", "escalate",
            "--defense_params",
            '{"patience": 3, "theta_up": 0.35, "theta_down": 0.1}',
            "--suspicion_halflife", "12",
            "--opt_args", '{"lr":"0.05"}',
            "--num_iter", "40", "--acc_freq", "20",
            "--telemetry", tele,
        ])
    finally:
        os.chdir(cwd)
    recs = [
        json.loads(l)
        for l in open(os.path.join(tele, "telemetry.jsonl"))
    ]
    assert any(r.get("event") == "attack_adapt" for r in recs)
    assert any(r.get("event") == "defense_weights" for r in recs)
    s = [r for r in recs if r["kind"] == "summary"][-1]
    assert s["attack_adapt"]["events"] == 40
    assert s["defense"] is not None and s["defense"]["rounds"] == 40
    assert s["suspicion_decayed"] is not None


def test_learn_per_plane_defense_with_adaptive_gossip_node(tmp_path):
    """6 LEARN nodes, one a real adaptive-lie GOSSIP poisoner
    (--model_attack adaptive-lie: collusion fake over its last gathered
    gossip stack, forward delta-probe feedback), every honest node
    running --defense escalate with INDEPENDENT per-plane ladders
    (DESIGN.md §17). Every role must exit rc 0, the attacker must close
    real probes, and an honest node's stream must carry plane-tagged
    defense events."""
    from garfield_tpu.utils import multihost

    n = 6
    pp = _ports(n)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path, nodes=[f"127.0.0.1:{p}" for p in pp],
        task_type="node", task_index=0,
    )
    env = _env()
    base = [
        sys.executable, "-m", "garfield_tpu.apps.learn",
        "--cluster", cfg_path,
        "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
        "--batch", "16", "--fw", "1", "--gar", "krum",
        "--num_iter", "10", "--acc_freq", "0",
        "--opt_args", '{"lr":"0.05"}',
        "--cluster_timeout_ms", "120000",
    ]
    tele = str(tmp_path / "tele")
    procs = []
    for k in range(n):
        argv = base + ["--task", f"node:{k}"]
        if k == n - 1:
            argv += ["--model_attack", "adaptive-lie",
                     "--model_attack_params", '{"mag_max": 4.0}']
        else:
            argv += ["--defense", "escalate",
                     "--suspicion_halflife", "8",
                     "--telemetry", tele]
        procs.append(subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for k, p in enumerate(procs):
        out, _ = p.communicate(timeout=600)
        outs.append(out)
        assert p.returncode == 0, f"node {k} failed:\n{out[-2000:]}"
    # The attacker ran REAL probes through the gossip delta channel.
    atk = json.loads(
        [l for l in outs[-1].splitlines() if l.startswith("{")][-1]
    )
    assert atk["model_attack_adapt"]["probes"] > 0
    # An honest node's stream carries plane-tagged defense evidence for
    # BOTH planes (independent histories).
    recs = [
        json.loads(l)
        for l in open(os.path.join(tele, "cluster-node-0.telemetry.jsonl"))
    ]
    planes = {
        r.get("plane") for r in recs
        if r.get("event") == "defense_weights"
    }
    esc_planes = {
        r.get("plane") for r in recs
        if r.get("event") == "defense_escalate"
    }
    assert planes <= {"gradient", "gossip"}
    assert esc_planes <= {"gradient", "gossip"}
    # Every record (v8 events included) is schema-valid.
    from garfield_tpu.telemetry import validate_jsonl

    validate_jsonl(os.path.join(tele, "cluster-node-0.telemetry.jsonl"))


def test_msmw_defense_and_adaptive_byzantine_ps(tmp_path):
    """3 PS replicas (one a real adaptive-lie Byzantine PS probing the
    replica gather) + 6 workers (one labelflip): the honest replicas run
    the MSMW gradient-plane defense; everyone exits rc 0 and the
    Byzantine PS closes real model-plane probes."""
    from garfield_tpu.utils import multihost

    n_ps, n_w = 3, 6
    pp = _ports(n_ps + n_w)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{p}" for p in pp[:n_ps]],
        workers=[f"127.0.0.1:{p}" for p in pp[n_ps:]],
        task_type="ps", task_index=0,
    )
    env = _env()
    tele = str(tmp_path / "tele")
    base = [
        sys.executable, "-m", "garfield_tpu.apps.byzsgd",
        "--cluster", cfg_path,
        "--dataset", "pima", "--model", "pimanet", "--loss", "bce",
        "--batch", "16", "--fw", "1", "--fps", "1",
        "--gar", "krum", "--model_gar", "median",
        "--num_iter", "10", "--acc_freq", "0",
        "--opt_args", '{"lr":"0.05"}',
        "--cluster_timeout_ms", "120000",
    ]
    procs = []
    for k in range(n_ps):
        argv = base + ["--task", f"ps:{k}"]
        if k == n_ps - 1:
            argv += ["--ps_attack", "adaptive-lie",
                     "--ps_attack_params", '{"mag_max": 4.0}']
        else:
            argv += ["--defense", "escalate",
                     "--suspicion_halflife", "8", "--telemetry", tele]
        procs.append(("ps", k, subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )))
    for k in range(n_w):
        argv = base + ["--task", f"worker:{k}"]
        if k == n_w - 1:
            argv += ["--attack", "labelflip"]
        procs.append(("worker", k, subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )))
    byz_out = None
    for role, k, p in procs:
        out, _ = p.communicate(timeout=600)
        assert p.returncode == 0, f"{role} {k} failed:\n{out[-2000:]}"
        if role == "ps" and k == n_ps - 1:
            byz_out = out
    atk = json.loads(
        [l for l in byz_out.splitlines() if l.startswith("{")][-1]
    )
    assert atk["ps_attack_adapt"]["probes"] > 0
    # Honest replica telemetry: gradient-plane defense weights landed.
    recs = [
        json.loads(l)
        for l in open(os.path.join(tele, "cluster-ps-0.telemetry.jsonl"))
    ]
    assert any(r.get("event") == "defense_weights" for r in recs)

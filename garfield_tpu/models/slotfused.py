"""Slot-fused per-worker gradients: fused fwd + fused dx, per-slot dw.

The round-4 closing decomposition (PERF.md, VERDICT r4 #1) left ONE big
cost on the table: folding n logical workers onto a chip with a Python
unroll pays ~8x the op count of a single fused fwd+bwd — measured 9.0 ms
(unroll, n=8 b=25 ResNet-18 bf16) against a 5.1 ms fused lower bound,
while both do identical FLOPs. vmap closes the op count but loses more to
5-D relayouts and grouped-conv weight gradients (12.9 ms; unrolling the
grouped dw inside vmap measured WORSE, 14.0 — r5 probe).

The structural fix: per-slot gradients only *differ* from the fused
computation in the parameter-cotangent contractions. Everything else —
the forward, the activation cotangents (dx), every elementwise op — is
identical arithmetic for "n workers of batch b" and "one batch n*b". So
run the model ONCE on the flat (n*b) batch and make ONLY the parameter
gradients slot-resolved:

  - every parameter enters the forward STACKED to (slots, ...) — the jax
    autodiff cotangent of a stacked parameter IS the per-slot gradient;
  - convolutions go through ``slotlayers.slot_conv`` (jax.custom_vjp):
    primal and dx use ``w[0]`` (all slot rows are equal by construction)
    at the fused n*b batch; the dw rule computes n per-slot conv weight
    gradients (grouped-transpose default, see slotlayers);
  - dense layers become slot-batched matmuls ('sbf,sfo->sbo'), which the
    MXU handles natively;
  - BatchNorm computes per-slot statistics over the flat batch
    (``slotlayers.bn_train``: one-hot slot matmul or sorted segment sum,
    per ``GARFIELD_SLOTFUSED_BN``) — matching the per-worker BN semantics
    of the unroll path exactly;
  - scale/bias/bias-like parameters broadcast via ``slot_expand``, whose
    autodiff transpose is a per-slot segment reduction.

The result is per-slot gradients equal to the unroll path's (asserted
per-leaf in tests/test_slotfused.py — exactly for cifarnet, to deep-net
f32 reassociation tolerance for the BN families) at close to fused cost.

r5 proved the formulation on two hand-written monolithic forwards
(ResNet, Cifarnet — a 407-LoC twin covering 2 families, VERDICT r5 weak
#3); this round factors the layer machinery into
``models/slotlayers.py`` and expresses each twin as a thin GRAPH
ASSEMBLY over those primitives, registered in ``SLOTFUSED_MODELS``.
Covered families (all the dropout-free zoo members with a measured win):

  ResNet (BasicBlock + Bottleneck) · Cifarnet · VGG (11/13/16/19) ·
  GoogLeNet/Inception-v1 · MobileNet · MobileNetV2 · DenseNet-BC ·
  Transformers (ViT-tiny + GPT, tied or untied head)

The transformer twins are the family where the formulation pays most:
attention is matmul-dominated, every per-slot parameter contraction is
an 'sbf,sfo->sbo'-shaped einsum (``slotlayers.seq_dense``), the
attention core itself (``slotlayers.attn_core``) is per-example
arithmetic shared VERBATIM with the flax modules, LayerNorm statistics
are per-example (no slot reduction at all — only the affine params are
worker-resolved), and the embedding's per-slot gradient falls out of a
slot-vmapped gather's scatter-add transpose.

The twins are functional TWINS of the flax zoo modules: they consume the
exact flax param/batch_stats trees by name (flax ``nn.compact``
auto-naming — ``Conv_i`` / ``BatchNorm_i`` in creation order, submodules
``ClassName_i``), so ``core.TrainState``, checkpoints and eval keep using
the flax module while only the gradient phase routes through the twin.
Dropout models (Net/CNNet) stay unregistered — a twin cannot replicate
flax's internal rng-path folding, so equality would be unverifiable;
``build_slot_grad_fn`` returns None and callers fall back to
``core.per_slot_grads``. Topologies resolve twins through
``core.resolve_slot_grad_fn``, so a family added to the registry reaches
aggregathor, LEARN and ByzSGD with no per-topology change (LEARN's
per-node params still gate it off — see ``resolve_slot_grad_fn``).

Reference anchor: this whole module replaces the per-worker backward pass
of Aggregathor/worker.py:89-91 (one process per worker on its own GPU);
folding n workers onto one chip has no reference counterpart.
"""

import jax
import jax.numpy as jnp

from . import slotlayers as sl
from .slotlayers import SlotCtx, slot_conv  # re-export (back-compat)

__all__ = ["build_slot_grad_fn", "slot_conv", "SLOTFUSED_MODELS"]


# --------------------------------------------------------------------------
# Shared micro-assemblies
# --------------------------------------------------------------------------

def _bn(ctx, h, p, s, name, new, relu=True):
    """BatchNorm_<name> (+ ReLU), recording the slot-stacked new stats."""
    y, ns = sl.bn_train(ctx, h, p[name], s[name])
    new[name] = ns
    return sl.relu(y) if relu else y


def _cbr(ctx, h, p, s, new, i, stride=1, groups=1, relu=True):
    """conv(Conv_i) -> BN(BatchNorm_i) [-> relu], padding derived from the
    kernel shape (the zoo's convention: k//2 'torch-like' padding; the
    stacked kernel is (slots, kh, kw, ci, co))."""
    pad = p[f"Conv_{i}"]["kernel"].shape[1] // 2
    h = sl.conv(ctx, h, p[f"Conv_{i}"], stride, pad, groups)
    return _bn(ctx, h, p, s, f"BatchNorm_{i}", new, relu=relu)


# --------------------------------------------------------------------------
# ResNet twin (models/resnet.py: BasicBlock and Bottleneck stacks)
# --------------------------------------------------------------------------

def _basic_block(ctx, h, p, s, new, features, stride):
    out = _cbr(ctx, h, p, s, new, 0, stride=stride)
    out = _cbr(ctx, out, p, s, new, 1, relu=False)
    if stride != 1 or h.shape[-1] != features:
        h = _cbr(ctx, h, p, s, new, 2, stride=stride, relu=False)
    return sl.relu(out + h)


def _bottleneck(ctx, h, p, s, new, features, stride):
    out = _cbr(ctx, h, p, s, new, 0)
    out = _cbr(ctx, out, p, s, new, 1, stride=stride)
    out = _cbr(ctx, out, p, s, new, 2, relu=False)
    if stride != 1 or h.shape[-1] != features * 4:
        h = _cbr(ctx, h, p, s, new, 3, stride=stride, relu=False)
    return sl.relu(out + h)


def _resnet_twin(module):
    from . import resnet

    if module.block is resnet.BasicBlock:
        block_fn, kind = _basic_block, "BasicBlock"
    elif module.block is resnet.Bottleneck:
        block_fn, kind = _bottleneck, "Bottleneck"
    else:
        return None
    stage_sizes = tuple(module.stage_sizes)

    def forward(ctx, p_st, stats, x):
        new = {}
        h = _cbr(ctx, x.astype(ctx.dtype), p_st, stats, new, 0)
        idx = 0
        for stage, nblocks in enumerate(stage_sizes):
            for i in range(nblocks):
                stride = 2 if stage > 0 and i == 0 else 1
                name = f"{kind}_{idx}"
                bnew = {}
                h = block_fn(
                    ctx, h, p_st[name], stats[name], bnew,
                    64 * 2 ** stage, stride,
                )
                new[name] = bnew
                idx += 1
        h = sl.global_avg_pool(h)
        return sl.dense(ctx, h, p_st["Dense_0"]), new

    return forward


# --------------------------------------------------------------------------
# Cifarnet twin (models/nets.py:40-57 — biased convs + dense head, no BN)
# --------------------------------------------------------------------------

def _cifarnet_twin(module):
    def forward(ctx, p_st, stats, x):
        del stats
        h = sl.max_pool(
            sl.relu(sl.conv(ctx, x.astype(ctx.dtype), p_st["Conv_0"], 1, 0)),
            2,
        )
        h = sl.max_pool(sl.relu(sl.conv(ctx, h, p_st["Conv_1"], 1, 0)), 2)

        def dense(h, name, relu=True):
            y = sl.dense(ctx, h.reshape(ctx.slots * ctx.nb, -1), p_st[name])
            return sl.relu(y) if relu else y

        h = dense(h, "Dense_0")
        h = dense(h, "Dense_1")
        return dense(h, "Dense_2", relu=False), {}

    return forward


# --------------------------------------------------------------------------
# VGG twin (models/vgg.py: conv+BN+ReLU stacks from the cfg table)
# --------------------------------------------------------------------------

def _vgg_twin(module):
    from . import vgg

    layer_cfg = tuple(vgg.cfg[module.name_cfg])

    def forward(ctx, p_st, stats, x):
        new = {}
        h = x.astype(ctx.dtype)
        ci = 0
        for v in layer_cfg:
            if v == "M":
                h = sl.max_pool(h, 2)
            else:
                h = _cbr(ctx, h, p_st, stats, new, ci)
                ci += 1
        h = h.reshape(h.shape[0], -1)
        return sl.dense(ctx, h, p_st["Dense_0"]), new

    return forward


# --------------------------------------------------------------------------
# GoogLeNet / Inception-v1 twin (models/googlenet.py)
# --------------------------------------------------------------------------

def _inception_block(ctx, h, p, s, new):
    """Inception submodule: four branches, Conv_i/BatchNorm_i in flax
    creation order (b1: 0; b2: 1-2; b3: 3-5; b4: 6), channel concat."""
    b1 = _cbr(ctx, h, p, s, new, 0)
    b2 = _cbr(ctx, _cbr(ctx, h, p, s, new, 1), p, s, new, 2)
    b3 = _cbr(ctx, _cbr(ctx, _cbr(ctx, h, p, s, new, 3), p, s, new, 4),
              p, s, new, 5)
    b4 = _cbr(ctx, sl.max_pool(h, 3, 1, padding=1), p, s, new, 6)
    return jnp.concatenate([b1, b2, b3, b4], axis=-1)


def _googlenet_twin(module):
    def forward(ctx, p_st, stats, x):
        new = {}
        h = _cbr(ctx, x.astype(ctx.dtype), p_st, stats, new, 0)
        for i in range(9):
            name = f"Inception_{i}"
            bnew = {}
            h = _inception_block(ctx, h, p_st[name], stats[name], bnew)
            new[name] = bnew
            if i in (1, 6):  # max_pool(3, 2, pad 1) after b3/e4 stacks
                h = sl.max_pool(h, 3, 2, padding=1)
        h = sl.global_avg_pool(h)
        return sl.dense(ctx, h, p_st["Dense_0"]), new

    return forward


# --------------------------------------------------------------------------
# MobileNet v1 twin (models/mobilenet.py: depthwise-separable stacks)
# --------------------------------------------------------------------------

def _mobilenet_twin(module):
    from . import mobilenet

    block_cfg = tuple(
        (v, 1) if isinstance(v, int) else v for v in mobilenet.cfg
    )

    def forward(ctx, p_st, stats, x):
        new = {}
        h = _cbr(ctx, x.astype(ctx.dtype), p_st, stats, new, 0)
        for i, (_out, stride) in enumerate(block_cfg):
            name = f"Block_{i}"
            bnew = {}
            p, s = p_st[name], stats[name]
            # depthwise 3x3 (groups = in_planes), then pointwise 1x1
            h = _cbr(ctx, h, p, s, bnew, 0, stride=stride,
                     groups=h.shape[-1])
            h = _cbr(ctx, h, p, s, bnew, 1)
            new[name] = bnew
        h = sl.global_avg_pool(h)
        return sl.dense(ctx, h, p_st["Dense_0"]), new

    return forward


# --------------------------------------------------------------------------
# MobileNetV2 twin (models/mobilenetv2.py: inverted residual blocks)
# --------------------------------------------------------------------------

def _inverted_residual(ctx, h, p, s, new, stride):
    out = _cbr(ctx, h, p, s, new, 0)                        # expand 1x1
    out = _cbr(ctx, out, p, s, new, 1, stride=stride,
               groups=out.shape[-1])                        # depthwise 3x3
    out = _cbr(ctx, out, p, s, new, 2, relu=False)          # project 1x1
    if stride == 1:
        if "Conv_3" in p:                                   # channel-match
            h = _cbr(ctx, h, p, s, new, 3, relu=False)
        out = out + h
    return out


def _mobilenetv2_twin(module):
    from . import mobilenetv2

    strides = []
    for _exp, _out, num_blocks, stride in mobilenetv2.cfg:
        strides += [stride] + [1] * (num_blocks - 1)

    def forward(ctx, p_st, stats, x):
        new = {}
        h = _cbr(ctx, x.astype(ctx.dtype), p_st, stats, new, 0)
        for i, stride in enumerate(strides):
            name = f"InvertedResidual_{i}"
            bnew = {}
            h = _inverted_residual(
                ctx, h, p_st[name], stats[name], bnew, stride
            )
            new[name] = bnew
        h = _cbr(ctx, h, p_st, stats, new, 1)               # head 1x1 1280
        h = sl.global_avg_pool(h)
        return sl.dense(ctx, h, p_st["Dense_0"]), new

    return forward


# --------------------------------------------------------------------------
# DenseNet-BC twin (models/densenet.py: pre-activation bottlenecks)
# --------------------------------------------------------------------------

def _dense_bottleneck(ctx, h, p, s, new):
    out = sl.conv(ctx, _bn(ctx, h, p, s, "BatchNorm_0", new),
                  p["Conv_0"], 1, 0)
    out = sl.conv(ctx, _bn(ctx, out, p, s, "BatchNorm_1", new),
                  p["Conv_1"], 1, 1)
    return jnp.concatenate([out, h], axis=-1)


def _densenet_twin(module):
    nblocks = tuple(module.nblocks)

    def forward(ctx, p_st, stats, x):
        new = {}
        h = sl.conv(ctx, x.astype(ctx.dtype), p_st["Conv_0"], 1, 1)
        bi = 0
        for i, nb in enumerate(nblocks):
            for _ in range(nb):
                name = f"Bottleneck_{bi}"
                bnew = {}
                h = _dense_bottleneck(ctx, h, p_st[name], stats[name], bnew)
                new[name] = bnew
                bi += 1
            if i != len(nblocks) - 1:
                name = f"Transition_{i}"
                bnew = {}
                p, s = p_st[name], stats[name]
                h = sl.conv(ctx, _bn(ctx, h, p, s, "BatchNorm_0", bnew),
                            p["Conv_0"], 1, 0)
                h = sl.avg_pool(h, 2)
                new[name] = bnew
        h = _bn(ctx, h, p_st, stats, "BatchNorm_0", new)
        h = sl.global_avg_pool(h)
        return sl.dense(ctx, h, p_st["Dense_0"]), new

    return forward


# --------------------------------------------------------------------------
# Transformer twins (models/transformer.py: ViT-tiny + GPT)
# --------------------------------------------------------------------------

def _encoder_block(ctx, h, p, heads, causal):
    """EncoderBlock twin: pre-LN attention + GELU MLP, both residual.

    Mirrors models/transformer.py:EncoderBlock layer for layer — the
    attention core is the SAME ``sl.attn_core`` callable the flax module
    traces, so only the per-slot projections (``seq_dense``) and the
    per-slot LayerNorm affines differ from the unrolled reference.
    """
    hn = sl.layer_norm(ctx, h, p["LayerNorm_0"])
    qkv = sl.seq_dense(ctx, hn, p["Dense_0"])
    q, k, v = jnp.split(qkv, 3, axis=-1)
    dim = q.shape[-1]
    shape = q.shape[:-1] + (heads, dim // heads)
    a = sl.attn_core(
        q.reshape(shape), k.reshape(shape), v.reshape(shape), causal=causal
    )
    a = a.reshape(a.shape[:-2] + (dim,))
    h = h + sl.seq_dense(ctx, a, p["Dense_1"])
    hn = sl.layer_norm(ctx, h, p["LayerNorm_1"])
    m = sl.gelu(sl.seq_dense(ctx, hn, p["Dense_2"]))
    return h + sl.seq_dense(ctx, m, p["Dense_3"])


def _vit_twin(module):
    patch, dim = int(module.patch), int(module.dim)
    heads, depth = int(module.heads), int(module.depth)

    def forward(ctx, p_st, stats, x):
        del stats
        h = sl.conv(ctx, x.astype(ctx.dtype), p_st["Conv_0"], patch, 0)
        h = h.reshape(h.shape[0], -1, dim)
        h = sl.pos_embed(ctx, h, p_st["pos_embedding"])
        for i in range(depth):
            h = _encoder_block(
                ctx, h, p_st[f"EncoderBlock_{i}"], heads, False
            )
        h = sl.layer_norm(ctx, h, p_st["LayerNorm_0"])
        h = jnp.mean(h, axis=1)
        return sl.dense(ctx, h, p_st["Dense_0"]), {}

    return forward


def _gpt_twin(module):
    heads, depth = int(module.heads), int(module.depth)
    tied = bool(module.tied)

    def forward(ctx, p_st, stats, x):
        del stats
        h = sl.embed(ctx, x, p_st["Embed_0"]["embedding"])
        h = sl.pos_embed(ctx, h, p_st["pos_embedding"])
        for i in range(depth):
            h = _encoder_block(
                ctx, h, p_st[f"EncoderBlock_{i}"], heads, True
            )
        h = sl.layer_norm(ctx, h, p_st["LayerNorm_0"])
        h = h[:, -1]
        if tied:
            # Embedding-tied head (nn.Embed.attend): a per-slot einsum
            # against the SAME stacked table — autodiff accumulates its
            # cotangent into the embedding's per-slot gradient alongside
            # the lookup's scatter-add, exactly like the unrolled path.
            h3 = h.reshape(ctx.slots, ctx.nb, -1).astype(ctx.dtype)
            emb = p_st["Embed_0"]["embedding"].astype(ctx.dtype)
            return jnp.einsum("sbf,svf->sbv", h3, emb), {}
        return sl.dense(ctx, h, p_st["Dense_0"]), {}

    return forward


# --------------------------------------------------------------------------
# Registry + dispatch
# --------------------------------------------------------------------------

def _registry():
    from . import densenet, googlenet, mobilenet, mobilenetv2, nets, \
        resnet, transformer, vgg

    return {
        resnet.ResNet: _resnet_twin,
        nets.Cifarnet: _cifarnet_twin,
        vgg.VGG: _vgg_twin,
        googlenet.GoogLeNet: _googlenet_twin,
        mobilenet.MobileNet: _mobilenet_twin,
        mobilenetv2.MobileNetV2: _mobilenetv2_twin,
        densenet.DenseNet: _densenet_twin,
        transformer.ViT: _vit_twin,
        transformer.GPT: _gpt_twin,
    }


#: The twin table (flax module class -> builder). A builder takes the
#: module instance and returns ``forward(ctx, p_st, stats, x_flat) ->
#: (logits (slots, b, classes), new_batch_stats)`` — or None when this
#: particular instance has no twin (e.g. an unknown ResNet block class).
#: Register a new family here (or mutate the dict) and every topology
#: picks it up through ``core.resolve_slot_grad_fn``.
SLOTFUSED_MODELS = _registry()


def build_slot_grad_fn(module, loss_fn):
    """A drop-in for the vmap/unroll per-slot gradient computation.

    Returns ``fn(params, model_state, x, y, keys) -> (grads, (loss, ms))``
    with the same shapes/semantics as
    ``jax.vmap(grad_fn, in_axes=(None, None, 0, 0, 0))`` — stacked grads,
    per-slot losses, per-slot updated batch_stats — or None when the
    module has no twin (callers fall back to ``core.per_slot_grads``).
    Resolution is by module class against ``SLOTFUSED_MODELS``.
    """
    builder = None
    for cls, b in SLOTFUSED_MODELS.items():
        if isinstance(module, cls):
            builder = b
            break
    if builder is None:
        return None
    forward = builder(module)
    if forward is None:
        return None
    dtype = getattr(module, "dtype", jnp.float32)

    def slot_grad_fn(params, model_state, x, y, keys):
        del keys  # twins exist only for deterministic (dropout-free) models
        slots, b = x.shape[0], x.shape[1]
        # Per-trace context: slot geometry + the slot matrix / segment ids
        # built ONCE and shared by every BN layer of the twin.
        ctx = SlotCtx(slots, b, dtype)
        x_flat = x.reshape((slots * b,) + x.shape[2:])
        stats = model_state.get("batch_stats", {})
        p_st = jax.tree.map(
            lambda p: jnp.broadcast_to(p[None], (slots,) + p.shape), params
        )

        def total_loss(p_st):
            logits, new_stats = forward(ctx, p_st, stats, x_flat)
            losses = jax.vmap(loss_fn)(logits, y)  # (slots,)
            return jnp.sum(losses), (losses, new_stats)

        grads_st, (losses, new_stats) = jax.grad(
            total_loss, has_aux=True
        )(p_st)
        # Every collection comes back slot-stacked like the vmap path:
        # batch_stats per-slot from the twin, anything else broadcast.
        new_ms = {
            k: (
                new_stats if k == "batch_stats"
                else jax.tree.map(
                    lambda l: jnp.broadcast_to(
                        l[None], (slots,) + jnp.shape(l)
                    ),
                    v,
                )
            )
            for k, v in model_state.items()
        }
        return grads_st, (losses, new_ms)

    return slot_grad_fn

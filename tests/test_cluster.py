"""Cross-process cluster trainer: real wait-n-f straggler/crash tolerance.

VERDICT r2 #3: the host-level async exchange must be CONSUMED by a training
path, not just unit-tested. This launches the reference's deployment shape
(run_exp.sh fan-out: one OS process per node) — 1 PS + 4 workers over
PeerExchange — kills one worker mid-run with SIGKILL, and asserts the
survivors keep training to completion: the PS's per-step quorum is the
q = n_w - f = 3 FASTEST gradients (server.py:134-155), so the dead worker
is simply absent from every later quorum. (q of at least 3 matters for
learning quality, not just tolerance: the coordinate-wise LOWER median of
a q = 2 quorum is the elementwise min — a biased aggregate.)
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

pytest.importorskip("garfield_tpu.native")
from garfield_tpu import native

if native.load() is None:
    pytest.skip("native runtime unavailable", allow_module_level=True)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ports(k):
    socks = [socket.socket() for _ in range(k)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _launch(role, cfg_path, env, extra=()):
    return subprocess.Popen(
        [
            sys.executable, "-m", "garfield_tpu.apps.aggregathor",
            "--cluster", cfg_path, "--task", role,
            "--dataset", "mnist", "--model", "convnet", "--batch", "16",
            "--fw", "1", "--gar", "median", "--num_iter", "60",
            "--acc_freq", "10", "--train_size", "512",
            "--cluster_timeout_ms", "120000", *extra,
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )


def test_worker_crash_survivors_converge(tmp_path):
    from garfield_tpu.utils import multihost

    n_w = 4
    pp = _ports(1 + n_w)
    cfg_path = str(tmp_path / "cluster.json")
    multihost.generate_config(
        cfg_path,
        ps=[f"127.0.0.1:{pp[0]}"],
        workers=[f"127.0.0.1:{p}" for p in pp[1:]],
        task_type="ps", task_index=0,
    )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep subprocesses off the TPU
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO
    # This test is about crash tolerance, not task difficulty: pin an easy
    # surrogate margin so 60 steps show clear learning (the default margin
    # is deliberately hard — hundreds of steps to climb; data/__init__.py).
    env["GARFIELD_SURROGATE_MARGIN"] = "30"
    env["GARFIELD_SURROGATE_LABEL_NOISE"] = "0"

    ps = _launch("ps:0", cfg_path, env)
    workers = [_launch(f"worker:{w}", cfg_path, env) for w in range(n_w)]
    victim = workers[-1]
    # Watchdog: the stdout readline loop below blocks on a silent-but-alive
    # PS, so bound the whole test from a side thread instead.
    import threading

    watchdog = threading.Timer(
        420, lambda: [p.kill() for p in [ps, *workers]]
    )
    watchdog.start()
    try:
        # Wait for training to be demonstrably under way (the step-10
        # accuracy line), then SIGKILL one worker — a hard crash, not an
        # orderly close.
        first_acc = None
        deadline = time.time() + 240
        for line in ps.stdout:
            if line.startswith("Step: 0 "):
                first_acc = float(line.split()[3])
            if line.startswith("Step: 10 "):
                victim.send_signal(signal.SIGKILL)
                break
            if time.time() > deadline:
                pytest.fail("PS never reached step 10")
        else:
            pytest.fail(f"PS exited early: rc={ps.wait()}")

        rest = ps.stdout.read()
        assert ps.wait(timeout=240) == 0, f"PS failed:\n{rest[-2000:]}"
        summary = json.loads(
            [l for l in rest.splitlines() if l.startswith("{")][-1]
        )
        assert summary["steps"] == 60
        # The surrogate task is separable: 60 post-crash-tolerant steps must
        # show real learning, not just survival.
        assert summary["final_accuracy"] > max(0.3, first_acc + 0.1)

        for w in workers[:-1]:  # survivors run to the end, rc 0
            out, _ = w.communicate(timeout=240)
            assert w.returncode == 0, f"survivor failed:\n{out[-2000:]}"
            wsum = json.loads(
                [l for l in out.splitlines() if l.startswith("{")][-1]
            )
            # Catch-up semantics may skip a round under CPU load; a
            # survivor still contributes nearly every step.
            assert wsum["steps"] >= 50
        assert victim.wait(timeout=60) == -signal.SIGKILL
    finally:
        watchdog.cancel()
        for p in [ps, *workers]:
            if p.poll() is None:
                p.kill()

"""Control plane: membership, failover, shard autoscaling (DESIGN.md §22).

The deployment layer over the federated shard plane: epoch-numbered
membership views distributed as CRC-tagged records (``membership``),
heartbeat failure detection + checkpointed span handoff so a shard
death costs one round (``failover``), and latency-driven span
split/merge reusing the worker autoscaler's control law
(``shardscale``). Every membership change — failover, split, merge —
is exactly one epoch increment, stamped into every data-plane wire
frame (utils/wire v2 header) so stale-membership traffic is an
attributable reject, never a silent mis-fold.
"""

from .failover import (
    EF_RESIDUAL_RESTORED,
    HeartbeatMonitor,
    heartbeat_interval_s,
    promote_standby,
    standby_shards,
    tcp_probe,
)
from .membership import (
    CONTROL_PLANE,
    MembershipDirectory,
    MembershipView,
    Seat,
    StaleViewError,
    ViewError,
)
from .shardscale import ShardAutoscaler

__all__ = [
    "CONTROL_PLANE",
    "EF_RESIDUAL_RESTORED",
    "HeartbeatMonitor",
    "MembershipDirectory",
    "MembershipView",
    "Seat",
    "ShardAutoscaler",
    "StaleViewError",
    "ViewError",
    "heartbeat_interval_s",
    "promote_standby",
    "standby_shards",
    "tcp_probe",
]

"""Partial participation: seeded per-round cohorts with a priced f budget.

At 10^6+ clients no round ingests everyone — each round samples a cohort
and aggregates only it (the Bonawitz-style FL round structure). The
robustness consequence is the point (Baruch et al., arXiv:1902.06156):
variance-exploiting attacks get exactly as much headroom as the COHORT's
f/n ratio allows, so the Byzantine budget must be priced PER SAMPLED
COHORT, not globally — a global f declared against the population says
nothing about the round the adversary actually concentrates into.

Pricing: with a Byzantine population fraction ``p = byz_frac``, a
uniformly sampled cohort of ``c`` clients contains a hypergeometric
number of Byzantine members with mean ``c·p``; the budget charges the
mean plus ``slack_sigmas`` binomial standard deviations (the binomial
upper-bounds the hypergeometric variance), clamped into the hierarchy's
composed capacity (``aggregators.hierarchy.max_tolerated_f``). A cohort
whose priced budget exceeds what the configured hierarchy can compose is
REFUSED loudly at planning time — under-declaring f silently is exactly
the failure mode the robustness matrix tests document (budget exceeded
=> the aggregate may leave the tolerance envelope; tests/test_federated
pins both sides).

Sampling is seeded and deterministic in ``(seed, round)`` — every shard
process derives the SAME cohort without coordination (the sampler is
metadata, not state), and a committed FEDBENCH row is reproducible.
Client identity is the STABLE GLOBAL id, never the per-round cohort
index: suspicion keyed by cohort position would reset every round, which
is a free laundering channel for any resampled Byzantine client
(telemetry/hub.py keys its decayed client suspicion by these ids; the
rotation regression test pins it).

Stragglers across round boundaries compose with the bounded-staleness
policy of ``utils/rounds.py``: a sampled client that delivers a gradient
computed against an older round's model enters the cohort at weight
``decay**tau`` (``cohort_weights``), and past the hard cutoff it is
EXCLUDED from the round before the hierarchy is planned — a zero-weight
row must never reach a Gram rule, where an all-zero vector reads as a
perfectly central inlier (the same inversion DESIGN.md §18 documents for
toward-zero row scaling; recorded in §19 as a negative result, not
hidden).
"""

import math

import numpy as np

from ..aggregators import hierarchy
from ..utils import rounds as rounds_lib

__all__ = ["CohortSampler"]


class CohortSampler:
    """Seeded per-round client sampler with a per-cohort f budget."""

    def __init__(self, population, cohort_size, *, seed=0, byz_frac=0.0,
                 bucket_gar="krum", top_gar=None, bucket_size=None,
                 levels="auto", slack_sigmas=4.0, staleness=None):
        self.population = int(population)
        self.cohort_size = int(cohort_size)
        if not 1 <= self.cohort_size <= self.population:
            raise ValueError(
                f"cohort_size must be in [1, population={self.population}],"
                f" got {cohort_size}"
            )
        self.seed = int(seed)
        self.byz_frac = float(byz_frac)
        if not 0.0 <= self.byz_frac < 0.5:
            raise ValueError(
                f"byz_frac must be in [0, 0.5), got {byz_frac}"
            )
        self.slack_sigmas = float(slack_sigmas)
        self.staleness = staleness  # a rounds_lib.StalenessPolicy or None
        self._gar_cfg = dict(
            bucket_gar=bucket_gar, top_gar=top_gar, bucket_size=bucket_size,
            levels=levels,
        )

    # -- sampling -----------------------------------------------------------

    def cohort(self, round_):
        """Global client ids sampled for ``round_`` — deterministic in
        (seed, round), without replacement, in sampled order (arrival
        order maps cohort position -> hierarchy bucket, so the order is
        part of the seeded contract)."""
        rng = np.random.default_rng([self.seed, int(round_)])
        if self.cohort_size == self.population:
            # Full participation keeps the identity order: the S=1
            # full-participation trajectory must be bitwise the
            # unsharded path's, including bucket assignment.
            return np.arange(self.population, dtype=np.int64)
        return rng.choice(
            self.population, self.cohort_size, replace=False
        ).astype(np.int64)

    # -- f pricing ----------------------------------------------------------

    def capacity(self, c=None):
        """Largest f the configured hierarchy composes for a ``c``-member
        cohort (None when even f=0 is impossible)."""
        c = self.cohort_size if c is None else int(c)
        return hierarchy.max_tolerated_f(c, **self._gar_cfg)

    def f_budget(self, c=None):
        """The cohort's priced Byzantine budget: mean + slack·sigma of
        the sampled Byzantine count, clamped to >= 1 whenever the
        population carries any Byzantine mass (a tail can always land
        one). Raises ValueError when the price exceeds the hierarchy's
        composed capacity — the cohort is unaggregatable at the declared
        threat level and refusing loudly beats aggregating unsoundly."""
        c = self.cohort_size if c is None else int(c)
        p = self.byz_frac
        if p == 0.0:
            return 0
        mean = c * p
        sigma = math.sqrt(c * p * (1.0 - p))
        budget = max(1, int(math.ceil(mean + self.slack_sigmas * sigma)))
        cap = self.capacity(c)
        if cap is None or budget > cap:
            raise ValueError(
                f"cohort f budget {budget} (c={c}, byz_frac={p}, "
                f"{self.slack_sigmas} sigmas) exceeds the hierarchy's "
                f"composed capacity {cap} — shrink byz_frac, grow the "
                "cohort, or pick a stronger bucket/top rule"
            )
        return budget

    def realized_byzantine(self, cohort_ids, byz_ids):
        """How many of ``byz_ids`` (global ids) the cohort sampled — the
        simulation/audit-side ground truth the budget is checked against
        in FEDBENCH rows and the composition tests."""
        return int(np.isin(
            np.asarray(cohort_ids), np.asarray(list(byz_ids))
        ).sum())

    # -- staleness composition ----------------------------------------------

    def cohort_weights(self, round_, cohort_ids, tags=None):
        """(active_ids, weights, dropped_ids): the staleness-composed
        round membership. ``tags`` maps client id -> the round whose
        model its gradient used (missing/None = fresh). Weights follow
        ``utils.rounds.staleness_weights`` (exactly 1.0 when fresh);
        members past the hard cutoff are DROPPED from the round entirely
        — never passed as zero-weight rows (see the module docstring) —
        and the caller prices f on the ACTIVE count."""
        cohort_ids = np.asarray(cohort_ids, np.int64)
        if not tags or self.staleness is None:
            return cohort_ids, np.ones(cohort_ids.size, np.float32), \
                np.empty(0, np.int64)
        tau = np.zeros(cohort_ids.size, np.int64)
        for i, cid in enumerate(cohort_ids.tolist()):
            tag = tags.get(cid)
            if tag is not None:
                tau[i] = max(0, int(round_) - int(tag))
        w = self.staleness.weights(tau)
        keep = w > 0.0
        return cohort_ids[keep], np.asarray(w[keep], np.float32), \
            cohort_ids[~keep]

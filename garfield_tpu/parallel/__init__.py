"""SPMD parallel training core: mesh, roles-as-functions, and the three
Byzantine-resilient topologies of the reference (SURVEY §2.3):

  - ``aggregathor`` — single trusted PS, n workers (SSMW;
    pytorch_impl/applications/Aggregathor/); ``granularity="layer"`` gives
    the Garfield_CC per-parameter collective semantics; num_workers=1, f=0
    degenerates to the Centralized baseline.
  - ``byzsgd``      — replicated Byzantine PS (MSMW / GuanYu;
    pytorch_impl/applications/ByzSGD/).
  - ``learn``       — fully decentralized gossip (LEARN;
    pytorch_impl/applications/LEARN/).

Each exposes ``make_trainer(...) -> (init_fn, step_fn, eval_fn)`` with
``step_fn`` one jit'd SPMD program over the ICI mesh — the reference's
RPC / NCCL / gRPC round trips (SURVEY §2.3 comm-backend row) appear only as
XLA all_gather/psum collectives inside it.
"""

import jax
import jax.numpy as jnp
import numpy as np

from . import aggregathor, byzsgd, core, learn, mesh
from .core import TrainState, default_byz_mask, make_worker_fns
from .mesh import make_mesh

__all__ = [
    "aggregathor",
    "byzsgd",
    "learn",
    "core",
    "mesh",
    "TrainState",
    "default_byz_mask",
    "make_worker_fns",
    "make_mesh",
    "topologies",
    "compute_accuracy",
]

topologies = {
    "centralized": aggregathor,  # num_workers=1, f=0 (P16)
    "aggregathor": aggregathor,  # P17
    "byzsgd": byzsgd,  # P18
    "learn": learn,  # P19
    "garfield_cc": aggregathor,  # P20 — granularity="layer"
}


def compute_accuracy(state, eval_fn, test_batches, *, binary=False):
    """Top-1 accuracy over a list of (x, y) test batches.

    Counterpart of ``Server.compute_accuracy`` (server.py:235-254) / the TF
    ``compute_accuracy`` (tensorflow_impl/libs/server.py:152-163). ``binary``
    follows the pima path (single sigmoid logit, byzWorker-era threshold 0.5).
    """
    correct = total = 0
    for x, y in test_batches:
        logits = np.asarray(eval_fn(state, jnp.asarray(x)))
        y = np.asarray(y)
        if binary:
            # pima path: sigmoid output, threshold 0.5 (demo.py accuracy).
            pred = (logits.reshape(-1) > 0.5).astype(y.dtype)
            correct += int((pred == y.reshape(-1)).sum())
        else:
            correct += int((logits.argmax(-1) == y.reshape(-1)).sum())
        total += len(y)
    return correct / max(total, 1)

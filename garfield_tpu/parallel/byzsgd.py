"""ByzSGD / GuanYu topology: replicated Byzantine parameter servers.

TPU-native re-design of ``pytorch_impl/applications/ByzSGD/trainer.py``:
each of ``num_ps`` servers runs the AggregaThor step on the shared worker
gradients, then a model-space "gather step" (trainer.py:240-244) pulls every
peer server's model, GAR-aggregates them, and writes the result back —
defending against Byzantine servers (byzServer.py) exactly as the gradient
GAR defends against Byzantine workers.

SPMD mapping (SURVEY §2.3 "Replicated-PS" row): a 2-D mesh ("ps", axis);
server state is stacked over the "ps" axis, worker batches are sharded over
``axis``. Per step, on the device at (i, j):

    grads[j]    = vmap(worker_grad)(params[i], batch[j])   # each PS pushes its
                                                           # own model, server.py:112
    stack       = all_gather(grads, axis)                  # (n_w, d) per ps slot
    stack       = attack(stack, byz_workers)               # byzWorker.py
    aggr[i]     = gar(stack[subset_i], f_w)                # per-PS wait n-f subset
    params[i]   = opt(params[i], aggr[i])                  # update_model
    models      = all_gather(flat(params), "ps")           # get_models, :161-184
    models      = model_attack(models, byz_ps)             # byzServer.py:86-108
    params[i]   = unflat(gar(models[msubset_i], f_ps))     # write_model, :289-297

Honest-PS divergence (the reason model aggregation exists at all) arises here
from per-PS wait-n-f subsets — each PS samples its *own* q of n gradients,
mirroring different arrival orders at different servers in the async
reference. ``model_subset`` extends the same emulation to the model gather:
the reference's gather step pulls only the fastest ``num_ps - fps`` peer
models (``get_models(num_ps - fps)``, trainer.py:240-242), so each PS
aggregates its own seeded model subset — composed onto the model Gram for
Gram-form rules, with deterministic PS attacks folded into the Gram remap
(fold.plan_for_model).

``worker_momentum`` (aggregathor/learn) is deliberately NOT offered here:
in this topology every PS slot evaluates the workers' batches against its
OWN model replica, so a per-worker gradient EMA would need one momentum per
(ps, worker) pair — semantics no deployed worker has (a real worker holds
one momentum for the one model it pulls). Run the momentum defense on the
SSMW or LEARN topologies, which match the paper's setting.
"""

import functools

import jax
import jax.numpy as jnp
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import aggregators
from ..attacks import (
    adaptive as adaptive_lib,
    apply_gradient_attack,
    apply_gradient_attack_tree,
    apply_model_attack_rows,
    model_attacks,
    model_collusion_attacks,
)
from ..telemetry import taps as taps_lib
from . import core, fold, mesh as mesh_lib
from .aggregathor import _check_gar, _resolve_gar, _tree_path_ok

__all__ = ["make_trainer"]


def make_trainer(
    module,
    loss_fn,
    optimizer,
    gar,
    *,
    num_workers,
    num_ps,
    fw=0,
    fps=0,
    attack=None,
    attack_params=None,
    ps_attack=None,
    ps_attack_params=None,
    byz_worker_mask=None,
    byz_ps_mask=None,
    mesh=None,
    axis="workers",
    ps_axis="ps",
    subset=None,
    model_subset=None,
    model_gar=None,
    granularity="model",
    tree_path=True,
    gar_dtype=None,
    gar_params=None,
    model_gar_params=None,
    num_iter=None,
    telemetry=False,
    defense=None,
):
    """Build ``(init_fn, step_fn, eval_fn)`` for the MSMW topology.

    ``telemetry`` adds ``metrics["tap"]`` — the WORKER-gradient plane's
    ``TapBundle`` (telemetry/taps.py), averaged across the num_ps server
    views (each PS evaluates the workers against its own replica and,
    under ``subset``, its own quorum): ``observed`` is the fraction of
    servers whose quorum contained the worker, ``selected`` the mean
    influence its gradient earned. The model gather plane is not tapped
    (PS models are few and the per-worker audit is the signal). Off by
    default — nothing tap-shaped is traced, taps never enter TrainState.

    ``gar`` aggregates gradients with tolerance ``fw``; ``model_gar``
    (default: same rule) aggregates server models with tolerance ``fps`` —
    the reference uses one GAR for both (ByzSGD/trainer.py:34 note).
    ``subset=q`` gives each PS its own sampled wait-for-q gradient subset.
    ``model_subset=q_m`` gives each PS its own sampled wait-for-q_m subset
    of the MODEL gather too — the reference-faithful semantics
    (``get_models(num_ps - fps)``, ByzSGD/trainer.py:240-242 /
    server.py:161-184: a server aggregates the fastest ``num_ps - fps``
    peer models, never all of them — pass ``q_m = num_ps - fps`` for exact
    protocol parity). With it, honest PS replicas hold genuinely DIFFERENT
    post-gather models (the async reality the broadcast-one-aggregate
    default hides); the contraction of the model GAR is what keeps them
    from drifting apart. The subset composes onto the model Gram for
    Gram-form rules (one (n_ps, n_ps) Gram build, per-PS (q_m, q_m)
    sub-Gram selections — the same fast-path composition as the gradient
    plane; ``tree_path=False`` forces the flat per-PS gathers), and the
    deterministic model attacks (reverse/crash) fold into the Gram remap
    (``fold.plan_for_model``). None (default) keeps the aggregate-all
    behavior.
    ``granularity="layer"`` applies both GARs independently per parameter
    tensor — the Garfield_CC GuanYu semantics (its reduce_gradients loops
    over model layers, Garfield_CC/trainer.py:55-204) — by segmenting the
    flat stacks at the (static) parameter boundaries; attacks still act on
    the whole flat vector.

    ``tree_path`` (default on): rules with tree-mode aggregation (average,
    krum, cclip, and the per-leaf coordinate-wise twins of median/tmean)
    run the gradient phase on the stacked gradient TREE — no
    (n_w, d) flat stack per PS slot (same win as aggregathor's tree path,
    PERF.md); the model gather phase always works on flat model vectors.

    ``gar_dtype`` narrows the gradient-phase pipeline (cast at the backward
    epilogue, attack + gather + GAR at the narrow width, cast back at the
    optimizer boundary) exactly like aggregathor's flag; the model-space
    phase stays full width (models are parameters, not gradients).

    ``gar_params`` passes rule hyperparameters (cclip tau/iters, condense
    p) to the gradient rule; ``model_gar_params`` to the model-space rule
    (default: same as ``gar_params``, matching the shared-rule default).

    ``ps_attack`` additionally accepts the model-plane COLLUSION attacks
    (``lie``/``empire`` — mu + z*sigma / -eps*mu over the gathered replica
    stack, DESIGN.md §17) and their ADAPTIVE controllers (``adaptive-lie``
    / ``adaptive-empire``, attacks/adaptive.py): the lie/empire magnitude
    becomes a bisection bracket carried in ``TrainState.attack_state``
    (the same carry slot aggregathor's gradient-plane bracket uses —
    this topology's adaptive adversary lives on the MODEL plane), fed
    back each step by whether the Byzantine PS rows entered the model
    gather's selection; ``ps_attack_params`` carries the controller knobs
    (``f_pool``/``rotation``/``mag_min``/``mag_max``). The model plane is
    the attack surface ByzSGD exists for — a Byzantine PS bisecting
    against the fastest-subset model gather (``model_subset``) is the
    gather step's worst case.

    ``defense`` (aggregators/defense.py) deploys suspicion weighting on
    BOTH planes: a dict with ``power``/``floor``/``halflife`` enables a
    per-rank exclusion EMA for the n_w workers AND one for the n_ps
    replicas, carried in ``TrainState.defense_state``, mapped through
    ``defense.suspicion_weights`` and composed as row scales into the
    gradient stacks (before the gradient rule) and the gathered model
    stack (before the model rule) — the MSMW twin of the SSMW PS's
    per-quorum weighting, covering the gradient plane *and* the model
    plane the adaptive PS attacker targets. ``defense=None`` (default)
    traces nothing: trajectories are bitwise the undefended ones. Rule
    ESCALATION lives above the trainer (apps/common.py rebuilds the step
    at level changes; the ladder swaps the GRADIENT rule only — the
    model rule is pinned so the two planes' ladders stay independent).

    ``step_fn(state, x, y)``: ``x``/``y`` lead with ``num_workers`` sharded
    over ``axis``; state params/opt_state lead with ``num_ps`` sharded over
    ``ps_axis``.
    """
    gar = _resolve_gar(gar)
    same_rule = model_gar is None
    model_gar = gar if same_rule else _resolve_gar(model_gar)
    attack_params = dict(attack_params or {})
    gar_params = dict(gar_params or {})
    # The model-space rule defaults to the gradient rule, and only then do
    # its params follow gar_params too. When model_gar is an explicitly
    # DIFFERENT rule, inheriting gradient-rule hyperparameters would be
    # silent misconfiguration (e.g. a cclip tau scaled to gradient radii
    # applied to model vectors, orders of magnitude larger — and unknown
    # keys vanish into the rules' **kwargs), so they default to {} there
    # (ADVICE r3).
    if model_gar_params is None:
        model_gar_params = dict(gar_params) if same_rule else {}
    else:
        model_gar_params = dict(model_gar_params)
    ps_attack_params = dict(ps_attack_params or {})
    if mesh is None:
        mesh = mesh_lib.make_mesh({ps_axis: 1, axis: -1})
    if subset is not None and not (1 <= subset <= num_workers):
        raise ValueError(
            f"subset (wait-for-q) must be in [1, num_workers], got {subset}"
        )
    n_eff = subset if subset is not None else num_workers
    _check_gar(gar, n_eff, fw)
    if telemetry and granularity == "layer":
        raise ValueError(
            "telemetry taps report one whole-model selection per rank; "
            'granularity="layer" has no single per-rank mask — run taps '
            "at model granularity"
        )
    per_w = mesh_lib.fold(num_workers, mesh.shape[axis], "workers")
    per_ps = mesh_lib.fold(num_ps, mesh.shape[ps_axis], "servers")
    if model_subset is not None and not (1 <= model_subset <= num_ps):
        raise ValueError(
            f"model_subset (wait-for-q models) must be in [1, {num_ps}], "
            f"got {model_subset}"
        )
    # The model GAR sees model_subset rows when waiting (the reference
    # passes the num_ps - fps received models straight to the rule,
    # ByzSGD/trainer.py:240-242).
    m_eff = model_subset if model_subset is not None else num_ps
    if num_ps > 1 or fps:
        _check_gar(model_gar, m_eff, fps)
    from ..attacks import targeted as targeted_lib

    if targeted_lib.is_targeted(attack):
        raise ValueError(
            f"targeted attack {attack!r} poisons worker BATCHES and is "
            "deployed on the aggregathor topology in-graph (and on real "
            "cluster workers via apps/cluster.py); the MSMW in-graph "
            "twin does not support it"
        )
    # Adaptive MODEL-plane attacker (DESIGN.md §17): resolve the
    # controller and strip it down to the base collusion attack; the
    # magnitude is supplied per step from the carried bracket.
    ps_adaptive_cfg = None
    if adaptive_lib.is_adaptive(ps_attack):
        if byz_ps_mask is not None:
            raise ValueError(
                "adaptive PS attacks derive their own Byzantine pool from "
                'ps_attack_params ("f_pool"/"pool"); an explicit '
                "byz_ps_mask would silently fight the rotation schedule"
            )
        ps_adaptive_cfg = adaptive_lib.configure(
            ps_attack, ps_attack_params, num_workers=num_ps, f=fps
        )
        ps_attack = ps_adaptive_cfg.base
        ps_attack_params = adaptive_lib.base_params(ps_attack_params)
        byz_ps_mask = ps_adaptive_cfg.pool_mask()
    if (ps_attack is not None and ps_attack != "none"
            and ps_attack not in model_attacks
            and ps_attack not in model_collusion_attacks):
        raise ValueError(f"unknown model attack {ps_attack!r}")
    if byz_worker_mask is None:
        byz_worker_mask = core.default_byz_mask(num_workers, fw if attack else 0)
    if byz_ps_mask is None:
        byz_ps_mask = core.default_byz_mask(num_ps, fps if ps_attack else 0)
    # Folded attack plan for the gradient phase: static for deterministic
    # attacks on fold-capable rules (see fold.plan_for); None -> where-path.
    fold_plan = fold.plan_for(gar, attack, byz_worker_mask, attack_params)
    # Model-plane twin: byzServer's reverse/crash are pure row scalings, so
    # under per-PS model subsets the poisoned model Gram is a static outer
    # scaling of the raw one (fold.plan_for_model); None -> where-path.
    model_fold_plan = fold.plan_for_model(
        model_gar, ps_attack, byz_ps_mask, ps_attack_params
    )
    byz_worker_mask = jnp.asarray(byz_worker_mask, bool)
    byz_ps_mask = jnp.asarray(byz_ps_mask, bool)
    # Closed-loop defense (see docstring): normalized EMA/weighting knobs,
    # the aggregathor convention. Defense routes the gradient plane
    # through the flat path (the weighted rows are what the host-plane
    # MSMW replicas aggregate; the sub-Gram weighted composition is
    # aggregathor's specialty) — a defense-only cost.
    d_power = d_floor = d_decay = None
    if defense is not None:
        from ..aggregators import defense as defense_lib

        if granularity == "layer":
            raise ValueError(
                "the suspicion-weighted defense needs whole-model "
                'selection evidence; granularity="layer" has no per-rank '
                "verdict"
            )
        dd = dict(defense)
        d_power = float(dd.pop("power", 2.0))
        d_floor = float(dd.pop("floor", 0.1))
        halflife = float(dd.pop("halflife", 16.0))
        if dd:
            raise ValueError(f"unknown defense keys {sorted(dd)}")
        if halflife <= 0.0:
            raise ValueError(f"defense halflife must be > 0, got {halflife}")
        d_decay = float(0.5 ** (1.0 / halflife))
        defense_lib.suspicion_weights([0.0], power=d_power, floor=d_floor)
    model_waiting = model_subset is not None and model_subset < num_ps
    # Per-PS model subsets compose onto the model Gram for Gram-form rules
    # (the gradient plane's sub-Gram fast path applied to the (n_ps, d)
    # model stack); other rules gather per-PS rows on the flat path.
    model_gram_ok = (
        tree_path and model_gar.gram_select is not None
        and granularity != "layer"
    )

    init_worker, grad_fn, eval_apply = core.make_worker_fns(module, loss_fn)
    # Slot-fused gradient twin (models/slotfused.py) — worker slots share
    # one model here, so the fused fwd/dx + per-slot dw formulation applies
    # exactly as in aggregathor (LEARN cannot use it: per-NODE params).
    slot_fused_fn, force_unroll = core.select_slot_path(
        module, loss_fn, per_w, num_iter, log_tag="byzsgd"
    )
    repl = NamedSharding(mesh, P())
    ps_sharding = NamedSharding(mesh, P(ps_axis))
    # True subsets force the flat path (dynamic per-leaf gathers measured
    # 3.5x slower); without them tree == flat on one chip and tree avoids
    # the per-PS flatten on real multi-chip meshes. See _tree_path_ok.
    # The suspicion-weighted defense also routes flat: its row weights
    # (and the selection feedback they need) are explicit there.
    tree_ok = (
        _tree_path_ok(tree_path, subset, num_workers, granularity, gar)
        and defense is None
    )

    def init_fn(key, example_x, seed_rng=None):
        params, model_state = init_worker(key, example_x)
        opt_state = optimizer.init(params)
        # Stack server-resident state over the ps axis (identical replicas at
        # t=0, like every server loading the same seeded model).
        stack = lambda tree: jax.tree.map(
            lambda l: jnp.broadcast_to(l[None], (num_ps,) + l.shape), tree
        )
        attack_state = None
        if ps_adaptive_cfg is not None:
            # The model-plane bisection bracket starts wide open; the
            # first gathers ARE the controller's probes.
            attack_state = jax.device_put(
                adaptive_lib.init_state(ps_adaptive_cfg), repl
            )
        defense_state = None
        if defense is not None:
            # One carried exclusion EMA PER PLANE: the workers' gradient
            # audit and the replicas' model-gather audit are independent
            # suspicion histories (independent planes, DESIGN.md §17).
            defense_state = jax.device_put({
                "obs": jnp.zeros((num_workers,), jnp.float32),
                "exc": jnp.zeros((num_workers,), jnp.float32),
                "ps_obs": jnp.zeros((num_ps,), jnp.float32),
                "ps_exc": jnp.zeros((num_ps,), jnp.float32),
            }, repl)
        state = core.TrainState(
            step=jnp.zeros((), jnp.int32),
            params=jax.device_put(stack(params), ps_sharding),
            model_state=jax.device_put(model_state, repl),
            opt_state=jax.device_put(stack(opt_state), ps_sharding),
            rng=jax.device_put(key if seed_rng is None else seed_rng, repl),
            attack_state=attack_state,
            defense_state=defense_state,
        )
        return state.replace(step=jax.device_put(state.step, repl))

    def _ps_slot_step(ps_id, params, opt_state, grads_stack, keys,
                      row_weights=None):
        """One server's gradient phase: attack is already applied; sample this
        PS's own arrival subset, aggregate, update (server.py:112-159 +
        update_model :277-287). ``row_weights`` is the defense's suspicion
        discount — composed after the subset, like the SSMW PS's quorum
        weighting (DESIGN.md §16)."""
        sub_key, gar_key = keys
        gkey = jax.random.fold_in(gar_key, ps_id)
        stack = grads_stack
        n = stack.shape[0]
        if subset is not None and subset < n:
            sel = core.subset_indices(
                jax.random.fold_in(sub_key, ps_id), n, subset
            )
            stack = stack[sel]
            if row_weights is not None:
                row_weights = row_weights[sel]
        if row_weights is not None:
            stack = (stack * row_weights[:, None]).astype(stack.dtype)
        if granularity == "layer":
            aggr = core.segmented_aggregate(
                lambda s, i: gar.unchecked(
                    s, f=fw, key=jax.random.fold_in(gkey, i), **gar_params
                ),
                stack,
                core.leaf_segments(params),
            )
        else:
            aggr = gar.unchecked(stack, f=fw, key=gkey, **gar_params)
        aggr_tree = core.unflatten_like(params, aggr)
        aggr_tree = core.cast_like(aggr_tree, params)  # no-op at f32
        updates, new_opt = optimizer.update(aggr_tree, opt_state, params)
        return optax.apply_updates(params, updates), new_opt

    def _local_step(state, x_local, y_local):
        base = jax.random.fold_in(state.rng, state.step)
        (atk_key, sub_key, psatk_key, drop_base,
         gar_key, mgar_key, msub_key) = jax.random.split(base, 7)
        ps_shard = jax.lax.axis_index(ps_axis)
        w_shard = jax.lax.axis_index(axis)
        ps_ids = ps_shard * per_ps + jnp.arange(per_ps)
        slot_ids = w_shard * per_w + jnp.arange(per_w)

        # Closed-loop defense weights (DESIGN.md §16/§17): per-PLANE
        # suspicion from the carried exclusion EMAs — one history for the
        # n_w workers, an independent one for the n_ps replicas. Exactly
        # 1.0 on clean histories (the weighted identity contract).
        def_w = ps_def_w = None
        if defense is not None:
            susp_w = state.defense_state["exc"] / jnp.maximum(
                state.defense_state["obs"], 1e-6
            )
            def_w = defense_lib.suspicion_weights(
                susp_w, power=d_power, floor=d_floor
            )
            susp_ps = state.defense_state["ps_exc"] / jnp.maximum(
                state.defense_state["ps_obs"], 1e-6
            )
            ps_def_w = defense_lib.suspicion_weights(
                susp_ps, power=d_power, floor=d_floor
            )

        # Adaptive MODEL-plane controller (DESIGN.md §17): play the
        # carried bracket's midpoint as the collusion magnitude, rotate
        # the active replica cohort. Nothing here is traced when the PS
        # attack is oblivious.
        act_ps_mask = byz_ps_mask
        eff_ps_params = ps_attack_params
        ps_mag = None
        p_lo = p_hi = None
        if ps_adaptive_cfg is not None:
            p_lo = state.attack_state["lo"]
            p_hi = state.attack_state["hi"]
            ps_mag = adaptive_lib.played_magnitude(p_lo, p_hi)
            act_ps_mask = adaptive_lib.active_mask_traced(
                ps_adaptive_cfg, state.step
            )
            eff_ps_params = dict(ps_attack_params)
            eff_ps_params[
                adaptive_lib.magnitude_key(ps_adaptive_cfg.base)
            ] = ps_mag

        # --- gradient phase, vmapped over this shard's local PS slots -----
        def grads_for_ps(ps_local_idx, params, ms):
            keys = jax.vmap(
                lambda i: jax.random.fold_in(
                    jax.random.fold_in(drop_base, ps_local_idx), i
                )
            )(slot_ids)
            g, (loss, ms_out) = core.per_slot_grads(
                grad_fn, params, ms, x_local, y_local, keys,
                fused_fn=slot_fused_fn, force_unroll=force_unroll,
            )
            g = core.cast_leaves(g, gar_dtype)
            if tree_ok:
                gathered = jax.tree.map(
                    lambda l: jax.lax.all_gather(l, axis, tiled=True), g
                )  # tree with (n_w, ...) leaves
                return gathered, loss, ms_out
            flat = core.flatten_rows(g)  # (per_w, d)
            stack = jax.lax.all_gather(flat, axis, tiled=True)  # (n_w, d)
            return stack, loss, ms_out

        # Unrolled over the (small, static) local PS slots: a vmap here would
        # batch conv kernels over the ps axis, which XLA's conv batching
        # rules handle poorly; per_ps is O(1) so unrolling is free.
        ms = state.model_state
        outs = [
            grads_for_ps(
                ps_ids[k],
                jax.tree.map(lambda l: l[k], state.params),
                ms,
            )
            for k in range(per_ps)
        ]
        losses = jnp.stack([o[1] for o in outs])  # (per_ps, per_w)
        ms_all = jax.tree.map(
            lambda *ls: jnp.stack(ls), *[o[2] for o in outs]
        )

        tap = None
        if tree_ok:
            # Tree-mode gradient phase: per-PS attack + GAR + update, all
            # on the stacked TREE (unrolled over the O(1) local PS slots;
            # no flat stack is built). subset is None here (see tree_ok).
            new_params_list, new_opt_list = [], []
            for k in range(per_ps):
                slot_gar_key = jax.random.fold_in(gar_key, ps_ids[k])
                if fold_plan is not None:
                    # Folded attack: Gram remap instead of row rewrite
                    # (parallel/fold.py) — same eligibility as aggregathor.
                    aggr_tree = fold.folded_tree_aggregate(
                        gar, fold_plan, outs[k][0], f=fw, key=slot_gar_key,
                        gar_params=gar_params,
                    )
                else:
                    poisoned = apply_gradient_attack_tree(
                        attack, outs[k][0], byz_worker_mask, key=atk_key,
                        **attack_params,
                    )
                    aggr_tree = gar.tree_aggregate(
                        poisoned, f=fw, key=slot_gar_key, **gar_params,
                    )
                p_k = jax.tree.map(lambda l: l[k], state.params)
                o_k = jax.tree.map(lambda l: l[k], state.opt_state)
                aggr_tree = core.cast_like(aggr_tree, p_k)  # no-op at f32
                updates, o_k = optimizer.update(aggr_tree, o_k, p_k)
                new_params_list.append(optax.apply_updates(p_k, updates))
                new_opt_list.append(o_k)
            new_params = jax.tree.map(
                lambda *ls: jnp.stack(ls), *new_params_list
            )
            new_opt = jax.tree.map(lambda *ls: jnp.stack(ls), *new_opt_list)
            if telemetry:
                # Per-PS audit taps on the gradient plane (no subsets on
                # this branch — see tree_ok): each slot's gathered tree
                # differs (its own replica's gradients), so tap each and
                # average; pmean folds in the other PS shards.
                bundles = [
                    taps_lib.compute_flat(
                        gar.name,
                        apply_gradient_attack(
                            attack, core.flatten_rows(outs[k][0]),
                            byz_worker_mask, key=atk_key, **attack_params,
                        ),
                        fw, key=jax.random.fold_in(gar_key, ps_ids[k]),
                        params=gar_params,
                    )
                    for k in range(per_ps)
                ]
                tap = taps_lib.mean_bundles(
                    jax.tree.map(lambda *ls: jnp.stack(ls), *bundles)
                )
        else:
            stacks = jnp.stack([o[0] for o in outs])  # (per_ps, n_w, d)
            stacks = jax.vmap(
                lambda s: apply_gradient_attack(
                    attack, s, byz_worker_mask, key=atk_key, **attack_params
                )
            )(stacks)

            new_params, new_opt = jax.vmap(
                _ps_slot_step, in_axes=(0, 0, 0, 0, None, None)
            )(ps_ids, state.params, state.opt_state, stacks,
              (sub_key, gar_key), def_w)
            if telemetry or defense is not None:
                def one_tap(ps_id, stack):
                    # SAME (sel, key, weight) derivation as _ps_slot_step,
                    # so the tap audits exactly the (suspicion-weighted)
                    # quorum this PS aggregated — the defense's feedback.
                    gkey = jax.random.fold_in(gar_key, ps_id)
                    if subset is not None and subset < num_workers:
                        sel = core.subset_indices(
                            jax.random.fold_in(sub_key, ps_id),
                            num_workers, subset,
                        )
                        sub = stack[sel]
                        if def_w is not None:
                            sub = (sub * def_w[sel][:, None]).astype(
                                sub.dtype
                            )
                        bundle = taps_lib.compute_flat(
                            gar.name, sub, fw, key=gkey,
                            params=gar_params,
                        )
                        return taps_lib.scatter(bundle, sel, num_workers)
                    sub = stack
                    if def_w is not None:
                        sub = (sub * def_w[:, None]).astype(sub.dtype)
                    return taps_lib.compute_flat(
                        gar.name, sub, fw, key=gkey, params=gar_params,
                    )

                tap = taps_lib.mean_bundles(
                    jax.vmap(one_tap)(ps_ids, stacks)
                )

        # --- model gather phase (ByzSGD/trainer.py:240-244) ----------------
        flat_models = core.flatten_rows(new_params)  # (per_ps, d)
        models = jax.lax.all_gather(flat_models, ps_axis, tiled=True)  # (n_ps, d)
        params0 = jax.tree.map(lambda l: l[0], new_params)
        # Model-plane selection feedback (DESIGN.md §17): the rule's
        # verdict over the SAME poisoned, weighted replica stack the
        # gather consumes — what the adaptive PS controller bisects
        # against and what feeds the replica-plane suspicion EMA. Under
        # model_subset the bundle is the observer mean over every PS
        # view, pmean'd so the carried state stays replicated.
        ps_bundle = None
        if defense is not None or ps_adaptive_cfg is not None:
            poisoned_m = apply_model_attack_rows(
                ps_attack, models, act_ps_mask, key=psatk_key,
                **eff_ps_params,
            )
            if ps_def_w is not None:
                poisoned_m = (poisoned_m * ps_def_w[:, None]).astype(
                    poisoned_m.dtype
                )
            if model_waiting:
                def one_mtap(ps_id):
                    # SAME (sel, key) derivation as the gather below.
                    sel = core.subset_indices(
                        jax.random.fold_in(msub_key, ps_id), num_ps,
                        model_subset,
                    )
                    mkey = jax.random.fold_in(mgar_key, ps_id)
                    bundle = taps_lib.compute_flat(
                        model_gar.name, poisoned_m[sel], fps, key=mkey,
                        params=model_gar_params,
                    )
                    return taps_lib.scatter(bundle, sel, num_ps)

                ps_bundle = taps_lib.mean_bundles(
                    jax.vmap(one_mtap)(ps_ids)
                )
                ps_bundle = jax.tree.map(
                    lambda l: jax.lax.pmean(l, ps_axis), ps_bundle
                )
            else:
                ps_bundle = taps_lib.compute_flat(
                    model_gar.name, poisoned_m, fps, key=mgar_key,
                    params=model_gar_params,
                )
        if model_waiting:
            # Reference-faithful wait-n-f on the model plane: each PS
            # aggregates only its own seeded fastest q_m peer models
            # (get_models(num_ps - fps), trainer.py:240-242 /
            # server.py:161-184) — honest replicas genuinely DIVERGE here;
            # the model GAR's contraction, not a broadcast, holds them
            # together. Same per-observer composition as the gradient
            # plane: for Gram-form rules ONE model Gram serves every local
            # PS slot via (q_m, q_m) sub-Gram selections, with
            # deterministic PS attacks (reverse/crash) folded into the
            # Gram remap instead of poisoning the rows.
            sels = jax.vmap(
                lambda i: core.subset_indices(
                    jax.random.fold_in(msub_key, i), num_ps, model_subset
                )
            )(ps_ids)
            mkeys = jax.vmap(
                lambda i: jax.random.fold_in(mgar_key, i)
            )(ps_ids)
            if model_gram_ok:
                base_models = models
                if model_fold_plan is None:
                    base_models = apply_model_attack_rows(
                        ps_attack, models, act_ps_mask, key=psatk_key,
                        **eff_ps_params,
                    )
                aggr_models = fold.folded_tree_aggregate_multi(
                    model_gar, model_fold_plan, base_models, f=fps,
                    keys=mkeys, gar_params=model_gar_params,
                    subset_sels=sels, row_weights=ps_def_w,
                )  # (per_ps, d)
            else:
                poisoned = apply_model_attack_rows(
                    ps_attack, models, act_ps_mask, key=psatk_key,
                    **eff_ps_params,
                )

                def one_ps(sel, mkey):
                    sub = poisoned[sel]
                    if ps_def_w is not None:
                        # Replica-plane suspicion discount composed after
                        # the subset — the gather's rows enter the rule
                        # weighted, like the gradient plane's quorum.
                        sub = (sub * ps_def_w[sel][:, None]).astype(
                            sub.dtype
                        )
                    if granularity == "layer":
                        return core.segmented_aggregate(
                            lambda s, i: model_gar.unchecked(
                                s, f=fps, key=jax.random.fold_in(mkey, i),
                                **model_gar_params,
                            ),
                            sub,
                            core.leaf_segments(params0),
                        )
                    return model_gar.unchecked(
                        sub, f=fps, key=mkey, **model_gar_params
                    )

                aggr_models = jax.vmap(one_ps)(sels, mkeys)  # (per_ps, d)
            new_params = jax.tree.map(
                lambda *ls: jnp.stack(ls),
                *[
                    core.unflatten_like(params0, aggr_models[k])
                    for k in range(per_ps)
                ],
            )
        else:
            models = apply_model_attack_rows(
                ps_attack, models, act_ps_mask, key=psatk_key,
                **eff_ps_params,
            )
            if ps_def_w is not None:
                models = (models * ps_def_w[:, None]).astype(models.dtype)
            if granularity == "layer":
                aggr_model = core.segmented_aggregate(
                    lambda s, i: model_gar.unchecked(
                        s, f=fps, key=jax.random.fold_in(mgar_key, i),
                        **model_gar_params,
                    ),
                    models,
                    core.leaf_segments(params0),
                )
            else:
                aggr_model = model_gar.unchecked(
                    models, f=fps, key=mgar_key, **model_gar_params
                )
            written = core.unflatten_like(params0, aggr_model)
            new_params = jax.tree.map(
                lambda l: jnp.broadcast_to(l[None], (per_ps,) + l.shape),
                written,
            )

        # losses: (per_ps, per_w) — honest-worker mean, then over the mesh.
        honest = (~byz_worker_mask).astype(losses.dtype)
        local_honest = honest[slot_ids]
        loss_num = jnp.sum(jnp.mean(losses, axis=0) * local_honest)
        loss_den = jnp.sum(local_honest)
        mean_loss = jax.lax.psum(loss_num, axis) / jnp.maximum(
            jax.lax.psum(loss_den, axis), 1.0
        )
        mean_loss = jax.lax.pmean(mean_loss, ps_axis)

        new_ms = core.mean_model_state(
            jax.tree.map(lambda l: l.reshape((-1,) + l.shape[2:]), ms_all), axis
        )
        new_ms = jax.tree.map(lambda l: jax.lax.pmean(l, ps_axis), new_ms)

        tap_full = None
        if tap is not None:
            # Observer mean over ALL num_ps server views (the local slots
            # were averaged where `tap` was built). pmean'd ONCE here so
            # the defense's carried state — updated from it below — stays
            # replicated across shards.
            tap_full = jax.tree.map(
                lambda l: jax.lax.pmean(l, ps_axis), tap
            )

        # Adaptive feedback: was the active replica cohort admitted by
        # the model gather? Majority-excluded among the OBSERVED
        # colluders counts as detected; a round that observed none
        # (cohort outside every model subset) holds the bracket.
        new_attack_state = state.attack_state
        ps_detected = None
        if ps_adaptive_cfg is not None:
            act_f = act_ps_mask.astype(jnp.float32) * ps_bundle["observed"]
            cnt = jnp.sum(act_f)
            admitted = jnp.sum(
                (ps_bundle["selected"] > 0).astype(jnp.float32) * act_f
            )
            ps_detected = admitted * 2.0 < cnt
            upd_lo, upd_hi = adaptive_lib.update_bracket(
                p_lo, p_hi, ps_detected,
                mag_min=ps_adaptive_cfg.mag_min,
                mag_max=ps_adaptive_cfg.mag_max,
                regrow=ps_adaptive_cfg.regrow,
            )
            hold = cnt == 0.0
            new_attack_state = {
                "lo": jnp.where(hold, p_lo, upd_lo),
                "hi": jnp.where(hold, p_hi, upd_hi),
            }

        new_defense_state = state.defense_state
        if defense is not None:
            # The hub's exclusion law (observed minus admitted) carried
            # as decayed EMAs, one pair PER PLANE — the in-graph twin of
            # the two MetricsHub histories the cluster roles keep.
            dec = jnp.float32(d_decay)
            w_obs = tap_full["observed"]
            w_ind = (tap_full["selected"] > 0).astype(jnp.float32) * w_obs
            m_obs = ps_bundle["observed"]
            m_ind = (ps_bundle["selected"] > 0).astype(jnp.float32) * m_obs
            new_defense_state = {
                "obs": state.defense_state["obs"] * dec + w_obs,
                "exc": state.defense_state["exc"] * dec + (w_obs - w_ind),
                "ps_obs": state.defense_state["ps_obs"] * dec + m_obs,
                "ps_exc": state.defense_state["ps_exc"] * dec
                + (m_obs - m_ind),
            }

        metrics = {"loss": mean_loss}
        if telemetry and tap_full is not None:
            metrics["tap"] = tap_full
        if ps_adaptive_cfg is not None:
            # Controller observability (schema v8 ``ps_attack_adapt``
            # events via the app loop): the magnitude played on the model
            # plane and whether the gather caught it this round.
            metrics["ps_attack_mag"] = jnp.asarray(ps_mag, jnp.float32)
            metrics["ps_attack_detected"] = ps_detected.astype(jnp.float32)
        if defense is not None:
            metrics["defense_w"] = def_w
            metrics["ps_defense_w"] = ps_def_w
        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                model_state=new_ms,
                opt_state=new_opt,
                attack_state=new_attack_state,
                defense_state=new_defense_state,
            ),
            metrics,
        )

    # Replicated carries for the model-plane controller bracket and the
    # per-plane defense EMAs (None fields stay structurally absent, so
    # oblivious/undefended programs are byte-identical to the pre-§17
    # ones).
    state_specs = core.TrainState(
        step=P(), params=P(ps_axis), model_state=P(),
        opt_state=P(ps_axis), rng=P(),
        attack_state=(P() if ps_adaptive_cfg is not None else None),
        defense_state=(P() if defense is not None else None),
    )
    sharded_step = mesh_lib.shard_map(
        _local_step,
        mesh=mesh,
        in_specs=(state_specs, P(axis), P(axis)),
        out_specs=(state_specs, P()),
        check_vma=False,
    )

    @functools.partial(jax.jit, donate_argnums=core.step_donation())
    def step_fn(state, x, y):
        return sharded_step(state, x, y)

    @jax.jit
    def eval_fn(state, x):
        params0 = jax.tree.map(lambda l: l[0], state.params)
        return eval_apply(params0, state.model_state, x)

    step_fn.mesh = mesh
    step_fn.batch_sharding = NamedSharding(mesh, P(axis))
    # Chunking hook (core.make_chunked_step): scan the shard_map body
    # directly; shardings propagate as in the per-step jit (none pinned).
    step_fn.inner = sharded_step
    return init_fn, step_fn, eval_fn

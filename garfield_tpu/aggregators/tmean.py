"""Coordinate-wise trimmed-mean GAR (beyond-reference addition).

The reference library does not ship trimmed mean, but its own evaluation
plans name it alongside Median (this repo's BASELINE.json north-star
configs: "Median vs Trimmed-Mean"), and it is the third classical
coordinate-wise robust estimator (Yin et al., ICML'18) next to the
reference's median (median.py) and Bulyan's averaged-median phase
(bulyan.py:77-84). Semantics: per coordinate, drop the f largest and f
smallest values and average the middle n-2f.

TPU form: dispatches to the fused Pallas sort+trim+mean kernel
(garfield_tpu/ops/coordinate.py, one HBM pass) like the median rule; jnp
sort elsewhere. NaN values sort last, so up to f NaNs per coordinate land
in the trimmed tail and do not contaminate the result.
"""

import math

from . import register
from ._common import as_stack, num_gradients, tree_coordinatewise


def aggregate(gradients, f, **kwargs):
    """Mean of the middle n-2f values per coordinate."""
    from .. import ops

    return ops.trimmed_mean(as_stack(gradients), f)


def tree_aggregate(stacked_tree, f, key=None, **kwargs):
    """Tree-mode twin (r3): coordinate-wise, so per-leaf like median's
    (see median.tree_aggregate for the chip measurement)."""
    from .. import ops

    return tree_coordinatewise(lambda g: ops.trimmed_mean(g, f), stacked_tree)


def tree_aggregate_ext(ext_tree, row_map, row_scale, f, key=None, **kwargs):
    """Folded-attack twin (parallel/fold.py): per-leaf trimmed mean over
    the EXTENDED stacked tree, remap applied in-register by the kernel."""
    from .. import ops

    return tree_coordinatewise(
        lambda g: ops.trimmed_mean(
            g, f, row_map=row_map, row_scale=row_scale
        ),
        ext_tree,
    )


def check(gradients, f, **kwargs):
    n = num_gradients(gradients)
    if n < 1:
        return f"expected at least one gradient to aggregate, got {gradients!r}"
    if not isinstance(f, int) or f < 1 or n < 2 * f + 1:
        return (
            f"invalid number of Byzantine gradients to tolerate, got f = {f!r}, "
            f"expected 1 <= f <= {(n - 1) // 2}"
        )
    return None


def upper_bound(n, f, d):
    """Same family bound as coordinate-wise median, 1/sqrt(n - f)."""
    return 1 / math.sqrt(n - f)


register("tmean", aggregate, check, upper_bound=upper_bound,
         tree_aggregate=tree_aggregate, tree_aggregate_ext=tree_aggregate_ext)
